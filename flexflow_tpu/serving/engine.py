"""Continuous-batching inference engine over a slot-based kv-cache pool.

The reference FlexFlow is training-only; ``FFModel.generate()`` (the
first inference surface here) is one-shot: it compiles a scan for ONE
(B, P, N) shape and blocks the caller for the whole decode.  Serving
heavy traffic needs the opposite: many callers, mixed prompt/output
lengths, stable jitted shapes, and no head-of-line blocking.  This
engine provides that with the classic TPU trick — keep every device
shape STATIC and move all dynamism to the host:

* A fixed pool of ``max_batch`` decode SLOTS.  The decode step is one
  jitted function over the full (max_batch,) token/position vectors and
  the pooled (max_batch, H, max_seq, D) kv caches — it compiles exactly
  once, regardless of traffic.
* Requests are ADMITTED AT TOKEN BOUNDARIES from a thread-safe priority
  queue.  A free slot prefills the prompt — padded to a LENGTH BUCKET so
  each bucket compiles once — then joins the running batch; rows of the
  same device batch sit at different sequence positions (per-row ``pos``
  vector, see ``FFModel.decode_step``).
* A finished sequence RELEASES ITS SLOT MID-FLIGHT: the host-side active
  mask stops collecting that lane, and the next admission overwrites the
  slot's cache slice wholesale.  Stale lanes still compute (shapes are
  static) but their causal masks zero their influence exactly, so greedy
  per-request output is equal to a standalone ``generate()`` call.

Paged KV mode (default when the model qualifies — see FF_SERVE_PAGED):
instead of a dense ``(max_batch, H, max_seq, D)`` slab per layer, the
caches are block pools ``(num_blocks, H, block_size, D)`` addressed
through per-slot int32 block tables (``serving/kvpool.py`` owns the
host-side free list / refcounts / prefix index).  Admission gates on
FREE BLOCKS, not just a free slot — exhaustion sheds with the existing
``ServeOverload`` 503, never a crash — prompts sharing an indexed
prefix skip to suffix prefill over the donor's chain (copy-on-write on
the partial tail block), and each decode boundary runs the jitted step
of the smallest WINDOW bucket covering the longest active sequence, so
per-token attention reads scale with actual length, not ``max_seq``.
All device shapes stay static: tables are a (B, W) argument, window
buckets form a power-of-two ladder compiled once each, and idle lanes
point at the never-allocated garbage block 0.

Observability (when the model was compiled with telemetry): per-request
``serve_queue_wait`` / ``serve_prefill`` / ``serve_decode`` spans, a
``serve_request_done`` event carrying TTFT/TPOT, ``serve_tokens`` /
``serve_requests`` counters and a per-token-boundary
``serve_batch_occupancy`` gauge — ``tools/serve_report.py`` folds them
into latency percentiles and an occupancy timeline.  Every record is
additionally stamped with the request's ``trace_id``
(observability/reqtrace.py); a SAMPLED request (FF_TRACE_SAMPLE) also
gets per-chunk ``serve_decode_chunk`` spans and KV block span events,
which ``tools/timeline_export.py`` folds into one Perfetto track.

Fault isolation: a request whose admission/prefill raises (including an
``FF_CHAOS`` ``serve`` fault) fails ALONE — the batch loop and every
other request keep running.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import reqtrace as _reqtrace
from ..testing.chaos import ChaosReplicaKill
from .config import ServeConfig
from .kvpool import BlockExhausted, KVBlockPool, blocks_for
from .queue import (CANCELLED, DONE, ERROR, RUNNING, TIMEOUT,
                    InferenceRequest, RequestQueue, ServeError)

_engine_uids = itertools.count(1)

# cancel reason an ABANDONED engine stamps on slots it still held at
# exit: the pool recognizes it in _on_attempt_done and re-dispatches
# (the attempt was popped after the failover snapshot — handing it
# back is the only exactly-once option left)
ABANDON_HANDBACK = "engine abandoned"


class _Slot:
    """Host-side state of one running sequence."""

    __slots__ = ("req", "pos", "t_first", "res", "tr_t0", "tr_n0")

    def __init__(self, req: InferenceRequest, pos: int, t_first: float,
                 res=None):
        self.req = req
        self.pos = pos          # position the NEXT fed token occupies
        self.t_first = t_first
        self.res = res          # kvpool.Reservation (paged mode only)
        # decode-chunk tracking for SAMPLED traces: start clock + token
        # count at the current chunk's open edge (None: not sampled)
        self.tr_t0: Optional[float] = None
        self.tr_n0 = 0


class InferenceEngine:
    """Continuous-batching decode loop over a compiled ``FFModel``.

    Usage::

        engine = InferenceEngine(model, max_batch=8, max_seq=128)
        with engine:                       # starts the loop thread
            h = engine.submit([1, 2, 3], max_new_tokens=16)
            tokens = h.result(timeout=30)  # (16,) int32

    Decoding is greedy (argmax) — bitwise the same tokens as
    ``model.generate(prompt[None], n)`` for every request, which is what
    makes the batching transparent to callers.
    """

    def __init__(self, model, config: Optional[ServeConfig] = None,
                 telemetry=None, queue: Optional[RequestQueue] = None,
                 name: Optional[str] = None, decode_fatal: bool = False,
                 zone: Optional[str] = None, **overrides):
        assert getattr(model, "_compiled", False), \
            "InferenceEngine needs a compiled model (call compile() first)"
        self.model = model
        self.config = config if config is not None \
            else ServeConfig.from_env(**overrides)
        # replica-pool plumbing (inert for a standalone engine):
        #  * ``queue`` — a SHARED admission queue owned by the pool; this
        #    engine then never drains it (other replicas' requests live
        #    there too),
        #  * ``name`` — stable replica name for telemetry attribution,
        #  * ``uid`` — per-INCARNATION key: failover re-dispatch marks a
        #    request ``avoid=uid`` so the same incarnation cannot pop it
        #    back, while a restarted replica (fresh uid) still can,
        #  * ``decode_fatal`` — a decode-step exception propagates out of
        #    the loop (the pool marks the replica UNHEALTHY and fails its
        #    requests over) instead of failing the batch in place.
        self.name = name or "replica-0"
        self.uid = f"{self.name}#{next(_engine_uids)}"
        # zone = failure-domain label.  The pop avoid-key set includes
        # "zone:<z>" so a hedge/failover marked to avoid a whole zone is
        # never popped back by ANY replica in it; telemetry carries the
        # zone for per-zone occupancy in serve_report.
        self.zone = zone
        self._avoid_keys = (self.uid,) if zone is None \
            else (self.uid, f"zone:{zone}")
        self._zone_attr = {} if zone is None else {"zone": zone}
        self._decode_fatal = bool(decode_fatal)
        self.crashed: Optional[str] = None   # set when the loop dies
        self.last_beat = time.perf_counter()  # decode-progress heartbeat
        self._tok_t, self._pos_t = model.resolve_decode_inputs()
        fed = {self._tok_t.guid}
        if self._pos_t is not None:
            fed.add(self._pos_t.guid)
        extra = [t for t in model.input_tensors if t.guid not in fed]
        if extra:
            raise ValueError(
                f"serving: model has {len(extra)} extra graph input(s) "
                f"beyond (tokens, positions) — seq2seq extra_inputs are "
                f"not served; use FFModel.generate()")
        model._check_position_table(self._pos_t, self.config.max_seq)

        self._telemetry = telemetry if telemetry is not None \
            else getattr(model, "_telemetry", None)
        # decode tokens per serve_decode_chunk span on a sampled trace
        # (loud parse; resolved once — 0 with telemetry off)
        self._trace_chunk = _reqtrace.chunk_tokens_from_env() \
            if self._telemetry is not None else 0
        # Compile plane (FF_MEMPLANE): wraps every bucket-ladder jit so
        # a silent retrace — THE serving failure mode — shows up as a
        # compile_done{retrace} event and on ff_compile_retraces_total.
        from ..observability import memplane as _memplane

        self._memplane = _memplane.maybe_plane(self._telemetry)
        self._chaos = getattr(model, "_chaos", None)

        B = self.config.max_batch
        self._queue = queue if queue is not None else RequestQueue()
        self._owns_queue = queue is None
        self._admitting: Optional[InferenceRequest] = None
        self._pending_admit: Optional[InferenceRequest] = None
        self._slots: List[Optional[_Slot]] = [None] * B
        self._toks = np.zeros(B, np.int32)   # last fed token per slot
        self._pos = np.zeros(B, np.int32)    # its position per slot
        self._caches = None                  # created lazily on device
        self._prefill_fns: Dict[int, Any] = {}
        self._step_fn = None
        self._insert_fn = None
        # donation keeps the pooled caches in-place on accelerators; the
        # CPU backend would warn on every call
        self._donate = jax.default_backend() != "cpu"

        # paged KV mode: geometry must divide AND every cache-carrying
        # op must have a paged decode path; "on" makes a miss loud,
        # "auto" falls back to the dense slot pool (LSTM stacks etc.)
        cfg = self.config
        if cfg.paged == "on" and not model.pageable_decode():
            raise ValueError(
                "FF_SERVE_PAGED=on but a cache-carrying op has no paged "
                "decode path — serve this model with FF_SERVE_PAGED=off")
        self._paged = cfg.paged_feasible() and model.pageable_decode()
        self._kvpool: Optional[KVBlockPool] = None
        if self._paged:
            bs = cfg.kv_block
            self._max_w = cfg.max_seq // bs  # window-bucket ceiling
            shapes = jax.eval_shape(
                lambda: model.init_paged_decode_caches(2, bs))
            bytes_per_block = sum(
                int(np.prod(leaf.shape[1:])) * leaf.dtype.itemsize
                for leaf in jax.tree.leaves(shapes))
            self._kvpool = KVBlockPool(cfg.kv_blocks_resolved() + 1, bs,
                                       bytes_per_block)
            self._paged_step_fns: Dict[int, Any] = {}
            self._paged_prefill_fns: Dict[Any, Any] = {}

        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._drain = True
        self._retiring = False   # graceful single-replica drain (pool)
        self._abandoned = False  # pool detached us; it owns our in-flight
        # submits are accepted from construction (queueing before
        # start() is legal — the loop admits once it runs); only stop()
        # closes the door
        self._accepting = True
        self._admit_seq = 0
        self._stats = dict(submitted=0, admitted=0, completed=0, failed=0,
                           timeouts=0, cancelled=0, tokens_out=0,
                           prefill_compiles=0, step_iterations=0,
                           occupancy_sum=0, max_active=0)

    # ------------------------------------------------------------------
    # jitted device functions (static shapes; compiled once per engine /
    # per prompt bucket)
    # ------------------------------------------------------------------
    def _get_step_fn(self):
        if self._step_fn is None:
            model, tok_t, pos_t = self.model, self._tok_t, self._pos_t

            def step(params, stats, caches, toks, pos):
                probs, caches = model.decode_step(
                    params, stats, caches, toks, pos, tok_t, pos_t)
                return caches, jnp.argmax(probs, axis=-1).astype(jnp.int32)

            fn = jax.jit(step, donate_argnums=(2,) if self._donate else ())
            if self._memplane is not None:
                fn = self._memplane.wrap("serve_step", fn)
            self._step_fn = fn
        return self._step_fn

    def _get_prefill_fn(self, bucket: int):
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            model, tok_t, pos_t = self.model, self._tok_t, self._pos_t
            max_seq = self.config.max_seq

            def prefill(params, stats, toks):        # toks (1, bucket)
                caches = model.init_decode_caches(1, max_seq)

                def body(caches, t):
                    probs, caches = model.decode_step(
                        params, stats, caches, toks[:, t], t, tok_t, pos_t)
                    return caches, jnp.argmax(probs, -1).astype(jnp.int32)

                caches, outs = jax.lax.scan(body, caches,
                                            jnp.arange(bucket))
                return caches, outs[:, 0]  # next-token after each prefix

            fn = jax.jit(prefill)
            if self._memplane is not None:
                fn = self._memplane.wrap(f"serve_prefill:{bucket}", fn)
            self._prefill_fns[bucket] = fn
            self._stats["prefill_compiles"] += 1
        return fn

    def _get_insert_fn(self):
        if self._insert_fn is None:
            from jax import lax

            def insert(pool, piece, slot):
                # overwrite slot's WHOLE cache slice: whatever the lane
                # held before (a released sequence, garbage writes from
                # its idle period) is gone
                return jax.tree.map(
                    lambda g, p: lax.dynamic_update_slice(
                        g, p.astype(g.dtype),
                        (slot,) + (jnp.int32(0),) * (g.ndim - 1)),
                    pool, piece)

            fn = jax.jit(insert,
                         donate_argnums=(0,) if self._donate else ())
            if self._memplane is not None:
                fn = self._memplane.wrap("serve_insert", fn)
            self._insert_fn = fn
        return self._insert_fn

    # ------------------------------------------------------------------
    # paged-mode jitted functions: one step per WINDOW bucket (W blocks
    # gathered per row), one prefill per (gather-bucket, suffix-bucket)
    # pair — the same compile-once-per-shape discipline as the dense
    # ladder, with the block tables passed as a (B, W) int32 argument
    # ------------------------------------------------------------------
    def _block_bucket(self, n: int) -> int:
        """Smallest power-of-two block count >= n (capped at the whole-
        sequence window); 0 stays 0 (no gather)."""
        if n <= 0:
            return 0
        w = 1
        while w < n:
            w *= 2
        return min(w, self._max_w)

    def _get_paged_step_fn(self, w: int):
        fn = self._paged_step_fns.get(w)
        if fn is None:
            model, tok_t, pos_t = self.model, self._tok_t, self._pos_t

            def step(params, stats, caches, toks, pos, tables):
                probs, caches = model.decode_step(
                    params, stats, caches, toks, pos, tok_t, pos_t,
                    block_tables=tables)
                return caches, jnp.argmax(probs, axis=-1).astype(jnp.int32)

            fn = jax.jit(step, donate_argnums=(2,) if self._donate else ())
            if self._memplane is not None:
                fn = self._memplane.wrap(f"serve_paged_step:w{w}", fn)
            self._paged_step_fns[w] = fn
        return fn

    def _get_paged_prefill_fn(self, n_gb: int, sbucket: int):
        """Gather -> dense scan -> scatter prefill.

        The matched prefix's ``n_gb`` chain blocks (a power-of-two
        bucket; unused entries name the garbage block) are gathered into
        a dense scratch, the suffix runs the standard dense decode scan
        over positions ``start + t``, and only the suffix's
        ``nsc = ceil(sbucket/bs) + 1`` blocks (the +1 is the
        copy-on-write partial tail) scatter back into the pool — an
        8-token prompt moves one block per leaf, not a whole
        ``(H, max_seq, D)`` slice."""
        key = (n_gb, sbucket)
        fn = self._paged_prefill_fns.get(key)
        if fn is None:
            model, tok_t, pos_t = self.model, self._tok_t, self._pos_t
            bs = self.config.kv_block
            sb_blocks = blocks_for(sbucket, bs)
            nsc = sb_blocks + 1
            from jax import lax

            def prefill(params, stats, pool, gids, toks, start, d0, sids):
                def gather(leaf):          # (N, H, bs, D) -> dense scratch
                    h, d = leaf.shape[1], leaf.shape[3]
                    g = leaf[gids].transpose(1, 0, 2, 3)
                    g = g.reshape(1, h, n_gb * bs, d)
                    z = jnp.zeros((1, h, nsc * bs, d), leaf.dtype)
                    return jnp.concatenate([g, z], axis=2)

                dense = jax.tree.map(gather, pool)

                def body(dense, t):
                    probs, dense = model.decode_step(
                        params, stats, dense, toks[:, t], start + t,
                        tok_t, pos_t)
                    return dense, jnp.argmax(probs, -1).astype(jnp.int32)

                dense, outs = lax.scan(body, dense, jnp.arange(sbucket))

                def scatter(leaf, dbuf):
                    h, d = leaf.shape[1], leaf.shape[3]
                    nb = dbuf.shape[2] // bs
                    blk = dbuf[0].reshape(h, nb, bs, d).transpose(1, 0, 2, 3)
                    win = lax.dynamic_slice(
                        blk, (d0, 0, 0, 0), (nsc,) + blk.shape[1:])
                    return leaf.at[sids].set(win.astype(leaf.dtype))

                pool = jax.tree.map(scatter, pool, dense)
                return pool, outs[:, 0]

            fn = jax.jit(prefill,
                         donate_argnums=(2,) if self._donate else ())
            if self._memplane is not None:
                fn = self._memplane.wrap(
                    f"serve_paged_prefill:g{n_gb}s{sbucket}", fn)
            self._paged_prefill_fns[key] = fn
            self._stats["prefill_compiles"] += 1
        return fn

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def start(self) -> "InferenceEngine":
        assert self._thread is None, "engine already started"
        self._stop_evt.clear()
        self._accepting = True
        self._thread = threading.Thread(target=self._run,
                                        name=f"ff-serve-{self.name}",
                                        daemon=True)
        self._thread.start()
        return self

    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def stop(self, drain: bool = True, timeout: float = 60.0) -> None:
        """Stop the loop.  ``drain=True`` finishes queued + running
        requests first; ``drain=False`` cancels everything outstanding
        at the next token boundary."""
        self._accepting = False
        self._drain = drain
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None

    def retire(self, timeout: float = 60.0) -> None:
        """Graceful single-replica drain for a SHARED-queue pool member:
        stop popping NEW work (other replicas keep serving the shared
        queue), finish the decode slots already live plus any parked
        admission, then exit.  ``stop(drain=True)`` is the wrong tool
        here — its exit condition waits for the WHOLE shared queue to
        empty, which under sustained load never happens."""
        self._accepting = False
        self._retiring = True
        self._drain = True
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            if not t.is_alive():
                self._thread = None

    def abandon(self) -> None:
        """Pool-side: detach this (crashed or wedged) incarnation
        WITHOUT joining its thread — a thread sleeping inside an
        injected hang may not wake for an hour, and it is a daemon.
        The loop exits at its next conscious moment; any request it
        still resolves afterwards loses the CAS against the pool's
        failover and is ignored.  The exiting loop must NOT cancel its
        slots either (``_abandoned`` gates the shutdown cancellation):
        a HEALTHY engine abandoned by a zone outage would otherwise
        race its "engine stopped" cancel against the pool's failover
        untracking — and win, failing the client."""
        self._abandoned = True
        self._accepting = False
        self._drain = False
        self._stop_evt.set()

    def active_requests(self) -> List[InferenceRequest]:
        """Unresolved requests this replica is holding: live decode
        slots plus one possibly mid-admission (a replica killed between
        pop and prefill must not lose that request).  Read by the pool's
        monitor from another thread — a snapshot, not a lock."""
        reqs = [s.req for s in self._slots if s is not None]
        for adm in (self._admitting, self._pending_admit):
            if adm is not None and all(r is not adm for r in reqs):
                reqs.append(adm)
        return [r for r in reqs if not r.done()]

    def __enter__(self) -> "InferenceEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(drain=not any(exc))

    def submit(self, prompt, max_new_tokens: Optional[int] = None, *,
               priority: int = 0, timeout_s: Optional[float] = None,
               eos_id: Optional[int] = None,
               request_id: Optional[str] = None) -> InferenceRequest:
        """Enqueue one prompt; returns the request handle (a future).
        Validation errors raise here, synchronously."""
        cfg = self.config
        n = cfg.max_new_tokens if max_new_tokens is None \
            else int(max_new_tokens)
        req = InferenceRequest(
            prompt, n, priority=priority, eos_id=eos_id,
            request_id=request_id,
            timeout_s=cfg.queue_timeout_s if timeout_s is None
            else timeout_s)
        if req.timeout_s == 0:
            req.timeout_s = None              # 0: wait forever
        cfg.validate_request(int(req.prompt.size), n)
        if not self._accepting:
            raise ServeError("engine is not accepting requests "
                             "(not started, or stopping)")
        if self._kvpool is not None:
            # free-block admission control: shed (503 + Retry-After)
            # when even evicting the whole prefix index couldn't cover
            # this request's worst case on top of in-flight promises
            self._kvpool.check_room(int(req.prompt.size), n)
        # trace context minted ONCE, here at admission (pool attempts
        # arrive on the shared queue already carrying a child context)
        if self._telemetry is not None and req.trace is None:
            req.trace = _reqtrace.begin(self._telemetry)
        self._stats["submitted"] += 1
        self._queue.put(req)
        return req

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 timeout: Optional[float] = None, **kw) -> np.ndarray:
        """Synchronous convenience: submit + result."""
        return self.submit(prompt, max_new_tokens, **kw).result(timeout)

    @property
    def num_active(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def num_queued(self) -> int:
        return len(self._queue)

    def stats(self) -> Dict[str, Any]:
        s = dict(self._stats)
        s["active"] = self.num_active
        s["queued"] = self.num_queued
        s["mean_occupancy"] = (s["occupancy_sum"] / s["step_iterations"]
                               if s["step_iterations"] else 0.0)
        s["paged"] = self._paged
        if self._kvpool is not None:
            s["kv"] = self._kvpool.stats()
        return s

    # ------------------------------------------------------------------
    # the loop (one background thread; all jax dispatch happens here)
    # ------------------------------------------------------------------
    def _run(self) -> None:
        """Thread body: the loop plus a crash recorder.  A loop that
        dies (``decode_fatal``, ChaosReplicaKill, a bug) must leave a
        diagnosis behind — a standalone engine fails its outstanding
        requests so no caller blocks forever; a pool replica leaves them
        UNRESOLVED for the pool's failover to re-enqueue."""
        try:
            self._loop()
        except BaseException as e:  # noqa: BLE001 — read by the pool
            self.crashed = f"{type(e).__name__}: {e}"
            if self._telemetry is not None:
                self._telemetry.event("serve_loop_crashed",
                                      replica=self.name, error=self.crashed)
                self._telemetry.flush()
            if self._owns_queue:
                self._fail_outstanding(f"engine crashed: {self.crashed}")
            elif self._paged:
                # pool replica: its requests stay unresolved so the
                # pool can fail them over, but this dead incarnation's
                # block reservations must not dangle (release is
                # idempotent; a later failover can't double-free)
                for slot in self._slots:
                    if slot is not None and slot.res is not None:
                        self._kvpool.release(slot.res)

    def _fail_outstanding(self, msg: str) -> None:
        for i, slot in enumerate(self._slots):
            if slot is not None:
                if slot.res is not None:
                    self._kvpool.release(slot.res)
                if slot.req._resolve(ERROR, msg):
                    self._stats["failed"] += 1
                    self._emit_done(slot.req)
                self._slots[i] = None
        parked, self._pending_admit = self._pending_admit, None
        if parked is not None and parked._resolve(ERROR, msg):
            self._stats["failed"] += 1
            self._emit_done(parked)
        self._stats["failed"] += self._queue.drain(ERROR, msg)

    def _loop(self) -> None:
        cfg = self.config
        while True:
            now = self.last_beat = time.perf_counter()
            self._stats["timeouts"] += self._queue.expire(now)
            if self._stop_evt.is_set():
                if not self._drain:
                    break
                if self._retiring:
                    # retiring pool member: own slots empty is enough —
                    # the shared queue belongs to the surviving replicas
                    if self.num_active == 0 and self._pending_admit is None:
                        break
                elif self.num_active == 0 and len(self._queue) == 0 \
                        and self._pending_admit is None:
                    break
            self._admit_ready(now)
            if self.num_active == 0:
                if len(self._queue):
                    # nonempty but nothing admittable: every queued item
                    # avoids THIS incarnation (failover/hedge targets) —
                    # sleep instead of spinning on wait_nonempty
                    time.sleep(cfg.poll_interval_s)
                elif not self._stop_evt.is_set():
                    self._queue.wait_nonempty(cfg.poll_interval_s)
                continue
            self._decode_iteration()
        # shutdown: a standalone engine owns its queue and cancels what
        # is left; a pool replica must NOT drain the shared queue (other
        # replicas' requests live there) — the pool drains it once
        if self._abandoned:
            # the pool detached this incarnation (failover/zone outage):
            # it untracks and re-dispatches the slots it SNAPSHOTTED, so
            # for those our cancel must lose the CAS — and it does, the
            # pool force-cancels them first.  But anything we popped in
            # the window between its snapshot and our exit is still
            # tracked: cancel with the ABANDON_HANDBACK marker so the
            # pool re-dispatches it instead of failing the client.
            parked, self._pending_admit = self._pending_admit, None
            if parked is not None \
                    and parked._resolve(CANCELLED, ABANDON_HANDBACK):
                self._stats["cancelled"] += 1
            for i, slot in enumerate(self._slots):
                if slot is not None:
                    if slot.res is not None:
                        self._kvpool.release(slot.res)
                    if slot.req._resolve(CANCELLED, ABANDON_HANDBACK):
                        self._stats["cancelled"] += 1
                    self._slots[i] = None
            return
        if self._owns_queue:
            self._stats["cancelled"] += self._queue.drain(
                CANCELLED, "engine stopped")
        parked, self._pending_admit = self._pending_admit, None
        if parked is not None \
                and parked._resolve(CANCELLED, "engine stopped"):
            self._stats["cancelled"] += 1
        for i, slot in enumerate(self._slots):
            if slot is not None:
                if slot.res is not None:
                    self._kvpool.release(slot.res)
                if slot.req._resolve(CANCELLED, "engine stopped"):
                    self._stats["cancelled"] += 1
                self._slots[i] = None

    def _admit_ready(self, now: float) -> None:
        while True:
            free = next((i for i, s in enumerate(self._slots)
                         if s is None), None)
            if free is None:
                return
            req, self._pending_admit = self._pending_admit, None
            if req is not None:
                # parked at the last boundary (no free KV blocks):
                # still honor cancellation and its queue-wait deadline
                if req.done():
                    continue
                if req.timeout_s is not None \
                        and now - req.t_submit > req.timeout_s:
                    if req._resolve(TIMEOUT,
                                    f"queue wait exceeded "
                                    f"{req.timeout_s:g}s"):
                        self._stats["timeouts"] += 1
                        self._emit_done(req)
                    continue
            else:
                if self._retiring or self._abandoned:
                    return      # no NEW pops: draining, or detached
                req = self._queue.pop_ready(now, avoid_key=self._avoid_keys)
            if req is None:
                return
            self._admitting = req
            try:
                self._admit(req, free)
            except ChaosReplicaKill:
                # replica-scoped fault: deliberately NOT isolated — the
                # loop thread dies; ``_admitting`` stays set so the pool
                # fails this request over with the in-flight ones
                raise
            except BlockExhausted:
                # blocks are all pinned by running sequences right now —
                # park the head and retry once a boundary frees some;
                # ordering is preserved (the park slot drains first)
                self._admitting = None
                self._pending_admit = req
                return
            except Exception as e:  # noqa: BLE001 — isolate per request
                req._resolve(ERROR, f"{type(e).__name__}: {e}")
                self._stats["failed"] += 1
                self._emit_done(req)
            self._admitting = None

    def _admit(self, req: InferenceRequest, slot: int) -> None:
        """Prefill ``req`` into ``slot``; on return the slot is live and
        the request owns its first generated token."""
        self._admit_seq += 1
        req.admit_seq = self._admit_seq
        req.admitted_by = self.uid
        if self._chaos is not None:
            # serve site: trigger = 1-based admission count; a raised
            # fault fails THIS request only (caught in _admit_ready)
            self._chaos.fire("serve", model=self.model)
        req.t_admit = time.perf_counter()
        req.status = RUNNING
        if self._paged:
            self._admit_paged(req, slot)
            return
        plen = int(req.prompt.size)
        bucket = self.config.bucket_for(plen)
        fn = self._get_prefill_fn(bucket)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :plen] = req.prompt
        t0 = time.perf_counter()
        params = self.model._decode_params()
        piece, nexts = fn(params, self.model._stats, jnp.asarray(padded))
        first_tok = int(np.asarray(nexts)[plen - 1])
        if self._caches is None:
            self._caches = self.model.init_decode_caches(
                self.config.max_batch, self.config.max_seq)
        self._caches = self._get_insert_fn()(
            self._caches, piece, jnp.int32(slot))
        t1 = time.perf_counter()

        req.tokens.append(first_tok)
        req.t_first = t1
        self._stats["admitted"] += 1
        log = self._telemetry
        if log is not None:
            tr = _reqtrace.tag(req.trace)
            log.span_at("serve_queue_wait", req.t_submit,
                        req.t_admit - req.t_submit,
                        request_id=req.request_id, priority=req.priority,
                        **tr)
            log.span_at("serve_prefill", t0, t1 - t0,
                        request_id=req.request_id, prompt_len=plen,
                        bucket=bucket, slot=slot, replica=self.name, **tr)
        if req.max_new_tokens == 1 or first_tok == req.eos_id:
            self._finish(req, slot=None, t_done=t1)
            return
        self._slots[slot] = self._new_slot(req, plen, t1)
        self._toks[slot] = first_tok
        self._pos[slot] = plen
        self._stats["max_active"] = max(self._stats["max_active"],
                                        self.num_active)

    def _new_slot(self, req: InferenceRequest, plen: int, t1: float,
                  res=None) -> _Slot:
        s = _Slot(req, plen, t_first=t1, res=res)
        if self._trace_chunk and req.trace is not None \
                and req.trace.sampled:
            s.tr_t0 = t1                # open the first decode chunk
            s.tr_n0 = len(req.tokens)
        return s

    def _admit_paged(self, req: InferenceRequest, slot: int) -> None:
        """Block-paged admission: reserve blocks (worst case promised so
        decode can never starve), gather any indexed prefix chain, run
        suffix-only prefill, scatter just the suffix's blocks into the
        pool, and index this prompt for future sharers."""
        pool = self._kvpool
        cfg = self.config
        bs = cfg.kv_block
        plen = int(req.prompt.size)
        res = pool.reserve(req.prompt, req.max_new_tokens)  # BlockExhausted
        try:
            m = res.hit_tokens                 # suffix starts here
            slen = plen - m
            sbucket = cfg.bucket_for(slen)
            n_gb = self._block_bucket(blocks_for(m, bs))
            nsc = blocks_for(sbucket, bs) + 1
            gids = np.zeros(n_gb, np.int32)
            gids[:len(res.gather)] = res.gather
            sids = np.zeros(nsc, np.int32)
            sids[:len(res.owned)] = res.owned
            padded = np.zeros((1, sbucket), np.int32)
            padded[0, :slen] = req.prompt[m:]
            fn = self._get_paged_prefill_fn(n_gb, sbucket)
            t0 = time.perf_counter()
            params = self.model._decode_params()
            if self._caches is None:
                self._caches = self.model.init_paged_decode_caches(
                    pool.num_blocks, bs)
            self._caches, nexts = fn(
                params, self.model._stats, self._caches,
                jnp.asarray(gids), jnp.asarray(padded), jnp.int32(m),
                jnp.int32(m // bs), jnp.asarray(sids))
            first_tok = int(np.asarray(nexts)[slen - 1])
            t1 = time.perf_counter()
        except BaseException:
            pool.release(res)                  # no leak on any failure
            raise
        pool.end_gather(res)
        pool.note_transfer(nsc)
        pool.note_gather(n_gb)
        pool.register_prefix(req.prompt, res)

        req.tokens.append(first_tok)
        req.t_first = t1
        self._stats["admitted"] += 1
        log = self._telemetry
        if log is not None:
            tr = _reqtrace.tag(req.trace)
            log.span_at("serve_queue_wait", req.t_submit,
                        req.t_admit - req.t_submit,
                        request_id=req.request_id, priority=req.priority,
                        **tr)
            log.span_at("serve_prefill", t0, t1 - t0,
                        request_id=req.request_id, prompt_len=plen,
                        bucket=sbucket, slot=slot, replica=self.name, **tr)
            if m > 0:
                log.counter("serve_prefix_hits", 1)
                log.counter("serve_prefill_tokens_saved", m)
            else:
                log.counter("serve_prefix_misses", 1)
            if req.trace is not None and req.trace.sampled:
                # the admission's KV story (alloc / prefix share / COW)
                # as span events on the request's trace
                for ev_name, ev_attrs in res.trace_events():
                    log.event(ev_name, request_id=req.request_id,
                              replica=self.name, **ev_attrs, **tr)
        if req.max_new_tokens == 1 or first_tok == req.eos_id:
            pool.release(res)
            self._finish(req, slot=None, t_done=t1)
            return
        self._slots[slot] = self._new_slot(req, plen, t1, res=res)
        self._toks[slot] = first_tok
        self._pos[slot] = plen
        self._stats["max_active"] = max(self._stats["max_active"],
                                        self.num_active)

    def _decode_iteration(self) -> None:
        """One token boundary: advance every slot one position.  Idle
        lanes compute too (static shapes) — their writes land in slots
        the next admission overwrites wholesale."""
        params = self.model._decode_params()
        try:
            if self._paged:
                # grow tables lazily (reservation-backed, cannot fail),
                # then step at the smallest window bucket that covers
                # the longest active row — FLOPs follow actual length
                pool, bs = self._kvpool, self.config.kv_block
                need_w = 1
                for s in self._slots:
                    if s is not None:
                        pool.extend(s.res, s.pos)
                        need_w = max(need_w, s.pos // bs + 1)
                w = self._block_bucket(need_w)
                tables = np.zeros((len(self._slots), w), np.int32)
                for i, s in enumerate(self._slots):
                    if s is not None:
                        row = s.res.table()
                        tables[i, :len(row)] = row
                self._caches, nxt = self._get_paged_step_fn(w)(
                    params, self.model._stats, self._caches,
                    jnp.asarray(self._toks), jnp.asarray(self._pos),
                    jnp.asarray(tables))
            else:
                self._caches, nxt = self._get_step_fn()(
                    params, self.model._stats, self._caches,
                    jnp.asarray(self._toks), jnp.asarray(self._pos))
            nxt = np.asarray(nxt)
        except Exception as e:  # noqa: BLE001 — a step fault kills the
            # BATCH's requests but never the loop: resolve them all and
            # keep serving (fresh admissions re-prefill fresh caches).
            # A pool replica (decode_fatal) instead lets it propagate —
            # the in-flight requests stay UNRESOLVED for failover.
            if self._decode_fatal:
                raise
            msg = f"decode step failed: {type(e).__name__}: {e}"
            for i, slot in enumerate(self._slots):
                if slot is not None:
                    if slot.res is not None:
                        self._kvpool.release(slot.res)
                    slot.req._resolve(ERROR, msg)
                    self._stats["failed"] += 1
                    self._emit_done(slot.req)
                    self._slots[i] = None
            return
        t_now = time.perf_counter()
        active = self.num_active
        self._stats["step_iterations"] += 1
        self._stats["occupancy_sum"] += active
        if self._telemetry is not None:
            self._telemetry.gauge("serve_batch_occupancy", active,
                                  replica=self.name, **self._zone_attr)
            if self._paged:
                st = self._kvpool.stats()
                self._telemetry.gauge("serve_kv_blocks_used",
                                      st["blocks_used"], replica=self.name)
                # KV residency folded into the live-HBM series: block
                # accounting is host-side truth for device bytes the
                # allocator gauges can't attribute
                if self._kvpool.bytes_per_block:
                    self._telemetry.gauge(
                        "hbm_bytes",
                        float(st["blocks_used"]
                              * self._kvpool.bytes_per_block),
                        device="pool", kind="kv_blocks",
                        replica=self.name)
                self._telemetry.counter("serve_decode_window", 1,
                                        window=w * self.config.kv_block)
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            if slot.req.done():
                # resolved externally mid-decode (hedge loser force-
                # cancelled, pool shutdown): free the lane; the next
                # admission overwrites its cache slice wholesale
                if slot.res is not None:
                    self._kvpool.release(slot.res)
                self._slots[i] = None
                self._toks[i] = 0
                self._pos[i] = 0
                self._stats["cancelled"] += 1
                continue
            tok = int(nxt[i])
            slot.req.tokens.append(tok)
            slot.pos += 1
            self._pos[i] = slot.pos
            self._toks[i] = tok
            if slot.tr_t0 is not None and \
                    len(slot.req.tokens) - slot.tr_n0 >= self._trace_chunk:
                self._emit_chunk(slot, t_now)
            if (len(slot.req.tokens) >= slot.req.max_new_tokens
                    or tok == slot.req.eos_id):
                self._finish(slot.req, slot=i, t_done=t_now)

    def _emit_chunk(self, slot: _Slot, t_now: float) -> None:
        """Close the open decode chunk of a SAMPLED request: one span
        per FF_TRACE_CHUNK token boundaries, so a long decode renders
        as a train of chunks instead of one opaque bar."""
        req = slot.req
        n = len(req.tokens)
        self._telemetry.span_at(
            "serve_decode_chunk", slot.tr_t0, t_now - slot.tr_t0,
            request_id=req.request_id, token_from=slot.tr_n0,
            token_to=n, replica=self.name, **_reqtrace.tag(req.trace))
        slot.tr_t0 = t_now
        slot.tr_n0 = n

    def _finish(self, req: InferenceRequest, slot: Optional[int],
                t_done: float) -> None:
        if slot is not None:
            s = self._slots[slot]
            if s is not None and s.tr_t0 is not None \
                    and len(req.tokens) > s.tr_n0:
                self._emit_chunk(s, t_done)   # flush the partial chunk
            if s is not None and s.res is not None:
                self._kvpool.release(s.res)  # unused promise returns too
            self._slots[slot] = None
            self._toks[slot] = 0
            self._pos[slot] = 0
        req.t_done = t_done
        if req._resolve(DONE):
            self._stats["completed"] += 1
            self._stats["tokens_out"] += len(req.tokens)
        self._emit_done(req)

    def _emit_done(self, req: InferenceRequest) -> None:
        log = self._telemetry
        if log is None:
            return
        tr = _reqtrace.tag(req.trace)
        if req.t_first is not None and req.t_done is not None:
            log.span_at("serve_decode", req.t_first,
                        req.t_done - req.t_first,
                        request_id=req.request_id, tokens=len(req.tokens),
                        **tr)
        attrs = dict(request_id=req.request_id, status=req.status,
                     prompt_len=int(req.prompt.size),
                     new_tokens=len(req.tokens), replica=self.name,
                     **self._zone_attr, **tr)
        for k in ("queue_wait_s", "ttft_s", "tpot_s"):
            v = getattr(req, k)
            if v is not None:
                attrs[k] = round(v, 6)
        log.event("serve_request_done", **attrs)
        if req.status == DONE:
            log.counter("serve_requests", 1)
            log.counter("serve_tokens", len(req.tokens))
        else:
            log.counter("serve_failed", 1, status=req.status)
        log.flush()
