"""Stdlib HTTP front end for the continuous-batching engine.

``ThreadingHTTPServer``: each connection blocks its own handler thread
on the request future while the single engine loop batches the actual
decoding — the classic many-waiters/one-worker shape, with zero
dependencies beyond the standard library.

The backend can be a single ``InferenceEngine`` or a ``ReplicaPool``
(same ``submit``/``stats`` surface); with a pool the health endpoints
expose per-replica state and shedding maps to 503 + ``Retry-After``.

Endpoints::

  POST /generate   {"prompt": [int, ...], "max_new_tokens": 16,
                    "priority": 0, "timeout_s": 30, "eos_id": null}
              ->   200 {"request_id": .., "tokens": [..],
                        "queue_wait_s": .., "ttft_s": .., "tpot_s": ..,
                        "trace_id": ..}   (trace_id when telemetry is on
                        — the join key into the event log / timeline)
              ->   400 malformed body / validation error
              ->   503 queue-wait timeout      (Retry-After: 1)
              ->   503 admission shed          (Retry-After: estimate)
              ->   503 pool draining / not accepting
              ->   500 engine-side failure
  GET  /healthz -> liveness: 200 while serving or draining (per-replica
                   detail with a pool backend), 503 once no replica can
                   serve
  GET  /readyz  -> readiness: 200 iff new submits would be accepted —
                   the load-balancer signal; 503 while draining or down
  GET  /metrics -> Prometheus text: the live registry's series (when
                   FF_METRICS_PORT lights up the metrics plane) plus
                   scrape-time backend state — per-replica
                   health/incarnation, queue depth
                   (observability/metrics.py)
  GET  /debug/vars -> the same aggregates as expvar-style JSON

Sampling knobs are rejected (400): the engine is greedy-only, which is
what keeps its outputs bitwise-equal to ``FFModel.generate()``.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..observability import metrics as _metrics
from .queue import ServeError, ServeOverload, ServeTimeout

# request knobs forwarded verbatim to InferenceEngine.submit
_SUBMIT_KEYS = ("priority", "timeout_s", "eos_id", "request_id")


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # the ServingAPI instance hangs off the server object
    @property
    def api(self) -> "ServingAPI":
        return self.server.api  # type: ignore[attr-defined]

    def log_message(self, fmt, *args):  # default: stderr per request
        if self.api.verbose:
            super().log_message(fmt, *args)

    def _reply(self, code: int, payload: dict, **headers) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers.items():
            self.send_header(k.replace("_", "-"), str(v))
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 — http.server API
        path = self.path.split("?")[0]
        backend = self.api.engine
        uptime = round(time.perf_counter() - self.api.t0, 3)
        if path == "/healthz":
            if hasattr(backend, "healthz"):        # ReplicaPool
                payload = backend.healthz()
                code = 200 if payload["status"] in ("ok", "draining") \
                    else 503
            else:                                  # bare InferenceEngine
                payload = backend.stats()
                payload["status"] = "ok"
                code = 200
            payload["uptime_s"] = uptime
            self._reply(code, payload)
        elif path == "/readyz":
            if hasattr(backend, "ready"):          # ReplicaPool
                ready = bool(backend.ready())
            else:
                ready = bool(getattr(backend, "_accepting", False))
            self._reply(200 if ready else 503,
                        {"ready": ready, "uptime_s": uptime})
        elif path == "/metrics":
            # the backend's live state arrives via the provider that
            # start() registered — shared with the standalone exporter
            self._reply_text(
                200, _metrics.scrape_text().encode(),
                "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/debug/vars":
            reg = _metrics.global_registry()
            body = reg.render_vars() if reg is not None \
                else {"disabled": True}
            body["backend"] = backend.stats()
            self._reply(200, body)
        else:
            self._reply(404, {"error": f"no such endpoint {self.path!r}"})

    def do_POST(self) -> None:  # noqa: N802 — http.server API
        if self.path.split("?")[0] != "/generate":
            self._reply(404, {"error": f"no such endpoint {self.path!r}"})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            if float(body.get("temperature", 0) or 0) != 0.0:
                raise ValueError("sampling is not served (greedy only); "
                                 "omit temperature or pass 0")
            prompt = body["prompt"]
            kw = {k: body[k] for k in _SUBMIT_KEYS if body.get(k) is not None}
            req = self.api.engine.submit(
                prompt, body.get("max_new_tokens"), **kw)
        except ServeOverload as e:
            # admission control shed this request: tell the client when
            # to come back instead of letting latency collapse
            self._reply(503, {"error": str(e)},
                        Retry_After=max(1, round(e.retry_after_s)))
            return
        except ServeError as e:
            # not accepting (draining, stopped) — also a retryable 503
            self._reply(503, {"error": str(e)}, Retry_After=1)
            return
        except (KeyError, TypeError, ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"{type(e).__name__}: {e}"})
            return
        # trace_id in every reply that has a request: the client-side
        # join key for timeline_export / exemplar reporting
        trace = {"trace_id": req.trace.trace_id} \
            if req.trace is not None else {}
        try:
            tokens = req.result(self.api.result_timeout_s)
        except ServeTimeout as e:
            self._reply(503, {"error": str(e),
                              "request_id": req.request_id, **trace},
                        Retry_After=1)
            return
        except ServeError as e:
            self._reply(500, {"error": str(e),
                              "request_id": req.request_id, **trace})
            return
        out = {"request_id": req.request_id,
               "tokens": [int(t) for t in tokens],
               "prompt_len": int(req.prompt.size), **trace}
        for k in ("queue_wait_s", "ttft_s", "tpot_s"):
            v = getattr(req, k)
            if v is not None:
                out[k] = round(v, 6)
        self._reply(200, out)


class ServingAPI:
    """Owns the HTTP server; pair with a started ``InferenceEngine``
    or ``ReplicaPool`` (both expose ``submit``/``stats``/``config``).

    ``port=0`` binds an ephemeral port (tests); read ``api.port`` after
    ``start()``.  ``result_timeout_s`` bounds how long a handler thread
    waits on the engine before giving the client a 503 — it defaults to
    generous (an admitted request decodes in bounded time; queue waits
    are already bounded by the request's own ``timeout_s``).
    """

    def __init__(self, engine, host: Optional[str] = None,
                 port: Optional[int] = None,
                 result_timeout_s: float = 300.0, verbose: bool = False):
        self.engine = engine
        self.host = engine.config.host if host is None else host
        self._want_port = engine.config.port if port is None else port
        self.result_timeout_s = result_timeout_s
        self.verbose = verbose
        self.t0 = time.perf_counter()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._provider = None  # metrics scrape-time backend renderer

    @property
    def port(self) -> int:
        assert self._httpd is not None, "not started"
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ServingAPI":
        assert self._httpd is None, "already started"
        self._httpd = ThreadingHTTPServer((self.host, self._want_port),
                                          _Handler)
        self._httpd.api = self  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="ff-serve-http", daemon=True)
        self._thread.start()
        # Light up the live metrics plane (no-op unless FF_METRICS_PORT
        # is set) and publish this backend's scrape-time state — per-
        # replica health/incarnation, queue depth — to every /metrics
        # endpoint, standalone exporter included.
        _metrics.maybe_start()
        self._provider = lambda: _metrics.render_backend(self.engine)
        _metrics.register_provider(self._provider)
        return self

    def stop(self) -> None:
        if getattr(self, "_provider", None) is not None:
            _metrics.unregister_provider(self._provider)
            self._provider = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(10)
            self._thread = None

    def __enter__(self) -> "ServingAPI":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
