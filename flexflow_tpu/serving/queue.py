"""Request queue for the continuous-batching engine.

STDLIB-ONLY: the HTTP front end and tests manipulate requests without
touching jax.  An ``InferenceRequest`` doubles as the caller's future —
``result()`` blocks until the engine (or an expiry sweep) resolves it.

Admission order is (priority desc, arrival asc): a higher ``priority``
request overtakes earlier lower-priority ones at the next token
boundary, but never preempts already-running slots.  ``timeout_s``
bounds QUEUE WAIT — a request not admitted in time fails with status
``"timeout"`` instead of rotting behind a long backlog (the client has
usually given up; prefilling it anyway would waste a slot).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import List, Optional

import numpy as np

# terminal statuses set exactly once, under the queue/engine lock
QUEUED, RUNNING, DONE, ERROR, TIMEOUT, CANCELLED = (
    "queued", "running", "done", "error", "timeout", "cancelled")


class ServeError(RuntimeError):
    """The engine failed this request (prefill/decode error, shutdown)."""


class ServeTimeout(TimeoutError):
    """The request expired waiting for admission (``timeout_s``)."""


_req_ids = itertools.count(1)


class InferenceRequest:
    """One generation request + its result future.

    Filled in by the engine: ``tokens`` (the greedy continuation),
    ``status``, and the latency decomposition (``t_submit`` ->
    ``t_admit`` -> ``t_first`` -> ``t_done``, all ``time.perf_counter``
    readings) that serve_report folds into queue-wait/TTFT/TPOT.
    """

    def __init__(self, prompt, max_new_tokens: int, *, priority: int = 0,
                 timeout_s: Optional[float] = None,
                 eos_id: Optional[int] = None,
                 request_id: Optional[str] = None):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        self.priority = int(priority)
        self.timeout_s = None if timeout_s is None else float(timeout_s)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.request_id = request_id or f"req-{next(_req_ids)}"

        self.status = QUEUED
        self.tokens: List[int] = []
        self.error: Optional[str] = None
        self.t_submit: Optional[float] = None
        self.t_admit: Optional[float] = None
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None
        self.admit_seq: Optional[int] = None  # engine admission order
        self._event = threading.Event()

    # -- metrics (valid once resolved) ----------------------------------
    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.t_submit is None or self.t_admit is None:
            return None
        return self.t_admit - self.t_submit

    @property
    def ttft_s(self) -> Optional[float]:
        """Submit -> first generated token available."""
        if self.t_submit is None or self.t_first is None:
            return None
        return self.t_first - self.t_submit

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean time per output token AFTER the first."""
        if self.t_first is None or self.t_done is None \
                or len(self.tokens) < 2:
            return None
        return (self.t_done - self.t_first) / (len(self.tokens) - 1)

    # -- future protocol ------------------------------------------------
    def done(self) -> bool:
        return self._event.is_set()

    def _resolve(self, status: str, error: Optional[str] = None) -> None:
        self.status = status
        self.error = error
        if self.t_done is None:
            self.t_done = time.perf_counter()
        self._event.set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until resolved; the greedy continuation as (N,) int32.
        Raises ServeTimeout (queue-wait expiry) or ServeError (engine
        failure / shutdown)."""
        if not self._event.wait(timeout):
            raise ServeTimeout(
                f"{self.request_id}: no result after {timeout}s")
        if self.status == TIMEOUT:
            raise ServeTimeout(
                f"{self.request_id}: expired after {self.timeout_s}s "
                f"in queue")
        if self.status != DONE:
            raise ServeError(f"{self.request_id}: {self.status}"
                             f"{': ' + self.error if self.error else ''}")
        return np.asarray(self.tokens, np.int32)


class RequestQueue:
    """Thread-safe admission queue: (priority desc, arrival asc)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._heap: List = []          # (-priority, seq, req)
        self._seq = itertools.count()

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)

    def put(self, req: InferenceRequest) -> None:
        req.t_submit = time.perf_counter()
        with self._nonempty:
            heapq.heappush(self._heap, (-req.priority, next(self._seq), req))
            self._nonempty.notify_all()

    def pop_ready(self, now: float) -> Optional[InferenceRequest]:
        """Highest-priority live request, resolving any expired ones
        encountered on the way (their callers unblock with TIMEOUT)."""
        with self._lock:
            while self._heap:
                _, _, req = heapq.heappop(self._heap)
                if self._expired(req, now):
                    req._resolve(TIMEOUT)
                    continue
                return req
        return None

    def expire(self, now: float) -> int:
        """Resolve every expired queued request (runs at each token
        boundary so a backlogged request times out even while the
        batch is full and nothing is being popped)."""
        n = 0
        with self._lock:
            live = []
            for entry in self._heap:
                if self._expired(entry[2], now):
                    entry[2]._resolve(TIMEOUT)
                    n += 1
                else:
                    live.append(entry)
            if n:
                heapq.heapify(live)
                self._heap = live
        return n

    def drain(self, status: str = CANCELLED,
              error: Optional[str] = None) -> int:
        """Resolve everything still queued (engine shutdown)."""
        with self._lock:
            n = len(self._heap)
            for _, _, req in self._heap:
                req._resolve(status, error)
            self._heap = []
        return n

    def wait_nonempty(self, timeout: float) -> bool:
        with self._nonempty:
            if self._heap:
                return True
            return self._nonempty.wait(timeout)

    @staticmethod
    def _expired(req: InferenceRequest, now: float) -> bool:
        return (req.timeout_s is not None and req.t_submit is not None
                and now - req.t_submit > req.timeout_s)
