"""Request queue for the continuous-batching engine.

STDLIB-ONLY: the HTTP front end and tests manipulate requests without
touching jax.  An ``InferenceRequest`` doubles as the caller's future —
``result()`` blocks until the engine (or an expiry sweep) resolves it.

Admission order is (priority desc, arrival asc): a higher ``priority``
request overtakes earlier lower-priority ones at the next token
boundary, but never preempts already-running slots.  ``timeout_s``
bounds QUEUE WAIT — a request not admitted in time fails with status
``"timeout"`` instead of rotting behind a long backlog (the client has
usually given up; prefilling it anyway would waste a slot).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Collection, List, Optional, Union

import numpy as np

# terminal statuses set exactly once, under the queue/engine lock
QUEUED, RUNNING, DONE, ERROR, TIMEOUT, CANCELLED = (
    "queued", "running", "done", "error", "timeout", "cancelled")


class ServeError(RuntimeError):
    """The engine failed this request (prefill/decode error, shutdown)."""


class ServeTimeout(TimeoutError):
    """The request expired waiting for admission (``timeout_s``)."""


class ServeOverload(ServeError):
    """Admission control shed this request (queue full / estimated wait
    too long).  ``retry_after_s`` is the server's drain estimate — the
    HTTP layer forwards it as a 503 ``Retry-After`` header."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = max(1.0, float(retry_after_s))


_req_ids = itertools.count(1)


class InferenceRequest:
    """One generation request + its result future.

    Filled in by the engine: ``tokens`` (the greedy continuation),
    ``status``, and the latency decomposition (``t_submit`` ->
    ``t_admit`` -> ``t_first`` -> ``t_done``, all ``time.perf_counter``
    readings) that serve_report folds into queue-wait/TTFT/TPOT.
    """

    def __init__(self, prompt, max_new_tokens: int, *, priority: int = 0,
                 timeout_s: Optional[float] = None,
                 eos_id: Optional[int] = None,
                 request_id: Optional[str] = None):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        self.priority = int(priority)
        self.timeout_s = None if timeout_s is None else float(timeout_s)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.request_id = request_id or f"req-{next(_req_ids)}"

        self.status = QUEUED
        self.tokens: List[int] = []
        self.error: Optional[str] = None
        self.t_submit: Optional[float] = None
        self.t_admit: Optional[float] = None
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None
        self.admit_seq: Optional[int] = None  # engine admission order
        # replica-pool fields: ``avoid`` names an engine uid — or a
        # tuple of keys (engine uid, "zone:<z>") — that must NOT pop
        # this request (hedge/failover re-dispatch targets a different
        # replica, and with zones a different failure domain);
        # ``admitted_by`` is stamped at admission
        self.avoid: Union[None, str, tuple] = None
        self.admitted_by: Optional[str] = None
        # request-scoped tracing (observability/reqtrace.TraceContext):
        # minted once at admission when telemetry is on, None otherwise.
        # Pool attempts carry a CHILD context of the client's root span.
        self.trace = None
        self._event = threading.Event()
        self._rlock = threading.RLock()   # guards the resolve CAS
        self._callbacks: List = []

    # -- metrics (valid once resolved) ----------------------------------
    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.t_submit is None or self.t_admit is None:
            return None
        return self.t_admit - self.t_submit

    @property
    def ttft_s(self) -> Optional[float]:
        """Submit -> first generated token available."""
        if self.t_submit is None or self.t_first is None:
            return None
        return self.t_first - self.t_submit

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean time per output token AFTER the first."""
        if self.t_first is None or self.t_done is None \
                or len(self.tokens) < 2:
            return None
        return (self.t_done - self.t_first) / (len(self.tokens) - 1)

    # -- future protocol ------------------------------------------------
    def done(self) -> bool:
        return self._event.is_set()

    def add_done_callback(self, fn) -> None:
        """``fn(req)`` runs exactly once, after resolution (immediately
        if already resolved).  Callbacks fire OUTSIDE the request lock,
        on whichever thread resolves the request."""
        with self._rlock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _resolve(self, status: str, error: Optional[str] = None) -> bool:
        """Compare-and-swap resolution: exactly one caller wins; every
        later attempt (a failed-over replica waking up, a hedge loser, a
        second expiry sweep) is a no-op.  Returns True iff this call
        resolved the request."""
        with self._rlock:
            if self._event.is_set():
                return False
            self.status = status
            self.error = error
            if self.t_done is None:
                self.t_done = time.perf_counter()
            cbs, self._callbacks = self._callbacks, []
            self._event.set()
        for cb in cbs:
            cb(self)
        return True

    def cancel(self, reason: str = "cancelled",
               force: bool = False) -> bool:
        """CAS to CANCELLED.  By default a no-op when the request is
        already RUNNING (mid-decode work is left to finish — the caller
        abandoned it, the engine did not); ``force=True`` cancels a
        running request too (hedge losers, pool shutdown) — the engine
        releases the slot at the next token boundary."""
        with self._rlock:
            if self._event.is_set():
                return False
            if self.status == RUNNING and not force:
                return False
            return self._resolve(CANCELLED, reason)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until resolved; the greedy continuation as (N,) int32.
        Raises ServeTimeout (queue-wait expiry) or ServeError (engine
        failure / shutdown).  A caller giving up (``timeout`` elapsed)
        CANCELS a still-queued request so abandoned work can never
        occupy a decode slot; a request already running is left to
        finish (its tokens are already half-paid-for)."""
        if not self._event.wait(timeout):
            self.cancel("caller gave up waiting")
            raise ServeTimeout(
                f"{self.request_id}: no result after {timeout}s")
        if self.status == TIMEOUT:
            raise ServeTimeout(
                f"{self.request_id}: expired after {self.timeout_s}s "
                f"in queue")
        if self.status != DONE:
            raise ServeError(f"{self.request_id}: {self.status}"
                             f"{': ' + self.error if self.error else ''}")
        return np.asarray(self.tokens, np.int32)


class RequestQueue:
    """Thread-safe admission queue: (priority desc, arrival asc)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._heap: List = []          # (-priority, seq, req)
        self._seq = itertools.count()
        self._sweep_stop: Optional[threading.Event] = None
        self._sweeper: Optional[threading.Thread] = None

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)

    def put(self, req: InferenceRequest) -> None:
        """Enqueue (or RE-enqueue: a failover/hedge attempt keeps its
        original ``t_submit`` so queue-wait metrics and the admission
        timeout stay truthful to the caller's clock)."""
        now = time.perf_counter()
        if req.t_submit is None:
            req.t_submit = now
        with self._nonempty:
            heapq.heappush(self._heap, (-req.priority, next(self._seq), req))
            self._nonempty.notify_all()
        # sweep on the put path too: an idle queue must not hold a dead
        # request's caller hostage until somebody pops
        self.expire(now)

    def pop_ready(self, now: float,
                  avoid_key: Union[None, str, Collection[str]] = None
                  ) -> Optional[InferenceRequest]:
        """Highest-priority live request, resolving any expired ones
        encountered on the way (their callers unblock with TIMEOUT).
        Requests already resolved externally (caller cancel, hedge
        winner) are dropped; requests whose ``avoid`` keys intersect
        ``avoid_key`` (either side may be a single key or a collection
        of keys) are left queued for a DIFFERENT replica."""
        expired: List[InferenceRequest] = []
        skipped: List = []
        got: Optional[InferenceRequest] = None
        with self._lock:
            while self._heap:
                entry = heapq.heappop(self._heap)
                req = entry[2]
                if req.done():
                    continue
                if self._expired(req, now):
                    expired.append(req)
                    continue
                if self._avoided(req.avoid, avoid_key):
                    skipped.append(entry)
                    continue
                got = req
                break
            for entry in skipped:
                heapq.heappush(self._heap, entry)
        for req in expired:     # resolve OUTSIDE the lock: callbacks
            req._resolve(TIMEOUT)
        return got

    @staticmethod
    def _avoided(avoid, avoid_key) -> bool:
        if avoid is None or avoid_key is None:
            return False
        av = (avoid,) if isinstance(avoid, str) else avoid
        keys = (avoid_key,) if isinstance(avoid_key, str) else avoid_key
        return any(a in keys for a in av)

    def expire(self, now: float) -> int:
        """Resolve every expired queued request (runs at each token
        boundary so a backlogged request times out even while the
        batch is full and nothing is being popped)."""
        expired: List[InferenceRequest] = []
        with self._lock:
            live = []
            for entry in self._heap:
                if self._expired(entry[2], now):
                    expired.append(entry[2])
                else:
                    live.append(entry)
            if expired:
                heapq.heapify(live)
                self._heap = live
        n = 0
        for req in expired:     # outside the lock: callbacks may re-lock
            n += bool(req._resolve(TIMEOUT))
        return n

    def drain(self, status: str = CANCELLED,
              error: Optional[str] = None) -> int:
        """Resolve everything still queued (engine shutdown)."""
        with self._lock:
            entries, self._heap = self._heap, []
        n = 0
        for _, _, req in entries:
            n += bool(req._resolve(status, error))
        return n

    def wait_nonempty(self, timeout: float) -> bool:
        # sweep BEFORE blocking: a request whose deadline passed while
        # the queue sat idle is released here, not at the next put/pop
        self.expire(time.perf_counter())
        with self._nonempty:
            if self._heap:
                return True
            return self._nonempty.wait(timeout)

    # -- standalone expiry sweeper --------------------------------------
    # The put/pop/wait sweeps above only run while SOMEONE is moving the
    # queue.  During a pool drain (or after an engine wedges) nothing
    # puts or pops, so a parked request could outlive its deadline — and
    # its caller's give-up cancel in ``result()`` would be the only way
    # out.  The sweeper keeps expiry and caller-cancel resolution
    # flowing no matter what the engines are doing.
    def start_sweeper(self, interval_s: float = 0.05) -> None:
        """Start a daemon thread sweeping expiry every ``interval_s``
        seconds.  Idempotent; ``stop_sweeper`` ends it."""
        with self._lock:
            if self._sweeper is not None and self._sweeper.is_alive():
                return
            stop = threading.Event()
            t = threading.Thread(
                target=self._sweep_loop, args=(stop, float(interval_s)),
                name="ff-queue-sweeper", daemon=True)
            self._sweep_stop, self._sweeper = stop, t
        t.start()

    def stop_sweeper(self, timeout: float = 2.0) -> None:
        with self._lock:
            stop, t = self._sweep_stop, self._sweeper
            self._sweep_stop = self._sweeper = None
        if stop is not None:
            stop.set()
        if t is not None and t.is_alive():
            t.join(timeout)

    def _sweep_loop(self, stop: threading.Event, interval_s: float) -> None:
        while not stop.wait(interval_s):
            self.expire(time.perf_counter())

    @staticmethod
    def _expired(req: InferenceRequest, now: float) -> bool:
        return (req.timeout_s is not None and req.t_submit is not None
                and now - req.t_submit > req.timeout_s)
