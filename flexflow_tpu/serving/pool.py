"""Health-checked replica pool: N engines behind one admission queue.

The continuous-batching ``InferenceEngine`` (engine.py) is a single
point of failure: one wedged decode step or one poisoned slot pool
takes the whole service down, and overload has no defined behavior
beyond unbounded queue growth.  ``ReplicaPool`` is the robustness
layer over it, following the TensorFlow fault-tolerance stance
(PAPERS.md, arXiv 1605.08695): assume replicas FAIL, detect it with
health checks, and recover by re-execution — never by preventing the
failure.

Architecture — the ATTEMPT-CLONE model::

    caller ── submit() ──> client InferenceRequest  (never enqueued)
                                │ 1..k attempts
                                v
            attempt InferenceRequest ("req-7#a1", "req-7#a2", ...)
                                │  shared RequestQueue
             ┌──────────────────┼──────────────────┐
         replica-0          replica-1          replica-2
        (own engine,       (own engine,       (own engine,
         own jit fns,       own kv pool)       own kv pool)
         own kv pool)

    Each dispatch is a FRESH engine-level request; a done-callback
    transfers the winning attempt's tokens/timestamps to the client via
    the CAS in ``InferenceRequest._resolve``.  A wedged replica waking
    up hours later and resolving its stale attempt simply LOSES the CAS
    — the client can never be double-resolved, and failover/hedging
    reduce to "make another attempt, first finisher wins".

Replicas are thread-isolated on CPU (one shared compiled model — the
jitted step is pure, params are read-only); on real hardware pass one
model per disjoint device slice (``models=[m0, m1, ...]``) and each
replica's engine, caches, and compiles live on its own slice.

Health model (monitor thread):

* every engine-loop iteration stamps ``engine.last_beat``; a beat older
  than ``FF_SERVE_REPLICA_TIMEOUT`` means the loop is wedged (injected
  ``replica_hang``, a stuck device transfer),
* a loop that THROWS (``decode_fatal`` engines re-raise decode faults;
  ``replica_kill`` propagates through admission) records
  ``engine.crashed`` and dies.

Either way the replica is marked down (``replica_down`` event), its
engine is abandoned (never joined — the thread may be asleep inside an
injected hang), its in-flight attempts are failed over (new attempts,
``avoid`` = the dead incarnation's uid so only OTHER replicas — or a
future restart of this one — can pop them, ``request_failover``
events), and a restart is scheduled with the shared bounded exponential
backoff (``runtime/resilience.backoff_delay``, ``replica_restart``
event on success).

Admission control (``submit``): with ``FF_SERVE_MAX_QUEUE`` set, a full
queue sheds with ``ServeOverload`` (HTTP 503 + ``Retry-After`` from the
estimated drain time); ``FF_SERVE_SHED_WAIT_S`` additionally sheds when
the estimated wait alone is too long.  Hedging (``FF_SERVE_HEDGE_MS``):
a request still unfinished that long after submit gets a second attempt
on a different replica; the losing attempt is force-cancelled and its
slot freed at the next token boundary.

Graceful degradation: ``attach_preemption`` wires a PR-4
``PreemptionHandler`` so SIGTERM drains the pool (finish everything
admitted or queued, shed nothing mid-flight, ``pool_drain`` event).
Losing replicas degrades THROUGHPUT only: greedy outputs are bitwise
``FFModel.generate()`` regardless of which replica, restart, or
failover served them, because every attempt prefills from scratch.

Zones (``FF_SERVE_ZONES``): replicas are placed round-robin across the
named failure domains and carry the zone as a telemetry label.  Hedges
and zone-outage failovers avoid the FIRST attempt's whole zone (the
``avoid`` key set grows ``"zone:<z>"``), so correlated failures — the
``serve:...=zone_outage[:zone]`` chaos fault marks every replica of a
zone down at once — strand nothing: the monitor fails all in-flight
attempts over exactly-once (same CAS model) and replicas in a down
zone are NOT restarted in place; capacity comes back via
``add_replica`` in surviving zones (the autoscaler's backfill).

Elastic membership (serving/autoscaler.py drives these, but they are
plain pool API): ``add_replica`` spawns a fresh replica;
``drain_replica`` gracefully retires one — it stops popping new work,
finishes its in-flight slots, then the incarnation is REMOVED from the
replica list so ``healthz``/``ff_replica_up`` never report a dead
series forever.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ..observability import reqtrace as _reqtrace
from ..runtime.resilience import backoff_delay
from .config import ServeConfig
from .engine import ABANDON_HANDBACK, InferenceEngine
from .queue import (CANCELLED, DONE, InferenceRequest, RequestQueue,
                    ServeError, ServeOverload)

import numpy as np

# replica states
READY, RESTARTING, STOPPED, DRAINING = (
    "ready", "restarting", "stopped", "draining")


class _Replica:
    """One replica slot: a stable name + the current engine incarnation
    and its restart bookkeeping."""

    __slots__ = ("name", "model", "engine", "state", "fails", "restarts",
                 "restart_at", "failovers", "zone")

    def __init__(self, name: str, model, zone: Optional[str] = None):
        self.name = name
        self.model = model
        self.zone = zone
        self.engine: Optional[InferenceEngine] = None
        self.state = STOPPED
        self.fails = 0           # consecutive down-marks (backoff input)
        self.restarts = 0        # successful restarts
        self.restart_at = 0.0
        self.failovers = 0       # requests moved OFF this replica


class _Client:
    """Pool-side state of one client request."""

    __slots__ = ("req", "attempts", "hedged", "n_attempts")

    def __init__(self, req: InferenceRequest):
        self.req = req
        self.attempts: List[InferenceRequest] = []
        self.hedged = False
        self.n_attempts = 0


class ReplicaPool:
    """N ``InferenceEngine`` replicas behind one admission queue.

    Usage::

        pool = ReplicaPool(model, replicas=3, max_queue=64)
        with pool:
            h = pool.submit([1, 2, 3], max_new_tokens=16)
            tokens = h.result(timeout=30)

    ``models`` may be a single compiled model (replicated
    ``config.replicas`` times, thread-isolated — the CPU/test shape) or
    a sequence of models, one per disjoint device slice (the TPU shape;
    ``replicas`` is then ``len(models)``).
    """

    def __init__(self, models, config: Optional[ServeConfig] = None,
                 telemetry=None, **overrides):
        self.config = config if config is not None \
            else ServeConfig.from_env(**overrides)
        if isinstance(models, (list, tuple)):
            model_list: Sequence = list(models)
        else:
            model_list = [models] * self.config.replicas
        if not model_list:
            raise ValueError("ReplicaPool needs at least one model")
        self._telemetry = telemetry if telemetry is not None \
            else getattr(model_list[0], "_telemetry", None)

        self._queue = RequestQueue()
        zones = self.config.zones
        self._replicas = [
            _Replica(f"replica-{i}", m,
                     zone=zones[i % len(zones)] if zones else None)
            for i, m in enumerate(model_list)]
        # models to hand to replicas added later (round-robin over the
        # DISTINCT models the caller gave us; on CPU they share one
        # compiled model, on hardware one per device slice)
        self._model_pool = list(models) if isinstance(models, (list, tuple)) \
            else [models]
        self._replica_seq = itertools.count(len(model_list))
        self._zones_down: set = set()   # chaos-marked failure domains
        self._chaos = getattr(model_list[0], "_chaos", None)
        self._lock = threading.RLock()
        self._clients: Dict[str, _Client] = {}    # client id -> state
        self._attempts: Dict[str, _Client] = {}   # attempt id -> state
        self._accepting = False
        self._draining = False
        self._stop_evt = threading.Event()
        self._monitor_thread: Optional[threading.Thread] = None
        self._preemption = None
        self._svc_ewma: Optional[float] = None   # submit->done seconds
        self._last_ready_gauge: Optional[int] = None
        self._stats = dict(submitted=0, shed=0, hedged=0, failovers=0,
                           completed=0, failed=0, timeouts=0, cancelled=0,
                           replica_downs=0, replica_restarts=0,
                           replicas_added=0, replicas_retired=0,
                           zone_outages=0)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "ReplicaPool":
        assert self._monitor_thread is None, "pool already started"
        for rep in self._replicas:
            self._spawn_engine(rep)
        # standalone expiry sweeper: keeps queue-wait deadlines honest
        # even while every engine is draining (nothing puts or pops)
        self._queue.start_sweeper()
        self._accepting = True
        self._stop_evt.clear()
        self._monitor_thread = threading.Thread(
            target=self._monitor, name="ff-pool-monitor", daemon=True)
        self._monitor_thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 120.0) -> None:
        """Stop the pool.  ``drain=True`` finishes everything admitted
        or queued first (the SIGTERM path); ``drain=False`` cancels all
        outstanding work."""
        if drain:
            self._begin_drain("stop")
        else:
            with self._lock:
                self._accepting = False
                self._draining = True
            for rep in list(self._replicas):
                if rep.engine is not None and rep.state == READY:
                    rep.engine.stop(drain=False)
                rep.state = STOPPED
            self._queue.drain(CANCELLED, "pool stopped")
            self._cancel_leftover("pool stopped")
        self._stop_evt.set()
        t = self._monitor_thread
        if t is not None:
            t.join(timeout)
            self._monitor_thread = None
        self._queue.stop_sweeper()

    def __enter__(self) -> "ReplicaPool":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(drain=not any(exc))

    def attach_preemption(self, handler) -> None:
        """Wire a ``runtime.resilience.PreemptionHandler``: once its
        flag is set (SIGTERM/SIGINT), the monitor drains the pool and
        exits — in-flight and queued work completes, new submits are
        refused."""
        self._preemption = handler

    def _spawn_engine(self, rep: _Replica) -> None:
        rep.engine = InferenceEngine(
            rep.model, config=self.config, telemetry=self._telemetry,
            queue=self._queue, name=rep.name, decode_fatal=True,
            zone=rep.zone)
        rep.engine.start()
        rep.state = READY

    # ------------------------------------------------------------------
    # elastic membership (the autoscaler's levers; plain pool API)
    # ------------------------------------------------------------------
    def add_replica(self, zone: Optional[str] = None) -> Optional[str]:
        """Scale up: spawn one fresh replica and return its name.
        ``zone=None`` auto-places in the least-populated zone that is
        not chaos-marked down (the surviving-zone backfill path).
        Returns None while the pool is not accepting (drain/stop)."""
        with self._lock:
            if not self._accepting or self._draining:
                return None
            idx = next(self._replica_seq)
            z = zone if zone is not None else self._pick_zone()
            model = self._model_pool[idx % len(self._model_pool)]
            rep = _Replica(f"replica-{idx}", model, zone=z)
            self._replicas.append(rep)
        try:
            self._spawn_engine(rep)
        except Exception as e:  # noqa: BLE001 — surface, don't die
            with self._lock:
                if rep in self._replicas:
                    self._replicas.remove(rep)
            if self._telemetry is not None:
                self._telemetry.event(
                    "replica_add_failed", replica=rep.name,
                    error=f"{type(e).__name__}: {e}")
                self._telemetry.flush()
            return None
        with self._lock:
            self._stats["replicas_added"] += 1
        log = self._telemetry
        if log is not None:
            attrs = dict(replica=rep.name, incarnation=rep.engine.uid)
            if z is not None:
                attrs["zone"] = z
            log.event("replica_added", **attrs)
            log.flush()
        return rep.name

    def drain_replica(self, name: Optional[str] = None,
                      timeout: float = 60.0) -> Optional[str]:
        """Scale down, gracefully: pick a READY victim (``name``, or the
        newest replica in the most-populated zone), stop admitting to
        it, let its in-flight slots finish, then RETIRE the incarnation
        — it disappears from ``healthz``/``ff_replica_up`` so scrapes
        never report a dead series.  A victim that wedges mid-drain is
        abandoned and its work failed over like a crash.  Returns the
        retired name, or None when nothing is drainable."""
        with self._lock:
            if self._draining:
                return None
            ready = [r for r in self._replicas if r.state == READY]
            if name is not None:
                victim = next((r for r in ready if r.name == name), None)
            elif not ready:
                victim = None
            else:
                def crowd(r):
                    return sum(1 for o in ready if o.zone == r.zone)
                victim = max(reversed(ready), key=crowd)
            if victim is None:
                return None
            victim.state = DRAINING
        eng = victim.engine
        eng.retire(timeout=timeout)
        if eng.alive() or eng.crashed is not None:
            self._fail_over(victim, "drain timeout"
                            if eng.alive() else f"crashed mid-drain: "
                            f"{eng.crashed}")
        with self._lock:
            if victim in self._replicas:
                self._replicas.remove(victim)
            victim.state = STOPPED
            self._stats["replicas_retired"] += 1
        log = self._telemetry
        if log is not None:
            attrs = dict(replica=victim.name, incarnation=eng.uid)
            if victim.zone is not None:
                attrs["zone"] = victim.zone
            log.event("replica_retired", **attrs)
            log.flush()
        return victim.name

    def _pick_zone(self) -> Optional[str]:
        """Least-populated zone that is not down (ties: config order)."""
        zones = self.config.zones
        if not zones:
            return None
        with self._lock:
            counts = {z: 0 for z in zones}
            for r in self._replicas:
                if r.zone in counts and r.state != STOPPED:
                    counts[r.zone] += 1
            alive = [z for z in zones if z not in self._zones_down]
        return min(alive or list(zones), key=lambda z: counts[z])

    # ------------------------------------------------------------------
    # submission (admission control lives here)
    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None, *,
               priority: int = 0, timeout_s: Optional[float] = None,
               eos_id: Optional[int] = None,
               request_id: Optional[str] = None) -> InferenceRequest:
        """Enqueue one prompt; returns the CLIENT request handle.
        Raises ``ServeOverload`` (503 + Retry-After) when admission
        control sheds, ``ValueError`` on shape problems, ``ServeError``
        when the pool is not accepting."""
        cfg = self.config
        n = cfg.max_new_tokens if max_new_tokens is None \
            else int(max_new_tokens)
        client = InferenceRequest(
            prompt, n, priority=priority, eos_id=eos_id,
            request_id=request_id,
            timeout_s=cfg.queue_timeout_s if timeout_s is None
            else timeout_s)
        if client.timeout_s == 0:
            client.timeout_s = None          # 0: wait forever
        cfg.validate_request(int(client.prompt.size), n)
        if not self._accepting:
            raise ServeError("pool is not accepting requests "
                             "(not started, draining, or stopped)")
        self._check_admission()
        # root trace context: minted ONCE here at admission; every
        # attempt gets a child context in _dispatch so failover/hedge
        # races render as sibling spans under one trace_id
        if self._telemetry is not None:
            client.trace = _reqtrace.begin(self._telemetry)
        st = _Client(client)
        with self._lock:
            self._stats["submitted"] += 1
            self._clients[client.request_id] = st
            client.add_done_callback(
                lambda r, st=st: self._on_client_done(st, r))
            self._dispatch(st, first=True)
        if client.trace is not None and client.trace.sampled:
            client.add_done_callback(self._emit_request_span)
        return client

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 timeout: Optional[float] = None, **kw) -> np.ndarray:
        """Synchronous convenience: submit + result."""
        return self.submit(prompt, max_new_tokens, **kw).result(timeout)

    def _check_admission(self) -> None:
        """Count- and estimated-wait-based load shedding."""
        cfg = self.config
        if not cfg.max_queue and not cfg.shed_wait_s:
            return
        qlen = len(self._queue)
        ready = sum(r.state == READY for r in list(self._replicas))
        svc = self._svc_ewma if self._svc_ewma is not None else 0.1
        capacity = max(1, ready) * cfg.max_batch
        est_wait = (qlen + 1) * svc / capacity
        reason = None
        if cfg.max_queue and qlen >= cfg.max_queue:
            reason = (f"queue full ({qlen} >= FF_SERVE_MAX_QUEUE="
                      f"{cfg.max_queue})")
        elif cfg.shed_wait_s and est_wait > cfg.shed_wait_s:
            reason = (f"estimated wait {est_wait:.2f}s exceeds "
                      f"FF_SERVE_SHED_WAIT_S={cfg.shed_wait_s:g}")
        if reason is None:
            return
        self._stats["shed"] += 1
        log = self._telemetry
        if log is not None:
            log.event("request_shed", reason=reason, queued=qlen,
                      ready_replicas=ready,
                      retry_after_s=round(est_wait, 3))
            log.counter("serve_shed", 1)
            log.flush()
        raise ServeOverload(f"overloaded: {reason}",
                            retry_after_s=est_wait)

    # ------------------------------------------------------------------
    # attempts (dispatch, transfer, failover, hedge)
    # ------------------------------------------------------------------
    def _dispatch(self, st: _Client, first: bool = False,
                  avoid=None) -> InferenceRequest:
        """Create + enqueue one attempt for ``st`` (pool lock held).
        Only the FIRST attempt carries the admission timeout — a
        failover/hedge attempt already won admission once and must not
        instant-expire against the original submit clock."""
        c = st.req
        st.n_attempts += 1
        att = InferenceRequest(
            c.prompt, c.max_new_tokens, priority=c.priority,
            eos_id=c.eos_id,
            request_id=f"{c.request_id}#a{st.n_attempts}",
            timeout_s=c.timeout_s if first else None)
        now = time.perf_counter()
        if c.t_submit is None:
            c.t_submit = now
        att.t_submit = c.t_submit    # queue-wait stays the CALLER's clock
        att.avoid = avoid
        if c.trace is not None:
            # child span per attempt: the engine's queue-wait/prefill/
            # decode records parent to THIS attempt, so two racing
            # attempts never interleave on one span
            att.trace = c.trace.child()
            if att.trace.sampled:
                att.add_done_callback(self._emit_attempt_span)
        st.attempts.append(att)
        self._attempts[att.request_id] = st
        att.add_done_callback(
            lambda a, st=st: self._on_attempt_done(st, a))
        self._queue.put(att)
        return att

    def _emit_request_span(self, req: InferenceRequest) -> None:
        """Root span of a SAMPLED client request: submit -> resolution.
        Fires once, on whichever thread resolved the client."""
        log = self._telemetry
        if log is None or req.t_submit is None:
            return
        t1 = req.t_done if req.t_done is not None else time.perf_counter()
        log.span_at("serve_request", req.t_submit, t1 - req.t_submit,
                    request_id=req.request_id, status=req.status,
                    **req.trace.ids())

    def _emit_attempt_span(self, att: InferenceRequest) -> None:
        """One attempt's span (child of the client root).  Starts on the
        CALLER's submit clock — the engine's serve_queue_wait span for
        this attempt then nests inside it even after a failover."""
        log = self._telemetry
        if log is None or att.t_submit is None:
            return
        t1 = att.t_done if att.t_done is not None else time.perf_counter()
        inc = att.admitted_by or ""
        log.span_at("serve_attempt", att.t_submit, t1 - att.t_submit,
                    request_id=att.request_id, status=att.status,
                    replica=inc.split("#")[0], incarnation=inc,
                    **att.trace.ids())

    def _on_attempt_done(self, st: _Client, att: InferenceRequest) -> None:
        """An attempt resolved (any thread).  Tracked attempts transfer
        their outcome to the client; anything already untracked is a
        stale incarnation artifact and is ignored."""
        with self._lock:
            if all(a is not att for a in st.attempts):
                return
            st.attempts.remove(att)
            self._attempts.pop(att.request_id, None)
            c = st.req
            if att.status == DONE:
                self._note_service_time(att)
                if not c.done():
                    # copy BEFORE the CAS: once resolved, readers may
                    # look at tokens/timestamps at any moment
                    c.tokens = list(att.tokens)
                    c.t_admit = att.t_admit
                    c.t_first = att.t_first
                    c.t_done = att.t_done
                    c.admitted_by = att.admitted_by
                c._resolve(DONE)
                return
            if st.attempts:
                # a sibling attempt (hedge) is still in flight — let it
                # decide the client's fate
                return
            if (att.status == CANCELLED
                    and att.error == ABANDON_HANDBACK
                    and not c.done() and self._accepting):
                # the abandoned engine popped this attempt AFTER the
                # failover snapshot and handed it back on exit —
                # re-dispatch to a survivor (exactly-once holds: the
                # client is unresolved and the old attempt already lost)
                new = self._dispatch(st, avoid=att.admitted_by)
                self._stats["failovers"] += 1
            else:
                c.error = att.error
                c._resolve(att.status, att.error)
                return
        log = self._telemetry
        if log is not None:
            log.event("request_failover", request_id=st.req.request_id,
                      from_replica=(att.admitted_by or "").split("#")[0],
                      attempt=new.request_id, reason="abandon handback",
                      **_reqtrace.tag(st.req.trace))
            log.counter("serve_failovers", 1)

    def _on_client_done(self, st: _Client, req: InferenceRequest) -> None:
        """Client resolved (transfer, shed, cancel, drain): cancel any
        attempt still in flight — force, so a hedge loser's decode slot
        frees at the next token boundary — and drop the state."""
        with self._lock:
            atts, st.attempts = st.attempts, []
            for a in atts:
                self._attempts.pop(a.request_id, None)
            self._clients.pop(req.request_id, None)
            key = {DONE: "completed", CANCELLED: "cancelled",
                   "timeout": "timeouts"}.get(req.status, "failed")
            self._stats[key] += 1
        for a in atts:
            a.cancel("client resolved", force=True)

    def _note_service_time(self, att: InferenceRequest) -> None:
        if att.t_submit is None or att.t_done is None:
            return
        dt = att.t_done - att.t_submit
        self._svc_ewma = dt if self._svc_ewma is None \
            else 0.8 * self._svc_ewma + 0.2 * dt

    def _fail_over(self, rep: _Replica, reason: str,
                   extra_avoid: Sequence[str] = ()) -> int:
        """Move a down replica's in-flight attempts to survivors.
        ``extra_avoid`` widens the avoid-key set beyond the dead
        incarnation (a zone outage adds ``zone:<z>`` so NO replica in
        the dead zone can pop the re-dispatch)."""
        eng = rep.engine
        eng.abandon()
        avoid = eng.uid if not extra_avoid \
            else (eng.uid,) + tuple(extra_avoid)
        moved = 0
        for att in eng.active_requests():
            with self._lock:
                st = self._attempts.get(att.request_id)
                if st is None or st.req.done() \
                        or all(a is not att for a in st.attempts):
                    continue
                st.attempts.remove(att)
                self._attempts.pop(att.request_id, None)
                new = self._dispatch(st, avoid=avoid)
            # cancel AFTER untracking: the dead incarnation waking up
            # and resolving the old attempt is now a guaranteed no-op
            att.cancel(f"failover: {reason}", force=True)
            moved += 1
            rep.failovers += 1
            self._stats["failovers"] += 1
            log = self._telemetry
            if log is not None:
                log.event("request_failover",
                          request_id=st.req.request_id,
                          from_replica=rep.name, attempt=new.request_id,
                          reason=reason, **_reqtrace.tag(st.req.trace))
                log.counter("serve_failovers", 1)
        if self._telemetry is not None:
            self._telemetry.flush()
        return moved

    # ------------------------------------------------------------------
    # the monitor (health checks, restarts, hedging, preemption)
    # ------------------------------------------------------------------
    def _monitor_interval(self) -> float:
        cfg = self.config
        iv = min(0.05, cfg.replica_timeout_s / 4.0)
        if cfg.hedge_ms:
            iv = min(iv, cfg.hedge_ms / 4000.0)
        return max(iv, 0.005)

    def _monitor(self) -> None:
        cfg = self.config
        iv = self._monitor_interval()
        while not self._stop_evt.wait(iv):
            if self._preemption is not None and self._preemption.requested \
                    and not self._draining:
                self._begin_drain(f"signal {self._preemption.signum}")
                break
            now = time.perf_counter()
            self._check_zone_outage(now)
            for rep in list(self._replicas):
                if rep.state == READY:
                    bad = self._diagnose(rep.engine, now)
                    if bad is not None:
                        self._mark_down(rep, bad, now)
                elif rep.state == RESTARTING and now >= rep.restart_at \
                        and (rep.zone is None
                             or rep.zone not in self._zones_down):
                    # a replica in a chaos-downed zone stays down in
                    # place; the autoscaler backfills elsewhere
                    self._restart(rep)
            self._emit_ready_gauge()
            if cfg.hedge_ms:
                self._hedge_scan(now)

    def _check_zone_outage(self, now: float) -> None:
        """Poll the chaos monkey's recorded zone-outage state: a newly
        down zone marks EVERY ready replica in it down at once, and all
        their stranded attempts fail over with the zone in the avoid
        set (exactly-once: the usual attempt CAS)."""
        mk = self._chaos
        zones = self.config.zones
        if mk is None or not zones:
            return
        for zi in tuple(getattr(mk, "zones_down", ()) or ()):
            z = zones[int(zi) % len(zones)]
            if z in self._zones_down:
                continue
            self._zones_down.add(z)
            self._stats["zone_outages"] += 1
            victims = [r for r in list(self._replicas)
                       if r.zone == z and r.state == READY]
            log = self._telemetry
            if log is not None:
                log.event("zone_down", zone=z,
                          replicas=[r.name for r in victims])
                log.counter("serve_zone_outages", 1, zone=z)
                log.flush()
            for rep in victims:
                self._mark_down(rep, f"zone outage: {z}", now,
                                extra_avoid=(f"zone:{z}",))

    def _emit_ready_gauge(self) -> None:
        """pool_ready_replicas (+ per-zone) on every change — the
        replica-count timeline serve_report and fleet_bench plot."""
        log = self._telemetry
        if log is None:
            return
        reps = list(self._replicas)
        ready = sum(r.state == READY for r in reps)
        if ready == self._last_ready_gauge:
            return
        self._last_ready_gauge = ready
        log.gauge("pool_ready_replicas", ready)
        for z in self.config.zones:
            log.gauge("pool_zone_ready",
                      sum(r.state == READY for r in reps if r.zone == z),
                      zone=z)
        log.flush()

    def _diagnose(self, eng: InferenceEngine, now: float) -> Optional[str]:
        if eng.crashed is not None:
            return f"loop crashed: {eng.crashed}"
        if not eng.alive():
            return "loop thread exited"
        stale = now - eng.last_beat
        if stale > self.config.replica_timeout_s:
            return (f"no decode progress for {stale:.1f}s "
                    f"(FF_SERVE_REPLICA_TIMEOUT="
                    f"{self.config.replica_timeout_s:g})")
        return None

    def _mark_down(self, rep: _Replica, reason: str, now: float,
                   extra_avoid: Sequence[str] = ()) -> None:
        rep.state = RESTARTING
        rep.fails += 1
        delay = backoff_delay(rep.fails, self.config.restart_backoff_s,
                              self.config.restart_cap_s)
        rep.restart_at = now + delay
        self._stats["replica_downs"] += 1
        log = self._telemetry
        if log is not None:
            log.event("replica_down", replica=rep.name,
                      incarnation=rep.engine.uid, reason=reason,
                      consecutive_fails=rep.fails,
                      restart_in_s=round(delay, 3))
            log.flush()
        self._fail_over(rep, reason, extra_avoid=extra_avoid)

    def _restart(self, rep: _Replica) -> None:
        try:
            self._spawn_engine(rep)
        except Exception as e:  # noqa: BLE001 — count it as another fail
            rep.state = RESTARTING
            rep.fails += 1
            rep.restart_at = time.perf_counter() + backoff_delay(
                rep.fails, self.config.restart_backoff_s,
                self.config.restart_cap_s)
            if self._telemetry is not None:
                self._telemetry.event(
                    "replica_restart_failed", replica=rep.name,
                    error=f"{type(e).__name__}: {e}")
                self._telemetry.flush()
            return
        rep.restarts += 1
        self._stats["replica_restarts"] += 1
        log = self._telemetry
        if log is not None:
            log.event("replica_restart", replica=rep.name,
                      incarnation=rep.engine.uid, restarts=rep.restarts)
            log.flush()

    def _hedge_scan(self, now: float) -> None:
        cfg = self.config
        with self._lock:
            if sum(r.state == READY for r in self._replicas) < 2:
                return
            for st in list(self._clients.values()):
                c = st.req
                if st.hedged or c.done() or len(st.attempts) != 1:
                    continue
                att = st.attempts[0]
                if att.t_admit is None:
                    continue    # still queued: a second copy won't help
                if c.t_submit is None \
                        or (now - c.t_submit) * 1000.0 < cfg.hedge_ms:
                    continue
                st.hedged = True
                self._stats["hedged"] += 1
                second = self._dispatch(
                    st, avoid=self._hedge_avoid(att.admitted_by))
                log = self._telemetry
                if log is not None:
                    log.event("request_hedged",
                              request_id=c.request_id,
                              first_attempt=att.request_id,
                              hedge_attempt=second.request_id,
                              age_ms=round((now - c.t_submit) * 1000, 1),
                              **_reqtrace.tag(c.trace))
                    log.counter("serve_hedged", 1)
                    log.flush()

    def _hedge_avoid(self, incarnation: Optional[str]):
        """Avoid keys for a hedge: the first attempt's incarnation —
        plus its whole ZONE when another zone still has a ready replica
        (spread the race across failure domains, not just engines)."""
        if incarnation is None:
            return None
        zone = next((r.zone for r in list(self._replicas)
                     if r.engine is not None
                     and r.engine.uid == incarnation), None)
        if zone is None:
            return incarnation
        other_zone_ready = any(
            r.state == READY and r.zone != zone
            for r in list(self._replicas))
        return (incarnation, f"zone:{zone}") if other_zone_ready \
            else incarnation

    # ------------------------------------------------------------------
    # drain
    # ------------------------------------------------------------------
    def _begin_drain(self, reason: str) -> None:
        with self._lock:
            if self._draining:
                return
            self._draining = True
            self._accepting = False
        log = self._telemetry
        if log is not None:
            log.event("pool_drain", reason=reason,
                      queued=len(self._queue),
                      inflight=len(self._clients))
            log.flush()
        for rep in list(self._replicas):
            if rep.engine is not None and rep.state == READY:
                rep.engine.stop(drain=True)
            rep.state = STOPPED
        # anything still queued could only be served by replicas that no
        # longer exist (all down, or died mid-drain): release the callers
        self._queue.drain(CANCELLED, "pool drained")
        self._cancel_leftover("pool drained")

    def _cancel_leftover(self, reason: str) -> None:
        with self._lock:
            leftovers = [st.req for st in self._clients.values()]
        for c in leftovers:
            c.cancel(reason, force=True)

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    @property
    def num_replicas(self) -> int:
        return len(self._replicas)

    @property
    def ready_replicas(self) -> int:
        return sum(r.state == READY for r in list(self._replicas))

    @property
    def service_time_ewma(self) -> Optional[float]:
        """Submit->done seconds EWMA (None before the first done)."""
        return self._svc_ewma

    def zones_down(self) -> frozenset:
        """Zones chaos has marked down (names, not indices)."""
        return frozenset(self._zones_down)

    @property
    def num_queued(self) -> int:
        return len(self._queue)

    @property
    def num_inflight(self) -> int:
        with self._lock:
            return len(self._clients)

    def ready(self) -> bool:
        """Readiness: accepting AND at least one replica can serve."""
        return self._accepting \
            and any(r.state == READY for r in list(self._replicas))

    def healthz(self) -> Dict[str, Any]:
        """Liveness detail (the HTTP ``/healthz`` body)."""
        now = time.perf_counter()
        reps = []
        for r in list(self._replicas):
            e = r.engine
            d = dict(
                name=r.name, state=r.state,
                incarnation=e.uid if e is not None else None,
                beat_age_s=round(now - e.last_beat, 3)
                if e is not None else None,
                active=e.num_active if e is not None else 0,
                fails=r.fails, restarts=r.restarts,
                failovers=r.failovers)
            if r.zone is not None:
                d["zone"] = r.zone
            reps.append(d)
        any_ready = any(r["state"] == READY for r in reps)
        if self._draining:
            status = "draining" if any_ready else "stopped"
        else:
            status = "ok" if any_ready else "down"
        out = dict(status=status, accepting=self._accepting,
                   queued=len(self._queue),
                   inflight=self.num_inflight, replicas=reps)
        if self.config.zones:
            out["zones"] = {
                z: dict(ready=sum(r["state"] == READY for r in reps
                                  if r.get("zone") == z),
                        total=sum(r.get("zone") == z for r in reps),
                        down=z in self._zones_down)
                for z in self.config.zones}
        return out

    def stats(self) -> Dict[str, Any]:
        reps = list(self._replicas)
        s = dict(self._stats)
        s["queued"] = len(self._queue)
        s["inflight"] = self.num_inflight
        s["ready_replicas"] = sum(r.state == READY for r in reps)
        if self.config.zones:
            s["zones_down"] = sorted(self._zones_down)
        s["replicas"] = {
            r.name: dict(state=r.state, zone=r.zone, fails=r.fails,
                         restarts=r.restarts, failovers=r.failovers,
                         engine=r.engine.stats()
                         if r.engine is not None else {})
            for r in reps}
        return s
