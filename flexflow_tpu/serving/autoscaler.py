"""Metrics-driven autoscaler for the replica pool.

The pool (pool.py) makes a FIXED fleet survive failures; this module
makes the fleet SIZE follow load.  One daemon thread samples the pool's
own signals every ``FF_SCALE_INTERVAL_S``:

  * admission-queue depth per ready replica (the backlog signal),
  * the submit->done service-time EWMA (how long that backlog takes),
  * the SLO burn rate (``slo_burn_rate`` gauges from observability/slo.py,
    observed straight off the telemetry EventLog — no scrape needed),

and turns them into ``pool.add_replica()`` / ``pool.drain_replica()``
calls bounded by ``FF_SCALE_MIN``/``FF_SCALE_MAX``.  Policy, in order:

  1. BACKFILL — ready replicas below ``FF_SCALE_MIN`` (a zone outage
     just took half the fleet): add immediately, no hysteresis, only the
     up-cooldown paces consecutive adds.  Placement picks the
     least-populated zone NOT marked down, so capacity returns in
     surviving zones.
  2. SCALE UP — queue depth per ready replica above ``FF_SCALE_UP_QUEUE``
     or burn rate above ``FF_SCALE_UP_BURN`` for ``FF_SCALE_STREAK``
     consecutive ticks (hysteresis), outside the up-cooldown, below
     ``FF_SCALE_MAX``.
  3. SCALE DOWN — queue per replica below ``FF_SCALE_DOWN_QUEUE`` AND
     burn quiet (< half the up threshold) for the streak, outside the
     (longer) down-cooldown, above ``FF_SCALE_MIN``.  The drain is
     GRACEFUL: the victim stops popping new work, finishes its in-flight
     slots (or fails them over if it wedges), then the incarnation is
     retired and its gauge series disappears from ``healthz``.

Every action emits a ``scale_event`` telemetry event and appends to
``Autoscaler.timeline`` — the replica-count-over-time record
fleet_bench and serve_report's "## Fleet" section render.

Knobs (loud ValueError on garbage, naming the variable):

  FF_SCALE_MIN            min ready replicas        (default 1)
  FF_SCALE_MAX            max replicas; 0 DISABLES the autoscaler
                          (default 0 — opt-in)
  FF_SCALE_INTERVAL_S     tick interval seconds     (default 0.25)
  FF_SCALE_UP_QUEUE       queued-per-ready-replica scale-up threshold
                          (default 4)
  FF_SCALE_UP_BURN        slo burn-rate scale-up threshold; 0 ignores
                          burn (default 2)
  FF_SCALE_DOWN_QUEUE     queued-per-ready-replica scale-down threshold
                          (default 0.5)
  FF_SCALE_STREAK         consecutive ticks a signal must persist
                          (default 2)
  FF_SCALE_UP_COOLDOWN_S  min seconds between adds   (default 2)
  FF_SCALE_DOWN_COOLDOWN_S min seconds between drains (default 15)

STDLIB-ONLY: doctor parses these knobs on hosts with no accelerator,
and the policy is unit-tested against a stub pool with a fake clock.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


def _env_int(name: str, default: int, lo: int = 0) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an integer")
    if v < lo:
        raise ValueError(f"{name}={v} must be >= {lo}")
    return v


def _env_float(name: str, default: float, lo: float = 0.0) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not a number")
    if v < lo:
        raise ValueError(f"{name}={v} must be >= {lo}")
    return v


@dataclasses.dataclass
class ScaleConfig:
    min_replicas: int = 1
    max_replicas: int = 0          # 0: autoscaler disabled
    interval_s: float = 0.25
    up_queue: float = 4.0          # queued per ready replica
    up_burn: float = 2.0           # slo burn rate; 0 ignores burn
    down_queue: float = 0.5
    streak: int = 2                # hysteresis: consecutive ticks
    up_cooldown_s: float = 2.0
    down_cooldown_s: float = 15.0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"FF_SCALE_MIN={self.min_replicas} must be >= 1")
        if self.max_replicas < 0:
            raise ValueError(
                f"FF_SCALE_MAX={self.max_replicas} must be >= 0 "
                f"(0 disables)")
        if self.max_replicas and self.max_replicas < self.min_replicas:
            raise ValueError(
                f"FF_SCALE_MAX={self.max_replicas} must be >= "
                f"FF_SCALE_MIN={self.min_replicas}")
        if self.interval_s <= 0:
            raise ValueError(
                f"FF_SCALE_INTERVAL_S={self.interval_s} must be > 0")
        if self.streak < 1:
            raise ValueError(f"FF_SCALE_STREAK={self.streak} must be >= 1")
        if self.down_queue > self.up_queue:
            raise ValueError(
                f"FF_SCALE_DOWN_QUEUE={self.down_queue} must be <= "
                f"FF_SCALE_UP_QUEUE={self.up_queue} (hysteresis band)")
        for name in ("up_queue", "up_burn", "down_queue",
                     "up_cooldown_s", "down_cooldown_s"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0, "
                                 f"got {getattr(self, name)}")

    @property
    def enabled(self) -> bool:
        return self.max_replicas > 0

    @classmethod
    def from_env(cls, **overrides) -> "ScaleConfig":
        """Build from ``FF_SCALE_*``; explicit kwargs win.  Raises
        ValueError naming the offending variable."""
        kw = dict(
            min_replicas=_env_int("FF_SCALE_MIN", cls.min_replicas, lo=1),
            max_replicas=_env_int("FF_SCALE_MAX", cls.max_replicas, lo=0),
            interval_s=_env_float("FF_SCALE_INTERVAL_S", cls.interval_s),
            up_queue=_env_float("FF_SCALE_UP_QUEUE", cls.up_queue),
            up_burn=_env_float("FF_SCALE_UP_BURN", cls.up_burn),
            down_queue=_env_float("FF_SCALE_DOWN_QUEUE", cls.down_queue),
            streak=_env_int("FF_SCALE_STREAK", cls.streak, lo=1),
            up_cooldown_s=_env_float("FF_SCALE_UP_COOLDOWN_S",
                                     cls.up_cooldown_s),
            down_cooldown_s=_env_float("FF_SCALE_DOWN_COOLDOWN_S",
                                       cls.down_cooldown_s),
        )
        kw.update(overrides)
        return cls(**kw)

    def describe(self) -> str:
        if not self.enabled:
            return "disabled (FF_SCALE_MAX=0)"
        return (f"replicas=[{self.min_replicas},{self.max_replicas}] "
                f"interval={self.interval_s:g}s "
                f"up_queue={self.up_queue:g}/replica "
                f"up_burn={self.up_burn:g} "
                f"down_queue={self.down_queue:g}/replica "
                f"streak={self.streak} "
                f"cooldown={self.up_cooldown_s:g}s up"
                f"/{self.down_cooldown_s:g}s down")


class Autoscaler:
    """One policy thread over a ``ReplicaPool``.

    Usage::

        scaler = Autoscaler(pool, ScaleConfig(min_replicas=2,
                                              max_replicas=6))
        scaler.start()
        ...
        scaler.stop()    # before pool.stop()

    The policy lives in ``_tick(now)`` — deterministic given the pool
    snapshot and the clock, so tests drive it directly against a stub
    pool with a fake clock and never sleep.
    """

    def __init__(self, pool, config: Optional[ScaleConfig] = None,
                 telemetry=None):
        self.pool = pool
        self.config = config if config is not None \
            else ScaleConfig.from_env()
        self._telemetry = telemetry if telemetry is not None \
            else getattr(pool, "_telemetry", None)
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._up_streak = 0
        self._down_streak = 0
        self._last_up = float("-inf")
        self._last_down = float("-inf")
        # latest slo_burn_rate per (slo, window) — fed by the EventLog
        # observer below; no metrics scrape in the loop
        self._burns: Dict[Tuple[str, str], float] = {}
        self._burn_lock = threading.Lock()
        self._observing = False
        # (t, ready_replicas, total_replicas) after every action + tick
        # where the count changed — the fleet timeline
        self.timeline: List[Tuple[float, int, int]] = []
        self._stats = dict(ticks=0, scale_ups=0, scale_downs=0,
                           blocked_max=0, blocked_min=0)

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "Autoscaler":
        assert self._thread is None, "autoscaler already started"
        if not self.config.enabled:
            raise ValueError(
                "autoscaler disabled: set FF_SCALE_MAX >= FF_SCALE_MIN "
                "(or pass ScaleConfig(max_replicas=...))")
        log = self._telemetry
        if log is not None and not self._observing:
            # EventLog has no remove_observer: attach once, gate on a flag
            self._observing = True
            log.add_observer(self._observe)
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name="ff-autoscaler", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 60.0) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        while not self._stop_evt.wait(self.config.interval_s):
            try:
                self._tick(time.perf_counter())
            except Exception as e:  # noqa: BLE001 — policy must not die
                if self._telemetry is not None:
                    self._telemetry.event(
                        "scale_error", error=f"{type(e).__name__}: {e}")
                    self._telemetry.flush()

    # -- burn-rate tap ---------------------------------------------------
    def _observe(self, rec: Dict[str, Any]) -> None:
        if not self._observing or rec.get("t") != "gauge" \
                or rec.get("name") != "slo_burn_rate":
            return
        attrs = rec.get("attrs") or {}
        key = (str(attrs.get("slo", "")), str(attrs.get("window", "")))
        with self._burn_lock:
            self._burns[key] = float(rec.get("v", 0.0))

    def burn_rate(self) -> float:
        """Worst current burn across SLOs (max over windows too: the
        short window is the 'happening NOW' signal we scale on)."""
        with self._burn_lock:
            return max(self._burns.values(), default=0.0)

    # -- the policy ------------------------------------------------------
    def _tick(self, now: float) -> None:
        cfg = self.config
        pool = self.pool
        self._stats["ticks"] += 1
        ready = pool.ready_replicas
        total = pool.num_replicas
        queued = pool.num_queued
        per_replica = queued / max(1, ready)
        burn = self.burn_rate()

        # 1. backfill below min: immediate, paced only by the up-cooldown
        if ready < cfg.min_replicas:
            if total < cfg.max_replicas \
                    and now - self._last_up >= cfg.up_cooldown_s:
                self._scale_up(now, ready, queued,
                               f"ready {ready} < FF_SCALE_MIN="
                               f"{cfg.min_replicas}")
            elif total >= cfg.max_replicas:
                self._stats["blocked_max"] += 1
            self._down_streak = 0
            return

        # 2. pressure up / 3. quiet down, with hysteresis streaks
        want_up = per_replica > cfg.up_queue \
            or (cfg.up_burn > 0 and burn > cfg.up_burn)
        want_down = per_replica < cfg.down_queue \
            and (cfg.up_burn <= 0 or burn < cfg.up_burn * 0.5)
        self._up_streak = self._up_streak + 1 if want_up else 0
        self._down_streak = self._down_streak + 1 if want_down else 0

        if self._up_streak >= cfg.streak:
            if total >= cfg.max_replicas:
                self._stats["blocked_max"] += 1
            elif now - self._last_up >= cfg.up_cooldown_s:
                reason = (f"queue {per_replica:.1f}/replica > "
                          f"FF_SCALE_UP_QUEUE={cfg.up_queue:g}"
                          if per_replica > cfg.up_queue else
                          f"burn {burn:.2f} > FF_SCALE_UP_BURN="
                          f"{cfg.up_burn:g}")
                self._scale_up(now, ready, queued, reason)
        elif self._down_streak >= cfg.streak:
            if ready <= cfg.min_replicas:
                self._stats["blocked_min"] += 1
            elif now - self._last_down >= cfg.down_cooldown_s:
                self._scale_down(now, ready, queued,
                                 f"queue {per_replica:.2f}/replica < "
                                 f"FF_SCALE_DOWN_QUEUE="
                                 f"{cfg.down_queue:g}")

    def _scale_up(self, now: float, ready: int, queued: int,
                  reason: str) -> None:
        name = self.pool.add_replica()
        if name is None:
            return
        self._last_up = now
        self._up_streak = 0
        self._stats["scale_ups"] += 1
        self._record(now, "up", name, reason, ready, queued)

    def _scale_down(self, now: float, ready: int, queued: int,
                    reason: str) -> None:
        name = self.pool.drain_replica()
        if name is None:
            return
        self._last_down = now
        self._down_streak = 0
        self._stats["scale_downs"] += 1
        self._record(now, "down", name, reason, ready, queued)

    def _record(self, now: float, direction: str, name: str,
                reason: str, ready: int, queued: int) -> None:
        ready_after = self.pool.ready_replicas
        self.timeline.append((now, ready_after, self.pool.num_replicas))
        log = self._telemetry
        if log is not None:
            log.event("scale_event", direction=direction, replica=name,
                      reason=reason, ready_before=ready,
                      ready_after=ready_after, queued=queued)
            log.counter("serve_scale_events", 1, which=direction)
            log.flush()

    def stats(self) -> Dict[str, Any]:
        s = dict(self._stats)
        s["burn_rate"] = self.burn_rate()
        s["up_streak"] = self._up_streak
        s["down_streak"] = self._down_streak
        return s
