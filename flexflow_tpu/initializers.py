"""Weight initializers.

TPU-native analogue of the reference initializer suite
(reference: include/initializer.h:26-100, src/runtime/initializer_kernel.cu).
The reference runs one Legion task per weight partition with curand; here
each initializer is a pure function of a jax PRNG key, evaluated inside the
jitted, sharded ``init_params`` so every device materializes only its own
shard (no host round-trip).
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


class Initializer:
    def __call__(self, key: jax.Array, shape: Tuple[int, ...], dtype=jnp.float32) -> jax.Array:
        raise NotImplementedError


class GlorotUniform(Initializer):
    """Glorot/Xavier uniform: U(-s, s), s = sqrt(6/(fan_in+fan_out)).

    Fan computation follows the reference's per-op conventions
    (initializer_kernel.cu GlorotUniform::init_task): for conv kernels
    (h, w, cin, cout here; NHWC-native) fan_in = h*w*cin,
    fan_out = h*w*cout; for dense (cin, cout) fan_in = cin, fan_out = cout.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed

    @staticmethod
    def _fans(shape: Sequence[int]) -> Tuple[float, float]:
        if len(shape) == 4:  # (kh, kw, cin, cout)
            rf = shape[0] * shape[1]
            return float(rf * shape[2]), float(rf * shape[3])
        if len(shape) == 2:  # (cin, cout)
            return float(shape[0]), float(shape[1])
        if len(shape) == 1:
            return float(shape[0]), float(shape[0])
        # fall back to matrix-like split
        recept = 1
        for d in shape[1:-1]:
            recept *= d
        return float(shape[0] * recept), float(shape[-1] * recept)

    def __call__(self, key, shape, dtype=jnp.float32):
        fan_in, fan_out = self._fans(shape)
        scale = math.sqrt(6.0 / max(1.0, fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, minval=-scale, maxval=scale)


class ZeroInitializer(Initializer):
    def __call__(self, key, shape, dtype=jnp.float32):
        return jnp.zeros(shape, dtype)


class ConstantInitializer(Initializer):
    def __init__(self, value: float):
        self.value = value

    def __call__(self, key, shape, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype)


class UniformInitializer(Initializer):
    def __init__(self, seed: int = 0, min_val: float = 0.0, max_val: float = 1.0):
        self.seed = seed
        self.min_val = min_val
        self.max_val = max_val

    def __call__(self, key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, minval=self.min_val, maxval=self.max_val)


class NormInitializer(Initializer):
    def __init__(self, seed: int = 0, mean: float = 0.0, stddev: float = 1.0):
        self.seed = seed
        self.mean = mean
        self.stddev = stddev

    def __call__(self, key, shape, dtype=jnp.float32):
        return self.mean + self.stddev * jax.random.normal(key, shape, dtype)


DefaultWeightInitializer = GlorotUniform
DefaultBiasInitializer = ZeroInitializer
