"""Configuration and parallelization-config types.

TPU-native re-design of the reference FlexFlow configuration layer
(reference: include/config.h:26-115, src/runtime/model.cc:1274-1342).

Two levels of configuration, mirroring the reference:
  * ``FFConfig``  — run-level flags (epochs, batch size, lr, search budget,
    strategy file paths, device counts).  CLI flags keep the reference
    spellings (``-e``, ``-b``, ``--lr``, ``--budget`` ...) and add
    ``-ll:tpu N`` (accepted alias: ``-ll:gpu``) for the per-host device count.
  * ``ParallelConfig`` — per-operator SOAP partition description
    (reference: include/config.h:42-51): a device type, a per-tensor-dim
    partition degree vector, and the flat list of device ids that the
    op's task grid maps onto.

On TPU the ``device_ids`` do not drive placement directly (XLA GSPMD places
shards by mesh coordinates); they are preserved for strategy-file round
tripping and for the execution simulator's machine model.
"""

from __future__ import annotations

import dataclasses
import enum
import os
from typing import Dict, List, Optional, Sequence, Tuple

MAX_DIM = 4
MAX_NUM_WORKERS = 1024

# Default MCMC budget for offline/auto search entry points.  Sized for
# the delta (incremental) simulator in simulator/delta.py, which re-costs
# a proposal ~20x cheaper than the full task-graph rebuild the old
# 1000-2000 defaults were calibrated against — more budget at lower cost
# than before (set FF_SIM_DELTA=0 to get the old per-proposal price).
DEFAULT_SEARCH_BUDGET = 8000


class DeviceType(enum.Enum):
    """Device kind an op is placed on.

    The reference uses GPU/CPU (include/config.h:43-46); the TPU build maps
    the accelerator type to TPU and keeps CPU for host-resident ops
    (e.g. DLRM's zero-copy embedding tables).  Wire value 0 in strategy
    files means "the accelerator".
    """

    TPU = 0
    CPU = 1

    # Alias used when importing reference-era strategy files.
    GPU = 0


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """Per-op SOAP partition config (reference: include/config.h:42-51).

    ``dims`` holds the partition degree for each dimension of the op's
    *output* tensor, in the tensor's natural dim order (batch first; image
    tensors are NHWC in this framework — the TPU-native layout).  The
    product of ``dims`` is the number of parts; ``device_ids`` lists the
    devices the parts map onto, length ``num_parts`` (may be empty, in
    which case parts map onto devices ``0..num_parts-1``).
    """

    device_type: DeviceType = DeviceType.TPU
    dims: Tuple[int, ...] = (1,)
    device_ids: Tuple[int, ...] = ()
    # Per-tensor memory placement (reference: Op.memory_types, strategy.proto
    # FBM=device HBM, ZCM=host pinned).  "hbm"/"host" here; host entries map
    # to JAX host-offload for CPU-placed embeddings (DLRM).
    memory_types: Tuple[str, ...] = ()

    def __post_init__(self):
        if len(self.dims) == 0 or len(self.dims) > MAX_DIM:
            raise ValueError(f"ParallelConfig dims must have 1..{MAX_DIM} entries, got {self.dims}")
        if any(d < 1 for d in self.dims):
            raise ValueError(f"partition degrees must be >= 1, got {self.dims}")

    @classmethod
    def host_rowsparse(cls, ndims: int = 2) -> "ParallelConfig":
        """Host placement for an embedding table (reference: the hetero
        DLRM strategies' CPU + ZC-memory placement,
        dlrm_strategy_hetero.cc:28-35) — the runtime's row-sparse
        host-resident path.  ONE definition shared by the strategy
        generators, both search engines, and the SOAP reports.

        ``ndims``: rank of the embedding's OUTPUT (2 for SUM/AVG bags,
        3 for aggr=NONE sequence lookups) — ``find_parallel_config``
        silently drops rank-mismatched entries, so a rank-2 config on a
        rank-3 embedding would lose the host placement entirely."""
        return cls(DeviceType.CPU, (1,) * max(2, int(ndims)), (0,),
                   ("host", "host", "host"))

    @property
    def host_placed(self) -> bool:
        """True when this config requests host placement: CPU device
        type, or ANY region's memory type marked "host" (the runtime
        treats either as "weights live host-side" — model.py offload /
        row-sparse paths).  The SIMULATOR's host-tier pricing applies
        this only to Embedding ops (the row-sparse path); other
        host-placed ops stream weights but still compute on device."""
        return self.device_type == DeviceType.CPU \
            or "host" in self.memory_types

    @property
    def ndims(self) -> int:
        return len(self.dims)

    def num_parts(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    def with_device_ids(self, ids: Sequence[int]) -> "ParallelConfig":
        return dataclasses.replace(self, device_ids=tuple(ids))

    @staticmethod
    def data_parallel(ndims: int, num_devices: int) -> "ParallelConfig":
        """Default data-parallel config: split the batch (first) dim only.

        Mirrors ``FFModel``'s auto-installed DataParallelism_{1..4}D
        strategies (reference: src/runtime/model.cc:391-401) — sample dim
        split across all devices, every other dim unsplit.
        """
        dims = (num_devices,) + (1,) * (ndims - 1)
        return ParallelConfig(DeviceType.TPU, dims, tuple(range(num_devices)))


# Full original argv stashed by the module runner (__main__.py) before it
# rewrites sys.argv to the filtered list for the target script.
_RUNNER_ARGV: Optional[List[str]] = None


def set_runner_argv(argv: Sequence[str]) -> None:
    global _RUNNER_ARGV
    _RUNNER_ARGV = list(argv)


def _env_default_devices() -> int:
    try:
        import jax

        return max(1, len(jax.devices()))
    except Exception:  # pragma: no cover - jax always present in practice
        return 1


@dataclasses.dataclass
class FFConfig:
    """Run-level configuration (reference: include/config.h:66-103).

    Defaults follow ``FFConfig::FFConfig`` / ``parse_args``
    (src/runtime/model.cc:1230-1342): batchSize 64, epochs 1, lr 0.01,
    wd 1e-4, search budget 0 (no search), alpha 0.05.
    """

    epochs: int = 1
    batch_size: int = 64
    iterations: int = -1  # -1: derive from dataset size
    print_freq: int = 10
    num_nodes: int = 1
    workers_per_node: int = 0  # 0 → all visible devices
    learning_rate: float = 0.01
    weight_decay: float = 0.0001
    synthetic_input: bool = False
    profiling: bool = False
    search_budget: int = 0
    search_alpha: float = 0.05
    search_overlap_backward_update: bool = False
    # Search engine: "" = auto (native C++ anneal, falling back to the
    # Python MCMC), "mcmc" = force the Python single chain, "population"
    # = parallel-tempered population of delta-simulator chains
    # (simulator/population.py; FF_SEARCH_* knobs tune it).
    search_engine: str = ""
    # Also search pipeline stage assignments during compile() and apply
    # the plan when it beats the best dim strategy (set_pipeline).
    search_pipeline: bool = False
    # Gradient accumulation: split each staged batch into K micro-batches
    # inside the jitted step (lax.scan; one micro's activations live at a
    # time), average grads, apply the optimizer once.
    grad_accum_steps: int = 1
    # Rematerialization: jax.checkpoint around weighted ops' forwards in
    # the train step — recompute activations in backward instead of
    # keeping them resident (FLOPs for HBM).
    remat: bool = False
    dataset_path: str = ""
    import_strategy_file: str = ""
    # Set when importing a file produced by the reference implementation,
    # whose dims are in Legion adim order (innermost first); this
    # framework's files use natural order (batch first).
    import_strategy_reference_order: bool = False
    export_strategy_file: str = ""
    seed: int = 0
    # Numerics: params kept in float32; activations computed in
    # ``compute_dtype`` (bfloat16 is the TPU-native default for benchmarks,
    # float32 for numerics tests).
    compute_dtype: str = "float32"
    # Route optimizer updates through the fused Pallas kernels
    # (kernels/fused_optimizer.py ≈ reference optimizer_kernel.cu); on a
    # mesh each parameter updates per-shard via a per-leaf shard_map.
    fused_optimizer: bool = False
    # ZeRO-1: shard optimizer state (momentum / Adam moments) over the
    # mesh axes the parameter itself does not occupy — replicated-param
    # state drops to ~1/N per device.  Beyond the reference (SURVEY §2.3
    # lists ZeRO-style optimizer sharding as design headroom).
    zero_optimizer: bool = False
    # Row-sparse host-resident embedding tables for host-placed Embedding
    # ops (reference: embedding.cc CPU tasks + dlrm_strategy_hetero.cc):
    # per step only the batch's unique rows move host<->device.  None =
    # auto (on exactly when sparse == dense numerics: plain SGD); True
    # forces lazy per-touched-row updates under momentum/Adam; False
    # always streams the full table.
    sparse_host_embeddings: Optional[bool] = None
    # Whole-graph lowering (parallel/lowering.py): compile the resolved
    # SOAP strategy into ONE jitted step with per-op sharding
    # constraints instead of per-op dispatch.  None = auto (on exactly
    # when the run spans nodes/processes); the FF_LOWERED env knob
    # (1/0/auto, loud ValueError on garbage) fills in when this is None.
    lowered: Optional[bool] = None
    # Structured telemetry (observability/): step spans, phase spans,
    # throughput/MFU counters to a JSONL trace.  ``FF_TELEMETRY=1`` in
    # the environment enables it too; ``telemetry_file`` (or
    # ``FF_TELEMETRY_FILE``) overrides the default ff_trace.jsonl.
    telemetry: bool = False
    telemetry_file: str = ""
    # Per-op strategies, keyed by op name (the reference keys an equivalent
    # map by hash(op name) — include/config.h:102, strategy.cc:23-26; the
    # hash is an implementation detail of Legion mapper tags that the TPU
    # build does not need).
    strategies: Dict[str, ParallelConfig] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.workers_per_node == 0:
            self.workers_per_node = _env_default_devices()

    @property
    def num_devices(self) -> int:
        return self.num_nodes * self.workers_per_node

    # -- CLI ---------------------------------------------------------------
    def parse_args(self, argv: Optional[List[str]] = None) -> List[str]:
        """Parse reference-style CLI flags; returns unrecognized args.

        Mirrors FFConfig::parse_args (src/runtime/model.cc:1274-1342) plus
        the Legion ``-ll:*`` device flags that the reference passes through
        (``-ll:gpu`` → ``-ll:tpu``).
        """
        if argv is None:
            # The module runner (``python -m flexflow_tpu script ...``)
            # rewrites sys.argv to the FILTERED args but stashes the full
            # original list here so framework flags stay reachable.
            if _RUNNER_ARGV is not None:
                argv = _RUNNER_ARGV
            else:
                import sys

                argv = sys.argv[1:]
        argv = list(argv)
        rest: List[str] = []
        i = 0

        def take() -> str:
            nonlocal i
            i += 1
            return argv[i]

        while i < len(argv):
            a = argv[i]
            if a in ("-e", "--epochs"):
                self.epochs = int(take())
            elif a in ("-b", "--batch-size"):
                self.batch_size = int(take())
            elif a in ("--lr", "--learning-rate"):
                self.learning_rate = float(take())
            elif a in ("--wd", "--weight-decay"):
                self.weight_decay = float(take())
            elif a in ("--iterations",):
                self.iterations = int(take())
            elif a in ("--budget", "--search-budget"):
                self.search_budget = int(take())
            elif a in ("--alpha", "--search-alpha"):
                self.search_alpha = float(take())
            elif a in ("--overlap",):
                self.search_overlap_backward_update = True
            elif a in ("--import", "--import-strategy"):
                self.import_strategy_file = take()
            elif a in ("--import-reference-order",):
                self.import_strategy_reference_order = True
            elif a in ("--export", "--export-strategy"):
                self.export_strategy_file = take()
            elif a in ("--dataset", "-d"):
                self.dataset_path = take()
            elif a in ("--synthetic",):
                self.synthetic_input = True
            elif a in ("--profiling",):
                self.profiling = True
            elif a in ("--nodes",):
                self.num_nodes = int(take())
            elif a in ("-ll:tpu", "-ll:gpu"):
                self.workers_per_node = int(take())
            elif a in ("-ll:cpu", "-ll:util", "-ll:py", "-ll:fsize", "-ll:zsize", "-lg:prof"):
                take()  # accepted for compatibility, no-op on TPU
            elif a == "--seed":
                self.seed = int(take())
            elif a == "--bf16":
                self.compute_dtype = "bfloat16"
            elif a == "--fused-optimizer":
                self.fused_optimizer = True
            elif a == "--zero-optimizer":
                self.zero_optimizer = True
            elif a == "--search-pipeline":
                self.search_pipeline = True
            elif a == "--search-engine":
                self.search_engine = take()
            elif a == "--grad-accum":
                self.grad_accum_steps = int(take())
            elif a == "--remat":
                self.remat = True
            elif a == "--sparse-host-embeddings":
                # force lazy row-sparse host tables even under
                # momentum/Adam (auto mode only sparsifies plain SGD)
                self.sparse_host_embeddings = True
            elif a == "--no-sparse-host-embeddings":
                self.sparse_host_embeddings = False
            elif a == "--lowered":
                self.lowered = True
            elif a == "--no-lowered":
                self.lowered = False
            elif a == "--telemetry":
                self.telemetry = True
            elif a == "--telemetry-file":
                self.telemetry = True
                self.telemetry_file = take()
            else:
                rest.append(a)
            i += 1
        return rest

    # -- strategy lookup ---------------------------------------------------
    def find_parallel_config(self, ndims: int, pcname: str) -> ParallelConfig:
        """Look up an op's config, falling back to data parallelism.

        Reference semantics (src/runtime/strategy.cc:28-85): exact-name hit
        must match dimensionality; otherwise fall back to the default
        data-parallel config of the right rank over all devices.
        """
        pc = self.strategies.get(pcname)
        if pc is not None:
            if pc.ndims == ndims:
                return pc
            # Rank-mismatched entry: reference asserts; we degrade to DP.
        return ParallelConfig.data_parallel(ndims, self.num_devices)
