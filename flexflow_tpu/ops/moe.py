"""Mixture-of-Experts operator with expert parallelism.

The reference has no MoE (SURVEY §2.3 "absent in reference"), but its
SOAP abstraction — partition any tensor dim of any op — is exactly the
hook expert parallelism needs: this op makes the EXPERT dim an explicit
partitionable axis the same way PipelineMLP exposes the operator dim
(ops/pipeline.py).  ``ParallelConfig`` dim 1 is the EXPERT-parallel
degree: expert weights shard over it, and XLA GSPMD emits the
token all_to_all (dispatch) + all_to_all (combine) pair over those mesh
axes from the sharding annotations alone — the TPU-native equivalent of
hand-written NCCL alltoall in GPU MoE stacks.

Routing is Switch-style top-1 with a capacity limit: per token,
``argmax(softmax(x @ router))`` picks the expert; tokens beyond
``capacity = ceil(tokens/E · capacity_factor)`` are dropped (output 0 —
callers add the residual).  Dispatch/combine are dense one-hot einsums:
static shapes, MXU-friendly, deterministic under any sharding — so
strategies change placement, not results.
"""

from __future__ import annotations

import math
from typing import List, Optional

import jax
import jax.numpy as jnp

from .base import FwdCtx, Op
from ..initializers import DefaultWeightInitializer, ZeroInitializer


class ExpertMLP(Op):
    _type = "ExpertMLP"

    def __init__(self, model, input_tensor, num_experts: int,
                 hidden_size: int, capacity_factor: float = 1.25,
                 activation: str = "relu", name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        dims = input_tensor.dims
        d = dims[-1]
        self.num_experts = int(num_experts)
        self.hidden_size = int(hidden_size)
        self.capacity_factor = float(capacity_factor)
        self.activation = activation
        e, h = self.num_experts, self.hidden_size
        # expert (leading) dim partitions over config dim 1 — the
        # expert-parallel degree; the router stays replicated.
        self._add_weight("router", (d, e), DefaultWeightInitializer())
        self._add_weight("w_in", (e, d, h), DefaultWeightInitializer(),
                         partition_dims=(1, None, None))
        self._add_weight("b_in", (e, h), ZeroInitializer(),
                         partition_dims=(1, None))
        self._add_weight("w_out", (e, h, d), DefaultWeightInitializer(),
                         partition_dims=(1, None, None))
        self._add_weight("b_out", (e, d), ZeroInitializer(),
                         partition_dims=(1, None))
        self._add_output(dims, input_tensor.dtype)

    # -- config semantics (mirrors PipelineMLP's non-layout dim 1) ------
    def _config_dim_bound(self, i: int):
        """Config dim 1 is the EXPERT-parallel degree: legal iff it
        divides ``num_experts`` — not the tensor dim the base size check
        would compare against."""
        if i == 1:
            return self.num_experts
        return super()._config_dim_bound(i)

    def constraint_pc(self):
        """Output activations are batch-sharded only; the expert degree
        places weights, not outputs."""
        from ..config import ParallelConfig

        dims = (self.pc.dims[0],) + (1,) * (self.output.num_dims - 1)
        return ParallelConfig(dims=dims)

    def _ep_axes(self):
        pc = getattr(self, "pc", None)
        machine = self.model.machine
        if (pc is None or len(pc.dims) < 2 or pc.dims[1] <= 1
                or machine is None or machine.num_devices <= 1):
            return None
        try:
            groups = machine.axes_for_degrees([pc.dims[0], pc.dims[1]])
        except ValueError:
            return None
        return groups[1] or None

    def capacity(self, tokens: int) -> int:
        return max(1, math.ceil(tokens / self.num_experts
                                * self.capacity_factor))

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        x = xs[0]
        shape = x.shape
        d = shape[-1]
        dt = x.dtype
        s = 1
        for dim in shape[:-1]:
            s *= dim
        xf = x.reshape(s, d)
        e = params["w_in"].shape[0]
        cap = self.capacity(s)

        # Router in f32: top-1 gate per token (Switch).
        logits = jnp.dot(xf.astype(jnp.float32),
                         params["router"].astype(jnp.float32))
        gates = jax.nn.softmax(logits, axis=-1)            # (S, E)
        expert_idx = jnp.argmax(gates, axis=-1)            # (S,)
        gate = jnp.max(gates, axis=-1)                     # (S,)
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)
        # position of each token in its expert's queue (capacity cut)
        pos = jnp.cumsum(onehot, axis=0) * onehot          # 1-based
        keep = (pos > 0) & (pos <= cap)
        pos_idx = jnp.clip(pos - 1.0, 0, cap - 1).astype(jnp.int32)
        slot = jax.nn.one_hot(jnp.max(pos_idx, axis=-1), cap,
                              dtype=jnp.float32)           # (S, C)
        disp = (onehot * keep).astype(jnp.float32)[:, :, None] \
            * slot[:, None, :]                             # (S, E, C)

        cons = self._expert_constraint
        expert_in = cons(jnp.einsum("sec,sd->ecd", disp,
                                    xf.astype(jnp.float32)))
        hmid = jnp.einsum("ecd,edh->ech", expert_in.astype(dt),
                          params["w_in"].astype(dt))
        hmid = hmid + params["b_in"].astype(hmid.dtype)[:, None, :]
        if self.activation == "relu":
            hmid = jax.nn.relu(hmid)
        elif self.activation == "gelu":
            hmid = jax.nn.gelu(hmid)
        hmid = cons(hmid)
        y_e = jnp.einsum("ech,ehd->ecd", hmid, params["w_out"].astype(dt))
        y_e = y_e + params["b_out"].astype(y_e.dtype)[:, None, :]
        y_e = cons(y_e)
        comb = disp * gate[:, None, None]                  # (S, E, C)
        y = jnp.einsum("sec,ecd->sd", comb,
                       y_e.astype(jnp.float32)).astype(dt)
        return [y.reshape(shape)]

    def decode(self, params, xs, cache, pos, ctx):
        """Dropless single-step routing: at decode only B tokens route
        per step, so the training-time capacity cut (which zeroes
        overflow tokens) would silently corrupt generations — compute
        every token's CHOSEN expert exactly instead.  Matches forward
        bit-for-bit whenever forward's capacity drops nothing."""
        x = xs[0]
        shape = x.shape
        d = shape[-1]
        dt = x.dtype
        s = 1
        for dim in shape[:-1]:
            s *= dim
        xf = x.reshape(s, d)
        e = params["w_in"].shape[0]
        logits = jnp.dot(xf.astype(jnp.float32),
                         params["router"].astype(jnp.float32))
        gates = jax.nn.softmax(logits, axis=-1)
        gate = jnp.max(gates, axis=-1)                     # (S,)
        onehot = jax.nn.one_hot(jnp.argmax(gates, axis=-1), e,
                                dtype=jnp.float32)         # (S, E)
        h = jnp.einsum("sd,edh->seh", xf.astype(dt), params["w_in"].astype(dt))
        h = h + params["b_in"].astype(h.dtype)[None, :, :]
        if self.activation == "relu":
            h = jax.nn.relu(h)
        elif self.activation == "gelu":
            h = jax.nn.gelu(h)
        y_e = jnp.einsum("seh,ehd->sed", h, params["w_out"].astype(dt))
        y_e = y_e + params["b_out"].astype(y_e.dtype)[None, :, :]
        y = jnp.einsum("se,sed->sd", onehot * gate[:, None],
                       y_e.astype(jnp.float32)).astype(dt)
        return [y.reshape(shape)], cache

    def _expert_constraint(self, a):
        """Pin the expert dim of (E, C, ...) intermediates to the ep mesh
        axes so GSPMD places per-expert compute on its shard (and emits
        the all_to_all at the dispatch/combine einsums)."""
        axes = self._ep_axes()
        if axes is None:
            return a
        from jax.sharding import NamedSharding, PartitionSpec

        spec = PartitionSpec(axes if len(axes) > 1 else axes[0],
                             *([None] * (a.ndim - 1)))
        return jax.lax.with_sharding_constraint(
            a, NamedSharding(self.model.machine.mesh, spec))

    def flops_per_sample(self):
        dims = self.output.dims
        d = dims[-1]
        tokens_per_sample = 1
        for dim in dims[1:-1]:
            tokens_per_sample *= dim
        h = self.hidden_size
        # router + one expert's in+out projections per token (capacity
        # overhead included)
        return tokens_per_sample * (
            2.0 * d * self.num_experts
            + self.capacity_factor * 4.0 * d * h)
