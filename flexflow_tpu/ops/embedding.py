"""Embedding operator.

Reference: src/ops/embedding.cu (custom gather/scatter kernels, SUM/AVG
aggregation, embedding.cu:173-220) + CPU task variants (embedding.cc:18-77)
that let DLRM keep huge tables in host zero-copy memory.

TPU-native: a ``jnp.take`` gather — XLA lowers it to a dynamic-gather that
runs on-chip; the backward scatter-add comes from autodiff.  Large tables
shard their *embedding dim* along the output channel config dim (riding
ICI), and the reference's CPU placement maps to host-offload: a config
with ``device_type=CPU`` pins the table to host memory via
``jax.device_put`` with a host-memory-kind sharding (DLRM path).

Input is (B, num_indices) int32; aggregation SUM or AVG over the
``num_indices`` dim, exactly the reference semantics.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from .base import FwdCtx, Op
from ..initializers import GlorotUniform


class AggrMode:
    NONE = "none"
    SUM = "sum"
    AVG = "avg"


class Embedding(Op):
    _type = "Embedding"

    def __init__(self, model, input_tensor, num_entries: int, out_dim: int,
                 aggr: str = AggrMode.SUM, kernel_initializer=None,
                 share_with=None, name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        self.num_entries = num_entries
        self.out_dim = out_dim
        self.aggr = aggr
        batch = input_tensor.dims[0]
        if aggr == AggrMode.NONE:
            if len(input_tensor.dims) != 2 or input_tensor.dims[1] != 1:
                # keep the sequence dim
                self._add_output(input_tensor.dims + (out_dim,), "float32")
            else:
                self._add_output((batch, out_dim), "float32")
        else:
            self._add_output((batch, out_dim), "float32")
        if share_with is not None:
            share_with = share_with.share_from or share_with  # resolve chains
            if not isinstance(share_with, Embedding) or \
                    (share_with.num_entries, share_with.out_dim) != (num_entries, out_dim):
                raise ValueError("share_with must be an Embedding of identical shape")
            self.share_from = share_with
        else:
            self._add_weight("weight", (num_entries, out_dim),
                             kernel_initializer or GlorotUniform(),
                             partition_dims=(None, len(self.output.dims) - 1))

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        idx = xs[0].astype(jnp.int32)
        table = params["weight"]
        emb = jnp.take(table, idx, axis=0)  # (B, I, D) or (B, D) when idx is (B,)
        if self.aggr == AggrMode.SUM and emb.ndim == 3:
            emb = jnp.sum(emb, axis=1)
        elif self.aggr == AggrMode.AVG and emb.ndim == 3:
            emb = jnp.mean(emb, axis=1)
        elif self.aggr == AggrMode.NONE and emb.ndim == 3 and self.output.num_dims == 2:
            emb = emb[:, 0, :]
        return [emb.astype(self.model.compute_dtype)]

    def flops_per_sample(self):
        n_idx = self.inputs[0].dims[1] if len(self.inputs[0].dims) > 1 else 1
        return float(n_idx * self.out_dim)
