"""Multi-head attention + LayerNorm ops — the long-context path.

The reference predates transformers and has no attention op (SURVEY §5.7);
its SOAP abstraction (partition any output dim, include/config.h:42-51) is
what these ops extend to the sequence dim.  A MultiHeadAttention output is
(B, S, E); a ParallelConfig of (dp, sp, 1) lowers to:

  * sp == 1: fused flash attention on-chip (kernels/flash_attention.py,
    pallas), GSPMD handling dp like any other op;
  * sp > 1: ring attention over the mesh axes assigned to the sequence
    dim (parallel/sequence.py) — K/V rotate over ICI via ppermute and
    per-chip memory stays O(S/sp · S/sp) instead of O(S²).
"""

from __future__ import annotations

import math
from typing import List, Optional

import jax
import jax.numpy as jnp

from .base import FwdCtx, Op
from ..initializers import ConstantInitializer, DefaultWeightInitializer, ZeroInitializer


class LayerNorm(Op):
    """Normalize over the last dim with learned scale/shift."""

    _type = "LayerNorm"

    def __init__(self, model, input_tensor, eps: float = 1e-5,
                 elementwise_affine: bool = True, name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        self.eps = eps
        self.affine = elementwise_affine
        dims = input_tensor.dims
        self._add_output(dims, input_tensor.dtype)
        if elementwise_affine:
            feat_cfg_dim = len(dims) - 1
            self._add_weight("scale", (dims[-1],), ConstantInitializer(1.0),
                             partition_dims=(feat_cfg_dim,))
            self._add_weight("bias", (dims[-1],), ZeroInitializer(),
                             partition_dims=(feat_cfg_dim,))

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        x = xs[0]
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + self.eps)
        if self.affine:
            y = y * params["scale"].astype(jnp.float32) + params["bias"].astype(jnp.float32)
        return [y.astype(x.dtype)]

    def flops_per_sample(self):
        import numpy as np
        return 8.0 * float(np.prod(self.output.dims[1:]))


class MultiHeadAttention(Op):
    """Scaled-dot-product multi-head attention with QKV/output projections.

    query/key/value: (B, Sq, E) / (B, Sk, E) / (B, Sk, E).  Output
    (B, Sq, E).  ``causal`` adds the autoregressive mask (requires
    Sq == Sk).  Sequence parallelism kicks in when the op's
    ParallelConfig splits dim 1 — see module docstring.
    """

    _type = "MultiHeadAttention"

    def __init__(self, model, query, key, value, embed_dim: int,
                 num_heads: int, causal: bool = False,
                 dropout: float = 0.0, use_bias: bool = False,
                 kernel_initializer=None, seq_parallel_mode: str = "ring",
                 name: Optional[str] = None):
        super().__init__(model, [query, key, value], name)
        assert embed_dim % num_heads == 0, "embed_dim must divide by num_heads"
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.causal = causal
        self.dropout = dropout
        self.use_bias = use_bias
        self.seq_parallel_mode = seq_parallel_mode
        b, sq, _ = query.dims
        self._add_output((b, sq, embed_dim), query.dtype)
        init = kernel_initializer or DefaultWeightInitializer()
        for wname, in_dim in (("wq", query.dims[-1]), ("wk", key.dims[-1]),
                              ("wv", value.dims[-1])):
            self._add_weight(wname, (in_dim, embed_dim), init,
                             partition_dims=(None, 2))
        self._add_weight("wo", (embed_dim, embed_dim), init,
                         partition_dims=(None, 2))
        if use_bias:
            for bname in ("bq", "bk", "bv", "bo"):
                self._add_weight(bname, (embed_dim,), ZeroInitializer(),
                                 partition_dims=(2,))

    # -- helpers -----------------------------------------------------------
    def _proj(self, params, x, w, b):
        acc = jnp.float32 if x.dtype == jnp.bfloat16 else None
        y = jnp.dot(x, params[w].astype(x.dtype), preferred_element_type=acc)
        y = y.astype(x.dtype)
        if self.use_bias:
            y = y + params[b].astype(y.dtype)
        return y

    def _config_dim_bound(self, i: int):
        """The feature split (dim 2) is head-parallel tensor parallelism:
        the degree must divide num_heads so each shard holds whole
        heads (the reshape to (B, S, H, D) then stays aligned)."""
        if i == 2:
            return self.num_heads
        return super()._config_dim_bound(i)

    def _seq_degree(self) -> int:
        pc = getattr(self, "pc", None)
        if pc is None or len(pc.dims) < 2:
            return 1
        return pc.dims[1]

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        q_in, k_in, v_in = xs
        B, Sq, _ = q_in.shape
        H, D = self.num_heads, self.head_dim

        q = self._proj(params, q_in, "wq", "bq")
        k = self._proj(params, k_in, "wk", "bk")
        v = self._proj(params, v_in, "wv", "bv")
        # (B, S, E) -> (B, H, S, D)
        split = lambda t: t.reshape(t.shape[0], t.shape[1], H, D).transpose(0, 2, 1, 3)
        qh, kh, vh = split(q), split(k), split(v)
        scale = 1.0 / math.sqrt(D)

        sp = self._seq_degree()
        machine = self.model.machine
        if sp > 1 and machine.num_devices > 1 and Sq == k_in.shape[1]:
            from ..parallel.sequence import sequence_parallel_attention
            degrees = list(self.pc.dims) + [1] * (3 - len(self.pc.dims))
            groups = machine.axes_for_degrees(degrees[:3])
            batch_axes = groups[0] if groups[0] else None
            seq_axes = groups[1]
            oh = sequence_parallel_attention(
                qh, kh, vh, machine.mesh, seq_axes, batch_axes=batch_axes,
                causal=self.causal, scale=scale, mode=self.seq_parallel_mode)
        elif jax.default_backend() == "tpu":
            from ..kernels.flash_attention import flash_attention
            oh = flash_attention(qh, kh, vh, causal=self.causal, scale=scale)
        else:
            from ..parallel.sequence import blockwise_attention
            oh, _ = blockwise_attention(qh, kh, vh, causal=self.causal,
                                        scale=scale)
        out = oh.transpose(0, 2, 1, 3).reshape(B, Sq, self.embed_dim)
        if self.dropout > 0.0 and ctx.training:
            keep = 1.0 - self.dropout
            mask = jax.random.bernoulli(ctx.op_rng(self), keep, out.shape)
            out = jnp.where(mask, out / keep, 0.0).astype(out.dtype)
        return [self._proj(params, out, "wo", "bo")]

    def init_cache(self, batch_size: int, max_len: int, dtype):
        shp = (batch_size, self.num_heads, max_len, self.head_dim)
        return {"k": jnp.zeros(shp, dtype), "v": jnp.zeros(shp, dtype)}

    def decode(self, params, xs, cache, pos, ctx):
        """kv-cached single-token attention: append this step's k/v at
        ``pos``, attend q over the cache prefix (static shapes — the
        future positions are masked, not sliced).  Full-sequence or
        non-causal calls (an encoder re-run per step, or cross-attention
        with a single-token q over full-sequence k/v) are stateless —
        fall back to forward."""
        from jax import lax

        q_in, k_in, v_in = xs
        if q_in.shape[1] != 1 or k_in.shape[1] != 1:
            # full-sequence pass (an encoder re-run, or cross-attention
            # q over full k/v) — stateless, forward is correct
            return self.forward(params, xs, ctx), cache
        if not self.causal:
            # a 1-token non-causal self-attention step would silently
            # attend only itself; no valid cache semantics exist for it
            raise ValueError(
                f"generate: op {self.name!r} is non-causal single-token "
                f"self-attention — not decodable")
        B, S1, _ = q_in.shape
        H, D = self.num_heads, self.head_dim
        q = self._proj(params, q_in, "wq", "bq")
        k = self._proj(params, k_in, "wk", "bk")
        v = self._proj(params, v_in, "wv", "bv")
        split = lambda t: t.reshape(B, S1, H, D).transpose(0, 2, 1, 3)
        qh, kh, vh = split(q), split(k), split(v)            # (B, H, 1, D)
        if jnp.ndim(pos):
            # per-row positions (the serving engine's continuous batch):
            # each row scatters its k/v into its own slot offset and
            # masks by its own prefix length — rows of the SAME batch
            # sit at different sequence positions mid-flight
            rows = jnp.arange(B)
            ck = cache["k"].at[rows, :, pos, :].set(
                kh[:, :, 0, :].astype(cache["k"].dtype))
            cv = cache["v"].at[rows, :, pos, :].set(
                vh[:, :, 0, :].astype(cache["v"].dtype))
            pos_b = pos[:, None, None, None]
        else:
            ck = lax.dynamic_update_slice(
                cache["k"], kh.astype(cache["k"].dtype), (0, 0, pos, 0))
            cv = lax.dynamic_update_slice(
                cache["v"], vh.astype(cache["v"].dtype), (0, 0, pos, 0))
            pos_b = pos
        scale = 1.0 / math.sqrt(D)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32),
                            ck.astype(jnp.float32)) * scale
        valid = jnp.arange(ck.shape[2])[None, None, None, :] <= pos_b
        scores = jnp.where(valid, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs,
                         cv.astype(jnp.float32)).astype(q_in.dtype)
        out = out.transpose(0, 2, 1, 3).reshape(B, S1, self.embed_dim)
        return [self._proj(params, out, "wo", "bo")], {"k": ck, "v": cv}

    def init_paged_cache(self, num_blocks: int, block_size: int, dtype):
        """Block-pool k/v storage shared by every slot: block id indexes
        dim 0, so a slot's cache is whatever its block table names.
        Block 0 is the garbage sink (serving/kvpool.py) — idle lanes
        write and read it, masked."""
        shp = (num_blocks, self.num_heads, block_size, self.head_dim)
        return {"k": jnp.zeros(shp, dtype), "v": jnp.zeros(shp, dtype)}

    def decode_paged(self, params, xs, cache, pos, tables, ctx):
        """Single-token attention over a paged cache: scatter this
        step's k/v into the block named by the row's table at
        ``pos // block_size``, gather the W blocks of the table window
        and attend over W*block_size positions (W is the static window
        bucket the engine picked; positions past ``pos`` are masked with
        the same -1e30 as the dense path, so softmax contributions are
        exactly zero and greedy outputs stay bitwise-equal).

        ``tables``: (B, W) int32 block ids; ``pos``: (B,) or scalar."""
        q_in, k_in, v_in = xs
        if q_in.shape[1] != 1 or k_in.shape[1] != 1:
            raise ValueError(
                f"decode_paged: op {self.name!r} got a full-sequence "
                f"input; paged decode is single-token only")
        if not self.causal:
            raise ValueError(
                f"decode_paged: op {self.name!r} is non-causal — "
                f"not decodable")
        B, S1, _ = q_in.shape
        H, D = self.num_heads, self.head_dim
        bs = cache["k"].shape[2]
        W = tables.shape[1]
        pos_v = pos if jnp.ndim(pos) else jnp.full((B,), pos, jnp.int32)
        q = self._proj(params, q_in, "wq", "bq")
        k = self._proj(params, k_in, "wk", "bk")
        v = self._proj(params, v_in, "wv", "bv")
        split = lambda t: t.reshape(B, S1, H, D).transpose(0, 2, 1, 3)
        qh, kh, vh = split(q), split(k), split(v)            # (B, H, 1, D)
        rows = jnp.arange(B)
        bidx = tables[rows, pos_v // bs]                     # (B,)
        roff = pos_v % bs
        ck = cache["k"].at[bidx, :, roff, :].set(
            kh[:, :, 0, :].astype(cache["k"].dtype))
        cv = cache["v"].at[bidx, :, roff, :].set(
            vh[:, :, 0, :].astype(cache["v"].dtype))
        # window gather: (B, W, H, bs, D) -> (B, H, W*bs, D); table order
        # is logical-block order, so the flat axis is position order
        gk = ck[tables].transpose(0, 2, 1, 3, 4).reshape(B, H, W * bs, D)
        gv = cv[tables].transpose(0, 2, 1, 3, 4).reshape(B, H, W * bs, D)
        scale = 1.0 / math.sqrt(D)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32),
                            gk.astype(jnp.float32)) * scale
        valid = jnp.arange(W * bs)[None, None, None, :] \
            <= pos_v[:, None, None, None]
        scores = jnp.where(valid, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs,
                         gv.astype(jnp.float32)).astype(q_in.dtype)
        out = out.transpose(0, 2, 1, 3).reshape(B, S1, self.embed_dim)
        return [self._proj(params, out, "wo", "bo")], {"k": ck, "v": cv}

    def flops_per_sample(self):
        _, sq, e = self.output.dims
        sk = self.inputs[1].dims[1]
        proj = 2.0 * sq * e * e * 4
        attn = 2.0 * self.num_heads * sq * sk * self.head_dim * 2
        return proj + attn

    def input_ranges(self, j, pc, part_idx):
        # K/V travel the full ring: a seq shard reads every other shard's
        # K/V exactly once, so its effective input range is the full seq.
        rng = super().input_ranges(j, pc, part_idx)
        if j in (1, 2):
            in_dims = self.inputs[j].dims
            rng[1] = (0, in_dims[1] - 1)
        return rng
