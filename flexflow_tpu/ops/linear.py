"""Linear (dense) operator — the tensor-parallel workhorse.

Reference: src/ops/linear.cu (864 LoC: 3 cuBLAS GEMMs + replica tensors).
The reference implements tensor parallelism by replicating the input per
out-channel shard and summing input-gradient replicas with a dedicated
``backward2`` launch (linear.cu:594-621,683-703; create_linear_replica
model.cc:791-846).

TPU-native: one ``jnp.dot`` with the weight sharded on its out-channel dim
along the same mesh axes as the output's channel dim.  XLA GSPMD derives
the forward all-gather/identity and the backward ``psum`` of the input
gradient automatically — the entire replica machinery reduces to a
sharding annotation.  MXU accumulation in float32 via
``preferred_element_type`` for bf16 activations.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from .base import FwdCtx, Op
from .conv2d import ActiMode, apply_activation
from ..initializers import DefaultBiasInitializer, DefaultWeightInitializer


class Linear(Op):
    _type = "Dense"

    def __init__(self, model, input_tensor, out_dim: int,
                 activation: str = ActiMode.NONE, use_bias: bool = True,
                 kernel_initializer=None, bias_initializer=None,
                 share_with=None, name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        in_dim = input_tensor.dims[-1]
        lead = input_tensor.dims[:-1]
        self.activation = activation
        self.use_bias = use_bias
        self._add_output(lead + (out_dim,), input_tensor.dtype)
        out_cfg_dim = len(lead + (out_dim,)) - 1  # channel dim of the output
        if share_with is not None:
            # resolve chains: sharing with an already-shared op means
            # sharing with its owner
            sw = share_with.share_from or share_with
            if not isinstance(sw, Linear) or sw.use_bias != use_bias or \
                    sw.weights[0].dims != (in_dim, out_dim):
                raise ValueError("share_with must be a Dense of identical shape")
            self.share_from = sw
            return
        self._add_weight("kernel", (in_dim, out_dim),
                         kernel_initializer or DefaultWeightInitializer(),
                         partition_dims=(None, out_cfg_dim))
        if use_bias:
            self._add_weight("bias", (out_dim,),
                             bias_initializer or DefaultBiasInitializer(),
                             partition_dims=(out_cfg_dim,))

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        x = xs[0]
        kernel = params["kernel"].astype(x.dtype)
        y = jnp.dot(x, kernel,
                    preferred_element_type=jnp.float32 if x.dtype == jnp.bfloat16 else None)
        y = y.astype(x.dtype)
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return [apply_activation(y, self.activation)]

    def flops_per_sample(self):
        in_dim = self.inputs[0].dims[-1]
        out_dim = self.output.dims[-1]
        return 2.0 * in_dim * out_dim

    def input_ranges(self, j, pc, part_idx):
        """Every out-channel shard reads the FULL input feature dim (the
        reference replicates the input per c-shard, linear.cu:174-185)."""
        rng = super().input_ranges(j, pc, part_idx)
        in_dims = self.inputs[0].dims
        rng[-1] = (0, in_dims[-1] - 1)
        return rng
