"""Pipeline-parallel stacked-dense operator.

SOAP's fourth letter is the Operator dimension; the reference exploits it
by pinning ops to different GPUs and letting Legion overlap them (the NMT
encoder/decoder placement, nmt/nmt.cc:269-308).  This op makes the depth
dimension an explicit partitionable axis: a stack of ``num_stages``
identical (d → d, activation) dense stages whose ``ParallelConfig``
dim 1 is the PIPELINE degree — each mesh-axis slice holds consecutive
stages and activations flow through a GPipe microbatch schedule
(parallel/pipeline.py).  Degree 1 (or single device) runs the same math
sequentially, so strategies change placement, not results.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from .base import FwdCtx, Op
from ..initializers import DefaultWeightInitializer, ZeroInitializer


class PipelineMLP(Op):
    _type = "PipelineMLP"

    def __init__(self, model, input_tensor, num_stages: int,
                 num_microbatches: int = 4, activation: str = "relu",
                 name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        d = input_tensor.dims[-1]
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.activation = activation
        # stage (leading) dim partitions over config dim 1 — the pipeline
        # degree; d×d stages keep one static ring-buffer shape.
        self._add_weight("kernel", (num_stages, d, d),
                         DefaultWeightInitializer(),
                         partition_dims=(1, None, None))
        self._add_weight("bias", (num_stages, d), ZeroInitializer(),
                         partition_dims=(1, None))
        self._add_output(input_tensor.dims)

    def _stage(self, p, h):
        y = jnp.dot(h, p["kernel"].astype(h.dtype))
        y = y + p["bias"].astype(y.dtype)
        if self.activation == "relu":
            y = jax.nn.relu(y)
        elif self.activation == "tanh":
            y = jnp.tanh(y)
        return y

    def _pipe_degree(self) -> int:
        pc = getattr(self, "pc", None)
        if pc is None or len(pc.dims) < 2:
            return 1
        return pc.dims[1]

    def _config_dim_bound(self, i: int):
        """Config dim 1 is the PIPELINE degree: legal iff it divides
        ``num_stages`` (the stage-dim weight sharding and the ppermute
        ring both require it) — NOT the feature width that the base
        size check would compare against."""
        if i == 1:
            return self.num_stages
        return super()._config_dim_bound(i)

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        x = xs[0]
        tree = {"kernel": params["kernel"], "bias": params["bias"]}
        degree = self._pipe_degree()
        machine = self.model.machine
        if degree > 1 and machine is not None and machine.num_devices > 1:
            from ..parallel.pipeline import pipeline_apply

            degrees = list(self.pc.dims) + [1] * (2 - len(self.pc.dims))
            groups = machine.axes_for_degrees(degrees[:2])
            batch_axes = groups[0] if groups[0] else None
            pipe_axes = groups[1]
            # gpipe_spmd sees the PER-SHARD batch (after dp sharding over
            # config dim 0), so microbatch divisibility is checked against
            # the local batch, not the global one.
            local_b = x.shape[0] // max(1, degrees[0])
            mb = min(self.num_microbatches, local_b)
            while local_b % mb != 0:
                mb -= 1
            return [pipeline_apply(self._stage, tree, x, machine.mesh,
                                   pipe_axes, mb, batch_axes=batch_axes)]
        from ..parallel.pipeline import sequential_stages

        return [sequential_stages(self._stage, tree, x)]

    def constraint_pc(self):
        # config dim 1 is the pipeline degree, not a feature-dim split:
        # the output is batch-sharded only (replicated over the pipe axes)
        from ..config import ParallelConfig
        return ParallelConfig(self.pc.device_type,
                              (self.pc.dims[0],) + (1,) * (len(self.pc.dims) - 1),
                              self.pc.device_ids)

    def flops_per_sample(self):
        d = self.output.dims[-1]
        per_tok = 2.0 * d * d * self.num_stages
        if len(self.output.dims) == 3:
            per_tok *= self.output.dims[1]
        return per_tok
