"""LSTM operator — the NMT workhorse.

Reference: nmt/lstm.cu (cudnnRNN over 10-step chunks; weights shared across
chunks via the SharedVariable param-server, nmt/rnn.h:37-51).

TPU-native design: the input projection for ALL timesteps is one large
(B·T, E)×(E, 4H) matmul (MXU-saturating), and only the recurrent
h×(H, 4H) product runs inside ``lax.scan`` — the idiomatic XLA recurrence
(static trip count, no dynamic shapes).  Weight sharing between ops
(reference SharedVariable) is the graph-level ``share_with`` mechanism:
a sharing op reads the owner op's parameters.

Gate order (i, f, g, o); accumulation in float32.

Inputs:  x (B, T, E) [+ optional h0 (B, H), c0 (B, H)]
Outputs: y (B, T, H), h_T (B, H), c_T (B, H)
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .base import FwdCtx, Op
from ..initializers import DefaultWeightInitializer, ZeroInitializer


class LSTM(Op):
    _type = "LSTM"

    def __init__(self, model, input_tensor, hidden_size: int,
                 hx=None, cx=None, share_with: Optional[Op] = None,
                 name: Optional[str] = None):
        inputs = [input_tensor]
        if (hx is None) != (cx is None):
            raise ValueError("provide both hx and cx or neither")
        if hx is not None:
            inputs += [hx, cx]
        super().__init__(model, inputs, name)
        b, t, e = input_tensor.dims
        h = hidden_size
        self.hidden_size = h
        self.has_state_inputs = hx is not None
        self._add_output((b, t, h), input_tensor.dtype)   # y
        self._add_output((b, h), input_tensor.dtype)      # h_T
        self._add_output((b, h), input_tensor.dtype)      # c_T
        if share_with is not None:
            if not isinstance(share_with, LSTM) or share_with.hidden_size != h:
                raise ValueError("share_with must be an LSTM with the same hidden size")
            self.share_from = share_with
        else:
            self._add_weight("w_ih", (e, 4 * h), DefaultWeightInitializer())
            self._add_weight("w_hh", (h, 4 * h), DefaultWeightInitializer())
            self._add_weight("bias", (4 * h,), ZeroInitializer())

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        x = xs[0]
        b, t, _ = x.shape
        h = self.hidden_size
        dt = x.dtype
        acc = jnp.float32 if dt == jnp.bfloat16 else None
        w_ih = params["w_ih"].astype(dt)
        w_hh = params["w_hh"].astype(dt)
        bias = params["bias"].astype(jnp.float32)
        if self.has_state_inputs:
            h0, c0 = xs[1].astype(jnp.float32), xs[2].astype(jnp.float32)
        else:
            h0 = jnp.zeros((b, h), jnp.float32)
            c0 = jnp.zeros((b, h), jnp.float32)

        # One big input projection over all timesteps (B·T on the MXU rows).
        xz = jnp.dot(x.reshape(b * t, -1), w_ih, preferred_element_type=acc)
        xz = xz.reshape(b, t, 4 * h).astype(jnp.float32) + bias
        xz = jnp.swapaxes(xz, 0, 1)  # (T, B, 4H) for scan

        def step(carry, xz_t):
            h_prev, c_prev = carry
            z = xz_t + jnp.dot(h_prev.astype(dt), w_hh,
                               preferred_element_type=acc).astype(jnp.float32)
            i, f, g, o = jnp.split(z, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return (h_new, c_new), h_new

        (h_t, c_t), ys = lax.scan(step, (h0, c0), xz)
        y = jnp.swapaxes(ys, 0, 1).astype(dt)  # (B, T, H)
        return [y, h_t.astype(dt), c_t.astype(dt)]

    def flops_per_sample(self):
        _, t, e = self.inputs[0].dims
        h = self.hidden_size
        return 2.0 * t * (e + h) * 4 * h

    def input_ranges(self, j, pc, part_idx):
        """Batch-tiled only: the recurrence needs the full time extent."""
        in_dims = self.inputs[j].dims
        b_lo, b_hi = self.output_tile(pc, part_idx)[0]
        return [(b_lo, b_hi)] + [(0, s - 1) for s in in_dims[1:]]
