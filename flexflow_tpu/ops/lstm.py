"""LSTM operator — the NMT workhorse.

Reference: nmt/lstm.cu (cudnnRNN over 10-step chunks; weights shared across
chunks via the SharedVariable param-server, nmt/rnn.h:37-51).

TPU-native design: the input projection for ALL timesteps is one large
(B·T, E)×(E, 4H) matmul (MXU-saturating), and only the recurrent
h×(H, 4H) product runs inside ``lax.scan`` — the idiomatic XLA recurrence
(static trip count, no dynamic shapes).  Weight sharing between ops
(reference SharedVariable) is the graph-level ``share_with`` mechanism:
a sharing op reads the owner op's parameters.

Gate order (i, f, g, o); accumulation in float32.

Inputs:  x (B, T, E) [+ optional h0 (B, H), c0 (B, H)]
Outputs: y (B, T, H), h_T (B, H), c_T (B, H)
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .base import FwdCtx, Op
from ..initializers import DefaultWeightInitializer, ZeroInitializer


class LSTM(Op):
    _type = "LSTM"

    def __init__(self, model, input_tensor, hidden_size: int,
                 hx=None, cx=None, share_with: Optional[Op] = None,
                 name: Optional[str] = None):
        inputs = [input_tensor]
        if (hx is None) != (cx is None):
            raise ValueError("provide both hx and cx or neither")
        if hx is not None:
            inputs += [hx, cx]
        super().__init__(model, inputs, name)
        b, t, e = input_tensor.dims
        h = hidden_size
        self.hidden_size = h
        self.has_state_inputs = hx is not None
        self._add_output((b, t, h), input_tensor.dtype)   # y
        self._add_output((b, h), input_tensor.dtype)      # h_T
        self._add_output((b, h), input_tensor.dtype)      # c_T
        if share_with is not None:
            if not isinstance(share_with, LSTM) or share_with.hidden_size != h:
                raise ValueError("share_with must be an LSTM with the same hidden size")
            self.share_from = share_with
        else:
            # Hidden-dim tensor parallelism (config dim 2 = h of y): the
            # 4H gate dim shards with it; w_hh's H contraction dim stays
            # full, so each step's h is all-gathered across shards — the
            # TPU analogue of the reference's hidden-sharded RNN Linear
            # whose replica backward sums per-shard input grads
            # (nmt/rnn.h:91-158, nmt/linear.cu:594-621; here GSPMD emits
            # the all-gather/psum pair from the sharding annotations).
            self._add_weight("w_ih", (e, 4 * h), DefaultWeightInitializer(),
                             partition_dims=(None, 2))
            self._add_weight("w_hh", (h, 4 * h), DefaultWeightInitializer(),
                             partition_dims=(None, 2))
            self._add_weight("bias", (4 * h,), ZeroInitializer(),
                             partition_dims=(2,))

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        x = xs[0]
        b, t, _ = x.shape
        # h from the weight (not self.hidden_size): the simulator measures
        # per-shard sub-shapes by feeding sliced weights.
        h = params["w_ih"].shape[1] // 4
        dt = x.dtype
        acc = jnp.float32 if dt == jnp.bfloat16 else None
        w_ih = params["w_ih"].astype(dt)
        w_hh = params["w_hh"].astype(dt)
        bias = params["bias"].astype(jnp.float32)
        # Under GSPMD h == H_full (logical shapes; the hidden split is a
        # sharding annotation).  h < H_full only when the simulator times
        # a PER-SHARD slice (weight_tile-sized arrays): then the h carry
        # is kept at H_full and each step's shard output is tiled back up,
        # standing in for the per-step all-gather the real TP execution
        # performs — the values are meaningless but the matmul shapes and
        # the gather volume match what one shard computes.
        H_full = w_hh.shape[0]
        if self.has_state_inputs:
            h0, c0 = xs[1].astype(jnp.float32), xs[2].astype(jnp.float32)
            if h != H_full:
                c0 = c0[:, :h]
        else:
            h0 = jnp.zeros((b, H_full), jnp.float32)
            c0 = jnp.zeros((b, h), jnp.float32)

        # One big input projection over all timesteps (B·T on the MXU rows).
        xz = jnp.dot(x.reshape(b * t, -1), w_ih, preferred_element_type=acc)
        xz = xz.reshape(b, t, 4 * h).astype(jnp.float32) + bias
        xz = jnp.swapaxes(xz, 0, 1)  # (T, B, 4H) for scan

        def step(carry, xz_t):
            h_prev, c_prev = carry
            z = xz_t + jnp.dot(h_prev.astype(dt), w_hh,
                               preferred_element_type=acc).astype(jnp.float32)
            h_new, c_new = LSTM._gates(z, c_prev, h)
            h_next = (h_new if h == H_full
                      else jnp.tile(h_new, (1, H_full // h)))
            return (h_next, c_new), h_new

        (_, c_t), ys = lax.scan(step, (h0, c0), xz)
        y = jnp.swapaxes(ys, 0, 1).astype(dt)  # (B, T, H)
        return [y, ys[-1].astype(dt), c_t.astype(dt)]

    @staticmethod
    def _gates(z, c_prev, h):
        """The LSTM cell from pre-activation gates z (B, 4H) — the ONE
        copy forward's scan body and decode both use.  (B, 4, H) so each
        gate's H dim carries the same sharding under hidden-TP (a flat
        4H split would straddle gates)."""
        z = z.reshape(z.shape[0], 4, h)
        i, f, g, o = z[:, 0], z[:, 1], z[:, 2], z[:, 3]
        c_new = jax.nn.sigmoid(f) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(g)
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        return h_new, c_new

    def init_cache(self, batch_size: int, max_len: int, dtype):
        h = self.hidden_size
        return {"h": jnp.zeros((batch_size, h), jnp.float32),
                "c": jnp.zeros((batch_size, h), jnp.float32)}

    def decode(self, params, xs, cache, pos, ctx):
        """Single-token recurrence step.  A full-sequence input (an
        encoder pass re-run each step) falls back to forward; a (B, 1, E)
        input advances the cached (h, c) carry — at pos 0 the carry
        seeds from the hx/cx graph inputs (the encoder's final state),
        matching forward's initialization."""
        x = xs[0]
        if x.shape[1] != 1:
            return self.forward(params, xs, ctx), cache
        dt = x.dtype
        acc = jnp.float32 if dt == jnp.bfloat16 else None
        w_ih = params["w_ih"].astype(dt)
        w_hh = params["w_hh"].astype(dt)
        bias = params["bias"].astype(jnp.float32)
        h_dim = w_ih.shape[1] // 4
        if self.has_state_inputs:
            # pos may be a per-row (B,) vector (serving engine) — align
            # it against the (B, H) state for broadcasting
            at0 = (pos == 0)[:, None] if jnp.ndim(pos) else pos == 0
            h0 = jnp.where(at0, xs[1].astype(jnp.float32), cache["h"])
            c0 = jnp.where(at0, xs[2].astype(jnp.float32), cache["c"])
        else:
            h0, c0 = cache["h"], cache["c"]
        z = jnp.dot(x[:, 0, :], w_ih, preferred_element_type=acc)
        z = z.astype(jnp.float32) + bias
        z = z + jnp.dot(h0.astype(dt), w_hh,
                        preferred_element_type=acc).astype(jnp.float32)
        h_new, c_new = LSTM._gates(z, c0, h_dim)
        y = h_new[:, None, :].astype(dt)
        return ([y, h_new.astype(dt), c_new.astype(dt)],
                {"h": h_new, "c": c_new})

    def flops_per_sample(self):
        _, t, e = self.inputs[0].dims
        h = self.hidden_size
        return 2.0 * t * (e + h) * 4 * h

    def _config_dim_bound(self, i: int):
        """Time (dim 1) never splits — the recurrence is sequential; the
        hidden split (dim 2) must divide H."""
        if i == 1:
            return 1
        return super()._config_dim_bound(i)

    def input_ranges(self, j, pc, part_idx):
        """Batch-tiled only; every hidden shard reads the full input
        features and full h0/c0 (the w_hh contraction needs all of H —
        the reference replicates the RNN Linear input per shard the same
        way, nmt/linear.cu:174-185)."""
        in_dims = self.inputs[j].dims
        b_lo, b_hi = self.output_tile(pc, part_idx)[0]
        return [(b_lo, b_hi)] + [(0, s - 1) for s in in_dims[1:]]
