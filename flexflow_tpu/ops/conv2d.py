"""Conv2D / Pool2D operators (NHWC, MXU-native).

Reference: src/ops/conv_2d.cu (1040 LoC of cuDNN host/launcher code) and
src/ops/pool_2d.cu.  Shape formula matches conv_2d.cu:100-101:
``out = 1 + (in + 2*pad - kernel) / stride``.

TPU-native design notes:
  * activations are NHWC so channels ride the 128-lane dim; kernels are
    HWIO — the layouts XLA:TPU tiles directly onto the MXU without
    relayout.
  * convolution lowers to a single ``lax.conv_general_dilated``; bias and
    activation fuse into it at the XLA level (no separate kernels as in
    the cuDNN path).
  * float32 accumulation is requested via ``preferred_element_type`` when
    activations are bfloat16.
  * spatial (H/W) partitioning — the reference's "attribute" parallelism
    with implicit Legion halo copies (conv_2d.cu:173-211) — is expressed by
    sharding H/W mesh axes; XLA GSPMD emits the halo-exchange
    collective-permutes over ICI.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .base import FwdCtx, Op
from ..initializers import DefaultBiasInitializer, DefaultWeightInitializer


class ActiMode:
    NONE = "none"
    RELU = "relu"
    SIGMOID = "sigmoid"
    TANH = "tanh"
    GELU = "gelu"


def apply_activation(x, activation: Optional[str]):
    if not activation or activation == ActiMode.NONE:
        return x
    if activation == ActiMode.RELU:
        return jax.nn.relu(x)
    if activation == ActiMode.SIGMOID:
        return jax.nn.sigmoid(x)
    if activation == ActiMode.TANH:
        return jnp.tanh(x)
    if activation == ActiMode.GELU:
        return jax.nn.gelu(x)
    raise ValueError(f"unknown activation {activation}")


class Conv2D(Op):
    _type = "Conv2D"

    def __init__(self, model, input_tensor, out_channels: int,
                 kernel_h: int, kernel_w: int, stride_h: int, stride_w: int,
                 padding_h: int, padding_w: int, activation: str = ActiMode.NONE,
                 use_bias: bool = True, groups: int = 1,
                 kernel_initializer=None, bias_initializer=None,
                 share_with=None, name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        n, h, w, cin = input_tensor.dims
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.padding = (padding_h, padding_w)
        self.activation = activation
        self.use_bias = use_bias
        self.groups = groups
        out_h = 1 + (h + 2 * padding_h - kernel_h) // stride_h
        out_w = 1 + (w + 2 * padding_w - kernel_w) // stride_w
        self._add_output((n, out_h, out_w, out_channels), input_tensor.dtype)
        if share_with is not None:
            sw = share_with.share_from or share_with  # resolve chains
            kshape = (kernel_h, kernel_w, cin // groups, out_channels)
            if not isinstance(sw, Conv2D) or sw.use_bias != use_bias or \
                    sw.weights[0].dims != kshape:
                raise ValueError("share_with must be a Conv2D of identical shape")
            self.share_from = sw
            return
        # Kernel replicated across sample/spatial parts (the reference
        # replicates it and aggregates grad replicas, model.cc:763-787;
        # here GSPMD psums the gradient); out-channel dim shards with the
        # output channel config dim (index 3, NHWC).
        self._add_weight(
            "kernel", (kernel_h, kernel_w, cin // groups, out_channels),
            kernel_initializer or DefaultWeightInitializer(),
            partition_dims=(None, None, None, 3))
        if use_bias:
            self._add_weight("bias", (out_channels,),
                             bias_initializer or DefaultBiasInitializer(),
                             partition_dims=(3,))

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        x = xs[0]
        kernel = params["kernel"].astype(x.dtype)
        ph, pw = self.padding
        # No explicit f32 upcast: the MXU accumulates bf16 convs in f32
        # internally, and a preferred_element_type≠input dtype breaks the
        # conv transpose (wgrad) rule under jax.grad.
        y = lax.conv_general_dilated(
            x, kernel,
            window_strides=self.stride,
            padding=((ph, ph), (pw, pw)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.groups,
        )
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return [apply_activation(y, self.activation)]

    def flops_per_sample(self):
        _, oh, ow, oc = self.output.dims
        kh, kw = self.kernel
        cin = self.inputs[0].dims[3]
        return 2.0 * oh * ow * oc * kh * kw * (cin // self.groups)

    def input_ranges(self, j, pc, part_idx):
        """Exact conv input rectangle incl. halo for an output tile
        (the reference's implicit Legion halo, conv_2d.cu:173-211)."""
        n, ih, iw, cin = self.inputs[0].dims
        (n_lo, n_hi), (oh_lo, oh_hi), (ow_lo, ow_hi), _ = \
            self.output_tile(pc, part_idx)
        sh, sw = self.stride
        ph, pw = self.padding
        kh, kw = self.kernel
        h_lo = max(0, oh_lo * sh - ph)
        h_hi = min(ih - 1, oh_hi * sh - ph + kh - 1)
        w_lo = max(0, ow_lo * sw - pw)
        w_hi = min(iw - 1, ow_hi * sw - pw + kw - 1)
        return [(n_lo, n_hi), (h_lo, h_hi), (w_lo, w_hi), (0, cin - 1)]


class PoolType:
    MAX = "max"
    AVG = "avg"


class Pool2D(Op):
    _type = "Pool2D"

    def __init__(self, model, input_tensor, kernel_h: int, kernel_w: int,
                 stride_h: int, stride_w: int, padding_h: int, padding_w: int,
                 pool_type: str = PoolType.MAX, activation: str = ActiMode.NONE,
                 name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        n, h, w, c = input_tensor.dims
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.padding = (padding_h, padding_w)
        self.pool_type = pool_type
        self.activation = activation
        out_h = 1 + (h + 2 * padding_h - kernel_h) // stride_h
        out_w = 1 + (w + 2 * padding_w - kernel_w) // stride_w
        self._add_output((n, out_h, out_w, c), input_tensor.dtype)

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        x = xs[0]
        kh, kw = self.kernel
        sh, sw = self.stride
        ph, pw = self.padding
        dims = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        pads = ((0, 0), (ph, ph), (pw, pw), (0, 0))
        if self.pool_type == PoolType.MAX:
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
            y = lax.reduce_window(x, init, lax.max, dims, strides, pads)
        else:
            # Average with padding excluded from the divisor, matching
            # cuDNN's CUDNN_POOLING_AVERAGE_COUNT_EXCLUDE_PADDING used by
            # the reference pool op.
            s = lax.reduce_window(x.astype(jnp.float32), 0.0, lax.add, dims, strides, pads)
            ones = jnp.ones(x.shape[1:3], jnp.float32)[None, :, :, None]
            cnt = lax.reduce_window(ones, 0.0, lax.add, (1, kh, kw, 1), strides,
                                    ((0, 0), (ph, ph), (pw, pw), (0, 0)))
            y = (s / cnt).astype(x.dtype)
        return [apply_activation(y, self.activation)]

    def flops_per_sample(self):
        _, oh, ow, c = self.output.dims
        return float(oh * ow * c * self.kernel[0] * self.kernel[1])
