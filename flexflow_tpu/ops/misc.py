"""Flat / Softmax / Concat / Dropout / element-wise operators.

Reference files: src/ops/flat.cu (cross-rank partition copy),
src/ops/softmax.cu (cudnnSoftmaxForward ACCURATE), src/ops/concat.cu,
src/ops/dropout.cu (cudnnDropout with reserve space),
src/ops/element_unary.cu, src/ops/element_binary.cu, src/ops/mse_loss.cu.

All are single jnp expressions here — XLA fuses them into neighbouring
matmuls/convs, which is precisely why the reference's hand-written copy
and activation kernels have no TPU counterpart.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from .base import FwdCtx, Op


class Flat(Op):
    """(B, H, W, C) → (B, H*W*C).  Reference: src/ops/flat.cu:96 uses a
    cross-dimensionality Legion partition; here it is a reshape, and the
    4D→2D partition transition (model.cc:571-606) is GSPMD resharding.
    Note the element order is HWC (NHWC-native), not the reference's CHW —
    a layout choice, not a semantic one."""

    _type = "Flat"

    def __init__(self, model, input_tensor, name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        n = input_tensor.dims[0]
        flat = 1
        for d in input_tensor.dims[1:]:
            flat *= d
        self._add_output((n, flat), input_tensor.dtype)

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        return [xs[0].reshape(xs[0].shape[0], -1)]


class Softmax(Op):
    """Reference: src/ops/softmax.cu:166 (CUDNN_SOFTMAX_ACCURATE — i.e. the
    max-subtracted stable form, which is jax.nn.softmax).  When a CE loss
    follows, the executor feeds the loss from this op's *input* so the
    fused log-softmax path is used (see losses.py)."""

    _type = "Softmax"

    def __init__(self, model, input_tensor, name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        self._add_output(input_tensor.dims, input_tensor.dtype)

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        return [jax.nn.softmax(xs[0].astype(jnp.float32), axis=-1).astype(xs[0].dtype)]


class Concat(Op):
    """Reference: src/ops/concat.cu (custom copy kernels, variable #inputs,
    axis in NCHW order).  ``axis`` here is in native (NHWC) order — the
    model-builder converts reference-style channel axes."""

    _type = "Concat"

    def __init__(self, model, input_tensors, axis: int, name: Optional[str] = None):
        super().__init__(model, list(input_tensors), name)
        self.axis = axis
        base = list(input_tensors[0].dims)
        base[axis] = sum(t.dims[axis] for t in input_tensors)
        for t in input_tensors[1:]:
            for d in range(len(base)):
                if d != axis and t.dims[d] != base[d]:
                    raise ValueError(f"concat shape mismatch at dim {d}: {t.dims} vs {base}")
        self._add_output(tuple(base), input_tensors[0].dtype)

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        return [jnp.concatenate(xs, axis=self.axis)]

    def input_ranges(self, j, pc, part_idx):
        """Output tile ranges shifted by the input's offset along the
        concat axis, clipped to that input's extent."""
        tile = self.output_tile(pc, part_idx)
        off = sum(t.dims[self.axis] for t in self.inputs[:j])
        in_dims = self.inputs[j].dims
        rng = []
        for i, (lo, hi) in enumerate(tile):
            if i == self.axis:
                lo, hi = lo - off, hi - off
                lo, hi = max(0, lo), min(in_dims[i] - 1, hi)
            rng.append((lo, hi))
        return rng


class Dropout(Op):
    """Reference: src/ops/dropout.cu (cudnnDropout, seeded reserve space).
    Pure-functional: the mask derives from the per-step RNG folded with the
    op guid; identity when not training."""

    _type = "Dropout"

    def __init__(self, model, input_tensor, rate: float, seed: int = 0,
                 name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        self.rate = float(rate)
        self.seed = seed
        self._add_output(input_tensor.dims, input_tensor.dtype)

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        x = xs[0]
        if not ctx.training or self.rate <= 0.0:
            return [x]
        keep = 1.0 - self.rate
        rng = jax.random.fold_in(ctx.op_rng(self), self.seed)
        mask = jax.random.bernoulli(rng, p=keep, shape=x.shape)
        return [jnp.where(mask, x / keep, 0).astype(x.dtype)]


_UNARY = {
    "exp": jnp.exp,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "elu": jax.nn.elu,
    "identity": lambda x: x,
}


class ElementUnary(Op):
    """Reference: src/ops/element_unary.cu (cudnnActivation or custom
    kernels; graph API FFModel::exp/relu/... element_unary.cu:19-50)."""

    _type = "ElementUnary"

    def __init__(self, model, input_tensor, op_name: str, name: Optional[str] = None):
        if op_name not in _UNARY:
            raise ValueError(f"unknown unary op {op_name}")
        super().__init__(model, [input_tensor], name)
        self.op_name = op_name
        self._add_output(input_tensor.dims, input_tensor.dtype)

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        return [_UNARY[self.op_name](xs[0])]


_BINARY = {
    "add": jnp.add,
    "subtract": jnp.subtract,
    "multiply": jnp.multiply,
    "divide": jnp.divide,
}


class ElementBinary(Op):
    """Reference: src/ops/element_binary.cu (add/sub/mul/div kernels,
    include/model.h:436-479)."""

    _type = "ElementBinary"

    def __init__(self, model, x, y, op_name: str, name: Optional[str] = None):
        if op_name not in _BINARY:
            raise ValueError(f"unknown binary op {op_name}")
        if x.dims != y.dims:
            raise ValueError(f"element binary shape mismatch: {x.dims} vs {y.dims}")
        super().__init__(model, [x, y], name)
        self.op_name = op_name
        self._add_output(x.dims, x.dtype)

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        return [_BINARY[self.op_name](xs[0], xs[1])]


class BatchNorm(Op):
    """Reference: src/ops/batch_norm.cu (cudnnBatchNorm spatial mode, scale
    and bias params, optional fused relu).  Batch statistics at train time;
    running moments kept as non-trainable stats for eval, updated with the
    reference cuDNN default momentum 0.1 semantics."""

    _type = "BatchNorm"
    MOMENTUM = 0.1
    EPS = 1e-5

    def __init__(self, model, input_tensor, relu: bool = True, name: Optional[str] = None):
        super().__init__(model, [input_tensor], name)
        self.relu = relu
        c = input_tensor.dims[-1]
        self._add_output(input_tensor.dims, input_tensor.dtype)
        from ..initializers import ConstantInitializer, ZeroInitializer

        cdim = len(input_tensor.dims) - 1
        self._add_weight("scale", (c,), ConstantInitializer(1.0), partition_dims=(cdim,))
        self._add_weight("bias", (c,), ZeroInitializer(), partition_dims=(cdim,))

    def init_stats(self):
        c = self.inputs[0].dims[-1]
        return {"mean": jnp.zeros((c,), jnp.float32), "var": jnp.ones((c,), jnp.float32)}

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        x = xs[0]
        axes = tuple(range(x.ndim - 1))
        xf = x.astype(jnp.float32)
        if ctx.training:
            mean = jnp.mean(xf, axis=axes)
            var = jnp.var(xf, axis=axes)
            if ctx.stats_out is not None:
                old = ctx.stats_in[self.name]
                m = BatchNorm.MOMENTUM
                ctx.stats_out[self.name] = {
                    "mean": (1 - m) * old["mean"] + m * mean,
                    "var": (1 - m) * old["var"] + m * var,
                }
        else:
            st = ctx.stats_in[self.name]
            mean, var = st["mean"], st["var"]
        inv = jax.lax.rsqrt(var + BatchNorm.EPS)
        y = (xf - mean) * inv * params["scale"] + params["bias"]
        y = y.astype(x.dtype)
        if self.relu:
            y = jax.nn.relu(y)
        return [y]


class MSELoss(Op):
    """Legacy MSE-loss op (reference: src/ops/mse_loss.cu — pre-``Loss``
    refactor path).  Produces the scalar mean-squared-error of its two
    inputs; kept for API parity."""

    _type = "MSELoss"

    def __init__(self, model, logit, label, reduction: str = "average",
                 name: Optional[str] = None):
        super().__init__(model, [logit, label], name)
        self.reduction = reduction
        self._add_output((1,), "float32")

    def forward(self, params, xs: List[jax.Array], ctx: FwdCtx):
        diff = xs[0].astype(jnp.float32) - xs[1].astype(jnp.float32)
        sq = jnp.sum(diff * diff)
        if self.reduction == "average":
            sq = sq / xs[0].shape[0]
        return [sq.reshape(1)]
