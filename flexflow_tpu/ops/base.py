"""Operator base class.

TPU-native analogue of the reference ``Op`` abstract class
(reference: include/model.h:190-231).  The reference contract is 8 Legion
methods (create_output_and_partition / create_weights / init / forward /
backward / measure_compute_time ...); here an op is a *pure function* plus
shape/partition metadata:

  * construction performs shape inference and declares weights
    (≈ create_weights + create_output_and_partition),
  * ``forward`` is a jit-traceable function of (weights, inputs) — the
    backward pass comes from ``jax.grad``, so no hand-written backward,
  * ``weight_partition_dims`` maps each weight dim to the output-config
    dim it shards with (compile lowers this to NamedShardings — the
    analogue of create_weights' region partitioning),
  * the simulator costs ops by compiling+timing ``forward`` on sub-shapes
    (≈ measure_compute_time).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import jax

from ..tensor import Parameter, Tensor


@dataclasses.dataclass
class FwdCtx:
    """Per-call context threaded through op forwards inside jit."""

    training: bool = False
    rng: Optional[jax.Array] = None  # folded per-op by guid before use
    stats_in: Optional[Dict[str, Dict[str, jax.Array]]] = None
    stats_out: Optional[Dict[str, Dict[str, jax.Array]]] = None

    def op_rng(self, op: "Op") -> jax.Array:
        assert self.rng is not None, "op requires an RNG but none was provided"
        return jax.random.fold_in(self.rng, op.guid)


class Op:
    """Graph node: inputs → outputs with optional weights/state."""

    _type: str = "Op"

    def __init__(self, model, inputs: Sequence[Tensor], name: Optional[str] = None):
        self.model = model
        self.guid = model._next_op_guid()
        # Reference auto-names ops "<Type>_<guid>" (src/runtime/model.cc:142-144)
        # unless the _v2 named API supplies one; strategy files bind by name.
        self.name = name if name else f"{self._type}_{self.guid}"
        self.inputs: List[Tensor] = list(inputs)
        self.weights: List[Parameter] = []
        self.outputs: List[Tensor] = []
        self.profiling = False
        # Weight sharing (reference: NMT SharedVariable nmt/rnn.h:37-51 and
        # the FFModel ops' weight_sharing argument): when set, this op has
        # no weights of its own and reads the owner op's parameters.
        self.share_from: Optional["Op"] = None

    @property
    def param_key(self) -> str:
        """Key into the params pytree: the owning op's name."""
        return self.share_from.name if self.share_from is not None else self.name

    # -- graph construction ------------------------------------------------
    def _add_output(self, dims, dtype="float32") -> Tensor:
        t = Tensor(dims=tuple(dims), dtype=dtype, owner_op=self, owner_idx=len(self.outputs))
        self.outputs.append(t)
        return t

    def _add_weight(self, name, dims, initializer, partition_dims=None, dtype="float32") -> Parameter:
        p = Parameter(name=name, dims=tuple(dims), dtype=dtype,
                      initializer=initializer, owner_op=self,
                      partition_dims=partition_dims)
        self.weights.append(p)
        return p

    @property
    def output(self) -> Tensor:
        return self.outputs[0]

    # -- execution ---------------------------------------------------------
    def forward(self, params: Dict[str, jax.Array], xs: List[jax.Array], ctx: FwdCtx) -> List[jax.Array]:
        raise NotImplementedError

    # -- autoregressive decoding (FFModel.generate) ------------------------
    def init_cache(self, batch_size: int, max_len: int, dtype):
        """Decode-cache pytree for kv-cached generation; None for
        stateless ops."""
        return None

    def decode(self, params, xs: List[jax.Array], cache, pos, ctx: FwdCtx):
        """One-token decode step at sequence position ``pos`` (scalar
        int array).  ``xs`` carry a single time step (B, 1, ...).
        Returns (ys, new_cache).  Default: stateless forward."""
        return self.forward(params, xs, ctx), cache

    def constraint_pc(self):
        """ParallelConfig used to place this op's OUTPUT activations.
        Defaults to the op's own config; ops whose config dims carry
        non-layout meaning (e.g. the pipeline degree) override this."""
        return self.pc

    def _config_dim_bound(self, i: int) -> Optional[int]:
        """The size config dim ``i``'s degree must divide (None: no
        bound).  Ops whose config dims carry non-size meaning override
        this (PipelineMLP's dim 1 is the pipe degree, bounded by
        num_stages rather than the feature width)."""
        return self.output.dims[i] if i < self.output.num_dims else None

    def legalize_pc(self, pc):
        """Clamp a proposed config to one this op can execute — used by
        compile() and by BOTH search paths before costing a candidate.
        Each dim's degree must divide the op's bound for that dim (the
        reference simply asserts; we degrade to the largest legal
        degree)."""
        import math

        from ..config import ParallelConfig

        dims = list(pc.dims)
        changed = False
        for i, d in enumerate(dims):
            bound = self._config_dim_bound(i)
            if bound is not None and bound % d != 0:
                dims[i] = math.gcd(d, bound)
                changed = True
        if not changed:
            return pc
        npc = ParallelConfig(pc.device_type, tuple(dims),
                             memory_types=pc.memory_types)
        return npc.with_device_ids(tuple(range(npc.num_parts())))

    # -- stats (non-trainable state, e.g. batchnorm running moments) -------
    def init_stats(self) -> Dict[str, jax.Array]:
        return {}

    # -- cost model hooks (used by the simulator) --------------------------
    def flops_per_sample(self) -> float:
        """Analytic forward FLOPs per sample; simulator fallback when a
        measured timing is unavailable."""
        return 0.0

    # -- tiling hooks (simulator comm model; analogue of the reference's
    # get_output_tensor_shape / get_input_tensor_shape, model.cc:333-380) --
    def _grid_coord(self, pc, part_idx):
        coord = []
        rem = part_idx
        for d in reversed(pc.dims):
            coord.append(rem % d)
            rem //= d
        return tuple(reversed(coord))

    def output_tile(self, pc, part_idx, output_idx: int = 0):
        """Per-dim (lo, hi) inclusive ranges of this part's output tile."""
        dims = self.outputs[output_idx].dims
        coord = self._grid_coord(pc, part_idx)
        out = []
        for i, size in enumerate(dims):
            deg = pc.dims[i] if i < len(pc.dims) else 1
            c = coord[i] if i < len(coord) else 0
            tile = size // deg
            out.append((c * tile, (c + 1) * tile - 1))
        return out

    def input_ranges(self, j: int, pc, part_idx):
        """Per-dim (lo, hi) ranges of input ``j`` this part reads.

        Default: proportional mapping when ranks match (a dim of the
        output maps onto the same dim of the input, scaled — this yields
        conv-style halos approximately); otherwise only the batch dim is
        tiled and the rest is read fully."""
        in_dims = self.inputs[j].dims
        out_dims = self.outputs[0].dims
        tile = self.output_tile(pc, part_idx)
        rng = []
        if len(in_dims) == len(out_dims):
            for i, isz in enumerate(in_dims):
                osz = out_dims[i]
                lo, hi = tile[i]
                if isz == osz:
                    rng.append((lo, hi))
                else:
                    rng.append((lo * isz // osz,
                                min(isz - 1, -((-(hi + 1) * isz) // osz) - 1)))
        else:
            b_lo, b_hi = tile[0]
            rng.append((b_lo * in_dims[0] // out_dims[0],
                        (b_hi + 1) * in_dims[0] // out_dims[0] - 1))
            for isz in in_dims[1:]:
                rng.append((0, isz - 1))
        return rng

    def weight_tile(self, pc, w_idx: int, part_idx):
        """Per-dim ranges of weight ``w_idx`` held by this part — full
        range for replicated dims, the part's slice for sharded dims."""
        w = self.weights[w_idx]
        coord = self._grid_coord(pc, part_idx)
        out = []
        for i, size in enumerate(w.dims):
            pd = w.partition_dims[i]
            if pd is None or pd >= len(pc.dims) or pc.dims[pd] == 1:
                out.append((0, size - 1))
            else:
                deg = pc.dims[pd]
                c = coord[pd]
                tile = size // deg
                out.append((c * tile, (c + 1) * tile - 1))
        return out

    def __repr__(self):
        ins = ",".join(str(t.dims) for t in self.inputs)
        outs = ",".join(str(t.dims) for t in self.outputs)
        return f"{self._type}({self.name}: {ins} -> {outs})"
