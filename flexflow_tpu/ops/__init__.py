"""Operator library (TPU-native analogues of src/ops/*.cu)."""

from .base import FwdCtx, Op
from .conv2d import ActiMode, Conv2D, Pool2D, PoolType, apply_activation
from .embedding import AggrMode, Embedding
from .linear import Linear
from .misc import (BatchNorm, Concat, Dropout, ElementBinary, ElementUnary,
                   Flat, MSELoss, Softmax)
from .attention import LayerNorm, MultiHeadAttention
