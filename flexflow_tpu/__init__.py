"""flexflow_tpu — a TPU-native FlexFlow-class training framework.

A from-scratch re-design of early (Legion-era, MLSys'19 "SOAP") FlexFlow
for TPUs: layer-graph model building, per-operator hybrid parallelization
over sample/attribute/parameter dimensions via strategy files, an execution
simulator + MCMC search for automatic parallelization, and end-to-end
training — all lowering to JAX/XLA SPMD over device meshes instead of
Legion tasks + cuDNN kernels.  See SURVEY.md at the repo root for the full
reference inventory this framework mirrors.
"""

import os as _os

if _os.environ.get("JAX_PLATFORMS", "").split(",")[0] == "cpu":
    # TPU site plugins force-select their platform at interpreter boot
    # via jax.config.update, which silently overrides the JAX_PLATFORMS
    # environment variable (config beats env in jax) — so
    # ``JAX_PLATFORMS=cpu python examples/...`` would still try to
    # initialize the TPU backend.  Re-assert an explicit CPU choice.
    # Only the cpu direction is handled: the site env exports a TPU
    # value by default, and re-asserting it would clobber test
    # harnesses that select "cpu" via jax.config after boot.
    import jax as _jax

    if (_jax.config.jax_platforms or "").split(",")[0] != "cpu":
        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

from .config import DeviceType, FFConfig, ParallelConfig
from .initializers import (ConstantInitializer, GlorotUniform, NormInitializer,
                           UniformInitializer, ZeroInitializer)
from .losses import Loss, LossType
from .metrics import MetricsType, PerfMetrics
from .model import FFModel
from .ops.base import Op
from .ops.conv2d import ActiMode, PoolType
from .ops.embedding import AggrMode
from .optimizers import (AdamOptimizer, OptaxOptimizer, Optimizer,
                         SGDOptimizer)
from .parallel.mesh import Machine
from .parallel.strategy import load_strategies_from_file, save_strategies_to_file
from .runtime.dataloader import DataLoader
from .tensor import DataType, Parameter, Tensor

__version__ = "0.6.0"

__all__ = [
    "ActiMode", "AdamOptimizer", "AggrMode", "ConstantInitializer",
    "DataLoader", "DataType", "DeviceType", "FFConfig", "FFModel",
    "GlorotUniform", "Loss", "LossType", "Machine", "MetricsType",
    "NormInitializer", "Op", "Optimizer", "Parameter", "ParallelConfig",
    "OptaxOptimizer", "PerfMetrics", "PoolType", "SGDOptimizer", "Tensor",
    "UniformInitializer", "ZeroInitializer", "load_strategies_from_file",
    "save_strategies_to_file",
]
