"""Loss functions with reference-exact gradient semantics.

TPU-native analogue of the reference loss layer
(reference: src/loss_functions/loss_functions.cu, include/loss_functions.h).

The reference computes the loss *gradient* directly at the softmax output
region and scales by ``1/batch_size`` (loss_functions.cu:141-150):
  * sparse CCE: grad = probs; probs[label] -= 1   (× 1/B)
  * CCE / MSE-avg: grad = logit - label           (× 1/B)

Here losses are scalar-valued pure functions differentiated by ``jax.grad``
— chosen so the autodiff gradient is *identical* to the reference kernels:
  * sparse/dense CCE is computed from the **pre-softmax** activations via
    ``log_softmax`` (the fused softmax+CE form: d/dlogits = (probs-onehot)/B
    — exactly the reference's fused pair of softmax-forward + CE-backward).
  * MSE-avg uses 0.5·mean over samples of the squared error, whose gradient
    is (logit-label)/B.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class LossType:
    CATEGORICAL_CROSSENTROPY = "categorical_crossentropy"
    SPARSE_CATEGORICAL_CROSSENTROPY = "sparse_categorical_crossentropy"
    MEAN_SQUARED_ERROR_AVG_REDUCE = "mean_squared_error"


def _canon(loss_type: str) -> str:
    aliases = {
        "categorical_crossentropy": LossType.CATEGORICAL_CROSSENTROPY,
        "sparse_categorical_crossentropy": LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        "mean_squared_error": LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
        "mse": LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
    }
    if loss_type not in aliases:
        raise ValueError(f"Unrecognized loss type: {loss_type}")
    return aliases[loss_type]


class Loss:
    """Scalar loss over (pre-softmax logits, labels).

    ``wants_logits`` tells the executor whether to feed the *input* of a
    trailing Softmax op (the fused, numerically-stable TPU path) instead of
    its output.
    """

    def __init__(self, loss_type: str):
        self.loss_type = _canon(loss_type)

    @property
    def wants_logits(self) -> bool:
        return self.loss_type in (
            LossType.CATEGORICAL_CROSSENTROPY,
            LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        )

    def __call__(self, preds: jax.Array, labels: jax.Array) -> jax.Array:
        """preds: (B, C) logits for CE losses, final outputs for MSE —
        or (B, T, C) for sequence models (NMT), reduced per-token.
        labels: (B,)/(B,1) [or (B,T)] int for sparse CE; matching shape
        otherwise."""
        preds = preds.astype(jnp.float32)
        if preds.ndim > 2:  # sequence logits: fold time into the batch dim
            preds = preds.reshape(-1, preds.shape[-1])
            labels = labels.reshape(preds.shape[0], -1) \
                if labels.ndim > 1 and labels.size != preds.shape[0] else labels
        batch = preds.shape[0]
        if self.loss_type == LossType.SPARSE_CATEGORICAL_CROSSENTROPY:
            labels = labels.reshape(batch).astype(jnp.int32)
            logp = jax.nn.log_softmax(preds, axis=-1)
            nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)
            return jnp.sum(nll) / batch
        if self.loss_type == LossType.CATEGORICAL_CROSSENTROPY:
            logp = jax.nn.log_softmax(preds, axis=-1)
            return jnp.sum(-labels.astype(jnp.float32) * logp) / batch
        # MSE avg-reduce: grad must be (pred-label)/B per element
        diff = preds - labels.astype(jnp.float32)
        return 0.5 * jnp.sum(diff * diff) / batch
