"""Deterministic, seeded fault injection (``FF_CHAOS``).

The reference FlexFlow is strictly fail-stop: any device error aborts
the process (FatalError, cuda_helper.h:6-36) and there is no way to
*provoke* a failure short of yanking hardware, so its (nonexistent)
recovery paths were never testable.  This module is the other half of
``runtime/resilience.py``: a fault injector precise enough that every
recovery path — skip-step, preemption save, checkpoint retry — is
exercised by a seeded spec and asserted bitwise in CI.

Spec grammar (``FF_CHAOS`` environment variable)::

    FF_CHAOS   = entry (";" entry)*
    entry      = site ":" trigger "=" fault [":" arg]
    site       = "step" | "data" | "ckpt_save" | "ckpt_restore" | "sync"
               | "serve" | "resharding"
    trigger    = INT          exact trigger (fires once, then is spent)
               | "p" FLOAT    per-call probability (seeded, repeatable)
    fault      = "nan_loss"   poison the staged batch's float leaves with
                              NaN (step site: the step's loss and grads
                              go non-finite)
               | "hang"       sleep ``arg`` seconds (default 3600) —
                              a wedged device/tunnel for watchdog tests
               | "io_error"   raise ChaosIOError (an OSError: retried by
                              the checkpoint retry wrapper)
               | "sigterm"    os.kill(self, SIGTERM) — a preemption
               | "sigint"     os.kill(self, SIGINT)
               | "error"      raise ChaosError (generic failure)
               | "device_loss"   ``arg`` (default 1) devices vanish from
                              the mesh — recorded on ``lost_device_count``
                              and observed by the reconfiguration
                              controller's probe at its ``resharding``
                              choke point (the controller re-searches
                              over the survivors and hot-swaps)
               | "device_gain"   ``arg`` (default 1) lost devices
                              reappear (clamped at a whole mesh)
               | "divergence" inflate every SUBSEQUENT measured step by
                              ``arg`` seconds (default 0.05) — a planted
                              perf regression for probation/rollback and
                              sim-divergence tests; persistent, not
                              one-shot
               | "replica_kill"  (serve site) raise ChaosReplicaKill out
                              of the admitting engine's decode loop —
                              the replica crashes; the pool fails over
               | "replica_hang"  (serve site) wedge the admitting
                              replica's loop thread for ``arg`` seconds
                              (default 3600) so the pool's heartbeat
                              monitor declares it stalled
               | "zone_outage"   (serve site) zone ``arg`` (an index
                              into FF_SERVE_ZONES, default 0) goes dark
                              — recorded on ``zones_down``; the pool's
                              monitor marks EVERY replica in that zone
                              down at once, fails their in-flight
                              attempts over to surviving zones, and the
                              autoscaler backfills capacity there.
                              Recorded state like device_loss: the
                              admitting request itself is unharmed.
    arg        = FLOAT        fault parameter (hang seconds, lost/regained
                              device count, per-step inflation seconds,
                              zone index)

For the ``step`` site the trigger is the model's GLOBAL step index
(``model._step_count`` at ``update()`` entry) — resume-aware, so an
injected fault does not re-fire after a checkpoint restore past it.
For every other site it is the 1-based count of calls to that site's
choke point *in this process*; checkpoint retry attempts each count,
so ``ckpt_save:1=io_error`` fails the first attempt and lets the retry
succeed.

The ``serve`` site fires at the serving engine's per-request ADMISSION
choke point (trigger = 1-based admission count), before the prefill —
so ``serve:2=error`` fails exactly the second admitted request, which
must NOT kill the batch loop or any other request (the engine's
per-request error isolation, tests/test_serving.py); ``serve:3=hang:2``
wedges the loop thread for 2s, stalling every in-flight request.  Two
faults target the REPLICA, not the request: ``serve:3=replica_kill``
throws ``ChaosReplicaKill`` out of the admitting engine's decode loop —
the whole replica crashes, the pool marks it UNHEALTHY, fails its
in-flight requests over to survivors, and restarts it with backoff;
``serve:3=replica_hang:5`` wedges the replica's loop thread for 5s so
the pool's heartbeat monitor (``FF_SERVE_REPLICA_TIMEOUT``) declares it
stalled.  Under a pool the admission counter is SHARED across replicas
(the monkey serializes ``fire`` with a lock), so triggers stay a
deterministic 1-based admission count regardless of which replica
admits.

The ``resharding`` site fires from the reconfiguration controller's
per-step-boundary hook (``runtime/reconfigure.py``), with the GLOBAL
step index as trigger domain (resume-aware, like ``step``) — so
``resharding:4=device_loss:4`` makes 4 devices vanish after step 4
and the controller re-parallelizes over the 4 survivors.  Device
loss/gain is *recorded state* (``lost_device_count``): on a virtual
CPU mesh a chip cannot physically vanish, so the controller's probe
reads the monkey instead of the hardware.

Examples::

    FF_CHAOS="step:23=nan_loss;step:40=hang:2;ckpt_save:2=io_error"
    FF_CHAOS="step:57=sigterm"            # deterministic preemption
    FF_CHAOS="step:p0.01=nan_loss" FF_CHAOS_SEED=7   # 1% of steps, seeded

Zero overhead when unset: ``from_env()`` returns None and every choke
point guards on a plain ``is not None`` attribute test — no parsing, no
dict lookups, no extra device dispatches (asserted by
tests/test_chaos.py).

STDLIB-ONLY at import time (jax is imported lazily inside the one fault
that touches arrays) so bench/tools can import this before jax
initializes.
"""

from __future__ import annotations

import os
import signal
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

SITES = ("step", "data", "ckpt_save", "ckpt_restore", "sync", "serve",
         "resharding")
FAULTS = ("nan_loss", "hang", "io_error", "sigterm", "sigint", "error",
         "device_loss", "device_gain", "divergence",
         "replica_kill", "replica_hang", "zone_outage")


class ChaosError(RuntimeError):
    """Generic injected failure (``fault=error``)."""


class ChaosIOError(OSError):
    """Injected I/O failure (``fault=io_error``) — an OSError so the
    checkpoint retry wrapper treats it exactly like a real filesystem
    error."""


class ChaosReplicaKill(RuntimeError):
    """Injected replica crash (``fault=replica_kill``).  The serving
    engine deliberately does NOT isolate this one per-request: it
    propagates out of the decode loop, the replica thread dies, and the
    pool's health monitor must notice and fail over."""


def parse_spec(spec: str) -> Tuple[Dict[Tuple[str, int], Tuple[str, Optional[float]]],
                                   List[Tuple[str, float, str, Optional[float]]]]:
    """Parse an ``FF_CHAOS`` spec into (exact, probabilistic) entries.

    Raises ValueError naming the offending entry — a typo'd chaos spec
    silently injecting nothing is worse than no chaos at all.
    """
    exact: Dict[Tuple[str, int], Tuple[str, Optional[float]]] = {}
    prob: List[Tuple[str, float, str, Optional[float]]] = []
    for raw in spec.split(";"):
        entry = raw.strip()
        if not entry:
            continue
        try:
            left, right = entry.split("=", 1)
            site, trigger = left.split(":", 1)
        except ValueError:
            raise ValueError(
                f"FF_CHAOS entry {entry!r}: expected 'site:trigger=fault'")
        site = site.strip()
        if site not in SITES:
            raise ValueError(f"FF_CHAOS entry {entry!r}: unknown site "
                             f"{site!r} (one of {', '.join(SITES)})")
        fault, _, argstr = right.partition(":")
        fault = fault.strip()
        if fault not in FAULTS:
            raise ValueError(f"FF_CHAOS entry {entry!r}: unknown fault "
                             f"{fault!r} (one of {', '.join(FAULTS)})")
        arg: Optional[float] = None
        if argstr:
            try:
                arg = float(argstr)
            except ValueError:
                raise ValueError(f"FF_CHAOS entry {entry!r}: fault arg "
                                 f"{argstr!r} is not a number")
        trigger = trigger.strip()
        if trigger.startswith("p"):
            try:
                p = float(trigger[1:])
            except ValueError:
                raise ValueError(f"FF_CHAOS entry {entry!r}: probability "
                                 f"trigger {trigger!r} is not 'p<float>'")
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"FF_CHAOS entry {entry!r}: probability "
                                 f"{p} outside [0, 1]")
            prob.append((site, p, fault, arg))
        else:
            try:
                t = int(trigger)
            except ValueError:
                raise ValueError(f"FF_CHAOS entry {entry!r}: trigger "
                                 f"{trigger!r} is not an int or 'p<float>'")
            if t < 0:
                raise ValueError(f"FF_CHAOS entry {entry!r}: negative "
                                 f"trigger {t}")
            exact[(site, t)] = (fault, arg)
    if not exact and not prob:
        raise ValueError(f"FF_CHAOS={spec!r}: no entries")
    return exact, prob


def _uniform(seed: int, site: str, idx: int) -> float:
    """Deterministic uniform in [0, 1) keyed by (seed, site, index) —
    the same spec + seed injects the same faults on every run."""
    h = zlib.crc32(f"{seed}:{site}:{idx}".encode())
    return (h % 1_000_000) / 1_000_000.0


class ChaosMonkey:
    """One parsed ``FF_CHAOS`` spec + per-site call counters.

    A model resolves its monkey ONCE at ``compile()`` (``from_env``) and
    every choke point is ``if self._chaos is not None: self._chaos.fire(..)``
    — identical to the telemetry-handle pattern, so the disabled hot
    path pays a single attribute test.
    """

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = int(seed)
        self._exact, self._prob = parse_spec(spec)
        self._counts: Dict[str, int] = {}
        # replica-pool engines fire the shared ``serve`` counter from N
        # loop threads; the lock keeps counts and exact-pops atomic
        # (single-threaded sites pay one uncontended acquire)
        self._lock = threading.Lock()
        self.fired: List[Tuple[str, int, str]] = []  # (site, trigger, fault)
        # resharding-site state, read by the reconfiguration controller
        self.lost_device_count = 0
        # serve-site state, read by the replica pool's monitor: indices
        # into FF_SERVE_ZONES whose replicas went dark all at once
        self.zones_down: List[int] = []
        # persistent per-step wall inflation (``divergence`` fault)
        self.inflate_step_s = 0.0

    def describe(self) -> str:
        parts = [f"{s}:{t}={f}" for (s, t), (f, _) in sorted(self._exact.items())]
        parts += [f"{s}:p{p:g}={f}" for (s, p, f, _) in self._prob]
        return f"{len(parts)} entr{'y' if len(parts) == 1 else 'ies'} " \
               f"({'; '.join(parts)})"

    # -- the choke point ------------------------------------------------
    def fire(self, site: str, index: Optional[int] = None,
             model: Any = None) -> Optional[str]:
        """Called from an instrumented site.  ``index`` is the site's
        own trigger domain (the global step for ``step``); when None the
        per-site call counter supplies it.  Returns the fault name when
        one fired (after executing its side effect), else None."""
        if site == "step" and self.inflate_step_s:
            # a previously fired ``divergence`` fault: every step pays
            # the planted inflation from here on
            time.sleep(self.inflate_step_s)
        with self._lock:
            if index is None:
                idx = self._counts.get(site, 0) + 1
                self._counts[site] = idx
            else:
                idx = int(index)
            hit = self._exact.pop((site, idx), None)
            if hit is None:
                for (s, p, fault, arg) in self._prob:
                    if s == site and _uniform(self.seed, site, idx) < p:
                        hit = (fault, arg)
                        break
            if hit is None:
                return None
            fault, arg = hit
            self.fired.append((site, idx, fault))
        self._emit(model, site, idx, fault)
        self._execute(fault, arg, site, idx, model)
        return fault

    # -- internals ------------------------------------------------------
    def _emit(self, model, site: str, idx: int, fault: str) -> None:
        # Before the side effect (a sigterm may end the process; the
        # sink is line-buffered so the record survives).
        log = getattr(model, "_telemetry", None) if model is not None else None
        if log is None:
            from ..observability import events

            log = events.active_log()
        if log is not None:
            log.event("fault_injected", site=site, trigger=idx, fault=fault)
            log.flush()

    def _execute(self, fault: str, arg: Optional[float], site: str,
                 idx: int, model) -> None:
        where = f"{site}:{idx}"
        if fault == "nan_loss":
            self._poison_batch(model, where)
        elif fault == "hang":
            time.sleep(arg if arg is not None else 3600.0)
        elif fault == "io_error":
            raise ChaosIOError(f"chaos-injected io_error at {where}")
        elif fault == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
        elif fault == "sigint":
            os.kill(os.getpid(), signal.SIGINT)
        elif fault == "error":
            raise ChaosError(f"chaos-injected error at {where}")
        elif fault == "device_loss":
            self.lost_device_count += int(arg) if arg else 1
        elif fault == "device_gain":
            self.lost_device_count = max(
                0, self.lost_device_count - (int(arg) if arg else 1))
        elif fault == "divergence":
            self.inflate_step_s = float(arg) if arg is not None else 0.05
        elif fault == "replica_kill":
            raise ChaosReplicaKill(
                f"chaos-injected replica crash at {where}")
        elif fault == "replica_hang":
            time.sleep(arg if arg is not None else 3600.0)
        elif fault == "zone_outage":
            # recorded state (like device_loss): the pool monitor polls
            # ``zones_down`` and downs every replica of the zone; the
            # admitting request itself proceeds unharmed
            zi = int(arg) if arg is not None else 0
            if zi not in self.zones_down:
                self.zones_down.append(zi)

    @staticmethod
    def _poison_batch(model, where: str) -> None:
        """Multiply every float leaf of the staged batch by NaN so this
        step's loss AND grads go non-finite — exactly the failure the
        NonFiniteGuard must absorb.  Int leaves (labels, indices) stay."""
        batch = getattr(model, "_batch", None)
        if not batch:
            raise ChaosError(
                f"chaos nan_loss at {where}: no staged batch to poison "
                "(inject at a step that follows next_batch)")
        import jax.numpy as jnp

        model._batch = {
            k: (v * jnp.asarray(float("nan"), v.dtype)
                if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating)
                else v)
            for k, v in batch.items()}


def from_env() -> Optional[ChaosMonkey]:
    """The process's chaos config: None when ``FF_CHAOS`` is unset (the
    common case — zero cost), else a fresh monkey.  Each model compile
    gets its own instance so per-site counters are per-run."""
    spec = os.environ.get("FF_CHAOS", "")
    if not spec:
        return None
    seed = int(os.environ.get("FF_CHAOS_SEED", "0") or 0)
    return ChaosMonkey(spec, seed=seed)
