"""Chaos smoke: the acceptance scenarios for the robustness layer, as a CLI.

Three scenarios, selected with ``--scenario``:

``recovery`` (default) — one seeded ``FF_CHAOS`` run injects a NaN step,
a mid-epoch SIGTERM, and a failing checkpoint write; the resumed run
must finish with parameters BITWISE-equal to an uninterrupted baseline,
leave no partial checkpoint file behind, and the trace must narrate
every recovery (``fault_injected`` / ``step_skipped`` /
``preemption_save`` / ``ckpt_retry``).

``reshard`` — a chaos-injected loss of half the device mesh mid-run;
the reconfiguration controller must re-search on the survivors, hot-swap
at a deterministic step boundary, finish training finite on the
degraded mesh, and leave a diffable pair of swap ``.pb`` records behind.
Two independent runs must produce bitwise-identical parameters — the
failover itself is deterministic.

``serve_failover`` — a chaos-injected replica crash (``replica_kill``)
in a 3-replica ``ReplicaPool`` mid-load; every request — including the
ones in flight on the killed replica — must still complete with tokens
BITWISE-equal to one-shot ``FFModel.generate()``, the monitor must
restart the dead replica, the trace must narrate the lifecycle
(``replica_down`` / ``request_failover`` / ``replica_restart``), and
the goodput headline lands in ``BENCH_SERVE.json``.

``zone_outage`` — a chaos-injected loss of a WHOLE ZONE (``zone_outage``
fault) in a 4-replica, 2-zone pool with the autoscaler running; every
request — including the ones in flight in the dead zone — must still
complete with tokens BITWISE-equal to ``FFModel.generate()``, the
re-dispatches must avoid the dead zone (``zone:<z>`` avoid-key), the
autoscaler must backfill the surviving zone back to ``min_replicas``
within its cooldown budget, and the trace must narrate the incident
(``zone_down`` / ``request_failover`` / ``scale_event``).

Run by ``test.sh``; also a handy pod-shell sanity check after touching
the robustness layer.

Usage:
    python -m flexflow_tpu.testing.chaos_smoke --workdir /tmp/chaos
    python -m flexflow_tpu.testing.chaos_smoke --workdir /tmp/rs --scenario reshard
    python -m flexflow_tpu.testing.chaos_smoke --workdir /tmp/sf --scenario serve_failover
    python -m flexflow_tpu.testing.chaos_smoke --workdir /tmp/zo --scenario zone_outage
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import List, Optional

# Both trajectories (baseline AND victim) carry the NaN injection: the
# guard's skip is deterministic, so the runs stay bitwise-comparable —
# only the preemption + checkpoint fault are exclusive to the victim.
NAN_SPEC = "step:2=nan_loss"
VICTIM_SPEC = NAN_SPEC + ";step:4=sigterm;ckpt_save:1=io_error"
EPOCHS = 3


def _build():
    import numpy as np

    import flexflow_tpu as ff

    cfg = ff.FFConfig(batch_size=16)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 8), nchw=False, name="input")
    t = m.dense(inp, 16, activation="relu", name="fc1")
    t = m.dense(t, 4, name="fc2")
    m.softmax(t, name="sm")
    m.compile(ff.AdamOptimizer(alpha=0.01),
              "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=9)
    rng = np.random.default_rng(3)
    x = rng.standard_normal((48, 8), dtype=np.float32)
    y = rng.integers(0, 4, size=(48, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y, seed=5)
    return m, dl


def _phase(env: dict):
    """Reset the telemetry singleton and apply this phase's env."""
    from ..observability import events

    events.reset_active()
    for k in ("FF_CHAOS", "FF_TELEMETRY", "FF_TELEMETRY_FILE",
              "FF_RECONFIGURE", "FF_RECONFIG_BUDGET",
              "FF_RECONFIG_LAG_STEPS"):
        os.environ.pop(k, None)
    os.environ.update(env)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--workdir", required=True,
                   help="scratch dir for checkpoints + traces")
    p.add_argument("--scenario",
                   choices=("recovery", "reshard", "serve_failover",
                            "zone_outage"),
                   default="recovery",
                   help="recovery = NaN/SIGTERM/io_error resume drill; "
                        "reshard = chaos device loss + hot-swap failover; "
                        "serve_failover = replica kill in a serving pool; "
                        "zone_outage = whole-zone loss + autoscaler "
                        "backfill")
    args = p.parse_args(argv)
    os.makedirs(args.workdir, exist_ok=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.scenario == "serve_failover":
        return _scenario_serve_failover(args.workdir)
    if args.scenario == "zone_outage":
        return _scenario_zone_outage(args.workdir)
    if args.scenario == "reshard":
        # the failover drill needs a mesh to shrink — must be set before
        # the first jax import touches the backend
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        return _scenario_reshard(args.workdir)
    return _scenario_recovery(args.workdir)


def _scenario_recovery(wd: str) -> int:
    os.environ["FF_SKIP_NONFINITE"] = "5"
    os.environ["FF_CKPT_BACKOFF_S"] = "0.01"

    import numpy as np

    from ..observability import events
    from ..runtime.elastic import elastic_train
    from ..runtime.resilience import Preempted, read_resume_meta

    trace = os.path.join(wd, "victim_trace.jsonl")

    # -- baseline: uninterrupted, same NaN injection ---------------------
    _phase({"FF_CHAOS": NAN_SPEC})
    mb, dlb = _build()
    elastic_train(mb, dlb, epochs=EPOCHS,
                  checkpoint_dir=os.path.join(wd, "base"))
    base = np.asarray(mb.get_parameter("fc1", "kernel"))
    assert mb._nonfinite_guard.total_skipped == 1, "baseline skip missing"
    print(f"baseline: {mb._step_count} steps, 1 NaN step skipped",
          flush=True)

    # -- victim: + SIGTERM mid-epoch + failing checkpoint write ----------
    _phase({"FF_CHAOS": VICTIM_SPEC, "FF_TELEMETRY": "1",
            "FF_TELEMETRY_FILE": trace})
    ck = os.path.join(wd, "ck")
    mv, dlv = _build()
    try:
        elastic_train(mv, dlv, epochs=EPOCHS, checkpoint_dir=ck)
        raise AssertionError("victim was not preempted")
    except Preempted as e:
        print(f"victim: preempted cleanly at step {e.step}", flush=True)
    meta = read_resume_meta(ck)
    assert meta and meta["step"] == mv._step_count, meta

    # -- resume: chaos off, finish the job -------------------------------
    _phase({})
    mr, dlr = _build()
    elastic_train(mr, dlr, epochs=EPOCHS, checkpoint_dir=ck)
    events.reset_active()
    got = np.asarray(mr.get_parameter("fc1", "kernel"))
    assert mr._step_count == mb._step_count, \
        (mr._step_count, mb._step_count)
    assert (got == base).all(), "resumed params differ from baseline"
    print(f"resume: finished at step {mr._step_count}, params "
          "bitwise-equal to uninterrupted baseline", flush=True)

    # -- no corrupt/partial checkpoint artifacts -------------------------
    stray = glob.glob(os.path.join(wd, "**", "*.tmp-*"), recursive=True)
    assert not stray, f"partial checkpoint files left behind: {stray}"

    # -- the trace narrates every recovery -------------------------------
    names = [json.loads(l).get("name")
             for l in open(trace) if l.strip()]
    for ev in ("fault_injected", "step_skipped", "preemption_save",
               "ckpt_retry"):
        assert ev in names, f"{ev} missing from trace (saw {set(names)})"
    injected = names.count("fault_injected")
    print(f"trace: {injected} faults injected, all recovery events "
          f"present ({trace})", flush=True)
    print("CHAOS SMOKE OK")
    return 0


def _reshard_run(wd: str):
    """One seeded failover run: lose 4 of 8 devices after step 4, let
    the controller re-search and hot-swap.  Returns (model, swap attrs,
    trace path)."""
    import numpy as np

    from ..runtime.elastic import elastic_train

    trace = os.path.join(wd, "trace.jsonl")
    _phase({"FF_CHAOS": "resharding:4=device_loss:4",
            "FF_RECONFIGURE": "1", "FF_RECONFIG_BUDGET": "40",
            "FF_RECONFIG_LAG_STEPS": "2",
            "FF_TELEMETRY": "1", "FF_TELEMETRY_FILE": trace})
    m, dl = _build()
    elastic_train(m, dl, epochs=EPOCHS,
                  checkpoint_dir=os.path.join(wd, "ckpt"))
    assert m.machine.num_devices == 4, \
        f"expected a degraded 4-device mesh, got {m.machine.num_devices}"
    k = np.asarray(m.get_parameter("fc1", "kernel"))
    assert np.isfinite(k).all(), "non-finite params after failover"
    swaps = [json.loads(l)["attrs"] for l in open(trace)
             if l.strip() and '"strategy_swap"' in l
             and json.loads(l).get("name") == "strategy_swap"]
    applied = [s for s in swaps if s.get("outcome") == "applied"]
    assert len(applied) == 1, f"expected exactly one applied swap: {swaps}"
    assert applied[0]["trigger"] == "device_loss", applied[0]
    return m, applied[0], trace


def _scenario_reshard(wd: str) -> int:
    import numpy as np

    from ..observability import events
    from ..tools.search_report import render_diff

    m1, swap, _trace = _reshard_run(os.path.join(wd, "run1"))
    print(f"run1: swap at step {swap['step']} "
          f"({swap['old_devices']} -> {swap['new_devices']} devices)",
          flush=True)

    # the flight recorder left a diffable pair of strategy records
    old_pb, new_pb = swap["old_pb"], swap["new_pb"]
    assert os.path.exists(old_pb) and os.path.exists(new_pb), \
        (old_pb, new_pb)
    diff = render_diff(old_pb, new_pb)
    assert "reconfig-mcmc" in diff, "diff lost the new side's engine"
    print(f"diff: search_report --diff renders {os.path.basename(old_pb)} "
          f"vs {os.path.basename(new_pb)} ({len(diff.splitlines())} lines)",
          flush=True)

    # determinism: an independent run reproduces the failover bitwise
    m2, swap2, _ = _reshard_run(os.path.join(wd, "run2"))
    events.reset_active()
    assert swap2["step"] == swap["step"], (swap2["step"], swap["step"])
    k1 = np.asarray(m1.get_parameter("fc1", "kernel"))
    k2 = np.asarray(m2.get_parameter("fc1", "kernel"))
    assert (k1 == k2).all(), "failover runs are not bitwise-reproducible"
    print(f"run2: swap at step {swap2['step']}, params bitwise-equal "
          "to run1", flush=True)
    print("RESHARD SMOKE OK")
    return 0


def _build_serve_model():
    """Tiny decoder transformer — same shape tests/test_serving.py uses,
    so greedy equivalence is checked against a known-good path."""
    import flexflow_tpu as ff
    from ..models.transformer import build_transformer

    cfg = ff.FFConfig(batch_size=4)
    m = ff.FFModel(cfg)
    build_transformer(m, 4, seq_length=64, num_layers=1,
                      embed_dim=16, num_heads=2, vocab_size=32)
    m.compile(ff.SGDOptimizer(lr=0.1),
              "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=3)
    return m


def _scenario_serve_failover(wd: str) -> int:
    import time

    import numpy as np

    from ..observability import events
    from ..serving import ServeConfig
    from ..serving.pool import ReplicaPool

    NEW = 8        # tokens per request
    N_REQ = 10
    trace = os.path.join(wd, "serve_trace.jsonl")
    # 5th pool-wide admission raises ChaosReplicaKill inside one
    # replica's decode loop: that thread dies with a request mid-admit
    # and (max_batch=2) possibly one more in a live slot
    _phase({"FF_CHAOS": "serve:5=replica_kill", "FF_TELEMETRY": "1",
            "FF_TELEMETRY_FILE": trace})
    m = _build_serve_model()
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 32, size=int(rng.integers(3, 11)))
               .astype(np.int32) for _ in range(N_REQ)]
    # ground truth first: the chaos spec only matches the serve site,
    # so one-shot generate() is uninstrumented (and warms the compiles)
    want = [m.generate(p[None], NEW)[0] for p in prompts]

    cfg = ServeConfig(max_batch=2, max_seq=64, max_new_tokens=NEW,
                      replicas=3, replica_timeout_s=120.0,
                      restart_backoff_s=0.05, restart_cap_s=0.2)
    pool = ReplicaPool(m, config=cfg)
    pool.start()
    t0 = time.perf_counter()
    reqs = [pool.submit(p, NEW) for p in prompts]
    outs = [r.result(180) for r in reqs]
    wall = time.perf_counter() - t0

    # every request — the queued ones AND the in-flight ones on the
    # killed replica — completed bitwise-equal to the single-engine path
    bad = [i for i, (got, w) in enumerate(zip(outs, want))
           if not np.array_equal(np.asarray(got, np.int32), w)]
    assert not bad, f"failover broke greedy equivalence for {bad}"
    st = pool.stats()
    assert st["replica_downs"] >= 1, f"chaos kill never landed: {st}"
    assert st["failovers"] >= 1, \
        f"no in-flight request failed over: {st}"

    # the monitor must bring the dead replica back (backoff is tiny)
    deadline = time.perf_counter() + 30
    while time.perf_counter() < deadline:
        hz = pool.healthz()
        if (pool.stats()["replica_restarts"] >= 1
                and all(r["state"] == "ready" for r in hz["replicas"])):
            break
        time.sleep(0.05)
    hz = pool.healthz()
    assert pool.stats()["replica_restarts"] >= 1, pool.stats()
    assert all(r["state"] == "ready" for r in hz["replicas"]), hz
    st = pool.stats()
    pool.stop()
    events.reset_active()
    print(f"pool: {st['completed']}/{N_REQ} completed bitwise-equal · "
          f"{st['replica_downs']} down, {st['failovers']} failovers, "
          f"{st['replica_restarts']} restarts", flush=True)

    # the trace narrates the whole replica lifecycle
    names = [json.loads(l).get("name") for l in open(trace) if l.strip()]
    for ev in ("replica_down", "request_failover", "replica_restart"):
        assert ev in names, f"{ev} missing from trace (saw {set(names)})"
    print(f"trace: replica lifecycle narrated ({trace})", flush=True)

    # goodput headline, same schema corner loadgen writes
    bench = {"bench": "serve_failover_smoke", "requests": N_REQ,
             "replicas": 3, "n_ok": len(outs), "n_fail": 0,
             "wall_s": round(wall, 3),
             "goodput_rps": round(len(outs) / wall, 3) if wall > 0
             else 0.0,
             "pool": {k: st[k] for k in
                      ("completed", "failovers", "replica_downs",
                       "replica_restarts", "shed", "hedged")}}
    out = os.path.join(wd, "BENCH_SERVE.json")
    with open(out, "w") as f:
        json.dump(bench, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"bench: goodput {bench['goodput_rps']:.2f} req/s -> {out}",
          flush=True)
    print("SERVE FAILOVER SMOKE OK")
    return 0


def _scenario_zone_outage(wd: str) -> int:
    import time

    import numpy as np

    from ..observability import events
    from ..serving import Autoscaler, ScaleConfig, ServeConfig
    from ..serving.pool import ReplicaPool

    NEW = 8
    N_REQ = 12
    trace = os.path.join(wd, "zone_trace.jsonl")
    # 6th pool-wide admission downs zone index 1 ("zone-b"): BOTH of its
    # replicas go dark at once, stranding whatever they hold in flight
    _phase({"FF_CHAOS": "serve:6=zone_outage:1", "FF_TELEMETRY": "1",
            "FF_TELEMETRY_FILE": trace})
    m = _build_serve_model()
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, 32, size=int(rng.integers(3, 11)))
               .astype(np.int32) for _ in range(N_REQ)]
    want = [m.generate(p[None], NEW)[0] for p in prompts]

    cfg = ServeConfig(max_batch=2, max_seq=64, max_new_tokens=NEW,
                      replicas=4, zones=("zone-a", "zone-b"),
                      replica_timeout_s=120.0,
                      restart_backoff_s=0.05, restart_cap_s=0.2)
    scale = ScaleConfig(min_replicas=4, max_replicas=6, interval_s=0.05,
                        streak=2, up_cooldown_s=0.1, down_cooldown_s=30.0)
    pool = ReplicaPool(m, config=cfg)
    pool.start()
    scaler = Autoscaler(pool, scale)
    scaler.start()
    t0 = time.perf_counter()
    reqs = [pool.submit(p, NEW) for p in prompts]
    outs = [r.result(180) for r in reqs]
    wall = time.perf_counter() - t0

    # exactly-once through the outage: every request — the queued ones
    # AND the ones stranded in the dead zone — completed bitwise-equal
    bad = [i for i, (got, w) in enumerate(zip(outs, want))
           if not np.array_equal(np.asarray(got, np.int32), w)]
    assert not bad, f"zone failover broke greedy equivalence for {bad}"
    st = pool.stats()
    assert st["zone_outages"] >= 1, f"chaos zone_outage never landed: {st}"
    assert st["replica_downs"] >= 2, \
        f"a whole zone (2 replicas) should be down: {st}"
    assert st["failovers"] >= 1, f"no stranded request failed over: {st}"
    assert "zone-b" in pool.zones_down(), pool.zones_down()

    # the autoscaler must backfill the surviving zone to min_replicas
    # (the 2 dead replicas stay down — their zone is dark)
    deadline = time.perf_counter() + 30
    while time.perf_counter() < deadline:
        if pool.ready_replicas >= scale.min_replicas:
            break
        time.sleep(0.05)
    hz = pool.healthz()
    assert pool.ready_replicas >= scale.min_replicas, hz
    assert hz["zones"]["zone-b"]["down"], hz["zones"]
    assert hz["zones"]["zone-b"]["ready"] == 0, hz["zones"]
    assert hz["zones"]["zone-a"]["ready"] >= scale.min_replicas, \
        hz["zones"]
    st = pool.stats()
    assert st["replicas_added"] >= 2, st
    sst = scaler.stats()
    scaler.stop()
    pool.stop()
    events.reset_active()
    print(f"pool: {st['completed']}/{N_REQ} completed bitwise-equal · "
          f"zone-b down ({st['replica_downs']} replicas), "
          f"{st['failovers']} failovers, {st['replicas_added']} backfills "
          f"({sst['scale_ups']} scale-ups)", flush=True)

    # the trace narrates the incident end to end
    names = [json.loads(l).get("name") for l in open(trace) if l.strip()]
    for ev in ("zone_down", "request_failover", "scale_event",
               "replica_added"):
        assert ev in names, f"{ev} missing from trace (saw {set(names)})"
    print(f"trace: zone incident narrated ({trace})", flush=True)
    print(f"wall: {wall:.2f}s for {N_REQ} requests through the outage",
          flush=True)
    print("ZONE OUTAGE SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
