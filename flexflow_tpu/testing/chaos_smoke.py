"""Chaos smoke: the acceptance scenario for the recovery layer, as a CLI.

One seeded ``FF_CHAOS`` run injects a NaN step, a mid-epoch SIGTERM, and
a failing checkpoint write; the resumed run must finish with parameters
BITWISE-equal to an uninterrupted baseline, leave no partial checkpoint
file behind, and the trace must narrate every recovery
(``fault_injected`` / ``step_skipped`` / ``preemption_save`` /
``ckpt_retry``).  Run by ``test.sh``; also a handy pod-shell sanity
check after touching the recovery layer.

Usage:
    python -m flexflow_tpu.testing.chaos_smoke --workdir /tmp/chaos
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import List, Optional

# Both trajectories (baseline AND victim) carry the NaN injection: the
# guard's skip is deterministic, so the runs stay bitwise-comparable —
# only the preemption + checkpoint fault are exclusive to the victim.
NAN_SPEC = "step:2=nan_loss"
VICTIM_SPEC = NAN_SPEC + ";step:4=sigterm;ckpt_save:1=io_error"
EPOCHS = 3


def _build():
    import numpy as np

    import flexflow_tpu as ff

    cfg = ff.FFConfig(batch_size=16)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 8), nchw=False, name="input")
    t = m.dense(inp, 16, activation="relu", name="fc1")
    t = m.dense(t, 4, name="fc2")
    m.softmax(t, name="sm")
    m.compile(ff.AdamOptimizer(alpha=0.01),
              "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=9)
    rng = np.random.default_rng(3)
    x = rng.standard_normal((48, 8), dtype=np.float32)
    y = rng.integers(0, 4, size=(48, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y, seed=5)
    return m, dl


def _phase(env: dict):
    """Reset the telemetry singleton and apply this phase's env."""
    from ..observability import events

    events.reset_active()
    for k in ("FF_CHAOS", "FF_TELEMETRY", "FF_TELEMETRY_FILE"):
        os.environ.pop(k, None)
    os.environ.update(env)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--workdir", required=True,
                   help="scratch dir for checkpoints + traces")
    args = p.parse_args(argv)
    os.makedirs(args.workdir, exist_ok=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["FF_SKIP_NONFINITE"] = "5"
    os.environ["FF_CKPT_BACKOFF_S"] = "0.01"

    import numpy as np

    from ..observability import events
    from ..runtime.elastic import elastic_train
    from ..runtime.resilience import Preempted, read_resume_meta

    wd = args.workdir
    trace = os.path.join(wd, "victim_trace.jsonl")

    # -- baseline: uninterrupted, same NaN injection ---------------------
    _phase({"FF_CHAOS": NAN_SPEC})
    mb, dlb = _build()
    elastic_train(mb, dlb, epochs=EPOCHS,
                  checkpoint_dir=os.path.join(wd, "base"))
    base = np.asarray(mb.get_parameter("fc1", "kernel"))
    assert mb._nonfinite_guard.total_skipped == 1, "baseline skip missing"
    print(f"baseline: {mb._step_count} steps, 1 NaN step skipped",
          flush=True)

    # -- victim: + SIGTERM mid-epoch + failing checkpoint write ----------
    _phase({"FF_CHAOS": VICTIM_SPEC, "FF_TELEMETRY": "1",
            "FF_TELEMETRY_FILE": trace})
    ck = os.path.join(wd, "ck")
    mv, dlv = _build()
    try:
        elastic_train(mv, dlv, epochs=EPOCHS, checkpoint_dir=ck)
        raise AssertionError("victim was not preempted")
    except Preempted as e:
        print(f"victim: preempted cleanly at step {e.step}", flush=True)
    meta = read_resume_meta(ck)
    assert meta and meta["step"] == mv._step_count, meta

    # -- resume: chaos off, finish the job -------------------------------
    _phase({})
    mr, dlr = _build()
    elastic_train(mr, dlr, epochs=EPOCHS, checkpoint_dir=ck)
    events.reset_active()
    got = np.asarray(mr.get_parameter("fc1", "kernel"))
    assert mr._step_count == mb._step_count, \
        (mr._step_count, mb._step_count)
    assert (got == base).all(), "resumed params differ from baseline"
    print(f"resume: finished at step {mr._step_count}, params "
          "bitwise-equal to uninterrupted baseline", flush=True)

    # -- no corrupt/partial checkpoint artifacts -------------------------
    stray = glob.glob(os.path.join(wd, "**", "*.tmp-*"), recursive=True)
    assert not stray, f"partial checkpoint files left behind: {stray}"

    # -- the trace narrates every recovery -------------------------------
    names = [json.loads(l).get("name")
             for l in open(trace) if l.strip()]
    for ev in ("fault_injected", "step_skipped", "preemption_save",
               "ckpt_retry"):
        assert ev in names, f"{ev} missing from trace (saw {set(names)})"
    injected = names.count("fault_injected")
    print(f"trace: {injected} faults injected, all recovery events "
          f"present ({trace})", flush=True)
    print("CHAOS SMOKE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
