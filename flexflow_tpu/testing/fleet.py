"""Deterministic fleet-scale incident scenarios (SLO goodput through chaos).

The serving pieces exist in isolation — arrival-trace loadgen, the
metrics plane, the health-checked ``ReplicaPool``, SLO burn rates, and
now the autoscaler.  This module composes them into reproducible
*incidents*: a seeded traffic shape replayed against a LIVE
pool+autoscaler on CPU, scored by what an SRE would score —

  * offered vs attained RPS, where "attained" means the request
    finished, matched ``FFModel.generate()`` bitwise, AND met its
    end-to-end SLO (the goodput-through-the-incident number),
  * shed vs failed split (admission control refusing politely is not
    the same failure as a lost response),
  * the replica-count timeline and, for incident scenarios,
    time-to-recover (zone goes dark -> ready count restored).

Scenarios (all driven by one seed; same seed => same arrivals, same
prompts, same chaos trigger):

  diurnal       sinusoidal rate ramp — the autoscaler should follow
                the wave up and (cooldown permitting) back down
  flash_crowd   steady trickle, then 40% of all traffic lands in a
                ~7% window — shedding + scale-up under burst
  long_tail     lognormal prompt/decode mix — a few huge requests
                head-of-line-block the small ones; hedging territory
  zone_outage   steady load, then chaos kills a whole zone mid-run —
                failover is exactly-once, the autoscaler backfills the
                surviving zone, goodput dips but correctness never does

``run_scenario`` owns its env phase (FF_CHAOS / FF_TELEMETRY*) the way
``chaos_smoke`` phases do, builds a fresh tiny transformer, replays the
trace, and returns the score dict ``tools/fleet_bench.py`` writes to
``BENCH_FLEET.json`` and the perf ledger.
"""

from __future__ import annotations

import dataclasses
import math
import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..serving.autoscaler import ScaleConfig

DEFAULT_SLO_MS = 3000.0
_SAMPLE_IV_S = 0.02       # replica-timeline sampler period


# ----------------------------------------------------------------------
# arrival shapes (deterministic: seeded, or closed-form in i/n)
# ----------------------------------------------------------------------
def _offsets_diurnal(n: int, duration: float, rng: random.Random) \
        -> List[float]:
    """Inverse-CDF sample of rate(t) = 1 + 0.8*sin(2*pi*(t/D - 0.25)):
    a trough at t=0 rising to a peak at D/2 and back — one 'day'."""
    grid = 512
    dens = [1.0 + 0.8 * math.sin(2 * math.pi * (k / grid - 0.25))
            for k in range(grid + 1)]
    cum = [0.0]
    for k in range(grid):
        cum.append(cum[-1] + (dens[k] + dens[k + 1]) / 2.0)
    total = cum[-1]
    out = []
    for i in range(n):
        target = (i + 0.5) / n * total
        k = next(j for j in range(grid + 1) if cum[j] >= target)
        out.append((k / grid) * duration)
    return out


def _offsets_flash(n: int, duration: float, rng: random.Random) \
        -> List[float]:
    """60% trickle over the first 55%, then 40% crammed into [0.55D,
    0.62D] — the flash crowd."""
    n_base = max(1, int(n * 0.6))
    out = sorted(rng.uniform(0.0, 0.55 * duration)
                 for _ in range(n_base))
    out += sorted(rng.uniform(0.55 * duration, 0.62 * duration)
                  for _ in range(n - n_base))
    return out


def _offsets_poisson(n: int, duration: float, rng: random.Random) \
        -> List[float]:
    rate = n / duration
    t, out = 0.0, []
    for _ in range(n):
        t += rng.expovariate(rate)
        out.append(min(t, duration))
    return out


def _offsets_uniform(n: int, duration: float, rng: random.Random) \
        -> List[float]:
    return [duration * (i + 0.5) / n for i in range(n)]


# ----------------------------------------------------------------------
# prompt mixes
# ----------------------------------------------------------------------
def _mix_uniform(n: int, rng: random.Random) -> List[Tuple[int, int]]:
    """(prompt_len, new_tokens) per request."""
    return [(rng.randint(3, 10), 6) for _ in range(n)]


def _mix_long_tail(n: int, rng: random.Random) -> List[Tuple[int, int]]:
    out = []
    for _ in range(n):
        plen = min(24, max(3, int(rng.lognormvariate(1.6, 0.7))))
        new = min(12, max(3, int(rng.lognormvariate(1.7, 0.5))))
        out.append((plen, new))
    return out


@dataclasses.dataclass
class Scenario:
    name: str
    description: str
    duration_s: float
    offsets: Callable[[int, float, random.Random], List[float]]
    mix: Callable[[int, random.Random], List[Tuple[int, int]]]
    replicas: int = 1
    zones: Tuple[str, ...] = ()
    max_queue: int = 0
    scale: Optional[Dict[str, Any]] = None      # ScaleConfig overrides
    # chaos spec as a function of (n, n_warm) — the warmup admissions
    # shift the serve-site trigger index (None: no incident)
    chaos: Optional[Callable[[int, int], str]] = None


def _zone_chaos(n: int, n_warm: int) -> str:
    # outage fires mid-load: at roughly the n/3rd SCORED admission
    # (warmup admissions hit the same chaos site counter, so offset),
    # zone index 1 ("zone-b") goes dark
    return f"serve:{n_warm + max(2, n // 3)}=zone_outage:1"


SCENARIOS: Dict[str, Scenario] = {
    "diurnal": Scenario(
        "diurnal", "sinusoidal rate ramp; scaler follows the wave",
        duration_s=3.0, offsets=_offsets_diurnal, mix=_mix_uniform,
        replicas=1,
        scale=dict(min_replicas=1, max_replicas=3, interval_s=0.05,
                   up_queue=2.0, down_queue=0.25, streak=2,
                   up_cooldown_s=0.2, down_cooldown_s=1.0)),
    "flash_crowd": Scenario(
        "flash_crowd", "steady trickle then a burst; shed + scale up",
        duration_s=3.0, offsets=_offsets_flash, mix=_mix_uniform,
        replicas=1, max_queue=12,
        scale=dict(min_replicas=1, max_replicas=4, interval_s=0.05,
                   up_queue=2.0, down_queue=0.25, streak=2,
                   up_cooldown_s=0.2, down_cooldown_s=2.0)),
    "long_tail": Scenario(
        "long_tail", "lognormal prompt/decode mix; head-of-line blocking",
        duration_s=3.0, offsets=_offsets_poisson, mix=_mix_long_tail,
        replicas=2,
        scale=dict(min_replicas=1, max_replicas=3, interval_s=0.05,
                   up_queue=2.0, down_queue=0.25, streak=2,
                   up_cooldown_s=0.2, down_cooldown_s=2.0)),
    "zone_outage": Scenario(
        "zone_outage", "chaos kills a whole zone mid-run; backfill",
        duration_s=3.0, offsets=_offsets_uniform, mix=_mix_uniform,
        replicas=4, zones=("zone-a", "zone-b"), chaos=_zone_chaos,
        scale=dict(min_replicas=4, max_replicas=6, interval_s=0.05,
                   up_queue=4.0, down_queue=0.25, streak=2,
                   up_cooldown_s=0.1, down_cooldown_s=30.0)),
}


def _build_model():
    from .chaos_smoke import _build_serve_model

    return _build_serve_model()


def run_scenario(name: str, requests: int = 16, seed: int = 0,
                 slo_ms: float = DEFAULT_SLO_MS,
                 telemetry_file: Optional[str] = None) -> Dict[str, Any]:
    """Replay one scenario against a live pool+autoscaler and score it.
    Deterministic traffic under a fixed seed; wall-clock latencies vary
    with the host, which is why the SLO is a knob."""
    import numpy as np

    from ..observability import events
    from ..serving import Autoscaler, ReplicaPool, ServeConfig
    from ..serving.queue import ServeOverload, ServeTimeout

    sc = SCENARIOS[name]
    n = int(requests)
    # str-seeded Random hashes via sha512 — stable across processes
    # (unlike hash(), which is salted)
    rng = random.Random(f"{seed}:{name}")
    offsets = sc.offsets(n, sc.duration_s, rng)
    mix = sc.mix(n, rng)

    # env phase (chaos_smoke._phase semantics, but save/restore so a
    # caller's env survives the scenario)
    saved = {k: os.environ.pop(k) for k in list(os.environ)
             if k.startswith("FF_CHAOS") or k.startswith("FF_TELEMETRY")}
    events.reset_active()
    try:
        if telemetry_file:
            os.environ["FF_TELEMETRY"] = "1"
            os.environ["FF_TELEMETRY_FILE"] = telemetry_file
        cfg = ServeConfig(
            max_batch=2, max_seq=64, max_new_tokens=16,
            replicas=sc.replicas, zones=sc.zones,
            max_queue=sc.max_queue, queue_timeout_s=60.0,
            replica_timeout_s=120.0,
            restart_backoff_s=0.05, restart_cap_s=0.2)
        # warmup plan: one wave per distinct prompt bucket, sized so
        # every replica admits a full batch — drives each engine's
        # per-bucket prefill/step jit compiles BEFORE the scored
        # window (cold-start compile otherwise adds seconds to e2e
        # and swamps the SLO).  Deterministic given the seed.
        buckets = sorted({b for b in (cfg.bucket_for(p) for p, _ in mix)
                          if b is not None})
        warm_plen = {b: max(p for p, _ in mix if cfg.bucket_for(p) == b)
                     for b in buckets}
        warm_new = {b: max(nt for p, nt in mix if cfg.bucket_for(p) == b)
                    for b in buckets}
        n_warm = len(buckets) * sc.replicas * cfg.max_batch
        if sc.chaos is not None:
            os.environ["FF_CHAOS"] = sc.chaos(n, n_warm)
        model = _build_model()
        prng = np.random.default_rng(seed)
        prompts = [prng.integers(0, 32, size=plen) for plen, _ in mix]
        want = [model.generate(p[None], new)[0]
                for p, (_, new) in zip(prompts, mix)]

        scale_cfg = ScaleConfig(**(sc.scale or
                                   dict(min_replicas=1, max_replicas=2)))
        pool = ReplicaPool(model, cfg)
        scaler = Autoscaler(pool, scale_cfg)

        rows: List[Dict[str, Any]] = [dict() for _ in range(n)]
        timeline: List[Tuple[float, int, int]] = []
        incident = {"t_down": None, "ready_before": None,
                    "ready_min": None, "t_recovered": None}
        stop_sampler = threading.Event()
        t0 = time.perf_counter()

        def sample():
            while not stop_sampler.wait(_SAMPLE_IV_S):
                t = time.perf_counter() - t0
                ready = pool.ready_replicas
                total = pool.num_replicas
                if not timeline or timeline[-1][1:] != (ready, total):
                    timeline.append((round(t, 3), ready, total))
                if pool.zones_down() and incident["t_down"] is None:
                    incident["t_down"] = round(t, 3)
                    incident["ready_before"] = timeline[0][1] \
                        if timeline else ready
                    incident["ready_min"] = ready
                elif incident["t_down"] is not None \
                        and incident["t_recovered"] is None:
                    incident["ready_min"] = min(
                        incident["ready_min"], ready)
                    if ready >= incident["ready_before"]:
                        incident["t_recovered"] = round(t, 3)

        def serve_one(i, handle):
            try:
                out = handle.result(timeout=120.0)
                rows[i]["status"] = "done"
                rows[i]["correct"] = bool(np.array_equal(out, want[i]))
                rows[i]["e2e_s"] = handle.t_done - handle.t_submit
            except ServeTimeout:
                rows[i]["status"] = "timeout"
            except Exception as e:  # noqa: BLE001 — scored, not raised
                rows[i]["status"] = "failed"
                rows[i]["error"] = f"{type(e).__name__}: {e}"

        waiters = []
        with pool:
            # warmup waves (unscored, before the scaler and the clock):
            # per bucket, replicas*max_batch requests so every engine
            # compiles that bucket's prefill + window ladder
            for b in buckets:
                wave = [pool.submit(np.zeros(warm_plen[b], np.int32),
                                    warm_new[b])
                        for _ in range(sc.replicas * cfg.max_batch)]
                for h in wave:
                    try:
                        h.result(timeout=120.0)
                    except Exception:   # noqa: BLE001 — best-effort
                        pass
            with scaler:
                sampler = threading.Thread(target=sample, daemon=True)
                sampler.start()
                t0 = time.perf_counter()
                for i, off in enumerate(offsets):
                    dt = t0 + off - time.perf_counter()
                    if dt > 0:
                        time.sleep(dt)
                    try:
                        h = pool.submit(prompts[i], mix[i][1])
                    except ServeOverload:
                        rows[i]["status"] = "shed"
                        continue
                    w = threading.Thread(target=serve_one, args=(i, h),
                                         daemon=True)
                    w.start()
                    waiters.append(w)
                for w in waiters:
                    w.join(180.0)
                wall = time.perf_counter() - t0
                # let the scaler see the quiet tail briefly (scale-down
                # evidence for the diurnal scenario)
                time.sleep(0.3)
                stop_sampler.set()
                sampler.join(2.0)
                scaler_stats = scaler.stats()
                pool_stats = pool.stats()

        n_done = sum(r.get("status") == "done" for r in rows)
        n_good = sum(r.get("status") == "done" and r.get("correct")
                     and r.get("e2e_s", 1e9) * 1000.0 <= slo_ms
                     for r in rows)
        n_incorrect = sum(r.get("status") == "done"
                          and not r.get("correct") for r in rows)
        n_shed = sum(r.get("status") == "shed" for r in rows)
        n_failed = sum(r.get("status") in ("failed", "timeout")
                       for r in rows)
        n_lost = sum("status" not in r for r in rows)
        ttr = None
        if incident["t_down"] is not None \
                and incident["t_recovered"] is not None:
            ttr = round(incident["t_recovered"] - incident["t_down"], 3)
        return dict(
            scenario=name, seed=int(seed), requests=n,
            slo_ms=float(slo_ms), duration_s=round(wall, 3),
            offered_rps=round(n / wall, 3),
            attained_rps=round(n_good / wall, 3),
            goodput_rps=round(n_good / wall, 3),
            slo_attainment=round(n_good / n, 4),
            n_done=n_done, n_good=n_good, n_shed=n_shed,
            n_failed=n_failed, n_incorrect=n_incorrect, n_lost=n_lost,
            time_to_recover_s=ttr,
            incident=incident if incident["t_down"] is not None else None,
            replica_timeline=timeline[:200],
            scale_events=dict(ups=scaler_stats["scale_ups"],
                              downs=scaler_stats["scale_downs"]),
            pool=dict(failovers=pool_stats["failovers"],
                      replica_downs=pool_stats["replica_downs"],
                      replicas_added=pool_stats["replicas_added"],
                      replicas_retired=pool_stats["replicas_retired"],
                      shed=pool_stats["shed"]),
        )
    finally:
        for k in list(os.environ):
            if k.startswith("FF_CHAOS") or k.startswith("FF_TELEMETRY"):
                del os.environ[k]
        os.environ.update(saved)
        events.reset_active()
