"""Test-harness subsystems that ship with the framework.

``chaos`` is the deterministic fault injector (``FF_CHAOS``) that
exercises the recovery layer in ``runtime/resilience.py``; it lives in
the package (not under tests/) because chaos runs are a supported
production debugging mode — the same spec that drives CI drives a
staging pod.
"""

from . import chaos  # noqa: F401
