"""Structured telemetry (events + per-step stats).

The reference ships two observability channels: per-op ``--profiling``
printouts (conv_2d.cu:448-473) and the Legion profiler behind
``-lg:prof``.  This package is the TPU-native third channel the
reference never had: a structured, machine-readable event log of the
RUN itself — step spans, phase spans (compile / data-wait /
metric-drain / checkpoint), throughput and MFU counters, search
progress — written as JSONL so ``tools/trace_report.py`` can fold any
run into a step-time/MFU breakdown after the fact (including a run a
watchdog killed: records are line-buffered to disk as they happen).

One flag lights up the whole stack: ``FF_TELEMETRY=1`` in the
environment or ``FFConfig.telemetry = True``.  Disabled (the default),
the hot path performs ZERO event-log calls — every site guards on a
``None`` handle resolved once at ``compile()``.

``events``    — the env/flag-gated structured event log (spans +
                counters + gauges, thread-safe, JSONL sink).
``stepstats`` — per-step instrumentation: wall time, first-step
                compile time, samples/s/chip, analytic-FLOP MFU,
                estimated collective bytes, device memory stats.
``health``    — ``FF_HEALTH=1`` live monitor on top of the log:
                non-finite loss/grad sampling, straggler detection
                with phase attribution, data-starvation warnings, and
                the ``FF_HEARTBEAT_PATH`` heartbeat file protocol.
``agreement`` — continuous simulator validation: predicted per-op /
                per-step times diffed against measured walls as
                ``sim_prediction`` / ``sim_divergence`` events.
``metrics``   — the LIVE plane: ``FF_METRICS_PORT``-gated in-process
                registry tapping the event log's observer hook into
                counters / gauges / rolling-window percentiles, served
                as Prometheus text at ``/metrics`` (and JSON at
                ``/debug/vars``) by a stdlib HTTP exporter; also
                mounted on the serving API server.
``opprof``    — ``FF_OPPROF``-cadence measured per-op attribution:
                jitted fwd/bwd fragments timed in-process under a
                step budget, emitted as ``op_runtime`` events, folded
                into the agreement table with measured provenance,
                and appended to the calibration corpus
                ``tools/calibrate.py`` refits from.
``chipwatch`` — the opportunistic chip-session layer: subprocess TPU
                probes with capped backoff (a wedged tunnel kills the
                child, never the parent), and first-healthy-window
                conversion into durable measurement artifacts
                (``chip_probe`` / ``chip_window`` /
                ``measurement_progress`` events).
``searchtrace`` — the search flight recorder: per-proposal
                ``search_candidate`` events from the MCMC engines,
                per-op "why this config" summaries (incl. best
                rejected alternative), and the provenance payload a
                strategy-file ``.meta.json`` sidecar carries.  Folded
                by ``tools/search_report.py`` (report + strategy
                ``--diff``).
``reqtrace``  — end-to-end request tracing: a ``TraceContext``
                (trace id, span id, ``FF_TRACE_SAMPLE`` sampling
                decision made once at admission) carried on every
                ``InferenceRequest`` and stamped onto the serve
                records, so one request's queue wait, prefill, decode
                chunks, KV events, and failover/hedge attempts join
                under one id — ``tools/timeline_export.py`` folds them
                into a Perfetto timeline.  Training runs carry a
                run-level trace id on step/compile/reconfig spans.
``slo``       — declarative serving SLOs (TTFT / TPOT / queue wait /
                availability via ``FF_SLO_*``) evaluated as multi-
                window burn rates over the same event tap, exported as
                ``ff_slo_burn_rate{slo,window}`` /
                ``ff_slo_budget_remaining{slo}`` gauges plus an
                hysteresis-guarded ``slo_alert`` event.
"""

from . import (chipwatch, events, health, metrics, opprof, reqtrace,
               searchtrace, slo)
from .events import EventLog, active_log, for_config
from .health import HealthMonitor, read_heartbeat, write_heartbeat
from .metrics import MetricsRegistry
from .reqtrace import TraceContext
from .searchtrace import SearchRecorder
from .slo import BurnRateEvaluator, SLOTarget

__all__ = ["BurnRateEvaluator", "EventLog", "HealthMonitor",
           "MetricsRegistry", "SLOTarget", "SearchRecorder",
           "TraceContext", "active_log", "chipwatch", "events",
           "for_config", "health", "metrics", "opprof", "read_heartbeat",
           "reqtrace", "searchtrace", "slo", "write_heartbeat"]
