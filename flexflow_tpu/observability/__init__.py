"""Structured telemetry (events + per-step stats).

The reference ships two observability channels: per-op ``--profiling``
printouts (conv_2d.cu:448-473) and the Legion profiler behind
``-lg:prof``.  This package is the TPU-native third channel the
reference never had: a structured, machine-readable event log of the
RUN itself — step spans, phase spans (compile / data-wait /
metric-drain / checkpoint), throughput and MFU counters, search
progress — written as JSONL so ``tools/trace_report.py`` can fold any
run into a step-time/MFU breakdown after the fact (including a run a
watchdog killed: records are line-buffered to disk as they happen).

One flag lights up the whole stack: ``FF_TELEMETRY=1`` in the
environment or ``FFConfig.telemetry = True``.  Disabled (the default),
the hot path performs ZERO event-log calls — every site guards on a
``None`` handle resolved once at ``compile()``.

``events``    — the env/flag-gated structured event log (spans +
                counters + gauges, thread-safe, JSONL sink).
``stepstats`` — per-step instrumentation: wall time, first-step
                compile time, samples/s/chip, analytic-FLOP MFU,
                estimated collective bytes, device memory stats.
"""

from . import events
from .events import EventLog, active_log, for_config

__all__ = ["EventLog", "active_log", "events", "for_config"]
