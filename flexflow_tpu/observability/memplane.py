"""Memory & compile plane: XLA executable introspection + retrace
tracking.

Two jobs, both riding an enabled telemetry log:

* **Predicted** (always on with telemetry): ``emit_memory_prediction``
  runs the analytic per-device memory model (``simulator/memory.py``)
  over the model's RESOLVED strategies at compile/recompile and emits
  one ``memory_predicted`` event — peak device, per-term breakdown,
  headroom against the calibrated machine's ``hbm_capacity``.

* **Compiled** (``FF_MEMPLANE=1``): ``MemPlane.wrap`` replaces a
  ``jax.jit`` callable's implicit compile cache with an explicit
  signature-keyed one built on the AOT path
  (``fn.lower(*args).compile()``), so every compile is OWNED: its wall
  is timed, ``compiled.memory_analysis()`` / ``cost_analysis()`` are
  harvested into ``xla_memory`` / ``xla_cost`` events with
  per-executable (``site``) attribution, and a recompile at a site that
  already compiled — a RETRACE, the serving bucket ladder's silent
  failure mode — increments the cumulative ``compile_retraces`` counter
  the ``/metrics`` exporter renders as ``ff_compile_retraces_total``.
  A known signature dispatches straight to the cached executable: the
  steady-state overhead is one dict lookup plus a leaf-shape key build.

  Distinct SITES are distinct executables (train step, eval step, each
  serving bucket, each generate shape class) — a new site compiling is
  expected and counted only in ``compiles``; only a same-site new
  signature is a retrace.

  If the AOT path is unavailable (exotic backend / staged-out
  transform), the wrapper falls back to calling the original jitted
  function — compile events still fire (the first call's wall includes
  the compile) with ``aot=false`` and no XLA analysis, and training is
  never broken by observability.

Disabled is free: ``maybe_plane`` returns None unless ``FF_MEMPLANE``
is set AND a telemetry log exists, and every call site guards on the
established None-handle pattern.
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Any, Dict, Optional

# Events carry at most this many per-op rows — a 1000-op graph must not
# turn one trace line into a megabyte.
MAX_OP_ROWS = 32


def enabled_from_env() -> bool:
    """``FF_MEMPLANE`` truthy (any non-empty value but "0")."""
    return os.environ.get("FF_MEMPLANE", "") not in ("", "0")


def maybe_plane(log) -> Optional["MemPlane"]:
    """Resolve the compile plane at ``compile()``: None unless
    ``FF_MEMPLANE`` is set AND telemetry is on (the events are the whole
    product — without a log there is nothing to attribute into)."""
    if log is None or not enabled_from_env():
        return None
    return MemPlane(log)


def _sig_key(args: tuple) -> tuple:
    """Signature key matching jit's retrace triggers for our call sites:
    pytree structure + per-leaf (shape, dtype) for arrays, type for
    python scalars (jit keys weak-typed scalars by type, not value)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            sig.append((tuple(shape), str(getattr(leaf, "dtype", ""))))
        else:
            sig.append((type(leaf).__name__,))
    return (str(treedef), tuple(sig))


def _fingerprint(site: str, key: tuple) -> str:
    return hashlib.sha1(repr((site, key)).encode()).hexdigest()[:12]


class MemPlane:
    """Per-model (or per-engine) compile observatory.  One instance per
    telemetry log consumer; all wrapped callables share its cumulative
    ``compiles`` / ``retraces`` counters."""

    def __init__(self, log):
        self.log = log
        self.compiles = 0
        self.retraces = 0

    def wrap(self, site: str, fn) -> "_WrappedJit":
        return _WrappedJit(self, site, fn)

    # -- event emission -------------------------------------------------
    def on_compile(self, site: str, key: tuple, wall_s: float,
                   retrace: bool, compiled, aot: bool) -> None:
        self.compiles += 1
        if retrace:
            self.retraces += 1
        fp = _fingerprint(site, key)
        log = self.log
        log.event("compile_done", site=site, fingerprint=fp,
                  wall_s=round(wall_s, 4), retrace=bool(retrace),
                  aot=bool(aot), total_compiles=self.compiles,
                  total_retraces=self.retraces)
        log.counter("compiles", 1, site=site)
        # 0-increments keep the series alive (and scrapeable) from the
        # first compile, so "flat" is observable, not just absent
        log.counter("compile_retraces", 1 if retrace else 0, site=site)
        if compiled is not None:
            try:
                m = compiled.memory_analysis()
                attrs = {}
                for k in ("argument_size_in_bytes", "output_size_in_bytes",
                          "temp_size_in_bytes",
                          "generated_code_size_in_bytes",
                          "alias_size_in_bytes"):
                    v = getattr(m, k, None)
                    if v is not None:
                        attrs[k.replace("_size_in_bytes", "_bytes")] = int(v)
                total = (attrs.get("argument_bytes", 0)
                         + attrs.get("output_bytes", 0)
                         + attrs.get("temp_bytes", 0)
                         - attrs.get("alias_bytes", 0))
                log.event("xla_memory", site=site, fingerprint=fp,
                          total_bytes=int(total), **attrs)
            except Exception as e:  # noqa: BLE001 — introspection is advisory
                log.event("xla_memory_error", site=site, error=repr(e))
            try:
                c = compiled.cost_analysis()
                if isinstance(c, (list, tuple)):  # older jaxlib returns [dict]
                    c = c[0] if c else {}
                log.event("xla_cost", site=site, fingerprint=fp,
                          flops=float(c.get("flops", 0.0)),
                          bytes_accessed=float(c.get("bytes accessed", 0.0)))
            except Exception as e:  # noqa: BLE001
                log.event("xla_cost_error", site=site, error=repr(e))
        log.flush()


class _WrappedJit:
    """Signature-keyed explicit compile cache around one jitted
    callable.  Positional args only — every wrapped call site in
    model.py / serving/engine.py calls positionally."""

    __slots__ = ("plane", "site", "fn", "_compiled")

    def __init__(self, plane: MemPlane, site: str, fn):
        self.plane = plane
        self.site = site
        self.fn = fn
        self._compiled: Dict[tuple, Any] = {}

    def __call__(self, *args):
        key = _sig_key(args)
        call = self._compiled.get(key)
        if call is not None:
            return call(*args)
        retrace = bool(self._compiled)
        t0 = time.perf_counter()
        try:
            compiled = self.fn.lower(*args).compile()
        except Exception:  # noqa: BLE001 — AOT unavailable: jit fallback
            out = self.fn(*args)  # first call pays trace+compile here
            self._compiled[key] = self.fn
            self.plane.on_compile(self.site, key,
                                  time.perf_counter() - t0, retrace,
                                  None, aot=False)
            return out
        wall = time.perf_counter() - t0
        self._compiled[key] = compiled
        self.plane.on_compile(self.site, key, wall, retrace, compiled,
                              aot=True)
        return compiled(*args)


# ---------------------------------------------------------------------------
# predicted-view emission (independent of FF_MEMPLANE: one cheap event
# per compile, the anchor every other view diffs against)
# ---------------------------------------------------------------------------

def emit_memory_prediction(model, log) -> None:
    """Run the analytic memory model over the model's resolved
    strategies and fold one ``memory_predicted`` event into ``log``.
    Advisory: a memory-model failure must never break compile."""
    if log is None:
        return
    try:
        from ..simulator.machine import TPUMachineModel
        from ..simulator.memory import memory_per_device

        nd = model.machine.num_devices if model.machine is not None \
            else model.config.num_devices
        mm = TPUMachineModel.calibrated(num_devices=nd)
        mem = memory_per_device(model, None, machine_model=mm)
        peak = mem["per_device"][mem["peak_device"]]
        ops = sorted(mem["by_op"].items(), key=lambda kv: -kv[1]["bytes"])
        by_op = {name: row["bytes"] for name, row in ops[:MAX_OP_ROWS]}
        if len(ops) > MAX_OP_ROWS:
            by_op["<other>"] = sum(row["bytes"]
                                   for _, row in ops[MAX_OP_ROWS:])
        log.event("memory_predicted",
                  num_devices=mem["num_devices"],
                  peak_bytes=mem["peak_bytes"],
                  peak_device=mem["peak_device"],
                  dominant_term=mem["dominant_term"],
                  terms={k: peak[k] for k in
                         ("params", "grads", "optimizer", "activations",
                          "staging")},
                  capacity_bytes=mem.get("capacity_bytes"),
                  headroom_bytes=mem.get("headroom_bytes"),
                  opt_slots=mem["opt_slots"],
                  by_op=by_op)
    except Exception as e:  # noqa: BLE001 — prediction is advisory
        log.event("memory_predicted_error", error=repr(e))
