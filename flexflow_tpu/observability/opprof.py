"""Measured per-op runtime attribution on a training cadence.

``runtime/profiling.op_profile`` measures every op once, standalone, on
demand; the agreement loop (``agreement.py``) otherwise validates the
simulator only at *step* granularity.  This module is the cadence
version: every ``FF_OPPROF`` steps it times a slice of the model's ops
as jitted forward / value_and_grad fragments (the ``tools/opbench.py``
harness, reused in-process) under a wall-clock budget, and

  * emits an ``op_runtime`` event per measured fragment — measured vs
    the non-measuring cost model's prediction, with both sides'
    provenance (``src``: measured-cache hit or analytic roofline;
    ``measured_src``: "opprof"),
  * emits the matching per-op ``sim_divergence`` rows so
    ``health_report`` folds in-training measurements into the same
    agreement table as standalone profiles,
  * appends each measured cost to the ``measured_v5e.json``-style
    corpus (``FF_OPPROF_CORPUS``; entries are tagged with the platform
    they were measured on, so CPU fragments can never masquerade as
    chip timings) — the corpus ``tools/calibrate.py --fit-only``
    refits machine constants from.

Knobs (all parsed loudly — a typo'd cadence must not silently disable
attribution):

  FF_OPPROF           cadence in steps (int >= 1); unset = disabled
  FF_OPPROF_BUDGET_S  wall budget per pass, default 2.0 s; the pass
                      round-robins across ops and stops mid-list when
                      the budget is spent, resuming there next time
  FF_OPPROF_CORPUS    measured-corpus path (default: the committed
                      ``simulator/measured_v5e.json`` cache)

Disabled, this module costs nothing: ``maybe_profiler`` returns None
and the per-step hook is one ``is not None`` test (the established
None-handle pattern).  Step 0 is never measured (it contains the jit
trace + XLA compile of the training step itself).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

DEFAULT_BUDGET_S = 2.0


def cadence_from_env() -> Optional[int]:
    """``FF_OPPROF`` as a step cadence, None when unset/empty."""
    raw = os.environ.get("FF_OPPROF", "")
    if raw == "":
        return None
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"FF_OPPROF={raw!r} is not an integer step cadence") from None
    if n < 1:
        raise ValueError(f"FF_OPPROF={n} must be >= 1")
    return n


def budget_from_env() -> float:
    raw = os.environ.get("FF_OPPROF_BUDGET_S", "")
    if raw == "":
        return DEFAULT_BUDGET_S
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(
            f"FF_OPPROF_BUDGET_S={raw!r} is not a number") from None
    if v <= 0:
        raise ValueError(f"FF_OPPROF_BUDGET_S={v} must be > 0")
    return v


def corpus_path_from_env() -> str:
    path = os.environ.get("FF_OPPROF_CORPUS", "")
    if path:
        return path
    from ..simulator.cost_model import MEASURED_CACHE

    return MEASURED_CACHE


def maybe_profiler(model, log) -> Optional["OpProfiler"]:
    """Resolve the per-model profiler at ``compile()``: None unless
    ``FF_OPPROF`` is set AND telemetry is on (the events are the whole
    product — without a log there is nothing to attribute into)."""
    cadence = cadence_from_env()
    if cadence is None or log is None:
        return None
    return OpProfiler(model, log, cadence=cadence,
                      budget_s=budget_from_env(),
                      corpus_path=corpus_path_from_env())


class OpProfiler:
    """Round-robin per-op fragment timer driven by ``StepStats``.

    Fragments are built and jitted once per op (the compile is paid
    inside the first pass's budget); later passes re-time the cached
    callables.  A fragment that fails to build is skipped permanently —
    one broken op must not starve the rest of the list.
    """

    def __init__(self, model, log, cadence: int,
                 budget_s: float = DEFAULT_BUDGET_S,
                 corpus_path: Optional[str] = None,
                 target_platform: Optional[str] = None,
                 iters: int = 5):
        self.model = model
        self.log = log
        self.cadence = int(cadence)
        self.budget_s = float(budget_s)
        self.iters = int(iters)
        self._rr = 0                       # round-robin cursor into ops
        self._frags: Dict[str, Any] = {}   # op.name -> (fwd, bwd, params, xs)
        self._broken: set = set()
        self._predicted: Optional[Dict[str, Dict[str, Any]]] = None
        self._corpus_cm = None
        self._corpus_path = corpus_path
        self._target_platform = target_platform
        self.passes = 0
        self.measured_total = 0

    # -- predictions / corpus (lazy: heavy imports stay off compile) ----
    def _predictions(self) -> Dict[str, Dict[str, Any]]:
        if self._predicted is None:
            from . import agreement

            try:
                self._predicted = agreement.predict_op_times(self.model)
            except Exception:
                self._predicted = {}
        return self._predicted

    def _corpus(self):
        """A NON-measuring CostModel used purely for its canonical
        ``_key`` and atomic ``_persist`` — entries land in the same
        schema calibrate reads, tagged with the platform the fragment
        actually ran on."""
        if self._corpus_cm is None:
            import jax

            from ..simulator.cost_model import CostModel
            from ..simulator.machine import TPUMachineModel

            nd = self.model.machine.num_devices if self.model.machine else 1
            self._corpus_cm = CostModel(
                TPUMachineModel.calibrated(num_devices=nd),
                measure=False, cache_path=self._corpus_path or "",
                compute_dtype=self.model.config.compute_dtype,
                target_platform=(self._target_platform
                                 or jax.default_backend()))
        return self._corpus_cm

    # -- fragment construction ------------------------------------------
    def _fragment(self, op):
        """(fwd_jit, vag_jit, params, xs) for the op's per-part
        sub-shape — the same shape logic as the measuring cost model
        (per-shard inputs AND weights), timed with the opbench loop."""
        cached = self._frags.get(op.name)
        if cached is not None:
            return cached
        import jax
        import jax.numpy as jnp

        from ..ops.base import FwdCtx

        pc = op.pc
        cdt = (jnp.bfloat16 if "16" in self.model.config.compute_dtype
               else jnp.float32)
        key = jax.random.key(0)
        xs = []
        for j, t in enumerate(op.inputs):
            sub = tuple(hi - lo + 1 for lo, hi in op.input_ranges(j, pc, 0))
            if "int" in t.dtype:
                xs.append(jnp.zeros(sub, jnp.int32))
            else:
                key, k = jax.random.split(key)
                xs.append(jax.random.normal(k, sub, cdt))
        owner = op.share_from if getattr(op, "share_from", None) else op
        params = {}
        for wi, w in enumerate(owner.weights):
            tile = op.weight_tile(pc, wi, 0)
            shape = tuple(hi - lo + 1 for lo, hi in tile) if tile else w.dims
            key, k = jax.random.split(key)
            params[w.name] = 0.02 * jax.random.normal(k, shape, cdt)
        stats = op.init_stats()
        ctx = FwdCtx(training=False, rng=key,
                     stats_in={op.name: stats} if stats else {})

        def fwd(params, xs):
            return op.forward(params, list(xs), ctx)[0]

        def loss(params, xs):
            return jnp.sum(fwd(params, xs).astype(jnp.float32))

        frag = (jax.jit(fwd), jax.jit(jax.value_and_grad(loss)),
                params, xs)
        self._frags[op.name] = frag
        return frag

    # -- the cadence hook (called by StepStats.timed_update) ------------
    def on_step(self, step_idx: int) -> None:
        if step_idx == 0 or step_idx % self.cadence != 0:
            return
        try:
            self._run_pass(step_idx)
        except Exception as e:  # noqa: BLE001 — attribution is advisory
            self.log.event("op_runtime_error", error=repr(e),
                           step=int(step_idx))

    def _run_pass(self, step_idx: int) -> None:
        from ..tools.opbench import time_jitted

        ops = [op for op in self.model.ops
               if getattr(op, "pc", None) is not None
               and not op.pc.host_placed]
        if not ops:
            return
        predicted = self._predictions()
        cm = self._corpus()
        t_start = time.perf_counter()
        measured = 0
        for i in range(len(ops)):
            if time.perf_counter() - t_start >= self.budget_s:
                break
            op = ops[(self._rr + i) % len(ops)]
            if op.name in self._broken:
                continue
            try:
                fwd, vag, params, xs = self._fragment(op)
            except Exception:
                self._broken.add(op.name)
                continue
            pred = predicted.get(op.name, {})
            for which, fn in (("forward", fwd), ("backward", vag)):
                try:
                    t = time_jitted(fn, params, xs, iters=self.iters)
                except Exception:
                    self._broken.add(op.name)
                    break
                meas_ms = t * 1e3
                pred_ms = float(pred.get(f"{which}_ms", 0.0))
                src = pred.get(f"{which}_src", "analytic")
                self.log.event(
                    "op_runtime", op=op.name, which=which,
                    measured_ms=round(meas_ms, 4),
                    predicted_ms=round(pred_ms, 4),
                    ratio=round(pred_ms / meas_ms, 4) if meas_ms > 0
                    else 0.0,
                    src=src, step=int(step_idx))
                from . import agreement

                agreement.emit_op_divergence(
                    self.log, op.name, which, pred_ms, meas_ms,
                    src=src, measured_src="opprof")
                cm._persist(cm._key(op, op.pc, which), float(t))
            else:
                measured += 1
        self._rr = (self._rr + max(1, measured)) % len(ops)
        self.passes += 1
        self.measured_total += measured
        self.log.event("op_runtime_pass", step=int(step_idx),
                       ops_measured=int(measured), ops_total=len(ops),
                       elapsed_s=round(time.perf_counter() - t_start, 4))
        self.log.flush()
