"""Search flight recorder: candidate-level tracing of the strategy search.

The simulator+MCMC search is the paper's core mechanism (Jia et al.,
"Beyond Data and Model Parallelism"), yet a strategy file tells you
nothing about HOW it was found.  This module records the search itself
through the structured event log (``events.py``):

  ``search_start``       engine, budget, devices, seed, initial cost
  ``search_candidate``   one per proposal: mutated op, old/new config,
                         simulated cost + delta, accept/reject with the
                         reason ("downhill" vs "metropolis", including
                         the Metropolis acceptance probability), and the
                         best-so-far
  ``search_op_summary``  one per op at the end: final config, proposal/
                         accept counts, cumulative improvement won by
                         mutating this op, and the BEST REJECTED
                         ALTERNATIVE — the cheapest proposal that lost,
                         which is what lets ``tools/search_report.py``
                         answer "why THIS config and not that one?"
  ``search_summary``     totals: proposals, accepted, initial→best cost,
                         iteration of the last improvement

Engines: ``mcmc`` (simulator/search.py) records every proposal;
``native`` (the C++ anneal owns its loop) records start/op-summary/
summary only; ``pipeline`` (simulator/pipeline_search.py) records each
(S, dp, M, remat) grid point as a candidate with op ``<pipeline>``.

ZERO COST WHEN DISABLED: ``SearchRecorder.maybe()`` returns ``None``
unless a telemetry log is active, and every call site guards on that —
a search without ``FF_TELEMETRY`` makes no event-log calls at all
(asserted by tests/test_search_report.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .events import EventLog, active_log


def pc_str(pc) -> str:
    """Compact one-token ParallelConfig rendering for event attrs and
    report tables: partition degrees joined by 'x', host placement and
    a non-zero device offset marked explicitly ("4x1x2x1", "host[1x1]",
    "2x1@4")."""
    if pc is None:
        return "?"
    dims = "x".join(str(d) for d in pc.dims)
    if getattr(pc, "host_placed", False):
        return f"host[{dims}]"
    ids = pc.device_ids[:pc.num_parts()]
    if ids and ids[0] != 0:
        return f"{dims}@{ids[0]}"
    return dims


def _r3(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(float(v), 3)


class SearchRecorder:
    """Per-search event emitter + per-op accounting.

    Costs are milliseconds of SIMULATED step time (the search
    objective); ``gain_ms`` is the cumulative step-time reduction from
    accepted proposals that mutated an op — the attribution the
    "most-improved ops" report section ranks by.
    """

    def __init__(self, log: EventLog, engine: str, budget: int,
                 num_devices: int, seed: int = 0):
        self.log = log
        self.engine = engine
        self.budget = budget
        self.num_devices = num_devices
        self.seed = seed
        self._ops: Dict[str, Dict[str, Any]] = {}
        self._proposals = 0
        self._accepted = 0
        self._initial_ms: Optional[float] = None
        self._best_ms: Optional[float] = None
        self._last_improve: Optional[int] = None

    @classmethod
    def maybe(cls, engine: str, budget: int, num_devices: int,
              seed: int = 0,
              log: Optional[EventLog] = None) -> Optional["SearchRecorder"]:
        """The recorder, or None when telemetry is off (the one branch
        every engine guards on — disabled searches make zero log calls)."""
        log = log if log is not None else active_log()
        if log is None:
            return None
        return cls(log, engine, budget, num_devices, seed)

    # -- lifecycle ------------------------------------------------------
    def start(self, initial_ms: Optional[float] = None,
              candidates: Optional[int] = None) -> None:
        self._initial_ms = initial_ms
        self._best_ms = initial_ms
        attrs: Dict[str, Any] = {"engine": self.engine,
                                 "budget": self.budget,
                                 "num_devices": self.num_devices,
                                 "seed": self.seed}
        if initial_ms is not None:
            attrs["initial_ms"] = _r3(initial_ms)
        if candidates is not None:
            attrs["candidates"] = int(candidates)
        self.log.event("search_start", **attrs)

    def _op(self, name: str) -> Dict[str, Any]:
        st = self._ops.get(name)
        if st is None:
            st = self._ops[name] = {"proposals": 0, "accepted": 0,
                                    "gain_ms": 0.0, "alt": None,
                                    "alt_ms": None}
        return st

    def candidate(self, it: int, op_name: str, old_pc, new_pc,
                  cur_ms: float, new_ms: float, best_ms: float,
                  accepted: bool, reason: str,
                  prob: Optional[float] = None,
                  **extra: Any) -> None:
        """One MCMC proposal.  ``reason``: "downhill" (new < current) or
        "metropolis" (uphill — accepted with probability ``prob``).
        ``best_ms`` is the best-so-far AFTER this proposal.  ``extra``
        attrs ride along verbatim (the population engine tags each
        proposal with its ``chain``)."""
        self._proposals += 1
        st = self._op(op_name)
        st["proposals"] += 1
        if accepted:
            self._accepted += 1
            st["accepted"] += 1
            st["gain_ms"] += cur_ms - new_ms
        elif st["alt_ms"] is None or new_ms < st["alt_ms"]:
            st["alt"] = pc_str(new_pc)
            st["alt_ms"] = new_ms
        if self._best_ms is None or best_ms < self._best_ms:
            self._best_ms = best_ms
            self._last_improve = it
        attrs = {"engine": self.engine, "iter": int(it), "op": op_name,
                 "old": pc_str(old_pc), "new": pc_str(new_pc),
                 "cur_ms": _r3(cur_ms), "new_ms": _r3(new_ms),
                 "delta_ms": _r3(new_ms - cur_ms), "best_ms": _r3(best_ms),
                 "accepted": bool(accepted), "reason": reason}
        if prob is not None:
            attrs["prob"] = round(float(prob), 6)
        attrs.update(extra)
        self.log.event("search_candidate", **attrs)

    # -- population-engine events ---------------------------------------
    def exchange(self, it: int, pair: tuple, low_ms: float, high_ms: float,
                 accepted: bool, prob: Optional[float] = None) -> None:
        """One replica-exchange attempt between the adjacent-temperature
        chains ``pair`` (colder chain first); ``low_ms``/``high_ms`` are
        their current simulated costs before the swap."""
        attrs = {"engine": self.engine, "iter": int(it),
                 "chain_a": int(pair[0]), "chain_b": int(pair[1]),
                 "a_ms": _r3(low_ms), "b_ms": _r3(high_ms),
                 "accepted": bool(accepted)}
        if prob is not None:
            attrs["prob"] = round(float(prob), 6)
        self.log.event("search_exchange", **attrs)

    def crossover(self, it: int, parents: tuple, child_chain: int,
                  patches: int, child_ms: Optional[float],
                  adopted: bool) -> None:
        """One genetic-crossover attempt: the elite ``parents`` spliced
        into a child costed on ``child_chain`` via ``patches`` delta
        patches; ``adopted`` marks whether the child replaced that
        chain's state (the lineage the report reconstructs)."""
        self.log.event("search_crossover", engine=self.engine,
                       iter=int(it), parent_a=int(parents[0]),
                       parent_b=int(parents[1]), chain=int(child_chain),
                       patches=int(patches), child_ms=_r3(child_ms),
                       adopted=bool(adopted))

    def elite(self, it: int, ranking: list) -> None:
        """Current population ranking at a crossover point:
        ``ranking`` = [(chain, cur_ms)] best first."""
        self.log.event("search_elite", engine=self.engine, iter=int(it),
                       chains=[int(c) for c, _ in ranking],
                       cur_ms=[_r3(m) for _, m in ranking])

    def plan(self, desc: str, cost_ms: float, accepted: bool,
             **attrs: Any) -> None:
        """One pipeline-grid plan, rendered as a candidate on the
        synthetic op ``<pipeline>`` (``desc`` e.g. "S4xdp2,M8,remat");
        ``accepted`` marks a new grid best."""
        self._proposals += 1
        if accepted:
            self._accepted += 1
            if self._best_ms is None or cost_ms < self._best_ms:
                self._best_ms = cost_ms
                self._last_improve = self._proposals - 1
        self.log.event("search_candidate", engine=self.engine,
                       iter=self._proposals - 1, op="<pipeline>",
                       new=desc, new_ms=_r3(cost_ms),
                       best_ms=_r3(self._best_ms),
                       accepted=bool(accepted), reason="grid", **attrs)

    def finish(self, best: Optional[Dict[str, Any]] = None,
               best_ms: Optional[float] = None,
               initial_ms: Optional[float] = None,
               proposals_per_s: Optional[float] = None,
               delta: Optional[bool] = None) -> None:
        """Emit the per-op summaries (one per op in the FINAL strategy,
        including ops the proposal stream never touched — the report's
        "why" table must cover every op) and the run summary.
        ``proposals_per_s``/``delta`` record search throughput and
        whether the incremental (delta) simulator was active at the end
        of the run — the numbers behind the ``search_throughput`` perf-
        ledger metric."""
        if initial_ms is not None:
            self._initial_ms = initial_ms
        if best_ms is not None:
            self._best_ms = best_ms
        names = list(best.keys()) if best else list(self._ops.keys())
        for name in names:
            st = self._ops.get(name) or {"proposals": 0, "accepted": 0,
                                         "gain_ms": 0.0, "alt": None,
                                         "alt_ms": None}
            attrs = {"engine": self.engine, "op": name,
                     "proposals": st["proposals"],
                     "accepted": st["accepted"],
                     "gain_ms": _r3(st["gain_ms"])}
            if best is not None:
                attrs["final"] = pc_str(best.get(name))
            if st["alt"] is not None:
                attrs["alt"] = st["alt"]
                attrs["alt_ms"] = _r3(st["alt_ms"])
                if self._best_ms is not None:
                    attrs["alt_delta_ms"] = _r3(st["alt_ms"] - self._best_ms)
            self.log.event("search_op_summary", **attrs)
        attrs = {"engine": self.engine, "budget": self.budget,
                 "num_devices": self.num_devices, "seed": self.seed,
                 "proposals": self._proposals, "accepted": self._accepted,
                 "num_ops": len(names)}
        if self._initial_ms is not None:
            attrs["initial_ms"] = _r3(self._initial_ms)
        if self._best_ms is not None:
            attrs["best_ms"] = _r3(self._best_ms)
        if self._last_improve is not None:
            attrs["last_improve_iter"] = int(self._last_improve)
        if proposals_per_s is not None:
            attrs["proposals_per_s"] = round(proposals_per_s, 1)
        if delta is not None:
            attrs["delta"] = bool(delta)
        self.log.event("search_summary", **attrs)


# ----------------------------------------------------------------------
# provenance helpers (used by the sidecar stampers, not the hot path)
# ----------------------------------------------------------------------

def per_op_attribution(model, strategies,
                       machine_model=None,
                       compute_dtype: Optional[str] = None
                       ) -> Dict[str, Dict[str, Any]]:
    """Per-op cost attribution for a strategy map: ``{op: {dims, parts,
    host, spec, fwd_ms, bwd_ms}}`` priced by the non-measuring cost
    model — the rows a ``.pb.meta.json`` sidecar carries so
    ``search_report --diff`` can name the simulated cost impact (and
    the resolved sharding-spec change) of each changed op."""
    from ..config import ParallelConfig
    from ..parallel import lowering as _lowering
    from ..simulator.cost_model import CostModel
    from ..simulator.machine import TPUMachineModel

    nd = model.machine.num_devices if getattr(model, "machine", None) \
        is not None else model.config.num_devices
    mm = machine_model or TPUMachineModel.calibrated(num_devices=nd)
    cm = CostModel(mm, measure=False,
                   compute_dtype=compute_dtype or model.config.compute_dtype)
    # Pure shadow of the mesh the lowering pass would target for this
    # device count: spec strings are derivable offline, so sidecars
    # written by search tools carry them even when no model compiled.
    names, sizes = _lowering.hybrid_axis_layout(
        nd, mm.num_hosts if nd % mm.chips_per_host == 0 else 1)
    rows: Dict[str, Dict[str, Any]] = {}
    for op in model.ops:
        pc = strategies.get(op.name) or getattr(op, "pc", None) \
            or ParallelConfig.data_parallel(op.output.num_dims, nd)
        pc = model._legalize_pc(op, pc) if hasattr(model, "_legalize_pc") \
            else pc
        try:
            groups, _ = _lowering.assign_axes(
                names, sizes, pc.dims,
                _lowering.dim_roles(op, len(pc.dims)))
            spec = _lowering.spec_string(groups)
        except ValueError:
            spec = "?"  # degree the mesh cannot express; advisory only
        rows[op.name] = {
            "dims": "x".join(str(d) for d in pc.dims),
            "parts": pc.num_parts(),
            "host": bool(getattr(pc, "host_placed", False)),
            "spec": spec,
            "fwd_ms": round(cm.op_time(op, pc, "forward") * 1e3, 4),
            "bwd_ms": round(cm.op_time(op, pc, "backward") * 1e3, 4),
        }
    return rows


def build_provenance(model, strategies, engine: str, budget: int,
                     seed: int, best_s: Optional[float] = None,
                     dp_s: Optional[float] = None,
                     machine_model=None,
                     extra: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """The provenance dict a strategy sidecar records (content hash and
    timestamps are stamped by ``parallel.strategy.write_provenance``).
    When a telemetry log is active its run id is included, so a training
    trace that loads this strategy links back to the search trace that
    produced it."""
    nd = model.machine.num_devices if getattr(model, "machine", None) \
        is not None else model.config.num_devices
    meta: Dict[str, Any] = {
        "engine": engine,
        "budget": int(budget),
        "seed": int(seed),
        "num_devices": int(nd),
        "batch_size": int(model.config.batch_size),
        "compute_dtype": model.config.compute_dtype,
    }
    if best_s is not None:
        meta["best_ms"] = round(float(best_s) * 1e3, 4)
    if dp_s is not None:
        meta["dp_ms"] = round(float(dp_s) * 1e3, 4)
    # Whole-graph lowering stamp: was this strategy compiled into ONE
    # pjit'd step (parallel/lowering.py), and what did each op's spec
    # resolve to (including any dcn spill the search failed to avoid)?
    low = getattr(model, "_lowering", None)
    meta["lowered"] = low is not None
    if low is not None:
        try:
            meta["lowering"] = low.plan()
        except Exception as e:  # advisory; never block export
            meta["lowering_error"] = repr(e)
    log = active_log()
    if log is not None:
        meta["search_run_id"] = log.run_id
    try:
        meta["ops"] = per_op_attribution(model, strategies,
                                         machine_model=machine_model)
    except Exception as e:  # attribution is advisory; never block export
        meta["ops_error"] = repr(e)
    try:
        # Predicted per-device HBM under this strategy map — the search
        # platform's multi-objective input (ROADMAP item 3) and what
        # tools/memory_report.py diffs against XLA's memory_analysis.
        from ..simulator.machine import TPUMachineModel
        from ..simulator.memory import memory_per_device

        mm = machine_model or TPUMachineModel.calibrated(num_devices=nd)
        mem = memory_per_device(model, strategies, machine_model=mm)
        meta["hbm_per_device_bytes"] = [row["total"]
                                        for row in mem["per_device"]]
        meta["hbm_peak_bytes"] = mem["peak_bytes"]
        meta["hbm_dominant_term"] = mem["dominant_term"]
        if "capacity_bytes" in mem:
            meta["hbm_capacity_bytes"] = mem["capacity_bytes"]
    except Exception as e:  # advisory; never block export
        meta["hbm_error"] = repr(e)
    if extra:
        meta.update(extra)
    return meta
