"""Simulator-agreement attribution: predicted vs measured times.

FlexFlow's execution simulator is only trustworthy because its inputs
are measured on real hardware (Jia et al., simulator.cc:275-448); this
module closes that loop continuously by diffing the cost model's
predictions against the walls the telemetry log actually records:

  * at ``compile()`` a ``sim_prediction`` event carries the simulator's
    predicted step time for the resolved strategies,
  * the health monitor refreshes a step-level ``sim_divergence`` event
    (predicted vs measured p50) once per sampling window,
  * ``runtime/profiling.op_profile`` emits per-op ``sim_divergence``
    events: the NON-measuring cost model's price (measured cache hit or
    analytic roofline — tagged by ``src``) vs the freshly measured
    standalone wall.

``tools/health_report.py`` folds these into the predicted-vs-measured
agreement table that slots into CALIBRATION.md's multi-point
validation.  Heavy imports stay inside functions: this module is only
reached from post-compile paths, but importing it must stay cheap for
the stdlib-only health monitor.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


def _cost_model(model, measure: bool = False):
    from ..simulator.cost_model import CostModel
    from ..simulator.machine import TPUMachineModel

    machine = TPUMachineModel.calibrated(
        num_devices=model.machine.num_devices if model.machine else 1)
    return machine, CostModel(machine, measure=measure,
                              compute_dtype=model.config.compute_dtype)


def predict_op_times(model) -> Dict[str, Dict[str, Any]]:
    """The simulator's a-priori per-op price under each op's resolved
    strategy: ``{op: {forward_ms, forward_src, backward_ms,
    backward_src}}`` where src is "measured" (durable cache hit) or
    "analytic" (roofline fallback)."""
    _, cm = _cost_model(model, measure=False)
    out: Dict[str, Dict[str, Any]] = {}
    for op in model.ops:
        pc = getattr(op, "pc", None)
        entry: Dict[str, Any] = {}
        for which in ("forward", "backward"):
            t = cm.op_time(op, pc, which)
            entry[f"{which}_ms"] = t * 1e3
            entry[f"{which}_src"] = (
                "measured" if cm._key(op, pc, which) in cm._measured
                else "analytic")
        out[op.name] = entry
    return out


def predicted_step_seconds(model) -> float:
    """Simulated seconds/iteration for the model's resolved strategies
    (the number the strategy search optimized)."""
    from ..simulator.simulator import Simulator

    machine, cm = _cost_model(model, measure=False)
    strategies = {op.name: op.pc for op in model.ops
                  if getattr(op, "pc", None) is not None}
    return Simulator(machine, cm).simulate_runtime(model, strategies)


def emit_compile_prediction(model, log) -> Optional[float]:
    """Post-compile hook: record the simulator's step prediction and
    stash it on the model for later step-level divergence.  Never lets
    a simulator failure break compile."""
    try:
        pred = predicted_step_seconds(model)
    except Exception as e:  # prediction is advisory, training is not
        log.event("sim_prediction_error", error=repr(e))
        return None
    model._predicted_step_s = pred
    log.event("sim_prediction",
              predicted_step_ms=round(pred * 1e3, 4),
              num_devices=model.machine.num_devices if model.machine else 1,
              batch_size=model.config.batch_size,
              compute_dtype=model.config.compute_dtype)
    return pred


def emit_step_divergence(model, log, measured_p50_s: float,
                         n_steps: int) -> None:
    """Step-level agreement: compile-time prediction vs the measured
    steady-state p50 (the last record per trace wins in the report)."""
    pred = getattr(model, "_predicted_step_s", None)
    if pred is None or measured_p50_s <= 0:
        return
    log.event("sim_divergence", scope="step",
              predicted_ms=round(pred * 1e3, 4),
              measured_ms=round(measured_p50_s * 1e3, 4),
              ratio=round(pred / measured_p50_s, 4),
              n_steps=int(n_steps))


def emit_op_divergence(log, op_name: str, which: str, predicted_ms: float,
                       measured_ms: float, src: str = "analytic",
                       measured_src: str = "standalone") -> None:
    """Per-op agreement row (emitted by ``op_profile`` next to each
    measured wall, and by ``opprof`` on its in-training cadence).

    Both sides carry provenance: ``src`` names where the PREDICTION came
    from ("measured" cache hit vs "analytic" roofline), ``measured_src``
    names where the MEASUREMENT came from ("standalone" one-shot profile
    vs "opprof" in-training cadence fragments)."""
    if measured_ms <= 0:
        return
    log.event("sim_divergence", scope="op", op=op_name, which=which,
              predicted_ms=round(predicted_ms, 4),
              measured_ms=round(measured_ms, 4),
              ratio=round(predicted_ms / measured_ms, 4), src=src,
              measured_src=measured_src)
