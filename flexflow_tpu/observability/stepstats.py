"""Per-step training instrumentation.

Computes, per ``update()``:

  * wall time (host-side; in steady state the dispatch blocks on the
    previous step's donated buffers, so wall-between-updates converges
    on true device step time — set ``FF_TELEMETRY_SYNC=1`` to force a
    ``model.sync()`` inside each timed step for exact-but-serialized
    numbers),
  * first-step wall time separately (jit trace + XLA compile happen
    inside step 0 — the reference's epoch-0 Legion trace capture),
  * samples/s and samples/s/chip,
  * analytic-FLOP MFU: train FLOPs estimated as 3x the graph's forward
    FLOPs (fwd + dgrad + wgrad — the same accounting bench.py and the
    reference's backward multiplier use) against the machine model's
    peak (``simulator/machine.py``, the calibrated numbers behind
    ``simulator/cost_model.py``'s roofline),
  * estimated per-step collective bytes from each op's RESOLVED
    ``ParallelConfig`` (gradient all-reduce of replicated weights over
    the batch axis + activation redistribution for non-batch splits),
  * device memory stats when the backend reports them (TPU HBM
    ``bytes_in_use`` / ``peak_bytes_in_use``; CPU reports none).

Everything here is reached ONLY through a non-None EventLog resolved at
``compile()`` — with telemetry off this module is never imported.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np

from .events import EventLog
from .health import write_heartbeat
from .reqtrace import run_trace_id

# Memory gauges are cheap but chatty; sample every N steps.
MEM_GAUGE_EVERY = 8


def estimate_collective_bytes(model) -> int:
    """Rough per-step collective traffic implied by the resolved per-op
    strategies.  Two terms, both analytic:

      * gradient synchronization: weights replicated across a batch
        degree d psum their grads — ring all-reduce moves
        ``2 (d-1)/d * bytes`` per weight (f32 grads),
      * activation redistribution: an output split on a non-batch dim
        with degree d costs ~``(d-1)/d`` of the output's bytes at the
        consumer boundary (allgather/reduce-scatter inserted by GSPMD).

    Halo exchanges and resharding between mismatched consecutive
    configs are NOT modeled — the simulator prices those; this is the
    one-number health gauge.
    """
    dt_bytes = 2 if "16" in model.config.compute_dtype else 4
    total = 0.0
    for op in model.ops:
        pc = getattr(op, "pc", None)
        if pc is None or pc.host_placed:
            continue
        d0 = pc.dims[0]
        if d0 > 1 and op.weights:
            wbytes = sum(float(np.prod(w.dims)) for w in op.weights) * 4.0
            total += 2.0 * (d0 - 1) / d0 * wbytes
        obytes = float(np.prod(op.output.dims)) * dt_bytes
        for d in pc.dims[1:]:
            if d > 1:
                total += (d - 1) / d * obytes
    return int(total)


# allocator-stat keys sampled per device, with the short ``kind`` label
# they export under on /metrics (``ff_hbm_bytes{device,kind}``)
MEM_STAT_KINDS = (("bytes_in_use", "in_use"),
                  ("peak_bytes_in_use", "peak"),
                  ("bytes_limit", "limit"))


def device_memory_stats() -> Optional[list]:
    """Per-device allocator stats across ALL local devices: a list of
    ``{"device": i, "bytes_in_use": ..., "peak_bytes_in_use": ...,
    "bytes_limit": ...}`` rows (keys present when the backend reports
    them).  Devices whose ``memory_stats()`` returns None or raises
    mid-list are skipped — some backends report stats for a subset.
    None when NO device reports (CPU)."""
    try:
        import jax

        devs = jax.local_devices()
    except Exception:
        return None
    out = []
    for i, d in enumerate(devs):
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        rec = {"device": i}
        for k, _ in MEM_STAT_KINDS:
            if k in ms:
                rec[k] = int(ms[k])
        if len(rec) > 1:
            out.append(rec)
    return out or None


class StepStats:
    """Times ``update()`` calls and folds the numbers into the event
    log.  One instance per model, created at ``compile()`` when
    telemetry is on."""

    def __init__(self, model, log: EventLog):
        self.model = model
        self.log = log
        # run-level trace id: step spans join the same timeline as the
        # serving plane's request traces (derived from run_id — stable,
        # zero per-step state)
        self.trace_id = run_trace_id(log.run_id)
        self.steps = 0
        self.sync_each_step = bool(os.environ.get("FF_TELEMETRY_SYNC"))
        self._fwd_flops_per_sample: Optional[float] = None
        self._peak_flops: Optional[float] = None
        self._collective_bytes: Optional[int] = None

    # -- lazy statics (graph + machine are fixed after compile) ---------
    def _statics(self):
        if self._fwd_flops_per_sample is None:
            self._fwd_flops_per_sample = float(
                sum(op.flops_per_sample() for op in self.model.ops))
            from ..simulator.machine import TPUMachineModel

            nd = self.model.machine.num_devices if self.model.machine else 1
            self._peak_flops = float(
                TPUMachineModel.calibrated(num_devices=nd).peak_flops)
            self._collective_bytes = estimate_collective_bytes(self.model)
        return self._fwd_flops_per_sample, self._peak_flops

    def timed_update(self, fn) -> None:
        """Run one training step under a "step" span with throughput /
        MFU counters."""
        log = self.log
        first = self.steps == 0
        step_idx = self.model._step_count
        # Heartbeat BEFORE dispatch: a wedged step leaves "step" (with
        # its index) on disk for the external watchdog to name.
        write_heartbeat("step", step=step_idx)
        t0 = time.perf_counter()
        fn()
        if self.sync_each_step:
            self.model.sync()
        dur = time.perf_counter() - t0
        self.steps += 1

        fwd_fps, peak = self._statics()
        bs = self.model.config.batch_size
        nd = self.model.machine.num_devices if self.model.machine else 1
        sps = bs / dur if dur > 0 else 0.0
        # fwd + dgrad + wgrad ~= 3x forward (reference backward accounting)
        mfu = (3.0 * fwd_fps * sps / (nd * peak)) if peak else 0.0
        log.span_at("step", t0, dur, step=step_idx, first=first,
                    trace_id=self.trace_id, batch_size=bs,
                    samples_per_sec=round(sps, 2),
                    samples_per_sec_per_chip=round(sps / nd, 2),
                    mfu=round(mfu, 6))
        log.counter("samples", float(bs))
        log.gauge("samples_per_sec", round(sps, 2))
        log.gauge("samples_per_sec_per_chip", round(sps / nd, 2))
        log.gauge("mfu", round(mfu, 6))
        if first:
            # step 0 wall includes jit trace + XLA compile
            log.gauge("first_step_wall_s", round(dur, 6))
            log.gauge("est_collective_bytes_per_step",
                      float(self._collective_bytes))
        if first or self.steps % MEM_GAUGE_EVERY == 0:
            mems = device_memory_stats()
            if mems:
                for rec in mems:
                    dev = str(rec["device"])
                    for k, kind in MEM_STAT_KINDS:
                        if k in rec:
                            log.gauge("hbm_bytes", float(rec[k]),
                                      device=dev, kind=kind)
                # legacy single-device series (trace_report's summary
                # line and older dashboards key on these)
                for k in ("bytes_in_use", "peak_bytes_in_use"):
                    if k in mems[0]:
                        log.gauge(f"device_{k}", float(mems[0][k]))
        log.flush()
        health = getattr(self.model, "_health", None)
        if health is not None:
            health.on_step(step_idx, log.to_rel(t0), dur, first)
        opprof = getattr(self.model, "_opprof", None)
        if opprof is not None:
            opprof.on_step(step_idx)
