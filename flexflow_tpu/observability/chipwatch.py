"""Opportunistic chip-session layer: probe the TPU early and often, and
convert the first healthy window into durable measurement artifacts.

The operating reality this module is built for: the TPU sits behind a
flaky remote tunnel that *wedges* — device ops hang forever inside C++
waits where Python signal handlers never run.  So every device touch
happens in a short-lived SUBPROCESS with a kill deadline: a wedged
tunnel kills the child, never the parent.  The parent is free to keep
probing with capped exponential backoff until a window opens, then
spend that window on the highest-value work:

1. ``probe_once`` — run a tiny TPU matmul in a subprocess (same contract
   as ``tools/tpu_probe.py``), SIGKILL it at the timeout.  Emits a
   ``chip_probe`` event per attempt.
2. ``wait_for_chip`` — probe loop with capped exponential backoff,
   bounded by a wall-clock budget and/or attempt count.
3. ``convert_window`` — run ``tools/calibrate.py`` (supervised, jobs in
   value-priority order) as a subprocess.  calibrate persists
   ``simulator/measured_v5e.json`` incrementally after every op via an
   atomic tmp+rename, so the window paying off does NOT require the
   window staying healthy: chipwatch polls the cache during the run and
   emits ``measurement_progress`` events as it grows; if the tunnel
   wedges mid-window the child is killed and every entry measured so
   far is already durable.  A grown cache then gets the machine-model
   refit (``calibrate --fit-only``, CPU-side).  Emits one
   ``chip_window`` event summarizing the conversion.

``probe_cmd`` / ``measure_cmd`` are injectable so tests can stand in a
fake backend; the default commands are the real thing.

CLI::

    python -m flexflow_tpu.observability.chipwatch --probe-only
    python -m flexflow_tpu.observability.chipwatch --budget 3600 \
        --max-seconds 2000
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import subprocess
import sys
import time
from typing import Callable, Iterator, List, Optional, Sequence

from .events import active_log

# Same probe contract as tools/tpu_probe.py: assert the default backend
# really is a TPU (the axon plugin force-selects it even when the env
# asks for cpu), run one matmul through the device, print a checksum.
PROBE_CODE = (
    "import jax\n"
    "d = jax.devices()[0]\n"
    "assert d.platform == 'tpu', f'platform={d.platform}'\n"
    "import jax.numpy as jnp\n"
    "x = jnp.ones((256, 256), jnp.bfloat16)\n"
    "s = float(jax.device_get((x @ x).astype(jnp.float32).sum()))\n"
    "print('TPU_OK', d.device_kind.replace(' ', '_'), s)\n")

DEFAULT_PROBE_TIMEOUT = 90.0
MEASURED_CACHE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "simulator", "measured_v5e.json")


def _emit(name: str, **attrs) -> None:
    log = active_log()
    if log is not None:
        log.event(name, **attrs)
        log.flush()


@dataclasses.dataclass
class ProbeResult:
    ok: bool
    latency_s: float
    device_kind: str = ""
    detail: str = ""


@dataclasses.dataclass
class WindowResult:
    converted: bool
    entries_before: int
    entries_after: int
    duration_s: float
    measure_rc: Optional[int] = None
    refit_rc: Optional[int] = None
    detail: str = ""


def probe_once(timeout: float = DEFAULT_PROBE_TIMEOUT,
               probe_cmd: Optional[Sequence[str]] = None,
               attempt: int = 1) -> ProbeResult:
    """One subprocess probe.  Never hangs the caller: subprocess.run
    kills the child on timeout before raising."""
    cmd = list(probe_cmd) if probe_cmd else [sys.executable, "-c", PROBE_CODE]
    t0 = time.monotonic()
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout)
        dt = time.monotonic() - t0
        out = (r.stdout or "").strip()
        if r.returncode == 0 and "TPU_OK" in out:
            kind = out.split("TPU_OK", 1)[1].split()[0] if \
                out.split("TPU_OK", 1)[1].split() else ""
            res = ProbeResult(True, round(dt, 2), device_kind=kind)
        else:
            err = (r.stderr or "").strip().splitlines()
            detail = err[-1] if err else f"rc={r.returncode}"
            res = ProbeResult(False, round(dt, 2), detail=detail[:200])
    except subprocess.TimeoutExpired:
        res = ProbeResult(False, round(time.monotonic() - t0, 2),
                          detail=f"no answer in {timeout:.0f}s "
                                 "(tunnel wedged?)")
    except OSError as e:
        res = ProbeResult(False, round(time.monotonic() - t0, 2),
                          detail=f"{type(e).__name__}: {e}")
    _emit("chip_probe", ok=res.ok, attempt=attempt, latency_s=res.latency_s,
          device_kind=res.device_kind, detail=res.detail)
    return res


def backoff_delays(initial: float = 20.0, factor: float = 2.0,
                   cap: float = 600.0) -> Iterator[float]:
    d = initial
    while True:
        yield d
        d = min(cap, d * factor)


def wait_for_chip(budget_s: float = 3600.0,
                  probe_timeout: float = DEFAULT_PROBE_TIMEOUT,
                  probe_cmd: Optional[Sequence[str]] = None,
                  initial_backoff: float = 20.0,
                  backoff_factor: float = 2.0,
                  backoff_cap: float = 600.0,
                  max_probes: Optional[int] = None,
                  sleep: Callable[[float], None] = time.sleep,
                  ) -> Optional[ProbeResult]:
    """Probe until a chip answers; None when the budget/attempts run out.

    The backoff is capped so a long outage still gets probed every
    ``backoff_cap`` seconds — the whole point is catching the window
    when the tunnel comes back.
    """
    t0 = time.monotonic()
    delays = backoff_delays(initial_backoff, backoff_factor, backoff_cap)
    attempt = 0
    while True:
        attempt += 1
        res = probe_once(probe_timeout, probe_cmd, attempt=attempt)
        if res.ok:
            return res
        if max_probes is not None and attempt >= max_probes:
            return None
        delay = next(delays)
        if time.monotonic() - t0 + delay >= budget_s:
            return None
        sleep(delay)


def read_measured_count(path: str, platform: str = "tpu") -> Optional[int]:
    """Measured entries for ``platform`` in a cache file.

    0 when the file is missing; None when it exists but is unreadable —
    the cost-model writer is atomic tmp+rename so that only happens with
    a non-atomic third-party writer, and the caller keeps its previous
    count rather than reporting a spurious drop.
    """
    if not os.path.exists(path):
        return 0
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict):
        return None
    return sum(1 for v in data.values()
               if isinstance(v, dict) and v.get("measured")
               and v.get("platform", "tpu") == platform)


def default_measure_cmd(cache_path: str, max_seconds: float,
                        job_timeout: float) -> List[str]:
    return [sys.executable, "-m", "flexflow_tpu.tools.calibrate",
            "--max-seconds", str(max_seconds),
            "--job-timeout", str(job_timeout),
            "--out", cache_path]


def default_refit_cmd() -> List[str]:
    return [sys.executable, "-m", "flexflow_tpu.tools.calibrate",
            "--fit-only"]


def convert_window(cache_path: Optional[str] = None,
                   measure_cmd: Optional[Sequence[str]] = None,
                   max_seconds: float = 2000.0,
                   job_timeout: float = 240.0,
                   poll_every: float = 5.0,
                   stall_timeout: Optional[float] = None,
                   refit: bool = True,
                   refit_cmd: Optional[Sequence[str]] = None,
                   refit_timeout: float = 900.0,
                   platform: str = "tpu",
                   grace: float = 60.0) -> WindowResult:
    """Spend a healthy window on measurement; kill it when it misbehaves.

    The measurement child (calibrate's supervisor by default) persists
    the cache incrementally, so killing it — budget exhausted, growth
    stalled, or the caller's own death — loses at most the op in
    flight.  ``converted`` means the cache grew at all.
    """
    cache_path = cache_path or MEASURED_CACHE
    cmd = list(measure_cmd) if measure_cmd else \
        default_measure_cmd(cache_path, max_seconds, job_timeout)
    before = read_measured_count(cache_path, platform) or 0
    t0 = time.monotonic()
    detail = ""
    rc: Optional[int] = None
    count = before
    last_growth = t0
    proc = subprocess.Popen(cmd)
    try:
        while True:
            try:
                rc = proc.wait(timeout=poll_every)
            except subprocess.TimeoutExpired:
                rc = None
            c = read_measured_count(cache_path, platform)
            if c is not None and c != count:
                count = c
                last_growth = time.monotonic()
                _emit("measurement_progress", entries=c,
                      new_entries=c - before,
                      elapsed_s=round(time.monotonic() - t0, 1))
            if rc is not None:
                break
            now = time.monotonic()
            if now - t0 > max_seconds + grace:
                detail = (f"window budget exhausted ({max_seconds:.0f}s) "
                          "— killed measurement")
                proc.kill()
                rc = proc.wait()
                break
            if stall_timeout and now - last_growth > stall_timeout:
                detail = (f"no cache growth for {stall_timeout:.0f}s "
                          "— killed measurement")
                proc.kill()
                rc = proc.wait()
                break
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    after = read_measured_count(cache_path, platform)
    if after is None:
        after = count
    converted = after > before
    refit_rc: Optional[int] = None
    if refit and converted:
        rcmd = list(refit_cmd) if refit_cmd else default_refit_cmd()
        try:
            refit_rc = subprocess.run(rcmd, capture_output=True,
                                      timeout=refit_timeout).returncode
        except (subprocess.TimeoutExpired, OSError):
            refit_rc = -1
    res = WindowResult(converted=converted, entries_before=before,
                       entries_after=after,
                       duration_s=round(time.monotonic() - t0, 1),
                       measure_rc=rc, refit_rc=refit_rc, detail=detail)
    _emit("chip_window", converted=converted, entries_before=before,
          entries_after=after, duration_s=res.duration_s, measure_rc=rc,
          refit_rc=refit_rc, detail=detail)
    return res


def run_opportunistic(budget_s: float = 3600.0,
                      probe_timeout: float = DEFAULT_PROBE_TIMEOUT,
                      probe_cmd: Optional[Sequence[str]] = None,
                      initial_backoff: float = 20.0,
                      backoff_cap: float = 600.0,
                      max_probes: Optional[int] = None,
                      **window_kwargs) -> Optional[WindowResult]:
    """Probe until a chip answers, then convert the window.  None when
    no chip ever answered within the budget."""
    probe = wait_for_chip(budget_s=budget_s, probe_timeout=probe_timeout,
                          probe_cmd=probe_cmd,
                          initial_backoff=initial_backoff,
                          backoff_cap=backoff_cap, max_probes=max_probes)
    if probe is None:
        return None
    return convert_window(**window_kwargs)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--budget", type=float, default=3600.0,
                   help="probe wall-clock budget (s)")
    p.add_argument("--probe-timeout", type=float,
                   default=DEFAULT_PROBE_TIMEOUT)
    p.add_argument("--backoff-initial", type=float, default=20.0)
    p.add_argument("--backoff-cap", type=float, default=600.0)
    p.add_argument("--max-seconds", type=float, default=2000.0,
                   help="measurement-window budget (s)")
    p.add_argument("--job-timeout", type=float, default=240.0)
    p.add_argument("--cache", default=MEASURED_CACHE)
    p.add_argument("--no-refit", action="store_true")
    p.add_argument("--probe-only", action="store_true",
                   help="single probe; print the result, rc 0 iff ok")
    args = p.parse_args(argv)

    if args.probe_only:
        res = probe_once(timeout=args.probe_timeout)
        print(json.dumps(dataclasses.asdict(res)))
        return 0 if res.ok else 1
    win = run_opportunistic(budget_s=args.budget,
                            probe_timeout=args.probe_timeout,
                            initial_backoff=args.backoff_initial,
                            backoff_cap=args.backoff_cap,
                            cache_path=args.cache,
                            max_seconds=args.max_seconds,
                            job_timeout=args.job_timeout,
                            refit=not args.no_refit)
    if win is None:
        print(json.dumps({"converted": False,
                          "detail": "no chip answered within budget"}))
        return 1
    print(json.dumps(dataclasses.asdict(win)))
    return 0 if win.converted else 1


if __name__ == "__main__":
    sys.exit(main())
