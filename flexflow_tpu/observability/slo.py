"""Declarative serving SLOs with multi-window burn-rate alerts.

Dashboards answer "what is the p99 right now"; an on-call needs the
other question — "at this error rate, how fast am I spending the
month's budget".  This module folds the serving plane's
``serve_request_done`` events into classic SRE burn rates:

  * each SLO names a per-request predicate (TTFT under X ms, TPOT under
    X ms, queue wait under X ms, or plain availability = the request
    finished ``done``) and an objective (default 99% of requests good),
  * over each window W the burn rate is ``bad_fraction / (1 -
    objective)`` — burn 1.0 spends budget exactly as fast as the
    objective allows, burn 2.0 spends a month's budget in half a month,
  * an alert fires only when EVERY window burns above the threshold
    (the standard multi-window guard: the short window proves it is
    happening NOW, the long window proves it is not a blip) and clears
    with hysteresis at half the threshold.

The evaluator is an ``EventLog`` observer (same tap as
``MetricsRegistry``): it reacts ONLY to ``serve_request_done`` records,
uses the RECORD's relative timestamp as its clock (deterministic under
test and in post-hoc replays), and publishes its verdicts back through
the same log —

  gauge ``slo_burn_rate{slo,window}``     -> ``ff_slo_burn_rate``
  gauge ``slo_budget_remaining{slo}``     -> ``ff_slo_budget_remaining``
  event ``slo_alert{slo,state}``          firing / cleared

so the registry, the trace file, and ``tools/timeline_export.py`` all
see them with zero extra plumbing.  Re-entry is safe: observers run
outside the EventLog lock, and gauge/event records never trigger the
evaluator again.

Knobs (all loud on garbage, per the serving/config.py convention):

  FF_SLO_TTFT_MS         TTFT target in ms      (default 500; 0 disables)
  FF_SLO_TPOT_MS         TPOT target in ms      (default 100; 0 disables)
  FF_SLO_QUEUE_WAIT_MS   queue-wait target      (default 1000; 0 disables)
  FF_SLO_AVAILABILITY    0 disables the availability SLO (default on)
  FF_SLO_OBJECTIVE       good-fraction objective (default 0.99)
  FF_SLO_WINDOWS         comma list of window seconds (default "60,300")
  FF_SLO_BURN_ALERT      burn threshold for the alert (default 2.0)

Zero-cost when telemetry is off: nothing attaches without an EventLog.
STDLIB-ONLY, like everything else in observability/.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import events

DEFAULT_TTFT_MS = 500.0
DEFAULT_TPOT_MS = 100.0
DEFAULT_QUEUE_WAIT_MS = 1000.0
DEFAULT_OBJECTIVE = 0.99
DEFAULT_WINDOWS = (60.0, 300.0)
DEFAULT_BURN_ALERT = 2.0


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not a number") from None


def windows_from_env() -> Tuple[float, ...]:
    raw = os.environ.get("FF_SLO_WINDOWS", "")
    if raw == "":
        return DEFAULT_WINDOWS
    try:
        out = tuple(sorted(float(p) for p in raw.split(",") if p.strip()))
    except ValueError:
        raise ValueError(
            f"FF_SLO_WINDOWS={raw!r} is not a comma list of seconds"
        ) from None
    if not out or any(w <= 0 for w in out):
        raise ValueError(
            f"FF_SLO_WINDOWS={raw!r} must name positive window seconds")
    return out


class SLOTarget:
    """One objective: ``field`` is the latency key on the
    ``serve_request_done`` record (None = availability — the request's
    terminal status must be ``done``); a request missing its latency
    field counts BAD (a shed or timed-out request certainly missed
    TTFT)."""

    __slots__ = ("name", "field", "threshold_s", "objective")

    def __init__(self, name: str, field: Optional[str],
                 threshold_s: Optional[float], objective: float):
        if not 0.0 < objective < 1.0:
            raise ValueError(
                f"SLO {name!r} objective {objective} must be in (0, 1)")
        self.name = name
        self.field = field
        self.threshold_s = threshold_s
        self.objective = objective

    def good(self, attrs: Dict[str, Any]) -> bool:
        if self.field is None:
            return attrs.get("status") == "done"
        v = attrs.get(self.field)
        if v is None:
            return False
        return float(v) <= self.threshold_s

    def describe(self) -> Dict[str, Any]:
        d = {"slo": self.name, "objective": self.objective}
        if self.threshold_s is not None:
            d["threshold_ms"] = round(self.threshold_s * 1e3, 3)
        return d


def targets_from_env() -> List[SLOTarget]:
    """The declarative SLO set: sensible defaults out of the box,
    ``FF_SLO_*_MS=0`` switches an SLO off, ``FF_SLO_OBJECTIVE``
    applies to all of them.  Raises ``ValueError`` on garbage."""
    obj = _env_float("FF_SLO_OBJECTIVE", DEFAULT_OBJECTIVE)
    if not 0.0 < obj < 1.0:
        raise ValueError(
            f"FF_SLO_OBJECTIVE={obj} must be in (0, 1) exclusive")
    out: List[SLOTarget] = []
    for name, env, field, dflt in (
            ("ttft", "FF_SLO_TTFT_MS", "ttft_s", DEFAULT_TTFT_MS),
            ("tpot", "FF_SLO_TPOT_MS", "tpot_s", DEFAULT_TPOT_MS),
            ("queue_wait", "FF_SLO_QUEUE_WAIT_MS", "queue_wait_s",
             DEFAULT_QUEUE_WAIT_MS)):
        ms = _env_float(env, dflt)
        if ms < 0:
            raise ValueError(f"{env}={ms} must be >= 0 (0 disables)")
        if ms > 0:
            out.append(SLOTarget(name, field, ms / 1e3, obj))
    if _env_float("FF_SLO_AVAILABILITY", 1.0) != 0.0:
        out.append(SLOTarget("availability", None, None, obj))
    return out


class BurnRateEvaluator:
    """EventLog observer computing per-SLO multi-window burn rates.

    Keeps one rolling sample deque of ``(ts, goods)`` rows (``goods``
    aligned to the target list) bounded by the longest window, so
    memory is O(requests in the long window).  All verdicts go back
    through ``log`` — see the module docstring for the series."""

    def __init__(self, log: events.EventLog,
                 targets: Optional[Sequence[SLOTarget]] = None,
                 windows: Optional[Sequence[float]] = None,
                 burn_alert: Optional[float] = None):
        self.log = log
        self.targets = list(targets if targets is not None
                            else targets_from_env())
        self.windows = tuple(sorted(windows if windows is not None
                                    else windows_from_env()))
        self.burn_alert = float(burn_alert if burn_alert is not None
                                else _env_float("FF_SLO_BURN_ALERT",
                                                DEFAULT_BURN_ALERT))
        if self.burn_alert <= 0:
            raise ValueError(
                f"FF_SLO_BURN_ALERT={self.burn_alert} must be > 0")
        self._lock = threading.Lock()
        self._samples: deque = deque()  # (ts, tuple-of-good-bools)
        self._firing = [False] * len(self.targets)

    # -- the observer ---------------------------------------------------
    def observe(self, rec: Dict[str, Any]) -> None:
        if rec.get("t") != "event" \
                or rec.get("name") != "serve_request_done" \
                or not self.targets:
            return
        attrs = rec.get("attrs") or {}
        now = float(rec.get("ts", 0.0))
        emits: List[Tuple[str, float, Dict[str, Any]]] = []
        alerts: List[Dict[str, Any]] = []
        with self._lock:
            self._samples.append(
                (now, tuple(t.good(attrs) for t in self.targets)))
            horizon = now - self.windows[-1]
            while self._samples and self._samples[0][0] < horizon:
                self._samples.popleft()
            for i, target in enumerate(self.targets):
                burns: List[float] = []
                for w in self.windows:
                    burn = self._burn(i, target, now, w)
                    burns.append(burn)
                    emits.append(("slo_burn_rate", round(burn, 4),
                                  {"slo": target.name,
                                   "window": str(int(w))}))
                # budget over the LONG window: 1 - burn, floored at 0 —
                # "how much of the allowance is left at this rate"
                emits.append(("slo_budget_remaining",
                              round(max(0.0, 1.0 - burns[-1]), 4),
                              {"slo": target.name}))
                firing = self._firing[i]
                if not firing and all(b > self.burn_alert for b in burns):
                    self._firing[i] = True
                    alerts.append(self._alert(target, "firing", burns))
                elif firing and all(b < self.burn_alert * 0.5
                                    for b in burns):
                    self._firing[i] = False
                    alerts.append(self._alert(target, "cleared", burns))
        # publish OUTSIDE our lock: the log fans these records back to
        # every observer (registry included); none react to gauges
        for name, v, labels in emits:
            self.log.gauge(name, v, **labels)
        for a in alerts:
            self.log.event("slo_alert", **a)

    def _burn(self, i: int, target: SLOTarget, now: float,
              window: float) -> float:
        total = bad = 0
        lo = now - window
        for ts, goods in self._samples:
            if ts >= lo:
                total += 1
                if not goods[i]:
                    bad += 1
        if total == 0:
            return 0.0
        return (bad / total) / (1.0 - target.objective)

    def _alert(self, target: SLOTarget, state: str,
               burns: Sequence[float]) -> Dict[str, Any]:
        a = {"slo": target.name, "state": state,
             "threshold": self.burn_alert}
        for w, b in zip(self.windows, burns):
            a[f"burn_{int(w)}s"] = round(b, 4)
        return a

    # -- introspection (doctor / tests) ---------------------------------
    def describe(self) -> Dict[str, Any]:
        return {"targets": [t.describe() for t in self.targets],
                "windows": list(self.windows),
                "burn_alert": self.burn_alert}


# ----------------------------------------------------------------------
# process-wide wiring (mirrors metrics.py's attach bookkeeping)
# ----------------------------------------------------------------------
_lock = threading.Lock()
_attached: List[Tuple[events.EventLog, BurnRateEvaluator]] = []


def maybe_attach(log: Optional[events.EventLog]) \
        -> Optional[BurnRateEvaluator]:
    """Attach a burn-rate evaluator to ``log`` (idempotent per log —
    identity-matched, like ``metrics._attached_logs``).  None log
    (telemetry off) or an empty target set (every SLO disabled via env)
    attaches nothing — the zero-cost path."""
    if log is None:
        return None
    targets = targets_from_env()
    if not targets:
        return None
    with _lock:
        for attached_log, ev in _attached:
            if attached_log is log:
                return ev
        ev = BurnRateEvaluator(log, targets=targets)
        _attached.append((log, ev))
    log.add_observer(ev.observe)
    return ev


def reset() -> None:
    """Forget attached evaluators (test hook; ``metrics.stop`` calls
    this alongside clearing its own attach list)."""
    with _lock:
        _attached.clear()
