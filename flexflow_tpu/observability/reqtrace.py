"""Request-scoped tracing: trace ids, sampling, and span parentage.

The serving stack spans five layers (HTTP api -> admission queue ->
replica-pool attempts -> engine decode slots -> paged KV pool) and each
already emits its own ``serve_*`` records — but nothing joined them.
This module is the joining key: a ``TraceContext`` minted ONCE at
admission and carried on the ``InferenceRequest`` through every layer,
so one request's queue wait, prefill, decode chunks, KV block events,
and failover/hedge attempts all share a ``trace_id`` and
``tools/timeline_export.py`` can render them as one Perfetto track.

STDLIB-ONLY like ``events.py``/``serving/queue.py`` — the queue module
(which carries the context) must stay importable without jax, and
``timeline_export`` folds traces on laptops.

Model (a deliberately small slice of the OpenTelemetry shape):

* ``trace_id``   — 16 random bytes (32 hex chars), one per CLIENT
                   request.  Every attempt, span, and event of that
                   request carries it.
* ``span_id``    — 8 bytes (16 hex); each attempt (``req-7#aN``) is a
                   CHILD span of the client's root span, so a failover
                   or hedge race renders as sibling spans under one
                   trace.
* ``sampled``    — decided once at admission from ``FF_TRACE_SAMPLE``
                   (probability in [0, 1]).  The decision is a
                   DETERMINISTIC hash of the trace id, so replays and
                   tests agree, and a trace is never half-sampled.

Cost discipline: with telemetry off, no context is ever created (the
``begin`` helpers return None and every call site guards on it — the
same None-handle pattern as the rest of the telemetry plane).  With
telemetry on but a request unsampled, the request carries ONLY the
16-byte id: existing ``serve_*`` records gain a ``trace_id`` attr (so
old tooling keeps working and logs still join), but no extra spans,
chunk records, or KV events are emitted.

Knobs (all parsed loudly — a typo raises, naming the variable):

  FF_TRACE_SAMPLE  sampling probability in [0, 1]; default 0
                   (ids only, no per-request span detail)
  FF_TRACE_CHUNK   decode tokens per ``serve_decode_chunk`` span on a
                   sampled request; default 8 (0 disables chunk spans)
"""

from __future__ import annotations

import hashlib
import os
from typing import Any, Dict, Optional

SAMPLE_ENV = "FF_TRACE_SAMPLE"
CHUNK_ENV = "FF_TRACE_CHUNK"
DEFAULT_CHUNK = 8

_HASH_SCALE = float(1 << 64)


def sample_rate_from_env() -> float:
    """``FF_TRACE_SAMPLE`` as a probability; 0.0 when unset.  Loud
    ``ValueError`` on garbage — a silently-dropped typo would leave an
    operator with no traces and no idea why."""
    raw = os.environ.get(SAMPLE_ENV, "")
    if raw == "":
        return 0.0
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(
            f"{SAMPLE_ENV}={raw!r} is not a number") from None
    if not 0.0 <= v <= 1.0:
        raise ValueError(
            f"{SAMPLE_ENV}={v:g} is outside [0, 1]")
    return v


def chunk_tokens_from_env() -> int:
    """``FF_TRACE_CHUNK``: decode tokens per chunk span; default 8,
    0 disables chunk spans on sampled requests."""
    raw = os.environ.get(CHUNK_ENV, "")
    if raw == "":
        return DEFAULT_CHUNK
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"{CHUNK_ENV}={raw!r} is not an integer") from None
    if v < 0:
        raise ValueError(f"{CHUNK_ENV}={v} must be >= 0")
    return v


def new_trace_id() -> str:
    """16 random bytes as 32 hex chars."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """8 random bytes as 16 hex chars."""
    return os.urandom(8).hex()


def decide(trace_id: str, rate: float) -> bool:
    """The sampling decision for ``trace_id`` at ``rate`` — a
    deterministic hash, NOT a coin flip: the same id always decides the
    same way, so the decision can be made once at admission and every
    later layer (or a test, or a replay) re-derives it identically."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    h = int.from_bytes(
        hashlib.blake2b(trace_id.encode(), digest_size=8).digest(), "big")
    return h / _HASH_SCALE < rate


def run_trace_id(run_id: str) -> str:
    """Run-level trace id for the TRAINING plane: derived (not random)
    from the EventLog ``run_id`` so step/compile/reconfig spans of one
    run share a stable id with zero per-step state."""
    return hashlib.blake2b(
        str(run_id).encode(), digest_size=16).hexdigest()


class TraceContext:
    """One span's identity within a trace.  Immutable by convention;
    ``child()`` derives the next hop (attempt under client root,
    ...)."""

    __slots__ = ("trace_id", "span_id", "parent_span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str,
                 parent_span_id: Optional[str], sampled: bool):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id
        self.sampled = sampled

    def child(self) -> "TraceContext":
        """A child span context (fresh span id, same trace + sampling
        decision) — one per pool attempt, so hedge/failover races
        render as siblings."""
        return TraceContext(self.trace_id, new_span_id(),
                            self.span_id, self.sampled)

    def ids(self) -> Dict[str, Any]:
        """Attrs identifying THIS span's own record (the attempt span,
        the client root span)."""
        out: Dict[str, Any] = {"trace_id": self.trace_id,
                               "span_id": self.span_id}
        if self.parent_span_id is not None:
            out["parent_span_id"] = self.parent_span_id
        return out

    def __repr__(self) -> str:  # debug/doctor output
        return (f"TraceContext({self.trace_id[:8]}../{self.span_id}"
                f"{' sampled' if self.sampled else ''})")


def begin(log, rate: Optional[float] = None) -> Optional[TraceContext]:
    """Mint the ROOT context for one client request at admission.
    Returns None when ``log`` is None (telemetry off — the zero-cost
    path: no ids, no hashing, nothing).  ``rate`` defaults to the
    loudly-parsed ``FF_TRACE_SAMPLE``."""
    if log is None:
        return None
    if rate is None:
        rate = sample_rate_from_env()
    tid = new_trace_id()
    return TraceContext(tid, new_span_id(), None, decide(tid, rate))


def tag(ctx: Optional[TraceContext]) -> Dict[str, Any]:
    """Attrs to stamp onto a record emitted UNDER ``ctx`` (queue-wait /
    prefill / decode spans, KV events, the done event).  {} when
    untraced; id-only when unsampled; id + parent linkage when sampled
    — old tooling ignores the extra attrs either way."""
    if ctx is None:
        return {}
    if not ctx.sampled:
        return {"trace_id": ctx.trace_id}
    return {"trace_id": ctx.trace_id, "parent_span_id": ctx.span_id}
