"""Structured event log: spans, counters, gauges over a JSONL sink.

STDLIB-ONLY on purpose: ``bench.py`` emits phase heartbeats through this
module before jax (or the rest of the framework) has initialized, and
``tools/trace_report.py`` reads the records back on hosts with no
accelerator — neither may drag in the heavy imports.

Record schema (one JSON object per line; ``ts``/``dur`` are seconds on a
monotonic clock relative to the log's creation):

  {"t": "meta",    "run_id": .., "pid": .., "unix_time": .., "argv": ..}
  {"t": "span",    "name": .., "id": n, "parent": m|null,
                   "ts": .., "dur": .., "attrs": {..}}
  {"t": "counter", "name": .., "v": float, "total": float, "ts": ..,
                   "attrs": {..}}
  {"t": "gauge",   "name": .., "v": float, "ts": .., "attrs": {..}}
  {"t": "event",   "name": .., "ts": .., "attrs": {..}}

(``attrs`` is present only when non-empty — gauges carry them too,
e.g. ``replica=`` on ``serve_batch_occupancy``.)

Spans nest per thread (a thread-local stack links ``parent``); counters
carry their running ``total`` so a tail-truncated trace still reports
correct aggregates.  The sink is line-buffered: every record reaches the
OS before the write returns, so a watchdog ``os._exit`` cannot eat the
events that explain what it killed.
"""

from __future__ import annotations

import contextlib
import io
import itertools
import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional

SCHEMA_VERSION = 1

DEFAULT_TRACE_FILE = "ff_trace.jsonl"


def _env_enabled() -> bool:
    return os.environ.get("FF_TELEMETRY", "") not in ("", "0")


def default_path() -> str:
    return os.environ.get("FF_TELEMETRY_FILE") or DEFAULT_TRACE_FILE


class EventLog:
    """Thread-safe structured event log writing JSONL to ``path``.

    The file opens lazily at the first record (constructing a log never
    touches the filesystem) and truncates: one log == one run's trace.
    ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, path: str, run_id: Optional[str] = None,
                 clock: Callable[[], float] = time.perf_counter):
        self.path = path
        self.run_id = run_id or f"{os.getpid()}-{int(time.time())}"
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._file: Optional[io.TextIOBase] = None
        self._ids = itertools.count(1)
        self._tls = threading.local()
        self._closed = False
        # Running per-counter totals (survive into truncated traces via
        # the per-record "total" field; tests assert aggregation here).
        self.totals: Dict[str, float] = {}
        # Observers see every written record (observability/health.py
        # taps spans for straggler attribution).  Called OUTSIDE the
        # lock: an observer may emit records of its own.  One that
        # raises is detached with a one-time warning — it must not
        # poison the emitting thread (see _drop_observer).
        self._observers: list = []

    # -- clock ----------------------------------------------------------
    def now(self) -> float:
        """Seconds since log creation (monotonic)."""
        return self._clock() - self._t0

    def to_rel(self, t: float) -> float:
        """Convert a raw clock reading (``time.perf_counter()`` with the
        default clock) into the log's relative time domain."""
        return t - self._t0

    # -- observers ------------------------------------------------------
    def add_observer(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        with self._lock:
            if fn not in self._observers:
                self._observers.append(fn)

    def _drop_observer(self, fn, exc: BaseException) -> None:
        """Detach an observer that raised.  The fan-out runs on whatever
        thread wrote the record (an engine loop, the pool monitor, an
        HTTP handler) — one broken observer must not poison them all on
        every subsequent record.  Removal is CAS-like under the lock, so
        when several emitting threads hit the same broken observer
        concurrently exactly one wins and prints the one-time warning."""
        with self._lock:
            try:
                self._observers.remove(fn)
            except ValueError:
                return  # another thread already detached + warned
        print(f"flexflow_tpu: telemetry observer {fn!r} raised "
              f"{type(exc).__name__}: {exc} — detached (records keep "
              f"flowing to the sink and remaining observers)",
              file=sys.stderr)

    # -- sink -----------------------------------------------------------
    def _write(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if self._closed:
                return
            if self._file is None:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)
                # buffering=1: line-buffered — each record reaches the
                # OS immediately (watchdog-kill durability)
                self._file = open(self.path, "w", buffering=1)
                self._file.write(json.dumps(
                    {"t": "meta", "version": SCHEMA_VERSION,
                     "run_id": self.run_id, "pid": os.getpid(),
                     "unix_time": time.time()}) + "\n")
            self._file.write(json.dumps(rec) + "\n")
            observers = tuple(self._observers)
        for fn in observers:
            try:
                fn(rec)
            except Exception as e:  # noqa: BLE001 — observer quarantine
                self._drop_observer(fn, e)

    def flush(self) -> None:
        with self._lock:
            if self._file is not None and not self._closed:
                self._file.flush()

    def close(self) -> None:
        with self._lock:
            if self._file is not None and not self._closed:
                self._file.flush()
                self._file.close()
            self._closed = True

    # -- span stack -----------------------------------------------------
    def _stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Context manager recording a completed span on exit.  Yields
        the attrs dict so callers can add attributes computed inside."""
        sid = next(self._ids)
        stack = self._stack()
        parent = stack[-1] if stack else None
        stack.append(sid)
        t0 = self._clock()
        try:
            yield attrs
        finally:
            dur = self._clock() - t0
            stack.pop()
            self._write({"t": "span", "name": name, "id": sid,
                         "parent": parent, "ts": round(t0 - self._t0, 6),
                         "dur": round(dur, 6), "attrs": attrs})

    def span_at(self, name: str, start: float, dur: float, **attrs) -> None:
        """Record an already-measured span (``start`` in the log's clock
        domain, i.e. a ``time.perf_counter()`` reading with the default
        clock)."""
        sid = next(self._ids)
        stack = self._stack()
        parent = stack[-1] if stack else None
        self._write({"t": "span", "name": name, "id": sid,
                     "parent": parent, "ts": round(start - self._t0, 6),
                     "dur": round(dur, 6), "attrs": attrs})

    # -- scalars --------------------------------------------------------
    def counter(self, name: str, value: float, **attrs) -> None:
        """Monotonic accumulation: the record carries both this delta
        and the running total."""
        with self._lock:
            total = self.totals.get(name, 0.0) + float(value)
            self.totals[name] = total
        rec = {"t": "counter", "name": name, "v": float(value),
               "total": total, "ts": round(self.now(), 6)}
        if attrs:
            rec["attrs"] = attrs
        self._write(rec)

    def gauge(self, name: str, value: float, **attrs) -> None:
        rec = {"t": "gauge", "name": name, "v": float(value),
               "ts": round(self.now(), 6)}
        if attrs:
            rec["attrs"] = attrs
        self._write(rec)

    def event(self, name: str, **attrs) -> None:
        self._write({"t": "event", "name": name,
                     "ts": round(self.now(), 6), "attrs": attrs})


# ----------------------------------------------------------------------
# process-wide active log (env-gated singleton)
# ----------------------------------------------------------------------
_active: Optional[EventLog] = None
_active_lock = threading.Lock()


def active_log() -> Optional[EventLog]:
    """The process's shared EventLog when ``FF_TELEMETRY`` is enabled,
    else None.  The env is re-checked per call (cheap: one dict lookup)
    so late ``os.environ`` changes and tests behave predictably; the
    log itself is created once."""
    global _active
    if _active is not None:
        return _active
    if not _env_enabled():
        return None
    with _active_lock:
        if _active is None:
            _active = EventLog(default_path())
            print(f"flexflow_tpu: telemetry enabled -> {_active.path}")
    return _active


def for_config(config) -> Optional[EventLog]:
    """Resolve the log for an ``FFConfig``: enabled when the config's
    ``telemetry`` flag OR the ``FF_TELEMETRY`` env is set.  Returns the
    process singleton (creating it with the config's ``telemetry_file``
    if it names one and no log exists yet)."""
    global _active
    if _active is not None:
        return _active
    if not (getattr(config, "telemetry", False) or _env_enabled()):
        return None
    with _active_lock:
        if _active is None:
            path = getattr(config, "telemetry_file", "") or default_path()
            _active = EventLog(path)
            print(f"flexflow_tpu: telemetry enabled -> {_active.path}")
    return _active


def reset_active() -> None:
    """Close and forget the singleton (test isolation hook)."""
    global _active
    with _active_lock:
        if _active is not None:
            _active.close()
        _active = None


def _atexit_flush() -> None:
    if _active is not None:
        _active.close()


import atexit  # noqa: E402  (stdlib; registered once at import)

atexit.register(_atexit_flush)
