"""Live metrics plane: in-process aggregation + ``/metrics`` exporter.

The trace file (``events.py``) is post-hoc: nothing reads it until the
run exits.  This module is the LIVE half — a ``MetricsRegistry`` that
taps ``EventLog.add_observer`` and folds every record into counters,
gauges, and rolling-window histograms as it is written, plus a stdlib
HTTP server exposing them as Prometheus text format at ``/metrics`` and
expvar-style JSON at ``/debug/vars``.

STDLIB-ONLY on purpose, like ``events.py``: ``bench.py`` starts the
exporter before jax initializes, and the serving ``api.py`` mounts the
same renderer without new dependencies.

Record folding:

  counter  -> per-(name, labels) running sum of deltas; at attach time
              the log's per-name ``totals`` seed the label-free series,
              so a registry attached mid-run still reports full totals
              (summing a name across its label sets == the log total)
  gauge    -> last value per (name, labels)
  span     -> rolling-window histogram of ``dur`` keyed by span name
              (p50/p95/p99 via the same linear-interpolation percentile
              as ``tools/trace_report.py``), plus monotonic count/sum
  event    -> ``ff_events_total{event="<name>"}``; ``serve_request_done``
              additionally feeds ``serve_ttft``/``serve_tpot`` histograms

Attrs become Prometheus labels only through an allowlist — request ids
and shapes would otherwise explode series cardinality.

Enablement: ``FF_METRICS_PORT=<port>`` starts the standalone exporter
(port 0 binds ephemerally; read ``server_port()``).  Unset, the module
is zero-cost: ``maybe_start()`` returns None without registering any
observer and the hot path never sees it (the established None-handle
pattern).  Scrapes are safe under concurrent writers: rendering
snapshots under the registry lock; observers already run outside the
EventLog lock.

Serving backends (``ReplicaPool``/``InferenceEngine``) additionally
register a *provider* — a callable rendering scrape-time series
(per-replica up/incarnation, queue depth) that have no event stream.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import events

# attr keys that may become Prometheus labels; everything else is
# dropped from the label set (NOT from the trace) to bound cardinality
LABEL_KEYS = ("device", "event", "kind", "op", "outcome", "phase", "reason",
              "replica", "scope", "site", "slo", "src", "status", "which",
              "window", "zone")

# histogram quantiles exposed on every summary series
QUANTILES = (50.0, 95.0, 99.0)

DEFAULT_WINDOW = 1024


def percentile(sorted_vals: List[float], q: float) -> float:
    """Linear-interpolation percentile on an already-sorted list (the
    same math as ``tools/trace_report.py`` — tests cross-check them)."""
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q / 100.0 * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def metrics_port_from_env() -> Optional[int]:
    """``FF_METRICS_PORT`` as an int port, None when unset/empty.
    Loud ``ValueError`` on garbage — a silently-ignored typo would
    leave an operator scraping nothing."""
    raw = os.environ.get("FF_METRICS_PORT", "")
    if raw == "":
        return None
    try:
        port = int(raw)
    except ValueError:
        raise ValueError(
            f"FF_METRICS_PORT={raw!r} is not an integer port") from None
    if not 0 <= port <= 65535:
        raise ValueError(
            f"FF_METRICS_PORT={port} is outside 0..65535")
    return port


def _san(name: str) -> str:
    """Sanitize to a Prometheus metric-name fragment."""
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _esc(v: Any) -> str:
    """Escape a label value per the text exposition format."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


def _labels(attrs: Optional[Dict[str, Any]]) -> Tuple[Tuple[str, str], ...]:
    if not attrs:
        return ()
    return tuple(sorted((k, str(attrs[k])) for k in attrs
                        if k in LABEL_KEYS))


def _label_str(labels: Tuple[Tuple[str, str], ...],
               extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = labels + extra
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{_esc(v)}"' for k, v in pairs) + "}"


class _Hist:
    """Rolling-window values for quantiles + monotonic count/sum."""

    __slots__ = ("window", "count", "total")

    def __init__(self, maxlen: int):
        self.window: deque = deque(maxlen=maxlen)
        self.count = 0
        self.total = 0.0

    def add(self, v: float) -> None:
        self.window.append(v)
        self.count += 1
        self.total += v

    def snapshot(self) -> Dict[str, float]:
        vals = sorted(self.window)
        out = {"count": self.count, "sum": round(self.total, 6)}
        for q in QUANTILES:
            out[f"p{q:g}"] = round(percentile(vals, q), 6)
        return out


class MetricsRegistry:
    """In-process aggregation of EventLog records.

    ``observe`` is the ``EventLog`` observer; it runs on whatever
    thread wrote the record (outside the log's lock), so every mutation
    holds the registry's own lock.  Rendering snapshots under the same
    lock — a scrape mid-burst sees a consistent point-in-time view.
    """

    def __init__(self, window: Optional[int] = None):
        if window is None:
            raw = os.environ.get("FF_METRICS_WINDOW", "")
            window = int(raw) if raw else DEFAULT_WINDOW
        self._window = max(8, int(window))
        self._lock = threading.Lock()
        # (name, labels) -> running sum / last value
        self._counters: Dict[Tuple[str, tuple], float] = {}
        self._gauges: Dict[Tuple[str, tuple], float] = {}
        # name -> _Hist (span durations + request-latency fields)
        self._hists: Dict[str, _Hist] = {}
        self._records_seen = 0

    # -- ingestion ------------------------------------------------------
    def attach(self, log: events.EventLog) -> None:
        """Register as an observer and seed counter totals accumulated
        before attach (``log.totals`` is per-name, label-free)."""
        with log._lock:
            seed = dict(log.totals)
        with self._lock:
            for name, total in seed.items():
                key = (name, ())
                self._counters[key] = self._counters.get(key, 0.0) + total
        log.add_observer(self.observe)

    def observe(self, rec: Dict[str, Any]) -> None:
        t = rec.get("t")
        name = rec.get("name", "?")
        attrs = rec.get("attrs")
        with self._lock:
            self._records_seen += 1
            if t == "counter":
                key = (name, _labels(attrs))
                self._counters[key] = (self._counters.get(key, 0.0)
                                       + float(rec.get("v", 0.0)))
            elif t == "gauge":
                self._gauges[(name, _labels(attrs))] = \
                    float(rec.get("v", 0.0))
            elif t == "span":
                self._hist(name).add(float(rec.get("dur", 0.0)))
            elif t == "event":
                key = ("events", (("event", name),))
                self._counters[key] = self._counters.get(key, 0.0) + 1.0
                if name == "serve_request_done" and attrs:
                    for field, series in (("ttft_s", "serve_ttft"),
                                          ("tpot_s", "serve_tpot")):
                        v = attrs.get(field)
                        if v is not None:
                            self._hist(series).add(float(v))
                elif name == "op_runtime" and attrs:
                    mm = attrs.get("measured_ms")
                    if mm is not None:
                        self._hist("op_runtime_ms").add(float(mm))

    def _hist(self, name: str) -> _Hist:
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = _Hist(self._window)
        return h

    def has_series(self, name: str) -> bool:
        """True when this registry already carries the series — backend
        providers use it to avoid emitting a duplicate metric name in
        the same scrape body."""
        with self._lock:
            return any(k[0] == name for k in self._counters) \
                or any(k[0] == name for k in self._gauges)

    # -- rendering ------------------------------------------------------
    def _snapshot(self):
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: h.snapshot() for k, h in self._hists.items()}
            seen = self._records_seen
        return counters, gauges, hists, seen

    def render_prom(self) -> str:
        counters, gauges, hists, seen = self._snapshot()
        out: List[str] = []
        by_name: Dict[str, List[Tuple[tuple, float]]] = {}
        for (name, labels), v in sorted(counters.items()):
            by_name.setdefault(name, []).append((labels, v))
        for name, rows in by_name.items():
            m = f"ff_{_san(name)}_total"
            out.append(f"# TYPE {m} counter")
            for labels, v in rows:
                out.append(f"{m}{_label_str(labels)} {v:g}")
        gby: Dict[str, List[Tuple[tuple, float]]] = {}
        for (name, labels), v in sorted(gauges.items()):
            gby.setdefault(name, []).append((labels, v))
        for name, rows in gby.items():
            m = f"ff_{_san(name)}"
            out.append(f"# TYPE {m} gauge")
            for labels, v in rows:
                out.append(f"{m}{_label_str(labels)} {v:g}")
        for name in sorted(hists):
            snap = hists[name]
            base = _san(name)
            unit = "ms" if base.endswith("_ms") else "seconds"
            if base.endswith(("_s", "_ms")):
                base = base.rsplit("_", 1)[0]
            m = f"ff_{base}_{unit}"
            out.append(f"# TYPE {m} summary")
            for q in QUANTILES:
                out.append(f'{m}{{quantile="{q / 100.0:g}"}} '
                           f'{snap[f"p{q:g}"]:g}')
            out.append(f"{m}_sum {snap['sum']:g}")
            out.append(f"{m}_count {snap['count']:g}")
        out.append("# TYPE ff_metrics_records_seen_total counter")
        out.append(f"ff_metrics_records_seen_total {seen}")
        return "\n".join(out) + "\n"

    def render_vars(self) -> Dict[str, Any]:
        """expvar-style dict for ``/debug/vars``."""
        counters, gauges, hists, seen = self._snapshot()

        def keyed(d):
            return {name + _label_str(labels): v
                    for (name, labels), v in sorted(d.items())}

        return {"records_seen": seen,
                "counters": keyed(counters),
                "gauges": keyed(gauges),
                "histograms": {k: hists[k] for k in sorted(hists)}}


# ----------------------------------------------------------------------
# scrape-time backend providers (serving state with no event stream)
# ----------------------------------------------------------------------
_providers: List[Callable[[], str]] = []
_providers_lock = threading.Lock()


def register_provider(fn: Callable[[], str]) -> None:
    with _providers_lock:
        if fn not in _providers:
            _providers.append(fn)


def unregister_provider(fn: Callable[[], str]) -> None:
    with _providers_lock:
        if fn in _providers:
            _providers.remove(fn)


def _kv_lines(used: int, free: int, hits: int) -> List[str]:
    """Paged-KV scrape lines from backend *state*.  The engine also
    streams ``serve_kv_blocks_used``/``serve_prefix_hits`` through its
    telemetry log; when an attached registry already renders those
    series the state-side copy is suppressed so one scrape body never
    carries a duplicate metric name (``blocks_free`` is state-only —
    always emitted)."""
    reg = global_registry()
    out: List[str] = []
    if reg is None or not reg.has_series("serve_kv_blocks_used"):
        out.append("# TYPE ff_serve_kv_blocks_used gauge")
        out.append(f"ff_serve_kv_blocks_used {used}")
    out.append("# TYPE ff_serve_kv_blocks_free gauge")
    out.append(f"ff_serve_kv_blocks_free {free}")
    if reg is None or not reg.has_series("serve_prefix_hits"):
        out.append("# TYPE ff_serve_prefix_hits_total counter")
        out.append(f"ff_serve_prefix_hits_total {hits}")
    return out


def render_backend(backend) -> str:
    """Prometheus lines for a serving backend's live state: per-replica
    health/incarnation (pool) or engine queue/active depth — values that
    exist as *state*, not as an event stream, so the registry can't see
    them.  Failures render as a comment, never break a scrape."""
    out: List[str] = []
    try:
        if hasattr(backend, "healthz"):            # ReplicaPool
            hz = backend.healthz()
            out.append("# TYPE ff_serve_queue_depth gauge")
            out.append(f"ff_serve_queue_depth {hz.get('queued', 0)}")
            out.append("# TYPE ff_serve_inflight gauge")
            out.append(f"ff_serve_inflight {hz.get('inflight', 0)}")
            out.append("# TYPE ff_replica_up gauge")
            ups, incs, rsts = [], [], []
            for r in hz.get("replicas", []):
                name = str(r.get("name"))
                pairs = [("replica", name),
                         ("state", str(r.get("state")))]
                if r.get("zone") is not None:
                    pairs.append(("zone", str(r["zone"])))
                lab = _label_str(tuple(sorted(pairs)))
                ups.append(f"ff_replica_up{lab} "
                           f"{1 if r.get('state') == 'ready' else 0}")
                inc = r.get("incarnation")
                if inc is not None:
                    # uid is a string ("replica-0#1") — expose it
                    # info-style (value 1, uid as a label), the
                    # build_info idiom
                    incs.append("ff_replica_incarnation%s 1" % _label_str(
                        (("incarnation", str(inc)), ("replica", name))))
                rsts.append("ff_replica_restarts%s %d" % (
                    _label_str((("replica", name),)),
                    int(r.get("restarts", 0) or 0)))
            out.extend(ups)
            if incs:
                out.append("# TYPE ff_replica_incarnation gauge")
                out.extend(incs)
            if rsts:
                out.append("# TYPE ff_replica_restarts gauge")
                out.extend(rsts)
            zones = hz.get("zones") or {}
            if zones:
                out.append("# TYPE ff_zone_ready_replicas gauge")
                for z, zd in zones.items():
                    out.append("ff_zone_ready_replicas%s %d" % (
                        _label_str((("zone", str(z)),)),
                        int(zd.get("ready", 0))))
                out.append("# TYPE ff_zone_down gauge")
                for z, zd in zones.items():
                    out.append("ff_zone_down%s %d" % (
                        _label_str((("zone", str(z)),)),
                        1 if zd.get("down") else 0))
            # fold paged-KV occupancy across live replica engines
            kvs = [r["engine"]["kv"]
                   for r in backend.stats().get("replicas", {}).values()
                   if r.get("engine") and r["engine"].get("kv")]
            if kvs:
                out.extend(_kv_lines(
                    sum(k["blocks_used"] for k in kvs),
                    sum(k["blocks_free"] for k in kvs),
                    sum(k["prefix_hits"] for k in kvs)))
        elif hasattr(backend, "stats"):            # bare InferenceEngine
            st = backend.stats()
            out.append("# TYPE ff_serve_queue_depth gauge")
            out.append(f"ff_serve_queue_depth {st.get('queued', 0)}")
            out.append("# TYPE ff_serve_active gauge")
            out.append(f"ff_serve_active {st.get('active', 0)}")
            kv = st.get("kv")
            if kv:
                out.extend(_kv_lines(kv["blocks_used"], kv["blocks_free"],
                                     kv["prefix_hits"]))
    except Exception as e:  # noqa: BLE001 — scrape must not 500
        out.append(f"# backend render failed: {type(e).__name__}: {e}")
    return "\n".join(out) + ("\n" if out else "")


def scrape_text(backend=None) -> str:
    """One scrape body: registry series (when enabled) + provider
    lines + an optional backend's live state."""
    parts: List[str] = []
    reg = global_registry()
    if reg is not None:
        parts.append(reg.render_prom())
    else:
        parts.append("# ff metrics registry disabled "
                     "(set FF_METRICS_PORT)\n")
    with _providers_lock:
        provs = tuple(_providers)
    for fn in provs:
        try:
            parts.append(fn())
        except Exception:
            pass  # a dead provider never breaks a scrape
    if backend is not None:
        parts.append(render_backend(backend))
    return "".join(p if p.endswith("\n") else p + "\n"
                   for p in parts if p)


# ----------------------------------------------------------------------
# standalone exporter (env-gated process singleton)
# ----------------------------------------------------------------------
class _MetricsHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet: scrapes are periodic
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 — http.server API
        path = self.path.split("?")[0]
        if path == "/metrics":
            self._send(200, scrape_text().encode(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/debug/vars":
            reg = global_registry()
            body = reg.render_vars() if reg is not None \
                else {"disabled": True}
            self._send(200, json.dumps(body).encode(), "application/json")
        else:
            self._send(404, b'{"error": "no such endpoint"}',
                       "application/json")


_state_lock = threading.Lock()
_registry: Optional[MetricsRegistry] = None
_server: Optional[ThreadingHTTPServer] = None
_attached_logs: list = []


def global_registry() -> Optional[MetricsRegistry]:
    return _registry


def server_port() -> Optional[int]:
    with _state_lock:
        return _server.server_address[1] if _server is not None else None


def maybe_start(log: Optional[events.EventLog] = None) \
        -> Optional[MetricsRegistry]:
    """Start the process-wide registry + exporter iff ``FF_METRICS_PORT``
    is set; idempotent (later calls attach any newly-created EventLog
    and return the existing registry).  Returns None — and registers NO
    observer — when the knob is unset.  Raises ``ValueError`` on a
    malformed port and ``OSError`` if the bind fails."""
    global _registry, _server
    port = metrics_port_from_env()
    if port is None:
        return None
    with _state_lock:
        if _registry is None:
            _registry = MetricsRegistry()
        if _server is None:
            host = os.environ.get("FF_METRICS_HOST", "")
            _server = ThreadingHTTPServer((host, port), _MetricsHandler)
            _server.daemon_threads = True
            threading.Thread(target=_server.serve_forever,
                             name="ff-metrics-http", daemon=True).start()
            print(f"flexflow_tpu: metrics exporter on "
                  f":{_server.server_address[1]} (/metrics, /debug/vars)")
        reg = _registry
    tap = log if log is not None else events.active_log()
    if tap is not None:
        with _state_lock:
            fresh = tap not in _attached_logs
            if fresh:
                _attached_logs.append(tap)
        if fresh:
            reg.attach(tap)
            # the SLO burn-rate evaluator rides the same tap: its
            # verdicts come back through the log as slo_* gauges, which
            # the registry just attached to this log will fold
            from . import slo

            slo.maybe_attach(tap)
    return reg


def stop() -> None:
    """Shut down the exporter and forget the registry (test hook)."""
    global _registry, _server
    with _state_lock:
        if _server is not None:
            _server.shutdown()
            _server.server_close()
            _server = None
        _registry = None
        _attached_logs.clear()
    from . import slo

    slo.reset()  # the evaluators attached alongside the registry
