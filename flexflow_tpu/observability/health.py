"""Training health monitor: interprets the event stream as it happens.

The reference FlexFlow has no runtime health layer — a NaN'd run or a
wedged data pipeline is discovered from the loss curve hours later.  At
pod scale, debugging lives or dies on attributing a stall to a phase
(Kumar et al., MLPerf-0.6 on TPU-v3 pods), so this module turns the
PR-1 event log from a flight recorder into a live monitor:

  * **non-finite detection** — the jitted train step folds an
    ``isfinite`` reduction over the loss and the global grad-norm into
    the on-device metric vector (model.py ``_build_train_step``); every
    ``FF_HEALTH_SAMPLE_EVERY`` steps the monitor forces the existing
    metric drain and flags any non-finite step in the window.  The
    reduction rides the metric accumulator, so detection adds zero
    extra device dispatches — just one drain per window,
  * **straggler detection** — rolling median over steady-state step
    walls; a step exceeding ``FF_HEALTH_STRAGGLER_K`` x p50 emits a
    ``health`` event attributed to whichever compile / data_wait /
    checkpoint spans overlapped the gap since the previous step,
  * **data starvation** — cumulative ``data_wait`` vs step time per
    window; a ratio above ``FF_HEALTH_DATA_WAIT_RATIO`` warns,
  * **heartbeat file** — ``FF_HEARTBEAT_PATH`` names a JSON file
    atomically rewritten at every phase entry and step, so an external
    watchdog (bench.py's included) can report *which phase* wedged
    instead of a bare "killed".

STDLIB-ONLY on purpose, like ``events.py``: bench.py writes heartbeats
before jax initializes, and the monitor itself touches no arrays — the
device-side work lives in the jitted step.

Enable with ``FF_HEALTH=1`` on top of ``FF_TELEMETRY=1``.  With
telemetry off the monitor is never constructed and the hot path makes
zero health calls (asserted by tests/test_health.py).
"""

from __future__ import annotations

import collections
import json
import os
import statistics
import time
from typing import Any, Dict, List, Optional

from .events import EventLog

# Metric-vector entries the train step appends when health is on; the
# drain pops them before they reach PerfMetrics (model._drain_metrics).
HEALTH_METRIC_KEYS = ("nonfinite_loss", "nonfinite_grad", "grad_norm")

# Span names a straggler step can be attributed to.
ATTRIBUTABLE_SPANS = ("compile", "data_wait", "checkpoint_save",
                      "checkpoint_restore")

# Emission cap per finding kind — a run that goes NaN and stays NaN
# should not turn the trace into a firehose.
MAX_EVENTS_PER_KIND = 100


def enabled() -> bool:
    return os.environ.get("FF_HEALTH", "") not in ("", "0")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


# ----------------------------------------------------------------------
# heartbeat file (FF_HEARTBEAT_PATH)
# ----------------------------------------------------------------------

def heartbeat_path() -> str:
    """Heartbeat file path from the environment ('' = disabled).  The
    env is re-checked per call (one dict lookup) so tests and late
    exports behave predictably."""
    return os.environ.get("FF_HEARTBEAT_PATH", "")


def write_heartbeat(phase: str, step: Optional[int] = None,
                    **extra: Any) -> None:
    """Atomically rewrite the heartbeat file with the phase being
    ENTERED (so a wedge leaves the wedged phase's record on disk).
    No-op when ``FF_HEARTBEAT_PATH`` is unset; never raises."""
    path = heartbeat_path()
    if not path:
        return
    rec: Dict[str, Any] = {"phase": phase, "unix_time": time.time(),
                           "pid": os.getpid()}
    if step is not None:
        rec["step"] = int(step)
    rec.update(extra)
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, path)
    except OSError:
        pass


def read_heartbeat(path: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """Last heartbeat record, or None (missing file / disabled /
    corrupt — a kill can race the atomic replace's window)."""
    path = path or heartbeat_path()
    if not path:
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def describe_heartbeat(hb: Optional[Dict[str, Any]],
                       now: Optional[float] = None) -> Optional[str]:
    """One-line human summary: ``phase 'step' (step 42, 12s stale)``."""
    if not hb or "phase" not in hb:
        return None
    parts = []
    if "step" in hb:
        parts.append(f"step {hb['step']}")
    t = hb.get("unix_time")
    if isinstance(t, (int, float)):
        age = (now if now is not None else time.time()) - t
        if age >= 0:
            parts.append(f"{age:.0f}s stale")
    detail = f" ({', '.join(parts)})" if parts else ""
    return f"phase '{hb['phase']}'{detail}"


# ----------------------------------------------------------------------
# the monitor
# ----------------------------------------------------------------------

class HealthMonitor:
    """Per-model health interpreter, created at ``compile()`` when both
    telemetry and ``FF_HEALTH`` are on.  Registered as an EventLog
    observer for span bookkeeping; ``stepstats.timed_update`` drives
    ``on_step`` and ``model._drain_metrics`` drives ``on_drain``.

    ``model`` may be None for unit tests that feed steps directly (the
    sampled drain is skipped, everything else runs).
    """

    METRIC_KEYS = HEALTH_METRIC_KEYS

    def __init__(self, model, log: EventLog,
                 sample_every: Optional[int] = None,
                 straggler_k: Optional[float] = None,
                 wait_ratio: Optional[float] = None,
                 window: Optional[int] = None,
                 min_window: int = 5):
        self.model = model
        self.log = log
        self.sample_every = int(sample_every if sample_every is not None
                                else _env_float("FF_HEALTH_SAMPLE_EVERY", 16))
        self.straggler_k = float(straggler_k if straggler_k is not None
                                 else _env_float("FF_HEALTH_STRAGGLER_K", 3.0))
        self.wait_ratio = float(wait_ratio if wait_ratio is not None
                                else _env_float("FF_HEALTH_DATA_WAIT_RATIO",
                                                0.3))
        window = int(window if window is not None
                     else _env_float("FF_HEALTH_WINDOW", 64))
        self.min_window = min_window
        self._durs: collections.deque = collections.deque(maxlen=window)
        self._recent_spans: collections.deque = collections.deque(maxlen=64)
        self._last_step_end: Optional[float] = None
        self._steps_seen = 0
        # per-sampling-window accumulators
        self._window_step_s = 0.0
        self._window_wait_s = 0.0
        self._window_batches = 0
        self.counts: Dict[str, int] = {}

    # -- EventLog observer (span bookkeeping only, never emits) ---------
    def observe(self, rec: Dict[str, Any]) -> None:
        if rec.get("t") != "span":
            return
        name = rec.get("name")
        if name in ATTRIBUTABLE_SPANS:
            self._recent_spans.append(
                (name, float(rec.get("ts", 0.0)), float(rec.get("dur", 0.0))))
            if name == "data_wait":
                self._window_wait_s += float(rec.get("dur", 0.0))
                self._window_batches += 1

    # -- per-step hook (stepstats.timed_update) -------------------------
    def on_step(self, step_idx: int, start: float, dur: float,
                first: bool) -> None:
        """``start`` is in the log's relative clock domain
        (``EventLog.to_rel`` of the step's perf_counter t0)."""
        write_heartbeat("step", step=step_idx)
        prev_end = self._last_step_end
        self._last_step_end = start + dur
        if not first:
            self._window_step_s += dur
            if len(self._durs) >= self.min_window:
                p50 = statistics.median(self._durs)
                if p50 > 0 and dur > self.straggler_k * p50:
                    t0 = prev_end if prev_end is not None else start
                    self._emit("straggler", step=step_idx,
                               dur_ms=round(dur * 1e3, 3),
                               p50_ms=round(p50 * 1e3, 3),
                               ratio=round(dur / p50, 2),
                               attribution="+".join(
                                   self._attribute(t0, start + dur)))
            self._durs.append(dur)
        self._steps_seen += 1
        if self.sample_every > 0 and self._steps_seen % self.sample_every == 0:
            if self.model is not None:
                # forces the existing metric drain: the isfinite counts
                # riding the metric vector reach on_drain() below
                self.model._drain_metrics()
            self._check_starvation(step_idx)
            self._emit_agreement()

    def _attribute(self, t0: float, t1: float) -> List[str]:
        """Attributable spans overlapping (t0, t1) — the gap since the
        previous step's end through this step's end."""
        names = sorted({n for (n, ts, d) in self._recent_spans
                        if ts < t1 and ts + d > t0})
        return names or ["unknown"]

    # -- drain hook (model._drain_metrics) ------------------------------
    def on_drain(self, health_totals: Dict[str, float], steps: float,
                 step_idx: int) -> None:
        """Receives the health entries popped off the drained metric
        vector: counts of non-finite loss / grad-norm steps and the
        summed grad norm since the previous drain."""
        nf_loss = health_totals.get("nonfinite_loss", 0.0)
        nf_grad = health_totals.get("nonfinite_grad", 0.0)
        if nf_loss > 0:
            self._emit("nonfinite_loss", step=step_idx,
                       count=int(nf_loss), window_steps=int(steps))
        if nf_grad > 0:
            self._emit("nonfinite_grad", step=step_idx,
                       count=int(nf_grad), window_steps=int(steps))
        gsum = health_totals.get("grad_norm")
        if gsum is not None and steps > 0:
            self.log.gauge("grad_global_norm", round(gsum / steps, 6))

    def _check_starvation(self, step_idx: int) -> None:
        if self._window_step_s > 0 and self._window_batches > 0:
            ratio = self._window_wait_s / self._window_step_s
            if ratio > self.wait_ratio:
                self._emit("data_starvation", step=step_idx,
                           wait_s=round(self._window_wait_s, 4),
                           step_s=round(self._window_step_s, 4),
                           ratio=round(ratio, 3),
                           threshold=self.wait_ratio)
        self._window_step_s = 0.0
        self._window_wait_s = 0.0
        self._window_batches = 0

    def _emit_agreement(self) -> None:
        """Step-level predicted-vs-measured divergence, refreshed once
        per sampling window (agreement.py stored the prediction on the
        model at compile)."""
        if self.model is None or len(self._durs) < self.min_window:
            return
        from . import agreement

        agreement.emit_step_divergence(
            self.model, self.log, statistics.median(self._durs),
            len(self._durs))

    def _emit(self, kind: str, **attrs: Any) -> None:
        n = self.counts.get(kind, 0) + 1
        self.counts[kind] = n
        if n > MAX_EVENTS_PER_KIND:
            return
        if n == MAX_EVENTS_PER_KIND:
            attrs["suppressing_further"] = True
        self.log.event("health", kind=kind, **attrs)
        self.log.flush()
