"""Training metrics.

TPU-native analogue of the reference metrics layer (reference:
src/metrics_functions/metrics_functions.{cc,cu}, include/metrics_functions.h).

The reference accumulates a device-side ``PerfMetrics`` struct with atomics
per partition, then folds per-part futures on the CPU
(src/runtime/model.cc:1145-1167).  Here per-batch sums are computed inside
the jitted step (XLA reduces across the mesh — the analogue of the future
fold), returned as a small dict of scalars, and accumulated on host in a
``PerfMetrics`` whose ``print`` mirrors PerfMetrics::print
(metrics_functions.cc:44-70).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Sequence

import jax
import jax.numpy as jnp

LOG_MIN_VALUE = 1e-20


class MetricsType:
    ACCURACY = "accuracy"
    CATEGORICAL_CROSSENTROPY = "categorical_crossentropy"
    SPARSE_CATEGORICAL_CROSSENTROPY = "sparse_categorical_crossentropy"
    MEAN_SQUARED_ERROR = "mean_squared_error"
    ROOT_MEAN_SQUARED_ERROR = "root_mean_squared_error"
    MEAN_ABSOLUTE_ERROR = "mean_absolute_error"


@dataclasses.dataclass
class PerfMetrics:
    """Host-side running totals (reference: include/metrics_functions.h:25-39)."""

    train_all: int = 0
    train_correct: int = 0
    cce_loss: float = 0.0
    sparse_cce_loss: float = 0.0
    mse_loss: float = 0.0
    rmse_loss: float = 0.0
    mae_loss: float = 0.0

    def update(self, one: Dict[str, float]) -> None:
        self.train_all += int(one.get("train_all", 0))
        self.train_correct += int(one.get("train_correct", 0))
        self.cce_loss += float(one.get("cce_loss", 0.0))
        self.sparse_cce_loss += float(one.get("sparse_cce_loss", 0.0))
        self.mse_loss += float(one.get("mse_loss", 0.0))
        self.rmse_loss += float(one.get("rmse_loss", 0.0))
        self.mae_loss += float(one.get("mae_loss", 0.0))

    def reset(self) -> None:
        self.__init__()

    @property
    def accuracy(self) -> float:
        return self.train_correct * 100.0 / max(1, self.train_all)

    def to_string(self) -> str:
        out = "[Metrics]"
        if self.train_all > 0:
            out += (f" accuracy: {self.accuracy:.6f}% "
                    f"({self.train_correct} / {self.train_all})")
        if self.cce_loss > 0:
            out += f" categorical_crossentropy: {self.cce_loss / max(1, self.train_all):.6f}"
        if self.sparse_cce_loss > 0:
            out += (" sparse_categorical_crossentropy: "
                    f"{self.sparse_cce_loss / max(1, self.train_all):.6f}")
        if self.mse_loss > 0:
            out += f" mean_squared_error: {self.mse_loss / max(1, self.train_all):.6f}"
        if self.rmse_loss > 0:
            out += f" root_mean_squared_error: {self.rmse_loss / max(1, self.train_all):.6f}"
        if self.mae_loss > 0:
            out += f" mean_absolute_error: {self.mae_loss / max(1, self.train_all):.6f}"
        return out

    def print(self) -> None:
        print(self.to_string())


class Metrics:
    """Jit-side per-batch metric sums (reference compute kernels:
    metrics_functions.cu:57-175).  ``probs`` is the softmax output (or raw
    final activation when the model has no softmax); ``labels`` is int
    (B,)/(B,1) when ``sparse`` else one-hot/regression targets (B, C)."""

    def __init__(self, loss_type: str, metrics: Sequence[str]):
        self.metrics = list(metrics)
        self.sparse = "sparse" in loss_type
        self.loss_type = loss_type

    def compute(self, probs: jax.Array, labels: jax.Array) -> Dict[str, jax.Array]:
        probs = probs.astype(jnp.float32)
        if probs.ndim > 2:  # sequence outputs: per-token metrics
            probs = probs.reshape(-1, probs.shape[-1])
            labels = labels.reshape(probs.shape[0], -1) \
                if self.sparse else labels.reshape(probs.shape)
        batch, num_classes = probs.shape[0], probs.shape[-1]
        out: Dict[str, jax.Array] = {"train_all": jnp.int32(batch)}
        m = self.metrics
        if self.sparse:
            sl = labels.reshape(batch).astype(jnp.int32)
            if MetricsType.ACCURACY in m:
                pred = jnp.argmax(probs, axis=-1).astype(jnp.int32)
                out["train_correct"] = jnp.sum(pred == sl).astype(jnp.int32)
            if MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY in m:
                p = jnp.take_along_axis(probs, sl[:, None], axis=-1)
                out["sparse_cce_loss"] = jnp.sum(-jnp.log(jnp.maximum(p, LOG_MIN_VALUE)))
            if (MetricsType.MEAN_SQUARED_ERROR in m
                    or MetricsType.ROOT_MEAN_SQUARED_ERROR in m
                    or MetricsType.MEAN_ABSOLUTE_ERROR in m):
                onehot = jax.nn.one_hot(sl, num_classes, dtype=jnp.float32)
                diff = probs - onehot
                mse = jnp.sum(diff * diff, axis=-1)
                if MetricsType.MEAN_SQUARED_ERROR in m:
                    out["mse_loss"] = jnp.sum(mse)
                if MetricsType.ROOT_MEAN_SQUARED_ERROR in m:
                    out["rmse_loss"] = jnp.sum(jnp.sqrt(mse))
                if MetricsType.MEAN_ABSOLUTE_ERROR in m:
                    out["mae_loss"] = jnp.sum(jnp.abs(diff))
        else:
            labels = labels.astype(jnp.float32)
            if MetricsType.ACCURACY in m:
                if num_classes == 1:
                    # accuracy is meaningless for 1 output; reference returns
                    # 100% (metrics_functions.cu:121-126)
                    out["train_correct"] = jnp.int32(batch)
                else:
                    pred = jnp.argmax(probs, axis=-1)
                    true = jnp.argmax(labels, axis=-1)
                    out["train_correct"] = jnp.sum(pred == true).astype(jnp.int32)
            if MetricsType.CATEGORICAL_CROSSENTROPY in m:
                cce = -labels * jnp.log(jnp.maximum(probs, LOG_MIN_VALUE))
                out["cce_loss"] = jnp.sum(jnp.where(labels > 0.0, cce, 0.0))
            diff = probs - labels
            mse = jnp.sum(diff * diff, axis=-1)
            if MetricsType.MEAN_SQUARED_ERROR in m:
                out["mse_loss"] = jnp.sum(mse)
            if MetricsType.ROOT_MEAN_SQUARED_ERROR in m:
                out["rmse_loss"] = jnp.sum(jnp.sqrt(mse))
            if MetricsType.MEAN_ABSOLUTE_ERROR in m:
                out["mae_loss"] = jnp.sum(jnp.abs(diff))
        return out
