"""FFModel — the graph builder and training runtime.

TPU-native analogue of the reference core (reference: src/runtime/model.cc,
include/model.h:241-434).  The reference FFModel builds an op graph, then
``compile()`` resolves a per-op ``ParallelConfig`` strategy, creates Legion
regions/partitions, and the train loop issues index-task launches per op
with the mapper placing point tasks on GPUs.

Here the same graph compiles to **one fused, jitted SPMD train step**:

  * per-op strategies lower to ``with_sharding_constraint`` annotations on
    op outputs over a factored device mesh (parallel/mesh.py) — XLA GSPMD
    inserts all resharding/halo/gradient collectives over ICI, playing the
    role of Legion's implicit region movement;
  * the backward pass is ``jax.value_and_grad`` of the scalar loss (no
    per-op backward methods);
  * gradient replica aggregation (reference optimizer_kernel.cu:168-180)
    becomes the automatic psum of sharded-graph gradients;
  * the reference's Legion-trace replay (begin_trace/end_trace around the
    hot loop, e.g. examples/cpp/AlexNet/alexnet.cc:110-117) is subsumed by
    XLA compilation caching — every step after the first replays the same
    fused program.

The reference's 4-call driver API (``forward/zero_gradients/backward/
update``) is preserved: the calls stage work and the fused step executes at
``update()``; ``eval_*`` paths run a forward-only jit.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import time
import warnings
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from .config import DeviceType, FFConfig, ParallelConfig
from .initializers import DefaultWeightInitializer
from .losses import Loss, LossType
from .metrics import Metrics, MetricsType, PerfMetrics
from .ops.base import FwdCtx, Op
from .ops.conv2d import ActiMode, Conv2D, Pool2D, PoolType
from .ops.embedding import AggrMode, Embedding
from .ops.linear import Linear
from .ops.misc import (BatchNorm, Concat, Dropout, ElementBinary, ElementUnary,
                       Flat, MSELoss, Softmax)
from .parallel.mesh import Machine
from .parallel.strategy import load_strategies_from_file, save_strategies_to_file
from .tensor import DataType, Parameter, Tensor


class LayerHandle:
    """Deferred layer from the legacy v2 builder API (reference:
    examples/python/native/alexnet_new.py — declare with *_v2, then
    ``init_inout`` builds it onto the graph)."""

    def __init__(self, build):
        self._build = build

    def init_inout(self, ffmodel: "FFModel", input_tensor: Tensor) -> Tensor:
        return self._build(ffmodel, input_tensor)


def _copy_params_tree(tree):
    """Shallow per-op copy of a params-shaped tree so callers can swap
    individual weight leaves without mutating the caller's tree."""
    return {k: (dict(v) if isinstance(v, dict) else v)
            for k, v in tree.items()}


def _copy_state_tree(state):
    """Shallow copy of an optimizer-state tree (slot -> params-shaped
    subtree), one level deeper than ``_copy_params_tree``."""
    return {k: ({opn: dict(ws) for opn, ws in v.items()}
                if isinstance(v, dict) else v)
            for k, v in state.items()}


class FFModel:
    def __init__(self, config: Optional[FFConfig] = None):
        self.config = config or FFConfig()
        self._guid = itertools.count(100)  # reference op_global_guid starts at 100
        self.ops: List[Op] = []
        self.input_tensors: List[Tensor] = []
        self._constants: Dict[int, Any] = {}  # guid -> (Tensor, fill value)
        self._offload: Dict[Tuple[str, str], Any] = {}  # host-offloaded weights
        self._offload_warned = False
        self._pipe_host_drop_warned = False
        # Row-sparse host-resident embedding tables (reference:
        # embedding.cc CPU tasks touch only the batch's rows): op name ->
        # {"weight", "input", "input_key", "u_max"}
        self._host_embed: Dict[str, Dict[str, Any]] = {}
        self._host_idx: Dict[str, np.ndarray] = {}  # host copies of index batches
        # async scatter-back of host-table rows (one in-flight step):
        # update() dispatches and returns; the worker forces the row
        # arrays and writes them home; _he_join() is the read barrier
        self._he_pool = None
        self._he_pending = None
        self._he_version = 0  # bumps when host-table rows change
        self._he_dev_cache = None  # decode's device copy of host tables
        self._dp_cache = None      # decode's unpacked-pipe params tree
        self.label_tensor: Optional[Tensor] = None
        self.machine: Optional[Machine] = None
        self.optimizer = None
        self.loss: Optional[Loss] = None
        self.metrics: Optional[Metrics] = None
        self.current_metrics = PerfMetrics()
        self.last_loss: Optional[float] = None
        self._metric_acc = None
        self._params = None
        self._stats = None
        self._opt_state = None
        self._step_count = 0
        self._batch: Optional[Dict[str, Any]] = None
        self._staged = False
        self._train_step_fn = None
        self._eval_step_fn = None
        # Whole-graph lowering plan (parallel/lowering.GraphLowering);
        # None = per-op dispatch.  Resolved by _compile_impl.
        self._lowering = None
        self._fresh_jit = False  # next train-step build bypasses the
        #                          persistent compile cache (recompile)
        self._compiled = False
        self._pipeline_req = None
        self._pipeline_plan = None
        # Telemetry handles, resolved ONCE at compile() (observability/):
        # None when disabled, so the hot path pays a single attribute
        # check and makes zero event-log calls.
        self._telemetry = None
        self._stepstats = None
        # Health monitor (observability/health.py): non-None only when
        # FF_HEALTH rides an enabled telemetry log.
        self._health = None
        # In-training per-op attribution (observability/opprof.py):
        # non-None only when FF_OPPROF rides an enabled telemetry log.
        self._opprof = None
        # Memory & compile plane (observability/memplane.py): non-None
        # only when FF_MEMPLANE rides an enabled telemetry log — wraps
        # the jitted steps with an explicit compile cache that emits
        # compile_done / xla_memory / xla_cost and counts retraces.
        self._memplane = None
        # Fault injector (testing/chaos.py, FF_CHAOS) and non-finite
        # step guard (runtime/resilience.py, FF_SKIP_NONFINITE) — both
        # resolved once at compile(), None when their env knob is unset
        # so every choke point is a single attribute test.
        self._chaos = None
        self._nonfinite_guard = None
        # Simulator's predicted step seconds (observability/agreement.py,
        # set post-compile under telemetry) for sim_divergence events.
        self._predicted_step_s = None

    # ------------------------------------------------------------------
    # graph construction
    # ------------------------------------------------------------------
    def _next_op_guid(self) -> int:
        return next(self._guid)

    @property
    def compute_dtype(self):
        return jnp.bfloat16 if self.config.compute_dtype == "bfloat16" else jnp.float32

    def create_tensor(self, dims: Sequence[int], name: str = "",
                      dtype: str = DataType.FLOAT, nchw: bool = True) -> Tensor:
        """Create a graph input.  4-D dims are accepted in the reference's
        (N, C, H, W) order by default (include/model.h create_tensor<4>)
        and stored NHWC-native; pass ``nchw=False`` for native order."""
        dims = tuple(int(d) for d in dims)
        if len(dims) == 4 and nchw:
            n, c, h, w = dims
            dims = (n, h, w, c)
        t = Tensor(dims=dims, dtype=dtype, owner_op=None, name=name)
        self.input_tensors.append(t)
        return t

    def create_constant(self, dims: Sequence[int], value: float,
                        name: str = "", dtype: str = DataType.FLOAT,
                        nchw: bool = True) -> Tensor:
        """Graph-constant tensor filled with ``value`` (reference:
        FFModel::create_constant, exercised by tests/PCA/pca.cc:75-78).
        Materialized inside the traced graph, so XLA constant-folds it
        into consumers; it never appears in ``set_batch``."""
        dims = tuple(int(d) for d in dims)
        if len(dims) == 4 and nchw:
            n, c, h, w = dims
            dims = (n, h, w, c)
        t = Tensor(dims=dims, dtype=dtype, owner_op=None,
                   name=name or f"const_{len(self._constants)}")
        self._constants[t.guid] = (t, float(value))
        return t

    def _append(self, op: Op) -> Tensor:
        self.ops.append(op)
        return op.output

    # -- op vocabulary (reference: include/model.h:241-434) ------------
    def conv2d(self, input_tensor: Tensor, out_channels: int, kernel_h: int,
               kernel_w: int, stride_h: int, stride_w: int, padding_h: int,
               padding_w: int, activation: str = ActiMode.NONE,
               use_bias: bool = True, groups: int = 1,
               kernel_initializer=None, bias_initializer=None,
               *, share_with=None, name: Optional[str] = None) -> Tensor:
        return self._append(Conv2D(self, input_tensor, out_channels, kernel_h,
                                   kernel_w, stride_h, stride_w, padding_h,
                                   padding_w, activation, use_bias, groups,
                                   kernel_initializer, bias_initializer,
                                   share_with, name))

    def pool2d(self, input_tensor: Tensor, kernel_h: int, kernel_w: int,
               stride_h: int, stride_w: int, padding_h: int, padding_w: int,
               pool_type: str = PoolType.MAX, activation: str = ActiMode.NONE,
               name: Optional[str] = None) -> Tensor:
        return self._append(Pool2D(self, input_tensor, kernel_h, kernel_w,
                                   stride_h, stride_w, padding_h, padding_w,
                                   pool_type, activation, name))

    def dense(self, input_tensor: Tensor, out_dim: int,
              activation: str = ActiMode.NONE, use_bias: bool = True,
              kernel_initializer=None, bias_initializer=None,
              *, share_with=None, name: Optional[str] = None) -> Tensor:
        return self._append(Linear(self, input_tensor, out_dim, activation,
                                   use_bias, kernel_initializer,
                                   bias_initializer, share_with, name))

    linear = dense

    def embedding(self, input_tensor: Tensor, num_entries: int, out_dim: int,
                  aggr: str = AggrMode.SUM, kernel_initializer=None,
                  share_with=None, name: Optional[str] = None) -> Tensor:
        return self._append(Embedding(self, input_tensor, num_entries, out_dim,
                                      aggr, kernel_initializer, share_with, name))

    def lstm(self, input_tensor: Tensor, hidden_size: int, hx: Optional[Tensor] = None,
             cx: Optional[Tensor] = None, share_with=None,
             name: Optional[str] = None):
        """Sequence LSTM (B,T,E)→(B,T,H); returns (y, h_T, c_T) tensors.
        Reference: nmt/lstm.cu chunk op + SharedVariable weight sharing."""
        from .ops.lstm import LSTM

        op = LSTM(self, input_tensor, hidden_size, hx, cx, share_with, name)
        self.ops.append(op)
        return op.outputs[0], op.outputs[1], op.outputs[2]

    def multihead_attention(self, query: Tensor, key: Optional[Tensor] = None,
                            value: Optional[Tensor] = None,
                            embed_dim: Optional[int] = None, num_heads: int = 8,
                            causal: bool = False, dropout: float = 0.0,
                            use_bias: bool = False, kernel_initializer=None,
                            seq_parallel_mode: str = "ring",
                            name: Optional[str] = None) -> Tensor:
        """Multi-head attention (B,S,E)→(B,S,E); self-attention when key/
        value are omitted.  Sequence-dim partition degrees in this op's
        strategy lower to ring attention over ICI (parallel/sequence.py)."""
        from .ops.attention import MultiHeadAttention

        key = key if key is not None else query
        value = value if value is not None else key
        embed_dim = embed_dim if embed_dim is not None else query.dims[-1]
        return self._append(MultiHeadAttention(
            self, query, key, value, embed_dim, num_heads, causal, dropout,
            use_bias, kernel_initializer, seq_parallel_mode, name))

    def layer_norm(self, input_tensor: Tensor, eps: float = 1e-5,
                   elementwise_affine: bool = True,
                   name: Optional[str] = None) -> Tensor:
        from .ops.attention import LayerNorm

        return self._append(LayerNorm(self, input_tensor, eps,
                                      elementwise_affine, name))

    def concat(self, tensors: Sequence[Tensor], axis: int,
               name: Optional[str] = None) -> Tensor:
        # Reference axis is in NCHW logical order (concat.cu); convert the
        # channel axis for 4-D tensors to the native NHWC position.
        if tensors[0].num_dims == 4:
            axis = {0: 0, 1: 3, 2: 1, 3: 2}[axis]
        return self._append(Concat(self, tensors, axis, name))

    def flat(self, input_tensor: Tensor, name: Optional[str] = None) -> Tensor:
        return self._append(Flat(self, input_tensor, name))

    def softmax(self, input_tensor: Tensor, name: Optional[str] = None) -> Tensor:
        return self._append(Softmax(self, input_tensor, name))

    def batch_norm(self, input_tensor: Tensor, relu: bool = True,
                   name: Optional[str] = None) -> Tensor:
        return self._append(BatchNorm(self, input_tensor, relu, name))

    # -- legacy "v2" declare-then-wire builders (reference:
    # python/flexflow/core used by examples/python/native/alexnet_new.py:
    # conv2d_v2(...) declares a layer handle, init_inout() wires it) -----
    def conv2d_v2(self, name: str, in_channels: int, out_channels: int,
                  kernel_h: int, kernel_w: int, stride_h: int, stride_w: int,
                  padding_h: int, padding_w: int,
                  activation: str = ActiMode.NONE,
                  use_bias: bool = True) -> "LayerHandle":
        def build(ff, t):
            if t.dims[3] != in_channels:  # NHWC
                raise ValueError(
                    f"{name}: declared in_channels={in_channels}, "
                    f"wired onto a {t.dims[3]}-channel tensor")
            return ff.conv2d(t, out_channels, kernel_h, kernel_w, stride_h,
                             stride_w, padding_h, padding_w,
                             activation=activation, use_bias=use_bias,
                             name=name)
        return LayerHandle(build)

    def pool2d_v2(self, name: str, kernel_h: int, kernel_w: int,
                  stride_h: int, stride_w: int, padding_h: int,
                  padding_w: int, pool_type: str = PoolType.MAX) -> "LayerHandle":
        return LayerHandle(lambda ff, t: ff.pool2d(
            t, kernel_h, kernel_w, stride_h, stride_w, padding_h, padding_w,
            pool_type=pool_type, name=name))

    def dense_v2(self, name: str, in_dim: int, out_dim: int,
                 activation: str = ActiMode.NONE,
                 use_bias: bool = True) -> "LayerHandle":
        def build(ff, t):
            if t.dims[-1] != in_dim:
                raise ValueError(f"{name}: declared in_dim={in_dim}, wired "
                                 f"onto a {t.dims[-1]}-wide tensor")
            return ff.dense(t, out_dim, activation=activation,
                            use_bias=use_bias, name=name)
        return LayerHandle(build)

    def flat_v2(self, name: str) -> "LayerHandle":
        return LayerHandle(lambda ff, t: ff.flat(t, name=name))

    def dropout(self, input_tensor: Tensor, rate: float, seed: int = 0,
                name: Optional[str] = None) -> Tensor:
        return self._append(Dropout(self, input_tensor, rate, seed, name))

    def pipeline_mlp(self, input_tensor: Tensor, num_stages: int,
                     num_microbatches: int = 4, activation: str = "relu",
                     name: Optional[str] = None) -> Tensor:
        """Stack of identical dense stages pipelined over config dim 1
        (GPipe microbatching; the SOAP Operator-dimension analogue of the
        reference's per-op GPU placement, nmt/nmt.cc:269-308)."""
        from .ops.pipeline import PipelineMLP
        return self._append(PipelineMLP(self, input_tensor, num_stages,
                                        num_microbatches, activation, name))

    def expert_mlp(self, input_tensor: Tensor, num_experts: int,
                   hidden_size: int, capacity_factor: float = 1.25,
                   activation: str = "relu",
                   name: Optional[str] = None) -> Tensor:
        """Switch-style MoE layer; config dim 1 is the EXPERT-parallel
        degree (expert weights shard over it, GSPMD emits the token
        all_to_all) — the SOAP hook SURVEY §2.3 marks as design headroom
        over the reference."""
        from .ops.moe import ExpertMLP
        return self._append(ExpertMLP(self, input_tensor, num_experts,
                                      hidden_size, capacity_factor,
                                      activation, name))

    def mse_loss(self, logits: Tensor, labels: Tensor,
                 reduction: str = "average", name: Optional[str] = None) -> Tensor:
        return self._append(MSELoss(self, logits, labels, reduction, name))

    # ------------------------------------------------------------------
    # general pipeline parallelism (operator placement)
    # ------------------------------------------------------------------
    def set_pipeline(self, num_stages: Optional[int] = None,
                     stages: Optional[Sequence[Sequence[str]]] = None,
                     num_microbatches: int = 4,
                     degree: Optional[int] = None,
                     dp_degree: int = 1,
                     remat: Optional[bool] = None) -> None:
        """Assign the op graph to pipeline stages (operator placement).

        The reference pipelines heterogeneous graphs by pinning each op to
        a GPU list (nmt/nmt.cc:269-308 pins encoder ops to one GPU set and
        decoder ops to another; src/mapper/mapper.cc:33-146 places the
        point tasks).  Here each stage is a contiguous run of ops executed
        by one slice of the mesh's pipe axes, with activations crossing
        stage boundaries over a ppermute ring under a GPipe microbatch
        schedule (parallel/pipeline.py pipeline_graph_apply).

        ``stages``: explicit op-name lists (contiguous partition of the
        graph), or ``num_stages`` to auto-balance the chain by per-op
        FLOPs.  ``degree``: ring size (defaults to num_stages; must divide
        it).  ``dp_degree``: batch-parallel degree composed with the
        pipeline (dp x pp).  ``remat``: rematerialize each ring slot so
        only boundary carries are stashed across the scan — the memory
        lever that lets ``num_microbatches`` grow and shrink the GPipe
        bubble fraction (defaults to ``config.remat``; see
        docs/ADR-002-pipeline-schedule.md).  Call before ``compile()``.
        """
        if stages is None:
            assert num_stages is not None and num_stages >= 1
            self._pipeline_req = {"num_stages": int(num_stages), "names": None}
        else:
            self._pipeline_req = {"num_stages": len(stages),
                                  "names": [list(g) for g in stages]}
        self._pipeline_req.update(num_microbatches=int(num_microbatches),
                                  degree=degree, dp_degree=int(dp_degree),
                                  remat=remat)

    def _plan_pipeline(self) -> None:
        """Resolve ``set_pipeline`` into a validated stage plan.

        The pipelined segment is the whole graph, minus a trailing Softmax
        (kept outside so the loss can read the pre-softmax logits).  Each
        stage must consume only tensors produced inside itself, the single
        boundary tensor from the previous stage, or graph constants.
        """
        self._pipeline_plan = None
        req = getattr(self, "_pipeline_req", None)
        if req is None:
            return
        seg = list(self.ops)
        tail: List[Op] = []
        while seg and isinstance(seg[-1], Softmax):
            tail.insert(0, seg.pop())
        # Host-placed row-sparse embeddings run BEFORE the ring as a
        # heterogeneous head (the reference's hetero DLRM: CPU-resident
        # tables + accelerator pipeline, dlrm_strategy_hetero.cc) —
        # packing a host table into the device pipe buffer would
        # silently drop the CPU placement.  Eligible embeddings depend
        # only on graph inputs, so hoisting is always legal; their
        # outputs feed stage 0 like extra segment inputs.
        head: List[Op] = []
        kept: List[Op] = []
        for op in seg:
            # the STRICT runtime predicate: hoisting an op the runtime
            # would not actually execute row-sparse (e.g. a shared index
            # consumed by a device-placed sibling) would exclude it from
            # the ring for no benefit and stream its full table
            if (isinstance(op, Embedding) and op.pc.host_placed
                    and self._sparse_embed_ok(op)):
                head.append(op)
            else:
                kept.append(op)
        seg = kept
        if not seg:
            raise ValueError("pipeline: no ops to pipeline")
        head_names = {op.name for op in head}
        if req["names"] is not None:
            by_name = {op.name: op for op in seg}
            stages = []
            for group in req["names"]:
                g = [by_name[n] for n in group if n not in head_names]
                if g:
                    stages.append(g)
            flat = [op for g in stages for op in g]
            if flat != seg:
                raise ValueError(
                    "pipeline stages must be a contiguous in-order "
                    "partition of the op graph (minus a trailing Softmax "
                    "and host-placed row-sparse embeddings)")
        else:
            from .parallel.pipeline_plan import balanced_stages

            stages = balanced_stages(seg, req["num_stages"])
        S = len(stages)

        # Dataflow plan FIRST (structural errors surface regardless of
        # whether a ring is expressible): each hop carries the k tensors
        # later stages still need (branching graphs, skip connections and
        # multi-input stage 0 welcome).  Shared with the stage-assignment
        # search so it never recommends a plan this planner would reject.
        from .parallel.pipeline_plan import plan_boundaries

        seg_ins, boundaries = plan_boundaries(
            stages, tail, set(self._constants.keys()),
            list(self.input_tensors) + [op.output for op in head])
        final_out = stages[-1][-1].output

        import math
        degree = req["degree"] if req["degree"] else S
        degree = math.gcd(degree, S)
        # Ring size must also be expressible over the mesh axes left after
        # the dp group (e.g. degree 3 can't factor over a 2^k mesh).
        while degree > 1:
            try:
                self.machine.axes_for_degrees([req["dp_degree"], degree])
                break
            except ValueError:
                degree = max(d for d in range(1, degree)
                             if S % d == 0 and degree % d == 0)
        if degree <= 1 or self.machine.num_devices <= 1:
            # No expressible ring: keep the ops' regular (data-parallel)
            # configs rather than forcing no-split placeholders — a
            # silently replicated segment would be a large perf
            # regression versus not pipelining at all.
            if self.machine.num_devices > 1:
                print(f"flexflow_tpu: pipeline degree for {S} stages not "
                      f"expressible over mesh "
                      f"{dict(zip(self.machine.axis_names, self.machine.axis_sizes))}"
                      f"; running without pipelining")
            return
        # warn only once the plan actually commits — bailing out above
        # (inexpressible ring) keeps every placement intact
        for op in seg:
            if op.pc.host_placed and not self._pipe_host_drop_warned:
                self._pipe_host_drop_warned = True
                print(f"flexflow_tpu: host placement for {op.name} is "
                      f"DROPPED inside the pipeline segment (stage "
                      f"weights pack into the device ring buffer); only "
                      f"row-sparse-eligible embeddings run host-side "
                      f"ahead of the ring")
        self._pipeline_plan = {
            "stages": stages, "head": head, "degree": int(degree),
            "dp_degree": int(req["dp_degree"]),
            "num_microbatches": int(req["num_microbatches"]),
            "remat": bool(self.config.remat if req.get("remat") is None
                          else req["remat"]),
            "seg_ins": seg_ins, "boundaries": boundaries,
            "seg_in_guids": {t.guid for t in seg_ins},
            "seg_out": final_out,
            "i0": self.ops.index(stages[0][0]),
            "i1": self.ops.index(stages[-1][-1]) + 1,
        }
        self._pipeline_plan["pack"] = self._plan_pipeline_pack(
            stages, int(degree))
        # Pipelined ops execute inside the pipeline's shard_map: force
        # their configs to no-split so op forwards take the plain jnp path
        # (no nested shard_map) and their weights replicate over the mesh.
        for g in stages:
            for op in g:
                if op.init_stats():
                    raise ValueError(
                        f"pipeline: op {op.name} carries running stats "
                        f"(e.g. BatchNorm) — unsupported inside a pipeline")
                op.pc = ParallelConfig(dims=(1,) * op.output.num_dims)

    def _plan_pipeline_pack(self, stages, ring: int):
        """Stage-weight placement layout: pack each ring slot's weights
        into one row of a (ring, width) float32 buffer sharded over the
        pipe axes, so an S-slot pipeline stores ~1/S of the segment's
        weights per device — the analogue of the reference mapper placing
        each op's weights only on its assigned GPUs
        (src/mapper/mapper.cc:33-146).  Weights shared across slots or
        with ops outside the segment stay replicated (excluded).

        Returns {"entries": {param_key: {wname: (slot, off, shape, n)}},
        "ring": ring, "width": W} or None when nothing is packable.
        """
        S = len(stages)
        k = S // ring
        seg_ops = [op for g in stages for op in g]
        key_slot: Dict[str, int] = {}
        conflict = set()
        for si, g in enumerate(stages):
            r = si // k
            for op in g:
                owner = op.share_from if op.share_from is not None else op
                if not owner.weights:
                    continue
                pk = op.param_key
                if pk in key_slot and key_slot[pk] != r:
                    conflict.add(pk)
                key_slot.setdefault(pk, r)
        seg_ids = {id(op) for op in seg_ops}
        for op in self.ops:
            if id(op) not in seg_ids and op.param_key in key_slot:
                conflict.add(op.param_key)
        slot_off = [0] * ring
        entries: Dict[str, Dict[str, tuple]] = {}
        for op in seg_ops:  # graph order: deterministic offsets
            owner = op.share_from if op.share_from is not None else op
            pk = op.param_key
            if (not owner.weights or pk in conflict or pk in entries
                    or pk not in key_slot):
                continue
            if any(w.dtype != "float32" for w in owner.weights):
                continue  # packing assumes one buffer dtype
            r = key_slot[pk]
            emap = {}
            for w in owner.weights:
                n = int(np.prod(w.dims))
                emap[w.name] = (r, slot_off[r], tuple(w.dims), n)
                slot_off[r] += n
            entries[pk] = emap
        width = max(slot_off) if entries else 0
        if width == 0:
            return None
        return {"entries": entries, "ring": ring, "width": width}

    def _pipe_pack(self):
        plan = getattr(self, "_pipeline_plan", None)
        return plan.get("pack") if plan else None

    # Pack-entry layout (slot, off, shape, n) read/write in one place.
    @staticmethod
    def _pack_read(buf_row, entry):
        _, off, shape, n = entry
        return buf_row[off:off + n].reshape(shape)

    @staticmethod
    def _pack_write(buf, entry, value):
        r, off, _, n = entry
        return buf.at[r, off:off + n].set(value.reshape(-1))

    @staticmethod
    def _pack_write_host(np_buf, entry, value):
        """In-place numpy twin of _pack_write (checkpoint assembly)."""
        r, off, _, n = entry
        np_buf[r, off:off + n] = np.asarray(value).reshape(-1)

    def _pipe_buffer_sharding(self) -> NamedSharding:
        plan = self._pipeline_plan
        groups = self.machine.axes_for_degrees(
            [plan["dp_degree"], plan["degree"]])
        paxes = groups[1]
        return NamedSharding(
            self.machine.mesh,
            PartitionSpec(paxes if len(paxes) > 1 else paxes[0]))

    # -- k-tensor ring-payload bundles (branching pipeline graphs) -----
    @staticmethod
    def _bundle_layout(tensors, pdtype):
        """[(tensor, offset, per-sample flat n, lanes)] + total width.

        The payload rides the compute dtype.  int32 tensors BITCAST in
        exactly: one f32 lane each on a float32 payload, two 16-bit
        lanes each on a bfloat16 payload — never a lossy value cast, and
        no f32 fallback doubling every hop's bandwidth for one token-id
        input (lax.bitcast has a zero JVP, so autodiff treats indices as
        the non-differentiable data they are)."""
        two_lane = jnp.dtype(pdtype).itemsize == 2
        layout, off = [], 0
        for t in tensors:
            n = int(np.prod(t.dims[1:])) if len(t.dims) > 1 else 1
            lanes = n * (2 if two_lane and "int" in t.dtype else 1)
            layout.append((t, off, n, lanes))
            off += lanes
        return layout, max(off, 1)

    @staticmethod
    def _bundle_pack(env, layout, pdtype):
        """Pack boundary tensors into one (B, width) payload."""
        parts = []
        for t, _, n, lanes in layout:
            v = env[t.guid]
            v = v.reshape(v.shape[0], n)
            if "int" in t.dtype:
                v = jax.lax.bitcast_convert_type(v.astype(jnp.int32),
                                                 pdtype)
                v = v.reshape(v.shape[0], lanes)  # (B,n,2)->(B,2n) on bf16
            parts.append(v.astype(pdtype))
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts, 1)

    def _bundle_unpack(self, h, layout, pdtype):
        cdtype = self.compute_dtype
        env = {}
        for t, off, n, lanes in layout:
            v = h[:, off:off + lanes]
            if "int" in t.dtype:
                if lanes != n:  # two 16-bit lanes per int32
                    v = v.reshape(v.shape[0], n, 2)
                v = jax.lax.bitcast_convert_type(v.astype(pdtype), jnp.int32)
            else:
                v = v.astype(cdtype)
            env[t.guid] = v.reshape((h.shape[0],) + tuple(t.dims[1:]))
        return env

    def _stage_fn(self, stage_ops: List[Op], in_layout, out_layout,
                  pdtype):
        const_items = list(self._constants.values())
        pack = self._pipe_pack()

        def resolve(params, op):
            """Op weights: packed stage-local slice of the pipe buffer
            (this device's row of the (ring, W) buffer — inside the
            shard_map the local view is (1, W)), else the plain tree."""
            pk = op.param_key
            if pack and pk in pack["entries"]:
                local = params["_pipe"]["buffer"].reshape(-1)
                return {wn: FFModel._pack_read(local, e)
                        for wn, e in pack["entries"][pk].items()}
            return params.get(pk, {})

        def fn(params, h, ctx, micro_idx):
            # Per-microbatch RNG stream: without the fold, every
            # microbatch (and dp shard) would reuse one dropout mask.
            rng = (jax.random.fold_in(ctx.rng, micro_idx)
                   if ctx.rng is not None else None)
            mctx = FwdCtx(training=ctx.training, rng=rng,
                          stats_in=ctx.stats_in, stats_out=ctx.stats_out)
            env = self._bundle_unpack(h, in_layout, pdtype)
            for t, val in const_items:
                fill_dtype = jnp.int32 if "int" in t.dtype \
                    else self.compute_dtype
                env[t.guid] = jnp.full(t.dims, val, fill_dtype)
            for op in stage_ops:
                xs = [env[t.guid] for t in op.inputs]
                ys = op.forward(resolve(params, op), xs, mctx)
                for t, y in zip(op.outputs, ys):
                    env[t.guid] = y
            return self._bundle_pack(env, out_layout, pdtype)

        return fn

    def _run_pipeline_segment(self, params, env, ctx):
        from .parallel.pipeline import pipeline_graph_apply

        plan = self._pipeline_plan
        stages = plan["stages"]
        seg_ins, boundaries = plan["seg_ins"], plan["boundaries"]
        seg_out = plan["seg_out"]
        pdtype = self.compute_dtype  # ints bitcast in (see _bundle_layout)
        in_bundles = [list(seg_ins)] + [list(h) for h in boundaries]
        out_bundles = [list(h) for h in boundaries] + [[seg_out]]
        fns, in_shapes, out_shapes = [], [], []
        in0_layout = None
        for si, g in enumerate(stages):
            in_l, n_in = self._bundle_layout(in_bundles[si], pdtype)
            out_l, n_out = self._bundle_layout(out_bundles[si], pdtype)
            if si == 0:
                in0_layout = in_l
            f = self._stage_fn(g, in_l, out_l, pdtype)
            fns.append(lambda p, h, mi, f=f: f(p, h, ctx, mi))
            in_shapes.append((n_in,))
            out_shapes.append((n_out,))
        x = self._bundle_pack(env, in0_layout, pdtype)
        groups = self.machine.axes_for_degrees(
            [plan["dp_degree"], plan["degree"]])
        batch_axes = groups[0] if groups[0] else None
        pipe_axes = groups[1]
        # Per-shard microbatch count (the shard_map body sees the batch
        # after dp sharding).
        local_b = x.shape[0] // max(1, plan["dp_degree"])
        mb = min(plan["num_microbatches"], local_b)
        while local_b % mb != 0:
            mb -= 1
        seg_params = {op.param_key: params[op.param_key]
                      for g in stages for op in g if op.param_key in params}
        param_specs = None
        pack = self._pipe_pack()
        if pack:
            seg_params["_pipe"] = params["_pipe"]
            param_specs = {k: jax.tree.map(lambda _: PartitionSpec(), v)
                           for k, v in seg_params.items()}
            param_specs["_pipe"] = {
                "buffer": self._pipe_buffer_sharding().spec}
        y = pipeline_graph_apply(fns, seg_params, x, self.machine.mesh,
                                 pipe_axes, mb, in_shapes, out_shapes,
                                 batch_axes=batch_axes,
                                 param_specs=param_specs,
                                 remat=plan.get("remat", False))
        out_l, _ = self._bundle_layout([seg_out], pdtype)
        return self._bundle_unpack(y.reshape(x.shape[0], -1),
                                   out_l, pdtype)[seg_out.guid]

    def _unary(self, op_name, x, name=None):
        return self._append(ElementUnary(self, x, op_name, name))

    def exp(self, x, name=None):
        return self._unary("exp", x, name)

    def relu(self, x, name=None):
        return self._unary("relu", x, name)

    def sigmoid(self, x, name=None):
        return self._unary("sigmoid", x, name)

    def tanh(self, x, name=None):
        return self._unary("tanh", x, name)

    def elu(self, x, name=None):
        return self._unary("elu", x, name)

    def _binary(self, op_name, x, y, name=None):
        return self._append(ElementBinary(self, x, y, op_name, name))

    def add(self, x, y, name=None):
        return self._binary("add", x, y, name)

    def subtract(self, x, y, name=None):
        return self._binary("subtract", x, y, name)

    def multiply(self, x, y, name=None):
        return self._binary("multiply", x, y, name)

    def divide(self, x, y, name=None):
        return self._binary("divide", x, y, name)

    # ------------------------------------------------------------------
    # compile
    # ------------------------------------------------------------------
    def compile(self, optimizer=None, loss_type: str = LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                metrics: Sequence[str] = (MetricsType.ACCURACY,),
                machine: Optional[Machine] = None) -> None:
        """Resolve strategies, build the mesh, stage the jitted SPMD step.

        Mirrors FFModel::compile (src/runtime/model.cc:986-1046): optional
        strategy import / search, per-op partition resolution, label tensor
        creation, optimizer wiring.

        Telemetry (observability/) is resolved here — the one place a
        model learns whether ``FFConfig.telemetry`` / ``FF_TELEMETRY`` is
        set — so every later step guards on a plain ``None`` handle.
        """
        from .observability import events as _ff_events
        from .observability import health as _ff_health
        from .runtime import resilience as _ff_resilience
        from .testing import chaos as _ff_chaos

        # Heartbeat is independent of telemetry (stdlib; no-op unless
        # FF_HEARTBEAT_PATH is set): an external watchdog can name a
        # wedged compile even on an untraced run.
        _ff_health.write_heartbeat("compile")
        self._telemetry = _ff_events.for_config(self.config)
        # Chaos + the non-finite guard are independent of telemetry
        # (recovery must work on untraced runs; events are narration).
        self._chaos = _ff_chaos.from_env()
        _nf = _ff_resilience.nonfinite_limit()
        self._nonfinite_guard = (
            _ff_resilience.NonFiniteGuard(self, _nf, self._telemetry)
            if _nf else None)
        if self._telemetry is None:
            self._stepstats = None
            self._health = None
            self._opprof = None
            self._memplane = None
            return self._compile_impl(optimizer, loss_type, metrics, machine)
        from .observability.reqtrace import run_trace_id as _ff_run_trace

        with self._telemetry.span(
                "compile", num_ops=len(self.ops),
                trace_id=_ff_run_trace(self._telemetry.run_id)) as at:
            self._compile_impl(optimizer, loss_type, metrics, machine)
            at["num_devices"] = self.machine.num_devices
            at["batch_size"] = self.config.batch_size
        from .observability.stepstats import StepStats

        self._stepstats = StepStats(self, self._telemetry)
        if _ff_health.enabled():
            self._health = _ff_health.HealthMonitor(self, self._telemetry)
            self._telemetry.add_observer(self._health.observe)
        else:
            self._health = None
        from .observability import metrics as _ff_metrics
        from .observability import opprof as _ff_opprof

        # Live metrics plane (FF_METRICS_PORT) + in-training per-op
        # attribution (FF_OPPROF) — both None-handle gated like health.
        _ff_metrics.maybe_start(self._telemetry)
        self._opprof = _ff_opprof.maybe_profiler(self, self._telemetry)
        from .observability import agreement as _ff_agreement
        from .observability import memplane as _ff_memplane

        _ff_agreement.emit_compile_prediction(self, self._telemetry)
        # Memory plane: the predicted view (one event, every telemetry
        # run) + the FF_MEMPLANE-gated compile observatory.
        self._memplane = _ff_memplane.maybe_plane(self._telemetry)
        _ff_memplane.emit_memory_prediction(self, self._telemetry)
        self._telemetry.flush()

    def _compile_impl(self, optimizer=None,
                      loss_type: str = LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                      metrics: Sequence[str] = (MetricsType.ACCURACY,),
                      machine: Optional[Machine] = None) -> None:
        cfg = self.config
        self.optimizer = optimizer
        self.loss = Loss(loss_type)
        self.metrics = Metrics(self.loss.loss_type, list(metrics))
        if machine is not None:
            self.machine = machine
        elif cfg.num_nodes > 1 or jax.process_count() > 1:
            # Multi-host: hybrid ICI×DCN mesh with the DCN axis leading
            # (parallel/distributed.py) — the GASNet-multi-node analogue.
            from .parallel.distributed import hybrid_machine
            self.machine = hybrid_machine(
                dcn_degree=max(cfg.num_nodes, jax.process_count()))
        else:
            self.machine = Machine(num_devices=min(
                cfg.num_devices, len(jax.devices())))

        if cfg.import_strategy_file:
            cfg.strategies.update(load_strategies_from_file(
                cfg.import_strategy_file,
                reference_order=cfg.import_strategy_reference_order))
        if cfg.search_budget > 0:
            # Native C++ annealing engine when built, Python MCMC otherwise
            # (reference: compile() launches STRATEGY_SEARCH_TASK,
            # model.cc:991-999).  Both engines must search the REAL
            # machine (self.machine, already clamped to this backend)
            # with the same overlap objective.
            from .simulator.machine import TPUMachineModel
            from .simulator.native_search import native_mcmc_search

            mm = TPUMachineModel.calibrated(num_devices=self.machine.num_devices)
            best = None
            if cfg.search_engine == "population":
                from .simulator.population import population_search

                best = population_search(self, budget=cfg.search_budget,
                                         alpha=cfg.search_alpha,
                                         machine_model=mm, seed=cfg.seed,
                                         verbose=False)
            elif cfg.search_engine not in ("", "mcmc", "native"):
                raise ValueError(
                    f"unknown search_engine {cfg.search_engine!r} "
                    "(expected '', 'native', 'mcmc', or 'population')")
            if best is None and cfg.search_engine in ("", "native"):
                r = native_mcmc_search(self, budget=cfg.search_budget,
                                       alpha=cfg.search_alpha,
                                       machine_model=mm,
                                       seed=cfg.seed,
                                       overlap=cfg.search_overlap_backward_update,
                                       verbose=False)
                if r is not None:
                    best = r[0]
            if best is None:
                from .simulator.search import mcmc_search

                best = mcmc_search(self, budget=cfg.search_budget,
                                   alpha=cfg.search_alpha, machine_model=mm,
                                   seed=cfg.seed)
            cfg.strategies.update(best)
            # Both engines return a SearchResult carrying the simulated
            # cost of the plan they just found — keep it for the
            # provenance sidecar (and the pipeline comparison below)
            # instead of re-simulating.
            self._search_provenance = {
                "engine": getattr(best, "engine", "mcmc"),
                "budget": cfg.search_budget,
                "seed": cfg.seed,
                "best_s": getattr(best, "best_s", None),
                "dp_s": getattr(best, "dp_s", None),
                "machine_model": mm,
                # population engine: per-chain stats + learned-tier CV
                # provenance ride into the exported sidecar
                "search_stats": getattr(best, "stats", None),
            }

            # Stage-assignment search (--search-pipeline): when a GPipe
            # plan beats the best dim strategy AND the user hasn't placed
            # stages by hand, apply it — operator placement discovered by
            # the search, not just by the user (the reference's searched
            # space and placement are one mechanism, mapper.cc:33-146).
            if (cfg.search_pipeline
                    and getattr(self, "_pipeline_req", None) is None):
                from .simulator.pipeline_search import search_pipeline

                dims_t = getattr(best, "best_s", None)
                if dims_t is None:
                    from .simulator.cost_model import CostModel
                    from .simulator.simulator import Simulator

                    sim = Simulator(mm, CostModel(
                        mm, measure=False, compute_dtype=cfg.compute_dtype))
                    dims_t = sim.simulate_runtime(self, dict(best))
                plan = search_pipeline(self, machine_model=mm)
                if plan is not None and plan["simulated_s"] < dims_t:
                    print(f"flexflow_tpu: search selected a pipeline plan "
                          f"({plan['num_stages']} stages x "
                          f"dp{plan['dp_degree']}, "
                          f"M={plan['num_microbatches']}"
                          f"{', remat' if plan.get('remat') else ''}): "
                          f"{plan['simulated_s'] * 1e3:.3f} ms vs "
                          f"{dims_t * 1e3:.3f} ms for the dim strategy")
                    self.set_pipeline(
                        num_stages=plan["num_stages"],
                        dp_degree=plan["dp_degree"],
                        num_microbatches=plan["num_microbatches"],
                        remat=plan.get("remat"))

        # Per-op partition configs (default: data parallel over all devices,
        # reference model.cc:391-401 + strategy.cc:28-85 fallback).
        nd = self.machine.num_devices
        for op in self.ops:
            pc = cfg.find_parallel_config(op.output.num_dims, op.name)
            if pc.num_parts() > nd:
                pc = ParallelConfig.data_parallel(op.output.num_dims, nd)
            op.pc = self._legalize_pc(op, pc)

        # Resolve operator placement (general pipeline parallelism) —
        # overrides the pipelined ops' configs with no-split placeholders.
        self._plan_pipeline()

        # Whole-graph lowering (parallel/lowering.py): resolve the knob
        # (FFConfig.lowered > FF_LOWERED > auto-on for multi-node runs,
        # loud on garbage) and precompute each op's logical-axis sharding
        # spec.  None = today's per-op dispatch; the step builders below
        # route constraints and jit through the plan when it's set.
        from .parallel import lowering as _ff_lowering
        self._lowering = _ff_lowering.maybe_lowering(self)

        # Fused Pallas optimizer kernels: on a multi-device machine each
        # parameter's update runs inside a per-leaf shard_map with its
        # own PartitionSpec (optimizers.Optimizer._shardwise) —
        # init_layers installs the mesh + specs.  Unconditional
        # assignment so an optimizer reused across compiles never
        # carries a stale flag.
        if optimizer is not None:
            optimizer.fused = bool(cfg.fused_optimizer)

        # Export AFTER resolution so imported/searched configs are what get
        # written (reference exports from FFConfig::strategies the same way).
        if cfg.export_strategy_file:
            save_strategies_to_file(cfg.export_strategy_file,
                                    self._all_strategies(),
                                    provenance=self._export_provenance())

        # Label tensor (reference creates it in compile; dims follow loss).
        logits = self._loss_input_tensor()
        if self.loss.loss_type == LossType.SPARSE_CATEGORICAL_CROSSENTROPY:
            # (B, 1) for classifiers (reference convention), (B, T) for
            # sequence models.
            ldims = logits.dims[:-1] if logits.num_dims > 2 else (logits.dims[0], 1)
            self.label_tensor = Tensor(ldims, DataType.INT32, name="label")
        else:
            self.label_tensor = Tensor(tuple(self.final_tensor().dims), DataType.FLOAT, name="label")

        self._compiled = True
        self._train_step_fn = None
        self._eval_step_fn = None

    def _legalize_pc(self, op: Op, pc: ParallelConfig) -> ParallelConfig:
        """Clamp a config to one the op can execute (op-specific hook:
        ops/base.py Op.legalize_pc)."""
        return op.legalize_pc(pc)

    def _all_strategies(self) -> Dict[str, ParallelConfig]:
        return {op.name: getattr(op, "pc", ParallelConfig.data_parallel(
            op.output.num_dims, self.machine.num_devices)) for op in self.ops}

    def recompile(self, strategies: Optional[Dict[str, ParallelConfig]] = None,
                  machine: Optional[Machine] = None) -> None:
        """Re-parallelize a compiled (and possibly mid-training) model IN
        PLACE: swap the strategy map and/or the machine, re-resolve
        per-op configs, rebuild the jitted step, and migrate the live
        training state onto the new shardings through the same canonical
        host-side form a cross-mesh checkpoint restore uses.

        This is the hot-swap half of online re-parallelization
        (runtime/reconfigure.py): the controller drains, saves, calls
        ``recompile`` with the re-searched strategies (and a shrunken
        ``Machine(devices=survivors)`` after a device loss), then
        restores — the restore targets are built from the model's
        CURRENT shardings, so state re-shards onto the new mesh.

        No search, no import/export: the caller owns strategy selection
        here.  ``config.strategies`` keeps the applied map so later
        exports/provenance reflect what is actually running.

        Limitation: pipelined models repack their stage buffer with the
        PREVIOUS buffer's sharding, so a pipelined swap is only safe
        while the device set is unchanged (divergence-triggered swaps).
        """
        assert self._compiled, "recompile() requires a compiled model"
        import contextlib

        from .runtime.checkpoint import _tree_from_model, place_state

        # Snapshot live state in the canonical layout-portable form
        # (host numpy) BEFORE the mesh/shardings change underneath it.
        state = None
        if self._params is not None:
            state = jax.tree.map(
                lambda a: np.asarray(jax.device_get(a))
                if hasattr(a, "shape") else a, _tree_from_model(self))

        cfg = self.config
        saved = (cfg.search_budget, cfg.import_strategy_file,
                 cfg.export_strategy_file)
        cfg.search_budget = 0
        cfg.import_strategy_file = None
        cfg.export_strategy_file = None
        if strategies is not None:
            cfg.strategies.update(strategies)
        tel = self._telemetry
        if tel is not None:
            from .observability.reqtrace import run_trace_id as _ff_run_trace

            span = tel.span("recompile", num_ops=len(self.ops),
                            trace_id=_ff_run_trace(tel.run_id))
        else:
            span = contextlib.nullcontext({})
        try:
            with span as at:
                self._compile_impl(
                    self.optimizer, self.loss.loss_type,
                    list(self.metrics.metrics),
                    machine=machine if machine is not None else self.machine)
                if at is not None:
                    at["num_devices"] = self.machine.num_devices
        finally:
            (cfg.search_budget, cfg.import_strategy_file,
             cfg.export_strategy_file) = saved
        # The swapped-in step function must be compiled fresh, never
        # deserialized from the persistent cache (_bypass_compile_cache).
        self._fresh_jit = True

        # Re-run the optimizer-wiring half of init_layers: mesh, per-leaf
        # specs, and the ZeRO layout all follow the new machine — a stale
        # mesh here would shard-map updates over devices that are gone.
        if self.optimizer is not None and state is not None:
            shardings = self._param_spec_tree()
            specs = {opn: {wn: sh.spec for wn, sh in ws.items()}
                     for opn, ws in shardings.items()}
            multi = self.machine.num_devices > 1
            nonfused = set(self._offload)
            nonfused |= {(opn, info["weight"])
                         for opn, info in self._host_embed.items()}
            zero_specs = (self._zero_state_specs()
                          if cfg.zero_optimizer and multi else None)
            if zero_specs:
                nonfused |= set(zero_specs)
            self.optimizer.set_mesh(self.machine.mesh if multi else None,
                                    specs, nonfused_paths=nonfused)
            self.optimizer.zero_specs = zero_specs

        if state is not None:
            place_state(self, state)
        # Device-resident caches keyed on the old mesh: the staged batch
        # is re-placed by the next set_batch; metric accumulation is
        # re-hosted (uncommitted) so the new step function may place it.
        self._batch = None
        if self._metric_acc is not None:
            self._metric_acc = jnp.asarray(
                np.asarray(jax.device_get(self._metric_acc)))
        self._dp_cache = None
        self._he_dev_cache = None

        if tel is not None:
            from .observability import agreement as _ff_agreement
            from .observability import memplane as _ff_memplane

            # post-swap divergence must compare against the NEW strategy
            _ff_agreement.emit_compile_prediction(self, tel)
            # ... and so must the predicted-HBM view (the swapped plan
            # may trade step time for residency)
            _ff_memplane.emit_memory_prediction(self, tel)
            tel.flush()

    def _export_provenance(self) -> Optional[Dict[str, Any]]:
        """Provenance sidecar payload for an exported strategy: which
        search produced it (engine/budget/seed + simulated cost when
        compile ran one; "import"/"manual" otherwise) and per-op cost
        attribution.  Advisory — never lets a simulator failure break
        the export itself."""
        sp = getattr(self, "_search_provenance", None)
        try:
            from .observability.searchtrace import build_provenance

            extra = {}
            if self.config.import_strategy_file:
                extra["imported_from"] = self.config.import_strategy_file
            if sp is not None and sp.get("search_stats"):
                ss = sp["search_stats"]
                extra["population"] = {k: ss[k] for k in
                                       ("population", "ladder", "spent",
                                        "winner_chain", "exchange",
                                        "crossover") if k in ss}
                if ss.get("learned"):
                    extra["learned_tier"] = ss["learned"]
            if sp is None:
                engine = "import" if self.config.import_strategy_file \
                    else "manual"
                return build_provenance(self, self._all_strategies(),
                                        engine=engine, budget=0,
                                        seed=self.config.seed, extra=extra)
            return build_provenance(
                self, self._all_strategies(), engine=sp["engine"],
                budget=sp["budget"], seed=sp["seed"], best_s=sp["best_s"],
                dp_s=sp["dp_s"], machine_model=sp["machine_model"],
                extra=extra)
        except Exception as e:  # noqa: BLE001 — sidecar is best-effort
            warnings.warn(f"strategy provenance sidecar not written: {e}")
            return None

    def final_tensor(self) -> Tensor:
        return self.ops[-1].output

    def _loss_input_tensor(self) -> Tensor:
        """Pre-softmax activations when the loss fuses with a trailing
        Softmax (the stable log-softmax+CE path — see losses.py)."""
        last = self.ops[-1]
        if isinstance(last, Softmax) and self.loss is not None and self.loss.wants_logits:
            return last.inputs[0]
        return last.output

    # ------------------------------------------------------------------
    # parameter/state initialization (≈ FFModel::init_layers + initializer
    # tasks, src/runtime/initializer.cc)
    # ------------------------------------------------------------------
    def _sparse_embed_structural_ok(self, op) -> bool:
        """Structure-only part of row-sparse eligibility: an Embedding
        with its own table fed straight from a graph input.  Shared with
        the SEARCH paths (search.py / native_search.py propose host
        candidates only for ops the runtime could actually execute
        row-sparse — pricing a candidate batch-scaled and then executing
        it table-scaled would make the search recommend regressions).
        Deliberately does NOT touch ``jax.process_count()``: that
        initializes the backend, and offline tools must never hang on a
        wedged TPU tunnel for a structure question — the runtime check
        in ``_sparse_embed_ok`` covers multi-process."""
        if not (isinstance(op, Embedding) and op.share_from is None
                and any(op.inputs[0] is t for t in self.input_tensors)):
            return False
        # Swap-in remaps the index input to the compact row space, so
        # row-sparse execution additionally requires every consumer of
        # that input to be an own-table Embedding.  This half of the
        # runtime check is strategy-independent, so search candidates
        # and report rows must apply it too — otherwise they price a
        # batch-scaled host path for a plan the runtime would silently
        # execute table-scaled.
        idx_t = op.inputs[0]
        return all(isinstance(o, Embedding) and o.share_from is None
                   for o in self.ops
                   if any(t is idx_t for t in o.inputs))

    def _sparse_embed_candidate_ok(self, op) -> bool:
        """Search-time eligibility: structural checks plus the optimizer
        check when an optimizer is already known (compile-time search);
        an offline search with no optimizer assumes the built-in SGD
        default."""
        from .optimizers import AdamOptimizer, SGDOptimizer

        if not self._sparse_embed_structural_ok(op):
            return False
        if self.optimizer is None:
            return True
        if not isinstance(self.optimizer, (SGDOptimizer, AdamOptimizer)):
            return False
        flag = getattr(self.config, "sparse_host_embeddings", None)
        if flag is not None:
            return bool(flag)
        opt = self.optimizer
        return (isinstance(opt, SGDOptimizer) and opt.momentum == 0.0
                and opt.weight_decay == 0.0)

    def _sparse_embed_ok(self, op) -> bool:
        """Row-sparse host placement applies when the op is an Embedding
        with its own table fed straight from a graph input, under a
        built-in SGD/Adam optimizer.  Multi-process runs shard the table
        by row range across hosts (reference: run_summit.sh multi-node
        CPU-embedding DLRM) — see ``_host_embed_swap_in``.  Auto mode
        (``config.sparse_host_embeddings is None``) additionally requires
        the update rule to be identity on untouched rows (plain SGD) so
        sparse and dense training are bit-identical; forcing the flag
        True opts into lazy per-touched-row semantics (torch
        SparseAdam-style) for momentum/Adam."""
        from .optimizers import AdamOptimizer, SGDOptimizer

        if not (self._sparse_embed_structural_ok(op)
                and isinstance(self.optimizer, (SGDOptimizer, AdamOptimizer))):
            return False
        # Swap-in REMAPS the index input's batch values to the compact
        # row space, so every consumer of that input must be a
        # host-placed own-table Embedding seeing the same remap — a
        # mixed on-device consumer would silently look up compacted ids.
        idx_t = op.inputs[0]
        for o in self.ops:
            if any(t is idx_t for t in o.inputs):
                if not (isinstance(o, Embedding) and o.share_from is None
                        and o.pc.host_placed):
                    return False
        flag = getattr(self.config, "sparse_host_embeddings", None)
        if flag is not None:
            return bool(flag)
        opt = self.optimizer
        return (isinstance(opt, SGDOptimizer) and opt.momentum == 0.0
                and opt.weight_decay == 0.0)

    def _param_spec_tree(self) -> Dict[str, Dict[str, NamedSharding]]:
        out: Dict[str, Dict[str, NamedSharding]] = {}
        self._offload: Dict[Tuple[str, str], Tuple[NamedSharding, NamedSharding]] = {}
        self._host_embed = {}
        pack = self._pipe_pack()
        packed_keys = set(pack["entries"]) if pack else set()
        if pack:
            # Stage weights live in the pipe buffer: one row per ring
            # slot, sharded over the pipe axes (1/ring per device).
            out["_pipe"] = {"buffer": self._pipe_buffer_sharding()}
        for op in self.ops:
            if not op.weights or op.name in packed_keys:
                continue
            degrees = list(op.pc.dims)
            rank = op.output.num_dims
            degrees += [1] * (rank - len(degrees))
            groups = self.machine.axes_for_degrees(degrees[:rank])
            specs = {}
            for w in op.weights:
                entries = []
                for pd in w.partition_dims:
                    if pd is None or pd >= len(groups) or not groups[pd]:
                        entries.append(None)
                    else:
                        g = groups[pd]
                        entries.append(g if len(g) > 1 else g[0])
                while entries and entries[-1] is None:
                    entries.pop()
                sh = NamedSharding(self.machine.mesh, PartitionSpec(*entries))
                host_placed = op.pc.host_placed
                if host_placed and self._sparse_embed_ok(op):
                    # Row-sparse path (reference: embedding.cc:18-77 CPU
                    # tasks + dlrm_strategy_hetero.cc host ZC tables):
                    # the table lives host-side as numpy; each step
                    # gathers ONLY the batch's unique rows to device and
                    # scatters the updated rows back — per-step transfer
                    # scales with the batch, not the table.  The spec
                    # recorded here shards the per-step GATHERED rows
                    # (replicated: they're batch-sized).
                    idx_t = op.inputs[0]
                    n_idx = int(np.prod(idx_t.dims))
                    # multi-process: each host OWNS a contiguous row
                    # range of the table (reference: run_summit.sh
                    # places per-node CPU embedding shards)
                    P = jax.process_count()
                    N = int(op.num_entries)
                    per = -(-N // P)
                    lo = min(N, jax.process_index() * per)
                    hi = min(N, lo + per)
                    self._host_embed[op.name] = {
                        "weight": w.name,
                        "input": idx_t,
                        "input_key": f"in_{idx_t.guid}",
                        "u_max": int(min(op.num_entries,
                                         -(-n_idx // 8) * 8)),
                        "row_lo": lo, "row_hi": hi, "rows_per": per,
                        "num_entries": N,
                    }
                    specs[w.name] = NamedSharding(self.machine.mesh,
                                                  PartitionSpec())
                    continue
                if host_placed:
                    # Heterogeneous placement (reference: ParallelConfig::
                    # device_type=CPU routes ops to CPU task variants, and
                    # memory_types ZCM entries pin regions to host
                    # zero-copy memory, so DLRM keeps huge embedding
                    # tables off-accelerator — embedding.cc +
                    # dlrm_strategy_hetero.cc).  TPU equivalent: the
                    # weight (and its optimizer state) LIVES in pinned
                    # host memory; each step streams it to device,
                    # computes, and streams the update back.
                    try:
                        host_sh = sh.with_memory_kind("pinned_host")
                        self._offload[(op.name, w.name)] = (host_sh, sh)
                        sh = host_sh
                    except ValueError:
                        # backend without host memory kinds: keep HBM,
                        # but say so — silently dropping offload turns
                        # into an accelerator OOM on real workloads.
                        if not self._offload_warned:
                            self._offload_warned = True
                            print(f"flexflow_tpu: host placement requested "
                                  f"for {op.name}/{w.name} but this backend "
                                  f"has no pinned_host memory; keeping "
                                  f"weights in device memory")
                specs[w.name] = sh
            out[op.name] = specs
        return out

    def _host_embed_swap_in(self, params_in, opt_in, batch):
        """Per-step row gather for host-resident embedding tables
        (reference: embedding.cc:18-77 — CPU tasks touch only the
        batch's rows).  For each registered table: unique the batch's
        indices on host, gather those rows (padded to an ADAPTIVE
        bucket: the smallest power-of-two holding the step's unique
        count, kept as a monotone high-water mark ``u_hwm`` and capped
        at the all-unique ``u_max`` — skewed key distributions, the
        DLRM norm, never pay worst-case all-unique padding, and the
        monotone ladder bounds jit retraces to the handful of distinct
        bucket shapes), remap the index batch to the compact row space,
        and gather the same rows of any table-shaped optimizer slot.
        The dense in-jit optimizer update then IS the lazy
        per-touched-row update, and ``_host_embed_scatter_back`` writes
        the rows home in place."""
        rep = self.machine.replicated()
        params_in = _copy_params_tree(params_in)
        batch_in = dict(batch)
        if opt_in is not None:
            opt_in = _copy_state_tree(opt_in)
        # pass 1 — table-INDEPENDENT host work (unique, remap, bucket):
        # runs while the previous step's async scatter-back is still in
        # flight, hiding this host cost behind the device step
        nproc = jax.process_count()
        preps = []
        for opn, info in self._host_embed.items():
            key = info["input_key"]
            idx = self._host_idx.get(key)
            if idx is None:
                idx = np.asarray(jax.device_get(batch[key]))
            if nproc > 1:
                # the compact row space must be GLOBAL (grads for the
                # gathered buffer psum across processes): union every
                # host's local uniques via a fixed-size id allgather
                from jax.experimental import multihost_utils
                local = np.unique(idx)
                pad_ids = np.full((info["u_max"],), -1, np.int64)
                pad_ids[:local.size] = local
                all_ids = np.asarray(
                    multihost_utils.process_allgather(pad_ids))
                uniq = np.unique(all_ids[all_ids >= 0])
                inv = np.searchsorted(uniq, idx)
            else:
                uniq, inv = np.unique(idx, return_inverse=True)
            n = int(uniq.size)
            b = 8
            while b < n:
                b <<= 1
            u = min(info["u_max"], max(b, info.get("u_hwm", 0)))
            if opt_in is not None:
                # training step: grow the monotone bucket and account
                # wire traffic.  Eval/predict (opt_in None) still sizes
                # THIS call's pad correctly but must not inflate the
                # train bucket (extra retrace) or the per-train-step
                # telemetry bench.py reports.
                info["u_hwm"] = u
                info["uniq_rows_total"] = info.get("uniq_rows_total", 0) + n
                info["uniq_rows_steps"] = info.get("uniq_rows_steps", 0) + 1
            deg = info.get("batch_degree")
            if deg is None:
                # fixed after compile; the consumer scan inside
                # _input_batch_degree is O(ops) and this runs per table
                # per step on the Python hot path
                deg = info["batch_degree"] = \
                    self._input_batch_degree(info["input"])
            batch_in[key] = self._place_batch(
                inv.reshape(idx.shape).astype(np.int32), deg)
            preps.append((opn, info, uniq, n, u))
        # read barrier: the previous step's rows must be home before the
        # tables are gathered
        self._he_join()
        ctxs = []
        for opn, info, uniq, n, u in preps:
            wn = info["weight"]
            table = params_in[opn][wn]
            uniq_p = np.zeros((u,), np.int64)
            uniq_p[:n] = uniq

            def gather(shard):
                """(u, D) buffer of the compact rows.  Multi-process:
                each host fills the rows IT owns and an allgather-sum
                assembles the full buffer (every compact id has exactly
                one owner, so the sum is exact) — the per-host gather +
                DCN exchange of the reference's multi-node CPU
                embeddings (run_summit.sh)."""
                if nproc == 1:
                    return np.ascontiguousarray(shard[uniq_p])
                from jax.experimental import multihost_utils
                lo, hi = info["row_lo"], info["row_hi"]
                part = np.zeros((u,) + shard.shape[1:], shard.dtype)
                own = (uniq_p >= lo) & (uniq_p < hi)
                part[own] = shard[uniq_p[own] - lo]
                return np.ascontiguousarray(np.asarray(
                    multihost_utils.process_allgather(part))
                    .sum(0, dtype=shard.dtype))

            params_in[opn][wn] = jax.device_put(gather(table), rep)
            slots = []
            if opt_in is not None:
                for k, v in opt_in.items():
                    full = (v.get(opn, {}).get(wn)
                            if isinstance(v, dict) else None)
                    if full is not None and \
                            getattr(full, "shape", None) == table.shape:
                        v[opn][wn] = jax.device_put(
                            gather(np.asarray(full)), rep)
                        slots.append((k, full))
            ctxs.append({"op": opn, "weight": wn, "table": table,
                         "uniq": uniq, "n": n, "slots": slots,
                         "row_lo": info["row_lo"],
                         "row_hi": info["row_hi"],
                         "multi": nproc > 1})
        return params_in, opt_in, batch_in, ctxs

    def _host_embed_scatter_back(self, new_params, new_opt, ctxs):
        """Swap the host tables back into the returned trees and write
        the step's updated rows home ASYNCHRONOUSLY.  The step's row
        arrays are device futures, so forcing them (np.asarray) blocks
        until the step completes; doing that on a worker thread lets
        ``update()`` return at dispatch time, so the training loop's
        host-side work for the next batch (data prep, set_batch, and
        swap-in pass 1: unique/remap/bucket) overlaps the device step —
        the overlap Legion's dataflow gives the reference's CPU
        embedding tasks for free (embedding.cc:18-77).  ``_he_join()``
        is the read barrier (swap-in pass 2, sync, weight accessors,
        checkpoint)."""
        step_params, step_opt = new_params, new_opt
        new_params = _copy_params_tree(new_params)
        if new_opt is not None:
            new_opt = _copy_state_tree(new_opt)
        for ctx in ctxs:
            opn, wn = ctx["op"], ctx["weight"]
            new_params[opn][wn] = ctx["table"]
            for k, full in ctx["slots"]:
                new_opt[k][opn][wn] = full
        if self._he_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._he_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ff-host-embed")
        self._he_join()  # at most one step in flight
        self._he_pending = self._he_pool.submit(
            self._he_write_rows, step_params, step_opt, ctxs)
        # decode's device-table cache invalidates; drop it NOW so the
        # full replicated device tables don't sit in HBM through a
        # training run between generate calls
        self._he_version += 1
        self._he_dev_cache = None
        if os.environ.get("FF_HE_SYNC_SCATTER"):
            # measurement knob: serialize the scatter-back with the step
            # (bench A/Bs this to report the async overlap's actual win)
            self._he_join()
        return new_params, new_opt

    @staticmethod
    def _he_write_rows(step_params, step_opt, ctxs):
        """Worker: force the updated row arrays and scatter them into
        the host tables (and optimizer-state arrays) in place.  In a
        multi-process run each host writes ONLY the rows it owns — the
        updated buffer is replicated, so no communication is needed and
        the lazy-row update stays local."""
        for ctx in ctxs:
            opn, wn, n = ctx["op"], ctx["weight"], ctx["n"]
            uniq, table = ctx["uniq"], ctx["table"]
            if ctx.get("multi"):
                sel = (uniq >= ctx["row_lo"]) & (uniq < ctx["row_hi"])
                dst = uniq[sel] - ctx["row_lo"]
            else:
                sel, dst = slice(None), uniq
            rows = np.asarray(step_params[opn][wn])
            table[dst] = rows[:n][sel].astype(table.dtype)
            for k, full in ctx["slots"]:
                srows = np.asarray(step_opt[k][opn][wn])
                full[dst] = srows[:n][sel].astype(full.dtype)

    def _he_join(self):
        """Read barrier for the async scatter-back: wait for the
        in-flight row write (if any) and re-raise worker exceptions.
        Must run before any host-table read or write."""
        f = self._he_pending
        if f is not None:
            self._he_pending = None
            f.result()

    def _he_info(self, op_name: str, weight_name: str):
        """Row-range sharding info when ``(op, weight)`` is a host table
        sharded across processes, else None."""
        info = self._host_embed.get(op_name)
        if (info and info["weight"] == weight_name
                and jax.process_count() > 1):
            return info
        return None

    @staticmethod
    def _he_assemble_full(info, shard: np.ndarray) -> np.ndarray:
        """Assemble the FULL table from this host's row-range shard via
        a process allgather (shards pad to the common per-host size)."""
        from jax.experimental import multihost_utils
        per = info["rows_per"]
        pad = np.zeros((per,) + shard.shape[1:], shard.dtype)
        pad[:shard.shape[0]] = shard
        allp = np.asarray(multihost_utils.process_allgather(pad))
        return np.ascontiguousarray(
            allp.reshape((-1,) + shard.shape[1:])[:info["num_entries"]])

    def _offload_put(self, tree, to_host: bool):
        """Move host-offloaded weights between pinned-host and device
        memory (params-shaped tree; missing entries are left alone)."""
        if not self._offload:
            return tree
        tree = {k: (dict(v) if isinstance(v, dict) else v)
                for k, v in tree.items()}
        for (opn, wn), (host_sh, dev_sh) in self._offload.items():
            if opn in tree and isinstance(tree[opn], dict) and wn in tree[opn]:
                tree[opn][wn] = jax.device_put(
                    tree[opn][wn], host_sh if to_host else dev_sh)
        return tree

    def _offload_put_state(self, state, to_host: bool):
        """Same as ``_offload_put`` for optimizer state: each value is a
        params-shaped subtree ("v"/"m"), scalars pass through."""
        if not self._offload or state is None:
            return state
        return {k: self._offload_put(v, to_host) if isinstance(v, dict) else v
                for k, v in state.items()}

    def init_layers(self, seed: Optional[int] = None) -> None:
        assert self._compiled, "call compile() first"
        seed = self.config.seed if seed is None else seed
        key = jax.random.key(seed)
        shardings = self._param_spec_tree()

        ops_with_weights = [op for op in self.ops if op.weights
                            and op.name not in self._host_embed]
        pack = self._pipe_pack()

        import zlib

        def init_fn(key):
            params = {}
            buf = (jnp.zeros((pack["ring"], pack["width"]), jnp.float32)
                   if pack else None)
            for op in ops_with_weights:
                p = {}
                for w in op.weights:
                    # Deterministic per-(op, weight) stream: same graph →
                    # same init regardless of strategy or process history.
                    salt = zlib.crc32(f"{op.name}/{w.name}".encode())
                    v = w.initializer(jax.random.fold_in(key, salt),
                                      w.dims, jnp.float32)
                    if pack and op.name in pack["entries"]:
                        buf = self._pack_write(
                            buf, pack["entries"][op.name][w.name], v)
                    else:
                        p[w.name] = v
                if p:
                    params[op.name] = p
            if pack:
                params["_pipe"] = {"buffer": buf}
            return params

        # Offloaded weights are initialized on device (the SPMD partitioner
        # rejects host-placement annotations inside this jit) and streamed
        # to pinned-host memory right after.
        init_shardings = {opn: {wn: (self._offload[(opn, wn)][1]
                                     if (opn, wn) in self._offload else sh)
                                for wn, sh in ws.items()}
                          for opn, ws in shardings.items()
                          if opn not in self._host_embed}
        self._params = jax.jit(init_fn, out_shardings=init_shardings)(key)
        self._params = self._offload_put(self._params, True)
        # Row-sparse host tables: initialized on the host CPU backend
        # (same threefry streams → bit-identical to a device init) and
        # kept as numpy so per-step row scatter-updates are in-place.
        for opn, info in self._host_embed.items():
            op = next(o for o in self.ops if o.name == opn)
            w = op.weights[0]
            salt = zlib.crc32(f"{op.name}/{w.name}".encode())
            cpu0 = jax.local_devices(backend="cpu")[0]
            with jax.default_device(cpu0):
                hkey = jax.device_put(key, cpu0)
                v = np.array(w.initializer(jax.random.fold_in(hkey, salt),
                                           w.dims, jnp.float32))
            if jax.process_count() > 1:
                # every host computes the same full init (one threefry
                # stream) and keeps only its OWNED row range
                v = v[info["row_lo"]:info["row_hi"]].copy()
            self._params.setdefault(opn, {})[w.name] = v
        self._stats = {}
        for op in self.ops:
            st = op.init_stats()
            if st:
                self._stats[op.name] = jax.device_put(
                    st, self.machine.replicated())
        # Optimizer state mirrors the params pytree and inherits each
        # param's sharding (momentum/moment buffers live with their shard).
        if self.optimizer is not None:
            specs = {opn: {wn: sh.spec for wn, sh in ws.items()}
                     for opn, ws in shardings.items()}
            multi = self.machine.num_devices > 1
            # Host-offloaded leaves take the plain update (their streaming
            # device_put pairs don't model Pallas aliasing); every other
            # leaf keeps the fused path.
            nonfused = set(self._offload)
            nonfused |= {(opn, info["weight"])
                         for opn, info in self._host_embed.items()}
            zero_specs = (self._zero_state_specs()
                          if self.config.zero_optimizer and multi else None)
            if self.config.zero_optimizer and multi:
                # ZeRO-1 eligibility is structural (leading dim unsharded
                # and divisible over the free mesh axes) — report which
                # state actually sharded so a silently-replicated slot is
                # never mistaken for a sharded one.  Pipeline-packed,
                # host-offloaded, and host-sparse weights are accounted
                # as their own categories: packed stage state is sharded
                # ~1/ring by the pipe buffer itself, and host-resident
                # state never occupies device HBM at all.
                eligible = zero_specs or {}
                packed = set(pack["entries"]) if pack else set()
                cats = {"packed(1/ring)": 0, "host": 0}
                skipped = []
                n_total = 0
                for op in self.ops:
                    for w in op.weights:
                        k = (op.name, w.name)
                        n_total += 1
                        if op.param_key in packed:
                            cats["packed(1/ring)"] += 1
                        elif k in self._offload or op.name in self._host_embed:
                            cats["host"] += 1
                        elif k not in eligible:
                            skipped.append(k)
                extras = ", ".join(f"{n} {c}" for c, n in cats.items() if n)
                print(f"flexflow_tpu: ZeRO-1 optimizer-state sharding: "
                      f"{len(eligible)}/{n_total} weights sharded"
                      + (f" (+{extras})" if extras else "")
                      + (f"; replicated (ineligible): "
                         f"{', '.join('/'.join(k) for k in skipped[:8])}"
                         + ("..." if len(skipped) > 8 else "")
                         if skipped else ""))
            if zero_specs:
                # state spec != param spec breaks the fused kernels'
                # same-spec shard_map; those leaves take the plain update
                nonfused |= set(zero_specs)
            self.optimizer.set_mesh(self.machine.mesh if multi else None,
                                    specs, nonfused_paths=nonfused)
            self.optimizer.zero_specs = zero_specs
        self._opt_state = (self._init_opt_state()
                           if self.optimizer is not None else None)
        self._step_count = 0

    def _zero_state_specs(self):
        """ZeRO-1 layout: shard each parameter's OPTIMIZER STATE over the
        mesh axes the parameter itself does not occupy (momentum/moments
        of replicated weights drop to ~1/N per device; the update's
        gather/scatter comes out of GSPMD).  Only leaves whose leading
        dim is unsharded and divisible participate; offloaded leaves are
        host-resident already.  Returns {(op, weight): PartitionSpec}."""
        out = {}
        mesh = self.machine.mesh
        for op in self.ops:
            if not op.weights or op.name not in self._params:
                continue
            for w in op.weights:
                if (op.name, w.name) in self._offload \
                        or op.name in self._host_embed:
                    continue
                arr = self._params[op.name].get(w.name)
                if arr is None:
                    continue
                spec = arr.sharding.spec
                used = set()
                for e in spec:
                    if e is None:
                        continue
                    used.update(e if isinstance(e, tuple) else (e,))
                free = [a for a in mesh.axis_names if a not in used]
                if not free:
                    continue
                n_free = 1
                for a in free:
                    n_free *= mesh.shape[a]
                dim0 = (spec[0] if len(spec) > 0 else None)
                if dim0 is not None or arr.shape[0] % n_free != 0:
                    continue
                entries = list(spec) + [None] * (arr.ndim - len(spec))
                entries[0] = tuple(free) if len(free) > 1 else free[0]
                while entries and entries[-1] is None:
                    entries.pop()
                out[(op.name, w.name)] = PartitionSpec(*entries)
        return out

    def _init_opt_state(self):
        params = self._params
        if self._offload or self._host_embed:
            params = {opn: (dict(ws) if isinstance(ws, dict) else ws)
                      for opn, ws in params.items()}
        if self._offload:
            # zeros_like cannot materialize a pinned-host buffer (jax
            # builds arrays from callbacks in default device memory
            # only), so every stateful optimizer would crash at init on
            # an offloaded weight.  Hand init_state a device-memory
            # stand-in of the same shape/dtype/layout; the created
            # state streams to pinned host right below, exactly like
            # the weights do.
            for (opn, wn), (host_sh, dev_sh) in self._offload.items():
                ws = params.get(opn)
                if isinstance(ws, dict) and wn in ws:
                    leaf = ws[wn]
                    # allocate shard-wise directly — a device_put of a
                    # full single-device zeros buffer could OOM device 0
                    # for exactly the weights offload exists to hold
                    ws[wn] = jnp.zeros(leaf.shape, leaf.dtype,
                                       device=dev_sh)
        if self._host_embed:
            # Host-resident tables stay OUT of init_state (zeros_like
            # would allocate a table-sized device buffer); their state
            # lives host-side as numpy, scatter-updated per step.
            tables = {}
            for opn, info in self._host_embed.items():
                wn = info["weight"]
                d = params[opn]
                tables[(opn, wn)] = d.pop(wn)
                if not d:
                    params.pop(opn)
            state = self.optimizer.init_state(params)
            for v in state.values():
                if isinstance(v, dict):
                    for (opn, wn), tbl in tables.items():
                        v.setdefault(opn, {})[wn] = np.zeros(tbl.shape,
                                                             np.float32)
        else:
            state = self.optimizer.init_state(params)
        # pin offloaded entries' state to host so every step sees
        # consistent memory kinds
        state = self._offload_put_state(state, True)
        zero_specs = getattr(self.optimizer, "zero_specs", None)
        if zero_specs:
            mesh = self.machine.mesh
            state = {
                k: ({opn: {wn: (jax.device_put(
                        a, NamedSharding(mesh, zero_specs[(opn, wn)]))
                        if (opn, wn) in zero_specs else a)
                     for wn, a in ws.items()}
                     for opn, ws in v.items()}
                    if isinstance(v, dict) else v)
                for k, v in state.items()}
        return state

    # ------------------------------------------------------------------
    # forward-graph evaluation (inside jit)
    # ------------------------------------------------------------------
    def _run_graph(self, params, stats, batch, training: bool, rng):
        env: Dict[int, jax.Array] = {}
        multi = self.machine.num_devices > 1
        cdtype = self.compute_dtype
        for t in self.input_tensors:
            x = batch[f"in_{t.guid}"]
            if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != cdtype:
                # Activations run in compute_dtype (bfloat16 on the MXU for
                # benchmarks); params stay float32 and ops cast per-use.
                x = x.astype(cdtype)
            if multi:
                deg = self._input_batch_degree(t)
                if deg > 1:
                    x = jax.lax.with_sharding_constraint(
                        x, self.machine.batch_sharding(deg))
            env[t.guid] = x
        for t, val in self._constants.values():
            fill_dtype = jnp.int32 if "int" in t.dtype else cdtype
            env[t.guid] = jnp.full(t.dims, val, fill_dtype)
        ctx = FwdCtx(training=training, rng=rng, stats_in=stats,
                     stats_out={} if training else None)
        plan = getattr(self, "_pipeline_plan", None)
        use_pipe = (plan is not None and multi and plan["degree"] > 1)
        head_ids = ({id(op) for op in plan["head"]}
                    if use_pipe and plan.get("head") else set())
        i = 0
        while i < len(self.ops):
            if use_pipe and i == plan["i0"]:
                # Heterogeneous head first: host-placed row-sparse
                # embeddings may sit anywhere in op order (DLRM builds
                # its bottom MLP before the tables) but their gathered
                # rows must be in env before the ring packs stage 0's
                # input bundle.
                for hop in plan["head"]:
                    if hop.output.guid not in env:
                        hxs = [env[t.guid] for t in hop.inputs]
                        hys = hop.forward(params.get(hop.param_key, {}),
                                          hxs, ctx)
                        if multi:
                            if self._lowering is not None:
                                hys = [self._lowering.constraint(y, hop)
                                       for y in hys]
                            else:
                                hys = [self.machine.constraint(
                                    y, hop.constraint_pc()) for y in hys]
                        for t, y in zip(hop.outputs, hys):
                            env[t.guid] = y
                # Pipelined segment: GPipe microbatch schedule over the
                # pipe mesh axes (parallel/pipeline.py), replacing the
                # sequential op walk for ops[i0:i1].
                y = self._run_pipeline_segment(params, env, ctx)
                env[plan["seg_out"].guid] = y
                i = plan["i1"]
                continue
            op = self.ops[i]
            if id(op) in head_ids and op.output.guid in env:
                i += 1  # head op already ran at segment entry
                continue
            xs = [env[t.guid] for t in op.inputs]
            pvals = params.get(op.param_key, {})
            if training and self.config.remat and op.weights \
                    and not op.init_stats():
                # Rematerialization: drop this op's internal activations
                # from the residual set and recompute them in backward —
                # FLOPs for HBM, the standard TPU memory lever.  Stateful
                # ops (running stats) stay un-remat'ed.
                ys = jax.checkpoint(
                    lambda p_, xs_, op_=op: op_.forward(p_, list(xs_), ctx)
                )(pvals, tuple(xs))
            else:
                ys = op.forward(pvals, xs, ctx)
            if multi:
                if self._lowering is not None:
                    # Whole-graph lowering: constraints come from the
                    # logical-axis rules (sample/attribute/parameter →
                    # mesh axis classes) instead of the raw greedy map.
                    ys = [self._lowering.constraint(y, op) for y in ys]
                else:
                    cpc = op.constraint_pc()
                    ys = [self.machine.constraint(y, cpc) for y in ys]
            for t, y in zip(op.outputs, ys):
                env[t.guid] = y
            i += 1
        new_stats = dict(stats)
        if training and ctx.stats_out:
            new_stats.update(ctx.stats_out)
        return env, new_stats

    def _input_batch_degree(self, t: Tensor) -> int:
        plan = getattr(self, "_pipeline_plan", None)
        if plan is not None and t.guid in plan["seg_in_guids"]:
            return plan["dp_degree"]
        for op in self.ops:
            if t in op.inputs:
                if op.name in self._host_embed:
                    # host-placed row-sparse embedding: its pc is the
                    # host sentinel (degree 1 = replicated), but a
                    # replicated batch leaf cannot be assembled from
                    # per-host local shards in a multi-process run —
                    # shard the indices with the table OUTPUT's consumer
                    # dp degree instead (the lookup into the replicated
                    # gathered-row buffer distributes over batch)
                    out = op.output
                    if plan is not None and out.guid in plan["seg_in_guids"]:
                        # hetero head feeding the pipeline ring: segment
                        # ops carry no-split placeholder pcs, so the
                        # plan's dp degree is the batch sharding
                        return plan["dp_degree"]
                    for o2 in self.ops:
                        if out in o2.inputs \
                                and o2.name not in self._host_embed:
                            return o2.pc.dims[0]
                    return max(1, jax.process_count())
                return op.pc.dims[0]
        return 1

    # ------------------------------------------------------------------
    # the fused SPMD train step
    # ------------------------------------------------------------------
    def _build_train_step(self):
        loss_t = self._loss_input_tensor()
        probs_t = self.final_tensor()
        base_key = jax.random.key(self.config.seed + 7919)
        opt = self.optimizer
        metrics = self.metrics
        loss_fn_obj = self.loss

        mkeys = self._metric_keys()

        accum = max(1, int(self.config.grad_accum_steps))

        # The guard needs the isfinite entries even without FF_HEALTH.
        guard_on = self._nonfinite_guard is not None
        track_health = self._health is not None or guard_on

        def health_metrics(loss, grads):
            # Device-side isfinite reduction over the loss and the
            # global grad-norm, folded into the metric vector — fetched
            # by the existing drain, no extra dispatches.
            gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                      for g in jax.tree.leaves(grads))
            gnorm = jnp.sqrt(gsq)
            vec = jnp.zeros((len(mkeys),), jnp.float32)
            vec = vec.at[mkeys.index("nonfinite_loss")].set(
                1.0 - jnp.isfinite(loss).astype(jnp.float32))
            vec = vec.at[mkeys.index("nonfinite_grad")].set(
                1.0 - jnp.isfinite(gnorm).astype(jnp.float32))
            vec = vec.at[mkeys.index("grad_norm")].set(
                jnp.where(jnp.isfinite(gnorm), gnorm, 0.0))
            return vec

        def micro_metrics(loss, probs, labels):
            msum = metrics.compute(probs, labels)
            msum["loss"] = loss
            msum["steps"] = 1.0
            # On-device metric accumulation: one small vector rides along
            # and is fetched once per drain — the analogue of the
            # reference's future-chain metric fold (model.cc:1145-1167)
            # without a host round-trip per step.
            return jnp.stack([jnp.float32(msum.get(k, 0.0)) for k in mkeys])

        def guard_finalize(params, stats, opt_state, new_params, new_stats,
                           new_opt, mvec, macc):
            # Non-finite step guard (runtime/resilience.py): when this
            # step's loss or grad-norm was non-finite, select the
            # PRE-step params/stats/opt-state back — a functional
            # in-jit select, so it is donation-safe (no host reference
            # to the donated input buffers) and the restore is bitwise.
            # The skipped step contributes only its health entries plus
            # skipped_steps=1 to the metric vector (steps stays 0), so
            # window means cover applied steps only; consec_skipped is
            # a run length, reset by any good step.
            from .observability.health import HEALTH_METRIC_KEYS
            bad = (mvec[mkeys.index("nonfinite_loss")]
                   + mvec[mkeys.index("nonfinite_grad")]) > 0

            def sel(old, new):
                return jax.tree.map(lambda o, n: jnp.where(bad, o, n),
                                    old, new)

            hmask = jnp.zeros((len(mkeys),), jnp.float32)
            for k in HEALTH_METRIC_KEYS:
                hmask = hmask.at[mkeys.index(k)].set(1.0)
            skip_vec = (mvec * hmask).at[
                mkeys.index("skipped_steps")].set(1.0)
            out = macc + jnp.where(bad, skip_vec, mvec)
            ci = mkeys.index("consec_skipped")
            out = out.at[ci].set(jnp.where(bad, macc[ci] + 1.0, 0.0))
            return (sel(params, new_params), sel(stats, new_stats),
                    sel(opt_state, new_opt), out)

        def step(params, stats, opt_state, hparams, batch, step_idx, macc):
            rng = jax.random.fold_in(base_key, step_idx)
            labels = batch["label"]

            def loss_fn(p):
                env, new_stats = self._run_graph(p, stats, batch, True, rng)
                loss = loss_fn_obj(env[loss_t.guid], labels)
                return loss, (env[probs_t.guid], new_stats)

            (loss, (probs, new_stats)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            mvec = micro_metrics(loss, probs, labels)
            if track_health:
                mvec = mvec + health_metrics(loss, grads)
            new_params, new_opt = opt.apply(params, grads, opt_state, hparams)
            if guard_on:
                return guard_finalize(params, stats, opt_state, new_params,
                                      new_stats, new_opt, mvec, macc)
            return new_params, new_stats, new_opt, macc + mvec

        def step_accum(params, stats, opt_state, hparams, batch, step_idx,
                       macc):
            # Gradient accumulation: K micro-batches through a lax.scan
            # (one micro's activations live at a time), grads averaged,
            # ONE optimizer apply — numerically the full-batch step for
            # linear-in-loss grads (BatchNorm normalizes per micro, and
            # dropout draws per-micro masks, as everywhere else).
            rng = jax.random.fold_in(base_key, step_idx)
            br = {k: v.reshape((accum, v.shape[0] // accum) + v.shape[1:])
                  for k, v in batch.items()}
            g0 = jax.tree.map(jnp.zeros_like, params)
            m0 = jnp.zeros((len(mkeys),), jnp.float32)

            def body(carry, idx):
                g_acc, mv_acc, stats_c = carry
                mb = {k: v[idx] for k, v in br.items()}
                mlabels = mb["label"]

                def loss_fn(p):
                    env, new_stats = self._run_graph(
                        p, stats_c, mb, True, jax.random.fold_in(rng, idx))
                    loss = loss_fn_obj(env[loss_t.guid], mlabels)
                    return loss, (env[probs_t.guid], new_stats)

                (loss, (probs, new_stats)), g = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                g_acc = jax.tree.map(lambda a, b: a + b / accum, g_acc, g)
                return (g_acc, mv_acc + micro_metrics(loss, probs, mlabels),
                        new_stats), None

            (grads, mvec, new_stats), _ = jax.lax.scan(
                body, (g0, m0, stats), jnp.arange(accum))
            # per-STEP metric semantics: counts sum across micros; the
            # loss entry is the mean micro loss; "steps" is one step
            fix = jnp.ones((len(mkeys),), jnp.float32)
            for name in ("loss", "steps"):
                if name in mkeys:
                    fix = fix.at[mkeys.index(name)].set(1.0 / accum)
            mvec = mvec * fix
            if track_health:
                # accumulated grads; the mean micro loss rides mvec and
                # is NaN iff any micro's loss was
                mvec = mvec + health_metrics(
                    mvec[mkeys.index("loss")], grads)
            new_params, new_opt = opt.apply(params, grads, opt_state, hparams)
            if guard_on:
                return guard_finalize(params, stats, opt_state, new_params,
                                      new_stats, new_opt, mvec, macc)
            return new_params, new_stats, new_opt, macc + mvec

        step_fn = step if accum == 1 else step_accum
        if self._lowering is not None:
            # ONE whole-graph pjit'd step (CPU fallback = the identical
            # jax.jit call below, so tier-1 parity is by construction).
            fn = self._lowering.jit_step(step_fn, donate_argnums=(0, 1, 2, 6))
        else:
            fn = jax.jit(step_fn, donate_argnums=(0, 1, 2, 6))
        if self._memplane is not None:
            fn = self._memplane.wrap("train_step", fn)
        return fn

    def _build_eval_step(self):
        loss_t = self._loss_input_tensor()
        probs_t = self.final_tensor()
        metrics = self.metrics
        loss_fn_obj = self.loss

        def estep(params, stats, batch):
            env, _ = self._run_graph(params, stats, batch, False, None)
            labels = batch["label"]
            loss = loss_fn_obj(env[loss_t.guid], labels)
            msum = metrics.compute(env[probs_t.guid], labels)
            msum["loss"] = loss
            return msum, env[probs_t.guid]

        if self._lowering is not None:
            fn = self._lowering.jit_step(estep)
        else:
            fn = jax.jit(estep)
        if self._memplane is not None:
            fn = self._memplane.wrap("eval_step", fn)
        return fn

    # ------------------------------------------------------------------
    # driver API (reference: forward/zero_gradients/backward/update —
    # staged here, fused execution at update())
    # ------------------------------------------------------------------
    def set_batch(self, inputs: Dict[Tensor, Any], labels: Any) -> None:
        batch: Dict[str, Any] = {}
        he_keys = {info["input_key"] for info in self._host_embed.values()}
        for t, arr in inputs.items():
            key = f"in_{t.guid}"
            if key in he_keys:
                if not isinstance(arr, jax.Array):
                    # keep a host copy: the sparse gather uniques these
                    # indices on host per step without a device round-trip
                    self._host_idx[key] = np.asarray(arr)
                else:
                    # device-array batch: drop any stale host copy so
                    # swap-in falls back to device_get of THIS batch
                    self._host_idx.pop(key, None)
            batch[f"in_{t.guid}"] = self._place_batch(arr, self._input_batch_degree(t))
        deg = getattr(self.ops[-1], "pc", ParallelConfig(dims=(1,))).dims[0] \
            if self.ops else 1
        batch["label"] = self._place_batch(labels, deg)
        self._batch = batch

    def _place_batch(self, arr, degree: int):
        if isinstance(arr, jax.Array) and arr.committed:
            return arr
        arr = np.asarray(arr)
        if jax.process_count() > 1:
            # Multi-host: ``arr`` is this host's local shard of the global
            # batch (parallel/distributed.py, host_local_batch).
            from .parallel.distributed import host_local_batch
            return host_local_batch(self.machine, arr, degree)
        return jax.device_put(arr, self.machine.batch_sharding(degree))

    def forward(self) -> None:
        self._staged = True

    def zero_gradients(self) -> None:
        """No-op: gradients are functional values, freshly computed per
        step (the reference zeroes its accumulation regions,
        model.cc:1109-1132)."""

    def backward(self) -> None:
        self._staged = True

    def _metric_keys(self) -> List[str]:
        keys = ["train_all", "train_correct", "cce_loss", "sparse_cce_loss",
                "mse_loss", "rmse_loss", "mae_loss", "loss", "steps"]
        if self._health is not None or self._nonfinite_guard is not None:
            # Health entries ride the same on-device vector (non-finite
            # loss/grad counts + summed grad norm) so detection costs
            # zero extra dispatches; the drain pops them before
            # PerfMetrics sees the dict.  The guard needs them even
            # when FF_HEALTH is off — its skip decision keys off them.
            from .observability.health import HEALTH_METRIC_KEYS
            keys += list(HEALTH_METRIC_KEYS)
        if self._nonfinite_guard is not None:
            keys += list(self._nonfinite_guard.METRIC_KEYS)
        return keys

    def update(self) -> None:
        # The step choke point fires on the GLOBAL step index, so an
        # exact trigger is resume-aware: after a restore past it, the
        # fault never re-fires.
        if self._chaos is not None:
            self._chaos.fire("step", index=self._step_count, model=self)
        # _stepstats is non-None only under telemetry; the disabled path
        # is a single attribute test.
        if self._stepstats is not None:
            return self._stepstats.timed_update(self._update_impl)
        self._update_impl()

    @staticmethod
    @contextlib.contextmanager
    def _bypass_compile_cache():
        """The persistent compilation cache and a mid-training re-compile
        don't mix: an executable deserialized from the on-disk cache can
        mis-alias donated buffers when it replaces a live step function
        (intermittent NaN params / heap corruption on the CPU backend),
        and a crash mid-write leaves a truncated entry that kills every
        later swap.  Hot-swap rebuilds compile fresh instead — the cache
        stays on for cold-start compiles, where it is safe and earns its
        keep."""
        old = jax.config.jax_enable_compilation_cache
        jax.config.update("jax_enable_compilation_cache", False)
        try:
            yield
        finally:
            jax.config.update("jax_enable_compilation_cache", old)

    def _update_impl(self) -> None:
        assert self._batch is not None, "no batch loaded: call a DataLoader first"
        compile_ctx = contextlib.nullcontext()
        if self._train_step_fn is None:
            self._train_step_fn = self._build_train_step()
            if self._fresh_jit:
                compile_ctx = self._bypass_compile_cache()
                self._fresh_jit = False
        if self._opt_state is None:
            self._opt_state = self._init_opt_state()
        if self._metric_acc is None:
            self._metric_acc = jnp.zeros((len(self._metric_keys()),), jnp.float32)
            guard = self._nonfinite_guard
            if guard is not None and guard.consec:
                # re-seed the run length a reset_metrics discarded
                ci = self._metric_keys().index("consec_skipped")
                self._metric_acc = self._metric_acc.at[ci].set(
                    float(guard.consec))
        hp = self.optimizer.hparams()
        # Host-offloaded weights stream on-chip for the step and back
        # after (eager device_put at the jit boundary: the reference's
        # CPU-resident tables likewise live in host memory between
        # iterations; the step itself computes on the accelerator).
        params_in = self._offload_put(self._params, False)
        opt_in = self._offload_put_state(self._opt_state, False)
        batch_in, he_ctxs = self._batch, None
        if self._host_embed:
            params_in, opt_in, batch_in, he_ctxs = \
                self._host_embed_swap_in(params_in, opt_in, self._batch)
        with compile_ctx:  # first call traces+compiles; later calls no-op
            new_params, self._stats, new_opt, self._metric_acc = \
                self._train_step_fn(params_in, self._stats, opt_in,
                                    hp, batch_in, jnp.uint32(self._step_count),
                                    self._metric_acc)
        if he_ctxs:
            new_params, new_opt = self._host_embed_scatter_back(
                new_params, new_opt, he_ctxs)
        self._params = self._offload_put(new_params, True)
        self._opt_state = self._offload_put_state(new_opt, True)
        self._step_count += 1
        self._staged = False

    def train_iteration(self) -> None:
        """Convenience: forward+backward+update in one fused call."""
        self.forward()
        self.zero_gradients()
        self.backward()
        self.update()

    def _eval_inputs(self):
        params_in = self._offload_put(self._params, False)
        batch_in = self._batch
        if self._host_embed:
            params_in, _, batch_in, _ = self._host_embed_swap_in(
                params_in, None, self._batch)
        return params_in, batch_in

    def eval_batch(self) -> Dict[str, float]:
        if self._eval_step_fn is None:
            self._eval_step_fn = self._build_eval_step()
        params_in, batch_in = self._eval_inputs()
        msum, _ = self._eval_step_fn(params_in, self._stats, batch_in)
        # one device fetch for the whole metric dict, split on host —
        # per-key float(v) would round-trip to the device once per metric
        msum = jax.device_get(msum)
        return {k: float(v) for k, v in msum.items()}

    def predict_batch(self) -> np.ndarray:
        """Final-op outputs (probabilities) for the staged batch."""
        if self._eval_step_fn is None:
            self._eval_step_fn = self._build_eval_step()
        params_in, batch_in = self._eval_inputs()
        _, probs = self._eval_step_fn(params_in, self._stats, batch_in)
        return np.asarray(probs)

    # ------------------------------------------------------------------
    # autoregressive generation (beyond the reference, which is
    # training-only: kv-cached decoding as one jitted lax.scan —
    # static shapes, no per-token retrace)
    # ------------------------------------------------------------------
    def _run_graph_decode(self, params, caches, batch, pos, ctx,
                          pre_env=None, skip=(), block_tables=None):
        env: Dict[int, jax.Array] = dict(pre_env) if pre_env else {}
        cdtype = self.compute_dtype
        for t in self.input_tensors:
            if t.guid in env:
                continue
            key = f"in_{t.guid}"
            if key not in batch:
                raise ValueError(
                    f"generate: graph input {t.name or t.guid!r} was not "
                    f"fed — pass it via extra_inputs")
            x = batch[key]
            if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != cdtype:
                x = x.astype(cdtype)
            env[t.guid] = x
        for t, val in self._constants.values():
            if t.guid not in env:
                fill_dtype = jnp.int32 if "int" in t.dtype else cdtype
                env[t.guid] = jnp.full(t.dims, val, fill_dtype)
        new_caches = {}
        for op in self.ops:
            if op.name in skip:
                continue
            xs = [env[t.guid] for t in op.inputs]
            if block_tables is not None and hasattr(op, "decode_paged"):
                # paged serving path: the op's cache rows are pool
                # blocks, addressed through the per-slot block tables
                ys, c = op.decode_paged(params.get(op.param_key, {}), xs,
                                        caches.get(op.name), pos,
                                        block_tables, ctx)
            else:
                ys, c = op.decode(params.get(op.param_key, {}), xs,
                                  caches.get(op.name), pos, ctx)
            new_caches[op.name] = c
            for t, y in zip(op.outputs, ys):
                env[t.guid] = y
        return env, new_caches

    def _decode_params(self):
        """Params tree for decoding: a pipelined model's packed stage
        weights unpack to per-op entries (the decode runner walks ops
        sequentially, not the GPipe ring), and host-resident embedding
        tables move to device ONCE per table version — generated ids
        are data-dependent, so the row-sparse pre-gather is impossible
        and feeding the numpy table into jit would re-upload the whole
        table every generate call.  Cached until a train step or restore
        replaces ``_params`` / bumps the table version."""
        # read barrier: decode reads host-resident tables the async
        # scatter-back may still be writing
        self._he_join()
        tree = self._params
        if self._pipe_pack() is not None:
            cached = getattr(self, "_dp_cache", None)
            if cached is not None and cached[0] is self._params:
                tree = cached[1]
            else:
                from .runtime.checkpoint import _unpack_tree
                tree = _unpack_tree(self, self._params)
                self._dp_cache = (self._params, tree)
        if self._host_embed:
            # keyed on the SOURCE TREE OBJECT (kept alive in the cache —
            # a raw id() could be recycled and false-hit, which in a
            # multi-process run would even diverge per rank around the
            # assemble collective) plus the table version
            cached = getattr(self, "_he_dev_cache", None)
            if (cached is None or cached[0] is not tree
                    or cached[1] != self._he_version):
                src = tree
                rep = self.machine.replicated()
                tree = {k: (dict(v) if isinstance(v, dict) else v)
                        for k, v in tree.items()}
                for opn, info in self._host_embed.items():
                    wn = info["weight"]
                    shard = tree[opn][wn]
                    if not isinstance(shard, np.ndarray):
                        continue
                    full = (self._he_assemble_full(info, shard)
                            if jax.process_count() > 1 else shard)
                    tree[opn][wn] = jax.device_put(
                        np.ascontiguousarray(full), rep)
                self._he_dev_cache = (src, self._he_version, tree)
            tree = self._he_dev_cache[2]
        return tree

    # ------------------------------------------------------------------
    # decode entry points — shared by generate()/beam_search() and the
    # serving engine (flexflow_tpu/serving/), which composes them into
    # its own jitted prefill/step functions over a slot-based kv pool
    # ------------------------------------------------------------------
    def resolve_decode_inputs(self, tokens_input: Optional[Tensor] = None,
                              positions_input: Optional[Tensor] = None):
        """Resolve the (tokens, positions) graph inputs fed one token at
        a time during decoding.  Explicit ``is None`` tests throughout: a
        falsy-but-valid Tensor handle must never be silently replaced by
        the default."""
        tok_t = tokens_input if tokens_input is not None \
            else self.input_tensors[0]
        pos_t = positions_input
        if pos_t is None and tokens_input is None \
                and len(self.input_tensors) > 1:
            # transformer layout (tokens, positions) — only guessed when
            # the tokens input was also defaulted
            pos_t = self.input_tensors[1]
        return tok_t, pos_t

    def init_decode_caches(self, batch_size: int, max_len: int, skip=()):
        """Fresh decode-cache pytree: one entry per op, ``batch_size``
        rows, ``max_len`` sequence positions (trace-safe)."""
        return {op.name: op.init_cache(batch_size, max_len,
                                       self.compute_dtype)
                for op in self.ops if op.name not in skip}

    def pageable_decode(self, skip=()) -> bool:
        """True when every cache-carrying op has a paged decode path —
        the serving engine's gate for block-paged KV (decoder-only
        transformers qualify; LSTM/seq2seq stacks fall back dense)."""
        from .ops.base import Op
        return all(type(op).init_cache is Op.init_cache
                   or hasattr(op, "init_paged_cache")
                   for op in self.ops if op.name not in skip)

    def init_paged_decode_caches(self, num_blocks: int, block_size: int,
                                 skip=()):
        """Fresh block-pool cache pytree: cache-carrying ops get
        ``(num_blocks, H, block_size, D)`` pools (block 0 is the garbage
        sink, serving/kvpool.py); stateless ops get None."""
        from .ops.base import Op
        out = {}
        for op in self.ops:
            if op.name in skip:
                continue
            if type(op).init_cache is Op.init_cache:
                out[op.name] = None
            elif hasattr(op, "init_paged_cache"):
                out[op.name] = op.init_paged_cache(num_blocks, block_size,
                                                   self.compute_dtype)
            else:
                raise ValueError(
                    f"paged decode: op {op.name!r} "
                    f"({type(op).__name__}) carries a decode cache but "
                    f"has no paged path — serve it with FF_SERVE_PAGED=off")
        return out

    def decode_step(self, params, stats, caches, cur, pos, tok_t, pos_t,
                    pre_env=None, skip=(), block_tables=None):
        """One single-token decode step: feed token ids ``cur`` (B,)
        int32 at position ``pos`` and return (probs (B, V) float32, new
        caches).  ``pos`` is a scalar, or a per-row (B,) vector when the
        rows sit at DIFFERENT sequence positions — the serving engine's
        continuous batch, where each slot carries its own write offset
        and causal-mask length.  Trace-safe: generate()/beam_search()
        call this inside their jitted scans, the serving engine inside
        its jitted prefill/step functions."""
        B = cur.shape[0]
        batch = {f"in_{tok_t.guid}": cur[:, None]}
        if pos_t is not None:
            p = pos if jnp.ndim(pos) else jnp.full((B,), pos, jnp.int32)
            batch[f"in_{pos_t.guid}"] = p[:, None]
        ctx = FwdCtx(training=False, rng=jax.random.key(self.config.seed),
                     stats_in=stats)
        env, caches = self._run_graph_decode(params, caches, batch, pos,
                                             ctx, pre_env=pre_env,
                                             skip=skip,
                                             block_tables=block_tables)
        probs = env[self.final_tensor().guid][:, -1, :].astype(jnp.float32)
        return probs, caches

    def _check_position_table(self, pos_t, s_max: int) -> None:
        """jnp.take clamps OOB position lookups under jit — catch an
        overlong request instead of degrading silently."""
        if pos_t is None:
            return
        # the scan runs P+N-1 steps over positions 0..P+N-2, so the
        # largest index used is s_max-2 — a table of s_max-1 entries is
        # exactly enough
        for op in self.ops:
            if isinstance(op, Embedding) and op.inputs[0] is pos_t \
                    and s_max - 1 > op.num_entries:
                raise ValueError(
                    f"decode: prompt + max_new_tokens = {s_max} needs "
                    f"{s_max - 1} positions but the position table has "
                    f"only {op.num_entries} entries")

    def _static_decode_ops(self, extra_guids):
        """Ops reachable from the FIXED extra inputs alone (a seq2seq
        encoder): run once before the decode scan, not once per token."""
        avail = set(extra_guids)
        avail.update(t.guid for t, _ in self._constants.values())
        static_ops = []
        if extra_guids:
            for op in self.ops:
                if op.inputs and all(t.guid in avail for t in op.inputs):
                    static_ops.append(op)
                    avail.update(t.guid for t in op.outputs)
        return static_ops, frozenset(op.name for op in static_ops)

    def _prefill_static(self, params, stats, extra, extra_guids,
                        static_ops, repeat: int = 1):
        cdtype = self.compute_dtype
        env = {}
        for g in extra_guids:
            x = extra[f"in_{g}"]
            env[g] = jnp.repeat(x, repeat, axis=0) if repeat > 1 else x
        for t, val in self._constants.values():
            fdt = jnp.int32 if "int" in t.dtype else cdtype
            env[t.guid] = jnp.full(t.dims, val, fdt)
        ctx = FwdCtx(training=False, rng=jax.random.key(self.config.seed),
                     stats_in=stats)
        for op in static_ops:
            xs = [env[t.guid] for t in op.inputs]
            ys = op.forward(params.get(op.param_key, {}), xs, ctx)
            for t, y in zip(op.outputs, ys):
                env[t.guid] = y
        return env

    def generate(self, prompt_tokens, max_new_tokens: int, *,
                 tokens_input: Optional[Tensor] = None,
                 positions_input: Optional[Tensor] = None,
                 extra_inputs: Optional[Dict[Tensor, Any]] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 seed: int = 0) -> np.ndarray:
        """Generate ``max_new_tokens`` continuations for a (B, P) int32
        prompt with kv-cached greedy (temperature=0) or sampled
        decoding.  The whole prefill+decode loop is ONE jitted
        ``lax.scan`` over P+N-1 single-token steps — each attention op
        carries a (B, H, P+N, head_dim) cache written in place.

        Sampling knobs (active only with temperature > 0): ``top_k``
        keeps the k most likely tokens; ``top_p`` keeps the smallest
        nucleus of tokens whose probabilities sum to >= p (the most
        likely token always survives); both may combine.

        ``tokens_input``/``positions_input`` default to the model's
        first/second graph inputs (the ``build_transformer`` layout).
        ``extra_inputs`` maps further graph inputs to FIXED full arrays
        fed every step — e.g. the source sentence of a seq2seq model
        (its encoder ops re-run per step; the decoder LSTMs carry their
        state in the decode cache).
        """
        assert self._compiled, "call compile() first"
        toks = jnp.asarray(prompt_tokens, jnp.int32)
        B, P = toks.shape
        N = int(max_new_tokens)
        if N <= 0:
            return np.zeros((B, 0), np.int32)
        tok_t, pos_t = self.resolve_decode_inputs(tokens_input,
                                                  positions_input)
        s_max = P + N
        self._check_position_table(pos_t, s_max)
        sampled = float(temperature) > 0.0
        # bad knob values fail loudly even when greedy ignores them ...
        if top_k is not None and int(top_k) < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        if top_p is not None and not 0.0 < float(top_p) <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        # ... then normalize to trace constants: inactive knobs don't
        # fork the compile cache
        t_k = int(top_k) if sampled and top_k is not None else None
        t_p = float(top_p) if sampled and top_p is not None else None

        extra_guids = {t.guid for t in (extra_inputs or {})}
        static_ops, static_names = self._static_decode_ops(extra_guids)

        def step(params, stats, pre_env, temp, carry, inp):
            caches, tok, pos, key = carry
            feed_tok, use_feed = inp
            cur = jnp.where(use_feed, feed_tok, tok)          # (B,)
            probs, caches = self.decode_step(
                params, stats, caches, cur, pos, tok_t, pos_t,
                pre_env=pre_env, skip=static_names)           # (B, V)
            if sampled:
                logits = jnp.log(probs + 1e-9)
                if t_k is not None or t_p is not None:
                    srt = jnp.sort(probs, axis=-1)[:, ::-1]       # desc
                    if t_k is not None:
                        kth = srt[:, min(t_k, srt.shape[1]) - 1][:, None]
                        logits = jnp.where(probs >= kth, logits, -jnp.inf)
                    if t_p is not None:
                        csum = jnp.cumsum(srt, axis=-1)
                        # smallest prefix with mass >= p; cutoff = that
                        # prefix's lowest prob (top token always
                        # survives).  Clamp: with p=1.0 a float32 row
                        # summing just under 1.0 would index past V
                        keep_n = jnp.minimum(jnp.sum(csum < t_p, axis=-1),
                                             srt.shape[1] - 1)
                        cutoff = jnp.take_along_axis(
                            srt, keep_n[:, None], axis=-1)
                        logits = jnp.where(probs >= cutoff, logits,
                                           -jnp.inf)
                key, k = jax.random.split(key)
                nxt = jax.random.categorical(k, logits / temp, axis=-1)
            else:
                nxt = jnp.argmax(probs, axis=-1)
            nxt = nxt.astype(jnp.int32)
            return (caches, nxt, pos + 1, key), nxt

        extra = {f"in_{t.guid}": jnp.asarray(v)
                 for t, v in (extra_inputs or {}).items()}
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        # seed/temperature are runtime ARGUMENTS (key0/temp below), not
        # trace constants — new seeds reuse the compiled scan
        ckey = (B, P, N, sampled, t_k, t_p, tok_t.guid,
                pos_t.guid if pos_t is not None else None,
                tuple(sorted((k, v.shape) for k, v in extra.items())))
        run = cache.get(ckey)
        if run is None:
            def run(params, stats, extra, feed, use, key0, temp):
                pre_env = self._prefill_static(params, stats, extra,
                                               extra_guids, static_ops)
                caches0 = self.init_decode_caches(B, s_max,
                                                  skip=static_names)
                carry0 = (caches0, jnp.zeros((B,), jnp.int32),
                          jnp.zeros((), jnp.int32), key0)
                _, outs = jax.lax.scan(
                    lambda c, i: step(params, stats, pre_env, temp, c, i),
                    carry0, (feed, use))
                return outs                                   # (P+N-1, B)

            run = (self._lowering.jit_step(run)
                   if self._lowering is not None else jax.jit(run))
            if self._memplane is not None:
                run = self._memplane.wrap(f"generate:{B}x{P}x{N}", run)
            cache[ckey] = run

        feed = jnp.concatenate(
            [toks.T, jnp.zeros((N - 1, B), jnp.int32)]) if N > 1 else toks.T
        use = jnp.concatenate([jnp.ones((P,), bool),
                               jnp.zeros((N - 1,), bool)])
        outs = run(self._decode_params(), self._stats, extra, feed, use,
                   jax.random.key(seed),
                   jnp.asarray(float(temperature), jnp.float32))
        return np.asarray(outs[P - 1:].T)                     # (B, N)

    def beam_search(self, prompt_tokens, max_new_tokens: int, *,
                    beam_size: int = 4,
                    tokens_input: Optional[Tensor] = None,
                    positions_input: Optional[Tensor] = None,
                    extra_inputs: Optional[Dict[Tensor, Any]] = None,
                    eos_id: Optional[int] = None,
                    length_penalty: float = 0.0):
        """Beam-search decoding: returns (sequences (B, K, N) int32,
        scores (B, K) float32 — summed token log-probs, best first).

        Beams ride the batch dim (B*K rows through the same kv-cached
        decode graph as ``generate``); at each step candidate scores
        expand to (B, K*V), the top K survive, and every cache leaf is
        gathered by the surviving beams' parent indices — all inside one
        jitted ``lax.scan``.  A finished beam (``eos_id`` emitted) is
        frozen by forcing its next-token distribution to eos at
        log-prob 0.  ``length_penalty`` alpha > 0 re-ranks the final
        beams by the GNMT normalization score/((5+len)/6)^alpha (len =
        tokens up to and including eos); the returned scores stay raw
        log-prob sums.
        """
        assert self._compiled, "call compile() first"
        toks = jnp.asarray(prompt_tokens, jnp.int32)
        B, P = toks.shape
        N = int(max_new_tokens)
        K = int(beam_size)
        if N <= 0:
            return (np.zeros((B, K, 0), np.int32),
                    np.zeros((B, K), np.float32))
        tok_t, pos_t = self.resolve_decode_inputs(tokens_input,
                                                  positions_input)
        s_max = P + N
        self._check_position_table(pos_t, s_max)
        BK = B * K

        extra_guids = {t.guid for t in (extra_inputs or {})}
        static_ops, static_names = self._static_decode_ops(extra_guids)

        def step(params, stats, pre_env, carry, inp):
            caches, buf, scores, last, pos = carry
            feed_tok, use_feed, do_expand = inp           # (B,), scalars
            cur = jnp.where(use_feed,
                            jnp.repeat(feed_tok, K), last)    # (BK,)
            probs, caches = self.decode_step(
                params, stats, caches, cur, pos, tok_t, pos_t,
                pre_env=pre_env, skip=static_names)
            logp = jnp.log(probs + 1e-30)                  # (BK, V)
            V = logp.shape[-1]
            if eos_id is not None:
                # freeze on the token at THIS position (cur) — the carry
                # `last` is one token stale at the first expand step
                fin = (cur == eos_id)[:, None]
                frozen = jnp.full((1, V), -jnp.inf).at[0, eos_id].set(0.0)
                logp = jnp.where(fin, frozen, logp)

            def expand(args):
                caches, buf, scores, _ = args
                total = scores.reshape(B, K, 1) + logp.reshape(B, K, V)
                top, idx = jax.lax.top_k(total.reshape(B, K * V), K)
                parent = idx // V                          # (B, K)
                token = (idx % V).astype(jnp.int32)
                flat = (parent + jnp.arange(B)[:, None] * K).reshape(-1)
                caches = jax.tree.map(lambda c: c[flat], caches)
                buf = buf[flat]
                widx = jnp.clip(pos - (P - 1), 0, N - 1)
                buf = jax.lax.dynamic_update_slice(
                    buf, token.reshape(BK, 1), (0, widx))
                return caches, buf, top, token.reshape(-1)

            def passthrough(args):
                caches, buf, scores, _ = args
                return caches, buf, scores, cur

            caches, buf, scores, last = jax.lax.cond(
                do_expand, expand, passthrough, (caches, buf, scores, cur))
            return (caches, buf, scores, last, pos + 1), None

        extra = {f"in_{t.guid}": jnp.asarray(v)
                 for t, v in (extra_inputs or {}).items()}
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        ckey = ("beam", B, P, N, K, eos_id, tok_t.guid,
                pos_t.guid if pos_t is not None else None,
                tuple(sorted((k, v.shape) for k, v in extra.items())))
        run = cache.get(ckey)
        if run is None:
            def run(params, stats, extra, feed, use):
                pre_env = self._prefill_static(params, stats, extra,
                                               extra_guids, static_ops,
                                               repeat=K)
                caches0 = self.init_decode_caches(BK, s_max,
                                                  skip=static_names)
                # beams 1..K-1 start at -inf so the first free step
                # expands from beam 0 alone
                scores0 = jnp.tile(
                    jnp.concatenate([jnp.zeros((1,)),
                                     jnp.full((K - 1,), -jnp.inf)])[None],
                    (B, 1)).astype(jnp.float32)
                carry0 = (caches0, jnp.zeros((BK, N), jnp.int32), scores0,
                          jnp.zeros((BK,), jnp.int32),
                          jnp.zeros((), jnp.int32))
                # T = P+N-1 steps: positions 0..P-2 feed the prompt;
                # positions P-1..P+N-2 expand (N beam updates)
                (caches, buf, scores, last, _), _ = jax.lax.scan(
                    lambda c, i: step(params, stats, pre_env, c, i),
                    carry0, (feed, use, do_exp))
                return buf.reshape(B, K, N), scores

            run = (self._lowering.jit_step(run)
                   if self._lowering is not None else jax.jit(run))
            if self._memplane is not None:
                run = self._memplane.wrap(
                    f"beam_search:{B}x{P}x{N}x{K}", run)
            cache[ckey] = run

        feed = jnp.concatenate(
            [toks.T, jnp.zeros((N - 1, B), jnp.int32)]) if N > 1 else toks.T
        use = jnp.concatenate([jnp.ones((P,), bool),
                               jnp.zeros((N - 1,), bool)])
        do_exp = jnp.concatenate([jnp.zeros((P - 1,), bool),
                                  jnp.ones((N,), bool)])
        seqs, scores = run(self._decode_params(), self._stats, extra, feed, use)
        seqs, scores = np.asarray(seqs), np.asarray(scores)
        if length_penalty > 0.0 and eos_id is not None:
            # without an eos all lens == N and the re-rank is a no-op
            hits = seqs == eos_id                          # (B, K, N)
            lens = np.where(hits.any(-1),
                            hits.argmax(-1) + 1, N).astype(np.float64)
            norm = scores / (((5.0 + lens) / 6.0) ** length_penalty)
            order = np.argsort(-norm, axis=1, kind="stable")  # best first
            seqs = np.take_along_axis(seqs, order[:, :, None], axis=1)
            scores = np.take_along_axis(scores, order, axis=1)
        return seqs, scores

    # ------------------------------------------------------------------
    # metrics (reference: UPDATE_METRICS_TASK fold, model.cc:1145-1167)
    # ------------------------------------------------------------------
    def reset_metrics(self) -> None:
        if self._nonfinite_guard is not None and self._metric_acc is not None:
            # Guard entries (skip counts, consec run length) ride the
            # accumulator — drain before discarding so narration and
            # escalation can't be dropped by an epoch-boundary reset.
            self._drain_metrics()
        self.current_metrics.reset()
        self.last_loss = None
        self._metric_acc = None

    def _drain_metrics(self) -> None:
        if self._metric_acc is not None:
            if self._telemetry is not None:
                with self._telemetry.span("metric_drain"):
                    vec = jax.device_get(self._metric_acc)
            else:
                vec = jax.device_get(self._metric_acc)  # single small transfer
            totals = dict(zip(self._metric_keys(), [float(v) for v in vec]))
            steps = totals.pop("steps", 0.0)
            loss_sum = totals.pop("loss", None)
            if steps > 0 and loss_sum is not None:
                self.last_loss = loss_sum / steps  # mean loss since last drain
            guard = self._nonfinite_guard
            guard_vals = None
            if guard is not None:
                guard_vals = {k: totals.pop(k, 0.0) for k in guard.METRIC_KEYS}
            if self._health is not None:
                from .observability.health import HEALTH_METRIC_KEYS
                health_vals = {k: totals.pop(k) for k in
                               HEALTH_METRIC_KEYS if k in totals}
                self._health.on_drain(health_vals, steps, self._step_count)
            elif guard is not None:
                # Health entries rode the vector only for the guard's
                # skip decision; pop so they don't leak into PerfMetrics.
                from .observability.health import HEALTH_METRIC_KEYS
                for k in HEALTH_METRIC_KEYS:
                    totals.pop(k, None)
            self.current_metrics.update(totals)
            self._metric_acc = jnp.zeros_like(self._metric_acc)
            if guard_vals is not None:
                consec = guard_vals.get("consec_skipped", 0.0)
                if consec > 0:
                    # consec_skipped is a run length, not a window sum:
                    # carry it through the accumulator reset so a NaN
                    # streak spanning drains still escalates.
                    ci = self._metric_keys().index("consec_skipped")
                    self._metric_acc = self._metric_acc.at[ci].set(consec)
                # Last: on_drain may raise NonFiniteEscalationError and
                # the window's totals are already folded in above.
                guard.on_drain(guard_vals.get("skipped_steps", 0.0),
                               consec, steps, self._step_count)

    def get_metrics(self) -> PerfMetrics:
        self._drain_metrics()
        return self.current_metrics

    def print_metrics(self) -> None:
        self.get_metrics().print()

    def sync(self) -> None:
        """Block until all dispatched device work completes (the analogue
        of the reference's execution fence + timing future).  Forces a
        small device→host transfer: a real synchronization barrier on
        every backend (block_until_ready alone does not block on some
        experimental PJRT platforms)."""
        if self._chaos is not None:
            self._chaos.fire("sync", model=self)
        self._he_join()
        if self._metric_acc is not None:
            jax.device_get(self._metric_acc)
        elif self._params is not None:
            leaf = jax.tree.leaves(self._params)[0]
            jax.device_get(jnp.sum(leaf))

    # ------------------------------------------------------------------
    # weight access (reference: Parameter::set_weights/get_weights,
    # src/runtime/model.cu:260-370)
    # ------------------------------------------------------------------
    # ------------------------------------------------------------------
    # checkpoint / profiling (runtime/checkpoint.py, runtime/profiling.py)
    # ------------------------------------------------------------------
    def save(self, path: str) -> None:
        """Save full training state (params/stats/optimizer/step)."""
        from .runtime.checkpoint import save_checkpoint
        save_checkpoint(self, path)

    def load(self, path: str) -> None:
        """Restore state saved by ``save``, re-sharded onto this mesh."""
        from .runtime.checkpoint import load_checkpoint
        load_checkpoint(self, path)

    def print_op_profile(self) -> None:
        """Per-op fwd/bwd ms (reference --profiling printouts)."""
        from .runtime.profiling import print_op_profile
        print_op_profile(self)

    def print_layers(self) -> None:
        """Per-op metadata dump (reference: FFModel::print_layers,
        src/runtime/model.cc — op type, output dims, weights, placement)."""
        strategies = self.get_strategies() if self._compiled else {}
        for i, op in enumerate(self.ops):
            pc = strategies.get(op.name)
            pcs = f" pc={list(pc.dims)}" if pc is not None else ""
            print(f"layer[{i}] {op.name} ({op._type}) "
                  f"out={op.output.dims}{pcs}")
            for w in op.weights:
                print(f"   weight {w.name}: {w.dims}")

    def _pack_entry(self, op_name: str, weight_name: str):
        pack = self._pipe_pack()
        if pack and op_name in pack["entries"]:
            return pack["entries"][op_name].get(weight_name)
        return None

    def get_parameter(self, op_name: str, weight_name: str = "kernel") -> np.ndarray:
        """Fetch a weight as numpy (reference: Parameter::get_weights).

        Multi-process NOTE: for a row-range-sharded host-resident
        embedding table this assembles the FULL table via a process
        allgather — a COLLECTIVE, so every process must call it in the
        same order (a rank-0-only call deadlocks, like any collective).
        """
        self._he_join()
        e = self._pack_entry(op_name, weight_name)
        if e is not None:
            # Slice the slot row on device first — fetching the whole
            # (ring, width) buffer per accessor call would move the
            # entire packed segment for one weight.
            _, off, shape, n = e
            row = self._params["_pipe"]["buffer"][e[0], off:off + n]
            return np.asarray(row).reshape(shape)
        w = self._params[op_name][weight_name]
        if isinstance(w, np.ndarray):
            info = self._he_info(op_name, weight_name)
            if info is not None:
                # row-range-sharded across processes: return the FULL
                # table (single-process accessor semantics)
                return self._he_assemble_full(info, w)
            # host-resident table: np.asarray would alias the live
            # array the scatter-back mutates in place — copy, matching
            # the device leaves (device_get always materializes fresh)
            return w.copy()
        return np.asarray(w)

    def set_parameter(self, op_name: str, weight_name: str, value: np.ndarray) -> None:
        self._he_join()
        e = self._pack_entry(op_name, weight_name)
        if e is not None:
            cur = self._params["_pipe"]["buffer"]
            new = self._pack_write(jnp.asarray(cur), e,
                                   jnp.asarray(value, jnp.float32))
            self._params["_pipe"]["buffer"] = jax.device_put(new, cur.sharding)
            # in-place rebind keeps id(self._params): the identity-keyed
            # decode caches would otherwise serve the pre-set weight
            self._dp_cache = None
            self._he_dev_cache = None
            return
        cur = self._params[op_name][weight_name]
        if isinstance(cur, np.ndarray):  # row-sparse host-resident table
            info = self._he_info(op_name, weight_name)
            if info is not None:  # full table in, own row range kept
                value = np.asarray(value)[info["row_lo"]:info["row_hi"]]
            self._params[op_name][weight_name] = np.asarray(
                value, dtype=cur.dtype).reshape(cur.shape).copy()
            self._he_version += 1
            self._he_dev_cache = None
            return
        self._params[op_name][weight_name] = jax.device_put(
            jnp.asarray(value, dtype=cur.dtype), cur.sharding)

    def get_strategies(self) -> Dict[str, ParallelConfig]:
        return self._all_strategies()
