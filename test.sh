#!/usr/bin/env bash
# Test-suite driver — the analogue of the reference's python/test.sh
# (which runs ~30 flexflow_python example invocations as the de-facto
# suite).  Here: the pytest suite on a virtual 8-device CPU mesh, then
# (with RUN_EXAMPLES=1) the example apps with VerifyMetrics assertions.
#
# Two gates:
#   ./test.sh          fast gate — `-m "not slow"`, the default loop
#   FULL=1 ./test.sh   everything, including slow integration tests
# (tests/conftest.py enables the persistent XLA compile cache, so warm
# re-runs are much faster than the first.)
set -e
cd "$(dirname "$0")"

python -m flexflow_tpu.tools.doctor --skip-accelerator

if [ -n "$FULL" ]; then
  python -m pytest tests/ -q "$@"
else
  python -m pytest tests/ -q -m "not slow" "$@"
fi

# Telemetry smoke: a 2-step tiny training run under FF_TELEMETRY +
# FF_HEALTH + FF_MEMPLANE must produce a readable trace (including the
# compile plane's owned-compile and XLA introspection events), a
# heartbeat file, and all three reports must fold it
# (docs/observability.md).
SMOKE_DIR=$(mktemp -d)
TRACE="$SMOKE_DIR/smoke.jsonl"
HEARTBEAT="$SMOKE_DIR/hb.json"
FF_TELEMETRY=1 FF_TELEMETRY_FILE="$TRACE" FF_MEMPLANE=1 \
  FF_HEALTH=1 FF_HEARTBEAT_PATH="$HEARTBEAT" \
  python examples/alexnet.py -b 8 --iterations 2 -e 1 > /dev/null
REPORT=$(python -m flexflow_tpu.tools.trace_report "$TRACE")
echo "$REPORT" | grep -q "## Steps" \
  || { echo "telemetry smoke: report missing step section"; exit 1; }
python -m flexflow_tpu.tools.health_report "$TRACE" > /dev/null \
  || { echo "health smoke: health_report failed"; exit 1; }
grep -q '"phase"' "$HEARTBEAT" \
  || { echo "health smoke: heartbeat file missing/empty"; exit 1; }
grep -q '"name": "compile_done"' "$TRACE" \
  || { echo "memory smoke: no compile_done event in trace"; exit 1; }
grep -q '"name": "xla_memory"' "$TRACE" \
  || { echo "memory smoke: no xla_memory event in trace"; exit 1; }
MEMREPORT=$(python -m flexflow_tpu.tools.memory_report "$TRACE") \
  || { echo "memory smoke: memory_report failed"; exit 1; }
echo "$MEMREPORT" | grep -q "headroom: \*\*" \
  || { echo "memory smoke: report missing headroom line"; exit 1; }
echo "telemetry+health+memory smoke: OK ($(wc -l < "$TRACE") trace records)"

# Lowering smoke: the whole-graph lowered step (FF_LOWERED=1) must be
# BITWISE-identical to per-op dispatch on a hybrid SOAP strategy, and
# bench.py --lowered must land a lowering_speedup perf-ledger entry
# (docs/lowering.md).
python - <<'EOF' \
  || { echo "lowering smoke: lowered/dispatch parity failed"; exit 1; }
import numpy as np
import flexflow_tpu as ff

def run(lowered):
    strategies = {"fc1": ff.ParallelConfig(dims=(2, 4)),
                  "fc2": ff.ParallelConfig(dims=(8, 1)),
                  "sm": ff.ParallelConfig(dims=(8, 1))}
    cfg = ff.FFConfig(batch_size=16, strategies=strategies, lowered=lowered)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 8), nchw=False)
    t = m.dense(inp, 16, activation=ff.ActiMode.RELU, name="fc1")
    m.softmax(m.dense(t, 4, name="fc2"), name="sm")
    m.compile(ff.SGDOptimizer(lr=0.1),
              "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=0)
    assert (m._lowering is not None) is lowered, m._lowering
    rng = np.random.default_rng(1)
    x = rng.standard_normal((16, 8), np.float32)
    y = rng.integers(0, 4, (16, 1), dtype=np.int32)
    m.set_batch({inp: x}, y)
    for _ in range(2):
        m.train_iteration()
    m.sync()
    return np.asarray(m.get_parameter("fc1", "kernel"))

a, b = run(False), run(True)
assert np.array_equal(a, b), np.abs(a - b).max()
print("lowering parity: bitwise OK")
EOF
LOWERED_LEDGER="$SMOKE_DIR/lowered_ledger.jsonl"
FF_BENCH_LOWERED_BATCH=8 FF_BENCH_LOWERED_STEPS=2 \
  FF_PERF_LEDGER="$LOWERED_LEDGER" \
  python bench.py --lowered > "$SMOKE_DIR/bench_lowered.out" \
  || { echo "lowering smoke: bench.py --lowered exited non-zero"; exit 1; }
grep -q '"metric": "lowering_speedup"' "$LOWERED_LEDGER" \
  || { echo "lowering smoke: no lowering_speedup ledger entry"; exit 1; }
echo "lowering smoke: OK ($(python -c "
import json
lines = [l for l in open('$SMOKE_DIR/bench_lowered.out') if l.strip().startswith('{')]
r = json.loads(lines[-1])
print(f\"{r['value']}x lowered/dispatch ({r['backend']})\")"))"

# Degradation-ladder smoke: with no chip attached, bench.py must DEGRADE
# (CPU proxy metric stamped proxy:true, rc=0, a parseable perf-ledger
# entry) instead of dying — the "bench never returns rc=1 without a
# result line" contract (docs/observability.md "Chip-session perf
# observatory").
PROXY_OUT="$SMOKE_DIR/bench_proxy.out"
FF_BENCH_FORCE_PROXY=1 FF_BENCH_PROXY_BATCH=8 FF_BENCH_PROXY_STEPS=2 \
  FF_PERF_LEDGER="$SMOKE_DIR/ledger.jsonl" \
  FF_BENCH_EXTRA_PATH="$SMOKE_DIR/bench_extra.json" \
  FF_HEARTBEAT_PATH="$SMOKE_DIR/bench_hb.json" \
  python bench.py > "$PROXY_OUT" \
  || { echo "proxy bench smoke: bench.py exited non-zero"; exit 1; }
python - "$PROXY_OUT" "$SMOKE_DIR/ledger.jsonl" <<'EOF' \
  || { echo "proxy bench smoke: result/ledger acceptance failed"; exit 1; }
import json, sys
lines = []
for raw in open(sys.argv[1]):
    try:
        lines.append(json.loads(raw.strip()))
    except ValueError:
        pass
assert lines, "no JSON result line on stdout"
r = lines[-1]
assert r.get("proxy") is True and r.get("backend") == "cpu", r
assert r.get("value", 0) > 0, r
entries = [json.loads(l) for l in open(sys.argv[2]) if l.strip()]
assert entries, "no ledger entry"
e = entries[-1]
assert e["proxy"] and e["status"] == "ok" and "commit" in e, e
EOF
python -m flexflow_tpu.tools.perf_ledger report \
    --ledger "$SMOKE_DIR/ledger.jsonl" | grep -q "# Perf ledger" \
  || { echo "proxy bench smoke: ledger report failed"; exit 1; }
echo "proxy bench smoke: OK ($(python -c "
import json, sys
lines = [l for l in open('$PROXY_OUT') if l.strip().startswith('{')]
r = json.loads(lines[-1])
print(f\"{r['value']} {r['unit']} (proxy)\")" ))"

# Search-observability smoke: a seeded tiny-budget search must produce a
# candidate-level trace + provenance sidecar, search_report must explain
# it, and --diff must name changed ops vs the shipped strategy
# (docs/observability.md "Search tracing").  --engine python so every
# proposal is recorded (the native engine logs summaries only).
STRACE="$SMOKE_DIR/search.jsonl"
FF_TELEMETRY=1 FF_TELEMETRY_FILE="$STRACE" \
  python -m flexflow_tpu.tools.offline_search alexnet --devices 16 \
    --budget 20 --seed 0 --engine python --quiet \
    --export "$SMOKE_DIR/alexnet_new.pb" > /dev/null
test -f "$SMOKE_DIR/alexnet_new.pb.meta.json" \
  || { echo "search smoke: provenance sidecar missing"; exit 1; }
SREPORT=$(python -m flexflow_tpu.tools.search_report "$STRACE")
echo "$SREPORT" | grep -q "## Why this config" \
  || { echo "search smoke: report missing why-this-config section"; exit 1; }
python -m flexflow_tpu.tools.search_report \
    --diff strategies/alexnet_16.pb "$SMOKE_DIR/alexnet_new.pb" \
  | grep -q "changed /" \
  || { echo "search smoke: strategy diff failed"; exit 1; }
echo "search smoke: OK ($(wc -l < "$STRACE") trace records)"

# Delta-simulation smoke: the incremental simulator must return the
# IDENTICAL seeded search result as the full rebuild (search_bench exits
# 1 on any mismatch) and append a search_throughput entry to the perf
# ledger (docs/simulator.md "Delta simulation").  Tiny budget: this
# verifies the equality contract and the ledger plumbing, not the 10x
# throughput number — that is search_bench's default-budget job.
DELTA_LEDGER="$SMOKE_DIR/delta_ledger.jsonl"
DELTA_OUT=$(python -m flexflow_tpu.tools.search_bench alexnet --devices 16 \
    --budget 200 --seed 0 --repeats 1 --ledger "$DELTA_LEDGER") \
  || { echo "delta smoke: search_bench failed (delta vs full mismatch?)"; exit 1; }
grep -q '"metric": "search_throughput"' "$DELTA_LEDGER" \
  || { echo "delta smoke: no search_throughput ledger entry"; exit 1; }
echo "delta smoke: OK ($(echo "$DELTA_OUT" | python -c "
import json, sys
b = json.loads(sys.stdin.read())
print(f\"identical={b['identical']}, {b['delta_proposals_per_s']} vs \"
      f\"{b['full_proposals_per_s']} proposals/s, ratio {b['ratio']}x\")"))"

# Population-search smoke: search_bench --mode quality runs the
# single-chain and population engines at an equal (tiny) budget on a
# small transformer, judges both winners under one fresh reference
# simulator, and appends a search_quality entry (value = single_ms /
# population_ms, higher is better) that the perf-ledger report must
# render without flagging a regression (docs/simulator.md
# "Population search").
POP_LEDGER="$SMOKE_DIR/pop_ledger.jsonl"
POP_OUT=$(python -m flexflow_tpu.tools.search_bench transformer --devices 16 \
    --batch-size 32 --budget 600 --seed 0 --mode quality \
    --ledger "$POP_LEDGER") \
  || { echo "population smoke: search_bench --mode quality failed"; exit 1; }
grep -q '"metric": "search_quality"' "$POP_LEDGER" \
  || { echo "population smoke: no search_quality ledger entry"; exit 1; }
python -m flexflow_tpu.tools.perf_ledger report --ledger "$POP_LEDGER" \
  | grep -q "# Perf ledger" \
  || { echo "population smoke: ledger report failed"; exit 1; }
python -m flexflow_tpu.tools.perf_ledger report --ledger "$POP_LEDGER" \
  | grep -q "REGRESSION" \
  && { echo "population smoke: report flags a regression on a fresh ledger"; exit 1; }
echo "population smoke: OK ($(echo "$POP_OUT" | python -c "
import json, sys
b = json.loads(sys.stdin.read())
print(f\"single {b['single_ms']}ms vs population {b['population_ms']}ms, \"
      f\"ratio {b['ratio']}x\")"))"

# Serving smoke: train the toy transformer, serve 8 concurrent HTTP
# requests through the continuous-batching engine, verify every greedy
# output bitwise against one-shot generate(), and fold the serving
# trace into a latency/occupancy report (docs/serving.md).
SERVE_TRACE="$SMOKE_DIR/serve.jsonl"
FF_TELEMETRY=1 FF_TELEMETRY_FILE="$SERVE_TRACE" \
  python -m flexflow_tpu.tools.loadgen --requests 8 --concurrency 4 \
    --seed 0 --train-iters 20 --check-generate \
    --out "$SMOKE_DIR/BENCH_SERVE.json" \
  || { echo "serving smoke: loadgen failed (request error or greedy mismatch)"; exit 1; }
python -m flexflow_tpu.tools.serve_report "$SERVE_TRACE" \
  | grep -q "## Latency" \
  || { echo "serving smoke: serve_report missing latency section"; exit 1; }
python - "$SMOKE_DIR/BENCH_SERVE.json" <<'EOF' \
  || { echo "serving smoke: BENCH_SERVE.json acceptance failed"; exit 1; }
import json, sys
b = json.load(open(sys.argv[1]))
assert b["n_ok"] == 8 and b["greedy_matches"] == 8, b
assert b["mean_batch_occupancy"] > 1.5, b["mean_batch_occupancy"]
EOF
echo "serving smoke: OK ($(python -c "
import json, sys
b = json.load(open('$SMOKE_DIR/BENCH_SERVE.json'))
print(f\"{b['achieved_tokens_s']} tok/s, occupancy {b['mean_batch_occupancy']}\")"))"

# Paged-KV smoke: a shared 16-token system prompt across a mixed-length
# request mix — the block-paged engine must reuse the cached prefix
# (prefix_hit_rate > 0, prefill tokens actually skipped), stay bitwise
# against one-shot generate(), and serve_report must fold the kv gauges
# into its "## KV cache" section (docs/serving.md "Paged KV cache").
PAGED_TRACE="$SMOKE_DIR/paged.jsonl"
FF_TELEMETRY=1 FF_TELEMETRY_FILE="$PAGED_TRACE" \
  python -m flexflow_tpu.tools.loadgen --requests 8 --concurrency 4 \
    --seed 0 --prefix-tokens 16 --len-dist mixed --check-generate \
    --out "$SMOKE_DIR/BENCH_PAGED.json" \
  || { echo "paged smoke: loadgen failed (request error or greedy mismatch)"; exit 1; }
python - "$SMOKE_DIR/BENCH_PAGED.json" <<'EOF' \
  || { echo "paged smoke: BENCH_PAGED.json acceptance failed"; exit 1; }
import json, sys
b = json.load(open(sys.argv[1]))
assert b["paged"] is True and b["n_ok"] == 8 and b["greedy_matches"] == 8, b
assert b["prefix_hit_rate"] > 0, b["prefix_hit_rate"]
assert b["prefill_tokens_saved"] > 0, b["prefill_tokens_saved"]
assert b["kv_blocks_peak"] > 0, b["kv_blocks_peak"]
EOF
python -m flexflow_tpu.tools.serve_report "$PAGED_TRACE" \
  | grep -q "## KV cache" \
  || { echo "paged smoke: serve_report missing KV cache section"; exit 1; }
echo "paged smoke: OK ($(python -c "
import json
b = json.load(open('$SMOKE_DIR/BENCH_PAGED.json'))
print(f\"hit rate {b['prefix_hit_rate']}, \"
      f\"{b['prefill_tokens_saved']} prefill tokens saved, \"
      f\"peak {b['kv_blocks_peak']} blocks\")"))"

# Metrics + tracing smoke: live /metrics while loadgen drives a
# 2-replica pool with every request traced (FF_TRACE_SAMPLE=1) — one
# mid-load scrape must return serving gauges (per-replica health,
# paged-KV block occupancy), training counters, AND the SLO burn-rate
# gauges in valid Prometheus text; afterwards the trace must fold into
# Perfetto-loadable Chrome-trace JSON with request tracks whose attempt
# spans nest prefill + decode children (docs/observability.md "Live
# metrics endpoint", "Request tracing", "Timeline export").
METRICS_PORT=9109
METRICS_TRACE="$SMOKE_DIR/metrics_serve.jsonl"
FF_TELEMETRY=1 FF_TELEMETRY_FILE="$METRICS_TRACE" FF_MEMPLANE=1 \
  FF_METRICS_PORT=$METRICS_PORT FF_METRICS_HOST=127.0.0.1 \
  FF_TRACE_SAMPLE=1 \
  python -m flexflow_tpu.tools.loadgen --requests 24 --concurrency 4 \
    --replicas 2 --seed 0 --train-iters 20 \
    --out "$SMOKE_DIR/BENCH_METRICS.json" > /dev/null &
LOADGEN_PID=$!
python - "$METRICS_PORT" <<'EOF' \
  || { kill $LOADGEN_PID 2>/dev/null; echo "metrics smoke: scrape failed"; exit 1; }
import re, sys, time, urllib.request
url = f"http://127.0.0.1:{sys.argv[1]}/metrics"
want = ("ff_replica_up", "ff_samples_total",   # serving + training series
        "ff_serve_kv_blocks_used", "ff_serve_kv_blocks_free",  # paged KV
        "ff_hbm_bytes",                # KV-pool block bytes (CPU has no
                                       # allocator stats; pool gauge only)
        "ff_compile_retraces_total",   # compile plane: flat-ladder ledger
        "ff_slo_burn_rate",            # SLO evaluator riding the same tap
        "ff_slo_budget_remaining")
sample = re.compile(r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [-+0-9.eE]+$')
deadline = time.time() + 180
while time.time() < deadline:
    try:
        with urllib.request.urlopen(url, timeout=5) as r:
            assert r.headers["Content-Type"].startswith("text/plain"), \
                r.headers["Content-Type"]
            text = r.read().decode()
    except OSError:
        time.sleep(0.5)
        continue
    if all(w in text for w in want):
        n = 0
        for line in text.splitlines():
            if line and not line.startswith("#"):
                assert sample.match(line), f"malformed sample: {line!r}"
                n += 1
        slo = [l for l in text.splitlines()
               if l.startswith("ff_slo_burn_rate")]
        assert slo, "no ff_slo_burn_rate sample"
        print(f"metrics smoke: scraped {n} well-formed samples mid-load "
              f"({len(slo)} SLO burn-rate series)")
        sys.exit(0)
    time.sleep(0.5)
sys.exit(f"never saw {want} at {url}")
EOF
wait $LOADGEN_PID \
  || { echo "metrics smoke: loadgen exited non-zero"; exit 1; }
echo "metrics smoke: OK"

# Timeline smoke: fold the traced run into Chrome trace-event JSON.
TIMELINE="$SMOKE_DIR/timeline.json"
python -m flexflow_tpu.tools.timeline_export "$METRICS_TRACE" -o "$TIMELINE" \
  || { echo "timeline smoke: export failed"; exit 1; }
python - "$TIMELINE" <<'EOF' \
  || { echo "timeline smoke: Chrome-trace acceptance failed"; exit 1; }
import collections, json, sys
doc = json.load(open(sys.argv[1]))
evs = [e for e in doc["traceEvents"] if e["ph"] != "M"]
assert evs, "empty timeline"
for a, b in zip(evs, evs[1:]):           # Perfetto ground rule 1
    assert a["ts"] <= b["ts"], (a, b)
depth = collections.Counter()            # ground rule 2: matched B/E
for e in evs:
    k = (e["pid"], e["tid"])
    if e["ph"] == "B":
        depth[k] += 1
    elif e["ph"] == "E":
        depth[k] -= 1
        assert depth[k] >= 0, f"E without B on {k}"
assert all(v == 0 for v in depth.values()), depth
# >=1 request track whose attempt span nests prefill + decode children
tracks = doc["otherData"]["request_tracks"]
assert tracks, "no request tracks despite FF_TRACE_SAMPLE=1"
procs = {e["pid"]: e["args"]["name"] for e in doc["traceEvents"]
         if e["ph"] == "M" and e["name"] == "process_name"}
req_pids = {p for p, n in procs.items() if n == "requests"}
by_tid = collections.defaultdict(list)
for e in evs:
    if e["pid"] in req_pids and e["ph"] == "B":
        by_tid[e["tid"]].append(e["name"])
nested = [tid for tid, names in by_tid.items()
          if names[0] == "serve_attempt"
          and "serve_prefill" in names and "serve_decode" in names]
assert nested, f"no attempt track nests prefill+decode: {dict(by_tid)}"
print(f"timeline smoke: {len(evs)} events, {len(tracks)} request "
      f"tracks, {len(nested)} attempt tracks with prefill+decode")
EOF
echo "timeline smoke: OK"

# Chaos smoke: one seeded FF_CHAOS run injects a NaN step, a mid-epoch
# SIGTERM, and a failing checkpoint write; the resumed run must finish
# bitwise-equal to an uninterrupted baseline and the trace must narrate
# every recovery (docs/robustness.md).
python -m flexflow_tpu.testing.chaos_smoke --workdir "$SMOKE_DIR/chaos" \
  || { echo "chaos smoke: FAILED"; exit 1; }
python -m flexflow_tpu.tools.trace_report "$SMOKE_DIR/chaos/victim_trace.jsonl" \
  | grep -q "## Resilience" \
  || { echo "chaos smoke: trace report missing resilience section"; exit 1; }
echo "chaos smoke: OK"

# Reshard smoke: chaos kills half the mesh mid-run; the reconfiguration
# controller must re-search on the survivors, hot-swap deterministically,
# leave a diffable swap-record pair, and health_report must narrate the
# swap (docs/robustness.md "Online re-parallelization").
python -m flexflow_tpu.testing.chaos_smoke --workdir "$SMOKE_DIR/reshard" \
    --scenario reshard \
  || { echo "reshard smoke: FAILED"; exit 1; }
python -m flexflow_tpu.tools.health_report "$SMOKE_DIR/reshard/run1/trace.jsonl" \
  | grep -q "## Reconfiguration" \
  || { echo "reshard smoke: health report missing reconfiguration section"; exit 1; }
echo "reshard smoke: OK"

# Serve-failover smoke: chaos kills 1 of 3 pool replicas mid-load; all
# requests (incl. the killed replica's in-flight ones) must complete
# bitwise-equal to one-shot generate(), the monitor must restart the
# replica, serve_report must show the per-replica lens, and the goodput
# headline lands in BENCH_SERVE.json (docs/serving.md "Resilience").
python -m flexflow_tpu.testing.chaos_smoke --workdir "$SMOKE_DIR/serve_failover" \
    --scenario serve_failover \
  || { echo "serve-failover smoke: FAILED"; exit 1; }
python -m flexflow_tpu.tools.serve_report "$SMOKE_DIR/serve_failover/serve_trace.jsonl" \
  | grep -q "## Replicas" \
  || { echo "serve-failover smoke: serve_report missing replicas section"; exit 1; }
python - "$SMOKE_DIR/serve_failover/BENCH_SERVE.json" <<'EOF' \
  || { echo "serve-failover smoke: BENCH_SERVE.json acceptance failed"; exit 1; }
import json, sys
b = json.load(open(sys.argv[1]))
assert b["n_ok"] == b["requests"] and b["n_fail"] == 0, b
assert b["goodput_rps"] > 0, b
assert b["pool"]["replica_downs"] >= 1 and b["pool"]["failovers"] >= 1, b
EOF
echo "serve-failover smoke: OK ($(python -c "
import json
b = json.load(open('$SMOKE_DIR/serve_failover/BENCH_SERVE.json'))
print(f\"goodput {b['goodput_rps']} req/s, \"
      f\"{b['pool']['failovers']} failovers\")"))"

# Zone-outage smoke: chaos downs a WHOLE ZONE of a 4-replica, 2-zone
# pool mid-load with the autoscaler running; every request (incl. the
# dead zone's in-flight ones) must complete bitwise-equal to generate(),
# re-dispatches must avoid the dead zone, and the autoscaler must
# backfill the surviving zone (docs/robustness.md "Zone outages").
python -m flexflow_tpu.testing.chaos_smoke --workdir "$SMOKE_DIR/zone_outage" \
    --scenario zone_outage \
  || { echo "zone-outage smoke: FAILED"; exit 1; }
python -m flexflow_tpu.tools.serve_report "$SMOKE_DIR/zone_outage/zone_trace.jsonl" \
  | grep -q "## Fleet" \
  || { echo "zone-outage smoke: serve_report missing fleet section"; exit 1; }
echo "zone-outage smoke: OK"

# Fleet smoke: the seeded flash-crowd incident scenario against a live
# pool+autoscaler — BENCH_FLEET.json must parse with zero lost/incorrect
# responses and nonzero SLO goodput, and the run lands a fleet_goodput
# perf-ledger entry (docs/serving.md "Fleet scenarios").  The zone
# scenario is exercised (with asserts) by the zone-outage smoke above;
# here the cheap traffic shape keeps the gate fast.
python -m flexflow_tpu.tools.fleet_bench --scenarios flash_crowd \
    --requests 10 --seed 0 --workdir "$SMOKE_DIR/fleet" \
    --ledger "$SMOKE_DIR/fleet_ledger.jsonl" \
  || { echo "fleet smoke: fleet_bench FAILED"; exit 1; }
python - "$SMOKE_DIR/fleet/BENCH_FLEET.json" <<'EOF' \
  || { echo "fleet smoke: BENCH_FLEET.json acceptance failed"; exit 1; }
import json, sys
b = json.load(open(sys.argv[1]))
assert b["bench"] == "fleet" and b["scenarios"], b.keys()
for name, s in b["scenarios"].items():
    assert s["n_lost"] == 0 and s["n_incorrect"] == 0, (name, s)
    assert s["goodput_rps"] > 0, (name, s["goodput_rps"])
EOF
grep -q '"metric": "fleet_goodput"' "$SMOKE_DIR/fleet_ledger.jsonl" \
  || { echo "fleet smoke: no fleet_goodput ledger entry"; exit 1; }
echo "fleet smoke: OK ($(python -c "
import json
b = json.load(open('$SMOKE_DIR/fleet/BENCH_FLEET.json'))
s = b['scenarios']['flash_crowd']
print(f\"goodput {s['goodput_rps']}/{s['offered_rps']} rps, \"
      f\"attainment {s['slo_attainment']:.0%}\")"))"

if [ -n "$RUN_EXAMPLES" ]; then
  for ex in examples/mnist_mlp_native.py \
            examples/keras/seq_mnist_mlp.py \
            examples/keras/func_mnist_mlp_concat.py; do
    echo "== $ex"
    python "$ex" -e 1 -b 64
  done
fi
echo "test.sh: OK"
