#!/usr/bin/env bash
# Test-suite driver — the analogue of the reference's python/test.sh
# (which runs ~30 flexflow_python example invocations as the de-facto
# suite).  Here: the pytest suite on a virtual 8-device CPU mesh, then
# (with RUN_EXAMPLES=1) the example apps with VerifyMetrics assertions.
#
# Two gates:
#   ./test.sh          fast gate — `-m "not slow"`, the default loop
#   FULL=1 ./test.sh   everything, including slow integration tests
# (tests/conftest.py enables the persistent XLA compile cache, so warm
# re-runs are much faster than the first.)
set -e
cd "$(dirname "$0")"

python -m flexflow_tpu.tools.doctor --skip-accelerator

if [ -n "$FULL" ]; then
  python -m pytest tests/ -q "$@"
else
  python -m pytest tests/ -q -m "not slow" "$@"
fi

# Telemetry smoke: a 2-step tiny training run under FF_TELEMETRY +
# FF_HEALTH must produce a readable trace, a heartbeat file, and both
# reports must fold it (docs/observability.md).
SMOKE_DIR=$(mktemp -d)
TRACE="$SMOKE_DIR/smoke.jsonl"
HEARTBEAT="$SMOKE_DIR/hb.json"
FF_TELEMETRY=1 FF_TELEMETRY_FILE="$TRACE" \
  FF_HEALTH=1 FF_HEARTBEAT_PATH="$HEARTBEAT" \
  python examples/alexnet.py -b 8 --iterations 2 -e 1 > /dev/null
REPORT=$(python -m flexflow_tpu.tools.trace_report "$TRACE")
echo "$REPORT" | grep -q "## Steps" \
  || { echo "telemetry smoke: report missing step section"; exit 1; }
python -m flexflow_tpu.tools.health_report "$TRACE" > /dev/null \
  || { echo "health smoke: health_report failed"; exit 1; }
grep -q '"phase"' "$HEARTBEAT" \
  || { echo "health smoke: heartbeat file missing/empty"; exit 1; }
echo "telemetry+health smoke: OK ($(wc -l < "$TRACE") trace records)"

if [ -n "$RUN_EXAMPLES" ]; then
  for ex in examples/mnist_mlp_native.py \
            examples/keras/seq_mnist_mlp.py \
            examples/keras/func_mnist_mlp_concat.py; do
    echo "== $ex"
    python "$ex" -e 1 -b 64
  done
fi
echo "test.sh: OK"
