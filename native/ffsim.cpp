// Native task-graph event-simulation engine.
//
// TPU-native counterpart of the reference's C++ simulator hot loop
// (reference: src/runtime/simulator.cc:410-443 — priority-queue event
// simulation).  The MCMC search calls simulate_runtime once per candidate
// strategy; at search budgets of 10^4-10^5 iterations the event loop
// dominates, so it lives here as a C-ABI shared library driven from
// Python via ctypes (the task graph is built in Python, flattened to
// arrays, and executed here).
//
// Device encoding: each task carries an int64 device key (chips >= 0,
// links < 0); the engine only needs keys to serialize per-device.
//
// Build: make -C native   (produces libffsim.so)

#include <cstdint>
#include <cstring>
#include <queue>
#include <unordered_map>
#include <vector>

namespace {

struct Task {
  double run_time;
  double ready_time;
  int64_t device;
  int32_t counter;
  int32_t order;
};

struct QEntry {
  double ready;
  int32_t order;
  int32_t idx;
};

struct QCmp {
  bool operator()(const QEntry& a, const QEntry& b) const {
    if (a.ready != b.ready) return a.ready > b.ready;
    return a.order > b.order;
  }
};

}  // namespace

extern "C" {

// Simulate a DAG of n tasks.
//   run_times[n]  : per-task compute/comm seconds
//   devices[n]    : per-task device key
//   edge_src/dst  : m dependency edges (src must finish before dst starts)
// Returns the makespan in seconds, or -1.0 on a cycle.
double ffsim_simulate(int32_t n, const double* run_times,
                      const int64_t* devices, int32_t m,
                      const int32_t* edge_src, const int32_t* edge_dst) {
  std::vector<Task> tasks(n);
  std::vector<std::vector<int32_t>> next(n);
  for (int32_t i = 0; i < n; i++) {
    tasks[i].run_time = run_times[i];
    tasks[i].ready_time = 0.0;
    tasks[i].device = devices[i];
    tasks[i].counter = 0;
    tasks[i].order = i;
  }
  for (int32_t e = 0; e < m; e++) {
    next[edge_src[e]].push_back(edge_dst[e]);
    tasks[edge_dst[e]].counter++;
  }
  std::priority_queue<QEntry, std::vector<QEntry>, QCmp> ready;
  for (int32_t i = 0; i < n; i++)
    if (tasks[i].counter == 0) ready.push({0.0, i, i});

  std::unordered_map<int64_t, double> device_time;
  device_time.reserve(64);
  double sim_time = 0.0;
  int32_t processed = 0;
  while (!ready.empty()) {
    QEntry qe = ready.top();
    ready.pop();
    Task& t = tasks[qe.idx];
    double dev_free = 0.0;
    auto it = device_time.find(t.device);
    if (it != device_time.end()) dev_free = it->second;
    double start = t.ready_time > dev_free ? t.ready_time : dev_free;
    double end = start + t.run_time;
    device_time[t.device] = end;
    if (end > sim_time) sim_time = end;
    processed++;
    for (int32_t nx : next[qe.idx]) {
      Task& nt = tasks[nx];
      if (end > nt.ready_time) nt.ready_time = end;
      if (--nt.counter == 0) ready.push({nt.ready_time, nt.order, nx});
    }
  }
  if (processed != n) return -1.0;  // cycle
  return sim_time;
}

}  // extern "C"
