// Native strategy-search engine: task-graph construction + event
// simulation + simulated annealing, entirely in C++.
//
// TPU-native counterpart of the reference's offline strategy searcher
// (reference: scripts/simulator.cc — a pure-C++ cost model + 250k-iteration
// simulated-annealing loop needing no accelerator), generalized from its
// NMT-specific graph to any op graph.  Python enumerates the legal
// candidate ParallelConfigs per op (with per-candidate analytic fwd/bwd
// costs and partition rectangles) and flattens them into arrays; this
// engine then proposes/evaluates candidate assignments at native speed —
// each evaluation rebuilds the task graph (compute tasks, inter-part comm
// from rectangle intersections, bulk-sync weight allreduce groups) and
// runs the priority-queue event simulation, mirroring
// flexflow_tpu/simulator/simulator.py task for task.
//
// Build: make -C native   (produces libffsearch.so)

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <queue>
#include <random>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------- machine
struct Machine {
  int32_t num_devices;
  int32_t chips_per_host;
  int32_t torus_x, torus_y;
  double ici_bw, dcn_bw;
  double elem_bytes;  // activation element size (bf16=2, f32=4)

  int hops(int a, int b) const {
    if (a == b) return 0;
    int ax = a % torus_x, ay = a / torus_x;
    int bx = b % torus_x, by = b / torus_x;
    int dx = std::abs(ax - bx), dy = std::abs(ay - by);
    dx = std::min(dx, torus_x - dx);
    dy = std::min(dy, torus_y - dy);
    return dx + dy;
  }
  bool same_host(int a, int b) const {
    return a / chips_per_host == b / chips_per_host;
  }
  double transfer_time(int a, int b, double bytes) const {
    if (a == b || bytes <= 0) return 0.0;
    if (same_host(a, b))
      return bytes * std::max(1, hops(a, b)) / ici_bw;
    return bytes / dcn_bw;
  }
  double allreduce_time(const std::vector<int>& devs, double bytes) const {
    std::vector<int> u(devs);
    std::sort(u.begin(), u.end());
    u.erase(std::unique(u.begin(), u.end()), u.end());
    size_t n = u.size();
    if (n <= 1 || bytes <= 0) return 0.0;
    double bw = ici_bw;
    for (size_t i = 1; i < n; i++)
      if (!same_host(u[0], u[i])) { bw = dcn_bw; break; }
    return 2.0 * double(n - 1) / double(n) * bytes / bw;
  }
};

// ------------------------------------------------------- flattened model
// Rectangles are [lo, hi] int64 pairs, rank pairs per rect.
struct Candidate {
  int32_t parts;
  const int32_t* devices;            // [parts]
  double fwd_cost, bwd_cost;
  // per output slot k: tiles [parts][rank_k][2] (multi-output ops —
  // e.g. LSTM hidden+cell — feed consumers from different slots)
  std::vector<const int64_t*> out_tiles;
  // per input j: rects [parts][in_rank_j][2], laid out input-major
  std::vector<const int64_t*> in_rects;
  // per weight w: rects [parts][w_rank_w][2]
  std::vector<const int64_t*> w_tiles;
};

struct OpDesc {
  int32_t out_rank;
  std::vector<int32_t> in_rank;      // rank of each input's rects
  std::vector<int32_t> w_rank;       // rank of each weight tile
  std::vector<int32_t> producer;     // producing op index per input, -1 if graph input
  std::vector<int32_t> producer_out; // producing op's OUTPUT SLOT per input
  // Row-sparse grad-sync clamp per weight (embeddings: the gradient
  // touches at most the batch's rows — simulator.py's clamp, mirrored
  // here so both engines share one objective).  -1 = no clamp; else the
  // batch's index count, multiplied by the tile's last-dim extent.
  std::vector<int64_t> sync_rows_cap;
  std::vector<Candidate> cands;
};

int64_t intersect(const int64_t* ra, const int64_t* rb, int rank) {
  int64_t vol = 1;
  for (int d = 0; d < rank; d++) {
    int64_t lo = std::max(ra[2 * d], rb[2 * d]);
    int64_t hi = std::min(ra[2 * d + 1], rb[2 * d + 1]);
    if (hi < lo) return 0;
    vol *= hi - lo + 1;
  }
  return vol;
}

// ------------------------------------------------------------ simulation
struct Sim {
  const Machine* m;
  const std::vector<OpDesc>* ops;
  bool overlap;

  // scratch (reused across evaluations)
  std::vector<double> run_time;
  std::vector<int64_t> device;   // chip id >= 0; links < 0; barrier uses chip
  std::vector<int32_t> edge_src, edge_dst;

  // Host tier (row-sparse host-resident embedding tables): the Python
  // marshaller encodes host placement as device id == num_devices; host
  // tasks run on their own serial timeline, host<->chip bytes ride PCIe
  // priced INSIDE the op's cost (no link task), and host-resident
  // weights need no device allreduce — mirroring simulator.py exactly.
  int host_id() const { return m->num_devices; }
  bool is_host(int d) const { return d >= m->num_devices; }
  int norm(int d) const { return is_host(d) ? host_id() : d % m->num_devices; }

  int add_task(double rt, int64_t dev) {
    run_time.push_back(rt);
    device.push_back(dev);
    return int(run_time.size()) - 1;
  }
  void add_edge(int a, int b) {
    edge_src.push_back(a);
    edge_dst.push_back(b);
  }
  int64_t link_key(int a, int b) const {
    int lo = std::min(a, b), hi = std::max(a, b);
    return -(int64_t(lo) * m->num_devices + hi + 1);
  }
  void xfer(int src_task, int dst_task, int a, int b, int64_t vol) {
    if (vol <= 0) return;
    if (a == b || is_host(a) || is_host(b)) {
      add_edge(src_task, dst_task);
      return;
    }
    double tt = m->transfer_time(a, b, m->elem_bytes * double(vol));
    int c = add_task(tt, link_key(a, b));
    add_edge(src_task, c);
    add_edge(c, dst_task);
  }

  double evaluate(const std::vector<int32_t>& choice) {
    run_time.clear(); device.clear(); edge_src.clear(); edge_dst.clear();
    const auto& O = *ops;
    size_t L = O.size();
    // fwd/bwd task ids per (op, part)
    std::vector<std::vector<int>> fwd(L), bwd(L);
    for (size_t i = 0; i < L; i++) {
      const Candidate& c = O[i].cands[choice[i]];
      fwd[i].resize(c.parts);
      bwd[i].resize(c.parts);
      for (int p = 0; p < c.parts; p++) {
        int dev = norm(c.devices[p]);
        fwd[i][p] = add_task(c.fwd_cost, dev);
        bwd[i][p] = add_task(c.bwd_cost, dev);
        add_edge(fwd[i][p], bwd[i][p]);
      }
    }
    // data deps + comm
    for (size_t i = 0; i < L; i++) {
      const OpDesc& od = O[i];
      const Candidate& c = od.cands[choice[i]];
      for (size_t j = 0; j < od.producer.size(); j++) {
        int pi = od.producer[j];
        if (pi < 0) continue;
        const Candidate& pcand = O[pi].cands[choice[pi]];
        int rank = od.in_rank[j];
        const int64_t* dst_rects = c.in_rects[j];
        const int64_t* src_rects =
            pcand.out_tiles[size_t(od.producer_out[j])];
        for (int dp = 0; dp < c.parts; dp++) {
          const int64_t* dr = dst_rects + size_t(dp) * rank * 2;
          int ddev = norm(c.devices[dp]);
          for (int sp = 0; sp < pcand.parts; sp++) {
            const int64_t* sr = src_rects + size_t(sp) * rank * 2;
            int64_t vol = intersect(dr, sr, rank);
            if (vol > 0) {
              int sdev = norm(pcand.devices[sp]);
              xfer(fwd[pi][sp], fwd[i][dp], sdev, ddev, vol);
              xfer(bwd[i][dp], bwd[pi][sp], ddev, sdev, vol);
            }
          }
        }
      }
    }
    // weight sync: bulk-sync barrier per device, then allreduce groups
    std::vector<int> barrier;
    if (!overlap) {
      barrier.resize(m->num_devices);
      for (int d = 0; d < m->num_devices; d++)
        barrier[d] = add_task(0.0, d);
      for (size_t i = 0; i < L; i++) {
        const Candidate& c = O[i].cands[choice[i]];
        for (int p = 0; p < c.parts; p++) {
          // host parts sync at chip 0's barrier (simulator.py wires the
          // host bwd to barriers[device_ids[p] % nd], which is 0 for the
          // host candidates the marshaller emits)
          int b = is_host(c.devices[p]) ? 0
                                        : c.devices[p] % m->num_devices;
          add_edge(bwd[i][p], barrier[b]);
        }
      }
    }
    std::vector<char> synched;
    std::vector<int> group;
    for (size_t i = 0; i < L; i++) {
      const OpDesc& od = O[i];
      const Candidate& c = od.cands[choice[i]];
      if (c.parts > 0 && is_host(c.devices[0]))
        continue;  // host-resident weights: update is the host scatter
      for (size_t w = 0; w < od.w_rank.size(); w++) {
        int rank = od.w_rank[w];
        const int64_t* tiles = c.w_tiles[w];
        synched.assign(c.parts, 0);
        for (int first = 0; first < c.parts; first++) {
          if (synched[first]) continue;
          synched[first] = 1;
          const int64_t* fr = tiles + size_t(first) * rank * 2;
          group.clear();
          group.push_back(first);
          for (int nxt = first + 1; nxt < c.parts; nxt++) {
            if (synched[nxt]) continue;
            if (intersect(fr, tiles + size_t(nxt) * rank * 2, rank) > 0) {
              synched[nxt] = 1;
              group.push_back(nxt);
            }
          }
          int64_t vol = 1;
          for (int d = 0; d < rank; d++) vol *= fr[2 * d + 1] - fr[2 * d] + 1;
          int64_t cap_rows = od.sync_rows_cap[w];
          if (cap_rows >= 0) {
            int64_t d_tile =
                rank > 0 ? fr[2 * (rank - 1) + 1] - fr[2 * (rank - 1)] + 1 : 1;
            vol = std::min(vol, cap_rows * d_tile);
          }
          std::vector<int> gdevs;
          for (int g : group) gdevs.push_back(c.devices[g] % m->num_devices);
          double art = m->allreduce_time(gdevs, 4.0 * double(vol));
          int upd = add_task(art, gdevs[0]);
          if (!overlap) {
            std::vector<int> u(gdevs);
            std::sort(u.begin(), u.end());
            u.erase(std::unique(u.begin(), u.end()), u.end());
            for (int d : u) add_edge(barrier[d], upd);
          } else {
            for (int g : group) add_edge(bwd[i][g], upd);
          }
        }
      }
    }
    if (std::getenv("FFSEARCH_DUMP")) {
      // one-shot task-graph dump for parity debugging against the
      // python simulator (tests/tools diff the two graphs)
      for (size_t t = 0; t < run_time.size(); t++)
        std::fprintf(stderr, "TASK %zu %.17g %lld\n", t, run_time[t],
                     (long long)device[t]);
      for (size_t e = 0; e < edge_src.size(); e++)
        std::fprintf(stderr, "EDGE %d %d\n", edge_src[e], edge_dst[e]);
      std::fprintf(stderr, "ENDDUMP\n");
    }
    return simulate();
  }

  // priority-queue event simulation (same semantics as ffsim.cpp)
  double simulate() {
    int n = int(run_time.size());
    std::vector<int32_t> counter(n, 0);
    std::vector<std::vector<int32_t>> next(n);
    for (size_t e = 0; e < edge_src.size(); e++) {
      next[edge_src[e]].push_back(edge_dst[e]);
      counter[edge_dst[e]]++;
    }
    struct Q { double ready; int32_t order, idx; };
    struct Cmp {
      bool operator()(const Q& a, const Q& b) const {
        if (a.ready != b.ready) return a.ready > b.ready;
        return a.order > b.order;
      }
    };
    std::priority_queue<Q, std::vector<Q>, Cmp> pq;
    std::vector<double> ready_time(n, 0.0);
    for (int i = 0; i < n; i++)
      if (counter[i] == 0) pq.push({0.0, i, i});
    std::unordered_map<int64_t, double> dev_time;
    double sim_time = 0.0;
    int processed = 0;
    while (!pq.empty()) {
      Q q = pq.top(); pq.pop();
      int i = q.idx;
      double& dt = dev_time[device[i]];
      double start = std::max(dt, ready_time[i]);
      double end = start + run_time[i];
      dt = end;
      sim_time = std::max(sim_time, end);
      processed++;
      for (int32_t nx : next[i]) {
        ready_time[nx] = std::max(ready_time[nx], end);
        if (--counter[nx] == 0) pq.push({ready_time[nx], nx, nx});
      }
    }
    if (processed != n) return -1.0;  // cycle
    return sim_time;
  }
};

}  // namespace

extern "C" {

// Run simulated annealing over flattened candidates.
//
// Layout (all arrays little-endian native):
//   L ops. cand_count[L]; per-op arrays flattened candidate-major via
//   offsets below.  For op i, candidate c (global index g = cand_off[i]+c):
//     parts[g], fwd_cost[g], bwd_cost[g]
//     devices:  dev_off[g] indexes into devices[] ([parts] entries)
//     out tiles: out_off[g*max_outputs + k] indexes into rects[]
//              ([parts*rank_k*2]) for output slot k; unused slots 0
//     inputs:  op i has num_inputs[i] inputs; in_rank at in_rank_off[i]..;
//              producer / producer_out (the producing op's output slot)
//              at same offsets; rect offsets per (g, j) laid out
//              per-candidate: in_rect_off[g * max_inputs + j]
//     weights: num_weights[i]; w_rank at w_rank_off[i]+w;
//              w_tile_off[g * max_weights + w]
//   choice_init[L]: starting candidate per op (data parallel).
//   Returns best simulated runtime; writes best choice into choice_out[L]
//   and the initial(dp) runtime into dp_runtime_out.
double ffsearch_anneal(
    // machine
    int32_t num_devices, int32_t chips_per_host, int32_t torus_x,
    int32_t torus_y, double ici_bw, double dcn_bw, double elem_bytes,
    // graph
    int32_t L, const int32_t* num_inputs, const int32_t* num_weights,
    int32_t max_inputs, int32_t max_weights, int32_t max_outputs,
    const int32_t* in_rank,    // [L*max_inputs]
    const int32_t* producer,   // [L*max_inputs]
    const int32_t* producer_out,  // [L*max_inputs] producer's output slot
    const int32_t* w_rank,     // [L*max_weights]
    const int64_t* sync_rows_cap,  // [L*max_weights]; -1 = no clamp
    const int32_t* out_rank,   // [L] (rank of output slot 0; informational)
    // candidates
    const int32_t* cand_off,   // [L+1]
    const int32_t* parts,      // [G]
    const double* fwd_cost,    // [G]
    const double* bwd_cost,    // [G]
    const int64_t* devices,    // device pool
    const int64_t* dev_off,    // [G]
    const int64_t* rects,      // rect pool
    const int64_t* out_off,    // [G*max_outputs] (slot-minor)
    const int64_t* in_rect_off,   // [G*max_inputs]
    const int64_t* w_tile_off,    // [G*max_weights]
    // search
    int32_t budget, double alpha, uint64_t seed, int32_t overlap,
    const int32_t* choice_init, int32_t* choice_out, double* dp_runtime_out) {
  Machine m{num_devices, chips_per_host, torus_x, torus_y, ici_bw,
            dcn_bw, elem_bytes > 0 ? elem_bytes : 4.0};
  std::vector<OpDesc> ops(L);
  // devices pool is int64 in the ABI for alignment simplicity; narrow it.
  std::vector<int32_t> dev_pool;
  {
    int64_t maxoff = 0;
    for (int32_t i = 0; i < L; i++)
      for (int32_t c = cand_off[i]; c < cand_off[i + 1]; c++)
        maxoff = std::max(maxoff, dev_off[c] + parts[c]);
    dev_pool.resize(size_t(maxoff));
    for (size_t k = 0; k < dev_pool.size(); k++)
      dev_pool[k] = int32_t(devices[k]);
  }
  for (int32_t i = 0; i < L; i++) {
    OpDesc& od = ops[i];
    od.out_rank = out_rank[i];
    for (int32_t j = 0; j < num_inputs[i]; j++) {
      od.in_rank.push_back(in_rank[i * max_inputs + j]);
      od.producer.push_back(producer[i * max_inputs + j]);
      od.producer_out.push_back(producer_out[i * max_inputs + j]);
    }
    for (int32_t w = 0; w < num_weights[i]; w++) {
      od.w_rank.push_back(w_rank[i * max_weights + w]);
      od.sync_rows_cap.push_back(sync_rows_cap[i * max_weights + w]);
    }
    for (int32_t g = cand_off[i]; g < cand_off[i + 1]; g++) {
      Candidate c;
      c.parts = parts[g];
      c.devices = dev_pool.data() + dev_off[g];
      c.fwd_cost = fwd_cost[g];
      c.bwd_cost = bwd_cost[g];
      for (int32_t k = 0; k < max_outputs; k++)
        c.out_tiles.push_back(rects + out_off[size_t(g) * max_outputs + k]);
      for (int32_t j = 0; j < num_inputs[i]; j++)
        c.in_rects.push_back(rects + in_rect_off[size_t(g) * max_inputs + j]);
      for (int32_t w = 0; w < num_weights[i]; w++)
        c.w_tiles.push_back(rects + w_tile_off[size_t(g) * max_weights + w]);
      od.cands.push_back(std::move(c));
    }
  }

  Sim sim{&m, &ops, overlap != 0};
  std::vector<int32_t> current(choice_init, choice_init + L);
  double cur_rt = sim.evaluate(current);
  if (dp_runtime_out) *dp_runtime_out = cur_rt;
  std::vector<int32_t> best(current);
  double best_rt = cur_rt;

  std::mt19937_64 rng(seed);
  std::uniform_real_distribution<double> uni(0.0, 1.0);
  for (int32_t it = 0; it < budget; it++) {
    int32_t i = int32_t(rng() % uint64_t(L));
    int32_t ncands = cand_off[i + 1] - cand_off[i];
    if (ncands <= 1) continue;
    int32_t prev = current[i];
    int32_t cand = int32_t(rng() % uint64_t(ncands));
    if (cand == prev) continue;
    current[i] = cand;
    double rt = sim.evaluate(current);
    if (rt < 0) { current[i] = prev; continue; }  // cycle guard
    if (rt < best_rt) { best_rt = rt; best = current; }
    // accept like the reference: always if faster, else annealed
    // (model.cc:1068-1089 uses exp(-alpha * delta); delta in ms there)
    if (rt < cur_rt || uni(rng) < std::exp(-alpha * (rt - cur_rt) * 1e3)) {
      cur_rt = rt;
    } else {
      current[i] = prev;
    }
  }
  std::memcpy(choice_out, best.data(), sizeof(int32_t) * size_t(L));
  return best_rt;
}

}  // extern "C"
