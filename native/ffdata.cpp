// Native dataloader batch gather.
//
// TPU-native counterpart of the reference's per-GPU batch scatter kernels
// (reference: python/flexflow_dataloader.cu, examples/cpp/AlexNet/
// alexnet.cu:19-90 — each device's copy kernel gathers its shard's
// samples from zero-copy memory).  On TPU the host assembles the batch
// (then jax.device_put DMA-transfers each shard), so the gather is a
// host-side multithreaded strided memcpy: rows `indices[0..batch)` of a
// contiguous (num_samples, row_bytes) dataset into a contiguous batch
// buffer.  numpy fancy-indexing does this single-threaded; this is the
// parallel version for large rows (images).
//
// Build: make -C native   (produces libffdata.so)

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Gather rows: dst[i, :] = src[indices[i], :] for i in [0, batch).
void ffdata_gather_rows(const uint8_t* src, uint8_t* dst,
                        const int64_t* indices, int64_t batch,
                        int64_t row_bytes, int32_t num_threads) {
  if (num_threads <= 1 || batch < num_threads * 4) {
    for (int64_t i = 0; i < batch; i++)
      std::memcpy(dst + i * row_bytes, src + indices[i] * row_bytes, row_bytes);
    return;
  }
  std::vector<std::thread> workers;
  int64_t chunk = (batch + num_threads - 1) / num_threads;
  for (int32_t w = 0; w < num_threads; w++) {
    int64_t lo = w * chunk;
    int64_t hi = lo + chunk < batch ? lo + chunk : batch;
    if (lo >= hi) break;
    workers.emplace_back([=]() {
      for (int64_t i = lo; i < hi; i++)
        std::memcpy(dst + i * row_bytes, src + indices[i] * row_bytes,
                    row_bytes);
    });
  }
  for (auto& t : workers) t.join();
}

}  // extern "C"
