/* C API implementation: embeds CPython and drives flexflow_tpu.
 *
 * Mirror-image of the reference architecture: the reference embeds a
 * Python interpreter inside a Legion task (python/main.cc) and wraps a
 * C++ core in C for cffi (python/flexflow_c.cc); here the core is Python,
 * so the C surface embeds the interpreter.  All handles are PyObject*.
 */

#include "flexflow_c.h"

#include <Python.h>

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

PyObject* g_module = nullptr;   // flexflow_tpu
PyObject* g_np = nullptr;       // numpy

bool ensure_init() {
  if (g_module) return true;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
  }
  // FLEXFLOW_TPU_PLATFORM=cpu|tpu|... wins over any site-level backend
  // selection (some environments force a platform from sitecustomize).
  const char* plat = getenv("FLEXFLOW_TPU_PLATFORM");
  if (plat && *plat) {
    std::string code = "import jax\njax.config.update('jax_platforms', '";
    code += plat;
    code += "')\n";
    PyRun_SimpleString(code.c_str());
  }
  g_module = PyImport_ImportModule("flexflow_tpu");
  if (!g_module) {
    PyErr_Print();
    return false;
  }
  g_np = PyImport_ImportModule("numpy");
  if (!g_np) {
    PyErr_Print();
    return false;
  }
  return true;
}

// Steals the reference to ``args`` (every call site builds a fresh tuple
// inline); kwargs stays borrowed.  A NULL ``args`` (failed Py_BuildValue,
// e.g. from a NULL handle) is reported, not dereferenced.
PyObject* call(PyObject* obj, const char* method, PyObject* args,
               PyObject* kwargs = nullptr) {
  if (!args) {
    PyErr_Print();
    PyErr_Clear();
    return nullptr;
  }
  PyObject* fn = PyObject_GetAttrString(obj, method);
  if (!fn) { PyErr_Print(); Py_DECREF(args); return nullptr; }
  PyObject* res = PyObject_Call(fn, args, kwargs);
  Py_DECREF(fn);
  Py_DECREF(args);
  if (!res) PyErr_Print();
  return res;
}

// Build a numpy array copying C data. fmt: 'f' float32, 'i' int32.
PyObject* np_array(const void* data, int64_t count, const int* dims, int ndims,
                   char fmt) {
  PyObject* list = PyList_New(count);
  if (fmt == 'f') {
    const float* p = static_cast<const float*>(data);
    for (int64_t i = 0; i < count; i++)
      PyList_SET_ITEM(list, i, PyFloat_FromDouble(p[i]));
  } else {
    const int32_t* p = static_cast<const int32_t*>(data);
    for (int64_t i = 0; i < count; i++)
      PyList_SET_ITEM(list, i, PyLong_FromLong(p[i]));
  }
  PyObject* arr = call(g_np, "array", Py_BuildValue("(O)", list),
                       Py_BuildValue("{s:s}", "dtype",
                                     fmt == 'f' ? "float32" : "int32"));
  Py_DECREF(list);
  if (!arr) return nullptr;
  if (ndims > 1) {
    PyObject* shape = PyTuple_New(ndims);
    for (int i = 0; i < ndims; i++)
      PyTuple_SET_ITEM(shape, i, PyLong_FromLong(dims[i]));
    PyObject* reshaped = call(arr, "reshape", Py_BuildValue("(O)", shape));
    Py_DECREF(shape);
    Py_DECREF(arr);
    return reshaped;
  }
  return arr;
}

PyObject* H(void* impl) { return static_cast<PyObject*>(impl); }

const char* kActNames[] = {"none", "relu", "sigmoid", "tanh"};

// Per-model pending batch: dict tensor-> array kept on the model object
// via a Python attribute so lifetimes follow the model handle.
int stage_input(flexflow_model_t m, PyObject* tensor, PyObject* arr) {
  if (!arr) return -1;
  PyObject* model = H(m.impl);
  PyObject* staged = PyObject_GetAttrString(model, "_c_api_batch");
  if (!staged || staged == Py_None) {
    Py_XDECREF(staged);
    staged = PyDict_New();
    PyObject_SetAttrString(model, "_c_api_batch", staged);
  }
  PyDict_SetItem(staged, tensor, arr);
  Py_DECREF(staged);
  Py_DECREF(arr);
  return 0;
}

int flush_batch_if_ready(flexflow_model_t m) {
  PyObject* model = H(m.impl);
  PyObject* staged = PyObject_GetAttrString(model, "_c_api_batch");
  PyObject* label = PyObject_GetAttrString(model, "_c_api_label");
  int ok = -1;
  if (staged && staged != Py_None && label && label != Py_None) {
    PyObject* res = call(model, "set_batch",
                         Py_BuildValue("(OO)", staged, label));
    if (res) { ok = 0; Py_DECREF(res); }
    PyObject_SetAttrString(model, "_c_api_batch", Py_None);
    PyObject_SetAttrString(model, "_c_api_label", Py_None);
  } else {
    ok = 0;  // nothing staged: batch already set
  }
  Py_XDECREF(staged);
  Py_XDECREF(label);
  return ok;
}

}  // namespace

extern "C" {

int flexflow_init(void) { return ensure_init() ? 0 : -1; }

void flexflow_finalize(void) { /* keep interpreter alive: cheap + safe */ }

flexflow_config_t flexflow_config_create(int batch_size, int epochs,
                                         int num_devices) {
  flexflow_config_t out{nullptr};
  if (!ensure_init()) return out;
  PyObject* cls = PyObject_GetAttrString(g_module, "FFConfig");
  PyObject* kw = Py_BuildValue("{s:i,s:i}", "batch_size", batch_size,
                               "epochs", epochs);
  if (num_devices > 0) {
    PyObject* v = PyLong_FromLong(num_devices);
    PyDict_SetItemString(kw, "workers_per_node", v);
    Py_DECREF(v);
  }
  PyObject* empty = PyTuple_New(0);
  out.impl = PyObject_Call(cls, empty, kw);
  if (!out.impl) PyErr_Print();
  Py_DECREF(empty);
  Py_DECREF(kw);
  Py_DECREF(cls);
  return out;
}

void flexflow_config_destroy(flexflow_config_t c) { Py_XDECREF(H(c.impl)); }

flexflow_model_t flexflow_model_create(flexflow_config_t c) {
  flexflow_model_t out{nullptr};
  if (!ensure_init()) return out;
  PyObject* cls = PyObject_GetAttrString(g_module, "FFModel");
  out.impl = PyObject_CallFunctionObjArgs(cls, H(c.impl), nullptr);
  if (!out.impl) PyErr_Print();
  Py_DECREF(cls);
  return out;
}

void flexflow_model_destroy(flexflow_model_t m) { Py_XDECREF(H(m.impl)); }

flexflow_tensor_t flexflow_tensor_create(flexflow_model_t m, int ndims,
                                         const int* dims, const char* dtype) {
  flexflow_tensor_t out{nullptr};
  PyObject* shape = PyTuple_New(ndims);
  for (int i = 0; i < ndims; i++)
    PyTuple_SET_ITEM(shape, i, PyLong_FromLong(dims[i]));
  PyObject* kw = Py_BuildValue("{s:s}", "dtype", dtype ? dtype : "float32");
  out.impl = call(H(m.impl), "create_tensor", Py_BuildValue("(O)", shape), kw);
  Py_DECREF(shape);
  Py_DECREF(kw);
  return out;
}

void flexflow_tensor_destroy(flexflow_tensor_t t) { Py_XDECREF(H(t.impl)); }

flexflow_tensor_t flexflow_model_add_conv2d(
    flexflow_model_t m, flexflow_tensor_t input, int out_channels,
    int kernel_h, int kernel_w, int stride_h, int stride_w, int padding_h,
    int padding_w, int activation, int use_bias, const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = Py_BuildValue("{s:s,s:O}", "activation",
                               kActNames[activation & 3], "use_bias",
                               use_bias ? Py_True : Py_False);
  if (name) {
    PyObject* n = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", n);
    Py_DECREF(n);
  }
  out.impl = call(H(m.impl), "conv2d",
                  Py_BuildValue("(Oiiiiiii)", H(input.impl), out_channels,
                                kernel_h, kernel_w, stride_h, stride_w,
                                padding_h, padding_w),
                  kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_pool2d(
    flexflow_model_t m, flexflow_tensor_t input, int kernel_h, int kernel_w,
    int stride_h, int stride_w, int padding_h, int padding_w, int pool_max,
    const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = Py_BuildValue("{s:s}", "pool_type", pool_max ? "max" : "avg");
  if (name) {
    PyObject* n = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", n);
    Py_DECREF(n);
  }
  out.impl = call(H(m.impl), "pool2d",
                  Py_BuildValue("(Oiiiiii)", H(input.impl), kernel_h, kernel_w,
                                stride_h, stride_w, padding_h, padding_w),
                  kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_dense(flexflow_model_t m,
                                           flexflow_tensor_t input,
                                           int out_dim, int activation,
                                           int use_bias, const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = Py_BuildValue("{s:s,s:O}", "activation",
                               kActNames[activation & 3], "use_bias",
                               use_bias ? Py_True : Py_False);
  if (name) {
    PyObject* n = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", n);
    Py_DECREF(n);
  }
  out.impl = call(H(m.impl), "dense",
                  Py_BuildValue("(Oi)", H(input.impl), out_dim), kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_flat(flexflow_model_t m,
                                          flexflow_tensor_t input,
                                          const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = PyDict_New();
  if (name) {
    PyObject* n = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", n);
    Py_DECREF(n);
  }
  out.impl = call(H(m.impl), "flat", Py_BuildValue("(O)", H(input.impl)), kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_softmax(flexflow_model_t m,
                                             flexflow_tensor_t input,
                                             const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = PyDict_New();
  if (name) {
    PyObject* n = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", n);
    Py_DECREF(n);
  }
  out.impl =
      call(H(m.impl), "softmax", Py_BuildValue("(O)", H(input.impl)), kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_embedding(flexflow_model_t m,
                                               flexflow_tensor_t input,
                                               int num_entries, int out_dim,
                                               int aggr_sum, const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = Py_BuildValue("{s:s}", "aggr", aggr_sum ? "sum" : "avg");
  if (name) {
    PyObject* n = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", n);
    Py_DECREF(n);
  }
  out.impl = call(H(m.impl), "embedding",
                  Py_BuildValue("(Oii)", H(input.impl), num_entries, out_dim),
                  kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_concat(flexflow_model_t m, int n,
                                            const flexflow_tensor_t* inputs,
                                            int axis, const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* list = PyList_New(n);
  for (int i = 0; i < n; i++) {
    Py_INCREF(H(inputs[i].impl));
    PyList_SET_ITEM(list, i, H(inputs[i].impl));
  }
  PyObject* kw = PyDict_New();
  if (name) {
    PyObject* nm = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", nm);
    Py_DECREF(nm);
  }
  out.impl = call(H(m.impl), "concat", Py_BuildValue("(Oi)", list, axis), kw);
  Py_DECREF(list);
  Py_DECREF(kw);
  return out;
}

static flexflow_tensor_t binary_op(flexflow_model_t m, const char* method,
                                   flexflow_tensor_t a, flexflow_tensor_t b,
                                   const char* name);

flexflow_tensor_t flexflow_model_add_add(flexflow_model_t m,
                                         flexflow_tensor_t a,
                                         flexflow_tensor_t b,
                                         const char* name) {
  return binary_op(m, "add", a, b, name);
}

static flexflow_tensor_t binary_op(flexflow_model_t m, const char* method,
                                   flexflow_tensor_t a, flexflow_tensor_t b,
                                   const char* name) {
  flexflow_tensor_t out{nullptr};
  if (!a.impl || !b.impl) return out;  // upstream builder failed
  PyObject* kw = PyDict_New();
  if (name) {
    PyObject* nm = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", nm);
    Py_DECREF(nm);
  }
  out.impl = call(H(m.impl), method,
                  Py_BuildValue("(OO)", H(a.impl), H(b.impl)), kw);
  Py_DECREF(kw);
  return out;
}

static flexflow_tensor_t unary_op(flexflow_model_t m, const char* method,
                                  flexflow_tensor_t input, const char* name) {
  flexflow_tensor_t out{nullptr};
  if (!input.impl) return out;  // upstream builder failed
  PyObject* kw = PyDict_New();
  if (name) {
    PyObject* nm = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", nm);
    Py_DECREF(nm);
  }
  out.impl = call(H(m.impl), method, Py_BuildValue("(O)", H(input.impl)), kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_subtract(flexflow_model_t m,
                                              flexflow_tensor_t a,
                                              flexflow_tensor_t b,
                                              const char* name) {
  return binary_op(m, "subtract", a, b, name);
}
flexflow_tensor_t flexflow_model_add_multiply(flexflow_model_t m,
                                              flexflow_tensor_t a,
                                              flexflow_tensor_t b,
                                              const char* name) {
  return binary_op(m, "multiply", a, b, name);
}
flexflow_tensor_t flexflow_model_add_divide(flexflow_model_t m,
                                            flexflow_tensor_t a,
                                            flexflow_tensor_t b,
                                            const char* name) {
  return binary_op(m, "divide", a, b, name);
}
flexflow_tensor_t flexflow_model_add_relu(flexflow_model_t m,
                                          flexflow_tensor_t input,
                                          const char* name) {
  return unary_op(m, "relu", input, name);
}
flexflow_tensor_t flexflow_model_add_sigmoid(flexflow_model_t m,
                                             flexflow_tensor_t input,
                                             const char* name) {
  return unary_op(m, "sigmoid", input, name);
}
flexflow_tensor_t flexflow_model_add_tanh(flexflow_model_t m,
                                          flexflow_tensor_t input,
                                          const char* name) {
  return unary_op(m, "tanh", input, name);
}
flexflow_tensor_t flexflow_model_add_elu(flexflow_model_t m,
                                         flexflow_tensor_t input,
                                         const char* name) {
  return unary_op(m, "elu", input, name);
}
flexflow_tensor_t flexflow_model_add_exp(flexflow_model_t m,
                                         flexflow_tensor_t input,
                                         const char* name) {
  return unary_op(m, "exp", input, name);
}

flexflow_tensor_t flexflow_model_add_batch_norm(flexflow_model_t m,
                                                flexflow_tensor_t input,
                                                int relu, const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = Py_BuildValue("{s:O}", "relu", relu ? Py_True : Py_False);
  if (name) {
    PyObject* nm = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", nm);
    Py_DECREF(nm);
  }
  out.impl = call(H(m.impl), "batch_norm",
                  Py_BuildValue("(O)", H(input.impl)), kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_dropout(flexflow_model_t m,
                                             flexflow_tensor_t input,
                                             double rate, int seed,
                                             const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = Py_BuildValue("{s:i}", "seed", seed);
  if (name) {
    PyObject* nm = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", nm);
    Py_DECREF(nm);
  }
  out.impl = call(H(m.impl), "dropout",
                  Py_BuildValue("(Od)", H(input.impl), rate), kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_mse_loss(flexflow_model_t m,
                                              flexflow_tensor_t logits,
                                              flexflow_tensor_t labels,
                                              const char* reduction,
                                              const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = Py_BuildValue("{s:s}", "reduction",
                               reduction ? reduction : "average");
  if (name) {
    PyObject* nm = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", nm);
    Py_DECREF(nm);
  }
  out.impl = call(H(m.impl), "mse_loss",
                  Py_BuildValue("(OO)", H(logits.impl), H(labels.impl)), kw);
  Py_DECREF(kw);
  return out;
}

int flexflow_model_compile(flexflow_model_t m, const char* optimizer,
                           double lr, const char* loss, const char** metrics,
                           int num_metrics) {
  PyObject* optcls = PyObject_GetAttrString(
      g_module, strcmp(optimizer, "adam") == 0 ? "AdamOptimizer"
                                               : "SGDOptimizer");
  PyObject* kw = strcmp(optimizer, "adam") == 0
                     ? Py_BuildValue("{s:d}", "alpha", lr)
                     : Py_BuildValue("{s:d}", "lr", lr);
  PyObject* empty = PyTuple_New(0);
  PyObject* opt = PyObject_Call(optcls, empty, kw);
  Py_DECREF(empty);
  Py_DECREF(kw);
  Py_DECREF(optcls);
  if (!opt) { PyErr_Print(); return -1; }
  PyObject* mlist = PyList_New(num_metrics);
  for (int i = 0; i < num_metrics; i++)
    PyList_SET_ITEM(mlist, i, PyUnicode_FromString(metrics[i]));
  PyObject* res = call(H(m.impl), "compile",
                       Py_BuildValue("(OsO)", opt, loss, mlist));
  Py_DECREF(opt);
  Py_DECREF(mlist);
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int flexflow_model_init_layers(flexflow_model_t m) {
  PyObject* res = call(H(m.impl), "init_layers", PyTuple_New(0));
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int flexflow_model_set_input_f32(flexflow_model_t m, flexflow_tensor_t t,
                                 const float* data, int64_t count) {
  // reshape to the tensor's *native* dims: C callers pass reference-order
  // data for 4-D (N,C,H,W) — convert via numpy transpose
  PyObject* tensor = H(t.impl);
  PyObject* dims_obj = PyObject_GetAttrString(tensor, "dims");
  int nd = (int)PyTuple_Size(dims_obj);
  std::vector<int> dims(nd);
  for (int i = 0; i < nd; i++)
    dims[i] = (int)PyLong_AsLong(PyTuple_GetItem(dims_obj, i));
  Py_DECREF(dims_obj);
  std::vector<int> cdims(dims);
  if (nd == 4) {  // caller provides N,C,H,W; tensor dims are N,H,W,C
    cdims[1] = dims[3]; cdims[2] = dims[1]; cdims[3] = dims[2];
  }
  PyObject* arr = np_array(data, count, cdims.data(), nd, 'f');
  if (!arr) return -1;
  if (nd == 4) {
    PyObject* tr = call(arr, "transpose", Py_BuildValue("(iiii)", 0, 2, 3, 1));
    Py_DECREF(arr);
    arr = tr;
    if (!arr) return -1;
  }
  return stage_input(m, tensor, arr);
}

int flexflow_model_set_input_i32(flexflow_model_t m, flexflow_tensor_t t,
                                 const int32_t* data, int64_t count) {
  PyObject* tensor = H(t.impl);
  PyObject* dims_obj = PyObject_GetAttrString(tensor, "dims");
  int nd = (int)PyTuple_Size(dims_obj);
  std::vector<int> dims(nd);
  for (int i = 0; i < nd; i++)
    dims[i] = (int)PyLong_AsLong(PyTuple_GetItem(dims_obj, i));
  Py_DECREF(dims_obj);
  PyObject* arr = np_array(data, count, dims.data(), nd, 'i');
  if (!arr) return -1;
  return stage_input(m, tensor, arr);
}

static int set_label(flexflow_model_t m, PyObject* arr) {
  if (!arr) return -1;
  PyObject_SetAttrString(H(m.impl), "_c_api_label", arr);
  Py_DECREF(arr);
  return flush_batch_if_ready(m);
}

int flexflow_model_set_label_i32(flexflow_model_t m, const int32_t* data,
                                 int64_t count) {
  PyObject* model = H(m.impl);
  PyObject* lt = PyObject_GetAttrString(model, "label_tensor");
  PyObject* dims_obj = PyObject_GetAttrString(lt, "dims");
  int nd = (int)PyTuple_Size(dims_obj);
  std::vector<int> dims(nd);
  for (int i = 0; i < nd; i++)
    dims[i] = (int)PyLong_AsLong(PyTuple_GetItem(dims_obj, i));
  Py_DECREF(dims_obj);
  Py_DECREF(lt);
  return set_label(m, np_array(data, count, dims.data(), nd, 'i'));
}

int flexflow_model_set_label_f32(flexflow_model_t m, const float* data,
                                 int64_t count) {
  PyObject* model = H(m.impl);
  PyObject* lt = PyObject_GetAttrString(model, "label_tensor");
  PyObject* dims_obj = PyObject_GetAttrString(lt, "dims");
  int nd = (int)PyTuple_Size(dims_obj);
  std::vector<int> dims(nd);
  for (int i = 0; i < nd; i++)
    dims[i] = (int)PyLong_AsLong(PyTuple_GetItem(dims_obj, i));
  Py_DECREF(dims_obj);
  Py_DECREF(lt);
  return set_label(m, np_array(data, count, dims.data(), nd, 'f'));
}

static int simple_call(flexflow_model_t m, const char* method) {
  PyObject* res = call(H(m.impl), method, PyTuple_New(0));
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int flexflow_model_forward(flexflow_model_t m) {
  if (flush_batch_if_ready(m) != 0) return -1;
  return simple_call(m, "forward");
}
int flexflow_model_zero_gradients(flexflow_model_t m) {
  return simple_call(m, "zero_gradients");
}
int flexflow_model_backward(flexflow_model_t m) {
  return simple_call(m, "backward");
}
int flexflow_model_update(flexflow_model_t m) {
  return simple_call(m, "update");
}
int flexflow_model_sync(flexflow_model_t m) { return simple_call(m, "sync"); }

void flexflow_model_reset_metrics(flexflow_model_t m) {
  simple_call(m, "reset_metrics");
}

double flexflow_model_get_accuracy(flexflow_model_t m, int64_t* train_all,
                                   int64_t* train_correct) {
  PyObject* pm = call(H(m.impl), "get_metrics", PyTuple_New(0));
  if (!pm) return -1.0;
  PyObject* acc = PyObject_GetAttrString(pm, "accuracy");
  PyObject* ta = PyObject_GetAttrString(pm, "train_all");
  PyObject* tc = PyObject_GetAttrString(pm, "train_correct");
  double result = acc ? PyFloat_AsDouble(acc) : -1.0;
  if (train_all && ta) *train_all = PyLong_AsLongLong(ta);
  if (train_correct && tc) *train_correct = PyLong_AsLongLong(tc);
  Py_XDECREF(acc); Py_XDECREF(ta); Py_XDECREF(tc); Py_DECREF(pm);
  return result;
}

int flexflow_model_train_iteration(flexflow_model_t m) {
  if (flush_batch_if_ready(m) != 0) return -1;
  return simple_call(m, "train_iteration");
}

double flexflow_model_get_metric(flexflow_model_t m, const char* name) {
  PyObject* pm = call(H(m.impl), "get_metrics", PyTuple_New(0));
  if (!pm) return -1.0;
  PyObject* v = PyObject_GetAttrString(pm, name);
  double result = v ? PyFloat_AsDouble(v) : -1.0;
  if (PyErr_Occurred()) { PyErr_Print(); result = -1.0; }
  Py_XDECREF(v);
  Py_DECREF(pm);
  return result;
}

int64_t flexflow_parameter_get_volume(flexflow_model_t m, const char* op_name,
                                      const char* weight_name) {
  PyObject* arr = call(H(m.impl), "get_parameter",
                       Py_BuildValue("(ss)", op_name, weight_name));
  if (!arr) return -1;
  PyObject* size = PyObject_GetAttrString(arr, "size");
  int64_t n = size ? PyLong_AsLongLong(size) : -1;
  if (PyErr_Occurred()) {
    PyErr_Print();
    n = -1;
  }
  Py_XDECREF(size);
  Py_DECREF(arr);
  return n;
}

int flexflow_model_get_parameter_f32(flexflow_model_t m, const char* op_name,
                                     const char* weight_name, float* out,
                                     int64_t count) {
  PyObject* arr = call(H(m.impl), "get_parameter",
                       Py_BuildValue("(ss)", op_name, weight_name));
  if (!arr) return -1;
  PyObject* flat = call(arr, "astype", Py_BuildValue("(s)", "float32"));
  Py_DECREF(arr);
  if (!flat) return -1;
  PyObject* rav = call(flat, "ravel", PyTuple_New(0));
  Py_DECREF(flat);
  if (!rav) return -1;
  PyObject* lst = call(rav, "tolist", PyTuple_New(0));
  Py_DECREF(rav);
  if (!lst) return -1;
  int64_t n = PyList_Size(lst);
  int rc = 0;
  if (n != count) {
    rc = -1;
  } else {
    for (int64_t i = 0; i < n; i++)
      out[i] = (float)PyFloat_AsDouble(PyList_GET_ITEM(lst, i));
  }
  Py_DECREF(lst);
  return rc;
}

int flexflow_model_set_parameter_f32(flexflow_model_t m, const char* op_name,
                                     const char* weight_name,
                                     const float* data, int64_t count) {
  PyObject* arr_flat = np_array(data, count, nullptr, 1, 'f');
  if (!arr_flat) return -1;
  // reshape to the current parameter's shape
  PyObject* cur = call(H(m.impl), "get_parameter",
                       Py_BuildValue("(ss)", op_name, weight_name));
  if (!cur) { Py_DECREF(arr_flat); return -1; }
  PyObject* shape = PyObject_GetAttrString(cur, "shape");
  Py_DECREF(cur);
  if (!shape) {
    PyErr_Print();
    Py_DECREF(arr_flat);
    return -1;
  }
  PyObject* arr = call(arr_flat, "reshape", Py_BuildValue("(O)", shape));
  Py_DECREF(shape);
  Py_DECREF(arr_flat);
  if (!arr) return -1;
  PyObject* res = call(H(m.impl), "set_parameter",
                       Py_BuildValue("(ssO)", op_name, weight_name, arr));
  Py_DECREF(arr);
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int flexflow_config_import_strategy(flexflow_config_t c, const char* path) {
  PyObject* p = PyUnicode_FromString(path);
  int rc = PyObject_SetAttrString(H(c.impl), "import_strategy_file", p);
  Py_DECREF(p);
  return rc;
}

int flexflow_model_export_strategy(flexflow_model_t m, const char* path) {
  PyObject* strategies = call(H(m.impl), "get_strategies", PyTuple_New(0));
  if (!strategies) return -1;
  PyObject* fn = PyObject_GetAttrString(g_module, "save_strategies_to_file");
  if (!fn) { Py_DECREF(strategies); PyErr_Print(); return -1; }
  PyObject* res = PyObject_CallFunction(fn, "sO", path, strategies);
  Py_DECREF(fn);
  Py_DECREF(strategies);
  if (!res) { PyErr_Print(); return -1; }
  Py_DECREF(res);
  return 0;
}

int flexflow_model_save(flexflow_model_t m, const char* path) {
  PyObject* res = call(H(m.impl), "save", Py_BuildValue("(s)", path));
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int flexflow_model_load(flexflow_model_t m, const char* path) {
  PyObject* res = call(H(m.impl), "load", Py_BuildValue("(s)", path));
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int flexflow_tensor_get_dims(flexflow_tensor_t t, int* dims) {
  PyObject* dims_obj = PyObject_GetAttrString(H(t.impl), "dims");
  if (!dims_obj) return -1;
  int nd = (int)PyTuple_Size(dims_obj);
  for (int i = 0; i < nd && i < 8; i++)
    dims[i] = (int)PyLong_AsLong(PyTuple_GetItem(dims_obj, i));
  Py_DECREF(dims_obj);
  return nd;
}

}  // extern "C"
