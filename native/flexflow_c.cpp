/* C API implementation: embeds CPython and drives flexflow_tpu.
 *
 * Mirror-image of the reference architecture: the reference embeds a
 * Python interpreter inside a Legion task (python/main.cc) and wraps a
 * C++ core in C for cffi (python/flexflow_c.cc); here the core is Python,
 * so the C surface embeds the interpreter.  All handles are PyObject*.
 */

#include "flexflow_c.h"

#include <Python.h>

#include <cstdio>
#include <cstring>
#include <ctime>
#include <string>
#include <vector>

namespace {

PyObject* g_module = nullptr;   // flexflow_tpu
PyObject* g_np = nullptr;       // numpy

bool ensure_init() {
  if (g_module) return true;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
  }
  // FLEXFLOW_TPU_PLATFORM=cpu|tpu|... wins over any site-level backend
  // selection (some environments force a platform from sitecustomize).
  const char* plat = getenv("FLEXFLOW_TPU_PLATFORM");
  if (plat && *plat) {
    std::string code = "import jax\njax.config.update('jax_platforms', '";
    code += plat;
    code += "')\n";
    PyRun_SimpleString(code.c_str());
  }
  g_module = PyImport_ImportModule("flexflow_tpu");
  if (!g_module) {
    PyErr_Print();
    return false;
  }
  g_np = PyImport_ImportModule("numpy");
  if (!g_np) {
    PyErr_Print();
    return false;
  }
  return true;
}

// Steals the reference to ``args`` (every call site builds a fresh tuple
// inline); kwargs stays borrowed.  A NULL ``args`` (failed Py_BuildValue,
// e.g. from a NULL handle) is reported, not dereferenced.
PyObject* call(PyObject* obj, const char* method, PyObject* args,
               PyObject* kwargs = nullptr) {
  if (!args) {
    PyErr_Print();
    PyErr_Clear();
    return nullptr;
  }
  PyObject* fn = PyObject_GetAttrString(obj, method);
  if (!fn) { PyErr_Print(); Py_DECREF(args); return nullptr; }
  PyObject* res = PyObject_Call(fn, args, kwargs);
  Py_DECREF(fn);
  Py_DECREF(args);
  if (!res) PyErr_Print();
  return res;
}

// Build a numpy array copying C data. fmt: 'f' float32, 'i' int32.
PyObject* np_array(const void* data, int64_t count, const int* dims, int ndims,
                   char fmt) {
  PyObject* list = PyList_New(count);
  if (fmt == 'f') {
    const float* p = static_cast<const float*>(data);
    for (int64_t i = 0; i < count; i++)
      PyList_SET_ITEM(list, i, PyFloat_FromDouble(p[i]));
  } else {
    const int32_t* p = static_cast<const int32_t*>(data);
    for (int64_t i = 0; i < count; i++)
      PyList_SET_ITEM(list, i, PyLong_FromLong(p[i]));
  }
  PyObject* arr = call(g_np, "array", Py_BuildValue("(O)", list),
                       Py_BuildValue("{s:s}", "dtype",
                                     fmt == 'f' ? "float32" : "int32"));
  Py_DECREF(list);
  if (!arr) return nullptr;
  if (ndims > 1) {
    PyObject* shape = PyTuple_New(ndims);
    for (int i = 0; i < ndims; i++)
      PyTuple_SET_ITEM(shape, i, PyLong_FromLong(dims[i]));
    PyObject* reshaped = call(arr, "reshape", Py_BuildValue("(O)", shape));
    Py_DECREF(shape);
    Py_DECREF(arr);
    return reshaped;
  }
  return arr;
}

PyObject* H(void* impl) { return static_cast<PyObject*>(impl); }

const char* kActNames[] = {"none", "relu", "sigmoid", "tanh"};

// Per-model pending batch: dict tensor-> array kept on the model object
// via a Python attribute so lifetimes follow the model handle.
int stage_input(flexflow_model_t m, PyObject* tensor, PyObject* arr) {
  if (!arr) return -1;
  PyObject* model = H(m.impl);
  PyObject* staged = PyObject_GetAttrString(model, "_c_api_batch");
  if (!staged || staged == Py_None) {
    Py_XDECREF(staged);
    staged = PyDict_New();
    PyObject_SetAttrString(model, "_c_api_batch", staged);
  }
  PyDict_SetItem(staged, tensor, arr);
  Py_DECREF(staged);
  Py_DECREF(arr);
  return 0;
}

int flush_batch_if_ready(flexflow_model_t m) {
  PyObject* model = H(m.impl);
  PyObject* staged = PyObject_GetAttrString(model, "_c_api_batch");
  PyObject* label = PyObject_GetAttrString(model, "_c_api_label");
  int ok = -1;
  if (staged && staged != Py_None && label && label != Py_None) {
    PyObject* res = call(model, "set_batch",
                         Py_BuildValue("(OO)", staged, label));
    if (res) { ok = 0; Py_DECREF(res); }
    PyObject_SetAttrString(model, "_c_api_batch", Py_None);
    PyObject_SetAttrString(model, "_c_api_label", Py_None);
  } else {
    ok = 0;  // nothing staged: batch already set
  }
  Py_XDECREF(staged);
  Py_XDECREF(label);
  return ok;
}

}  // namespace

extern "C" {

int flexflow_init(void) { return ensure_init() ? 0 : -1; }

void flexflow_finalize(void) { /* keep interpreter alive: cheap + safe */ }

flexflow_config_t flexflow_config_create(int batch_size, int epochs,
                                         int num_devices) {
  flexflow_config_t out{nullptr};
  if (!ensure_init()) return out;
  PyObject* cls = PyObject_GetAttrString(g_module, "FFConfig");
  PyObject* kw = Py_BuildValue("{s:i,s:i}", "batch_size", batch_size,
                               "epochs", epochs);
  if (num_devices > 0) {
    PyObject* v = PyLong_FromLong(num_devices);
    PyDict_SetItemString(kw, "workers_per_node", v);
    Py_DECREF(v);
  }
  PyObject* empty = PyTuple_New(0);
  out.impl = PyObject_Call(cls, empty, kw);
  if (!out.impl) PyErr_Print();
  Py_DECREF(empty);
  Py_DECREF(kw);
  Py_DECREF(cls);
  return out;
}

void flexflow_config_destroy(flexflow_config_t c) { Py_XDECREF(H(c.impl)); }

flexflow_model_t flexflow_model_create(flexflow_config_t c) {
  flexflow_model_t out{nullptr};
  if (!ensure_init()) return out;
  PyObject* cls = PyObject_GetAttrString(g_module, "FFModel");
  out.impl = PyObject_CallFunctionObjArgs(cls, H(c.impl), nullptr);
  if (!out.impl) PyErr_Print();
  Py_DECREF(cls);
  return out;
}

void flexflow_model_destroy(flexflow_model_t m) { Py_XDECREF(H(m.impl)); }

flexflow_tensor_t flexflow_tensor_create(flexflow_model_t m, int ndims,
                                         const int* dims, const char* dtype) {
  flexflow_tensor_t out{nullptr};
  PyObject* shape = PyTuple_New(ndims);
  for (int i = 0; i < ndims; i++)
    PyTuple_SET_ITEM(shape, i, PyLong_FromLong(dims[i]));
  PyObject* kw = Py_BuildValue("{s:s}", "dtype", dtype ? dtype : "float32");
  out.impl = call(H(m.impl), "create_tensor", Py_BuildValue("(O)", shape), kw);
  Py_DECREF(shape);
  Py_DECREF(kw);
  return out;
}

void flexflow_tensor_destroy(flexflow_tensor_t t) { Py_XDECREF(H(t.impl)); }

flexflow_tensor_t flexflow_model_add_conv2d(
    flexflow_model_t m, flexflow_tensor_t input, int out_channels,
    int kernel_h, int kernel_w, int stride_h, int stride_w, int padding_h,
    int padding_w, int activation, int use_bias, const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = Py_BuildValue("{s:s,s:O}", "activation",
                               kActNames[activation & 3], "use_bias",
                               use_bias ? Py_True : Py_False);
  if (name) {
    PyObject* n = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", n);
    Py_DECREF(n);
  }
  out.impl = call(H(m.impl), "conv2d",
                  Py_BuildValue("(Oiiiiiii)", H(input.impl), out_channels,
                                kernel_h, kernel_w, stride_h, stride_w,
                                padding_h, padding_w),
                  kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_pool2d(
    flexflow_model_t m, flexflow_tensor_t input, int kernel_h, int kernel_w,
    int stride_h, int stride_w, int padding_h, int padding_w, int pool_max,
    const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = Py_BuildValue("{s:s}", "pool_type", pool_max ? "max" : "avg");
  if (name) {
    PyObject* n = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", n);
    Py_DECREF(n);
  }
  out.impl = call(H(m.impl), "pool2d",
                  Py_BuildValue("(Oiiiiii)", H(input.impl), kernel_h, kernel_w,
                                stride_h, stride_w, padding_h, padding_w),
                  kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_dense(flexflow_model_t m,
                                           flexflow_tensor_t input,
                                           int out_dim, int activation,
                                           int use_bias, const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = Py_BuildValue("{s:s,s:O}", "activation",
                               kActNames[activation & 3], "use_bias",
                               use_bias ? Py_True : Py_False);
  if (name) {
    PyObject* n = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", n);
    Py_DECREF(n);
  }
  out.impl = call(H(m.impl), "dense",
                  Py_BuildValue("(Oi)", H(input.impl), out_dim), kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_flat(flexflow_model_t m,
                                          flexflow_tensor_t input,
                                          const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = PyDict_New();
  if (name) {
    PyObject* n = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", n);
    Py_DECREF(n);
  }
  out.impl = call(H(m.impl), "flat", Py_BuildValue("(O)", H(input.impl)), kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_softmax(flexflow_model_t m,
                                             flexflow_tensor_t input,
                                             const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = PyDict_New();
  if (name) {
    PyObject* n = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", n);
    Py_DECREF(n);
  }
  out.impl =
      call(H(m.impl), "softmax", Py_BuildValue("(O)", H(input.impl)), kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_embedding(flexflow_model_t m,
                                               flexflow_tensor_t input,
                                               int num_entries, int out_dim,
                                               int aggr_sum, const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = Py_BuildValue("{s:s}", "aggr", aggr_sum ? "sum" : "avg");
  if (name) {
    PyObject* n = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", n);
    Py_DECREF(n);
  }
  out.impl = call(H(m.impl), "embedding",
                  Py_BuildValue("(Oii)", H(input.impl), num_entries, out_dim),
                  kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_concat(flexflow_model_t m, int n,
                                            const flexflow_tensor_t* inputs,
                                            int axis, const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* list = PyList_New(n);
  for (int i = 0; i < n; i++) {
    Py_INCREF(H(inputs[i].impl));
    PyList_SET_ITEM(list, i, H(inputs[i].impl));
  }
  PyObject* kw = PyDict_New();
  if (name) {
    PyObject* nm = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", nm);
    Py_DECREF(nm);
  }
  out.impl = call(H(m.impl), "concat", Py_BuildValue("(Oi)", list, axis), kw);
  Py_DECREF(list);
  Py_DECREF(kw);
  return out;
}

static flexflow_tensor_t binary_op(flexflow_model_t m, const char* method,
                                   flexflow_tensor_t a, flexflow_tensor_t b,
                                   const char* name);

flexflow_tensor_t flexflow_model_add_add(flexflow_model_t m,
                                         flexflow_tensor_t a,
                                         flexflow_tensor_t b,
                                         const char* name) {
  return binary_op(m, "add", a, b, name);
}

static flexflow_tensor_t binary_op(flexflow_model_t m, const char* method,
                                   flexflow_tensor_t a, flexflow_tensor_t b,
                                   const char* name) {
  flexflow_tensor_t out{nullptr};
  if (!a.impl || !b.impl) return out;  // upstream builder failed
  PyObject* kw = PyDict_New();
  if (name) {
    PyObject* nm = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", nm);
    Py_DECREF(nm);
  }
  out.impl = call(H(m.impl), method,
                  Py_BuildValue("(OO)", H(a.impl), H(b.impl)), kw);
  Py_DECREF(kw);
  return out;
}

static flexflow_tensor_t unary_op(flexflow_model_t m, const char* method,
                                  flexflow_tensor_t input, const char* name) {
  flexflow_tensor_t out{nullptr};
  if (!input.impl) return out;  // upstream builder failed
  PyObject* kw = PyDict_New();
  if (name) {
    PyObject* nm = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", nm);
    Py_DECREF(nm);
  }
  out.impl = call(H(m.impl), method, Py_BuildValue("(O)", H(input.impl)), kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_subtract(flexflow_model_t m,
                                              flexflow_tensor_t a,
                                              flexflow_tensor_t b,
                                              const char* name) {
  return binary_op(m, "subtract", a, b, name);
}
flexflow_tensor_t flexflow_model_add_multiply(flexflow_model_t m,
                                              flexflow_tensor_t a,
                                              flexflow_tensor_t b,
                                              const char* name) {
  return binary_op(m, "multiply", a, b, name);
}
flexflow_tensor_t flexflow_model_add_divide(flexflow_model_t m,
                                            flexflow_tensor_t a,
                                            flexflow_tensor_t b,
                                            const char* name) {
  return binary_op(m, "divide", a, b, name);
}
flexflow_tensor_t flexflow_model_add_relu(flexflow_model_t m,
                                          flexflow_tensor_t input,
                                          const char* name) {
  return unary_op(m, "relu", input, name);
}
flexflow_tensor_t flexflow_model_add_sigmoid(flexflow_model_t m,
                                             flexflow_tensor_t input,
                                             const char* name) {
  return unary_op(m, "sigmoid", input, name);
}
flexflow_tensor_t flexflow_model_add_tanh(flexflow_model_t m,
                                          flexflow_tensor_t input,
                                          const char* name) {
  return unary_op(m, "tanh", input, name);
}
flexflow_tensor_t flexflow_model_add_elu(flexflow_model_t m,
                                         flexflow_tensor_t input,
                                         const char* name) {
  return unary_op(m, "elu", input, name);
}
flexflow_tensor_t flexflow_model_add_exp(flexflow_model_t m,
                                         flexflow_tensor_t input,
                                         const char* name) {
  return unary_op(m, "exp", input, name);
}

flexflow_tensor_t flexflow_model_add_batch_norm(flexflow_model_t m,
                                                flexflow_tensor_t input,
                                                int relu, const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = Py_BuildValue("{s:O}", "relu", relu ? Py_True : Py_False);
  if (name) {
    PyObject* nm = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", nm);
    Py_DECREF(nm);
  }
  out.impl = call(H(m.impl), "batch_norm",
                  Py_BuildValue("(O)", H(input.impl)), kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_dropout(flexflow_model_t m,
                                             flexflow_tensor_t input,
                                             double rate, int seed,
                                             const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = Py_BuildValue("{s:i}", "seed", seed);
  if (name) {
    PyObject* nm = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", nm);
    Py_DECREF(nm);
  }
  out.impl = call(H(m.impl), "dropout",
                  Py_BuildValue("(Od)", H(input.impl), rate), kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_mse_loss(flexflow_model_t m,
                                              flexflow_tensor_t logits,
                                              flexflow_tensor_t labels,
                                              const char* reduction,
                                              const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = Py_BuildValue("{s:s}", "reduction",
                               reduction ? reduction : "average");
  if (name) {
    PyObject* nm = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", nm);
    Py_DECREF(nm);
  }
  out.impl = call(H(m.impl), "mse_loss",
                  Py_BuildValue("(OO)", H(logits.impl), H(labels.impl)), kw);
  Py_DECREF(kw);
  return out;
}

int flexflow_model_compile(flexflow_model_t m, const char* optimizer,
                           double lr, const char* loss, const char** metrics,
                           int num_metrics) {
  PyObject* opt = nullptr;
  if (!optimizer || !*optimizer) {
    /* optimizer object bound earlier via flexflow_model_set_*_optimizer */
    opt = PyObject_GetAttrString(H(m.impl), "_c_api_optimizer");
    if (!opt || opt == Py_None) {
      fprintf(stderr, "flexflow_model_compile: no optimizer bound\n");
      Py_XDECREF(opt);
      PyErr_Clear();
      return -1;
    }
  } else {
    PyObject* optcls = PyObject_GetAttrString(
        g_module, strcmp(optimizer, "adam") == 0 ? "AdamOptimizer"
                                                 : "SGDOptimizer");
    PyObject* kw = strcmp(optimizer, "adam") == 0
                       ? Py_BuildValue("{s:d}", "alpha", lr)
                       : Py_BuildValue("{s:d}", "lr", lr);
    PyObject* empty = PyTuple_New(0);
    opt = PyObject_Call(optcls, empty, kw);
    Py_DECREF(empty);
    Py_DECREF(kw);
    Py_DECREF(optcls);
  }
  if (!opt) { PyErr_Print(); return -1; }
  PyObject* mlist = PyList_New(num_metrics);
  for (int i = 0; i < num_metrics; i++)
    PyList_SET_ITEM(mlist, i, PyUnicode_FromString(metrics[i]));
  PyObject* res = call(H(m.impl), "compile",
                       Py_BuildValue("(OsO)", opt, loss, mlist));
  Py_DECREF(opt);
  Py_DECREF(mlist);
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int flexflow_model_init_layers(flexflow_model_t m) {
  PyObject* res = call(H(m.impl), "init_layers", PyTuple_New(0));
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int flexflow_model_set_input_f32(flexflow_model_t m, flexflow_tensor_t t,
                                 const float* data, int64_t count) {
  // reshape to the tensor's *native* dims: C callers pass reference-order
  // data for 4-D (N,C,H,W) — convert via numpy transpose
  PyObject* tensor = H(t.impl);
  PyObject* dims_obj = PyObject_GetAttrString(tensor, "dims");
  int nd = (int)PyTuple_Size(dims_obj);
  std::vector<int> dims(nd);
  for (int i = 0; i < nd; i++)
    dims[i] = (int)PyLong_AsLong(PyTuple_GetItem(dims_obj, i));
  Py_DECREF(dims_obj);
  std::vector<int> cdims(dims);
  if (nd == 4) {  // caller provides N,C,H,W; tensor dims are N,H,W,C
    cdims[1] = dims[3]; cdims[2] = dims[1]; cdims[3] = dims[2];
  }
  PyObject* arr = np_array(data, count, cdims.data(), nd, 'f');
  if (!arr) return -1;
  if (nd == 4) {
    PyObject* tr = call(arr, "transpose", Py_BuildValue("(iiii)", 0, 2, 3, 1));
    Py_DECREF(arr);
    arr = tr;
    if (!arr) return -1;
  }
  return stage_input(m, tensor, arr);
}

int flexflow_model_set_input_i32(flexflow_model_t m, flexflow_tensor_t t,
                                 const int32_t* data, int64_t count) {
  PyObject* tensor = H(t.impl);
  PyObject* dims_obj = PyObject_GetAttrString(tensor, "dims");
  int nd = (int)PyTuple_Size(dims_obj);
  std::vector<int> dims(nd);
  for (int i = 0; i < nd; i++)
    dims[i] = (int)PyLong_AsLong(PyTuple_GetItem(dims_obj, i));
  Py_DECREF(dims_obj);
  PyObject* arr = np_array(data, count, dims.data(), nd, 'i');
  if (!arr) return -1;
  return stage_input(m, tensor, arr);
}

static int set_label(flexflow_model_t m, PyObject* arr) {
  if (!arr) return -1;
  PyObject_SetAttrString(H(m.impl), "_c_api_label", arr);
  Py_DECREF(arr);
  return flush_batch_if_ready(m);
}

int flexflow_model_set_label_i32(flexflow_model_t m, const int32_t* data,
                                 int64_t count) {
  PyObject* model = H(m.impl);
  PyObject* lt = PyObject_GetAttrString(model, "label_tensor");
  PyObject* dims_obj = PyObject_GetAttrString(lt, "dims");
  int nd = (int)PyTuple_Size(dims_obj);
  std::vector<int> dims(nd);
  for (int i = 0; i < nd; i++)
    dims[i] = (int)PyLong_AsLong(PyTuple_GetItem(dims_obj, i));
  Py_DECREF(dims_obj);
  Py_DECREF(lt);
  return set_label(m, np_array(data, count, dims.data(), nd, 'i'));
}

int flexflow_model_set_label_f32(flexflow_model_t m, const float* data,
                                 int64_t count) {
  PyObject* model = H(m.impl);
  PyObject* lt = PyObject_GetAttrString(model, "label_tensor");
  PyObject* dims_obj = PyObject_GetAttrString(lt, "dims");
  int nd = (int)PyTuple_Size(dims_obj);
  std::vector<int> dims(nd);
  for (int i = 0; i < nd; i++)
    dims[i] = (int)PyLong_AsLong(PyTuple_GetItem(dims_obj, i));
  Py_DECREF(dims_obj);
  Py_DECREF(lt);
  return set_label(m, np_array(data, count, dims.data(), nd, 'f'));
}

static int simple_call(flexflow_model_t m, const char* method) {
  PyObject* res = call(H(m.impl), method, PyTuple_New(0));
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int flexflow_model_forward(flexflow_model_t m) {
  if (flush_batch_if_ready(m) != 0) return -1;
  return simple_call(m, "forward");
}
int flexflow_model_zero_gradients(flexflow_model_t m) {
  return simple_call(m, "zero_gradients");
}
int flexflow_model_backward(flexflow_model_t m) {
  return simple_call(m, "backward");
}
int flexflow_model_update(flexflow_model_t m) {
  return simple_call(m, "update");
}
int flexflow_model_sync(flexflow_model_t m) { return simple_call(m, "sync"); }

void flexflow_model_reset_metrics(flexflow_model_t m) {
  simple_call(m, "reset_metrics");
}

double flexflow_model_get_accuracy(flexflow_model_t m, int64_t* train_all,
                                   int64_t* train_correct) {
  PyObject* pm = call(H(m.impl), "get_metrics", PyTuple_New(0));
  if (!pm) return -1.0;
  PyObject* acc = PyObject_GetAttrString(pm, "accuracy");
  PyObject* ta = PyObject_GetAttrString(pm, "train_all");
  PyObject* tc = PyObject_GetAttrString(pm, "train_correct");
  double result = acc ? PyFloat_AsDouble(acc) : -1.0;
  if (train_all && ta) *train_all = PyLong_AsLongLong(ta);
  if (train_correct && tc) *train_correct = PyLong_AsLongLong(tc);
  Py_XDECREF(acc); Py_XDECREF(ta); Py_XDECREF(tc); Py_DECREF(pm);
  return result;
}

int flexflow_model_train_iteration(flexflow_model_t m) {
  if (flush_batch_if_ready(m) != 0) return -1;
  return simple_call(m, "train_iteration");
}

double flexflow_model_get_metric(flexflow_model_t m, const char* name) {
  PyObject* pm = call(H(m.impl), "get_metrics", PyTuple_New(0));
  if (!pm) return -1.0;
  PyObject* v = PyObject_GetAttrString(pm, name);
  double result = v ? PyFloat_AsDouble(v) : -1.0;
  if (PyErr_Occurred()) { PyErr_Print(); result = -1.0; }
  Py_XDECREF(v);
  Py_DECREF(pm);
  return result;
}

int64_t flexflow_parameter_get_volume(flexflow_model_t m, const char* op_name,
                                      const char* weight_name) {
  PyObject* arr = call(H(m.impl), "get_parameter",
                       Py_BuildValue("(ss)", op_name, weight_name));
  if (!arr) return -1;
  PyObject* size = PyObject_GetAttrString(arr, "size");
  int64_t n = size ? PyLong_AsLongLong(size) : -1;
  if (PyErr_Occurred()) {
    PyErr_Print();
    n = -1;
  }
  Py_XDECREF(size);
  Py_DECREF(arr);
  return n;
}

int flexflow_model_get_parameter_f32(flexflow_model_t m, const char* op_name,
                                     const char* weight_name, float* out,
                                     int64_t count) {
  PyObject* arr = call(H(m.impl), "get_parameter",
                       Py_BuildValue("(ss)", op_name, weight_name));
  if (!arr) return -1;
  PyObject* flat = call(arr, "astype", Py_BuildValue("(s)", "float32"));
  Py_DECREF(arr);
  if (!flat) return -1;
  PyObject* rav = call(flat, "ravel", PyTuple_New(0));
  Py_DECREF(flat);
  if (!rav) return -1;
  PyObject* lst = call(rav, "tolist", PyTuple_New(0));
  Py_DECREF(rav);
  if (!lst) return -1;
  int64_t n = PyList_Size(lst);
  int rc = 0;
  if (n != count) {
    rc = -1;
  } else {
    for (int64_t i = 0; i < n; i++)
      out[i] = (float)PyFloat_AsDouble(PyList_GET_ITEM(lst, i));
  }
  Py_DECREF(lst);
  return rc;
}

int flexflow_model_set_parameter_f32(flexflow_model_t m, const char* op_name,
                                     const char* weight_name,
                                     const float* data, int64_t count) {
  PyObject* arr_flat = np_array(data, count, nullptr, 1, 'f');
  if (!arr_flat) return -1;
  // reshape to the current parameter's shape
  PyObject* cur = call(H(m.impl), "get_parameter",
                       Py_BuildValue("(ss)", op_name, weight_name));
  if (!cur) { Py_DECREF(arr_flat); return -1; }
  PyObject* shape = PyObject_GetAttrString(cur, "shape");
  Py_DECREF(cur);
  if (!shape) {
    PyErr_Print();
    Py_DECREF(arr_flat);
    return -1;
  }
  PyObject* arr = call(arr_flat, "reshape", Py_BuildValue("(O)", shape));
  Py_DECREF(shape);
  Py_DECREF(arr_flat);
  if (!arr) return -1;
  PyObject* res = call(H(m.impl), "set_parameter",
                       Py_BuildValue("(ssO)", op_name, weight_name, arr));
  Py_DECREF(arr);
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int flexflow_config_import_strategy(flexflow_config_t c, const char* path) {
  PyObject* p = PyUnicode_FromString(path);
  int rc = PyObject_SetAttrString(H(c.impl), "import_strategy_file", p);
  Py_DECREF(p);
  return rc;
}

int flexflow_model_export_strategy(flexflow_model_t m, const char* path) {
  PyObject* strategies = call(H(m.impl), "get_strategies", PyTuple_New(0));
  if (!strategies) return -1;
  PyObject* fn = PyObject_GetAttrString(g_module, "save_strategies_to_file");
  if (!fn) { Py_DECREF(strategies); PyErr_Print(); return -1; }
  PyObject* res = PyObject_CallFunction(fn, "sO", path, strategies);
  Py_DECREF(fn);
  Py_DECREF(strategies);
  if (!res) { PyErr_Print(); return -1; }
  Py_DECREF(res);
  return 0;
}

int flexflow_model_save(flexflow_model_t m, const char* path) {
  PyObject* res = call(H(m.impl), "save", Py_BuildValue("(s)", path));
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int flexflow_model_load(flexflow_model_t m, const char* path) {
  PyObject* res = call(H(m.impl), "load", Py_BuildValue("(s)", path));
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int flexflow_tensor_get_dims(flexflow_tensor_t t, int* dims) {
  PyObject* dims_obj = PyObject_GetAttrString(H(t.impl), "dims");
  if (!dims_obj) return -1;
  int nd = (int)PyTuple_Size(dims_obj);
  for (int i = 0; i < nd && i < 8; i++)
    dims[i] = (int)PyLong_AsLong(PyTuple_GetItem(dims_obj, i));
  Py_DECREF(dims_obj);
  return nd;
}

/* ====================================================================
 * Extended surface (reference parity: python/flexflow_c.h:27-718)
 * ==================================================================== */

/* ---- config accessors ---------------------------------------------- */

int flexflow_config_parse_args(flexflow_config_t c, int argc, char** argv) {
  PyObject* list = PyList_New(argc);
  for (int i = 0; i < argc; i++)
    PyList_SET_ITEM(list, i, PyUnicode_FromString(argv[i]));
  PyObject* res = call(H(c.impl), "parse_args", Py_BuildValue("(O)", list));
  Py_DECREF(list);
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

int flexflow_config_parse_args_default(flexflow_config_t c) {
  PyObject* res = call(H(c.impl), "parse_args", PyTuple_New(0));
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

static int config_get_int(flexflow_config_t c, const char* attr) {
  PyObject* v = PyObject_GetAttrString(H(c.impl), attr);
  if (!v) { PyErr_Print(); return -1; }
  int out = (int)PyLong_AsLong(v);
  Py_DECREF(v);
  return out;
}

int flexflow_config_get_batch_size(flexflow_config_t c) {
  return config_get_int(c, "batch_size");
}
int flexflow_config_get_epochs(flexflow_config_t c) {
  return config_get_int(c, "epochs");
}
int flexflow_config_get_num_nodes(flexflow_config_t c) {
  return config_get_int(c, "num_nodes");
}
int flexflow_config_get_workers_per_node(flexflow_config_t c) {
  return config_get_int(c, "workers_per_node");
}

/* ---- optimizer objects --------------------------------------------- */

static void* make_object(const char* cls_name, PyObject* kw) {
  if (!ensure_init()) { Py_XDECREF(kw); return nullptr; }
  PyObject* cls = PyObject_GetAttrString(g_module, cls_name);
  if (!cls) { PyErr_Print(); Py_XDECREF(kw); return nullptr; }
  PyObject* empty = PyTuple_New(0);
  PyObject* obj = PyObject_Call(cls, empty, kw);
  if (!obj) PyErr_Print();
  Py_DECREF(empty);
  Py_XDECREF(kw);
  Py_DECREF(cls);
  return obj;
}

flexflow_sgd_optimizer_t flexflow_sgd_optimizer_create(
    flexflow_model_t m, double lr, double momentum, int nesterov,
    double weight_decay) {
  (void)m;  /* reference binds the model at create; ours binds at compile */
  flexflow_sgd_optimizer_t out{nullptr};
  out.impl = make_object("SGDOptimizer",
      Py_BuildValue("{s:d,s:d,s:O,s:d}", "lr", lr, "momentum", momentum,
                    "nesterov", nesterov ? Py_True : Py_False,
                    "weight_decay", weight_decay));
  return out;
}

void flexflow_sgd_optimizer_destroy(flexflow_sgd_optimizer_t o) {
  Py_XDECREF(H(o.impl));
}

void flexflow_sgd_optimizer_set_lr(flexflow_sgd_optimizer_t o, double lr) {
  PyObject* v = PyFloat_FromDouble(lr);
  PyObject_SetAttrString(H(o.impl), "lr", v);
  Py_DECREF(v);
}

flexflow_adam_optimizer_t flexflow_adam_optimizer_create(
    flexflow_model_t m, double alpha, double beta1, double beta2,
    double weight_decay, double epsilon) {
  (void)m;
  flexflow_adam_optimizer_t out{nullptr};
  out.impl = make_object("AdamOptimizer",
      Py_BuildValue("{s:d,s:d,s:d,s:d,s:d}", "alpha", alpha, "beta1", beta1,
                    "beta2", beta2, "weight_decay", weight_decay,
                    "epsilon", epsilon));
  return out;
}

void flexflow_adam_optimizer_destroy(flexflow_adam_optimizer_t o) {
  Py_XDECREF(H(o.impl));
}

void flexflow_adam_optimizer_set_lr(flexflow_adam_optimizer_t o, double lr) {
  PyObject* v = PyFloat_FromDouble(lr);
  PyObject_SetAttrString(H(o.impl), "alpha", v);
  Py_DECREF(v);
}

static int set_model_optimizer(flexflow_model_t m, void* opt) {
  if (!opt) return -1;
  return PyObject_SetAttrString(H(m.impl), "_c_api_optimizer",
                                H(opt)) == 0 ? 0 : -1;
}

int flexflow_model_set_sgd_optimizer(flexflow_model_t m,
                                     flexflow_sgd_optimizer_t o) {
  return set_model_optimizer(m, o.impl);
}

int flexflow_model_set_adam_optimizer(flexflow_model_t m,
                                      flexflow_adam_optimizer_t o) {
  return set_model_optimizer(m, o.impl);
}

/* ---- initializer objects ------------------------------------------- */

flexflow_initializer_t flexflow_initializer_create_null(void) {
  flexflow_initializer_t out{nullptr};  /* null = op default initializer */
  return out;
}

flexflow_glorot_uniform_initializer_t
flexflow_glorot_uniform_initializer_create(int seed) {
  flexflow_glorot_uniform_initializer_t out{nullptr};
  out.impl = make_object("GlorotUniform", Py_BuildValue("{s:i}", "seed", seed));
  return out;
}
void flexflow_glorot_uniform_initializer_destroy(
    flexflow_glorot_uniform_initializer_t i) { Py_XDECREF(H(i.impl)); }

flexflow_zero_initializer_t flexflow_zero_initializer_create(void) {
  flexflow_zero_initializer_t out{nullptr};
  out.impl = make_object("ZeroInitializer", nullptr);
  return out;
}
void flexflow_zero_initializer_destroy(flexflow_zero_initializer_t i) {
  Py_XDECREF(H(i.impl));
}

flexflow_uniform_initializer_t flexflow_uniform_initializer_create(
    int seed, float min_val, float max_val) {
  flexflow_uniform_initializer_t out{nullptr};
  out.impl = make_object("UniformInitializer",
      Py_BuildValue("{s:i,s:d,s:d}", "seed", seed, "min_val",
                    (double)min_val, "max_val", (double)max_val));
  return out;
}
void flexflow_uniform_initializer_destroy(flexflow_uniform_initializer_t i) {
  Py_XDECREF(H(i.impl));
}

flexflow_norm_initializer_t flexflow_norm_initializer_create(
    int seed, float mean, float stddev) {
  flexflow_norm_initializer_t out{nullptr};
  out.impl = make_object("NormInitializer",
      Py_BuildValue("{s:i,s:d,s:d}", "seed", seed, "mean", (double)mean,
                    "stddev", (double)stddev));
  return out;
}
void flexflow_norm_initializer_destroy(flexflow_norm_initializer_t i) {
  Py_XDECREF(H(i.impl));
}

/* ---- builder variants with initializer handles --------------------- */

static void kw_set_init(PyObject* kw, const char* key, void* init) {
  if (init) PyDict_SetItemString(kw, key, H(init));
}

flexflow_tensor_t flexflow_model_add_dense_v2(
    flexflow_model_t m, flexflow_tensor_t input, int out_dim, int activation,
    int use_bias, flexflow_initializer_t kernel_init,
    flexflow_initializer_t bias_init, const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = Py_BuildValue("{s:s,s:O}", "activation",
                               kActNames[activation & 3], "use_bias",
                               use_bias ? Py_True : Py_False);
  kw_set_init(kw, "kernel_initializer", kernel_init.impl);
  kw_set_init(kw, "bias_initializer", bias_init.impl);
  if (name) {
    PyObject* n = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", n);
    Py_DECREF(n);
  }
  out.impl = call(H(m.impl), "dense",
                  Py_BuildValue("(Oi)", H(input.impl), out_dim), kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_conv2d_v2(
    flexflow_model_t m, flexflow_tensor_t input, int out_channels,
    int kernel_h, int kernel_w, int stride_h, int stride_w, int padding_h,
    int padding_w, int activation, int use_bias,
    flexflow_initializer_t kernel_init, flexflow_initializer_t bias_init,
    const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = Py_BuildValue("{s:s,s:O}", "activation",
                               kActNames[activation & 3], "use_bias",
                               use_bias ? Py_True : Py_False);
  kw_set_init(kw, "kernel_initializer", kernel_init.impl);
  kw_set_init(kw, "bias_initializer", bias_init.impl);
  if (name) {
    PyObject* n = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", n);
    Py_DECREF(n);
  }
  out.impl = call(H(m.impl), "conv2d",
                  Py_BuildValue("(Oiiiiiii)", H(input.impl), out_channels,
                                kernel_h, kernel_w, stride_h, stride_w,
                                padding_h, padding_w),
                  kw);
  Py_DECREF(kw);
  return out;
}

flexflow_tensor_t flexflow_model_add_expert_mlp(
    flexflow_model_t m, flexflow_tensor_t input, int num_experts,
    int hidden_size, double capacity_factor, const char* name) {
  flexflow_tensor_t out{nullptr};
  PyObject* kw = Py_BuildValue("{s:d}", "capacity_factor", capacity_factor);
  if (name) {
    PyObject* n = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", n);
    Py_DECREF(n);
  }
  out.impl = call(H(m.impl), "expert_mlp",
                  Py_BuildValue("(Oii)", H(input.impl), num_experts,
                                hidden_size),
                  kw);
  Py_DECREF(kw);
  return out;
}

/* ---- NetConfig ------------------------------------------------------ */

flexflow_net_config_t flexflow_net_config_create(void) {
  flexflow_net_config_t out{nullptr};
  const char* p = getenv("FF_DATASET");
  out.impl = PyUnicode_FromString(p ? p : "");
  return out;
}
void flexflow_net_config_destroy(flexflow_net_config_t c) {
  Py_XDECREF(H(c.impl));
}
const char* flexflow_net_config_get_dataset_path(flexflow_net_config_t c) {
  return c.impl ? PyUnicode_AsUTF8(H(c.impl)) : "";
}

/* ---- deferred-shape (functional) builders --------------------------- */

static flexflow_op_t deferred_op(const char* method, PyObject* args,
                                 PyObject* kw, const char* name) {
  flexflow_op_t out{nullptr};
  PyObject* d = PyDict_New();
  PyObject* me = PyUnicode_FromString(method);
  PyDict_SetItemString(d, "_deferred", me);
  Py_DECREF(me);
  PyDict_SetItemString(d, "args", args);
  PyDict_SetItemString(d, "kwargs", kw);
  if (name) {
    PyObject* n = PyUnicode_FromString(name);
    PyDict_SetItemString(kw, "name", n);
    Py_DECREF(n);
  }
  Py_DECREF(args);
  Py_DECREF(kw);
  out.impl = d;
  return out;
}

flexflow_op_t flexflow_model_add_conv2d_no_inout(
    flexflow_model_t m, int out_channels, int kernel_h, int kernel_w,
    int stride_h, int stride_w, int padding_h, int padding_w, int activation,
    int use_bias, const char* name) {
  (void)m;
  return deferred_op("conv2d",
      Py_BuildValue("(iiiiiii)", out_channels, kernel_h, kernel_w, stride_h,
                    stride_w, padding_h, padding_w),
      Py_BuildValue("{s:s,s:O}", "activation", kActNames[activation & 3],
                    "use_bias", use_bias ? Py_True : Py_False),
      name);
}

flexflow_op_t flexflow_model_add_dense_no_inout(
    flexflow_model_t m, int out_dim, int activation, int use_bias,
    const char* name) {
  (void)m;
  return deferred_op("dense", Py_BuildValue("(i)", out_dim),
      Py_BuildValue("{s:s,s:O}", "activation", kActNames[activation & 3],
                    "use_bias", use_bias ? Py_True : Py_False),
      name);
}

flexflow_op_t flexflow_model_add_pool2d_no_inout(
    flexflow_model_t m, int kernel_h, int kernel_w, int stride_h,
    int stride_w, int padding_h, int padding_w, int pool_max,
    const char* name) {
  (void)m;
  return deferred_op("pool2d",
      Py_BuildValue("(iiiiii)", kernel_h, kernel_w, stride_h, stride_w,
                    padding_h, padding_w),
      Py_BuildValue("{s:s}", "pool_type", pool_max ? "max" : "avg"), name);
}

flexflow_op_t flexflow_model_add_flat_no_inout(flexflow_model_t m,
                                               const char* name) {
  (void)m;
  return deferred_op("flat", PyTuple_New(0), PyDict_New(), name);
}

flexflow_tensor_t flexflow_op_init_inout(flexflow_op_t op, flexflow_model_t m,
                                         flexflow_tensor_t input) {
  flexflow_tensor_t out{nullptr};
  PyObject* d = H(op.impl);
  if (!d || !PyDict_Check(d)) return out;
  PyObject* method = PyDict_GetItemString(d, "_deferred");
  PyObject* args = PyDict_GetItemString(d, "args");
  PyObject* kw = PyDict_GetItemString(d, "kwargs");
  if (!method || !args) return out;
  Py_ssize_t n = PyTuple_Size(args);
  PyObject* full = PyTuple_New(n + 1);
  Py_INCREF(H(input.impl));
  PyTuple_SET_ITEM(full, 0, H(input.impl));
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* it = PyTuple_GetItem(args, i);
    Py_INCREF(it);
    PyTuple_SET_ITEM(full, i + 1, it);
  }
  out.impl = call(H(m.impl), PyUnicode_AsUTF8(method), full, kw);
  if (out.impl) {
    PyDict_SetItemString(d, "output", H(out.impl));
    PyObject* ops = PyObject_GetAttrString(H(m.impl), "ops");
    if (ops) {
      PyObject* last = PyList_GetItem(ops, PyList_Size(ops) - 1);
      if (last) PyDict_SetItemString(d, "op", last);
      Py_DECREF(ops);
    }
  }
  return out;
}

int flexflow_op_add_to_model(flexflow_op_t op, flexflow_model_t m) {
  (void)m;  /* ops join the graph at creation in this core */
  return (op.impl && (!PyDict_Check(H(op.impl)) ||
                      PyDict_GetItemString(H(op.impl), "op"))) ? 0 : -1;
}

int flexflow_op_init(flexflow_op_t op, flexflow_model_t m) {
  (void)op;  /* per-op init happens inside model init_layers */
  (void)m;
  return 0;
}

int flexflow_op_forward(flexflow_op_t op, flexflow_model_t m) {
  (void)op;  /* the fused step runs the whole graph; a standalone op
                forward maps to the staged driver's forward */
  PyObject* res = call(H(m.impl), "forward", PyTuple_New(0));
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

/* ---- op / parameter handles ----------------------------------------- */

static PyObject* resolve_op(flexflow_op_t op) {
  PyObject* h = H(op.impl);
  if (h && PyDict_Check(h)) return PyDict_GetItemString(h, "op");
  return h;
}

int flexflow_model_get_num_layers(flexflow_model_t m) {
  PyObject* ops = PyObject_GetAttrString(H(m.impl), "ops");
  if (!ops) return -1;
  int n = (int)PyList_Size(ops);
  Py_DECREF(ops);
  return n;
}

flexflow_op_t flexflow_model_get_layer_by_id(flexflow_model_t m, int id) {
  flexflow_op_t out{nullptr};
  PyObject* ops = PyObject_GetAttrString(H(m.impl), "ops");
  if (!ops) return out;
  PyObject* op = PyList_GetItem(ops, id);  /* borrowed */
  if (op) { Py_INCREF(op); out.impl = op; }
  else PyErr_Clear();
  Py_DECREF(ops);
  return out;
}

void flexflow_op_destroy(flexflow_op_t op) { Py_XDECREF(H(op.impl)); }

static flexflow_tensor_t op_tensor_by_id(flexflow_op_t op, const char* attr,
                                         int id) {
  flexflow_tensor_t out{nullptr};
  PyObject* o = resolve_op(op);
  if (!o) return out;
  PyObject* lst = PyObject_GetAttrString(o, attr);
  if (!lst) { PyErr_Print(); return out; }
  PyObject* t = PySequence_GetItem(lst, id);  /* new ref */
  if (!t) PyErr_Clear();
  out.impl = t;
  Py_DECREF(lst);
  return out;
}

flexflow_tensor_t flexflow_op_get_input_by_id(flexflow_op_t op, int id) {
  return op_tensor_by_id(op, "inputs", id);
}

flexflow_tensor_t flexflow_op_get_output_by_id(flexflow_op_t op, int id) {
  PyObject* h = H(op.impl);
  if (h && PyDict_Check(h)) {  /* deferred handle: cached output tensor */
    flexflow_tensor_t out{nullptr};
    PyObject* t = PyDict_GetItemString(h, "output");
    if (t && id == 0) { Py_INCREF(t); out.impl = t; }
    return out;
  }
  return op_tensor_by_id(op, "outputs", id);
}

flexflow_parameter_t flexflow_op_get_parameter_by_id(flexflow_op_t op,
                                                     int id) {
  flexflow_parameter_t out{nullptr};
  PyObject* o = resolve_op(op);
  if (!o) return out;
  PyObject* ws = PyObject_GetAttrString(o, "weights");
  if (!ws) { PyErr_Print(); return out; }
  PyObject* w = PySequence_GetItem(ws, id);
  if (!w) PyErr_Clear();
  out.impl = w;
  Py_DECREF(ws);
  return out;
}

flexflow_parameter_t flexflow_model_get_parameter_by_id(flexflow_model_t m,
                                                        int id) {
  flexflow_parameter_t out{nullptr};
  PyObject* ops = PyObject_GetAttrString(H(m.impl), "ops");
  if (!ops) return out;
  int seen = 0;
  for (Py_ssize_t i = 0; i < PyList_Size(ops) && !out.impl; i++) {
    PyObject* ws = PyObject_GetAttrString(PyList_GetItem(ops, i), "weights");
    if (!ws) continue;
    int nw = (int)PySequence_Size(ws);
    if (id < seen + nw) out.impl = PySequence_GetItem(ws, id - seen);
    seen += nw;
    Py_DECREF(ws);
  }
  Py_DECREF(ops);
  return out;
}

void flexflow_parameter_destroy(flexflow_parameter_t p) {
  Py_XDECREF(H(p.impl));
}

int64_t flexflow_parameter_get_volume_v2(flexflow_parameter_t p) {
  PyObject* v = call(H(p.impl), "volume", PyTuple_New(0));
  if (!v) return -1;
  int64_t out = PyLong_AsLongLong(v);
  Py_DECREF(v);
  return out;
}

/* (owner_op.model, owner_op.name, param.name) → get/set via model API */
static PyObject* param_model(PyObject* p) {
  PyObject* op = PyObject_GetAttrString(p, "owner_op");
  if (!op) return nullptr;
  PyObject* model = PyObject_GetAttrString(op, "model");
  Py_DECREF(op);
  return model;
}

static int param_names(PyObject* p, PyObject** op_name, PyObject** w_name) {
  PyObject* op = PyObject_GetAttrString(p, "owner_op");
  if (!op) return -1;
  *op_name = PyObject_GetAttrString(op, "name");
  Py_DECREF(op);
  *w_name = PyObject_GetAttrString(p, "name");
  return (*op_name && *w_name) ? 0 : -1;
}

int flexflow_parameter_get_weights_float(flexflow_parameter_t p, float* out,
                                         int64_t count) {
  PyObject* model = param_model(H(p.impl));
  PyObject *opn = nullptr, *wn = nullptr;
  if (!model || param_names(H(p.impl), &opn, &wn) != 0) {
    Py_XDECREF(model);
    return -1;
  }
  PyObject* arr = call(model, "get_parameter",
                       Py_BuildValue("(OO)", opn, wn));
  Py_DECREF(model); Py_DECREF(opn); Py_DECREF(wn);
  if (!arr) return -1;
  PyObject* flat = call(arr, "ravel", PyTuple_New(0));
  Py_DECREF(arr);
  if (!flat) return -1;
  PyObject* f32 = call(flat, "astype", Py_BuildValue("(s)", "float32"));
  Py_DECREF(flat);
  if (!f32) return -1;
  PyObject* bytes = call(f32, "tobytes", PyTuple_New(0));
  Py_DECREF(f32);
  if (!bytes) return -1;
  int64_t have = (int64_t)(PyBytes_Size(bytes) / sizeof(float));
  int ok = -1;
  if (have <= count) {
    memcpy(out, PyBytes_AsString(bytes), (size_t)have * sizeof(float));
    ok = 0;
  }
  Py_DECREF(bytes);
  return ok;
}

int flexflow_parameter_set_weights_float(flexflow_parameter_t p,
                                         const float* data, int64_t count) {
  PyObject* model = param_model(H(p.impl));
  PyObject *opn = nullptr, *wn = nullptr;
  if (!model || param_names(H(p.impl), &opn, &wn) != 0) {
    Py_XDECREF(model);
    return -1;
  }
  PyObject* dims = PyObject_GetAttrString(H(p.impl), "dims");
  int nd = dims ? (int)PyTuple_Size(dims) : 1;
  std::vector<int> cdims(nd, (int)count);
  for (int i = 0; dims && i < nd; i++)
    cdims[i] = (int)PyLong_AsLong(PyTuple_GetItem(dims, i));
  Py_XDECREF(dims);
  PyObject* arr = np_array(data, count, cdims.data(), nd, 'f');
  int ok = -1;
  if (arr) {
    PyObject* res = call(model, "set_parameter",
                         Py_BuildValue("(OOO)", opn, wn, arr));
    if (res) { ok = 0; Py_DECREF(res); }
    Py_DECREF(arr);
  }
  Py_DECREF(model); Py_DECREF(opn); Py_DECREF(wn);
  return ok;
}

/* ---- label tensor / layers / prefetch ------------------------------- */

flexflow_tensor_t flexflow_model_get_label_tensor(flexflow_model_t m) {
  flexflow_tensor_t out{nullptr};
  out.impl = PyObject_GetAttrString(H(m.impl), "label_tensor");
  if (!out.impl) PyErr_Clear();
  return out;
}

void flexflow_model_print_layers(flexflow_model_t m, int id) {
  PyObject* ops = PyObject_GetAttrString(H(m.impl), "ops");
  if (!ops) return;
  for (Py_ssize_t i = 0; i < PyList_Size(ops); i++) {
    if (id >= 0 && i != id) continue;
    PyObject* r = PyObject_Repr(PyList_GetItem(ops, i));
    if (r) {
      printf("layer[%zd]: %s\n", i, PyUnicode_AsUTF8(r));
      Py_DECREF(r);
    }
  }
  Py_DECREF(ops);
}

int flexflow_model_prefetch(flexflow_model_t m) {
  (void)m;  /* device_put of the staged batch is already async */
  return 0;
}

/* ---- perf metrics handle -------------------------------------------- */

flexflow_perf_metrics_t flexflow_model_get_perf_metrics(flexflow_model_t m) {
  flexflow_perf_metrics_t out{nullptr};
  out.impl = call(H(m.impl), "get_metrics", PyTuple_New(0));
  return out;
}

void flexflow_per_metrics_destroy(flexflow_perf_metrics_t p) {
  Py_XDECREF(H(p.impl));
}

float flexflow_per_metrics_get_accuracy(flexflow_perf_metrics_t p) {
  PyObject* acc = PyObject_GetAttrString(H(p.impl), "accuracy");
  if (!acc) { PyErr_Print(); return -1.0f; }
  float out = (float)PyFloat_AsDouble(acc);
  Py_DECREF(acc);
  return out;
}

int flexflow_model_compute_metrics(flexflow_model_t m) {
  /* metrics accumulate on-device inside the fused step; draining folds
     them into the host PerfMetrics (reference: UPDATE_METRICS_TASK) */
  PyObject* res = call(H(m.impl), "_drain_metrics", PyTuple_New(0));
  if (!res) return -1;
  Py_DECREF(res);
  return 0;
}

/* ---- tracing + timing ----------------------------------------------- */

void flexflow_begin_trace(flexflow_model_t m, int trace_id) {
  (void)m; (void)trace_id;  /* XLA traces the fused step once at jit;
                               replay is automatic (≈ Legion begin_trace) */
}

void flexflow_end_trace(flexflow_model_t m, int trace_id) {
  (void)m; (void)trace_id;
}

double flexflow_get_current_time(flexflow_model_t m) {
  (void)m;
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1e6 + ts.tv_nsec * 1e-3;  /* microseconds */
}

/* ---- raw-ptr attach + inline map ------------------------------------ */

static PyObject* model_dict_attr(PyObject* model, const char* attr) {
  PyObject* d = PyObject_GetAttrString(model, attr);
  if (!d || d == Py_None) {
    Py_XDECREF(d);
    PyErr_Clear();
    d = PyDict_New();
    PyObject_SetAttrString(model, attr, d);
  }
  return d;  /* new ref */
}

int flexflow_tensor_attach_raw_ptr(flexflow_model_t m, flexflow_tensor_t t,
                                   void* ptr, int64_t count, int is_float) {
  /* zero-copy: wrap the caller's memory as a numpy view shaped
     (-1, *tensor.dims[1:]) — the host-resident full dataset */
  PyObject* mv = PyMemoryView_FromMemory(
      (char*)ptr, count * 4, PyBUF_WRITE);
  if (!mv) { PyErr_Print(); return -1; }
  PyObject* arr = call(g_np, "frombuffer", Py_BuildValue("(O)", mv),
                       Py_BuildValue("{s:s}", "dtype",
                                     is_float ? "float32" : "int32"));
  Py_DECREF(mv);
  if (!arr) return -1;
  PyObject* dims_obj = PyObject_GetAttrString(H(t.impl), "dims");
  if (dims_obj) {
    Py_ssize_t nd = PyTuple_Size(dims_obj);
    PyObject* shape = PyTuple_New(nd);
    PyTuple_SET_ITEM(shape, 0, PyLong_FromLong(-1));
    for (Py_ssize_t i = 1; i < nd; i++) {
      PyObject* s = PyTuple_GetItem(dims_obj, i);
      Py_INCREF(s);
      PyTuple_SET_ITEM(shape, i, s);
    }
    PyObject* reshaped = call(arr, "reshape", Py_BuildValue("(O)", shape));
    Py_DECREF(shape);
    Py_DECREF(dims_obj);
    if (reshaped) { Py_DECREF(arr); arr = reshaped; }
  }
  PyObject* att = model_dict_attr(H(m.impl), "_c_api_attached");
  PyDict_SetItem(att, H(t.impl), arr);
  Py_DECREF(att);
  Py_DECREF(arr);
  return 0;
}

int flexflow_tensor_detach_raw_ptr(flexflow_model_t m, flexflow_tensor_t t) {
  PyObject* att = model_dict_attr(H(m.impl), "_c_api_attached");
  int ok = PyDict_DelItem(att, H(t.impl)) == 0 ? 0 : -1;
  if (ok != 0) PyErr_Clear();
  Py_DECREF(att);
  return ok;
}

static PyObject* tensor_host_data(PyObject* model, PyObject* tensor) {
  /* attached first, then the staged batch, then the staged label */
  PyObject* att = model_dict_attr(model, "_c_api_attached");
  PyObject* found = PyDict_GetItem(att, tensor);  /* borrowed */
  Py_XINCREF(found);
  Py_DECREF(att);
  if (found) return found;
  PyObject* staged = PyObject_GetAttrString(model, "_c_api_batch");
  if (staged && staged != Py_None) {
    found = PyDict_GetItem(staged, tensor);
    Py_XINCREF(found);
  }
  Py_XDECREF(staged);
  if (found) return found;
  PyErr_Clear();
  PyObject* label_t = PyObject_GetAttrString(model, "label_tensor");
  if (label_t == tensor) {
    found = PyObject_GetAttrString(model, "_c_api_label");
    if (found == Py_None) { Py_DECREF(found); found = nullptr; }
  }
  Py_XDECREF(label_t);
  PyErr_Clear();
  return found;
}

int flexflow_tensor_inline_map(flexflow_model_t m, flexflow_tensor_t t) {
  PyObject* data = tensor_host_data(H(m.impl), H(t.impl));
  if (!data) return -1;
  PyObject* contig = call(g_np, "ascontiguousarray",
                          Py_BuildValue("(O)", data));
  Py_DECREF(data);
  if (!contig) return -1;
  PyObject* mapped = model_dict_attr(H(m.impl), "_c_api_mapped");
  PyDict_SetItem(mapped, H(t.impl), contig);
  Py_DECREF(mapped);
  Py_DECREF(contig);
  return 0;
}

void flexflow_tensor_inline_unmap(flexflow_model_t m, flexflow_tensor_t t) {
  PyObject* mapped = model_dict_attr(H(m.impl), "_c_api_mapped");
  if (PyDict_DelItem(mapped, H(t.impl)) != 0) PyErr_Clear();
  Py_DECREF(mapped);
}

int flexflow_tensor_is_mapped(flexflow_model_t m, flexflow_tensor_t t) {
  PyObject* mapped = model_dict_attr(H(m.impl), "_c_api_mapped");
  int out = PyDict_GetItem(mapped, H(t.impl)) != nullptr;
  Py_DECREF(mapped);
  return out;
}

static void* mapped_ptr(flexflow_model_t m, flexflow_tensor_t t) {
  PyObject* mapped = model_dict_attr(H(m.impl), "_c_api_mapped");
  PyObject* arr = PyDict_GetItem(mapped, H(t.impl));  /* borrowed */
  Py_DECREF(mapped);
  if (!arr) return nullptr;
  PyObject* ct = PyObject_GetAttrString(arr, "ctypes");
  if (!ct) { PyErr_Print(); return nullptr; }
  PyObject* dp = PyObject_GetAttrString(ct, "data");
  Py_DECREF(ct);
  if (!dp) { PyErr_Print(); return nullptr; }
  void* p = (void*)PyLong_AsUnsignedLongLong(dp);
  Py_DECREF(dp);
  return p;
}

float* flexflow_tensor_get_raw_ptr_float(flexflow_model_t m,
                                         flexflow_tensor_t t) {
  return (float*)mapped_ptr(m, t);
}

int32_t* flexflow_tensor_get_raw_ptr_int32(flexflow_model_t m,
                                           flexflow_tensor_t t) {
  return (int32_t*)mapped_ptr(m, t);
}

int flexflow_tensor_get_num_dims(flexflow_tensor_t t) {
  PyObject* dims_obj = PyObject_GetAttrString(H(t.impl), "dims");
  if (!dims_obj) return -1;
  int nd = (int)PyTuple_Size(dims_obj);
  Py_DECREF(dims_obj);
  return nd;
}

int flexflow_tensor_get_data_type(flexflow_tensor_t t) {
  PyObject* dt = PyObject_GetAttrString(H(t.impl), "dtype");
  if (!dt) return -1;
  const char* s = PyUnicode_AsUTF8(dt);
  int out = 0;
  if (s && strstr(s, "int64")) out = 2;
  else if (s && strstr(s, "int")) out = 1;
  Py_DECREF(dt);
  return out;
}

flexflow_op_t flexflow_tensor_get_owner_op(flexflow_tensor_t t) {
  flexflow_op_t out{nullptr};
  out.impl = PyObject_GetAttrString(H(t.impl), "owner_op");
  if (out.impl == Py_None) { Py_DECREF(H(out.impl)); out.impl = nullptr; }
  if (!out.impl) PyErr_Clear();
  return out;
}

/* ---- dataloader handles --------------------------------------------- */

/* handle dict: model, tensor, input (np|None), label (np|None), num, next,
   is_label.  next_batch stages a [next, next+batch) slice the same way
   flexflow_model_set_input/set_label do, wrapping at num_samples —
   the reference's full-dataset-then-scatter pattern
   (python/flexflow_dataloader.cc:541-640). */

static void* loader_create(flexflow_model_t m, flexflow_tensor_t t,
                           const void* full_input, char in_fmt,
                           const int32_t* full_label, int64_t num_samples,
                           int is_label) {
  PyObject* model = H(m.impl);
  PyObject* d = PyDict_New();
  PyDict_SetItemString(d, "model", model);
  PyDict_SetItemString(d, "tensor", H(t.impl));
  PyObject* dims_obj = PyObject_GetAttrString(H(t.impl), "dims");
  int nd = dims_obj ? (int)PyTuple_Size(dims_obj) : 1;
  std::vector<int> dims(nd, 1);
  int64_t per_sample = 1;
  for (int i = 0; i < nd; i++) {
    dims[i] = (int)PyLong_AsLong(PyTuple_GetItem(dims_obj, i));
    if (i > 0) per_sample *= dims[i];
  }
  Py_XDECREF(dims_obj);
  dims[0] = (int)num_samples;
  if (full_input) {
    PyObject* arr = np_array(full_input, num_samples * per_sample,
                             dims.data(), nd, in_fmt);
    if (!arr) { Py_DECREF(d); return nullptr; }
    PyDict_SetItemString(d, "input", arr);
    Py_DECREF(arr);
  } else {
    /* fall back to a previously attached raw ptr (reference flow:
       attach_raw_ptr then SingleDataLoader) */
    PyObject* att = tensor_host_data(model, H(t.impl));
    if (att) {
      PyDict_SetItemString(d, "input", att);
      Py_DECREF(att);
    }
  }
  if (full_label) {
    int ldims[2] = {(int)num_samples, 1};
    PyObject* larr = np_array(full_label, num_samples, ldims, 2, 'i');
    if (!larr) { Py_DECREF(d); return nullptr; }
    PyDict_SetItemString(d, "label", larr);
    Py_DECREF(larr);
  }
  PyObject* n = PyLong_FromLongLong(num_samples);
  PyDict_SetItemString(d, "num", n);
  Py_DECREF(n);
  PyObject* z = PyLong_FromLong(0);
  PyDict_SetItemString(d, "next", z);
  Py_DECREF(z);
  PyObject* il = PyLong_FromLong(is_label);
  PyDict_SetItemString(d, "is_label", il);
  Py_DECREF(il);
  return d;
}

static int loader_next_batch(void* impl) {
  PyObject* d = H(impl);
  if (!d) return -1;
  PyObject* model = PyDict_GetItemString(d, "model");
  PyObject* tensor = PyDict_GetItemString(d, "tensor");
  PyObject* cfg = PyObject_GetAttrString(model, "config");
  PyObject* bs = cfg ? PyObject_GetAttrString(cfg, "batch_size") : nullptr;
  Py_XDECREF(cfg);
  if (!bs) { PyErr_Print(); return -1; }
  long batch = PyLong_AsLong(bs);
  Py_DECREF(bs);
  long num = PyLong_AsLong(PyDict_GetItemString(d, "num"));
  long next = PyLong_AsLong(PyDict_GetItemString(d, "next"));
  if (next + batch > num) next = 0;  /* wrap like DataLoader.reset */
  PyObject* lo = PyLong_FromLong(next);
  PyObject* hi = PyLong_FromLong(next + batch);
  PyObject* slice = PySlice_New(lo, hi, nullptr);
  Py_DECREF(lo);
  Py_DECREF(hi);
  int is_label = (int)PyLong_AsLong(PyDict_GetItemString(d, "is_label"));
  int ok = 0;
  for (const char* key : {"input", "label"}) {
    PyObject* arr = PyDict_GetItemString(d, key);
    if (!arr) continue;
    PyObject* part = PyObject_GetItem(arr, slice);
    if (!part) { PyErr_Print(); ok = -1; continue; }
    if (strcmp(key, "label") == 0 || is_label) {
      PyObject_SetAttrString(model, "_c_api_label", part);
      Py_DECREF(part);
    } else {
      flexflow_model_t mh{model};
      Py_INCREF(tensor);
      stage_input(mh, tensor, part);  /* steals part */
      Py_DECREF(tensor);
    }
  }
  Py_DECREF(slice);
  PyObject* nn = PyLong_FromLong(next + batch);
  PyDict_SetItemString(d, "next", nn);
  Py_DECREF(nn);
  return ok;
}

static void loader_reset(void* impl) {
  if (!impl) return;
  PyObject* z = PyLong_FromLong(0);
  PyDict_SetItemString(H(impl), "next", z);
  Py_DECREF(z);
}

static int64_t loader_num(void* impl) {
  return impl ? PyLong_AsLongLong(PyDict_GetItemString(H(impl), "num")) : -1;
}

static void loader_set_num(void* impl, int64_t n) {
  if (!impl) return;
  PyObject* v = PyLong_FromLongLong(n);
  PyDict_SetItemString(H(impl), "num", v);
  Py_DECREF(v);
}

flexflow_dataloader_4d_t flexflow_dataloader_4d_create(
    flexflow_model_t m, flexflow_tensor_t input, const float* full_input,
    const int32_t* full_label, int64_t num_samples) {
  flexflow_dataloader_4d_t out{
      loader_create(m, input, full_input, 'f', full_label, num_samples, 0)};
  return out;
}

flexflow_dataloader_4d_t flexflow_dataloader_4d_create_v2(
    flexflow_model_t m, flexflow_tensor_t input, const float* full_input,
    int64_t num_samples) {
  flexflow_dataloader_4d_t out{
      loader_create(m, input, full_input, 'f', nullptr, num_samples, 0)};
  return out;
}

void flexflow_dataloader_4d_destroy(flexflow_dataloader_4d_t d) {
  Py_XDECREF(H(d.impl));
}
void flexflow_dataloader_4d_reset(flexflow_dataloader_4d_t d) {
  loader_reset(d.impl);
}
int flexflow_dataloader_4d_next_batch(flexflow_dataloader_4d_t d,
                                      flexflow_model_t m) {
  (void)m;
  return loader_next_batch(d.impl);
}
int64_t flexflow_dataloader_4d_get_num_samples(flexflow_dataloader_4d_t d) {
  return loader_num(d.impl);
}
void flexflow_dataloader_4d_set_num_samples(flexflow_dataloader_4d_t d,
                                            int64_t n) {
  loader_set_num(d.impl, n);
}

flexflow_dataloader_2d_t flexflow_dataloader_2d_create(
    flexflow_model_t m, flexflow_tensor_t input, const float* full_input,
    const int32_t* full_label, int64_t num_samples) {
  flexflow_dataloader_2d_t out{
      loader_create(m, input, full_input, 'f', full_label, num_samples, 0)};
  return out;
}

flexflow_dataloader_2d_t flexflow_dataloader_2d_create_v2(
    flexflow_model_t m, flexflow_tensor_t input, const float* full_input,
    int64_t num_samples) {
  flexflow_dataloader_2d_t out{
      loader_create(m, input, full_input, 'f', nullptr, num_samples, 0)};
  return out;
}

void flexflow_dataloader_2d_destroy(flexflow_dataloader_2d_t d) {
  Py_XDECREF(H(d.impl));
}
void flexflow_dataloader_2d_reset(flexflow_dataloader_2d_t d) {
  loader_reset(d.impl);
}
int flexflow_dataloader_2d_next_batch(flexflow_dataloader_2d_t d,
                                      flexflow_model_t m) {
  (void)m;
  return loader_next_batch(d.impl);
}
int64_t flexflow_dataloader_2d_get_num_samples(flexflow_dataloader_2d_t d) {
  return loader_num(d.impl);
}
void flexflow_dataloader_2d_set_num_samples(flexflow_dataloader_2d_t d,
                                            int64_t n) {
  loader_set_num(d.impl, n);
}

flexflow_single_dataloader_t flexflow_single_dataloader_create(
    flexflow_model_t m, flexflow_tensor_t t, const void* full_data,
    int64_t num_samples, int is_float, int is_label) {
  flexflow_single_dataloader_t out{
      loader_create(m, t, full_data, is_float ? 'f' : 'i', nullptr,
                    num_samples, is_label)};
  return out;
}

void flexflow_single_dataloader_destroy(flexflow_single_dataloader_t d) {
  Py_XDECREF(H(d.impl));
}
void flexflow_single_dataloader_reset(flexflow_single_dataloader_t d) {
  loader_reset(d.impl);
}
int flexflow_single_dataloader_next_batch(flexflow_single_dataloader_t d,
                                          flexflow_model_t m) {
  (void)m;
  return loader_next_batch(d.impl);
}
int64_t flexflow_single_dataloader_get_num_samples(
    flexflow_single_dataloader_t d) {
  return loader_num(d.impl);
}
void flexflow_single_dataloader_set_num_samples(flexflow_single_dataloader_t d,
                                                int64_t n) {
  loader_set_num(d.impl, n);
}

}  // extern "C"
