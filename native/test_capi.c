/* C API smoke test — analogue of the reference's C-API smoke tests
 * (reference: tests/alexnet_c/alexnet.cc:16-30).  Builds an MLP via the C
 * surface, trains a few steps on a learnable synthetic task, asserts the
 * accuracy climbs above chance. */

#include "flexflow_c.h"

#include <assert.h>
#include <stdio.h>
#include <stdlib.h>

int main(void) {
  assert(flexflow_init() == 0);
  flexflow_config_t cfg = flexflow_config_create(/*batch*/ 32, /*epochs*/ 1,
                                                 /*devices*/ 0);
  flexflow_model_t model = flexflow_model_create(cfg);

  int in_dims[2] = {32, 8};
  flexflow_tensor_t input = flexflow_tensor_create(model, 2, in_dims, "float32");
  flexflow_tensor_t t = flexflow_model_add_dense(model, input, 32, /*relu*/ 1,
                                                 1, "fc1");
  t = flexflow_model_add_dense(model, t, 4, /*none*/ 0, 1, "fc2");
  t = flexflow_model_add_softmax(model, t, "softmax");

  const char* metrics[] = {"accuracy", "sparse_categorical_crossentropy"};
  assert(flexflow_model_compile(model, "sgd", 0.5,
                                "sparse_categorical_crossentropy", metrics,
                                2) == 0);
  assert(flexflow_model_init_layers(model) == 0);

  /* learnable task: label = argmax(x[:4]) */
  float x[32 * 8];
  int32_t y[32];
  srand(7);
  for (int i = 0; i < 32; i++) {
    int best = 0;
    for (int j = 0; j < 8; j++) {
      x[i * 8 + j] = (float)rand() / RAND_MAX - 0.5f;
      if (j < 4 && x[i * 8 + j] > x[i * 8 + best]) best = j;
    }
    y[i] = best;
  }

  for (int step = 0; step < 40; step++) {
    if (step == 30) flexflow_model_reset_metrics(model);
    assert(flexflow_model_set_input_f32(model, input, x, 32 * 8) == 0);
    assert(flexflow_model_set_label_i32(model, y, 32) == 0);
    assert(flexflow_model_forward(model) == 0);
    assert(flexflow_model_zero_gradients(model) == 0);
    assert(flexflow_model_backward(model) == 0);
    assert(flexflow_model_update(model) == 0);
  }
  flexflow_model_sync(model);
  int64_t all = 0, correct = 0;
  double acc = flexflow_model_get_accuracy(model, &all, &correct);
  printf("C API accuracy: %.2f%% (%lld/%lld)\n", acc, (long long)correct,
         (long long)all);
  assert(acc > 60.0);
  assert(flexflow_model_get_metric(model, "sparse_cce_loss") >= 0.0);

  /* fused train step via the staged batch */
  assert(flexflow_model_set_input_f32(model, input, x, 32 * 8) == 0);
  assert(flexflow_model_set_label_i32(model, y, 32) == 0);
  assert(flexflow_model_train_iteration(model) == 0);
  assert(flexflow_model_sync(model) == 0);

  /* parameter get/set round-trip (reference: Parameter::get/set_weights) */
  int64_t vol = flexflow_parameter_get_volume(model, "fc1", "kernel");
  assert(vol == 8 * 32);
  float* w = (float*)malloc(sizeof(float) * vol);
  assert(flexflow_model_get_parameter_f32(model, "fc1", "kernel", w, vol) == 0);
  w[0] += 1.0f;
  assert(flexflow_model_set_parameter_f32(model, "fc1", "kernel", w, vol) == 0);
  float* w2 = (float*)malloc(sizeof(float) * vol);
  assert(flexflow_model_get_parameter_f32(model, "fc1", "kernel", w2, vol) == 0);
  assert(w2[0] > w[0] - 1e-3f && w2[0] < w[0] + 1e-3f);
  free(w);
  free(w2);

  /* strategy export */
  assert(flexflow_model_export_strategy(model, "/tmp/capi_strategy.pb") == 0);

  /* checkpoint save/load round-trip */
  assert(flexflow_model_save(model, "/tmp/capi_ckpt.npz") == 0);
  assert(flexflow_model_load(model, "/tmp/capi_ckpt.npz") == 0);

  flexflow_model_destroy(model);
  flexflow_config_destroy(cfg);

  /* elementwise builders compile into a second graph */
  flexflow_config_t cfg2 = flexflow_config_create(8, 1, 0);
  flexflow_model_t m2 = flexflow_model_create(cfg2);
  int d2[2] = {8, 16};
  flexflow_tensor_t a = flexflow_tensor_create(m2, 2, d2, "float32");
  flexflow_tensor_t b = flexflow_tensor_create(m2, 2, d2, "float32");
  flexflow_tensor_t s = flexflow_model_add_subtract(m2, a, b, NULL);
  s = flexflow_model_add_multiply(m2, s, b, NULL);
  s = flexflow_model_add_relu(m2, s, NULL);
  s = flexflow_model_add_tanh(m2, s, NULL);
  s = flexflow_model_add_dense(m2, s, 4, 0, 1, "head");
  s = flexflow_model_add_softmax(m2, s, NULL);
  assert(s.impl != NULL);
  const char* mets2[] = {"accuracy"};
  assert(flexflow_model_compile(m2, "adam", 0.001,
                                "sparse_categorical_crossentropy", mets2,
                                1) == 0);
  assert(flexflow_model_init_layers(m2) == 0);
  flexflow_model_destroy(m2);
  flexflow_config_destroy(cfg2);

  /* ---- extended surface (reference parity) ------------------------- */

  /* config accessors + optimizer/initializer objects + builders _v2 +
     deferred (functional) builders + dataloaders + attach/inline-map */
  flexflow_config_t cfg3 = flexflow_config_create(16, 2, 0);
  assert(flexflow_config_parse_args_default(cfg3) == 0);
  assert(flexflow_config_get_batch_size(cfg3) == 16);
  assert(flexflow_config_get_epochs(cfg3) == 2);
  assert(flexflow_config_get_num_nodes(cfg3) >= 1);
  assert(flexflow_config_get_workers_per_node(cfg3) >= 1);

  flexflow_model_t m3 = flexflow_model_create(cfg3);
  int d3[2] = {16, 8};
  flexflow_tensor_t in3 = flexflow_tensor_create(m3, 2, d3, "float32");
  assert(flexflow_tensor_get_num_dims(in3) == 2);
  assert(flexflow_tensor_get_data_type(in3) == 0);

  flexflow_glorot_uniform_initializer_t gi =
      flexflow_glorot_uniform_initializer_create(3);
  flexflow_zero_initializer_t zi = flexflow_zero_initializer_create();
  flexflow_initializer_t ki = {gi.impl};
  flexflow_initializer_t bi = {zi.impl};
  flexflow_tensor_t t3 =
      flexflow_model_add_dense_v2(m3, in3, 32, 1, 1, ki, bi, "v2fc1");
  assert(t3.impl != NULL);

  /* deferred-shape (functional) builder: dense bound to its input later */
  flexflow_op_t dop = flexflow_model_add_dense_no_inout(m3, 4, 0, 1, "v2fc2");
  flexflow_tensor_t t4 = flexflow_op_init_inout(dop, m3, t3);
  assert(t4.impl != NULL);
  assert(flexflow_op_add_to_model(dop, m3) == 0);
  flexflow_tensor_t t4b = flexflow_op_get_output_by_id(dop, 0);
  assert(t4b.impl != NULL);
  flexflow_tensor_t sm3 = flexflow_model_add_softmax(m3, t4, "v2sm");
  assert(sm3.impl != NULL);

  /* optimizer object bound ahead of compile (optimizer="") */
  flexflow_sgd_optimizer_t sgd =
      flexflow_sgd_optimizer_create(m3, 0.1, 0.0, 0, 0.0);
  flexflow_sgd_optimizer_set_lr(sgd, 0.5);
  assert(flexflow_model_set_sgd_optimizer(m3, sgd) == 0);
  const char* mets3[] = {"accuracy"};
  assert(flexflow_model_compile(m3, "", 0.0,
                                "sparse_categorical_crossentropy", mets3,
                                1) == 0);
  assert(flexflow_model_init_layers(m3) == 0);
  assert(flexflow_model_get_num_layers(m3) == 3);

  /* op + parameter handles */
  flexflow_op_t l0 = flexflow_model_get_layer_by_id(m3, 0);
  flexflow_tensor_t l0in = flexflow_op_get_input_by_id(l0, 0);
  flexflow_tensor_t l0out = flexflow_op_get_output_by_id(l0, 0);
  assert(l0in.impl && l0out.impl);
  flexflow_op_t owner = flexflow_tensor_get_owner_op(l0out);
  assert(owner.impl != NULL);
  flexflow_parameter_t k0 = flexflow_op_get_parameter_by_id(l0, 0);
  assert(k0.impl != NULL);
  assert(flexflow_parameter_get_volume_v2(k0) == 8 * 32);
  float* wv = (float*)malloc(sizeof(float) * 8 * 32);
  assert(flexflow_parameter_get_weights_float(k0, wv, 8 * 32) == 0);
  assert(flexflow_parameter_set_weights_float(k0, wv, 8 * 32) == 0);
  free(wv);
  flexflow_parameter_t p0 = flexflow_model_get_parameter_by_id(m3, 0);
  assert(p0.impl != NULL);

  /* label tensor exists post-compile */
  flexflow_tensor_t lbl = flexflow_model_get_label_tensor(m3);
  assert(lbl.impl != NULL);
  flexflow_model_print_layers(m3, -1);
  assert(flexflow_model_prefetch(m3) == 0);

  /* dataloaders: full dataset host-resident, per-step slice staging */
  enum { NS = 64 };
  static float xs3[NS * 8];
  static int32_t ys3[NS];
  for (int i = 0; i < NS; i++) {
    int best = 0;
    for (int j = 0; j < 8; j++) {
      xs3[i * 8 + j] = (float)rand() / RAND_MAX - 0.5f;
      if (j < 4 && xs3[i * 8 + j] > xs3[i * 8 + best]) best = j;
    }
    ys3[i] = best;
  }
  flexflow_dataloader_2d_t dl =
      flexflow_dataloader_2d_create(m3, in3, xs3, ys3, NS);
  assert(dl.impl != NULL);
  assert(flexflow_dataloader_2d_get_num_samples(dl) == NS);
  flexflow_dataloader_2d_set_num_samples(dl, NS);
  flexflow_dataloader_2d_reset(dl);
  double t_start = flexflow_get_current_time(m3);
  for (int e = 0; e < 2; e++) {
    flexflow_begin_trace(m3, 111);
    for (int it = 0; it < NS / 16; it++) {
      assert(flexflow_dataloader_2d_next_batch(dl, m3) == 0);
      assert(flexflow_model_train_iteration(m3) == 0);
    }
    flexflow_end_trace(m3, 111);
  }
  assert(flexflow_model_sync(m3) == 0);
  assert(flexflow_get_current_time(m3) > t_start);
  assert(flexflow_model_compute_metrics(m3) == 0);
  flexflow_perf_metrics_t pm = flexflow_model_get_perf_metrics(m3);
  assert(pm.impl != NULL);
  float acc3 = flexflow_per_metrics_get_accuracy(pm);
  printf("C API extended: dataloader-trained accuracy %.2f%%\n", acc3);
  assert(acc3 > 30.0f);
  flexflow_per_metrics_destroy(pm);
  flexflow_dataloader_2d_destroy(dl);

  /* attach_raw_ptr (zero-copy numpy view) + single dataloader + inline map */
  assert(flexflow_tensor_attach_raw_ptr(m3, in3, xs3, NS * 8, 1) == 0);
  flexflow_single_dataloader_t sdl =
      flexflow_single_dataloader_create(m3, in3, NULL, NS, 1, 0);
  assert(sdl.impl != NULL);
  assert(flexflow_single_dataloader_get_num_samples(sdl) == NS);
  assert(flexflow_single_dataloader_next_batch(sdl, m3) == 0);
  assert(flexflow_tensor_inline_map(m3, in3) == 0);
  assert(flexflow_tensor_is_mapped(m3, in3) == 1);
  float* raw = flexflow_tensor_get_raw_ptr_float(m3, in3);
  assert(raw != NULL);
  assert(raw[0] == xs3[0]);  /* attached view aliases the caller's memory */
  flexflow_tensor_inline_unmap(m3, in3);
  assert(flexflow_tensor_is_mapped(m3, in3) == 0);
  assert(flexflow_tensor_detach_raw_ptr(m3, in3) == 0);
  flexflow_single_dataloader_destroy(sdl);

  /* MoE layer through the C surface */
  {
    flexflow_config_t mc = flexflow_config_create(8, 1, 0);
    flexflow_model_t mm = flexflow_model_create(mc);
    int md[2] = {8, 16};
    flexflow_tensor_t mi = flexflow_tensor_create(mm, 2, md, "float32");
    flexflow_tensor_t mo =
        flexflow_model_add_expert_mlp(mm, mi, 4, 32, 1.25, "moe");
    assert(mo.impl != NULL);
    flexflow_tensor_t mh = flexflow_model_add_dense(mm, mo, 4, 0, 1, "h");
    mh = flexflow_model_add_softmax(mm, mh, NULL);
    const char* mmet[] = {"accuracy"};
    assert(flexflow_model_compile(mm, "sgd", 0.1,
                                  "sparse_categorical_crossentropy", mmet,
                                  1) == 0);
    assert(flexflow_model_init_layers(mm) == 0);
    flexflow_model_destroy(mm);
    flexflow_config_destroy(mc);
  }

  /* adam object + net config */
  flexflow_adam_optimizer_t adam =
      flexflow_adam_optimizer_create(m3, 0.001, 0.9, 0.999, 0.0, 1e-8);
  flexflow_adam_optimizer_set_lr(adam, 0.002);
  assert(flexflow_model_set_adam_optimizer(m3, adam) == 0);
  flexflow_adam_optimizer_destroy(adam);
  flexflow_net_config_t nc = flexflow_net_config_create();
  assert(flexflow_net_config_get_dataset_path(nc) != NULL);
  flexflow_net_config_destroy(nc);

  flexflow_sgd_optimizer_destroy(sgd);
  flexflow_glorot_uniform_initializer_destroy(gi);
  flexflow_zero_initializer_destroy(zi);
  flexflow_op_destroy(l0);
  flexflow_op_destroy(owner);
  flexflow_parameter_destroy(k0);
  flexflow_parameter_destroy(p0);
  flexflow_model_destroy(m3);
  flexflow_config_destroy(cfg3);

  printf("C API smoke test: OK\n");
  return 0;
}
