/* C API smoke test — analogue of the reference's C-API smoke tests
 * (reference: tests/alexnet_c/alexnet.cc:16-30).  Builds an MLP via the C
 * surface, trains a few steps on a learnable synthetic task, asserts the
 * accuracy climbs above chance. */

#include "flexflow_c.h"

#include <assert.h>
#include <stdio.h>
#include <stdlib.h>

int main(void) {
  assert(flexflow_init() == 0);
  flexflow_config_t cfg = flexflow_config_create(/*batch*/ 32, /*epochs*/ 1,
                                                 /*devices*/ 0);
  flexflow_model_t model = flexflow_model_create(cfg);

  int in_dims[2] = {32, 8};
  flexflow_tensor_t input = flexflow_tensor_create(model, 2, in_dims, "float32");
  flexflow_tensor_t t = flexflow_model_add_dense(model, input, 32, /*relu*/ 1,
                                                 1, "fc1");
  t = flexflow_model_add_dense(model, t, 4, /*none*/ 0, 1, "fc2");
  t = flexflow_model_add_softmax(model, t, "softmax");

  const char* metrics[] = {"accuracy", "sparse_categorical_crossentropy"};
  assert(flexflow_model_compile(model, "sgd", 0.5,
                                "sparse_categorical_crossentropy", metrics,
                                2) == 0);
  assert(flexflow_model_init_layers(model) == 0);

  /* learnable task: label = argmax(x[:4]) */
  float x[32 * 8];
  int32_t y[32];
  srand(7);
  for (int i = 0; i < 32; i++) {
    int best = 0;
    for (int j = 0; j < 8; j++) {
      x[i * 8 + j] = (float)rand() / RAND_MAX - 0.5f;
      if (j < 4 && x[i * 8 + j] > x[i * 8 + best]) best = j;
    }
    y[i] = best;
  }

  for (int step = 0; step < 40; step++) {
    if (step == 30) flexflow_model_reset_metrics(model);
    assert(flexflow_model_set_input_f32(model, input, x, 32 * 8) == 0);
    assert(flexflow_model_set_label_i32(model, y, 32) == 0);
    assert(flexflow_model_forward(model) == 0);
    assert(flexflow_model_zero_gradients(model) == 0);
    assert(flexflow_model_backward(model) == 0);
    assert(flexflow_model_update(model) == 0);
  }
  flexflow_model_sync(model);
  int64_t all = 0, correct = 0;
  double acc = flexflow_model_get_accuracy(model, &all, &correct);
  printf("C API accuracy: %.2f%% (%lld/%lld)\n", acc, (long long)correct,
         (long long)all);
  assert(acc > 60.0);

  flexflow_model_destroy(model);
  flexflow_config_destroy(cfg);
  printf("C API smoke test: OK\n");
  return 0;
}
