/* C API smoke test — analogue of the reference's C-API smoke tests
 * (reference: tests/alexnet_c/alexnet.cc:16-30).  Builds an MLP via the C
 * surface, trains a few steps on a learnable synthetic task, asserts the
 * accuracy climbs above chance. */

#include "flexflow_c.h"

#include <assert.h>
#include <stdio.h>
#include <stdlib.h>

int main(void) {
  assert(flexflow_init() == 0);
  flexflow_config_t cfg = flexflow_config_create(/*batch*/ 32, /*epochs*/ 1,
                                                 /*devices*/ 0);
  flexflow_model_t model = flexflow_model_create(cfg);

  int in_dims[2] = {32, 8};
  flexflow_tensor_t input = flexflow_tensor_create(model, 2, in_dims, "float32");
  flexflow_tensor_t t = flexflow_model_add_dense(model, input, 32, /*relu*/ 1,
                                                 1, "fc1");
  t = flexflow_model_add_dense(model, t, 4, /*none*/ 0, 1, "fc2");
  t = flexflow_model_add_softmax(model, t, "softmax");

  const char* metrics[] = {"accuracy", "sparse_categorical_crossentropy"};
  assert(flexflow_model_compile(model, "sgd", 0.5,
                                "sparse_categorical_crossentropy", metrics,
                                2) == 0);
  assert(flexflow_model_init_layers(model) == 0);

  /* learnable task: label = argmax(x[:4]) */
  float x[32 * 8];
  int32_t y[32];
  srand(7);
  for (int i = 0; i < 32; i++) {
    int best = 0;
    for (int j = 0; j < 8; j++) {
      x[i * 8 + j] = (float)rand() / RAND_MAX - 0.5f;
      if (j < 4 && x[i * 8 + j] > x[i * 8 + best]) best = j;
    }
    y[i] = best;
  }

  for (int step = 0; step < 40; step++) {
    if (step == 30) flexflow_model_reset_metrics(model);
    assert(flexflow_model_set_input_f32(model, input, x, 32 * 8) == 0);
    assert(flexflow_model_set_label_i32(model, y, 32) == 0);
    assert(flexflow_model_forward(model) == 0);
    assert(flexflow_model_zero_gradients(model) == 0);
    assert(flexflow_model_backward(model) == 0);
    assert(flexflow_model_update(model) == 0);
  }
  flexflow_model_sync(model);
  int64_t all = 0, correct = 0;
  double acc = flexflow_model_get_accuracy(model, &all, &correct);
  printf("C API accuracy: %.2f%% (%lld/%lld)\n", acc, (long long)correct,
         (long long)all);
  assert(acc > 60.0);
  assert(flexflow_model_get_metric(model, "sparse_cce_loss") >= 0.0);

  /* fused train step via the staged batch */
  assert(flexflow_model_set_input_f32(model, input, x, 32 * 8) == 0);
  assert(flexflow_model_set_label_i32(model, y, 32) == 0);
  assert(flexflow_model_train_iteration(model) == 0);
  assert(flexflow_model_sync(model) == 0);

  /* parameter get/set round-trip (reference: Parameter::get/set_weights) */
  int64_t vol = flexflow_parameter_get_volume(model, "fc1", "kernel");
  assert(vol == 8 * 32);
  float* w = (float*)malloc(sizeof(float) * vol);
  assert(flexflow_model_get_parameter_f32(model, "fc1", "kernel", w, vol) == 0);
  w[0] += 1.0f;
  assert(flexflow_model_set_parameter_f32(model, "fc1", "kernel", w, vol) == 0);
  float* w2 = (float*)malloc(sizeof(float) * vol);
  assert(flexflow_model_get_parameter_f32(model, "fc1", "kernel", w2, vol) == 0);
  assert(w2[0] > w[0] - 1e-3f && w2[0] < w[0] + 1e-3f);
  free(w);
  free(w2);

  /* strategy export */
  assert(flexflow_model_export_strategy(model, "/tmp/capi_strategy.pb") == 0);

  /* checkpoint save/load round-trip */
  assert(flexflow_model_save(model, "/tmp/capi_ckpt.npz") == 0);
  assert(flexflow_model_load(model, "/tmp/capi_ckpt.npz") == 0);

  flexflow_model_destroy(model);
  flexflow_config_destroy(cfg);

  /* elementwise builders compile into a second graph */
  flexflow_config_t cfg2 = flexflow_config_create(8, 1, 0);
  flexflow_model_t m2 = flexflow_model_create(cfg2);
  int d2[2] = {8, 16};
  flexflow_tensor_t a = flexflow_tensor_create(m2, 2, d2, "float32");
  flexflow_tensor_t b = flexflow_tensor_create(m2, 2, d2, "float32");
  flexflow_tensor_t s = flexflow_model_add_subtract(m2, a, b, NULL);
  s = flexflow_model_add_multiply(m2, s, b, NULL);
  s = flexflow_model_add_relu(m2, s, NULL);
  s = flexflow_model_add_tanh(m2, s, NULL);
  s = flexflow_model_add_dense(m2, s, 4, 0, 1, "head");
  s = flexflow_model_add_softmax(m2, s, NULL);
  assert(s.impl != NULL);
  const char* mets2[] = {"accuracy"};
  assert(flexflow_model_compile(m2, "adam", 0.001,
                                "sparse_categorical_crossentropy", mets2,
                                1) == 0);
  assert(flexflow_model_init_layers(m2) == 0);
  flexflow_model_destroy(m2);
  flexflow_config_destroy(cfg2);

  printf("C API smoke test: OK\n");
  return 0;
}
