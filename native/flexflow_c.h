/* C API for the flexflow_tpu framework.
 *
 * Counterpart of the reference C API (reference: python/flexflow_c.h —
 * ~190 extern "C" wrappers with opaque handle structs over FFModel).  The
 * reference wraps a C++ core for Python/cffi; this framework's core is the
 * Python/JAX SPMD layer, so the C API embeds CPython and drives the same
 * objects — C callers get the reference-style surface (opaque handles,
 * flexflow_model_add_* builders, compile/train-step calls) with the TPU
 * execution engine underneath.
 *
 * Link: -lflexflow_c (built by native/Makefile) plus the Python runtime.
 * The process must be able to `import flexflow_tpu` (set PYTHONPATH).
 */

#ifndef FLEXFLOW_TPU_C_H
#define FLEXFLOW_TPU_C_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct flexflow_config_t { void* impl; } flexflow_config_t;
typedef struct flexflow_model_t { void* impl; } flexflow_model_t;
typedef struct flexflow_tensor_t { void* impl; } flexflow_tensor_t;

/* runtime */
int flexflow_init(void);          /* idempotent; returns 0 on success */
void flexflow_finalize(void);

/* config (reference: flexflow_config_create / parse_args) */
flexflow_config_t flexflow_config_create(int batch_size, int epochs,
                                         int num_devices);
void flexflow_config_destroy(flexflow_config_t c);

/* model + tensors */
flexflow_model_t flexflow_model_create(flexflow_config_t c);
void flexflow_model_destroy(flexflow_model_t m);
/* dims reference-ordered (N,C,H,W for 4-D); dtype "float32"|"int32"|"int64" */
flexflow_tensor_t flexflow_tensor_create(flexflow_model_t m, int ndims,
                                         const int* dims, const char* dtype);
void flexflow_tensor_destroy(flexflow_tensor_t t);

/* layer builders (reference: flexflow_model_add_*; activation:
 * 0=none 1=relu 2=sigmoid 3=tanh) */
flexflow_tensor_t flexflow_model_add_conv2d(
    flexflow_model_t m, flexflow_tensor_t input, int out_channels,
    int kernel_h, int kernel_w, int stride_h, int stride_w,
    int padding_h, int padding_w, int activation, int use_bias,
    const char* name);
flexflow_tensor_t flexflow_model_add_pool2d(
    flexflow_model_t m, flexflow_tensor_t input, int kernel_h, int kernel_w,
    int stride_h, int stride_w, int padding_h, int padding_w,
    int pool_max /*1=max 0=avg*/, const char* name);
flexflow_tensor_t flexflow_model_add_dense(
    flexflow_model_t m, flexflow_tensor_t input, int out_dim, int activation,
    int use_bias, const char* name);
flexflow_tensor_t flexflow_model_add_flat(flexflow_model_t m,
                                          flexflow_tensor_t input,
                                          const char* name);
flexflow_tensor_t flexflow_model_add_softmax(flexflow_model_t m,
                                             flexflow_tensor_t input,
                                             const char* name);
flexflow_tensor_t flexflow_model_add_embedding(
    flexflow_model_t m, flexflow_tensor_t input, int num_entries, int out_dim,
    int aggr_sum /*1=sum 0=avg*/, const char* name);
flexflow_tensor_t flexflow_model_add_concat(
    flexflow_model_t m, int n, const flexflow_tensor_t* inputs, int axis,
    const char* name);
flexflow_tensor_t flexflow_model_add_add(flexflow_model_t m,
                                         flexflow_tensor_t a,
                                         flexflow_tensor_t b,
                                         const char* name);
flexflow_tensor_t flexflow_model_add_subtract(flexflow_model_t m,
                                              flexflow_tensor_t a,
                                              flexflow_tensor_t b,
                                              const char* name);
flexflow_tensor_t flexflow_model_add_multiply(flexflow_model_t m,
                                              flexflow_tensor_t a,
                                              flexflow_tensor_t b,
                                              const char* name);
flexflow_tensor_t flexflow_model_add_divide(flexflow_model_t m,
                                            flexflow_tensor_t a,
                                            flexflow_tensor_t b,
                                            const char* name);
flexflow_tensor_t flexflow_model_add_relu(flexflow_model_t m,
                                          flexflow_tensor_t input,
                                          const char* name);
flexflow_tensor_t flexflow_model_add_sigmoid(flexflow_model_t m,
                                             flexflow_tensor_t input,
                                             const char* name);
flexflow_tensor_t flexflow_model_add_tanh(flexflow_model_t m,
                                          flexflow_tensor_t input,
                                          const char* name);
flexflow_tensor_t flexflow_model_add_elu(flexflow_model_t m,
                                         flexflow_tensor_t input,
                                         const char* name);
flexflow_tensor_t flexflow_model_add_exp(flexflow_model_t m,
                                         flexflow_tensor_t input,
                                         const char* name);
flexflow_tensor_t flexflow_model_add_batch_norm(flexflow_model_t m,
                                                flexflow_tensor_t input,
                                                int relu, const char* name);
flexflow_tensor_t flexflow_model_add_dropout(flexflow_model_t m,
                                             flexflow_tensor_t input,
                                             double rate, int seed,
                                             const char* name);
flexflow_tensor_t flexflow_model_add_mse_loss(flexflow_model_t m,
                                              flexflow_tensor_t logits,
                                              flexflow_tensor_t labels,
                                              const char* reduction,
                                              const char* name);

/* compile: optimizer "sgd"|"adam"; loss per reference names */
int flexflow_model_compile(flexflow_model_t m, const char* optimizer,
                           double lr, const char* loss,
                           const char** metrics, int num_metrics);
int flexflow_model_init_layers(flexflow_model_t m);

/* batch feeding (host data, reference-ordered layout) */
int flexflow_model_set_input_f32(flexflow_model_t m, flexflow_tensor_t t,
                                 const float* data, int64_t count);
int flexflow_model_set_input_i32(flexflow_model_t m, flexflow_tensor_t t,
                                 const int32_t* data, int64_t count);
int flexflow_model_set_label_i32(flexflow_model_t m, const int32_t* data,
                                 int64_t count);
int flexflow_model_set_label_f32(flexflow_model_t m, const float* data,
                                 int64_t count);

/* train drivers (reference: forward/zero_gradients/backward/update) */
int flexflow_model_forward(flexflow_model_t m);
int flexflow_model_zero_gradients(flexflow_model_t m);
int flexflow_model_backward(flexflow_model_t m);
int flexflow_model_update(flexflow_model_t m);
int flexflow_model_sync(flexflow_model_t m);
void flexflow_model_reset_metrics(flexflow_model_t m);

/* fused train step (staged batch must be set) */
int flexflow_model_train_iteration(flexflow_model_t m);

/* metrics: returns accuracy %; train_all/correct optional out-params */
double flexflow_model_get_accuracy(flexflow_model_t m, int64_t* train_all,
                                   int64_t* train_correct);
/* any PerfMetrics field by name ("accuracy", "cce_loss", "sparse_cce_loss",
 * "mse_loss", "rmse_loss", "mae_loss", "train_all", "train_correct") */
double flexflow_model_get_metric(flexflow_model_t m, const char* name);

/* weights (reference: Parameter::get_weights/set_weights) */
int64_t flexflow_parameter_get_volume(flexflow_model_t m, const char* op_name,
                                      const char* weight_name);
int flexflow_model_get_parameter_f32(flexflow_model_t m, const char* op_name,
                                     const char* weight_name, float* out,
                                     int64_t count);
int flexflow_model_set_parameter_f32(flexflow_model_t m, const char* op_name,
                                     const char* weight_name,
                                     const float* data, int64_t count);

/* strategy files (reference: --import-strategy / --export-strategy) */
int flexflow_config_import_strategy(flexflow_config_t c, const char* path);
int flexflow_model_export_strategy(flexflow_model_t m, const char* path);

/* checkpoint / resume */
int flexflow_model_save(flexflow_model_t m, const char* path);
int flexflow_model_load(flexflow_model_t m, const char* path);

/* tensor introspection */
int flexflow_tensor_get_dims(flexflow_tensor_t t, int* dims /*>=4 slots*/);

/* ----------------------------------------------------------------------
 * Extended surface: parity with the reference C API
 * (reference: python/flexflow_c.h:27-718 — config accessors, optimizer /
 * initializer / NetConfig objects, dataloader handles, tensor raw-ptr
 * attach + inline map, op handles and the deferred-shape builders).
 * -------------------------------------------------------------------- */

typedef struct flexflow_sgd_optimizer_t { void* impl; } flexflow_sgd_optimizer_t;
typedef struct flexflow_adam_optimizer_t { void* impl; } flexflow_adam_optimizer_t;
typedef struct flexflow_initializer_t { void* impl; } flexflow_initializer_t;
typedef struct flexflow_glorot_uniform_initializer_t { void* impl; } flexflow_glorot_uniform_initializer_t;
typedef struct flexflow_zero_initializer_t { void* impl; } flexflow_zero_initializer_t;
typedef struct flexflow_uniform_initializer_t { void* impl; } flexflow_uniform_initializer_t;
typedef struct flexflow_norm_initializer_t { void* impl; } flexflow_norm_initializer_t;
typedef struct flexflow_net_config_t { void* impl; } flexflow_net_config_t;
typedef struct flexflow_op_t { void* impl; } flexflow_op_t;
typedef struct flexflow_parameter_t { void* impl; } flexflow_parameter_t;
typedef struct flexflow_perf_metrics_t { void* impl; } flexflow_perf_metrics_t;
typedef struct flexflow_dataloader_4d_t { void* impl; } flexflow_dataloader_4d_t;
typedef struct flexflow_dataloader_2d_t { void* impl; } flexflow_dataloader_2d_t;
typedef struct flexflow_single_dataloader_t { void* impl; } flexflow_single_dataloader_t;

/* config accessors (reference: flexflow_config_get_*) */
int flexflow_config_parse_args(flexflow_config_t c, int argc, char** argv);
int flexflow_config_parse_args_default(flexflow_config_t c);
int flexflow_config_get_batch_size(flexflow_config_t c);
int flexflow_config_get_epochs(flexflow_config_t c);
int flexflow_config_get_num_nodes(flexflow_config_t c);
int flexflow_config_get_workers_per_node(flexflow_config_t c);

/* optimizer objects (reference: optimizer.cc semantics) */
flexflow_sgd_optimizer_t flexflow_sgd_optimizer_create(
    flexflow_model_t m, double lr, double momentum, int nesterov,
    double weight_decay);
void flexflow_sgd_optimizer_destroy(flexflow_sgd_optimizer_t o);
void flexflow_sgd_optimizer_set_lr(flexflow_sgd_optimizer_t o, double lr);
flexflow_adam_optimizer_t flexflow_adam_optimizer_create(
    flexflow_model_t m, double alpha, double beta1, double beta2,
    double weight_decay, double epsilon);
void flexflow_adam_optimizer_destroy(flexflow_adam_optimizer_t o);
void flexflow_adam_optimizer_set_lr(flexflow_adam_optimizer_t o, double lr);
/* bind for the next compile; pass optimizer="" to flexflow_model_compile */
int flexflow_model_set_sgd_optimizer(flexflow_model_t m,
                                     flexflow_sgd_optimizer_t o);
int flexflow_model_set_adam_optimizer(flexflow_model_t m,
                                      flexflow_adam_optimizer_t o);

/* initializer objects (reference: initializer.h:26-100) */
flexflow_initializer_t flexflow_initializer_create_null(void);
flexflow_glorot_uniform_initializer_t
flexflow_glorot_uniform_initializer_create(int seed);
void flexflow_glorot_uniform_initializer_destroy(
    flexflow_glorot_uniform_initializer_t i);
flexflow_zero_initializer_t flexflow_zero_initializer_create(void);
void flexflow_zero_initializer_destroy(flexflow_zero_initializer_t i);
flexflow_uniform_initializer_t flexflow_uniform_initializer_create(
    int seed, float min_val, float max_val);
void flexflow_uniform_initializer_destroy(flexflow_uniform_initializer_t i);
flexflow_norm_initializer_t flexflow_norm_initializer_create(
    int seed, float mean, float stddev);
void flexflow_norm_initializer_destroy(flexflow_norm_initializer_t i);

/* builder variants taking initializer handles (pass {NULL} for default) */
flexflow_tensor_t flexflow_model_add_dense_v2(
    flexflow_model_t m, flexflow_tensor_t input, int out_dim, int activation,
    int use_bias, flexflow_initializer_t kernel_init,
    flexflow_initializer_t bias_init, const char* name);
flexflow_tensor_t flexflow_model_add_conv2d_v2(
    flexflow_model_t m, flexflow_tensor_t input, int out_channels,
    int kernel_h, int kernel_w, int stride_h, int stride_w, int padding_h,
    int padding_w, int activation, int use_bias,
    flexflow_initializer_t kernel_init, flexflow_initializer_t bias_init,
    const char* name);

/* Switch-style MoE layer; expert weights shard over config dim 1 */
flexflow_tensor_t flexflow_model_add_expert_mlp(
    flexflow_model_t m, flexflow_tensor_t input, int num_experts,
    int hidden_size, double capacity_factor, const char* name);

/* NetConfig (reference: --dataset flag carrier) */
flexflow_net_config_t flexflow_net_config_create(void);
void flexflow_net_config_destroy(flexflow_net_config_t c);
const char* flexflow_net_config_get_dataset_path(flexflow_net_config_t c);

/* deferred-shape (functional) builders: create the op descriptor now,
 * bind the input later (reference: *_no_inout + op_init_inout) */
flexflow_op_t flexflow_model_add_conv2d_no_inout(
    flexflow_model_t m, int out_channels, int kernel_h, int kernel_w,
    int stride_h, int stride_w, int padding_h, int padding_w, int activation,
    int use_bias, const char* name);
flexflow_op_t flexflow_model_add_dense_no_inout(
    flexflow_model_t m, int out_dim, int activation, int use_bias,
    const char* name);
flexflow_op_t flexflow_model_add_pool2d_no_inout(
    flexflow_model_t m, int kernel_h, int kernel_w, int stride_h,
    int stride_w, int padding_h, int padding_w, int pool_max,
    const char* name);
flexflow_op_t flexflow_model_add_flat_no_inout(flexflow_model_t m,
                                               const char* name);
flexflow_tensor_t flexflow_op_init_inout(flexflow_op_t op, flexflow_model_t m,
                                         flexflow_tensor_t input);
int flexflow_op_add_to_model(flexflow_op_t op, flexflow_model_t m);
int flexflow_op_init(flexflow_op_t op, flexflow_model_t m);
int flexflow_op_forward(flexflow_op_t op, flexflow_model_t m);

/* op / parameter handles (reference: model_get_layer_by_id etc.) */
int flexflow_model_get_num_layers(flexflow_model_t m);
flexflow_op_t flexflow_model_get_layer_by_id(flexflow_model_t m, int id);
void flexflow_op_destroy(flexflow_op_t op);
flexflow_tensor_t flexflow_op_get_input_by_id(flexflow_op_t op, int id);
flexflow_tensor_t flexflow_op_get_output_by_id(flexflow_op_t op, int id);
flexflow_parameter_t flexflow_op_get_parameter_by_id(flexflow_op_t op, int id);
flexflow_parameter_t flexflow_model_get_parameter_by_id(flexflow_model_t m,
                                                        int id);
void flexflow_parameter_destroy(flexflow_parameter_t p);
int64_t flexflow_parameter_get_volume_v2(flexflow_parameter_t p);
int flexflow_parameter_get_weights_float(flexflow_parameter_t p, float* out,
                                         int64_t count);
int flexflow_parameter_set_weights_float(flexflow_parameter_t p,
                                         const float* data, int64_t count);

/* label tensor + layer printing */
flexflow_tensor_t flexflow_model_get_label_tensor(flexflow_model_t m);
void flexflow_model_print_layers(flexflow_model_t m, int id /* -1 = all */);
int flexflow_model_prefetch(flexflow_model_t m);

/* perf metrics handle (reference: model_get_perf_metrics +
 * per_metrics_get_accuracy; the short "per_metrics" spelling matches the
 * reference header) */
flexflow_perf_metrics_t flexflow_model_get_perf_metrics(flexflow_model_t m);
void flexflow_per_metrics_destroy(flexflow_perf_metrics_t p);
float flexflow_per_metrics_get_accuracy(flexflow_perf_metrics_t p);
int flexflow_model_compute_metrics(flexflow_model_t m);

/* tracing + timing (reference: begin/end_trace replay Legion traces; the
 * fused jitted step is traced once by XLA, so these are semantic no-ops
 * kept for source compatibility) */
void flexflow_begin_trace(flexflow_model_t m, int trace_id);
void flexflow_end_trace(flexflow_model_t m, int trace_id);
double flexflow_get_current_time(flexflow_model_t m); /* microseconds */

/* raw-pointer attach (reference: Tensor::attach_raw_ptr model.cc:73-93 —
 * zero-copy host data; here the pointer is wrapped as a numpy view and
 * becomes the tensor's host-resident data) */
int flexflow_tensor_attach_raw_ptr(flexflow_model_t m, flexflow_tensor_t t,
                                   void* ptr, int64_t count,
                                   int is_float /*1=f32 0=i32*/);
int flexflow_tensor_detach_raw_ptr(flexflow_model_t m, flexflow_tensor_t t);
/* inline map: materialize the tensor's current host data (attached or
 * staged) and expose the raw pointer */
int flexflow_tensor_inline_map(flexflow_model_t m, flexflow_tensor_t t);
void flexflow_tensor_inline_unmap(flexflow_model_t m, flexflow_tensor_t t);
int flexflow_tensor_is_mapped(flexflow_model_t m, flexflow_tensor_t t);
float* flexflow_tensor_get_raw_ptr_float(flexflow_model_t m,
                                         flexflow_tensor_t t);
int32_t* flexflow_tensor_get_raw_ptr_int32(flexflow_model_t m,
                                           flexflow_tensor_t t);
int flexflow_tensor_get_num_dims(flexflow_tensor_t t);
int flexflow_tensor_get_data_type(flexflow_tensor_t t); /* 0=f32 1=i32 2=i64 */
flexflow_op_t flexflow_tensor_get_owner_op(flexflow_tensor_t t);

/* dataloader handles (reference: flexflow_dataloader_{4d,2d} +
 * single_dataloader — full dataset host-resident, per-step batch scatter).
 * create: input + label arrays together; create_v2: one tensor's data only. */
flexflow_dataloader_4d_t flexflow_dataloader_4d_create(
    flexflow_model_t m, flexflow_tensor_t input, const float* full_input,
    const int32_t* full_label, int64_t num_samples);
flexflow_dataloader_4d_t flexflow_dataloader_4d_create_v2(
    flexflow_model_t m, flexflow_tensor_t input, const float* full_input,
    int64_t num_samples);
void flexflow_dataloader_4d_destroy(flexflow_dataloader_4d_t d);
void flexflow_dataloader_4d_reset(flexflow_dataloader_4d_t d);
int flexflow_dataloader_4d_next_batch(flexflow_dataloader_4d_t d,
                                      flexflow_model_t m);
int64_t flexflow_dataloader_4d_get_num_samples(flexflow_dataloader_4d_t d);
void flexflow_dataloader_4d_set_num_samples(flexflow_dataloader_4d_t d,
                                            int64_t n);
flexflow_dataloader_2d_t flexflow_dataloader_2d_create(
    flexflow_model_t m, flexflow_tensor_t input, const float* full_input,
    const int32_t* full_label, int64_t num_samples);
flexflow_dataloader_2d_t flexflow_dataloader_2d_create_v2(
    flexflow_model_t m, flexflow_tensor_t input, const float* full_input,
    int64_t num_samples);
void flexflow_dataloader_2d_destroy(flexflow_dataloader_2d_t d);
void flexflow_dataloader_2d_reset(flexflow_dataloader_2d_t d);
int flexflow_dataloader_2d_next_batch(flexflow_dataloader_2d_t d,
                                      flexflow_model_t m);
int64_t flexflow_dataloader_2d_get_num_samples(flexflow_dataloader_2d_t d);
void flexflow_dataloader_2d_set_num_samples(flexflow_dataloader_2d_t d,
                                            int64_t n);
/* any-rank, any-dtype single-tensor loader (reference: SingleDataLoader) */
flexflow_single_dataloader_t flexflow_single_dataloader_create(
    flexflow_model_t m, flexflow_tensor_t t, const void* full_data,
    int64_t num_samples, int is_float /*1=f32 0=i32*/,
    int is_label /*feed as label instead of input*/);
void flexflow_single_dataloader_destroy(flexflow_single_dataloader_t d);
void flexflow_single_dataloader_reset(flexflow_single_dataloader_t d);
int flexflow_single_dataloader_next_batch(flexflow_single_dataloader_t d,
                                          flexflow_model_t m);
int64_t flexflow_single_dataloader_get_num_samples(
    flexflow_single_dataloader_t d);
void flexflow_single_dataloader_set_num_samples(flexflow_single_dataloader_t d,
                                                int64_t n);

#ifdef __cplusplus
}
#endif

#endif /* FLEXFLOW_TPU_C_H */
