/* C API for the flexflow_tpu framework.
 *
 * Counterpart of the reference C API (reference: python/flexflow_c.h —
 * ~190 extern "C" wrappers with opaque handle structs over FFModel).  The
 * reference wraps a C++ core for Python/cffi; this framework's core is the
 * Python/JAX SPMD layer, so the C API embeds CPython and drives the same
 * objects — C callers get the reference-style surface (opaque handles,
 * flexflow_model_add_* builders, compile/train-step calls) with the TPU
 * execution engine underneath.
 *
 * Link: -lflexflow_c (built by native/Makefile) plus the Python runtime.
 * The process must be able to `import flexflow_tpu` (set PYTHONPATH).
 */

#ifndef FLEXFLOW_TPU_C_H
#define FLEXFLOW_TPU_C_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct flexflow_config_t { void* impl; } flexflow_config_t;
typedef struct flexflow_model_t { void* impl; } flexflow_model_t;
typedef struct flexflow_tensor_t { void* impl; } flexflow_tensor_t;

/* runtime */
int flexflow_init(void);          /* idempotent; returns 0 on success */
void flexflow_finalize(void);

/* config (reference: flexflow_config_create / parse_args) */
flexflow_config_t flexflow_config_create(int batch_size, int epochs,
                                         int num_devices);
void flexflow_config_destroy(flexflow_config_t c);

/* model + tensors */
flexflow_model_t flexflow_model_create(flexflow_config_t c);
void flexflow_model_destroy(flexflow_model_t m);
/* dims reference-ordered (N,C,H,W for 4-D); dtype "float32"|"int32"|"int64" */
flexflow_tensor_t flexflow_tensor_create(flexflow_model_t m, int ndims,
                                         const int* dims, const char* dtype);
void flexflow_tensor_destroy(flexflow_tensor_t t);

/* layer builders (reference: flexflow_model_add_*; activation:
 * 0=none 1=relu 2=sigmoid 3=tanh) */
flexflow_tensor_t flexflow_model_add_conv2d(
    flexflow_model_t m, flexflow_tensor_t input, int out_channels,
    int kernel_h, int kernel_w, int stride_h, int stride_w,
    int padding_h, int padding_w, int activation, int use_bias,
    const char* name);
flexflow_tensor_t flexflow_model_add_pool2d(
    flexflow_model_t m, flexflow_tensor_t input, int kernel_h, int kernel_w,
    int stride_h, int stride_w, int padding_h, int padding_w,
    int pool_max /*1=max 0=avg*/, const char* name);
flexflow_tensor_t flexflow_model_add_dense(
    flexflow_model_t m, flexflow_tensor_t input, int out_dim, int activation,
    int use_bias, const char* name);
flexflow_tensor_t flexflow_model_add_flat(flexflow_model_t m,
                                          flexflow_tensor_t input,
                                          const char* name);
flexflow_tensor_t flexflow_model_add_softmax(flexflow_model_t m,
                                             flexflow_tensor_t input,
                                             const char* name);
flexflow_tensor_t flexflow_model_add_embedding(
    flexflow_model_t m, flexflow_tensor_t input, int num_entries, int out_dim,
    int aggr_sum /*1=sum 0=avg*/, const char* name);
flexflow_tensor_t flexflow_model_add_concat(
    flexflow_model_t m, int n, const flexflow_tensor_t* inputs, int axis,
    const char* name);
flexflow_tensor_t flexflow_model_add_add(flexflow_model_t m,
                                         flexflow_tensor_t a,
                                         flexflow_tensor_t b,
                                         const char* name);
flexflow_tensor_t flexflow_model_add_subtract(flexflow_model_t m,
                                              flexflow_tensor_t a,
                                              flexflow_tensor_t b,
                                              const char* name);
flexflow_tensor_t flexflow_model_add_multiply(flexflow_model_t m,
                                              flexflow_tensor_t a,
                                              flexflow_tensor_t b,
                                              const char* name);
flexflow_tensor_t flexflow_model_add_divide(flexflow_model_t m,
                                            flexflow_tensor_t a,
                                            flexflow_tensor_t b,
                                            const char* name);
flexflow_tensor_t flexflow_model_add_relu(flexflow_model_t m,
                                          flexflow_tensor_t input,
                                          const char* name);
flexflow_tensor_t flexflow_model_add_sigmoid(flexflow_model_t m,
                                             flexflow_tensor_t input,
                                             const char* name);
flexflow_tensor_t flexflow_model_add_tanh(flexflow_model_t m,
                                          flexflow_tensor_t input,
                                          const char* name);
flexflow_tensor_t flexflow_model_add_elu(flexflow_model_t m,
                                         flexflow_tensor_t input,
                                         const char* name);
flexflow_tensor_t flexflow_model_add_exp(flexflow_model_t m,
                                         flexflow_tensor_t input,
                                         const char* name);
flexflow_tensor_t flexflow_model_add_batch_norm(flexflow_model_t m,
                                                flexflow_tensor_t input,
                                                int relu, const char* name);
flexflow_tensor_t flexflow_model_add_dropout(flexflow_model_t m,
                                             flexflow_tensor_t input,
                                             double rate, int seed,
                                             const char* name);
flexflow_tensor_t flexflow_model_add_mse_loss(flexflow_model_t m,
                                              flexflow_tensor_t logits,
                                              flexflow_tensor_t labels,
                                              const char* reduction,
                                              const char* name);

/* compile: optimizer "sgd"|"adam"; loss per reference names */
int flexflow_model_compile(flexflow_model_t m, const char* optimizer,
                           double lr, const char* loss,
                           const char** metrics, int num_metrics);
int flexflow_model_init_layers(flexflow_model_t m);

/* batch feeding (host data, reference-ordered layout) */
int flexflow_model_set_input_f32(flexflow_model_t m, flexflow_tensor_t t,
                                 const float* data, int64_t count);
int flexflow_model_set_input_i32(flexflow_model_t m, flexflow_tensor_t t,
                                 const int32_t* data, int64_t count);
int flexflow_model_set_label_i32(flexflow_model_t m, const int32_t* data,
                                 int64_t count);
int flexflow_model_set_label_f32(flexflow_model_t m, const float* data,
                                 int64_t count);

/* train drivers (reference: forward/zero_gradients/backward/update) */
int flexflow_model_forward(flexflow_model_t m);
int flexflow_model_zero_gradients(flexflow_model_t m);
int flexflow_model_backward(flexflow_model_t m);
int flexflow_model_update(flexflow_model_t m);
int flexflow_model_sync(flexflow_model_t m);
void flexflow_model_reset_metrics(flexflow_model_t m);

/* fused train step (staged batch must be set) */
int flexflow_model_train_iteration(flexflow_model_t m);

/* metrics: returns accuracy %; train_all/correct optional out-params */
double flexflow_model_get_accuracy(flexflow_model_t m, int64_t* train_all,
                                   int64_t* train_correct);
/* any PerfMetrics field by name ("accuracy", "cce_loss", "sparse_cce_loss",
 * "mse_loss", "rmse_loss", "mae_loss", "train_all", "train_correct") */
double flexflow_model_get_metric(flexflow_model_t m, const char* name);

/* weights (reference: Parameter::get_weights/set_weights) */
int64_t flexflow_parameter_get_volume(flexflow_model_t m, const char* op_name,
                                      const char* weight_name);
int flexflow_model_get_parameter_f32(flexflow_model_t m, const char* op_name,
                                     const char* weight_name, float* out,
                                     int64_t count);
int flexflow_model_set_parameter_f32(flexflow_model_t m, const char* op_name,
                                     const char* weight_name,
                                     const float* data, int64_t count);

/* strategy files (reference: --import-strategy / --export-strategy) */
int flexflow_config_import_strategy(flexflow_config_t c, const char* path);
int flexflow_model_export_strategy(flexflow_model_t m, const char* path);

/* checkpoint / resume */
int flexflow_model_save(flexflow_model_t m, const char* path);
int flexflow_model_load(flexflow_model_t m, const char* path);

/* tensor introspection */
int flexflow_tensor_get_dims(flexflow_tensor_t t, int* dims /*>=4 slots*/);

#ifdef __cplusplus
}
#endif

#endif /* FLEXFLOW_TPU_C_H */
