"""Test harness: an 8-device virtual CPU mesh.

The reference has no way to test multi-node without a cluster (SURVEY.md
§4); this framework tests every sharding path on a fake mesh of 8 CPU
devices via --xla_force_host_platform_device_count, so the full SOAP
strategy space is exercised in CI with no TPU attached.
"""

import os
import tempfile

# Must be set before the XLA CPU client initializes.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Tests invoking soap_report (any config) must not overwrite the repo's
# committed calibration-priority hints (flexflow_tpu/simulator/
# report_keys.json) with their tiny test configs.  Per-session temp dir:
# concurrent suites (or stale files from another user) must not share
# one fixed /tmp path.
os.environ.setdefault(
    "FF_REPORT_KEYS_PATH",
    os.path.join(tempfile.mkdtemp(prefix="ff_test_report_keys_"),
                 "report_keys.json"))

import jax  # noqa: E402

# The axon sitecustomize force-selects the TPU backend at interpreter boot
# (jax.config.update('jax_platforms', 'axon,cpu')); tests run on the
# virtual CPU mesh regardless.
jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: the suite's wall time is dominated by XLA
# compiles of the fused SPMD train steps; a warm cache cuts re-runs by
# minutes.  Keyed by HLO+flags, so code changes re-compile as needed.
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("FF_TEST_JAX_CACHE", "/tmp/ff_test_jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

# The cache's put() writes the entry straight to its final name
# (LRUCache.put -> Path.write_bytes, jax 0.4.37).  A test process
# killed mid-write — suite timeout, OOM kill, ^C — leaves a TRUNCATED
# entry under the real key, and every later process that deserializes
# it dies with a general-protection fault deep inside jaxlib; one
# poisoned entry turns the whole suite red until someone deletes the
# cache dir by hand.  Make the write crash-atomic: stage under a
# pid-suffixed temp key, then os.replace onto the final name.
try:
    from jax._src import lru_cache as _lru

    _CACHE_SUF = getattr(_lru, "_CACHE_SUFFIX", "-cache")
    _ATIME_SUF = getattr(_lru, "_ATIME_SUFFIX", "-atime")
    _orig_put = _lru.LRUCache.put

    def _crash_atomic_put(self, key, val):
        tmp_key = f"{key}.tmp{os.getpid()}"
        _orig_put(self, tmp_key, val)
        for suf in (_CACHE_SUF, _ATIME_SUF):
            src, dst = self.path / f"{tmp_key}{suf}", self.path / f"{key}{suf}"
            try:
                if dst.exists():        # another process won the race
                    src.unlink()
                else:
                    os.replace(src, dst)
            except OSError:
                pass                    # best-effort: it's only a cache

    _lru.LRUCache.put = _crash_atomic_put
except Exception:                       # jax internals moved: skip hardening
    pass

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running integration test (deselect with -m 'not slow')")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs
