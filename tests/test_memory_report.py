"""memory_report CLI tests: three-view folding on a synthetic trace,
divergence flagging, missing-view tolerance, and a byte-exact golden
check (the report is a committed artifact format — changes must be
deliberate)."""

import json
import os
import sys

sys.path.insert(0, ".")

from flexflow_tpu.tools import memory_report

GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "memory_report.md")


def synthetic_records():
    """Deterministic mini-trace exercising every report section: a
    predicted view, two executables (one retraced), live gauges from two
    devices plus the serving KV pool, and a live/XLA ratio far enough
    out to trip the divergence flag."""
    recs = [{"t": "meta", "version": 1, "run_id": "golden-run", "pid": 7,
             "unix_time": 1700000000.0}]
    recs.append({"t": "event", "name": "memory_predicted", "ts": 0.5,
                 "attrs": {"num_devices": 8, "peak_bytes": 480 * 2**20,
                           "peak_device": 3, "dominant_term": "params",
                           "terms": {"params": 220 * 2**20,
                                     "grads": 220 * 2**20,
                                     "optimizer": 0,
                                     "activations": 30 * 2**20,
                                     "staging": 10 * 2**20},
                           "capacity_bytes": 16 * 2**30,
                           "headroom_bytes": 16 * 2**30 - 480 * 2**20,
                           "opt_slots": 0,
                           "by_op": {"fc1": 300 * 2**20,
                                     "conv1": 120 * 2**20,
                                     "sm": 2**20}}})
    recs.append({"t": "event", "name": "compile_done", "ts": 1.0,
                 "attrs": {"site": "train_step", "fingerprint": "aa11",
                           "wall_s": 3.1, "retrace": False, "aot": True,
                           "total_compiles": 1, "total_retraces": 0}})
    recs.append({"t": "counter", "name": "compiles", "v": 1, "total": 1,
                 "ts": 1.0, "attrs": {"site": "train_step"}})
    recs.append({"t": "counter", "name": "compile_retraces", "v": 0,
                 "total": 0, "ts": 1.0, "attrs": {"site": "train_step"}})
    recs.append({"t": "event", "name": "xla_memory", "ts": 1.0,
                 "attrs": {"site": "train_step", "fingerprint": "aa11",
                           "total_bytes": 512 * 2**20,
                           "argument_bytes": 230 * 2**20,
                           "output_bytes": 220 * 2**20,
                           "temp_bytes": 282 * 2**20,
                           "generated_code_bytes": 2**16,
                           "alias_bytes": 220 * 2**20}})
    recs.append({"t": "event", "name": "xla_cost", "ts": 1.0,
                 "attrs": {"site": "train_step", "fingerprint": "aa11",
                           "flops": 3.1e10, "bytes_accessed": 2.0e9}})
    # a serving prefill that retraced once (the failure the plane is for)
    for i, (fp, retrace) in enumerate([("bb22", False), ("bb33", True)]):
        recs.append({"t": "event", "name": "compile_done", "ts": 2.0 + i,
                     "attrs": {"site": "serve_prefill:8",
                               "fingerprint": fp, "wall_s": 0.4,
                               "retrace": retrace, "aot": True,
                               "total_compiles": 2 + i,
                               "total_retraces": int(retrace)}})
        recs.append({"t": "counter", "name": "compiles", "v": 1,
                     "total": 2 + i, "ts": 2.0 + i,
                     "attrs": {"site": "serve_prefill:8"}})
        recs.append({"t": "counter", "name": "compile_retraces",
                     "v": int(retrace), "total": int(retrace),
                     "ts": 2.0 + i, "attrs": {"site": "serve_prefill:8"}})
        recs.append({"t": "event", "name": "xla_memory", "ts": 2.0 + i,
                     "attrs": {"site": "serve_prefill:8",
                               "fingerprint": fp,
                               "total_bytes": 64 * 2**20,
                               "argument_bytes": 40 * 2**20,
                               "output_bytes": 8 * 2**20,
                               "temp_bytes": 16 * 2**20,
                               "generated_code_bytes": 2**14,
                               "alias_bytes": 0}})
    # live gauges: device 0 peak deliberately ~4x the largest executable
    # to trip the divergence flag
    for dev, kind, v in [("0", "in_use", 1800 * 2**20),
                         ("0", "peak", 2048 * 2**20),
                         ("0", "limit", 16 * 2**30),
                         ("1", "in_use", 500 * 2**20),
                         ("pool", "kv_blocks", 24 * 2**20)]:
        recs.append({"t": "gauge", "name": "hbm_bytes", "v": float(v),
                     "ts": 5.0, "attrs": {"device": dev, "kind": kind}})
    return recs


def write_trace(path, recs):
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_report_sections_and_folding(tmp_path):
    path = str(tmp_path / "t.jsonl")
    write_trace(path, synthetic_records())
    report = memory_report.main([path, "-o", str(tmp_path / "r.md")])
    for section in ("## Predicted (analytic model)", "## Headroom",
                    "## XLA executables", "## Live HBM", "## Divergence"):
        assert section in report
    assert "480.0 MiB" in report          # predicted peak
    assert "dominant term: params" in report
    assert "headroom: **15.5 GiB**" in report
    assert "train_step" in report and "serve_prefill:8" in report
    assert "**1 retrace(s)**" in report
    # the out-of-band live/XLA ratio is flagged loudly
    assert "!! live(peak) / XLA" in report
    assert "| pool | kv_blocks | 24.0 MiB |" in report
    assert (tmp_path / "r.md").read_text() == report


def test_missing_views_tolerated(tmp_path):
    # live-only trace (e.g. scraped gauges, no compile plane): the
    # report renders the absence of the other views, rc stays 0
    path = str(tmp_path / "live.jsonl")
    write_trace(path, [{"t": "gauge", "name": "hbm_bytes", "v": 1024.0,
                        "ts": 1.0, "attrs": {"device": "0",
                                             "kind": "in_use"}}])
    report = memory_report.main([path])
    assert "no `memory_predicted` event" in report
    assert "no compile events" in report
    assert "nothing to cross-check" in report


def test_empty_and_corrupt_trace(tmp_path):
    path = str(tmp_path / "e.jsonl")
    with open(path, "w") as f:
        f.write('{"t": "event", "name": "xla_mem')  # truncated mid-write
    report = memory_report.main([path])
    assert "## Divergence" in report


def test_golden_output(tmp_path):
    """Byte-exact golden: regenerate with
    ``python tests/test_memory_report.py --regen`` after deliberate
    format changes."""
    path = str(tmp_path / "t.jsonl")
    write_trace(path, synthetic_records())
    report = memory_report.render(
        memory_report.fold(memory_report.parse_trace(path)), "golden.jsonl")
    with open(GOLDEN) as f:
        assert report == f.read()


if __name__ == "__main__" and "--regen" in sys.argv:
    import tempfile

    tmp = os.path.join(tempfile.mkdtemp(), "t.jsonl")
    write_trace(tmp, synthetic_records())
    os.makedirs(os.path.dirname(GOLDEN), exist_ok=True)
    with open(GOLDEN, "w") as f:
        f.write(memory_report.render(
            memory_report.fold(memory_report.parse_trace(tmp)),
            "golden.jsonl"))
    print(f"regenerated {GOLDEN}")
