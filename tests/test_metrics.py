"""Metrics-plane tests: Prometheus text well-formedness under a live
scrape, percentile agreement with the trace_report reference math,
counter totals under concurrent writer threads, and the zero-observer
guarantee when FF_METRICS_PORT is unset.

Pure stdlib — no jax import, so this file also proves metrics.py stays
safe on the pre-jax import path (bench.py starts the exporter before
the backend initializes).
"""

import json
import re
import sys
import threading
import urllib.request

import pytest

sys.path.insert(0, ".")

from flexflow_tpu.observability import events, metrics


@pytest.fixture(autouse=True)
def _isolated(monkeypatch):
    """Fresh env + process-wide singletons per test."""
    for var in ("FF_TELEMETRY", "FF_TELEMETRY_FILE", "FF_METRICS_PORT",
                "FF_METRICS_HOST", "FF_METRICS_WINDOW"):
        monkeypatch.delenv(var, raising=False)
    events.reset_active()
    metrics.stop()
    yield
    metrics.stop()
    events.reset_active()


# one sample line: name{labels} value  (labels optional; value is a
# float literal — the renderer uses %g so no NaN/Inf/timestamps here)
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'
    r' [-+]?[0-9]*\.?[0-9]+([eE][-+]?[0-9]+)?$')


def assert_prom_wellformed(text):
    """Every non-comment line parses as a sample, and every sample's
    base family has a preceding # TYPE declaration."""
    assert text.endswith("\n")
    typed = set()
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            typed.add(line.split()[2])
            continue
        if line.startswith("#"):
            continue
        assert _SAMPLE_RE.match(line), f"malformed sample line: {line!r}"
        name = line.split("{")[0].split(" ")[0]
        family_ok = (name in typed
                     or name.rsplit("_", 1)[0] in typed)  # _sum/_count
        assert family_ok, f"sample {name} has no # TYPE declaration"


# ---------------------------------------------------------------------------
# env knob parsing
# ---------------------------------------------------------------------------

def test_port_unset_is_none():
    assert metrics.metrics_port_from_env() is None


def test_port_garbage_is_loud(monkeypatch):
    monkeypatch.setenv("FF_METRICS_PORT", "banana")
    with pytest.raises(ValueError, match="FF_METRICS_PORT"):
        metrics.metrics_port_from_env()
    monkeypatch.setenv("FF_METRICS_PORT", "70000")
    with pytest.raises(ValueError, match="outside"):
        metrics.metrics_port_from_env()


# ---------------------------------------------------------------------------
# zero-cost when disabled
# ---------------------------------------------------------------------------

def test_disabled_registers_no_observer(tmp_path):
    log = events.EventLog(str(tmp_path / "t.jsonl"))
    assert metrics.maybe_start(log) is None
    assert log._observers == []
    assert metrics.global_registry() is None
    assert metrics.server_port() is None
    # scrape helper still renders (serving mounts it unconditionally)
    assert "registry disabled" in metrics.scrape_text()


# ---------------------------------------------------------------------------
# registry folding + rendering
# ---------------------------------------------------------------------------

def _feed(reg, recs):
    for r in recs:
        reg.observe(r)


def test_render_prom_wellformed_and_values():
    reg = metrics.MetricsRegistry(window=64)
    _feed(reg, [
        {"t": "counter", "name": "samples", "v": 32.0},
        {"t": "counter", "name": "samples", "v": 32.0},
        {"t": "counter", "name": "serve_failed", "v": 1.0,
         "attrs": {"status": "shed", "request": "r-123"}},
        {"t": "gauge", "name": "mfu", "v": 0.41},
        {"t": "gauge", "name": "serve_batch_occupancy", "v": 0.5,
         "attrs": {"replica": "r0"}},
        {"t": "span", "name": "step", "dur": 0.01},
        {"t": "span", "name": "step", "dur": 0.03},
        {"t": "event", "name": "replica_failover",
         "attrs": {"reason": "health"}},
        {"t": "event", "name": "serve_request_done",
         "attrs": {"ttft_s": 0.12, "tpot_s": 0.004}},
    ])
    text = reg.render_prom()
    assert_prom_wellformed(text)
    assert "ff_samples_total 64" in text
    # allowlisted label kept, request id dropped (cardinality bound)
    assert 'ff_serve_failed_total{status="shed"} 1' in text
    assert 'request="r-123"' not in text
    assert "ff_mfu 0.41" in text
    assert 'ff_serve_batch_occupancy{replica="r0"} 0.5' in text
    # span -> summary with unit suffix
    assert "ff_step_seconds_count 2" in text
    assert "ff_step_seconds_sum 0.04" in text
    # events fold into one family, labelled by event name
    assert 'ff_events_total{event="replica_failover"} 1' in text
    # request-done latencies extracted into histograms
    assert "ff_serve_ttft_seconds_count 1" in text
    assert "ff_serve_tpot_seconds_count 1" in text
    assert "ff_metrics_records_seen_total 9" in text


def test_histogram_percentiles_match_reference():
    from flexflow_tpu.tools.trace_report import percentile as ref_pct
    reg = metrics.MetricsRegistry(window=256)
    durs = [0.001 * (i % 17 + 1) for i in range(100)]
    _feed(reg, [{"t": "span", "name": "step", "dur": d} for d in durs])
    snap = reg.render_vars()["histograms"]["step"]
    vals = sorted(durs)
    for q in (50.0, 95.0, 99.0):
        assert snap[f"p{q:g}"] == pytest.approx(ref_pct(vals, q), abs=1e-9)
        # and the module-local copy agrees with the trace_report math
        assert metrics.percentile(vals, q) == pytest.approx(
            ref_pct(vals, q), abs=1e-12)
    assert snap["count"] == 100
    assert snap["sum"] == pytest.approx(sum(durs), abs=1e-6)


def test_window_bounds_quantiles_but_not_totals():
    reg = metrics.MetricsRegistry(window=8)
    _feed(reg, [{"t": "span", "name": "s", "dur": float(i)}
                for i in range(100)])
    snap = reg.render_vars()["histograms"]["s"]
    assert snap["count"] == 100               # monotonic
    assert snap["sum"] == pytest.approx(sum(range(100)))
    assert snap["p50"] >= 92.0                # quantiles from last 8 only


def test_attach_seeds_preexisting_totals(tmp_path):
    log = events.EventLog(str(tmp_path / "t.jsonl"))
    log.counter("samples", 128.0)
    reg = metrics.MetricsRegistry()
    reg.attach(log)
    log.counter("samples", 32.0)
    log.close()
    assert "ff_samples_total 160" in reg.render_prom()


# ---------------------------------------------------------------------------
# concurrency: writer races + scrape-under-load
# ---------------------------------------------------------------------------

def test_counter_totals_survive_writer_races(tmp_path, monkeypatch):
    monkeypatch.setenv("FF_METRICS_PORT", "0")
    monkeypatch.setenv("FF_METRICS_HOST", "127.0.0.1")
    log = events.EventLog(str(tmp_path / "t.jsonl"))
    reg = metrics.maybe_start(log)
    n_obs = len(log._observers)   # registry tap + SLO evaluator tap
    assert reg is not None and n_obs >= 1
    # second call must not double-attach (idempotence)
    assert metrics.maybe_start(log) is reg
    assert len(log._observers) == n_obs

    port = metrics.server_port()
    n_threads, n_incr = 8, 200
    stop_scraping = threading.Event()
    scrapes = []

    def scrape_loop():
        while not stop_scraping.is_set():
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/plain")
                scrapes.append(r.read().decode())

    def writer():
        for _ in range(n_incr):
            log.counter("races", 1.0)
            log.span_at("step", 0.0, 0.001)

    scraper = threading.Thread(target=scrape_loop)
    scraper.start()
    writers = [threading.Thread(target=writer) for _ in range(n_threads)]
    for t in writers:
        t.start()
    for t in writers:
        t.join()
    stop_scraping.set()
    scraper.join()
    log.close()

    # every mid-load scrape was well-formed
    assert scrapes
    for text in scrapes:
        assert_prom_wellformed(text)
    # no lost increments despite 8 racing observer threads
    final = reg.render_vars()
    assert final["counters"]["races"] == n_threads * n_incr
    assert final["histograms"]["step"]["count"] == n_threads * n_incr
    assert log.totals["races"] == n_threads * n_incr


def test_debug_vars_endpoint(tmp_path, monkeypatch):
    monkeypatch.setenv("FF_METRICS_PORT", "0")
    monkeypatch.setenv("FF_METRICS_HOST", "127.0.0.1")
    log = events.EventLog(str(tmp_path / "t.jsonl"))
    metrics.maybe_start(log)
    log.counter("samples", 16.0)
    log.close()
    port = metrics.server_port()
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/vars", timeout=5) as r:
        body = json.loads(r.read())
    assert body["counters"]["samples"] == 16.0
    assert body["records_seen"] >= 1
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/nope", timeout=5)
    assert ei.value.code == 404


# ---------------------------------------------------------------------------
# serving backend provider (pool-shaped fake; no jax needed)
# ---------------------------------------------------------------------------

class _FakePool:
    def healthz(self):
        return {"status": "ok", "queued": 3, "inflight": 2,
                "replicas": [
                    {"name": "r0", "state": "ready",
                     "incarnation": "r0#1", "restarts": 0},
                    {"name": "r1", "state": "restarting",
                     "incarnation": "r1#4", "restarts": 3},
                ]}


def test_backend_provider_renders_replica_state():
    pool = _FakePool()
    provider = lambda: metrics.render_backend(pool)  # noqa: E731
    metrics.register_provider(provider)
    try:
        text = metrics.scrape_text()
        assert_prom_wellformed(text)
        assert "ff_serve_queue_depth 3" in text
        assert "ff_serve_inflight 2" in text
        assert 'ff_replica_up{replica="r0",state="ready"} 1' in text
        assert 'ff_replica_up{replica="r1",state="restarting"} 0' in text
        # incarnation uid is a string -> info-style series (value 1)
        assert ('ff_replica_incarnation{incarnation="r1#4",replica="r1"} 1'
                in text)
        assert 'ff_replica_restarts{replica="r1"} 3' in text
    finally:
        metrics.unregister_provider(provider)
    assert "ff_replica_up" not in metrics.scrape_text()


def test_broken_backend_never_breaks_scrape():
    class Broken:
        def healthz(self):
            raise RuntimeError("pool wedged")

    text = metrics.render_backend(Broken())
    assert "backend render failed" in text
    assert_prom_wellformed(text)


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
