"""End-to-end training tests on the 8-device virtual mesh.

The reference's real test contract is "every example trains to threshold
accuracy" (SURVEY.md §4); these tests assert loss decreases / the model
fits a learnable synthetic task, plus weight get/set round-trip
(Parameter::set_weights/get_weights analogue).
"""

import numpy as np
import pytest

import jax

import flexflow_tpu as ff


def build_mlp(m, inp, classes=4):
    t = m.dense(inp, 32, activation=ff.ActiMode.RELU)
    t = m.dense(t, classes)
    return m.softmax(t)


def test_mlp_learns_separable_task(devices):
    cfg = ff.FFConfig(batch_size=32, compute_dtype="float32")
    m = ff.FFModel(cfg)
    inp = m.create_tensor((32, 8), nchw=False)
    build_mlp(m, inp)
    m.compile(ff.SGDOptimizer(lr=0.5), ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY, ff.MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY])
    m.init_layers()

    # learnable task: label = argmax of 4 coordinates
    rng = np.random.default_rng(0)
    x = rng.standard_normal((256, 8), dtype=np.float32)
    y = np.argmax(x[:, :4], axis=1).astype(np.int32)[:, None]
    dl = ff.DataLoader(m, {inp: x}, y)

    for epoch in range(30):
        dl.reset()
        m.reset_metrics()
        for _ in range(dl.num_batches()):
            dl.next_batch(m)
            m.forward(); m.zero_gradients(); m.backward(); m.update()
    acc = m.get_metrics().accuracy
    assert acc > 90.0, f"model failed to learn, accuracy={acc}"


def test_convnet_loss_decreases(devices):
    cfg = ff.FFConfig(batch_size=16)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 3, 16, 16))
    t = m.conv2d(inp, 8, 3, 3, 1, 1, 1, 1, activation=ff.ActiMode.RELU)
    t = m.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = m.flat(t)
    t = m.dense(t, 10)
    t = m.softmax(t)
    m.compile(ff.SGDOptimizer(lr=0.05), "sparse_categorical_crossentropy",
              ["accuracy", "sparse_categorical_crossentropy"])
    m.init_layers()
    dl = ff.DataLoader.synthetic(m, inp, num_samples=32)

    losses = []
    for epoch in range(8):
        dl.reset()
        m.reset_metrics()
        for _ in range(dl.num_batches()):
            dl.next_batch(m)
            m.train_iteration()
        pm = m.get_metrics()
        losses.append(pm.sparse_cce_loss / max(1, pm.train_all))
    assert losses[-1] < losses[0] * 0.7, f"loss did not decrease: {losses}"


def test_weight_get_set_round_trip(devices):
    m = ff.FFModel(ff.FFConfig(batch_size=8))
    inp = m.create_tensor((8, 8), nchw=False)
    build_mlp(m, inp)
    m.compile(ff.SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers()
    name = m.ops[0].name
    w = m.get_parameter(name, "kernel")
    assert w.shape == (8, 32)
    w2 = np.arange(w.size, dtype=np.float32).reshape(w.shape)
    m.set_parameter(name, "kernel", w2)
    np.testing.assert_allclose(m.get_parameter(name, "kernel"), w2)


def test_adam_training(devices):
    m = ff.FFModel(ff.FFConfig(batch_size=16))
    inp = m.create_tensor((16, 8), nchw=False)
    build_mlp(m, inp)
    m.compile(ff.AdamOptimizer(alpha=0.01), "sparse_categorical_crossentropy",
              ["accuracy", "sparse_categorical_crossentropy"])
    m.init_layers()
    rng = np.random.default_rng(1)
    x = rng.standard_normal((64, 8), dtype=np.float32)
    y = np.argmax(x[:, :4], axis=1).astype(np.int32)[:, None]
    from flexflow_tpu.runtime.dataloader import DataLoader
    dl = DataLoader(m, {inp: x}, y)
    first = None
    for epoch in range(15):
        m.optimizer.next_epoch()
        dl.reset()
        m.reset_metrics()
        for _ in range(dl.num_batches()):
            dl.next_batch(m)
            m.train_iteration()
        pm = m.get_metrics()
        loss = pm.sparse_cce_loss / max(1, pm.train_all)
        if first is None:
            first = loss
    assert loss < first * 0.5, f"adam failed to reduce loss: {first} -> {loss}"


def test_mse_regression(devices):
    m = ff.FFModel(ff.FFConfig(batch_size=16))
    inp = m.create_tensor((16, 4), nchw=False)
    m.dense(inp, 1)
    m.compile(ff.SGDOptimizer(lr=0.1), "mean_squared_error",
              ["mean_squared_error"])
    m.init_layers()
    rng = np.random.default_rng(2)
    x = rng.standard_normal((64, 4), dtype=np.float32)
    w_true = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    y = x @ w_true
    from flexflow_tpu.runtime.dataloader import DataLoader
    dl = DataLoader(m, {inp: x}, y)
    for epoch in range(40):
        dl.reset()
        for _ in range(dl.num_batches()):
            dl.next_batch(m)
            m.train_iteration()
    m.sync()
    w = m.get_parameter(m.ops[0].name, "kernel")
    np.testing.assert_allclose(w, w_true, atol=0.05)
