"""Per-op numerics vs. independent references (torch CPU / numpy).

The reference validates ops only end-to-end (SURVEY.md §4); here each op is
unit-tested against torch.nn.functional (layout-converted NCHW↔NHWC) or
closed-form numpy.
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax
import jax.numpy as jnp

import flexflow_tpu as ff
from flexflow_tpu.ops.base import FwdCtx


def run_op(op, params, *xs, training=False, rng=None):
    ctx = FwdCtx(training=training, rng=rng,
                 stats_in={op.name: op.init_stats()} if op.init_stats() else {},
                 stats_out={} if training else None)
    return op.forward(params, list(xs), ctx)[0]


def make_model(batch=4):
    return ff.FFModel(ff.FFConfig(batch_size=batch, workers_per_node=1))


def test_conv2d_matches_torch():
    m = make_model()
    inp = m.create_tensor((4, 3, 16, 16))  # reference NCHW order
    out = m.conv2d(inp, 8, 3, 3, 2, 2, 1, 1)
    op = m.ops[0]
    assert out.dims == (4, 8, 8, 8)  # NHWC: (N, H', W', C)

    rng = np.random.default_rng(0)
    x_nchw = rng.standard_normal((4, 3, 16, 16), dtype=np.float32)
    k_hwio = rng.standard_normal((3, 3, 3, 8), dtype=np.float32)
    b = rng.standard_normal((8,), dtype=np.float32)

    y = run_op(op, {"kernel": jnp.asarray(k_hwio), "bias": jnp.asarray(b)},
               jnp.asarray(x_nchw.transpose(0, 2, 3, 1)))
    y_ref = F.conv2d(torch.from_numpy(x_nchw),
                     torch.from_numpy(k_hwio.transpose(3, 2, 0, 1)),
                     torch.from_numpy(b), stride=2, padding=1)
    np.testing.assert_allclose(np.asarray(y).transpose(0, 3, 1, 2),
                               y_ref.numpy(), rtol=2e-5, atol=2e-5)


def test_conv2d_shape_formula():
    # out = 1 + (in + 2p - k)/s  (reference conv_2d.cu:100-101)
    m = make_model()
    inp = m.create_tensor((4, 3, 229, 229))
    t = m.conv2d(inp, 64, 11, 11, 4, 4, 2, 2)
    assert t.dims == (4, 56, 56, 64)


def test_pool2d_max_matches_torch():
    m = make_model()
    inp = m.create_tensor((2, 4, 13, 13))
    out = m.pool2d(inp, 3, 3, 2, 2, 0, 0)
    assert out.dims == (2, 6, 6, 4)
    x = np.random.default_rng(1).standard_normal((2, 4, 13, 13), dtype=np.float32)
    y = run_op(m.ops[0], {}, jnp.asarray(x.transpose(0, 2, 3, 1)))
    y_ref = F.max_pool2d(torch.from_numpy(x), 3, 2)
    np.testing.assert_allclose(np.asarray(y).transpose(0, 3, 1, 2), y_ref.numpy(),
                               rtol=1e-6, atol=1e-6)


def test_pool2d_avg_excludes_padding():
    m = make_model()
    inp = m.create_tensor((1, 1, 4, 4))
    m.pool2d(inp, 3, 3, 2, 2, 1, 1, pool_type=ff.PoolType.AVG)
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    y = run_op(m.ops[0], {}, jnp.asarray(x.transpose(0, 2, 3, 1)))
    y_ref = F.avg_pool2d(torch.from_numpy(x), 3, 2, padding=1,
                         count_include_pad=False)
    np.testing.assert_allclose(np.asarray(y).transpose(0, 3, 1, 2), y_ref.numpy(),
                               rtol=1e-6, atol=1e-6)


def test_linear_matches_numpy():
    m = make_model()
    inp = m.create_tensor((4, 32))
    out = m.dense(inp, 16, activation=ff.ActiMode.RELU)
    assert out.dims == (4, 16)
    rng = np.random.default_rng(2)
    x = rng.standard_normal((4, 32), dtype=np.float32)
    w = rng.standard_normal((32, 16), dtype=np.float32)
    b = rng.standard_normal((16,), dtype=np.float32)
    y = run_op(m.ops[0], {"kernel": jnp.asarray(w), "bias": jnp.asarray(b)}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y), np.maximum(x @ w + b, 0), rtol=1e-5, atol=1e-5)


def test_embedding_sum_avg():
    m = make_model()
    inp = m.create_tensor((3, 5), dtype=ff.DataType.INT32, nchw=False)
    m.embedding(inp, num_entries=20, out_dim=6, aggr=ff.AggrMode.SUM)
    table = np.random.default_rng(3).standard_normal((20, 6), dtype=np.float32)
    idx = np.array([[0, 1, 2, 3, 4], [5, 5, 5, 5, 5], [19, 0, 19, 0, 1]], np.int32)
    y = run_op(m.ops[0], {"weight": jnp.asarray(table)}, jnp.asarray(idx))
    np.testing.assert_allclose(np.asarray(y), table[idx].sum(1), rtol=1e-6, atol=1e-6)

    m2 = make_model()
    inp2 = m2.create_tensor((3, 5), dtype=ff.DataType.INT32, nchw=False)
    m2.embedding(inp2, 20, 6, aggr=ff.AggrMode.AVG)
    y2 = run_op(m2.ops[0], {"weight": jnp.asarray(table)}, jnp.asarray(idx))
    np.testing.assert_allclose(np.asarray(y2), table[idx].mean(1), rtol=1e-6, atol=1e-6)


def test_flat_softmax_concat_elementwise():
    m = make_model()
    inp = m.create_tensor((2, 3, 4, 4))
    t = m.flat(inp)
    assert t.dims == (2, 48)

    x = np.random.default_rng(4).standard_normal((2, 4, 4, 3), dtype=np.float32)
    y = run_op(m.ops[0], {}, jnp.asarray(x))
    assert y.shape == (2, 48)

    # softmax
    sm = make_model()
    si = sm.create_tensor((2, 10), nchw=False)
    sm.softmax(si)
    logits = np.random.default_rng(5).standard_normal((2, 10), dtype=np.float32)
    p = run_op(sm.ops[0], {}, jnp.asarray(logits))
    np.testing.assert_allclose(np.asarray(p), F.softmax(torch.from_numpy(logits), -1).numpy(),
                               rtol=1e-5, atol=1e-6)

    # concat channel axis: reference axis=1 (NCHW) → native 3
    cm = make_model()
    a = cm.create_tensor((2, 3, 4, 4))
    b = cm.create_tensor((2, 5, 4, 4))
    out = cm.concat([a, b], axis=1)
    assert out.dims == (2, 4, 4, 8)

    # element binary
    em = make_model()
    u = em.create_tensor((2, 6), nchw=False)
    v = em.create_tensor((2, 6), nchw=False)
    em.add(u, v)
    xu = np.ones((2, 6), np.float32)
    xv = np.full((2, 6), 2.0, np.float32)
    y = run_op(em.ops[0], {}, jnp.asarray(xu), jnp.asarray(xv))
    np.testing.assert_allclose(np.asarray(y), xu + xv)


def test_batchnorm_train_matches_torch():
    m = make_model()
    inp = m.create_tensor((4, 3, 8, 8))
    m.batch_norm(inp, relu=True)
    op = m.ops[0]
    x = np.random.default_rng(6).standard_normal((4, 3, 8, 8), dtype=np.float32)
    scale = np.array([1.5, 0.5, 2.0], np.float32)
    bias = np.array([0.1, -0.2, 0.0], np.float32)
    y = run_op(op, {"scale": jnp.asarray(scale), "bias": jnp.asarray(bias)},
               jnp.asarray(x.transpose(0, 2, 3, 1)), training=True)
    bn = F.batch_norm(torch.from_numpy(x), None, None,
                      torch.from_numpy(scale), torch.from_numpy(bias),
                      training=True, eps=1e-5)
    np.testing.assert_allclose(np.asarray(y).transpose(0, 3, 1, 2),
                               F.relu(bn).numpy(), rtol=1e-4, atol=1e-4)


def test_dropout_train_and_eval():
    m = make_model()
    inp = m.create_tensor((8, 100), nchw=False)
    m.dropout(inp, rate=0.5)
    op = m.ops[0]
    x = jnp.ones((8, 100))
    y_eval = run_op(op, {}, x, training=False)
    np.testing.assert_allclose(np.asarray(y_eval), np.ones((8, 100)))
    y_tr = run_op(op, {}, x, training=True, rng=jax.random.key(0))
    arr = np.asarray(y_tr)
    assert set(np.unique(arr)).issubset({0.0, 2.0})
    assert 0.3 < (arr == 0).mean() < 0.7
