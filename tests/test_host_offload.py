"""Heterogeneous (host-memory) weight placement.

Reference: ParallelConfig::device_type=CPU routes ops to CPU task variants
so DLRM keeps huge embedding tables in host zero-copy memory
(embedding.cc:18-77, dlrm_strategy_hetero.cc).  TPU equivalent under test:
a CPU-typed config pins the op's weights (and optimizer state) in
pinned-host memory; each step streams them on-chip and back."""

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.config import DeviceType


def _build(offload: bool, momentum: float = 0.9):
    cfg = ff.FFConfig(batch_size=16)
    if offload:
        cfg.strategies["emb"] = ff.ParallelConfig(
            DeviceType.CPU, (1, 1), (0,))
    m = ff.FFModel(cfg)
    ids = m.create_tensor((16, 4), dtype="int32", name="ids")
    t = m.embedding(ids, 100, 8, name="emb")
    t = m.dense(t, 4, name="head")
    m.softmax(t, name="sm")
    m.compile(ff.SGDOptimizer(m, lr=0.1, momentum=momentum),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    m.init_layers(seed=11)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 100, (16, 4)).astype(np.int32)
    y = (x[:, 0] % 4).astype(np.int32).reshape(-1, 1)
    m.set_batch({ids: x}, y)
    return m


def test_offloaded_table_lives_in_host_memory(devices):
    m = _build(offload=True)
    w = m._params["emb"]["weight"]
    assert w.sharding.memory_kind == "pinned_host"
    assert ("emb", "weight") in m._offload


def test_offloaded_training_matches_device_training(devices):
    m_dev = _build(offload=False)
    m_host = _build(offload=True)
    for _ in range(8):
        m_dev.train_iteration()
        m_host.train_iteration()
    m_dev.sync()
    m_host.sync()
    np.testing.assert_allclose(m_dev.get_parameter("emb", "weight"),
                               m_host.get_parameter("emb", "weight"),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(m_dev.get_parameter("head", "kernel"),
                               m_host.get_parameter("head", "kernel"),
                               rtol=2e-5, atol=2e-6)
    # updated table still lives in host memory after training
    assert m_host._params["emb"]["weight"].sharding.memory_kind == "pinned_host"


def test_memory_types_host_triggers_offload(devices):
    # strategy-file memory_types wire field ("host" = reference ZCM)
    # must drive placement like device_type=CPU does
    cfg = ff.FFConfig(batch_size=16)
    cfg.strategies["emb"] = ff.ParallelConfig(
        DeviceType.TPU, (1, 1), (0,), memory_types=("host",))
    m = ff.FFModel(cfg)
    ids = m.create_tensor((16, 4), dtype="int32", name="ids")
    t = m.embedding(ids, 50, 8, name="emb")
    t = m.dense(t, 4, name="head")
    m.softmax(t, name="sm")
    m.compile(ff.SGDOptimizer(m, lr=0.1),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    m.init_layers()
    # plain SGD qualifies for the row-sparse path: the table is
    # host-resident numpy (tests/test_sparse_host_embed.py covers it);
    # the point here is that memory_types=("host",) drove host placement
    assert "emb" in m._host_embed
    assert isinstance(m._params["emb"]["weight"], np.ndarray)


def test_offloaded_momentum_state_in_host_memory(devices):
    m = _build(offload=True, momentum=0.9)
    m.train_iteration()
    m.sync()
    v = m._opt_state["v"]["emb"]["weight"]
    assert v.sharding.memory_kind == "pinned_host"
