"""Heterogeneous (host-memory) weight placement.

Reference: ParallelConfig::device_type=CPU routes ops to CPU task variants
so DLRM keeps huge embedding tables in host zero-copy memory
(embedding.cc:18-77, dlrm_strategy_hetero.cc).  TPU equivalent under test:
a CPU-typed config pins the op's weights (and optimizer state) in
pinned-host memory; each step streams them on-chip and back."""

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.config import DeviceType


def _build(offload: bool, momentum: float = 0.9, opt: str = "sgd",
           zero: bool = False, rows: int = 100):
    cfg = ff.FFConfig(batch_size=16, zero_optimizer=zero)
    if offload:
        cfg.strategies["emb"] = ff.ParallelConfig(
            DeviceType.CPU, (1, 1), (0,))
    m = ff.FFModel(cfg)
    ids = m.create_tensor((16, 4), dtype="int32", name="ids")
    t = m.embedding(ids, rows, 8, name="emb")
    t = m.dense(t, 4, name="head")
    m.softmax(t, name="sm")
    optimizer = (ff.AdamOptimizer(m, alpha=0.01) if opt == "adam"
                 else ff.SGDOptimizer(m, lr=0.1, momentum=momentum))
    m.compile(optimizer, ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    m.init_layers(seed=11)
    rng = np.random.default_rng(0)
    x = rng.integers(0, rows, (16, 4)).astype(np.int32)
    y = (x[:, 0] % 4).astype(np.int32).reshape(-1, 1)
    m.set_batch({ids: x}, y)
    return m


def test_offloaded_table_lives_in_host_memory(devices):
    m = _build(offload=True)
    w = m._params["emb"]["weight"]
    assert w.sharding.memory_kind == "pinned_host"
    assert ("emb", "weight") in m._offload


def test_offloaded_training_matches_device_training(devices):
    m_dev = _build(offload=False)
    m_host = _build(offload=True)
    for _ in range(8):
        m_dev.train_iteration()
        m_host.train_iteration()
    m_dev.sync()
    m_host.sync()
    np.testing.assert_allclose(m_dev.get_parameter("emb", "weight"),
                               m_host.get_parameter("emb", "weight"),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(m_dev.get_parameter("head", "kernel"),
                               m_host.get_parameter("head", "kernel"),
                               rtol=2e-5, atol=2e-6)
    # updated table still lives in host memory after training
    assert m_host._params["emb"]["weight"].sharding.memory_kind == "pinned_host"


def test_memory_types_host_triggers_offload(devices):
    # strategy-file memory_types wire field ("host" = reference ZCM)
    # must drive placement like device_type=CPU does
    cfg = ff.FFConfig(batch_size=16)
    cfg.strategies["emb"] = ff.ParallelConfig(
        DeviceType.TPU, (1, 1), (0,), memory_types=("host",))
    m = ff.FFModel(cfg)
    ids = m.create_tensor((16, 4), dtype="int32", name="ids")
    t = m.embedding(ids, 50, 8, name="emb")
    t = m.dense(t, 4, name="head")
    m.softmax(t, name="sm")
    m.compile(ff.SGDOptimizer(m, lr=0.1),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    m.init_layers()
    # plain SGD qualifies for the row-sparse path: the table is
    # host-resident numpy (tests/test_sparse_host_embed.py covers it);
    # the point here is that memory_types=("host",) drove host placement
    assert "emb" in m._host_embed
    assert isinstance(m._params["emb"]["weight"], np.ndarray)


def test_offloaded_momentum_state_in_host_memory(devices):
    m = _build(offload=True, momentum=0.9)
    m.train_iteration()
    m.sync()
    v = m._opt_state["v"]["emb"]["weight"]
    assert v.sharding.memory_kind == "pinned_host"


@pytest.mark.parametrize("zero", [False, True])
def test_offloaded_stateful_adam_trains(devices, zero):
    """Adam (two table-shaped state slots) x streaming pinned-host
    offload, with and without ZeRO-1: state init must not try to
    materialize pinned-host buffers from zeros_like (regression: ZeRO x
    offload crashed at init with a memory-kind mismatch), and numerics
    must match the no-offload run."""
    def build(offload):
        m = _build(offload, opt="adam", zero=zero, rows=512)
        for _ in range(4):
            m.train_iteration()
        m.sync()
        return m

    m_host = build(True)
    assert ("emb", "weight") in m_host._offload  # streaming, not row-sparse
    m_dev = build(False)
    np.testing.assert_allclose(m_dev.get_parameter("emb", "weight"),
                               m_host.get_parameter("emb", "weight"),
                               rtol=2e-5, atol=2e-6)
