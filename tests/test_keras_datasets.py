"""Keras datasets/preprocessing/utils parity.

Mirrors the reference's de-facto test contract (python/test.sh +
VerifyMetrics callbacks): datasets load with the right shapes, pad/one-hot
utilities behave like keras, and a Sequential MLP trains on the mnist
loader's output to threshold accuracy.
"""

import numpy as np

from flexflow_tpu import keras


def test_mnist_shapes():
    (x, y), (xt, yt) = keras.datasets.mnist.load_data()
    assert x.shape == (60000, 28, 28) and x.dtype == np.uint8
    assert y.shape == (60000,)
    assert xt.shape == (10000, 28, 28)
    assert set(np.unique(y)) <= set(range(10))


def test_cifar10_shapes():
    (x, y), (xt, yt) = keras.datasets.cifar10.load_data()
    assert x.shape == (50000, 3, 32, 32) and x.dtype == np.uint8
    assert y.shape == (50000, 1)


def test_reuters_contract():
    (x, y), (xt, yt) = keras.datasets.reuters.load_data(num_words=1000)
    assert len(x) + len(xt) == 11228
    # start_char=1, index_from offset, oov capping
    assert all(seq[0] == 1 for seq in x[:50])
    assert max(max(seq) for seq in x[:50]) < 1000


def test_pad_sequences():
    seqs = [[1, 2, 3], [4, 5], [6]]
    out = keras.preprocessing.pad_sequences(seqs, maxlen=4)
    np.testing.assert_array_equal(out, [[0, 1, 2, 3], [0, 0, 4, 5], [0, 0, 0, 6]])
    out = keras.preprocessing.pad_sequences(seqs, maxlen=2, padding="post",
                                            truncating="post")
    np.testing.assert_array_equal(out, [[1, 2], [4, 5], [6, 0]])


def test_to_categorical():
    out = keras.utils.to_categorical([0, 2, 1], num_classes=3)
    np.testing.assert_array_equal(out, np.eye(3)[[0, 2, 1]])


def test_tokenizer():
    tok = keras.preprocessing.text.Tokenizer(num_words=10)
    tok.fit_on_texts(["the cat sat", "the cat ran", "the dog"])
    seqs = tok.texts_to_sequences(["the cat", "the dog"])
    assert seqs[0][0] == tok.word_index["the"] == 1  # most frequent
    assert len(seqs[1]) == 2


def test_seq_mnist_mlp_trains(devices):
    """Reference: examples/python/keras/seq_mnist_mlp.py + VerifyMetrics."""
    import flexflow_tpu as ff

    (x_train, y_train), _ = keras.datasets.mnist.load_data()
    x_train = x_train[:512].reshape(512, 784).astype("float32") / 255
    y_train = y_train[:512].astype(np.int32)

    model = keras.Sequential(config=ff.FFConfig(batch_size=64,
                                                compute_dtype="float32"))
    model.add(keras.layers.Input(shape=(784,)))
    model.add(keras.layers.Dense(64, activation="relu"))
    model.add(keras.layers.Dense(10))
    model.add(keras.layers.Activation("softmax"))
    model.compile(optimizer=keras.optimizers.SGD(learning_rate=0.2),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    cb = keras.callbacks.VerifyMetrics(accuracy_threshold=60.0)
    model.fit(x_train, y_train, epochs=20, callbacks=[cb], verbose=False)
