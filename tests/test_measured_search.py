"""The search consumes REAL measured cost entries (VERDICT r2 #1).

Reference semantics: the MCMC search costs every candidate with measured
kernel times cached by (op, config) hash (simulator.cc:235-273).  Here
the measurements are taken up-front (tools/calibrate.py on the chip) and
shipped in a durable cache; these tests pin the contract that a search
run actually READS those entries — and that provenance rules hold
(only real measurements persist; platform tags filter)."""

import json
import os

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.simulator.cost_model import CostModel
from flexflow_tpu.simulator.machine import TPUMachineModel
from flexflow_tpu.simulator.search import mcmc_search
from flexflow_tpu.simulator.simulator import Simulator


def _model(batch=64):
    cfg = ff.FFConfig(batch_size=batch)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((batch, 16), nchw=False)
    t = m.dense(inp, 32, activation="relu", name="fc1")
    t = m.dense(t, 10, name="fc2")
    m.softmax(t, name="sm")
    return m


def _fill_cache(path, model, mm, value=1e-3):
    """Fabricate a 'measured' cache covering every candidate sub-shape."""
    from flexflow_tpu.simulator.native_search import enumerate_candidates

    probe = CostModel(mm, measure=False, cache_path=None,
                      measured_cache_path="/nonexistent")
    entries = {}
    for op in model.ops:
        for pc in enumerate_candidates(op, mm.num_devices):
            pc = op.legalize_pc(pc)
            for which in ("forward", "backward"):
                key = probe._key(op, pc, which)
                entries[key] = {"t": value, "measured": True,
                                "platform": "tpu"}
    with open(path, "w") as f:
        json.dump(entries, f)
    return len(entries)


def test_search_consumes_measured_entries(tmp_path, devices):
    model = _model()
    mm = TPUMachineModel(num_devices=8)
    cache = str(tmp_path / "measured.json")
    n = _fill_cache(cache, model, mm, value=1e-3)
    assert n > 0

    cost = CostModel(mm, measure=False, cache_path=None,
                     measured_cache_path=cache)
    sim = Simulator(mm, cost)
    dp = {op.name: ff.ParallelConfig.data_parallel(op.output.num_dims, 8)
          .with_device_ids(tuple(range(8)))
          for op in model.ops}
    rt = sim.simulate_runtime(model, dp)
    assert cost.stats["measured_hits"] > 0
    assert cost.stats["analytic"] == 0  # full coverage: nothing analytic
    # compute portion = 3 ops x (1ms fwd after deps...) — at minimum the
    # critical path carries the fabricated values, not roofline guesses
    assert rt >= 2e-3  # fwd+bwd of at least one op chain at 1 ms each


def test_measured_entries_change_search_outcome(tmp_path, devices):
    """Poisoning the measured cache against batch splits steers the
    search away from them — proof the entries drive the objective."""
    from flexflow_tpu.simulator.native_search import enumerate_candidates

    model = _model()
    mm = TPUMachineModel(num_devices=8)
    cache = str(tmp_path / "measured.json")
    probe = CostModel(mm, measure=False, cache_path=None,
                      measured_cache_path="/nonexistent")
    entries = {}
    for op in model.ops:
        for pc in enumerate_candidates(op, mm.num_devices):
            pc = op.legalize_pc(pc)
            # any sample-dim split is 'measured' as catastrophically slow
            bad = pc.dims[0] > 1
            for which in ("forward", "backward"):
                entries[probe._key(op, pc, which)] = {
                    "t": 1.0 if bad else 1e-6,
                    "measured": True, "platform": "tpu"}
    with open(cache, "w") as f:
        json.dump(entries, f)

    import flexflow_tpu.simulator.search as search_mod

    orig = CostModel

    def patched(mm_, **kw):
        kw["measured_cache_path"] = cache
        kw["cache_path"] = None
        return orig(mm_, **kw)

    search_mod.CostModel, saved = patched, search_mod.CostModel
    try:
        best = mcmc_search(model, budget=300, machine_model=mm, seed=1,
                           verbose=False)
    finally:
        search_mod.CostModel = saved
    assert all(pc.dims[0] == 1 for pc in best.values()), best


def test_cpu_measurements_never_masquerade_as_tpu(tmp_path, devices):
    """Platform-tagged entries: a cpu-tagged measurement is invisible to
    a TPU-targeting cost model (the provenance rule calibrate relies on)."""
    model = _model()
    mm = TPUMachineModel(num_devices=8)
    cache = str(tmp_path / "measured.json")
    probe = CostModel(mm, measure=False, cache_path=None,
                      measured_cache_path="/nonexistent")
    op = model.ops[0]
    pc = ff.ParallelConfig.data_parallel(op.output.num_dims, 8)
    key = probe._key(op, op.legalize_pc(pc), "forward")
    with open(cache, "w") as f:
        json.dump({key: {"t": 123.0, "measured": True,
                         "platform": "cpu"}}, f)
    tpu_cost = CostModel(mm, measure=False, cache_path=None,
                         measured_cache_path=cache, target_platform="tpu")
    assert key not in tpu_cost._measured
    cpu_cost = CostModel(mm, measure=False, cache_path=None,
                         measured_cache_path=cache, target_platform="cpu")
    assert cpu_cost._measured[key] == 123.0


def test_only_measured_entries_persist(tmp_path, devices):
    """Analytic fallbacks never reach the durable cache."""
    model = _model()
    mm = TPUMachineModel(num_devices=8)
    local = str(tmp_path / "local.json")
    cost = CostModel(mm, measure=False, cache_path=local,
                     measured_cache_path="/nonexistent")
    op = model.ops[0]
    pc = op.legalize_pc(
        ff.ParallelConfig.data_parallel(op.output.num_dims, 8))
    t = cost.op_time(op, pc, "forward")
    assert t > 0 and cost.stats["analytic"] == 1
    assert not os.path.exists(local)  # nothing persisted


def test_soap_report_generator(tmp_path, devices):
    """End-to-end report: search runs, report + strategy file written."""
    from flexflow_tpu.tools.soap_report import main

    out = str(tmp_path / "REPORT.md")
    pb = str(tmp_path / "s.pb")
    res = main(["alexnet", "--devices", "8", "--batch-size", "128",
                "--budget", "200", "--export", pb, "--out", out,
                "--measured-single-chip-ms", "10.0"])
    assert os.path.exists(out) and os.path.exists(pb)
    assert res["speedup"] >= 1.0
    text = open(out).read()
    assert "SOAP searched" in text and "agreement" in text.lower()


def test_fit_machine_recovers_known_constants():
    """fit_machine's grid fit recovers roofline constants from synthetic
    records generated BY that roofline (sanity for the calibration
    math)."""
    from flexflow_tpu.simulator.machine import TPUMachineModel
    from flexflow_tpu.tools.calibrate import fit_machine

    mm = TPUMachineModel(num_devices=1)
    eff, hbm_frac, ovh = 0.52, 0.8, 4e-6
    rng = np.random.default_rng(0)
    recs = []
    for _ in range(64):
        flops = float(10 ** rng.uniform(6, 11))
        byts = float(10 ** rng.uniform(4, 8))
        t = max(flops / (mm.peak_flops * eff),
                byts / (mm.hbm_bandwidth * hbm_frac)) + ovh
        recs.append({"flops": flops, "bytes": byts, "t_fwd": t,
                     "t_bwd": 2.1 * t})
    fit = fit_machine(recs, mm)
    assert abs(fit["mxu_efficiency"] - eff) < 0.03
    assert abs(fit["hbm_bandwidth"] / mm.hbm_bandwidth - hbm_frac) < 0.07
    assert fit["kernel_launch_overhead"] == 4e-6
    assert abs(fit["backward_multiplier"] - 2.1) < 0.05
    assert fit["fit_log_rmse"] < 0.05
