"""DataLoader: batch order, layout conversion, prefetch determinism.

The prefetch worker gathers the NEXT batch while the device computes
(the reference's scatter launch overlaps under Legion the same way) —
it must never change WHAT is delivered, only when the gather runs.
"""

import numpy as np
import pytest

import flexflow_tpu as ff


class _CaptureModel:
    """Stands in for FFModel: records every batch set_batch receives."""

    def __init__(self, batch_size):
        class _C:
            pass

        self.config = _C()
        self.config.batch_size = batch_size
        self.batches = []

    def set_batch(self, inputs, labels):
        self.batches.append(([np.asarray(v).copy()
                              for v in inputs.values()],
                             np.asarray(labels).copy()))


def _real_tensor():
    cfg = ff.FFConfig(batch_size=8)
    m = ff.FFModel(cfg)
    return m.create_tensor((8, 4), nchw=False)


def _drive(prefetch, shuffle, epochs=3):
    t = _real_tensor()
    cap = _CaptureModel(batch_size=8)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((40, 4), dtype=np.float32)
    y = np.arange(40, dtype=np.int32).reshape(-1, 1)
    dl = ff.DataLoader(cap, {t: x}, y, shuffle=shuffle, seed=11,
                       prefetch=prefetch)
    for _ in range(epochs):
        dl.reset()
        for _ in range(dl.num_batches()):
            dl.next_batch(cap)
    return cap.batches


@pytest.mark.parametrize("shuffle", [False, True])
def test_prefetch_delivers_identical_batches(shuffle):
    plain = _drive(prefetch=False, shuffle=shuffle)
    pre = _drive(prefetch=True, shuffle=shuffle)
    assert len(plain) == len(pre) == 15
    for (xi, yi), (xj, yj) in zip(plain, pre):
        np.testing.assert_array_equal(yi, yj)
        for a, b in zip(xi, xj):
            np.testing.assert_array_equal(a, b)


def test_prefetch_survives_mid_epoch_reset():
    """A reset between next_batch calls invalidates the pending gather
    (the version check) — the following epoch starts at sample 0."""
    t = _real_tensor()
    cap = _CaptureModel(batch_size=8)
    x = np.arange(40 * 4, dtype=np.float32).reshape(40, 4)
    y = np.arange(40, dtype=np.int32).reshape(-1, 1)
    dl = ff.DataLoader(cap, {t: x}, y, prefetch=True)
    dl.next_batch(cap)
    dl.next_batch(cap)
    dl.reset()
    dl.next_batch(cap)
    labels = [b[1].ravel().tolist() for b in cap.batches]
    assert labels[0] == list(range(8))
    assert labels[1] == list(range(8, 16))
    assert labels[2] == list(range(8))  # restarted, not the stale prefetch
