"""Cross-feature composition: the features are only real if they stack.

Each test trains one model with SEVERAL round-3 features enabled at once
and pins numerics against the plain run — TP x remat x grad-accum,
pipeline x ZeRO, MoE x grad-accum, fused x ZeRO.
"""

import numpy as np
import pytest

import flexflow_tpu as ff


def _mlp_model(cfg, batch=16, din=12, width=32, nout=6):
    m = ff.FFModel(cfg)
    inp = m.create_tensor((batch, din), nchw=False)
    t = m.dense(inp, width, activation="relu", name="fc1")
    t = m.dense(t, width, activation="relu", name="fc2")
    t = m.dense(t, nout, name="head")
    m.softmax(t, name="sm")
    return m, inp


def _data(batch=16, din=12, nout=6, seed=3):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((batch, din), dtype=np.float32)
    y = rng.integers(0, nout, size=(batch, 1), dtype=np.int32)
    return x, y


def _run(cfg_kwargs, strategies=None, steps=3, opt="sgd",
         pipeline=False):
    cfg = ff.FFConfig(batch_size=16, strategies=dict(strategies or {}),
                      **cfg_kwargs)
    m, inp = _mlp_model(cfg)
    if pipeline:
        m.set_pipeline(num_stages=2, num_microbatches=4, dp_degree=4)
    optimizer = (ff.SGDOptimizer(lr=0.1, momentum=0.9) if opt == "sgd"
                 else ff.AdamOptimizer(alpha=0.01))
    m.compile(optimizer, "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=21)
    x, y = _data()
    m.set_batch({inp: x}, y)
    for _ in range(steps):
        m.train_iteration()
    m.sync()
    return (m.get_parameter("fc1", "kernel"),
            m.get_parameter("head", "kernel"), m)


TP = {"fc1": ff.ParallelConfig(dims=(2, 4)),
      "fc2": ff.ParallelConfig(dims=(8, 1)),
      "head": ff.ParallelConfig(dims=(8, 1)),
      "sm": ff.ParallelConfig(dims=(8, 1))}


def test_tp_remat_grad_accum(devices):
    """Tensor parallel + rematerialization + 4-way grad accumulation ==
    the plain data-parallel step."""
    a0, b0, _ = _run({})
    a1, b1, _ = _run({"remat": True, "grad_accum_steps": 4},
                     strategies=TP)
    np.testing.assert_allclose(a0, a1, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(b0, b1, rtol=2e-5, atol=2e-6)


def test_fused_zero_stack(devices):
    """Fused Pallas updates + ZeRO-1 state sharding together: ZeRO
    leaves take the plain per-leaf update, the rest stay fused; the
    result equals the plain optimizer."""
    a0, b0, _ = _run({}, opt="adam")
    a1, b1, m = _run({"fused_optimizer": True, "zero_optimizer": True},
                     opt="adam")
    np.testing.assert_allclose(a0, a1, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(b0, b1, rtol=1e-5, atol=1e-6)
    st = m._opt_state["m"]["fc2"]["kernel"]
    assert st.sharding.spec and st.sharding.spec[0] is not None


@pytest.mark.slow
def test_pipeline_zero_stack(devices):
    """General pipeline (packed stage weights) + ZeRO-1: the pipe buffer
    keeps its pipe sharding, other leaves shard state over free axes,
    numerics match the sequential run."""
    def run(pipeline):
        cfg = ff.FFConfig(batch_size=16, zero_optimizer=True)
        m, inp = _mlp_model(cfg)
        if pipeline:
            m.set_pipeline(num_stages=2, num_microbatches=4, dp_degree=4)
        m.compile(ff.AdamOptimizer(alpha=0.01),
                  "sparse_categorical_crossentropy", ["accuracy"])
        m.init_layers(seed=21)
        x, y = _data()
        m.set_batch({inp: x}, y)
        for _ in range(3):
            m.train_iteration()
        m.sync()
        return m.get_parameter("fc1", "kernel"), m

    a0, _ = run(False)
    a1, m = run(True)
    assert m._pipeline_plan is not None and m._pipe_pack() is not None
    np.testing.assert_allclose(a0, a1, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_moe_grad_accum_ep(devices):
    """MoE under expert parallelism + grad accumulation == plain run.
    Routing is per-micro-batch deterministic (capacity depends on the
    micro size), so compare accum=2 ep-sharded vs accum=2 default."""
    def run(strategies):
        cfg = ff.FFConfig(batch_size=16, grad_accum_steps=2,
                          strategies=dict(strategies))
        m = ff.FFModel(cfg)
        inp = m.create_tensor((16, 12), nchw=False)
        t = m.dense(inp, 16, activation="relu", name="fc_in")
        t = m.expert_mlp(t, num_experts=4, hidden_size=32, name="moe")
        t = m.dense(t, 6, name="head")
        m.softmax(t, name="sm")
        m.compile(ff.SGDOptimizer(lr=0.05),
                  "sparse_categorical_crossentropy", ["accuracy"])
        m.init_layers(seed=4)
        x, y = _data(din=12, nout=6, seed=9)
        m.set_batch({inp: x}, y)
        for _ in range(3):
            m.train_iteration()
        m.sync()
        return m.get_parameter("moe", "w_in")

    w0 = run({})
    w1 = run({"moe": ff.ParallelConfig(dims=(2, 4))})
    np.testing.assert_allclose(w0, w1, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_pipeline_remat_grad_accum(devices):
    """GPipe pipeline x rematerialization x 2-way grad accumulation ==
    the plain run (the accum micro-loop wraps the ring schedule; remat
    recomputes inside the stage branches)."""
    a1, b1, m = _run({"remat": True, "grad_accum_steps": 2},
                     pipeline=True)
    assert m._pipeline_plan is not None  # 2 x dp4 always fits 8 devices
    a0, b0, _ = _run({})
    np.testing.assert_allclose(a0, a1, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(b0, b1, rtol=2e-4, atol=2e-5)
