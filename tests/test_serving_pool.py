"""Replica-pool resilience (flexflow_tpu/serving/pool.py).

The load-bearing claims: losing a replica degrades THROUGHPUT, never
correctness (every request — including the killed replica's in-flight
ones — still resolves with tokens bitwise-equal to one-shot
``FFModel.generate()``, exactly once); admission control sheds with
``ServeOverload`` (HTTP 503 + Retry-After) instead of letting latency
collapse; and SIGTERM drains instead of dropping work.

Replicas here are thread-isolated on the shared CPU model — the test
shape pool.py documents; real deployments pass one model per device
slice.
"""

import collections
import signal
import time

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.models.transformer import build_transformer
from flexflow_tpu.runtime.resilience import (PreemptionHandler,
                                             backoff_delay)
from flexflow_tpu.serving import (ServeConfig, ServeError, ServeOverload)
from flexflow_tpu.serving.pool import ReplicaPool
from flexflow_tpu.serving.queue import DONE
from flexflow_tpu.testing.chaos import ChaosMonkey

V = 32          # vocab
MAX_SEQ = 64


def _make_model(seed=3):
    cfg = ff.FFConfig(batch_size=4)
    m = ff.FFModel(cfg)
    build_transformer(m, 4, seq_length=MAX_SEQ, num_layers=1,
                      embed_dim=16, num_heads=2, vocab_size=V)
    m.compile(ff.SGDOptimizer(lr=0.1),
              "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=seed)
    return m


@pytest.fixture(scope="module")
def model():
    return _make_model()


def _prompts(n, seed=0, lo=3, hi=11):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, V, size=int(rng.integers(lo, hi + 1)))
            .astype(np.int32) for _ in range(n)]


def _cfg(**kw):
    # generous replica_timeout: a cold prefill compile stalls the beat
    # for seconds and must not read as a wedged replica
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq", MAX_SEQ)
    kw.setdefault("replica_timeout_s", 120.0)
    kw.setdefault("restart_backoff_s", 0.05)
    kw.setdefault("restart_cap_s", 0.2)
    return ServeConfig(**kw)


# ---------------------------------------------------------------------------
# N=1 parity: a pool of one behaves like the bare engine
# ---------------------------------------------------------------------------

def test_pool_n1_matches_generate(model):
    prompts = _prompts(5, seed=1)
    with ReplicaPool(model, config=_cfg(replicas=1)) as pool:
        handles = [pool.submit(p, 8) for p in prompts]
        outs = [h.result(120) for h in handles]
    for p, got in zip(prompts, outs):
        assert np.array_equal(got, model.generate(p[None], 8)[0])
    st = pool.stats()
    assert st["completed"] == 5
    assert st["failovers"] == 0 and st["shed"] == 0 and st["hedged"] == 0


# ---------------------------------------------------------------------------
# failover: kill a replica mid-load, everything still resolves exactly once
# ---------------------------------------------------------------------------

def test_pool_failover_exactly_once(model, monkeypatch):
    # 3rd pool-wide admission raises ChaosReplicaKill inside whichever
    # replica pops it: that loop thread dies holding one mid-admit
    # request and possibly a live slot
    monkeypatch.setattr(model, "_chaos", ChaosMonkey("serve:3=replica_kill"))
    prompts = _prompts(8, seed=2)
    fires = collections.Counter()
    with ReplicaPool(model, config=_cfg(replicas=3)) as pool:
        handles = [pool.submit(p, 8) for p in prompts]
        for h in handles:
            h.add_done_callback(lambda r: fires.update([r.request_id]))
        outs = [h.result(120) for h in handles]
        st = pool.stats()
    for i, (p, got) in enumerate(zip(prompts, outs)):
        assert np.array_equal(got, model.generate(p[None], 8)[0]), i
    assert st["replica_downs"] >= 1, st
    assert st["failovers"] >= 1, "the kill never caught a request in flight"
    assert st["completed"] == 8, st
    # exactly-once: the CAS in _resolve means each client fires its done
    # callbacks a single time, however many attempts raced for it
    assert len(fires) == 8 and set(fires.values()) == {1}, fires
    assert not pool._attempts and not pool._clients


def test_pool_single_replica_restart_serves_queued(model, monkeypatch):
    # N=1 and the only replica dies: the failover attempt can only be
    # served by the RESTARTED incarnation (avoid = the dead uid, not the
    # replica name) — and healthz narrates down -> ok on the way
    monkeypatch.setattr(model, "_chaos", ChaosMonkey("serve:1=replica_kill"))
    p = _prompts(1, seed=4)[0]
    with ReplicaPool(model, config=_cfg(
            replicas=1, restart_backoff_s=0.4, restart_cap_s=1.0)) as pool:
        assert pool.ready()
        h = pool.submit(p, 6)
        saw_down = False
        deadline = time.perf_counter() + 30
        while time.perf_counter() < deadline:
            if pool.healthz()["status"] == "down":
                saw_down = True
                assert not pool.ready()     # LB signal drops with it
                break
            time.sleep(0.005)
        assert saw_down, "replica death never surfaced in healthz"
        toks = h.result(120)
        assert np.array_equal(toks, model.generate(p[None], 6)[0])
        deadline = time.perf_counter() + 30
        while pool.healthz()["status"] != "ok" \
                and time.perf_counter() < deadline:
            time.sleep(0.01)
        assert pool.healthz()["status"] == "ok" and pool.ready()
        st = pool.stats()
        assert st["replica_downs"] == 1 and st["replica_restarts"] == 1
    assert pool.healthz()["status"] == "stopped"


# ---------------------------------------------------------------------------
# admission control: shed with 503 + Retry-After, keep the accepted tail
# ---------------------------------------------------------------------------

def test_pool_shedding_503_retry_after(model):
    cfg = _cfg(replicas=1, max_batch=1, max_queue=2)
    pool = ReplicaPool(model, config=cfg)
    accepted, sheds = [], []
    with pool:
        for p in _prompts(10, seed=5):
            try:
                accepted.append((p, pool.submit(p, 24)))
            except ServeOverload as e:
                sheds.append(e)
        for p, h in accepted:
            assert np.array_equal(h.result(120),
                                  model.generate(p[None], 24)[0])
    assert sheds, "FF_SERVE_MAX_QUEUE never shed under a 10-request burst"
    # HTTP contract: Retry-After is a positive whole-ish delay
    assert all(e.retry_after_s >= 1.0 for e in sheds)
    st = pool.stats()
    assert st["shed"] == len(sheds)
    assert st["completed"] == len(accepted) == 10 - len(sheds)
    # the point of shedding: accepted requests wait behind a BOUNDED
    # queue (cap + one slot), not the whole burst
    e2e = sorted(h.t_done - h.t_submit for _, h in accepted)
    assert e2e[-1] < 60.0, f"accepted p99 unbounded: {e2e[-1]:.1f}s"


def test_pool_unbounded_queue_never_sheds(model):
    with ReplicaPool(model, config=_cfg(replicas=1, max_batch=1)) as pool:
        handles = [pool.submit(p, 8) for p in _prompts(6, seed=6)]
        for h in handles:
            h.result(120)
    assert pool.stats()["shed"] == 0


# ---------------------------------------------------------------------------
# hedging: winner takes the client, loser is cancelled
# ---------------------------------------------------------------------------

def test_pool_hedge_winner_takes_all(model):
    p = _prompts(1, seed=7, lo=3, hi=6)[0]
    with ReplicaPool(model, config=_cfg(
            replicas=2, hedge_ms=10.0)) as pool:
        h = pool.submit(p, 32)
        toks = h.result(120)
        assert np.array_equal(toks, model.generate(p[None], 32)[0])
        st = pool.stats()
        assert st["hedged"] == 1, st
        assert st["completed"] == 1 and st["failed"] == 0
        # the losing attempt is untracked + force-cancelled; its slot
        # frees at the next token boundary
        deadline = time.perf_counter() + 10
        while pool._attempts and time.perf_counter() < deadline:
            time.sleep(0.01)
        assert not pool._attempts
    assert h.status == DONE


def test_pool_hedge_needs_two_ready_replicas(model):
    # hedge_ms set but N=1: the scan must stay inert (doctor WARNs on
    # this config; the pool must simply not hedge against itself)
    p = _prompts(1, seed=8)[0]
    with ReplicaPool(model, config=_cfg(replicas=1, hedge_ms=1.0)) as pool:
        assert np.array_equal(pool.generate(p, 16, timeout=120),
                              model.generate(p[None], 16)[0])
        assert pool.stats()["hedged"] == 0


# ---------------------------------------------------------------------------
# restart backoff: bounded exponential, shared helper
# ---------------------------------------------------------------------------

def test_backoff_delay_caps():
    assert backoff_delay(1, 0.5, 30.0) == 0.5
    assert backoff_delay(2, 0.5, 30.0) == 1.0
    assert backoff_delay(3, 0.5, 30.0) == 2.0
    assert backoff_delay(10, 0.5, 30.0) == 30.0     # capped
    assert backoff_delay(0, 0.5, 30.0) == 0.5       # clamped to first


def test_pool_restart_backoff_caps(model):
    # repeated down-marks walk the shared bounded-exponential schedule:
    # base, then capped — never unbounded
    cfg = _cfg(replicas=1, restart_backoff_s=5.0, restart_cap_s=8.0)
    with ReplicaPool(model, config=cfg) as pool:
        rep = pool._replicas[0]
        for want in (5.0, 8.0, 8.0):      # 5, 10->8, 20->8
            now = time.perf_counter()
            pool._mark_down(rep, "test", now)
            assert rep.restart_at - now == pytest.approx(want, rel=1e-6)
        assert pool.stats()["replica_downs"] == 3


# ---------------------------------------------------------------------------
# graceful drain: SIGTERM finishes everything, refuses new work
# ---------------------------------------------------------------------------

def test_pool_sigterm_drains(model):
    prompts = _prompts(4, seed=9)
    pool = ReplicaPool(model, config=_cfg(replicas=2))
    pool.start()
    try:
        handler = PreemptionHandler()
        pool.attach_preemption(handler)
        handles = [pool.submit(p, 8) for p in prompts]
        # simulate SIGTERM: the handler only sets a cooperative flag,
        # which is exactly what the monitor polls
        handler.signum = signal.SIGTERM
        handler.requested = True
        outs = [h.result(120) for h in handles]
        for p, got in zip(prompts, outs):
            assert np.array_equal(got, model.generate(p[None], 8)[0])
        deadline = time.perf_counter() + 30
        while not pool._draining and time.perf_counter() < deadline:
            time.sleep(0.01)
        assert pool._draining and not pool.ready()
        with pytest.raises(ServeError, match="not accepting"):
            pool.submit(prompts[0], 4)
        assert pool.healthz()["status"] in ("draining", "stopped")
        assert pool.stats()["completed"] == 4       # nothing dropped
    finally:
        pool.stop()


# ---------------------------------------------------------------------------
# healthz/readyz shape
# ---------------------------------------------------------------------------

def test_pool_healthz_detail(model):
    with ReplicaPool(model, config=_cfg(replicas=2)) as pool:
        hz = pool.healthz()
        assert hz["status"] == "ok" and hz["accepting"]
        assert [r["name"] for r in hz["replicas"]] \
            == ["replica-0", "replica-1"]
        for r in hz["replicas"]:
            assert r["state"] == "ready"
            assert r["incarnation"].startswith(r["name"] + "#")
            assert r["beat_age_s"] is not None
    hz = pool.healthz()
    assert hz["status"] == "stopped" and not pool.ready()
