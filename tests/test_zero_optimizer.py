"""ZeRO-1 optimizer-state sharding (FFConfig.zero_optimizer).

SURVEY §2.3 lists ZeRO-style optimizer sharding as design headroom over
the reference.  Contracts: state of replicated params shards over the
free mesh axes (~1/N per device), training numerics are unchanged, and
non-divisible leaves are skipped, not broken.
"""

import numpy as np
import pytest

import flexflow_tpu as ff


def _train(zero, steps=4, opt="adam"):
    cfg = ff.FFConfig(batch_size=16, zero_optimizer=zero)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 8), nchw=False)
    t = m.dense(inp, 64, activation="relu", name="fc1")
    t = m.dense(t, 10, name="fc2")   # out dim 10: bias not divisible by 8
    t = m.softmax(t, name="sm")
    optimizer = (ff.AdamOptimizer(alpha=0.01) if opt == "adam"
                 else ff.SGDOptimizer(lr=0.1, momentum=0.9))
    m.compile(optimizer, "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=12)
    rng = np.random.default_rng(7)
    x = rng.standard_normal((16, 8), dtype=np.float32)
    y = rng.integers(0, 10, size=(16, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y)
    for _ in range(steps):
        dl.next_batch(m)
        m.train_iteration()
    m.sync()
    return m


@pytest.mark.parametrize("opt", ["adam", "sgd"])
def test_zero_numerics_match_plain(devices, opt):
    ref = _train(False, opt=opt)
    z = _train(True, opt=opt)
    for name in ("fc1", "fc2"):
        np.testing.assert_allclose(ref.get_parameter(name, "kernel"),
                                   z.get_parameter(name, "kernel"),
                                   rtol=1e-5, atol=1e-6)


def test_zero_state_actually_sharded(devices):
    m = _train(True, steps=1)
    state = m._opt_state["m"]
    # fc1 kernel (8, 64): dim0 divisible by the 8 free axes -> sharded
    arr = state["fc1"]["kernel"]
    assert arr.sharding.spec and arr.sharding.spec[0] is not None
    per_dev = max(int(np.prod(s.data.shape))
                  for s in arr.addressable_shards)
    assert per_dev == arr.size // 8
    # fc2 bias (10,): 10 % 8 != 0 -> skipped, stays replicated
    b = state["fc2"]["bias"]
    assert all(e is None for e in b.sharding.spec)
    # plain run keeps everything replicated
    ref = _train(False, steps=1)
    rarr = ref._opt_state["m"]["fc1"]["kernel"]
    assert all(e is None for e in rarr.sharding.spec)


def test_zero_state_stays_sharded_across_steps(devices):
    """The computed state re-enters the step still sharded (the
    with_sharding_constraint in apply holds between iterations)."""
    m = _train(True, steps=3)
    arr = m._opt_state["m"]["fc1"]["kernel"]
    assert arr.sharding.spec and arr.sharding.spec[0] is not None


def test_zero_state_checkpoint_roundtrip(tmp_path, devices):
    """Sharded optimizer state survives save/load: values match AND the
    loaded state carries the ZeRO layout again (not silently
    replicated)."""
    m = _train(True, steps=2)
    before = np.asarray(m._opt_state["m"]["fc1"]["kernel"])
    path = str(tmp_path / "ck.npz")
    m.save(path)
    m2 = _train(True, steps=1)
    m2.load(path)
    arr = m2._opt_state["m"]["fc1"]["kernel"]
    np.testing.assert_allclose(np.asarray(arr), before,
                               rtol=1e-6, atol=1e-7)
    assert arr.sharding.spec and arr.sharding.spec[0] is not None, \
        arr.sharding
    np.testing.assert_allclose(m2.get_parameter("fc1", "kernel"),
                               m.get_parameter("fc1", "kernel"),
                               rtol=1e-6, atol=1e-7)
