"""Measured per-op attribution tests: cadence gating, event + corpus
emission from a real CPU training loop, measured-sum sanity against the
measured step wall, and the corpus round-trip through
``calibrate --fit-only``."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, ".")

import flexflow_tpu as ff
from flexflow_tpu.observability import events, opprof


@pytest.fixture(autouse=True)
def _isolated(monkeypatch):
    for var in ("FF_TELEMETRY", "FF_TELEMETRY_FILE", "FF_OPPROF",
                "FF_OPPROF_BUDGET_S", "FF_OPPROF_CORPUS",
                "FF_METRICS_PORT"):
        monkeypatch.delenv(var, raising=False)
    events.reset_active()
    yield
    events.reset_active()


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _tiny_model(batch=16):
    cfg = ff.FFConfig(batch_size=batch, compute_dtype="float32")
    m = ff.FFModel(cfg)
    inp = m.create_tensor((batch, 8), nchw=False)
    t = m.dense(inp, 16, activation=ff.ActiMode.RELU)
    m.softmax(m.dense(t, 4))
    return m, inp


def _compile(m):
    m.compile(ff.SGDOptimizer(lr=0.1),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])


def _train_steps(m, inp, steps):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((m.config.batch_size * steps, 8), np.float32)
    y = rng.integers(0, 4, (m.config.batch_size * steps, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y)
    for _ in range(steps):
        dl.next_batch(m)
        m.train_iteration()


# ---------------------------------------------------------------------------
# knob parsing
# ---------------------------------------------------------------------------

def test_cadence_unset_is_none():
    assert opprof.cadence_from_env() is None
    assert opprof.budget_from_env() == opprof.DEFAULT_BUDGET_S


def test_knobs_parse_loudly(monkeypatch):
    monkeypatch.setenv("FF_OPPROF", "every-few")
    with pytest.raises(ValueError, match="FF_OPPROF"):
        opprof.cadence_from_env()
    monkeypatch.setenv("FF_OPPROF", "0")
    with pytest.raises(ValueError, match=">= 1"):
        opprof.cadence_from_env()
    monkeypatch.setenv("FF_OPPROF_BUDGET_S", "-3")
    with pytest.raises(ValueError, match="> 0"):
        opprof.budget_from_env()


def test_disabled_is_none(devices, tmp_path, monkeypatch):
    # unset -> no profiler even with telemetry on
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
    m, _ = _tiny_model()
    _compile(m)
    assert m._telemetry is not None and m._opprof is None
    events.reset_active()
    # set, but telemetry off -> still None (nothing to attribute into)
    monkeypatch.setenv("FF_OPPROF", "2")
    assert opprof.maybe_profiler(m, None) is None


# ---------------------------------------------------------------------------
# in-training cadence pass
# ---------------------------------------------------------------------------

def test_cadence_emits_events_and_corpus(devices, tmp_path, monkeypatch):
    trace = tmp_path / "run.jsonl"
    corpus = tmp_path / "measured.json"
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(trace))
    monkeypatch.setenv("FF_OPPROF", "2")
    monkeypatch.setenv("FF_OPPROF_BUDGET_S", "30")  # cover all ops on CPU
    monkeypatch.setenv("FF_OPPROF_CORPUS", str(corpus))
    m, inp = _tiny_model()
    _compile(m)
    assert m._opprof is not None and m._opprof.cadence == 2
    m.init_layers()
    _train_steps(m, inp, 5)  # passes fire at steps 2 and 4
    events.reset_active()

    recs = _read_jsonl(str(trace))
    runtime = [r for r in recs if r["t"] == "event"
               and r["name"] == "op_runtime"]
    passes = [r for r in recs if r["t"] == "event"
              and r["name"] == "op_runtime_pass"]
    assert not [r for r in recs if r["t"] == "event"
                and r["name"] == "op_runtime_error"]
    assert passes and {p["attrs"]["step"] for p in passes} == {2, 4}
    assert runtime
    for r in runtime:
        a = r["attrs"]
        assert a["measured_ms"] > 0
        assert a["which"] in ("forward", "backward")
        assert a["src"] in ("measured", "analytic")
        assert a["step"] in (2, 4)
    # every compute op got both directions within the wide budget
    op_names = {op.name for op in m.ops
                if getattr(op, "pc", None) is not None
                and not op.pc.host_placed}
    assert {r["attrs"]["op"] for r in runtime} == op_names
    assert passes[0]["attrs"]["ops_measured"] == len(op_names)

    # agreement rows carry in-training measurement provenance
    div = [r for r in recs if r["t"] == "event"
           and r["name"] == "sim_divergence"
           and r["attrs"].get("scope") == "op"]
    assert div and all(d["attrs"]["measured_src"] == "opprof" for d in div)

    # corpus entries: measured=True, tagged with the REAL backend (cpu
    # under the test harness — never masquerading as chip timings)
    with open(corpus) as f:
        entries = json.load(f)
    assert entries
    for key, v in entries.items():
        assert v["measured"] is True
        assert v["platform"] == "cpu"
        assert v["t"] > 0

    # measured per-op sum is the same order of magnitude as the measured
    # step wall (CPU dispatch overhead dominates tiny fragments, so the
    # tolerance is deliberately wide: two decades either way)
    last = {}
    for r in runtime:
        last[(r["attrs"]["op"], r["attrs"]["which"])] = \
            r["attrs"]["measured_ms"]
    sum_ms = sum(last.values())
    steps = sorted(r["dur"] for r in recs if r["t"] == "span"
                   and r["name"] == "step" and not r["attrs"].get("first"))
    step_ms = steps[len(steps) // 2] * 1e3
    assert step_ms > 0 and sum_ms > 0
    assert step_ms / 100.0 < sum_ms < step_ms * 100.0


def test_broken_op_skipped_permanently(devices, tmp_path, monkeypatch):
    trace = tmp_path / "run.jsonl"
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(trace))
    m, inp = _tiny_model()
    _compile(m)
    m.init_layers()
    log = m._telemetry
    prof = opprof.OpProfiler(m, log, cadence=1, budget_s=30.0,
                             corpus_path=str(tmp_path / "c.json"))
    first = next(op for op in m.ops
                 if getattr(op, "pc", None) is not None
                 and not op.pc.host_placed)
    orig = prof._fragment

    def boom(op):
        if op.name == first.name:
            raise RuntimeError("no fragment for you")
        return orig(op)

    prof._fragment = boom
    prof.on_step(1)
    prof.on_step(2)
    assert first.name in prof._broken
    events.reset_active()
    runtime_ops = {r["attrs"]["op"] for r in _read_jsonl(str(trace))
                   if r["t"] == "event" and r["name"] == "op_runtime"}
    assert first.name not in runtime_ops
    assert runtime_ops  # the rest of the list still measured


# ---------------------------------------------------------------------------
# corpus round-trip: opprof entries -> calibrate --fit-only
# ---------------------------------------------------------------------------

def test_corpus_roundtrips_through_calibrate_fit_only(
        devices, tmp_path, monkeypatch, capsys):
    trace = tmp_path / "run.jsonl"
    corpus = str(tmp_path / "measured.json")
    fit_out = str(tmp_path / "fit.json")
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(trace))
    monkeypatch.setenv("FF_PERF_LEDGER", str(tmp_path / "ledger.jsonl"))
    m, inp = _tiny_model()
    _compile(m)
    m.init_layers()
    # target_platform="tpu" stands in for running on the chip: entries
    # must come back out of calibrate's TPU-filtered load
    prof = opprof.OpProfiler(m, m._telemetry, cadence=1, budget_s=30.0,
                             corpus_path=corpus, target_platform="tpu")
    prof.on_step(1)
    events.reset_active()
    with open(corpus) as f:
        n_entries = len(json.load(f))
    assert n_entries > 0

    from flexflow_tpu.tools import calibrate
    rc = calibrate.main(["--fit-only", "--out", corpus,
                         "--fit-out", fit_out, "--devices", "2",
                         "--alexnet-batch", "64", "--bench-batch", "16",
                         "--models", "alexnet", "--no-inception",
                         "--quiet"])
    assert rc in (None, 0)
    out = capsys.readouterr().out
    # calibrate loaded every opprof-written entry without complaint
    assert f"measured cache: {n_entries} entries" in out

    # and the perf ledger recorded the refit session
    led = _read_jsonl(str(tmp_path / "ledger.jsonl"))
    assert any(e.get("kind") == "calibration" and e.get("fit_only")
               for e in led)


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
