"""Pipeline parallelism: homogeneous (PipelineMLP) and general graphs.

The reference pipelines heterogeneous ops by pinning each op to a GPU
list (nmt/nmt.cc:269-308) and letting Legion overlap execution.  The TPU
equivalents under test:

  * ``PipelineMLP`` — stacked identical dense stages, config dim 1 =
    pipeline degree, GPipe schedule via ppermute ring (ops/pipeline.py);
  * ``FFModel.set_pipeline`` — per-op stage assignment for ARBITRARY
    contiguous graphs, stage subgraphs dispatched by ``lax.switch`` on
    the pipe-axis index inside a shard_map (parallel/pipeline.py
    pipeline_graph_apply), composable with data parallelism (dp x pp).

Every test pins numerics against the single-device sequential path —
the framework's "strategies change placement, not results" contract.
"""

import numpy as np
import pytest

import flexflow_tpu as ff


# ----------------------------------------------------------------------
# PipelineMLP (homogeneous stages)
# ----------------------------------------------------------------------

def _train_pipeline_mlp(pc_dims, batch=16, steps=4, num_stages=4, d=8,
                        dp_in=1):
    cfg = ff.FFConfig(batch_size=batch)
    if pc_dims is not None:
        cfg.strategies["pipe"] = ff.ParallelConfig(dims=pc_dims)
        cfg.strategies["head"] = ff.ParallelConfig(dims=(dp_in, 1))
    m = ff.FFModel(cfg)
    inp = m.create_tensor((batch, d), nchw=False)
    t = m.pipeline_mlp(inp, num_stages=num_stages, num_microbatches=4,
                       name="pipe")
    t = m.dense(t, 5, name="head")
    t = m.softmax(t, name="sm")
    m.compile(ff.SGDOptimizer(lr=0.05), "sparse_categorical_crossentropy",
              ["accuracy"])
    m.init_layers(seed=11)
    rng = np.random.default_rng(3)
    x = rng.standard_normal((batch, d), dtype=np.float32)
    y = rng.integers(0, 5, size=(batch, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y)
    for _ in range(steps):
        dl.next_batch(m)
        m.train_iteration()
    m.sync()
    return (m.get_parameter("pipe", "kernel"),
            m.get_parameter("head", "kernel"), m)


def test_pipeline_mlp_numerics_vs_sequential(devices):
    """degree-4 GPipe == single-device sequential (same init, same data)."""
    k_ref, h_ref, _ = _train_pipeline_mlp(None)
    k_pp, h_pp, m = _train_pipeline_mlp((1, 4))
    assert m.get_strategies()["pipe"].dims == (1, 4)
    np.testing.assert_allclose(k_ref, k_pp, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(h_ref, h_pp, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_pipeline_mlp_dp_x_pp(devices):
    """dp x pp composition: batch split 2 ways x 4-deep pipeline."""
    k_ref, h_ref, _ = _train_pipeline_mlp(None)
    k_pp, h_pp, _ = _train_pipeline_mlp((2, 4), dp_in=2)
    np.testing.assert_allclose(k_ref, k_pp, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(h_ref, h_pp, rtol=2e-4, atol=2e-5)


def test_pipeline_mlp_legalize_pipe_degree(devices):
    """A config whose pipe degree exceeds num_stages must legalize
    against num_stages (NOT the feature width) in both compile and
    search candidate paths."""
    cfg = ff.FFConfig(batch_size=16)
    cfg.strategies["pipe"] = ff.ParallelConfig(dims=(1, 8))
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 8), nchw=False)
    t = m.pipeline_mlp(inp, num_stages=4, name="pipe")
    m.dense(t, 5, name="head")
    m.compile(ff.SGDOptimizer(lr=0.05), "sparse_categorical_crossentropy",
              ["accuracy"])
    # gcd(8, 4 stages) = 4
    assert m.get_strategies()["pipe"].dims == (1, 4)


@pytest.mark.slow
def test_pipeline_mlp_search_candidates_legal(devices):
    """Search-generated PipelineMLP candidates are legal after the op
    legalize hook (pipe degree divides num_stages)."""
    import random
    from flexflow_tpu.simulator.search import random_parallel_config

    cfg = ff.FFConfig(batch_size=16)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 8), nchw=False)
    m.pipeline_mlp(inp, num_stages=3, name="pipe")
    op = m.ops[0]
    rng = random.Random(0)
    for _ in range(50):
        pc = op.legalize_pc(random_parallel_config(op, 8, rng))
        assert 3 % pc.dims[1] == 0, pc


# ----------------------------------------------------------------------
# General per-op stage assignment (set_pipeline)
# ----------------------------------------------------------------------

def _build_mlp(m, inp):
    t = m.dense(inp, 32, activation=ff.ActiMode.RELU, name="fc1")
    t = m.dense(t, 48, activation=ff.ActiMode.RELU, name="fc2")
    t = m.dense(t, 24, activation=ff.ActiMode.RELU, name="fc3")
    t = m.dense(t, 10, name="fc4")
    return m.softmax(t, name="sm")


def _train_general(pipeline_kw, batch=16, steps=4, seed=5):
    cfg = ff.FFConfig(batch_size=batch)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((batch, 16), nchw=False)
    _build_mlp(m, inp)
    if pipeline_kw is not None:
        m.set_pipeline(**pipeline_kw)
    m.compile(ff.SGDOptimizer(lr=0.05), "sparse_categorical_crossentropy",
              ["accuracy"])
    m.init_layers(seed=seed)
    rng = np.random.default_rng(9)
    x = rng.standard_normal((batch, 16), dtype=np.float32)
    y = rng.integers(0, 10, size=(batch, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y)
    losses = []
    for _ in range(steps):
        dl.next_batch(m)
        m.train_iteration()
    m.sync()
    m._drain_metrics()
    return (m.get_parameter("fc1", "kernel"),
            m.get_parameter("fc4", "kernel"), m)


def test_general_pipeline_heterogeneous_mlp(devices):
    """4 heterogeneous dense stages (different widths: the boundary
    buffers pad to the largest) == sequential numerics."""
    a_ref, b_ref, _ = _train_general(None)
    a_pp, b_pp, m = _train_general(dict(num_stages=4, num_microbatches=4))
    assert m._pipeline_plan is not None and m._pipeline_plan["degree"] == 4
    np.testing.assert_allclose(a_ref, a_pp, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(b_ref, b_pp, rtol=2e-4, atol=2e-5)


def test_general_pipeline_remat_numerics(devices):
    """Rematerialized ring (boundary-only residuals, the schedule
    ADR-002 picks over literal 1F1B) == plain ring == sequential, and
    large M runs: the bubble-shrinking corner the search can now
    reach."""
    a_ref, b_ref, _ = _train_general(None)
    a_rm, b_rm, m = _train_general(
        dict(num_stages=4, num_microbatches=8, remat=True))
    assert m._pipeline_plan["remat"] is True
    np.testing.assert_allclose(a_ref, a_rm, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(b_ref, b_rm, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_general_pipeline_dp_x_pp(devices):
    """dp=2 x pp=4 over the 8-device mesh, microbatches per dp shard."""
    a_ref, b_ref, _ = _train_general(None)
    a_pp, b_pp, m = _train_general(
        dict(num_stages=4, num_microbatches=4, dp_degree=2))
    np.testing.assert_allclose(a_ref, a_pp, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(b_ref, b_pp, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_general_pipeline_explicit_stages(devices):
    """Explicit per-op stage lists (the nmt.cc:269-308 placement style)."""
    a_ref, b_ref, _ = _train_general(None)
    a_pp, b_pp, m = _train_general(
        dict(stages=[["fc1", "fc2"], ["fc3", "fc4"]], num_microbatches=4))
    assert len(m._pipeline_plan["stages"]) == 2
    np.testing.assert_allclose(a_ref, a_pp, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(b_ref, b_pp, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_general_pipeline_transformer(devices):
    """2-stage transformer (attention + layernorm + ffn per stage) —
    the VERDICT's 'pipeline a real model's heterogeneous layers' case."""
    def build(pipelined):
        cfg = ff.FFConfig(batch_size=8)
        m = ff.FFModel(cfg)
        inp = m.create_tensor((8, 16, 32), nchw=False)
        t = inp
        for i in range(2):
            a = m.multihead_attention(t, num_heads=4, causal=True,
                                      name=f"attn{i}")
            t = m.add(a, t, name=f"res{i}")
            t = m.layer_norm(t, name=f"ln{i}")
            t = m.dense(t, 32, activation=ff.ActiMode.RELU, name=f"ffn{i}")
        t = m.dense(t, 11, name="head")
        m.softmax(t, name="sm")
        if pipelined:
            m.set_pipeline(stages=[["attn0", "res0", "ln0", "ffn0"],
                                   ["attn1", "res1", "ln1", "ffn1", "head"]],
                           num_microbatches=2)
        m.compile(ff.SGDOptimizer(lr=0.05),
                  "sparse_categorical_crossentropy", ["accuracy"])
        m.init_layers(seed=2)
        rng = np.random.default_rng(1)
        x = rng.standard_normal((8, 16, 32), dtype=np.float32)
        y = rng.integers(0, 11, size=(8, 16), dtype=np.int32)
        dl = ff.DataLoader(m, {inp: x}, y)
        for _ in range(3):
            dl.next_batch(m)
            m.train_iteration()
        m.sync()
        return (m.get_parameter("attn0", "wq"),
                m.get_parameter("head", "kernel"))

    wq_ref, hk_ref = build(False)
    wq_pp, hk_pp = build(True)
    np.testing.assert_allclose(wq_ref, wq_pp, rtol=3e-4, atol=3e-5)
    np.testing.assert_allclose(hk_ref, hk_pp, rtol=3e-4, atol=3e-5)


def test_general_pipeline_skip_connection_rides_hops(devices):
    """A tensor consumed two stages later rides the intermediate hop as
    part of the k-tensor ring payload (generalized planner — the old
    single-boundary rule rejected this)."""
    cfg = ff.FFConfig(batch_size=8)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((8, 16), nchw=False)
    t1 = m.dense(inp, 16, name="fc1")
    t2 = m.dense(t1, 16, name="fc2")
    m.add(t1, t2, name="skip")  # reads fc1 output from two stages back
    m.set_pipeline(stages=[["fc1"], ["fc2"], ["skip"]])
    m.compile(ff.SGDOptimizer(lr=0.05),
              "sparse_categorical_crossentropy", ["accuracy"])
    plan = m._pipeline_plan
    if plan is not None:  # ring expressible on this mesh
        # hop 1 (fc2 -> skip) carries BOTH fc1's and fc2's outputs
        assert len(plan["boundaries"][1]) == 2


def test_general_pipeline_validation(devices):
    """A non-topological stage order (a stage consuming a LATER stage's
    tensor) must be rejected."""
    cfg = ff.FFConfig(batch_size=8)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((8, 16), nchw=False)
    t1 = m.dense(inp, 16, name="fc1")
    t2 = m.dense(t1, 16, name="fc2")
    m.add(t1, t2, name="skip")
    m.set_pipeline(stages=[["fc1"], ["skip"], ["fc2"]])
    with pytest.raises(ValueError, match="contiguous|topological"):
        m.compile(ff.SGDOptimizer(lr=0.05),
                  "sparse_categorical_crossentropy", ["accuracy"])


def test_general_pipeline_single_device_fallback():
    """degree resolves but a 1-device machine runs the sequential path."""
    import jax
    from flexflow_tpu.parallel.mesh import Machine

    cfg = ff.FFConfig(batch_size=8)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((8, 16), nchw=False)
    _build_mlp(m, inp)
    m.set_pipeline(num_stages=4)
    m.compile(ff.SGDOptimizer(lr=0.05), "sparse_categorical_crossentropy",
              ["accuracy"], machine=Machine(devices=jax.devices()[:1]))
    m.init_layers(seed=0)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 16), dtype=np.float32)
    y = rng.integers(0, 10, size=(8, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y)
    dl.next_batch(m)
    m.train_iteration()
    m.sync()


def test_general_pipeline_stage_weight_placement(devices):
    """Stage weights live only on their ring slot: per-device bytes for
    the pipelined segment shrink ~1/ring vs the segment's total weights
    (reference: the mapper places op weights only on assigned GPUs,
    src/mapper/mapper.cc:33-146)."""
    _, _, m = _train_general(dict(num_stages=4, num_microbatches=4))
    pack = m._pipe_pack()
    assert pack is not None and pack["ring"] == 4
    buf = m._params["_pipe"]["buffer"]
    assert buf.shape == (4, pack["width"])
    # every dense kernel+bias is packed, none left as a plain tree leaf
    for name in ("fc1", "fc2", "fc3", "fc4"):
        assert name in pack["entries"]
        assert name not in m._params
    seg_elems = sum(n for emap in pack["entries"].values()
                    for (_, _, _, n) in emap.values())
    # per-device slice of the buffer (sharded over the pipe axes)
    shard_elems = {d: 0 for d in range(8)}
    for s in buf.addressable_shards:
        shard_elems[s.device.id] += int(np.prod(s.data.shape))
    per_dev = max(shard_elems.values())
    assert per_dev == pack["width"]          # exactly one slot row each
    assert per_dev <= seg_elems / 2          # ~1/4 of the segment here
    # and the packed values round-trip through the accessor API
    k = m.get_parameter("fc2", "kernel")
    assert k.shape == (32, 48)
    m.set_parameter("fc2", "kernel", np.zeros_like(k))
    np.testing.assert_array_equal(m.get_parameter("fc2", "kernel"), 0.0)


@pytest.mark.slow
def test_general_pipeline_uneven_boundaries(devices):
    """Conv-heavy front stage vs tiny dense back stages: boundary
    buffers pad to the largest flattened boundary (conv activations),
    numerics must still match sequential (VERDICT r2 weak #5)."""
    def run(pipeline):
        cfg = ff.FFConfig(batch_size=8)
        m = ff.FFModel(cfg)
        inp = m.create_tensor((8, 3, 12, 12))
        t = m.conv2d(inp, 8, 3, 3, 1, 1, 1, 1,
                     activation=ff.ActiMode.RELU, name="conv1")
        t = m.pool2d(t, 2, 2, 2, 2, 0, 0, name="pool1")   # (8, 6, 6) = 288
        t = m.flat(t, name="flat")
        t = m.dense(t, 16, activation=ff.ActiMode.RELU, name="fc1")  # 16
        t = m.dense(t, 5, name="fc2")                                # 5
        t = m.softmax(t, name="sm")
        if pipeline:
            # conv front stage: 432-float flattened input / 288-float
            # boundary vs a 5-float final output — maximally uneven
            m.set_pipeline(stages=[["conv1", "pool1"],
                                   ["flat", "fc1", "fc2"]],
                           num_microbatches=4, dp_degree=2)
        m.compile(ff.SGDOptimizer(lr=0.05), "sparse_categorical_crossentropy",
                  ["accuracy"])
        m.init_layers(seed=7)
        rng = np.random.default_rng(1)
        x = rng.standard_normal((8, 3, 12, 12), dtype=np.float32)
        y = rng.integers(0, 5, size=(8, 1), dtype=np.int32)
        dl = ff.DataLoader(m, {inp: x}, y)
        for _ in range(3):
            dl.next_batch(m)
            m.train_iteration()
        m.sync()
        return (m.get_parameter("conv1", "kernel"),
                m.get_parameter("fc2", "kernel"), m)

    c_ref, f_ref, _ = run(False)
    c_pp, f_pp, m = run(True)
    plan = m._pipeline_plan
    if plan is None:
        pytest.skip("degree 3 not expressible on this mesh")
    np.testing.assert_allclose(c_ref, c_pp, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(f_ref, f_pp, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_pipeline_graph_apply_bare_grad_uneven(devices):
    """jax.grad straight through pipeline_graph_apply with replicated
    params and strongly uneven boundaries — pins the wire-trimmed ring
    (payload = largest real hop, wrap dropped) against a sequential
    reference.  A per-hop-sized multi-ppermute variant broke shard_map's
    transpose sharding inference here; keep this path to one collective."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from flexflow_tpu.parallel.pipeline import pipeline_graph_apply

    P = 8
    devs = np.array(jax.devices()).reshape(P)
    mesh = Mesh(devs, ("pipe",))
    dims = [(16, 64), (64, 64), (64, 4), (4, 4), (4, 4), (4, 4), (4, 4),
            (4, 3)]
    params = [jnp.asarray(np.random.default_rng(i).standard_normal(d) * 0.1,
                          jnp.float32) for i, d in enumerate(dims)]
    fns = [lambda p, h, mi, i=i: jnp.tanh(h @ p[i]) for i in range(P)]
    in_shapes = [(d[0],) for d in dims]
    out_shapes = [(d[1],) for d in dims]
    x = jnp.asarray(np.random.default_rng(9).standard_normal((8, 16)),
                    jnp.float32)

    def loss(params, x):
        y = pipeline_graph_apply(fns, params, x, mesh, "pipe", 4,
                                 in_shapes, out_shapes)
        return jnp.sum(y ** 2)

    def loss_seq(params, x):
        h = x
        for i in range(P):
            h = jnp.tanh(h @ params[i])
        return jnp.sum(h ** 2)

    v, g = jax.value_and_grad(loss)(params, x)
    v_ref, g_ref = jax.value_and_grad(loss_seq)(params, x)
    np.testing.assert_allclose(v, v_ref, rtol=1e-5)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_generate_on_pipelined_model(devices):
    """generate() on a pipeline-packed model: the decode runner walks
    ops sequentially, so the packed stage-weight buffer unpacks to
    per-op params (FFModel._decode_params) — and must match the same
    model decoded without a pipeline."""
    from flexflow_tpu.ops.embedding import AggrMode
    from flexflow_tpu.parallel.mesh import Machine
    import jax

    B, S, V = 8, 8, 30

    def build(pipeline):
        cfg = ff.FFConfig(batch_size=B, workers_per_node=8)
        m = ff.FFModel(cfg)
        tok = m.create_tensor((B, S), name="tokens", dtype="int32",
                              nchw=False)
        x = m.embedding(tok, V, 16, aggr=AggrMode.NONE, name="embed")
        x = m.dense(x, 32, activation=ff.ActiMode.RELU, name="mlp1")
        x = m.dense(x, 32, activation=ff.ActiMode.RELU, name="mlp2")
        x = m.dense(x, 32, activation=ff.ActiMode.RELU, name="mlp3")
        x = m.dense(x, V, name="head")
        m.softmax(x, name="sm")
        if pipeline:
            m.set_pipeline(stages=[["embed", "mlp1", "mlp2"],
                                   ["mlp3", "head"]],
                           num_microbatches=4, dp_degree=2)
        m.compile(ff.SGDOptimizer(lr=0.05),
                  "sparse_categorical_crossentropy", ["accuracy"],
                  machine=Machine(jax.devices()))
        m.init_layers(seed=5)
        return m, tok

    m, tok = build(True)
    if m._pipe_pack() is None:
        pytest.skip("pipeline not expressible on this mesh")
    m2, _ = build(False)
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, V, size=(B, 3)).astype(np.int32)
    out_p = m.generate(prompt, 3)
    out_r = m2.generate(prompt, 3)
    np.testing.assert_array_equal(out_p, out_r)
