"""Expert parallelism (ExpertMLP / MoE).

No reference counterpart (SURVEY §2.3: MoE absent there; the SOAP per-op
partition abstraction is the hook).  Contracts under test: expert-dim
weight sharding over config dim 1, all_to_all-backed execution equal to
the unsharded run (strategies change placement, not results), capacity
determinism, search-space legality, and that the layer learns.
"""

import numpy as np
import pytest

import flexflow_tpu as ff


def _train(strategies, batch=16, steps=4, seed=6, experts=4):
    cfg = ff.FFConfig(batch_size=batch, strategies=dict(strategies))
    m = ff.FFModel(cfg)
    inp = m.create_tensor((batch, 8), nchw=False)
    t = m.dense(inp, 16, activation="relu", name="fc_in")
    t = m.expert_mlp(t, num_experts=experts, hidden_size=32,
                     name="moe")
    t = m.dense(t, 5, name="head")
    t = m.softmax(t, name="sm")
    m.compile(ff.SGDOptimizer(lr=0.05), "sparse_categorical_crossentropy",
              ["accuracy"])
    m.init_layers(seed=seed)
    rng = np.random.default_rng(4)
    x = rng.standard_normal((batch * 2, 8), dtype=np.float32)
    y = rng.integers(0, 5, size=(batch * 2, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y)
    for _ in range(steps):
        dl.next_batch(m)
        m.train_iteration()
    m.sync()
    return (m.get_parameter("moe", "w_in"),
            m.get_parameter("moe", "router"),
            m.get_parameter("head", "kernel"), m)


EP = {
    "fc_in": ff.ParallelConfig(dims=(2, 1)),
    "moe": ff.ParallelConfig(dims=(2, 4)),    # dp2 x ep4
    "head": ff.ParallelConfig(dims=(2, 1)),
    "sm": ff.ParallelConfig(dims=(2, 1)),
}


def test_expert_parallel_numerics_vs_default(devices):
    """dp2 x ep4 placement == default data parallelism, numerically."""
    ref = _train({})
    ep = _train(EP)
    for a, b in zip(ref[:3], ep[:3]):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_expert_weights_actually_sharded(devices):
    *_, m = _train(EP, steps=1)
    for wname in ("w_in", "w_out", "b_in", "b_out"):
        spec = m._params["moe"][wname].sharding.spec
        assert len(spec) >= 1 and spec[0] is not None, (wname, spec)
    # the router stays replicated
    assert all(s is None for s in m._params["moe"]["router"].sharding.spec)


def test_expert_degree_legalized(devices):
    """Config dim 1 is bounded by num_experts, not the tensor dim."""
    import random

    from flexflow_tpu.simulator.search import random_parallel_config

    cfg = ff.FFConfig(batch_size=8)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((8, 8), nchw=False)
    m.expert_mlp(inp, num_experts=4, hidden_size=16, name="moe")
    op = m.ops[-1]
    rng = random.Random(0)
    for _ in range(40):
        pc = op.legalize_pc(random_parallel_config(op, 8, rng))
        assert 4 % pc.dims[1] == 0, pc


def test_moe_learns(devices):
    """Loss decreases through the MoE layer (router + experts train)."""
    cfg = ff.FFConfig(batch_size=32)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((32, 8), nchw=False)
    t = m.expert_mlp(inp, num_experts=4, hidden_size=32,
                     capacity_factor=2.0, name="moe")
    t = m.add(inp, t, name="residual")   # dropped tokens pass through
    t = m.dense(t, 4, name="head")
    m.softmax(t, name="sm")
    m.compile(ff.SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy",
              ["accuracy"])
    m.init_layers(seed=2)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 8), dtype=np.float32)
    y = np.argmax(x[:, :4], axis=1).astype(np.int32)[:, None]
    dl = ff.DataLoader(m, {inp: x}, y)
    for _ in range(25):
        dl.reset()
        m.reset_metrics()
        for _ in range(dl.num_batches()):
            dl.next_batch(m)
            m.train_iteration()
    m.sync()
    acc = m.get_metrics().accuracy   # final epoch only
    assert acc > 60.0, acc


def test_capacity_and_dropped_tokens():
    """Capacity math: ceil(S/E * factor); overflowing tokens output 0."""
    from flexflow_tpu.ops.moe import ExpertMLP

    cfg = ff.FFConfig(batch_size=8)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((8, 8), nchw=False)
    m.expert_mlp(inp, num_experts=2, hidden_size=8, capacity_factor=1.0,
                 name="moe")
    op = m.ops[-1]
    assert isinstance(op, ExpertMLP)
    assert op.capacity(8) == 4
    assert op.capacity(10) == 5


def test_moe_transformer_generate(devices):
    """generate() through Switch-MoE blocks (ExpertMLP.decode routes
    droplessly — the training-time capacity cut would corrupt decode
    batches); greedy output pinned to the full-forward oracle at a size
    where the oracle's capacity also drops nothing."""
    import jax.numpy as jnp

    from flexflow_tpu.models.transformer import build_transformer

    S, V, B, P, N = 16, 40, 4, 5, 5
    cfg = ff.FFConfig(batch_size=B)
    m = ff.FFModel(cfg)
    tok, pos, _ = build_transformer(m, B, seq_length=S, num_layers=2,
                                    embed_dim=32, num_heads=4, vocab_size=V,
                                    moe_every=2, num_experts=4)
    m.compile(ff.SGDOptimizer(lr=0.01), "sparse_categorical_crossentropy",
              ["accuracy"])
    m.init_layers(seed=7)
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, V, size=(B, P)).astype(np.int32)
    out = m.generate(prompt, N)
    assert out.shape == (B, N)

    seq = prompt.copy()
    for _ in range(N):
        L = seq.shape[1]
        tf = np.zeros((B, S), np.int32)
        tf[:, :L] = seq
        posa = np.broadcast_to(np.arange(S, dtype=np.int32), (B, S)).copy()
        env, _ = m._run_graph(m._params, m._stats,
                              {f"in_{tok.guid}": jnp.asarray(tf),
                               f"in_{pos.guid}": jnp.asarray(posa)},
                              False, None)
        nxt = np.asarray(env[m.final_tensor().guid])[:, L - 1, :] \
            .argmax(-1).astype(np.int32)
        seq = np.concatenate([seq, nxt[:, None]], 1)
    np.testing.assert_array_equal(out, seq[:, P:])
