"""DLRM strategy generators (reference: src/runtime/dlrm_strategy*.cc).

The generated files must be wire-compatible, load under
reference-order semantics, and actually drive a DLRM model's compile.
"""

import shutil
import subprocess

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.config import DeviceType
from flexflow_tpu.models.dlrm import build_dlrm, synthetic_batch
from flexflow_tpu.parallel.strategy import load_strategies_from_file
from flexflow_tpu.tools import dlrm_strategy


def test_generate_matches_reference_layout(tmp_path):
    out = str(tmp_path / "s.pb")
    dlrm_strategy.main(["--gpu", "4", "--node", "2", "-o", out])
    loaded = load_strategies_from_file(out, reference_order=True)
    assert len(loaded) == 24 + 3
    # Reference: embedding i on device i % total, dims (1,1).
    assert loaded["embedding5"].device_ids == (5,)
    assert loaded["embedding5"].dims == (1, 1)
    # concat split across nodes (sample dim first after reversal).
    assert loaded["concat"].dims == (2, 1)
    assert loaded["concat"].device_ids == (0, 4)
    assert loaded["linear"].dims == (8, 1)
    assert loaded["mse_loss"].memory_types == ("hbm",)


def test_generate_hetero_places_tables_on_host(tmp_path):
    out = str(tmp_path / "h.pb")
    dlrm_strategy.main(["--hetero", "--gpu", "2", "-o", out])
    loaded = load_strategies_from_file(out, reference_order=True)
    assert loaded["embedding0"].device_type == DeviceType.CPU
    assert loaded["embedding0"].memory_types == ("host", "host", "host")
    assert loaded["linear"].dims == (2, 1)


@pytest.mark.skipif(shutil.which("protoc") is None, reason="protoc not available")
def test_generated_file_decodes_with_reference_schema(tmp_path):
    out = str(tmp_path / "s.pb")
    dlrm_strategy.main(["--gpu", "1", "--node", "1", "--emb", "4", "-o", out])
    with open(out, "rb") as f:
        dec = subprocess.run(
            ["protoc", "--proto_path=/root/reference/src/runtime",
             "--decode=FFProtoBuf.Strategy", "strategy.proto"],
            stdin=f, capture_output=True, check=True)
    text = dec.stdout.decode()
    assert 'name: "embedding0"' in text
    assert "memory_types: FBM" in text


def test_dlrm_trains_with_generated_strategy(devices, tmp_path):
    out = str(tmp_path / "s.pb")
    # 8 virtual chips on one node: MLPs DP over 8, embeddings round-robin.
    dlrm_strategy.main(["--gpu", "8", "--node", "1", "--emb", "4", "-o", out])
    sizes = [64] * 4
    cfg = ff.FFConfig(batch_size=16, compute_dtype="float32",
                      import_strategy_file=out,
                      import_strategy_reference_order=True)
    m = ff.FFModel(cfg)
    sparse, dense, p = build_dlrm(m, 16, embedding_sizes=sizes,
                                  embedding_bag_size=2,
                                  sparse_feature_size=8,
                                  mlp_bot=[8, 16, 8],
                                  mlp_top=[8 * 5, 16, 1])
    m.compile(ff.SGDOptimizer(lr=0.05), ff.LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
              [ff.MetricsType.MEAN_SQUARED_ERROR])
    emb_op = next(op for op in m.ops if op.name == "embedding1")
    assert emb_op.pc.dims == (1, 1)
    m.init_layers()
    xs, xd, y = synthetic_batch(16, sizes, 2, 8)
    m.set_batch({t: a for t, a in zip(sparse + [dense], xs + [xd])}, y)
    for _ in range(3):
        m.train_iteration()
    m.sync()


def test_hetero_strategy_file_drives_row_sparse_runtime(devices, tmp_path):
    """End-to-end parity story: a reference-wire-format HETERO strategy
    file (dlrm_strategy_hetero.cc's output shape) imported into compile
    routes the tables onto the row-sparse host-resident path — the
    file a reference user already has drives the TPU-native feature."""
    out = str(tmp_path / "h.pb")
    dlrm_strategy.main(["--hetero", "--gpu", "8", "--emb", "4", "-o", out])
    sizes = [64] * 4
    cfg = ff.FFConfig(batch_size=16, compute_dtype="float32",
                      import_strategy_file=out,
                      import_strategy_reference_order=True)
    m = ff.FFModel(cfg)
    sparse, dense, p = build_dlrm(m, 16, embedding_sizes=sizes,
                                  embedding_bag_size=2,
                                  sparse_feature_size=8,
                                  mlp_bot=[8, 16, 8],
                                  mlp_top=[8 * 5, 16, 1])
    m.compile(ff.SGDOptimizer(lr=0.05),
              ff.LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
              [ff.MetricsType.MEAN_SQUARED_ERROR])
    m.init_layers()
    # all four tables took the row-sparse host path (numpy tables)
    assert len(m._host_embed) == 4, m._host_embed
    assert isinstance(m._params["embedding0"]["weight"], np.ndarray)
    xs, xd, y = synthetic_batch(16, sizes, 2, 8)
    m.set_batch({t: a for t, a in zip(sparse + [dense], xs + [xd])}, y)
    for _ in range(3):
        m.train_iteration()
    m.sync()
