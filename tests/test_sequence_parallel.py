"""Ring attention / Ulysses sequence parallelism vs dense attention.

Reference has no sequence parallelism (SURVEY §5.7); these tests pin the
TPU-native design: sequence-sharded attention over a ring of devices must
be numerically identical to dense attention over the gathered sequence,
forward and backward, causal and not.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from flexflow_tpu.kernels.flash_attention import mha_reference
from flexflow_tpu.parallel.sequence import (
    blockwise_attention,
    sequence_parallel_attention,
)

B, H, S, D = 2, 4, 64, 16


def _qkv(seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    return mk(), mk(), mk()


def test_blockwise_matches_dense():
    q, k, v = _qkv()
    out, _ = blockwise_attention(q, k, v)
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_blockwise_causal_offsets():
    q, k, v = _qkv(1)
    # Merge of two k-blocks with offsets == causal dense over full k.
    ref = mha_reference(q, k, v, causal=True)
    half = S // 2
    from flexflow_tpu.parallel.sequence import _merge_partials
    o1, l1 = blockwise_attention(q, k[:, :, :half], v[:, :, :half],
                                 causal=True, q_offset=0, k_offset=0)
    o2, l2 = blockwise_attention(q, k[:, :, half:], v[:, :, half:],
                                 causal=True, q_offset=0, k_offset=half)
    out, _ = _merge_partials(o1, l1, o2, l2)
    np.testing.assert_allclose(out, ref, atol=1e-5)


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_sequence_parallel_matches_dense(devices, mode, causal):
    if mode == "ulysses" and causal:
        pytest.skip("ulysses+causal covered by ring; local attention is causal-safe only when aligned")
    mesh = Mesh(np.array(devices).reshape(2, 4), ("dp", "sp"))
    q, k, v = _qkv(2)
    out = sequence_parallel_attention(q, k, v, mesh, "sp", batch_axes="dp",
                                      causal=causal, mode=mode)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_ring_attention_grads_match(devices):
    mesh = Mesh(np.array(devices).reshape(2, 4), ("dp", "sp"))
    q, k, v = _qkv(3)

    def loss_ring(q, k, v):
        o = sequence_parallel_attention(q, k, v, mesh, "sp", batch_axes="dp",
                                        causal=True)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = mha_reference(q, k, v, causal=True)
        return jnp.sum(o * o)

    g = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)


def test_ring_with_flash_kernel_interpret(devices):
    """The ring's flash-kernel path (what runs on a real pod), with the
    pallas kernel in interpret mode on the CPU mesh."""
    mesh = Mesh(np.array(devices).reshape(2, 4), ("dp", "sp"))
    q, k, v = _qkv(5)
    for causal in (False, True):
        out = sequence_parallel_attention(q, k, v, mesh, "sp", batch_axes="dp",
                                          causal=causal, use_flash=True)
        ref = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_ulysses_requires_divisible_heads(devices):
    mesh = Mesh(np.array(devices).reshape(2, 4), ("dp", "sp"))
    q, k, v = _qkv(4)
    out = sequence_parallel_attention(q, k, v, mesh, "sp", batch_axes="dp",
                                      mode="ulysses")
    assert out.shape == (B, H, S, D)
