"""Branching-graph pipelines: k-tensor ring payloads.

Reference: the mapper pipelines ARBITRARY per-op placements
(nmt/nmt.cc:269-308, src/mapper/mapper.cc:33-146) — stages are not
restricted to single-boundary chains.  Under test: a DLRM-style
branching graph (multiple graph inputs, embeddings + MLPs joined by a
concat) pipelined with multiple tensors per hop, including int32 index
tensors riding later-stage hops via bitcast, matching the plain
(non-pipelined) run's numerics.
"""

import numpy as np
import pytest

import flexflow_tpu as ff


def _build_branching(pipeline: bool, batch: int = 16):
    cfg = ff.FFConfig(batch_size=batch)
    m = ff.FFModel(cfg)
    ids0 = m.create_tensor((batch, 2), dtype="int32", name="ids0")
    ids1 = m.create_tensor((batch, 2), dtype="int32", name="ids1")
    dense_in = m.create_tensor((batch, 8), name="dense", nchw=False)
    # bottom MLP on the dense features
    b = m.dense(dense_in, 16, activation="relu", name="bot0")
    b = m.dense(b, 8, activation="relu", name="bot1")
    # two embedding branches — placed in a LATER stage so their int32
    # index inputs must ride the first hop(s) of the ring
    e0 = m.embedding(ids0, 50, 8, name="emb0")
    e1 = m.embedding(ids1, 60, 8, name="emb1")
    z = m.concat([b, e0, e1], axis=1, name="cat")
    t = m.dense(z, 16, activation="relu", name="top0")
    t = m.dense(t, 4, name="top1")
    m.softmax(t, name="sm")
    if pipeline:
        m.set_pipeline(stages=[["bot0", "bot1"],
                               ["emb0", "emb1", "cat"],
                               ["top0"], ["top1"]],
                       num_microbatches=4, degree=4, dp_degree=2)
    m.compile(ff.SGDOptimizer(m, lr=0.1),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    m.init_layers(seed=3)
    rng = np.random.default_rng(0)
    x0 = rng.integers(0, 50, (batch, 2)).astype(np.int32)
    x1 = rng.integers(0, 60, (batch, 2)).astype(np.int32)
    xd = rng.standard_normal((batch, 8)).astype(np.float32)
    y = rng.integers(0, 4, (batch, 1)).astype(np.int32)
    m.set_batch({ids0: x0, ids1: x1, dense_in: xd}, y)
    return m


def test_branching_plan_has_multi_tensor_hops(devices):
    m = _build_branching(pipeline=True)
    plan = m._pipeline_plan
    assert plan is not None and plan["degree"] == 4
    # hop 0 (after the bottom MLP stage) must carry the MLP output AND
    # both untouched int32 index inputs — three tensors on the wire
    assert len(plan["boundaries"][0]) == 3
    dtypes = sorted(t.dtype for t in plan["boundaries"][0])
    assert dtypes.count("int32") == 2


def test_branching_pipeline_matches_plain(devices):
    m_plain = _build_branching(pipeline=False)
    m_pipe = _build_branching(pipeline=True)
    for _ in range(4):
        m_plain.train_iteration()
        m_pipe.train_iteration()
    m_plain.sync()
    m_pipe.sync()
    for opn, wn in [("bot0", "kernel"), ("emb0", "weight"),
                    ("emb1", "weight"), ("top1", "kernel")]:
        np.testing.assert_allclose(
            m_plain.get_parameter(opn, wn), m_pipe.get_parameter(opn, wn),
            rtol=2e-4, atol=2e-5,
            err_msg=f"{opn}/{wn} diverged between plain and pipelined run")


def test_pipeline_search_prices_branching_graph(devices):
    """The stage-assignment search must return an executable plan for a
    branching (DLRM-style) graph instead of 'n/a'."""
    from flexflow_tpu.simulator.pipeline_search import search_pipeline

    m = _build_branching(pipeline=False)
    plan = search_pipeline(m, microbatches=4)
    assert plan is not None
    assert plan["num_stages"] >= 2
    assert np.isfinite(plan["simulated_s"]) and plan["simulated_s"] > 0


@pytest.mark.slow
def test_conv_branching_pipeline_matches_plain(devices):
    """Inception-style stage: parallel CONV branches joined by a concat,
    pipelined with rank-3 activations riding flattened hops — numerics
    match the plain run (reference: inception ops pipelined by per-op
    GPU placement like any others, src/mapper/mapper.cc)."""
    def build(pipeline):
        cfg = ff.FFConfig(batch_size=8)
        m = ff.FFModel(cfg)
        inp = m.create_tensor((8, 3, 12, 12), name="img")
        t = m.conv2d(inp, 8, 3, 3, 1, 1, 1, 1, activation="relu",
                     name="stem")
        # two parallel branches off the stem (the inception_a shape)
        b1 = m.conv2d(t, 8, 1, 1, 1, 1, 0, 0, activation="relu", name="b1")
        b2 = m.conv2d(t, 8, 3, 3, 1, 1, 1, 1, activation="relu", name="b2")
        z = m.concat([b1, b2], axis=1, name="mix")
        t = m.pool2d(z, 2, 2, 2, 2, 0, 0, name="pool")
        t = m.flat(t, name="flat")
        t = m.dense(t, 4, name="head")
        m.softmax(t, name="sm")
        if pipeline:
            m.set_pipeline(stages=[["stem"], ["b1", "b2"],
                                   ["mix", "pool"], ["flat", "head"]],
                           num_microbatches=4, degree=4, dp_degree=2)
        m.compile(ff.SGDOptimizer(m, lr=0.1),
                  ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                  [ff.MetricsType.ACCURACY])
        m.init_layers(seed=5)
        rng = np.random.default_rng(1)
        x = rng.standard_normal((8, 12, 12, 3)).astype(np.float32)
        y = rng.integers(0, 4, (8, 1)).astype(np.int32)
        m.set_batch({inp: x}, y)
        for _ in range(3):
            m.train_iteration()
        m.sync()
        return m

    m_plain = build(False)
    m_pipe = build(True)
    plan = m_pipe._pipeline_plan
    assert plan is not None
    # hop 1 (branches -> mix) carries BOTH branch outputs
    assert len(plan["boundaries"][1]) == 2
    for opn, wn in [("stem", "kernel"), ("b1", "kernel"),
                    ("b2", "kernel"), ("head", "kernel")]:
        np.testing.assert_allclose(
            m_plain.get_parameter(opn, wn), m_pipe.get_parameter(opn, wn),
            rtol=3e-4, atol=3e-5,
            err_msg=f"{opn}/{wn} diverged between plain and pipelined run")
