"""Keras-like and torch-like frontend tests.

Mirror the reference's frontend test style (examples/python/keras/*:
train and assert accuracy via VerifyMetrics; python/flexflow/torch tests:
module lowering)."""

import numpy as np
import pytest

import flexflow_tpu as ffcore
from flexflow_tpu import keras
from flexflow_tpu import torch_frontend as nn_frontend
from flexflow_tpu.config import FFConfig


def test_sequential_mlp_trains_with_verify_metrics(devices):
    cfg = FFConfig(batch_size=32)
    model = keras.Sequential(config=cfg)
    model.add(keras.Input(shape=(8,)))
    model.add(keras.Dense(32, activation="relu"))
    model.add(keras.Dense(4, activation="softmax"))
    model.compile(optimizer=keras.SGD(learning_rate=0.5),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy", "sparse_categorical_crossentropy"])
    rng = np.random.default_rng(0)
    x = rng.standard_normal((256, 8), dtype=np.float32)
    y = np.argmax(x[:, :4], axis=1).astype(np.int32)
    model.fit(x, y, epochs=25, verbose=False,
              callbacks=[keras.VerifyMetrics(0.9)])
    logs = model.evaluate(x, y)
    assert logs["accuracy"] > 0.9


def test_functional_model_with_merge(devices):
    cfg = FFConfig(batch_size=16)
    in1 = keras.Input(shape=(8,))
    in2 = keras.Input(shape=(8,))
    d1 = keras.Dense(16, activation="relu")(in1)
    d2 = keras.Dense(16, activation="relu")(in2)
    merged = keras.Concatenate(axis=1)([d1, d2])
    out = keras.Dense(4, activation="softmax")(merged)
    model = keras.Model(inputs=[in1, in2], outputs=out, config=cfg)
    model.compile(optimizer=keras.Adam(learning_rate=0.01),
                  loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    rng = np.random.default_rng(1)
    x1 = rng.standard_normal((64, 8), dtype=np.float32)
    x2 = rng.standard_normal((64, 8), dtype=np.float32)
    y = rng.integers(0, 4, 64).astype(np.int32)
    model.fit([x1, x2], y, epochs=2, verbose=False)
    model.summary()


def test_sequential_cnn(devices):
    cfg = FFConfig(batch_size=16)
    model = keras.Sequential([
        keras.Conv2D(8, (3, 3), strides=(1, 1), padding="same", activation="relu"),
        keras.MaxPooling2D((2, 2)),
        keras.Flatten(),
        keras.Dense(10, activation="softmax"),
    ], config=cfg)
    model.add(keras.Input(shape=(3, 16, 16)))  # channels-first reference style
    model.compile(optimizer=keras.SGD(0.05),
                  loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    rng = np.random.default_rng(2)
    x = rng.standard_normal((32, 3, 16, 16), dtype=np.float32)
    y = rng.integers(0, 10, 32).astype(np.int32)
    model.fit(x, y, epochs=1, verbose=False)


def test_lr_scheduler(devices):
    cfg = FFConfig(batch_size=16)
    model = keras.Sequential(config=cfg)
    model.add(keras.Input(shape=(4,)))
    model.add(keras.Dense(2, activation="softmax"))
    model.compile(optimizer=keras.SGD(0.1),
                  loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    seen = []

    def sched(epoch):
        lr = 0.1 * (0.5 ** epoch)
        seen.append(lr)
        return lr

    rng = np.random.default_rng(3)
    x = rng.standard_normal((32, 4), dtype=np.float32)
    y = rng.integers(0, 2, 32).astype(np.int32)
    model.fit(x, y, epochs=3, verbose=False,
              callbacks=[keras.LearningRateScheduler(sched)])
    assert seen == [0.1, 0.05, 0.025]
    assert model.ffmodel.optimizer.lr == 0.025


def test_torch_module_lowering(devices):
    class CNN(nn_frontend.Module):
        def __init__(self):
            self.conv1 = nn_frontend.Conv2d(3, 8, 3, padding=1)
            self.relu1 = nn_frontend.ReLU()
            self.pool1 = nn_frontend.MaxPool2d(2)
            self.flat = nn_frontend.Flatten()
            self.fc1 = nn_frontend.Linear(8 * 8 * 8, 10)
            self.sm = nn_frontend.Softmax()

        def forward(self, x):
            x = self.conv1(x)
            x = self.relu1(x)
            x = self.pool1(x)
            x = self.flat(x)
            x = self.fc1(x)
            return self.sm(x)

    m = CNN()
    ff = m.build((16, 3, 16, 16), FFConfig(batch_size=16))
    # named layers: op names come from attribute names (reference *_v2 API)
    names = [op.name for op in ff.ops]
    assert "conv1" in names and "fc1" in names
    ff.compile(ffcore.SGDOptimizer(lr=0.05), "sparse_categorical_crossentropy",
               ["accuracy"])
    ff.init_layers()
    dl = ffcore.DataLoader.synthetic(ff, m._input_tensor, num_samples=16)
    dl.next_batch(ff)
    ff.train_iteration()
    ff.sync()


def test_keras_predict(devices):
    """predict returns per-sample probabilities consistent with the
    trained accuracy (argmax matches labels where evaluate says so)."""
    import numpy as np

    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.keras import Dense, Input, Sequential
    from flexflow_tpu.keras.optimizers import SGD

    rng = np.random.default_rng(0)
    x = rng.standard_normal((100, 8), dtype=np.float32)
    y = np.argmax(x[:, :4], axis=1).astype(np.int32)

    model = Sequential(config=FFConfig(batch_size=16))
    model.add(Input(shape=(8,)))
    model.add(Dense(32, activation="relu"))
    model.add(Dense(4, activation="softmax"))
    model.compile(SGD(lr=0.2), "sparse_categorical_crossentropy",
                  ["accuracy"])
    model.fit(x, y, epochs=12, verbose=False)
    probs = model.predict(x)   # 100 samples: exercises the padded tail
    assert probs.shape == (100, 4)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-3)
    acc = float((np.argmax(probs, axis=1) == y).mean())
    assert acc > 0.7, acc


def test_torch_frontend_extended_layers(devices):
    """BatchNorm2d / Dropout / AvgPool2d lower and train."""
    import numpy as np

    import flexflow_tpu as ff
    from flexflow_tpu.torch_frontend import nn

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, 8, 3, padding=1)
            self.bn1 = nn.BatchNorm2d(8)
            self.relu = nn.ReLU()
            self.pool = nn.AvgPool2d(2, 2)
            self.drop = nn.Dropout(0.1)
            self.flat = nn.Flatten()
            self.fc = nn.Linear(8 * 6 * 6, 4)
            self.sm = nn.Softmax()

        def forward(self, x):
            x = self.pool(self.relu(self.bn1(self.conv1(x))))
            return self.sm(self.fc(self.flat(self.drop(x))))

    cfg = ff.FFConfig(batch_size=8)
    net = Net()
    model = net.build((8, 3, 12, 12), cfg)
    inp = net._input_tensor
    model.compile(ff.SGDOptimizer(lr=0.05), "sparse_categorical_crossentropy",
                  ["accuracy"])
    model.init_layers(seed=2)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 3, 12, 12), dtype=np.float32)
    y = rng.integers(0, 4, size=(16, 1), dtype=np.int32)
    dl = ff.DataLoader(model, {inp: x}, y)
    for _ in range(3):
        dl.next_batch(model)
        model.train_iteration()
    model.sync()
    assert any(op._type == "BatchNorm" for op in model.ops)
    assert any(op._type == "Dropout" for op in model.ops)


def test_keras_layer_reuse_shares_weights(devices):
    """Calling the same Layer object twice in one graph shares its
    weights (classic keras semantics; reference analogue: NMT
    SharedVariable, nmt/rnn.h:37-51)."""
    cfg = FFConfig(batch_size=8)
    shared = keras.Dense(8, activation="relu", name="shared")
    inp = keras.Input(shape=(8,))
    h = shared(inp)
    h = shared(h)            # second use of the SAME layer object
    out = keras.Dense(4, activation="softmax", name="head")(h)
    model = keras.Model(inp, out, config=cfg)
    model.compile(keras.SGD(learning_rate=0.1),
                  "sparse_categorical_crossentropy", ["accuracy"])

    core = model.ffmodel
    reused = [op for op in core.ops if op.param_key == "shared"]
    assert len(reused) == 2
    assert reused[1].share_from is reused[0]
    assert not reused[1].weights  # no weights of its own

    # forward equals applying the one weight set twice
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 8), dtype=np.float32)
    kernel = core.get_parameter("shared", "kernel")
    bias = core.get_parameter("shared", "bias")
    ref = np.maximum(x @ kernel + bias, 0.0)
    ref = np.maximum(ref @ kernel + bias, 0.0)
    probs = model.predict(x)
    hk = core.get_parameter("head", "kernel")
    hb = core.get_parameter("head", "bias")
    logits = ref @ hk + hb
    want = np.exp(logits - logits.max(axis=1, keepdims=True))
    want /= want.sum(axis=1, keepdims=True)
    np.testing.assert_allclose(probs, want, rtol=2e-4, atol=2e-5)

    # gradients flow through BOTH uses into the one parameter set
    y = rng.integers(0, 4, size=(8, 1), dtype=np.int32)
    core.set_batch({model._core_inputs[0]: x}, y)
    core.train_iteration()
    core.sync()
    assert not np.allclose(core.get_parameter("shared", "kernel"), kernel)


def test_keras_nested_model_composition(devices):
    """model2(model1(x)) replays sub-model layer graphs into one core
    graph (reference: func_cifar10_cnn_nested.py)."""
    in1 = keras.Input(shape=(6,))
    t = keras.Dense(12, activation="relu", name="f1")(in1)
    feat = keras.Model(in1, t, name="feat")

    in2 = keras.Input(shape=(12,))
    t = keras.Dense(3, activation="softmax", name="h1")(in2)
    head = keras.Model(in2, t, name="head")

    in3 = keras.Input(shape=(6,))
    model = keras.Model(in3, head(feat(in3)), config=FFConfig(batch_size=8))
    model.compile(keras.SGD(learning_rate=0.2),
                  "sparse_categorical_crossentropy", ["accuracy"])
    assert model.get_layer("f1").name == "f1"
    assert model.get_layer(index=0).name == "f1"

    rng = np.random.default_rng(1)
    x = rng.standard_normal((64, 6), dtype=np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    model.fit(x, y, epochs=10, verbose=False)
    assert model.evaluate(x, y)["accuracy"] > 0.8


def test_keras_sequential_input_shape_inference(devices):
    """Sequential without an explicit Input infers it from the first
    layer's input_shape (reference frontend convention)."""
    model = keras.Sequential([
        keras.Dense(16, input_shape=(8,), activation="relu"),
        keras.Dense(2, activation="softmax"),
    ], config=FFConfig(batch_size=8))
    model.compile(keras.SGD(learning_rate=0.2),
                  "sparse_categorical_crossentropy", ["accuracy"])
    assert model.input[0].shape == (8,)
    rng = np.random.default_rng(2)
    x = rng.standard_normal((32, 8), dtype=np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    model.fit(x, y, epochs=5, verbose=False)


def test_keras_sequential_of_models(devices):
    """Sequential.add(model) composes whole models as layers
    (reference: seq_mnist_cnn_nested.py)."""
    front = keras.Sequential([
        keras.Dense(16, input_shape=(8,), activation="relu", name="fr1"),
    ], name="front")
    in2 = keras.Input(shape=(16,))
    out2 = keras.Dense(2, activation="softmax", name="bk1")(in2)
    back = keras.Model(in2, out2, name="back")

    model = keras.Sequential(config=FFConfig(batch_size=8))
    model.add(front)
    model.add(back)
    model.compile(keras.SGD(learning_rate=0.2),
                  "sparse_categorical_crossentropy", ["accuracy"])
    names = [op.name for op in model.ffmodel.ops]
    assert any("fr1" in n for n in names) and any("bk1" in n for n in names)


def test_keras_sequential_recompile_after_add(devices):
    """add() after compile marks the graph stale; a second compile
    rebuilds onto a fresh core model with fresh input tensors."""
    model = keras.Sequential([
        keras.Dense(8, input_shape=(4,), activation="relu"),
        keras.Dense(2, activation="softmax"),
    ], config=FFConfig(batch_size=8))
    model.compile(keras.SGD(learning_rate=0.2),
                  "sparse_categorical_crossentropy", ["accuracy"])
    first_core = model.ffmodel

    model.add(keras.Dense(2, activation="softmax"))
    model.compile(keras.SGD(learning_rate=0.2),
                  "sparse_categorical_crossentropy", ["accuracy"])
    assert model.ffmodel is not first_core
    assert len(model._core_inputs) == 1  # no stale input from compile #1

    rng = np.random.default_rng(3)
    x = rng.standard_normal((16, 4), dtype=np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    model.fit(x, y, epochs=2, verbose=False)


def test_keras_optax_optimizer(devices):
    """keras.Optax(optax chain) trains through the keras fit loop."""
    import optax

    model = keras.Sequential([
        keras.Dense(32, input_shape=(8,), activation="relu"),
        keras.Dense(4, activation="softmax"),
    ], config=FFConfig(batch_size=32))
    model.compile(keras.Optax(optax.adamw(5e-3)),
                  "sparse_categorical_crossentropy", ["accuracy"])
    rng = np.random.default_rng(0)
    x = rng.standard_normal((256, 8), dtype=np.float32)
    y = np.argmax(x[:, :4], axis=1).astype(np.int32)
    model.fit(x, y, epochs=20, verbose=False,
              callbacks=[keras.VerifyMetrics(0.85)])
