"""Keras-like and torch-like frontend tests.

Mirror the reference's frontend test style (examples/python/keras/*:
train and assert accuracy via VerifyMetrics; python/flexflow/torch tests:
module lowering)."""

import numpy as np
import pytest

import flexflow_tpu as ffcore
from flexflow_tpu import keras
from flexflow_tpu import torch_frontend as nn_frontend
from flexflow_tpu.config import FFConfig


def test_sequential_mlp_trains_with_verify_metrics(devices):
    cfg = FFConfig(batch_size=32)
    model = keras.Sequential(config=cfg)
    model.add(keras.Input(shape=(8,)))
    model.add(keras.Dense(32, activation="relu"))
    model.add(keras.Dense(4, activation="softmax"))
    model.compile(optimizer=keras.SGD(learning_rate=0.5),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy", "sparse_categorical_crossentropy"])
    rng = np.random.default_rng(0)
    x = rng.standard_normal((256, 8), dtype=np.float32)
    y = np.argmax(x[:, :4], axis=1).astype(np.int32)
    model.fit(x, y, epochs=25, verbose=False,
              callbacks=[keras.VerifyMetrics(0.9)])
    logs = model.evaluate(x, y)
    assert logs["accuracy"] > 0.9


def test_functional_model_with_merge(devices):
    cfg = FFConfig(batch_size=16)
    in1 = keras.Input(shape=(8,))
    in2 = keras.Input(shape=(8,))
    d1 = keras.Dense(16, activation="relu")(in1)
    d2 = keras.Dense(16, activation="relu")(in2)
    merged = keras.Concatenate(axis=1)([d1, d2])
    out = keras.Dense(4, activation="softmax")(merged)
    model = keras.Model(inputs=[in1, in2], outputs=out, config=cfg)
    model.compile(optimizer=keras.Adam(learning_rate=0.01),
                  loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    rng = np.random.default_rng(1)
    x1 = rng.standard_normal((64, 8), dtype=np.float32)
    x2 = rng.standard_normal((64, 8), dtype=np.float32)
    y = rng.integers(0, 4, 64).astype(np.int32)
    model.fit([x1, x2], y, epochs=2, verbose=False)
    model.summary()


def test_sequential_cnn(devices):
    cfg = FFConfig(batch_size=16)
    model = keras.Sequential([
        keras.Conv2D(8, (3, 3), strides=(1, 1), padding="same", activation="relu"),
        keras.MaxPooling2D((2, 2)),
        keras.Flatten(),
        keras.Dense(10, activation="softmax"),
    ], config=cfg)
    model.add(keras.Input(shape=(3, 16, 16)))  # channels-first reference style
    model.compile(optimizer=keras.SGD(0.05),
                  loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    rng = np.random.default_rng(2)
    x = rng.standard_normal((32, 3, 16, 16), dtype=np.float32)
    y = rng.integers(0, 10, 32).astype(np.int32)
    model.fit(x, y, epochs=1, verbose=False)


def test_lr_scheduler(devices):
    cfg = FFConfig(batch_size=16)
    model = keras.Sequential(config=cfg)
    model.add(keras.Input(shape=(4,)))
    model.add(keras.Dense(2, activation="softmax"))
    model.compile(optimizer=keras.SGD(0.1),
                  loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    seen = []

    def sched(epoch):
        lr = 0.1 * (0.5 ** epoch)
        seen.append(lr)
        return lr

    rng = np.random.default_rng(3)
    x = rng.standard_normal((32, 4), dtype=np.float32)
    y = rng.integers(0, 2, 32).astype(np.int32)
    model.fit(x, y, epochs=3, verbose=False,
              callbacks=[keras.LearningRateScheduler(sched)])
    assert seen == [0.1, 0.05, 0.025]
    assert model.ffmodel.optimizer.lr == 0.025


def test_torch_module_lowering(devices):
    class CNN(nn_frontend.Module):
        def __init__(self):
            self.conv1 = nn_frontend.Conv2d(3, 8, 3, padding=1)
            self.relu1 = nn_frontend.ReLU()
            self.pool1 = nn_frontend.MaxPool2d(2)
            self.flat = nn_frontend.Flatten()
            self.fc1 = nn_frontend.Linear(8 * 8 * 8, 10)
            self.sm = nn_frontend.Softmax()

        def forward(self, x):
            x = self.conv1(x)
            x = self.relu1(x)
            x = self.pool1(x)
            x = self.flat(x)
            x = self.fc1(x)
            return self.sm(x)

    m = CNN()
    ff = m.build((16, 3, 16, 16), FFConfig(batch_size=16))
    # named layers: op names come from attribute names (reference *_v2 API)
    names = [op.name for op in ff.ops]
    assert "conv1" in names and "fc1" in names
    ff.compile(ffcore.SGDOptimizer(lr=0.05), "sparse_categorical_crossentropy",
               ["accuracy"])
    ff.init_layers()
    dl = ffcore.DataLoader.synthetic(ff, m._input_tensor, num_samples=16)
    dl.next_batch(ff)
    ff.train_iteration()
    ff.sync()


def test_keras_predict(devices):
    """predict returns per-sample probabilities consistent with the
    trained accuracy (argmax matches labels where evaluate says so)."""
    import numpy as np

    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.keras import Dense, Input, Sequential
    from flexflow_tpu.keras.optimizers import SGD

    rng = np.random.default_rng(0)
    x = rng.standard_normal((100, 8), dtype=np.float32)
    y = np.argmax(x[:, :4], axis=1).astype(np.int32)

    model = Sequential(config=FFConfig(batch_size=16))
    model.add(Input(shape=(8,)))
    model.add(Dense(32, activation="relu"))
    model.add(Dense(4, activation="softmax"))
    model.compile(SGD(lr=0.2), "sparse_categorical_crossentropy",
                  ["accuracy"])
    model.fit(x, y, epochs=12, verbose=False)
    probs = model.predict(x)   # 100 samples: exercises the padded tail
    assert probs.shape == (100, 4)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-3)
    acc = float((np.argmax(probs, axis=1) == y).mean())
    assert acc > 0.7, acc


def test_torch_frontend_extended_layers(devices):
    """BatchNorm2d / Dropout / AvgPool2d lower and train."""
    import numpy as np

    import flexflow_tpu as ff
    from flexflow_tpu.torch_frontend import nn

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, 8, 3, padding=1)
            self.bn1 = nn.BatchNorm2d(8)
            self.relu = nn.ReLU()
            self.pool = nn.AvgPool2d(2, 2)
            self.drop = nn.Dropout(0.1)
            self.flat = nn.Flatten()
            self.fc = nn.Linear(8 * 6 * 6, 4)
            self.sm = nn.Softmax()

        def forward(self, x):
            x = self.pool(self.relu(self.bn1(self.conv1(x))))
            return self.sm(self.fc(self.flat(self.drop(x))))

    cfg = ff.FFConfig(batch_size=8)
    net = Net()
    model = net.build((8, 3, 12, 12), cfg)
    inp = net._input_tensor
    model.compile(ff.SGDOptimizer(lr=0.05), "sparse_categorical_crossentropy",
                  ["accuracy"])
    model.init_layers(seed=2)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 3, 12, 12), dtype=np.float32)
    y = rng.integers(0, 4, size=(16, 1), dtype=np.int32)
    dl = ff.DataLoader(model, {inp: x}, y)
    for _ in range(3):
        dl.next_batch(model)
        model.train_iteration()
    model.sync()
    assert any(op._type == "BatchNorm" for op in model.ops)
    assert any(op._type == "Dropout" for op in model.ops)
