"""Online re-parallelization (runtime/reconfigure.py).

The self-healing loop the reference cannot express (fail-stop, no
checkpointing, strategies fixed at compile — SURVEY §5.3/5.4): a seeded
chaos device loss mid-training triggers a background re-search over the
surviving mesh and a step-boundary hot-swap through the elastic
checkpoint/resume path; training runs to completion on the degraded
mesh, deterministically.  A planted post-swap regression rolls back to
the old strategy inside the probation window.  Every swap/rollback is a
``strategy_swap`` event plus an old/new ``.pb`` + sidecar pair
renderable by ``search_report --diff``.
"""

import glob
import json
import os
import threading

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.observability import events
from flexflow_tpu.parallel.strategy import strategies_fingerprint
from flexflow_tpu.runtime import reconfigure
from flexflow_tpu.runtime.elastic import (DeviceHangError, StepWatchdog,
                                          elastic_train)
from flexflow_tpu.runtime.reconfigure import (ReconfigPolicy,
                                              ReconfigurationController,
                                              maybe_controller,
                                              refit_machine_model)
from flexflow_tpu.runtime.resilience import StrategyMismatchError

RECONFIG_KEYS = ("FF_RECONFIGURE", "FF_RECONFIG_GAIN",
                 "FF_RECONFIG_PROBATION", "FF_RECONFIG_DIVERGENCE",
                 "FF_RECONFIG_SUSTAIN", "FF_RECONFIG_BUDGET",
                 "FF_RECONFIG_LAG_STEPS", "FF_RECONFIG_REGRESS",
                 "FF_RECONFIG_SEED", "FF_RECONFIG_DIR")


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for k in RECONFIG_KEYS + ("FF_CHAOS", "FF_CHAOS_SEED", "FF_TELEMETRY",
                              "FF_TELEMETRY_FILE", "FF_HEALTH"):
        monkeypatch.delenv(k, raising=False)
    events.reset_active()
    yield
    events.reset_active()


def _build(strategies=None, n_samples=48, seed=9):
    cfg = ff.FFConfig(batch_size=16)
    if strategies:
        cfg.strategies.update(strategies)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 8), nchw=False, name="input")
    t = m.dense(inp, 16, activation="relu", name="fc1")
    t = m.dense(t, 4, name="fc2")
    m.softmax(t, name="sm")
    m.compile(ff.AdamOptimizer(alpha=0.01),
              "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=seed)
    rng = np.random.default_rng(3)
    x = rng.standard_normal((n_samples, 8), dtype=np.float32)
    y = rng.integers(0, 4, size=(n_samples, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y, seed=5)
    return m, dl


def _swap_events(trace):
    out = []
    with open(trace) as f:
        for line in f:
            if line.strip() and '"strategy_swap"' in line:
                rec = json.loads(line)
                if rec.get("name") == "strategy_swap":
                    out.append(rec["attrs"])
    return out


# ---------------------------------------------------------------------------
# policy / knobs
# ---------------------------------------------------------------------------

def test_policy_from_env(monkeypatch):
    assert ReconfigPolicy.from_env() is None  # unset -> zero overhead
    monkeypatch.setenv("FF_RECONFIGURE", "1")
    monkeypatch.setenv("FF_RECONFIG_GAIN", "0.1")
    monkeypatch.setenv("FF_RECONFIG_PROBATION", "5")
    pol = ReconfigPolicy.from_env()
    assert pol.gain == 0.1 and pol.probation == 5
    assert "probation=5" in pol.describe()

    monkeypatch.setenv("FF_RECONFIG_GAIN", "lots")
    with pytest.raises(ValueError, match="FF_RECONFIG_GAIN"):
        ReconfigPolicy.from_env()  # a typo'd knob is named, not ignored
    monkeypatch.setenv("FF_RECONFIG_GAIN", "0.1")
    monkeypatch.setenv("FF_RECONFIG_REGRESS", "0.9")
    with pytest.raises(ValueError, match="FF_RECONFIG_REGRESS"):
        ReconfigPolicy.from_env()


def test_refit_quantizes_and_clamps():
    base8 = refit_machine_model(8)
    base4 = refit_machine_model(4)
    assert base8.num_devices == 8 and base4.num_devices == 4
    # CPU walls vs a TPU prediction: a ratio >> 4 clamps to the 4x bucket
    slow = refit_machine_model(4, predicted_s=1e-5, measured_s=1e-2)
    assert slow.mxu_efficiency == pytest.approx(base4.mxu_efficiency / 4.0)
    # near-1 ratios quantize to the identity bucket — per-run wall noise
    # must not flip which strategy the seeded re-search returns
    near = refit_machine_model(8, predicted_s=1.0, measured_s=1.2)
    assert near.mxu_efficiency == base8.mxu_efficiency


def test_zero_overhead_when_unset(tmp_path, monkeypatch):
    """FF_RECONFIGURE unset: no controller is even constructed — the
    loop pays one `is not None` test per step."""
    def boom(*a, **k):
        raise AssertionError("controller constructed with "
                             "FF_RECONFIGURE unset")

    monkeypatch.setattr(reconfigure, "ReconfigurationController", boom)
    assert maybe_controller(object(), None, str(tmp_path)) is None
    m, dl = _build()
    assert elastic_train(m, dl, epochs=1,
                         checkpoint_dir=str(tmp_path / "ckpt")) == 1
    assert not hasattr(m, "_reconfig")


# ---------------------------------------------------------------------------
# trigger streams
# ---------------------------------------------------------------------------

def test_divergence_observer_arms_after_sustained_windows(tmp_path):
    m, _ = _build()
    ctrl = ReconfigurationController(
        m, None, str(tmp_path),
        policy=ReconfigPolicy(divergence=1.5, sustain=2))
    div = lambda ratio: {"t": "event", "name": "sim_divergence",
                         "attrs": {"scope": "step", "ratio": ratio}}
    ctrl._observe(div(0.5))          # 2x off — window 1 of 2
    assert ctrl._pending is None
    ctrl._observe(div(1.1))          # back within threshold: streak resets
    ctrl._observe(div(0.5))
    assert ctrl._pending is None
    ctrl._observe(div(2.0))          # 2nd consecutive bad window -> armed
    assert ctrl._pending[0] == "divergence"
    # non-step scopes and other events never count
    ctrl._pending = None
    ctrl._observe({"t": "event", "name": "sim_divergence",
                   "attrs": {"scope": "epoch", "ratio": 9.0}})
    ctrl._observe({"t": "event", "name": "step", "attrs": {"ratio": 9.0}})
    assert ctrl._pending is None


def test_chaos_device_loss_and_gain_probe(tmp_path, monkeypatch):
    from flexflow_tpu.testing.chaos import ChaosMonkey

    m, _ = _build()
    m._chaos = ChaosMonkey("resharding:2=device_loss:4;"
                           "resharding:5=device_gain:4")
    ctrl = ReconfigurationController(m, None, str(tmp_path),
                                     policy=ReconfigPolicy())
    fired = []
    monkeypatch.setattr(
        ctrl, "_launch",
        lambda: (fired.append(ctrl._pending),
                 setattr(ctrl, "_pending", None)))
    for step in range(1, 7):
        m._step_count = step
        ctrl.on_step()
    assert [t for (t, _) in fired] == ["device_loss", "device_gain"]
    assert fired[0][1]["lost"] == [4, 5, 6, 7]
    assert fired[1][1]["lost"] == []


# ---------------------------------------------------------------------------
# the tentpole: seeded end-to-end hot swap on device loss
# ---------------------------------------------------------------------------

def _run_device_loss(workdir, monkeypatch):
    monkeypatch.setenv("FF_RECONFIGURE", "1")
    monkeypatch.setenv("FF_RECONFIG_BUDGET", "40")
    monkeypatch.setenv("FF_RECONFIG_LAG_STEPS", "2")
    monkeypatch.setenv("FF_CHAOS", "resharding:4=device_loss:4")
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", f"{workdir}/trace.jsonl")
    events.reset_active()
    m, dl = _build()
    ran = elastic_train(m, dl, epochs=3, checkpoint_dir=f"{workdir}/ckpt")
    events.reset_active()
    return m, ran


def test_device_loss_hot_swap_e2e_deterministic(tmp_path, monkeypatch):
    from flexflow_tpu.tools.search_report import read_sidecar, render_diff

    m1, ran1 = _run_device_loss(tmp_path / "a", monkeypatch)
    # training survived the loss of half the mesh and finished on it
    assert ran1 == 3 and m1._step_count == 9
    assert m1.machine.num_devices == 4
    k1 = np.asarray(m1._params["fc1"]["kernel"])
    assert np.isfinite(k1).all()
    # every surviving op really runs on <= 4 parts
    assert all(pc.num_parts() <= 4 for pc in m1._all_strategies().values())

    swaps = _swap_events(tmp_path / "a" / "trace.jsonl")
    applied = [s for s in swaps if s["outcome"] == "applied"]
    assert len(applied) == 1
    a = applied[0]
    assert a["trigger"] == "device_loss" and a["new_devices"] == 4
    # deterministic apply boundary: chaos fires at step 4, lag 2 -> swap
    # lands at step 6 regardless of how fast the search thread ran
    assert a["step"] == 6
    assert a["probation"] == "skipped_device_change"
    assert m1._reconfig.swaps == [(6, "device_loss", "applied")]

    # the flight recorder: old/new .pb + sidecar, diffable
    assert os.path.exists(a["old_pb"]) and os.path.exists(a["new_pb"])
    meta_old, status = read_sidecar(a["old_pb"])
    assert status == "ok" and meta_old["engine"] == "active"
    assert meta_old["num_devices"] == 8
    assert meta_old["reconfig_trigger"] == "device_loss"
    meta_new, status = read_sidecar(a["new_pb"])
    assert status == "ok" and meta_new["engine"] == "reconfig-mcmc"
    assert meta_new["num_devices"] == 4 and meta_new["budget"] == 40
    out = render_diff(a["old_pb"], a["new_pb"])
    assert "changed /" in out and "reconfig-mcmc" in out

    # run-to-run determinism given the chaos seed: bitwise-equal params
    m2, _ = _run_device_loss(tmp_path / "b", monkeypatch)
    k2 = np.asarray(m2._params["fc1"]["kernel"])
    assert np.array_equal(k1, k2)
    assert _swap_events(tmp_path / "b" / "trace.jsonl")[0]["step"] == 6


# ---------------------------------------------------------------------------
# acceptance gate + probation
# ---------------------------------------------------------------------------

def test_no_swap_below_gain_threshold(tmp_path, monkeypatch):
    monkeypatch.setenv("FF_RECONFIGURE", "1")
    monkeypatch.setenv("FF_RECONFIG_BUDGET", "40")
    monkeypatch.setenv("FF_RECONFIG_GAIN", "0.99")  # unreachable bar
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(tmp_path / "trace.jsonl"))
    events.reset_active()
    m, dl = _build()
    before = strategies_fingerprint(m._all_strategies())

    def kick(epoch, _metrics):
        if epoch == 0:
            m._reconfig.request("divergence", ratio=3.0)

    elastic_train(m, dl, epochs=3, checkpoint_dir=str(tmp_path / "ckpt"),
                  on_epoch=kick)
    swaps = _swap_events(tmp_path / "trace.jsonl")
    assert [s["outcome"] for s in swaps] == ["rejected_gain"]
    assert swaps[0]["threshold"] == 0.99
    # nothing swapped: same strategies, same mesh, no flight records
    assert strategies_fingerprint(m._all_strategies()) == before
    assert m.machine.num_devices == 8
    assert not glob.glob(str(tmp_path / "ckpt" / "reconfig" / "*.pb"))


def test_probation_rollback_on_planted_regression(tmp_path, monkeypatch):
    monkeypatch.setenv("FF_RECONFIGURE", "1")
    monkeypatch.setenv("FF_RECONFIG_BUDGET", "40")
    monkeypatch.setenv("FF_RECONFIG_LAG_STEPS", "2")
    monkeypatch.setenv("FF_RECONFIG_GAIN", "-10")   # accept any swap
    monkeypatch.setenv("FF_RECONFIG_PROBATION", "3")
    # the planted regression: after the swap lands at step 6, every
    # subsequent step is inflated by 150 ms (chaos divergence fault)
    monkeypatch.setenv("FF_CHAOS", "resharding:7=divergence:0.15")
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(tmp_path / "trace.jsonl"))
    events.reset_active()
    m, dl = _build()
    before = strategies_fingerprint(m._all_strategies())

    def kick(epoch, _metrics):
        if epoch == 0:
            m._reconfig.request("divergence", ratio=2.0)

    elastic_train(m, dl, epochs=5, checkpoint_dir=str(tmp_path / "ckpt"),
                  on_epoch=kick)
    assert m._reconfig.swaps[0] == (6, "divergence", "applied")
    assert [o for (_, _, o) in m._reconfig.swaps] == ["applied",
                                                      "rolled_back"]
    swaps = _swap_events(tmp_path / "trace.jsonl")
    rb = [s for s in swaps if s["outcome"] == "rolled_back"][0]
    assert rb["swap_step"] == 6
    assert rb["measured_post_ms"] > rb["measured_pre_ms"] * 1.3
    # rolled back TO the pre-swap strategy; training then completed
    assert strategies_fingerprint(m._all_strategies()) == before
    assert m._step_count == 15
    assert np.isfinite(np.asarray(m._params["fc1"]["kernel"])).all()
    # both halves of the swap are on disk for the flight recorder
    assert len(glob.glob(str(tmp_path / "ckpt" / "reconfig" / "*.pb"))) == 2


def test_probation_ok_keeps_new_strategy(tmp_path, monkeypatch):
    monkeypatch.setenv("FF_RECONFIGURE", "1")
    monkeypatch.setenv("FF_RECONFIG_BUDGET", "40")
    monkeypatch.setenv("FF_RECONFIG_GAIN", "-10")
    monkeypatch.setenv("FF_RECONFIG_PROBATION", "3")
    # headroom for CPU wall noise — no planted regression here
    monkeypatch.setenv("FF_RECONFIG_REGRESS", "5.0")
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(tmp_path / "trace.jsonl"))
    events.reset_active()
    m, dl = _build()

    def kick(epoch, _metrics):
        if epoch == 0:
            m._reconfig.request("divergence", ratio=2.0)

    elastic_train(m, dl, epochs=4, checkpoint_dir=str(tmp_path / "ckpt"),
                  on_epoch=kick)
    outcomes = [s["outcome"] for s in _swap_events(tmp_path / "trace.jsonl")]
    assert outcomes == ["applied", "probation_ok"]


# ---------------------------------------------------------------------------
# resume-after-reconfigure (strategy hash in resume_meta.json)
# ---------------------------------------------------------------------------

def test_resume_meta_records_strategy_hash(tmp_path):
    m, dl = _build()
    elastic_train(m, dl, epochs=1, checkpoint_dir=str(tmp_path))
    with open(tmp_path / "resume_meta.json") as f:
        meta = json.load(f)
    assert meta["strategy_hash"] == \
        strategies_fingerprint(m._all_strategies())


def test_strategy_mismatch_on_resume(tmp_path):
    m, dl = _build()
    elastic_train(m, dl, epochs=1, checkpoint_dir=str(tmp_path))

    changed = {"fc1": ff.ParallelConfig(dims=(4, 2))}  # hybrid, not dp8
    m2, dl2 = _build(strategies=changed)
    with pytest.raises(StrategyMismatchError, match="strategy"):
        elastic_train(m2, dl2, epochs=2, checkpoint_dir=str(tmp_path))
    # recompute mirrors on_steps_mismatch: warn, continue on the
    # compiled strategies (the restore itself is layout-portable)
    m3, dl3 = _build(strategies=changed)
    with pytest.warns(RuntimeWarning, match="strategy"):
        ran = elastic_train(m3, dl3, epochs=2,
                            checkpoint_dir=str(tmp_path),
                            on_strategy_mismatch="recompute")
    assert ran == 1 and m3._step_count == 6
    assert np.isfinite(np.asarray(m3._params["fc1"]["kernel"])).all()


def test_elastic_train_rejects_bad_on_strategy_mismatch(tmp_path):
    m, dl = _build()
    with pytest.raises(ValueError, match="on_strategy_mismatch"):
        elastic_train(m, dl, epochs=1, checkpoint_dir=str(tmp_path),
                      on_strategy_mismatch="explode")


# ---------------------------------------------------------------------------
# recompile-in-place (the hot-swap half, without a controller)
# ---------------------------------------------------------------------------

def test_recompile_preserves_training_state():
    m, dl = _build()
    for _ in range(3):
        dl.next_batch(m)
        m.train_iteration()
    m.sync()
    before = np.asarray(m._params["fc1"]["kernel"])
    step = m._step_count
    m.recompile(strategies={"fc1": ff.ParallelConfig(dims=(4, 2))})
    assert m._step_count == step  # live state survived, bit for bit
    assert np.array_equal(np.asarray(m._params["fc1"]["kernel"]), before)
    assert m._all_strategies()["fc1"].num_parts() == 8
    for _ in range(3):  # and the rebuilt step function still trains
        dl.next_batch(m)
        m.train_iteration()
    m.sync()
    assert m._step_count == step + 3
    assert np.isfinite(np.asarray(m._params["fc1"]["kernel"])).all()


# ---------------------------------------------------------------------------
# watchdog stranded-thread accounting (satellite)
# ---------------------------------------------------------------------------

def test_watchdog_stranded_cap_and_gauge(tmp_path, monkeypatch):
    trace = tmp_path / "trace.jsonl"
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(trace))
    events.reset_active()
    StepWatchdog._stranded.clear()
    StepWatchdog._warned_sites.clear()
    monkeypatch.setattr(StepWatchdog, "STRANDED_MAX", 4)
    release = threading.Event()
    try:
        wd = StepWatchdog(timeout=0.02)
        with pytest.warns(RuntimeWarning, match="stranded"):
            for _ in range(7):
                with pytest.raises(DeviceHangError):
                    wd.run(release.wait)
        # the bookkeeping is capped even though 7 workers are pinned
        assert len(StepWatchdog._stranded) == 4
        # one warning per distinct call site, not one per hang: the
        # single loop site above warned exactly once
        assert len(StepWatchdog._warned_sites) == 1
        with pytest.warns(RuntimeWarning, match="stranded"):
            with pytest.raises(DeviceHangError):
                wd.run(release.wait)  # a DIFFERENT call site warns again
        assert len(StepWatchdog._warned_sites) == 2
    finally:
        release.set()
        StepWatchdog._stranded.clear()
        StepWatchdog._warned_sites.clear()
        events.reset_active()
    with open(trace) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    gauges = [r for r in recs if r.get("t") == "gauge"
              and r.get("name") == "stranded_count"]
    assert len(gauges) == 8          # one per hang
    assert gauges[-1]["v"] <= 4.0    # reflects the capped pile
