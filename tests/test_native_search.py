"""Native C++ strategy-search engine vs the Python simulator.

The native engine (native/ffsearch.cpp) rebuilds the task graph and runs
the event simulation itself; these tests pin its semantics to the Python
reference implementation (flexflow_tpu/simulator/simulator.py)."""

import sys
import time

import pytest

sys.path.insert(0, ".")

from flexflow_tpu.config import ParallelConfig
from flexflow_tpu.simulator.cost_model import CostModel
from flexflow_tpu.simulator.machine import TPUMachineModel
from flexflow_tpu.simulator.native_search import (enumerate_candidates,
                                                  native_lib,
                                                  native_mcmc_search)
from flexflow_tpu.simulator.simulator import Simulator
from flexflow_tpu.tools.offline_search import build_model

pytestmark = pytest.mark.skipif(native_lib() is None,
                                reason="native search library not built")


def _setup(model_name="alexnet", nd=8):
    model = build_model(model_name, 64, nd)
    mm = TPUMachineModel(num_devices=nd)
    sim = Simulator(mm, CostModel(mm, measure=False))
    return model, mm, sim


def test_native_dp_runtime_matches_python_simulator():
    model, mm, sim = _setup()
    _, _, dp_rt = native_mcmc_search(model, budget=0, machine_model=mm,
                                     verbose=False)
    dp = {op.name: ParallelConfig.data_parallel(op.output.num_dims,
                                                mm.num_devices)
          .with_device_ids(tuple(range(mm.num_devices)))
          for op in model.ops}
    py_rt = sim.simulate_runtime(model, dp)
    assert dp_rt == pytest.approx(py_rt, rel=1e-9)


def test_native_best_runtime_consistent_with_python_simulator():
    model, mm, sim = _setup()
    best, best_rt, dp_rt = native_mcmc_search(model, budget=3000,
                                              machine_model=mm, seed=3,
                                              verbose=False)
    py_rt = sim.simulate_runtime(model, best)
    # same graph-construction semantics → same simulated time
    assert best_rt == pytest.approx(py_rt, rel=1e-9)
    assert best_rt <= dp_rt


def test_native_multi_output_shared_weight_parity():
    """NMT exercises the two graph features the native engine gained in
    round 5: multi-output ops (LSTM hidden+cell feed the decoder from
    different output slots) and weight sharing (embed_dst reads
    embed_src's table; its compute is priced with the OWNER's weights).
    The searched best must price identically in both engines."""
    model, mm, sim = _setup("nmt", nd=8)
    r = native_mcmc_search(model, budget=2000, machine_model=mm, seed=1,
                           verbose=False)
    assert r is not None, "native engine must handle multi-output graphs"
    best, best_rt, dp_rt = r
    py_rt = sim.simulate_runtime(model, best)
    assert best_rt == pytest.approx(py_rt, rel=1e-9)
    assert best_rt <= dp_rt


def test_native_warm_start():
    """init_strategies warm-starts the anneal: with budget=0 the
    dp-runtime slot is the native evaluation of exactly that plan."""
    model, mm, sim = _setup()
    best, _, _ = native_mcmc_search(model, budget=2000, machine_model=mm,
                                    seed=2, verbose=False)
    _, _, warm_rt = native_mcmc_search(model, budget=0, machine_model=mm,
                                       verbose=False, init_strategies=best)
    py_rt = sim.simulate_runtime(model, best)
    assert warm_rt == pytest.approx(py_rt, rel=1e-9)


def test_shared_weight_compute_priced_like_owner():
    """A share_with op's forward reads the shared table — its analytic
    compute cost must equal the owner's at the same config, not the
    weightless variant (the round-5 embed_dst key-collision bug)."""
    from flexflow_tpu.simulator.cost_model import CostModel

    model, mm, _ = _setup("nmt", nd=8)
    cost = CostModel(mm, measure=False)
    src = next(op for op in model.ops if op.share_from is None
               and op._type == "Embedding")
    dst = next(op for op in model.ops if op.share_from is not None)
    pc = ParallelConfig.data_parallel(src.output.num_dims, 8) \
        .with_device_ids(tuple(range(8)))
    t_src = cost._analytic(src, model._legalize_pc(src, pc), "forward")
    t_dst = cost._analytic(dst, model._legalize_pc(dst, pc), "forward")
    assert t_dst == pytest.approx(t_src, rel=1e-12)


def test_native_search_speed():
    """The native engine must beat the Python engine on iterations/sec —
    a RELATIVE bound (an absolute wall-clock cap is flaky on loaded CI
    machines; the point of the C++ engine is the speedup itself, like the
    reference's offline searcher running 250k iterations practically)."""
    from flexflow_tpu.simulator.search import mcmc_search

    model, mm, _ = _setup()
    budget_native, budget_py = 4000, 400
    t0 = time.perf_counter()
    native_mcmc_search(model, budget=budget_native, machine_model=mm,
                       verbose=False)
    native_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    mcmc_search(model, budget=budget_py, machine_model=mm, verbose=False)
    py_dt = time.perf_counter() - t0
    native_ips = budget_native / max(native_dt, 1e-9)
    py_ips = budget_py / max(py_dt, 1e-9)
    assert native_ips > 2.0 * py_ips, (native_ips, py_ips)


def test_enumerate_candidates_legal():
    model, mm, _ = _setup(nd=8)
    for op in model.ops:
        cands = enumerate_candidates(op, 8)
        assert cands, op.name
        for pc in cands:
            assert pc.num_parts() <= 8
            for d, deg in enumerate(pc.dims):
                assert op.output.dims[d] % deg == 0


def test_dlrm_native_search_runs():
    model, mm, sim = _setup("dlrm", 8)
    best, best_rt, dp_rt = native_mcmc_search(model, budget=2000,
                                              machine_model=mm, seed=1,
                                              verbose=False)
    assert best_rt <= dp_rt
    assert best_rt == pytest.approx(sim.simulate_runtime(model, best),
                                    rel=1e-9)


def test_host_placement_in_searched_space(devices):
    """Embedding ops carry a HOST-placement candidate (the reference's
    hetero CPU strategy, dlrm_strategy_hetero.cc) — the search can
    discover what the reference hand-writes; Dense ops don't."""
    import flexflow_tpu as ff
    from flexflow_tpu.config import DeviceType

    cfg = ff.FFConfig(batch_size=32, workers_per_node=8)
    m = ff.FFModel(cfg)
    ids = m.create_tensor((32, 2), dtype="int32", name="ids")
    t = m.embedding(ids, 500_000, 16, name="emb")
    t = m.dense(t, 8, name="head")
    m.softmax(t, name="sm")

    emb, head = m.ops[0], m.ops[1]
    assert any(pc.device_type == DeviceType.CPU
               for pc in enumerate_candidates(emb, 8, model=m))
    assert not any(pc.device_type == DeviceType.CPU
                   for pc in enumerate_candidates(head, 8, model=m))
    # without a model the enumeration is chip-only (calibration jobs)
    assert not any(pc.device_type == DeviceType.CPU
                   for pc in enumerate_candidates(emb, 8))

    # the native annealer consumes the enlarged space; for a 500k-row
    # table at batch 32 the host row-sparse plan dominates, and the
    # search DISCOVERS it (the reference hand-writes this placement,
    # dlrm_strategy_hetero.cc)
    r = native_mcmc_search(m, budget=600,
                           machine_model=TPUMachineModel(num_devices=8),
                           seed=0, verbose=False)
    if r is not None:  # native lib present
        best, best_rt, dp_rt = r
        assert set(best) == {"emb", "head", "sm"}
        assert best["emb"].device_type == DeviceType.CPU
        assert best_rt < dp_rt
