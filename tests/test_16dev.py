"""16-device virtual-mesh coverage (BASELINE configs #3/#4 shapes).

The main suite runs on an 8-device mesh (conftest); the device count is
baked into the XLA CPU client at init, so 16-device coverage runs in
subprocesses with their own XLA_FLAGS.  Covers the two BASELINE configs
that specify 16 cores: AlexNet-style SOAP hybrid (via dryrun_multichip)
and NMT at reference size (hidden 2048, vocab 20k — nmt/nmt.cc:34-44)
with hidden-TP LSTM over a dp4×tp4 mesh, plus hetero DLRM (8 host
row-sparse tables ahead of a dp4×pp4 remat ring).
"""

import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run16(code: str, timeout=900):
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    prologue = "import jax; jax.config.update('jax_platforms','cpu')\n"
    return subprocess.run([sys.executable, "-c", prologue + code],
                          cwd=_ROOT, env=env, timeout=timeout,
                          capture_output=True, text=True)


@pytest.mark.slow
def test_dryrun_multichip_16():
    r = _run16(
        "import importlib.util\n"
        "spec = importlib.util.spec_from_file_location('ge', '__graft_entry__.py')\n"
        "ge = importlib.util.module_from_spec(spec); spec.loader.exec_module(ge)\n"
        "ge.dryrun_multichip(16)\n")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "dryrun_multichip(16): pipeline ok" in r.stdout
    assert "remat ring" in r.stdout           # round-5 boundary-only ring
    assert "dlrm host-sparse ok" in r.stdout  # round-4 fifth graph


@pytest.mark.slow
def test_nmt_reference_size_16dev():
    """One NMT train step at the reference config (2 layers, seq 20,
    hidden=embed=2048, vocab 20480) on 16 virtual devices, dp4 x tp4."""
    r = _run16("""
import sys
sys.path.insert(0, '.')
import numpy as np
import flexflow_tpu as ff
from flexflow_tpu.models.nmt import build_nmt, synthetic_batch

B, T, H, V = 16, 20, 2048, 20480
tp = {}
for n in ('embed_src', 'embed_dst'):
    tp[n] = ff.ParallelConfig(dims=(4, 1, 4))
for n in ('enc_lstm0', 'enc_lstm1', 'dec_lstm0', 'dec_lstm1'):
    tp[n] = ff.ParallelConfig(dims=(4, 1, 4))
tp['vocab_proj'] = ff.ParallelConfig(dims=(4, 1, 4))
tp['softmax_dp'] = ff.ParallelConfig(dims=(16, 1, 1))
cfg = ff.FFConfig(batch_size=B, strategies=tp)
m = ff.FFModel(cfg)
src, dst, _ = build_nmt(m, B, seq_length=T, num_layers=2,
                        hidden_size=H, embed_size=H, vocab_size=V)
m.compile(ff.SGDOptimizer(lr=0.1), 'sparse_categorical_crossentropy',
          ['accuracy'])
m.init_layers(seed=1)
s, d, l = synthetic_batch(B, T, V)
m.set_batch({src: s, dst: d}, l)
m.train_iteration()
m.sync()
spec = m._params['enc_lstm0']['w_ih'].sharding.spec
assert len(spec) >= 2 and spec[1] is not None, spec
print('nmt16: ok', spec)
""", timeout=1500)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "nmt16: ok" in r.stdout


@pytest.mark.slow
def test_hetero_head_dlrm_16dev():
    """Reference-shaped hetero DLRM at 16 devices: 8 host-resident
    row-sparse tables lift out of a dp4 x pp4 GPipe ring (the
    dlrm_strategy_hetero.cc layout at the run_summit.sh scale)."""
    r = _run16("""
import sys
sys.path.insert(0, '.')
import numpy as np
import flexflow_tpu as ff
from flexflow_tpu.models.dlrm import build_dlrm, synthetic_batch

sizes = [20000] * 8
cfg = ff.FFConfig(batch_size=256, workers_per_node=16)
for i in range(8):
    cfg.strategies[f'embedding{i}'] = ff.ParallelConfig.host_rowsparse()
m = ff.FFModel(cfg)
sparse_in, dense_in, _ = build_dlrm(m, 256, embedding_sizes=sizes)
m.set_pipeline(num_stages=4, num_microbatches=8, dp_degree=4, remat=True)
m.compile(ff.SGDOptimizer(m, lr=0.01),
          ff.LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
          [ff.MetricsType.MEAN_SQUARED_ERROR])
m.init_layers()
assert len(m._host_embed) == 8, m._host_embed
assert m._pipeline_plan is not None
assert len(m._pipeline_plan['head']) == 8
assert m._pipeline_plan['remat'] is True
assert m._pipeline_plan['degree'] == 4 and m._pipeline_plan['dp_degree'] == 4
sparse, dense, labels = synthetic_batch(256, sizes, 1, 64)
inputs = {t: a for t, a in zip(sparse_in, sparse)}
inputs[dense_in] = dense
m.set_batch(inputs, labels)
m.train_iteration()
m.train_iteration()
m.sync()
# tables stayed host-resident through pipelined training
assert all(isinstance(m._params[f'embedding{i}']['weight'], np.ndarray)
           for i in range(8))
print('hetero16: ok, head', len(m._pipeline_plan['head']),
      'ring', m._pipeline_plan['degree'])
""", timeout=1500)
    assert r.returncode == 0, r.stderr[-2500:]
    assert "hetero16: ok" in r.stdout
