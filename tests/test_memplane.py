"""Compile plane (observability/memplane.py): AOT wrapper compile
events, retrace counting, serving bucket-ladder flatness, and the
zero-work guarantee when telemetry is off."""

import json
import sys

import numpy as np
import pytest

sys.path.insert(0, ".")

import flexflow_tpu as ff
from flexflow_tpu.observability import events, memplane


@pytest.fixture(autouse=True)
def _isolated_singleton(monkeypatch):
    monkeypatch.delenv("FF_TELEMETRY", raising=False)
    monkeypatch.delenv("FF_TELEMETRY_FILE", raising=False)
    monkeypatch.delenv("FF_MEMPLANE", raising=False)
    events.reset_active()
    yield
    events.reset_active()


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _named(recs, name):
    return [r for r in recs if r.get("name") == name]


# ---------------------------------------------------------------------------
# unit: wrapper around a plain jax.jit callable
# ---------------------------------------------------------------------------

def test_wrap_emits_compile_once_and_counts_retrace(tmp_path):
    import jax
    import jax.numpy as jnp

    log = events.EventLog(str(tmp_path / "t.jsonl"))
    plane = memplane.MemPlane(log)
    fn = plane.wrap("unit", jax.jit(lambda x: jnp.sum(x * 2.0)))

    a = np.ones((4,), np.float32)
    r1 = fn(a)
    r2 = fn(a + 1)            # same signature: cached executable, silent
    assert float(r1) == 8.0 and float(r2) == 16.0
    recs = _read_jsonl(log.path)
    dones = _named(recs, "compile_done")
    assert len(dones) == 1
    assert dones[0]["attrs"]["site"] == "unit"
    assert dones[0]["attrs"]["retrace"] is False
    assert dones[0]["attrs"]["aot"] is True
    assert dones[0]["attrs"]["wall_s"] > 0
    # XLA introspection rode along
    xm = _named(recs, "xla_memory")[0]["attrs"]
    assert xm["fingerprint"] == dones[0]["attrs"]["fingerprint"]
    assert xm["total_bytes"] >= 0
    assert _named(recs, "xla_cost")[0]["attrs"]["flops"] >= 0

    # a NEW shape at the SAME site is a retrace
    fn(np.ones((8,), np.float32))
    recs = _read_jsonl(log.path)
    dones = _named(recs, "compile_done")
    assert len(dones) == 2
    assert dones[1]["attrs"]["retrace"] is True
    assert dones[1]["attrs"]["total_retraces"] == 1
    retr = [r for r in recs if r["t"] == "counter"
            and r["name"] == "compile_retraces"]
    # 0-increment on the first compile keeps the series scrapeable;
    # the retrace increments the running total to 1
    assert [r["v"] for r in retr] == [0, 1]
    assert retr[-1]["total"] == 1
    log.close()


def test_distinct_sites_are_not_retraces(tmp_path):
    import jax
    import jax.numpy as jnp

    log = events.EventLog(str(tmp_path / "t.jsonl"))
    plane = memplane.MemPlane(log)
    f1 = plane.wrap("site_a", jax.jit(lambda x: x + 1))
    f2 = plane.wrap("site_b", jax.jit(lambda x: x * 3))
    f1(np.ones((4,), np.float32))
    f2(np.ones((4,), np.float32))
    recs = _read_jsonl(log.path)
    dones = _named(recs, "compile_done")
    assert len(dones) == 2
    assert all(d["attrs"]["retrace"] is False for d in dones)
    assert plane.compiles == 2 and plane.retraces == 0
    log.close()


def test_scalar_args_key_by_type_not_value(tmp_path):
    # jit keys weak-typed python scalars by type: calling with 2 then 3
    # must NOT retrace (the serving slot index rides this path)
    import jax

    log = events.EventLog(str(tmp_path / "t.jsonl"))
    plane = memplane.MemPlane(log)
    fn = plane.wrap("scalars", jax.jit(lambda x, i: x + i))
    fn(np.ones((4,), np.float32), 2)
    fn(np.ones((4,), np.float32), 3)
    assert len(_named(_read_jsonl(log.path), "compile_done")) == 1
    log.close()


# ---------------------------------------------------------------------------
# integration: training emits the compile plane
# ---------------------------------------------------------------------------

def _tiny_model(batch=16):
    cfg = ff.FFConfig(batch_size=batch, compute_dtype="float32")
    m = ff.FFModel(cfg)
    inp = m.create_tensor((batch, 8), nchw=False)
    t = m.dense(inp, 16, activation=ff.ActiMode.RELU)
    m.softmax(m.dense(t, 4))
    return m, inp


def _train_steps(m, inp, steps):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((m.config.batch_size * steps, 8), np.float32)
    y = rng.integers(0, 4, (m.config.batch_size * steps, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y)
    for _ in range(steps):
        dl.next_batch(m)
        m.train_iteration()


def test_training_emits_compile_plane(devices, tmp_path, monkeypatch):
    trace = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", trace)
    monkeypatch.setenv("FF_MEMPLANE", "1")
    events.reset_active()
    m, inp = _tiny_model()
    m.compile(ff.SGDOptimizer(lr=0.1),
              "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=0)
    _train_steps(m, inp, 3)
    m.sync()
    recs = _read_jsonl(trace)
    dones = _named(recs, "compile_done")
    # ONE train_step compile across 3 steps — steady state is a dict hit
    ts = [d for d in dones if d["attrs"]["site"] == "train_step"]
    assert len(ts) == 1 and ts[0]["attrs"]["retrace"] is False
    xm = [r["attrs"] for r in _named(recs, "xla_memory")
          if r["attrs"]["site"] == "train_step"]
    assert len(xm) == 1 and xm[0]["total_bytes"] > 0
    assert xm[0]["temp_bytes"] >= 0
    xc = [r["attrs"] for r in _named(recs, "xla_cost")
          if r["attrs"]["site"] == "train_step"]
    assert len(xc) == 1 and xc[0]["flops"] > 0
    # the predicted view landed in the same trace
    assert len(_named(recs, "memory_predicted")) == 1


def test_memplane_off_by_default(devices, tmp_path, monkeypatch):
    # FF_TELEMETRY alone must NOT pay for the AOT wrapper
    trace = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", trace)
    events.reset_active()
    m, inp = _tiny_model()
    m.compile(ff.SGDOptimizer(lr=0.1),
              "sparse_categorical_crossentropy", ["accuracy"])
    assert m._memplane is None
    m.init_layers(seed=0)
    _train_steps(m, inp, 1)
    m.sync()
    recs = _read_jsonl(trace)
    assert not _named(recs, "compile_done")
    # the predicted view is telemetry-gated, not FF_MEMPLANE-gated
    assert len(_named(recs, "memory_predicted")) == 1


def test_disabled_zero_event_log_calls(devices, tmp_path, monkeypatch):
    """FF_MEMPLANE=1 WITHOUT FF_TELEMETRY: no plane, no trace file, and
    literally zero event-log calls (any write would raise)."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("FF_MEMPLANE", "1")
    monkeypatch.setattr(
        events.EventLog, "_write",
        lambda self, rec: (_ for _ in ()).throw(
            AssertionError(f"event-log call while disabled: {rec}")))
    m, inp = _tiny_model()
    m.compile(ff.SGDOptimizer(lr=0.1),
              "sparse_categorical_crossentropy", ["accuracy"])
    assert m._memplane is None
    m.init_layers(seed=0)
    _train_steps(m, inp, 1)
    m.sync()
    assert not (tmp_path / "ff_trace.jsonl").exists()


# ---------------------------------------------------------------------------
# serving: the bucket ladder stays retrace-flat
# ---------------------------------------------------------------------------

def test_serving_ladder_is_retrace_flat(devices, tmp_path, monkeypatch):
    """Mixed prompt lengths across a warm {4, 8} bucket ladder: every
    serving executable (per-bucket prefill, shared step, insert) compiles
    exactly once and the cumulative retrace counter stays 0 — the silent
    failure mode this plane exists to catch."""
    from flexflow_tpu.models.transformer import build_transformer
    from flexflow_tpu.serving.engine import InferenceEngine

    trace = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", trace)
    monkeypatch.setenv("FF_MEMPLANE", "1")
    events.reset_active()
    V, max_seq = 32, 64
    m = ff.FFModel(ff.FFConfig(batch_size=4))
    build_transformer(m, 4, seq_length=max_seq, num_layers=1,
                      embed_dim=16, num_heads=2, vocab_size=V)
    m.compile(ff.SGDOptimizer(lr=0.1),
              "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=3)

    eng = InferenceEngine(m, max_batch=2, max_seq=max_seq,
                          buckets=(4, 8), max_new_tokens=4)
    assert eng._memplane is not None
    rng = np.random.default_rng(5)
    with eng:
        # two passes over the ladder: the second is fully warm
        for _ in range(2):
            hs = [eng.submit(rng.integers(0, V, size=n).astype(np.int32), 3)
                  for n in (3, 4, 5, 7, 8)]
            for h in hs:
                h.result(300)
    recs = _read_jsonl(trace)
    serve_dones = [r["attrs"] for r in _named(recs, "compile_done")
                   if r["attrs"]["site"].startswith("serve_")]
    assert serve_dones, "serving compiles did not ride the plane"
    # every serving site compiled exactly once...
    sites = [d["site"] for d in serve_dones]
    assert len(sites) == len(set(sites)), f"site recompiled: {sites}"
    # ...and nothing anywhere counted as a retrace
    assert all(d["retrace"] is False for d in serve_dones)
    assert eng._memplane.retraces == 0
    # per-bucket prefill sites are distinct by design (a shared site
    # would make the ladder LOOK like retraces)
    prefills = [s for s in sites if "prefill" in s]
    assert len(prefills) == 2
