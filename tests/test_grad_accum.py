"""Gradient accumulation (FFConfig.grad_accum_steps).

K micro-batches through a lax.scan with averaged grads and one
optimizer apply must equal the full-batch step exactly (CE loss is a
mean over samples, so the gradient is linear in the micro means).
"""

import numpy as np
import pytest

import flexflow_tpu as ff


def _train(accum, steps=3, batch=32, opt="sgd"):
    cfg = ff.FFConfig(batch_size=batch, grad_accum_steps=accum)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((batch, 12), nchw=False)
    t = m.dense(inp, 24, activation="relu", name="fc1")
    t = m.dense(t, 6, name="fc2")
    m.softmax(t, name="sm")
    optimizer = (ff.SGDOptimizer(lr=0.1, momentum=0.9) if opt == "sgd"
                 else ff.AdamOptimizer(alpha=0.01))
    m.compile(optimizer, "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=8)
    rng = np.random.default_rng(5)
    x = rng.standard_normal((batch, 12), dtype=np.float32)
    y = rng.integers(0, 6, size=(batch, 1), dtype=np.int32)
    m.set_batch({inp: x}, y)
    for _ in range(steps):
        m.train_iteration()
    m.sync()
    m._drain_metrics()
    return m


@pytest.mark.parametrize("opt", ["sgd", "adam"])
@pytest.mark.parametrize("accum", [2, 4])
def test_grad_accum_matches_full_batch(devices, accum, opt):
    ref = _train(1, opt=opt)
    acc = _train(accum, opt=opt)
    np.testing.assert_allclose(ref.get_parameter("fc1", "kernel"),
                               acc.get_parameter("fc1", "kernel"),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(ref.get_parameter("fc2", "kernel"),
                               acc.get_parameter("fc2", "kernel"),
                               rtol=2e-5, atol=2e-6)


def test_grad_accum_metrics_count_all_samples(devices):
    m = _train(4, steps=2)
    pm = m.get_metrics()
    assert pm.train_all == 2 * 32  # every micro's samples counted

def test_remat_matches_plain(devices):
    """--remat: recompute-in-backward changes memory, not numerics."""
    def run(remat):
        cfg = ff.FFConfig(batch_size=16, remat=remat)
        m = ff.FFModel(cfg)
        inp = m.create_tensor((16, 3, 12, 12))
        t = m.conv2d(inp, 8, 3, 3, 1, 1, 1, 1,
                     activation=ff.ActiMode.RELU, name="conv1")
        t = m.pool2d(t, 2, 2, 2, 2, 0, 0, name="pool1")
        t = m.flat(t, name="flat")
        t = m.dense(t, 10, name="fc")
        m.softmax(t, name="sm")
        m.compile(ff.SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy",
                  ["accuracy"])
        m.init_layers(seed=3)
        rng = np.random.default_rng(1)
        x = rng.standard_normal((16, 12, 12, 3), dtype=np.float32)  # NHWC
        y = rng.integers(0, 10, size=(16, 1), dtype=np.int32)
        m.set_batch({inp: x}, y)
        for _ in range(3):
            m.train_iteration()
        m.sync()
        return m.get_parameter("conv1", "kernel"), m.get_parameter("fc", "kernel")

    c_ref, f_ref = run(False)
    c_r, f_r = run(True)
    np.testing.assert_allclose(c_ref, c_r, rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(f_ref, f_r, rtol=1e-6, atol=1e-7)
