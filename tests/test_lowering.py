"""Whole-graph lowering (parallel/lowering.py).

Contract under test: compiling a resolved SOAP strategy into ONE jitted
step with per-op ``with_sharding_constraint``s must be *bitwise*
identical to the per-op dispatch path — strategy changes placement, not
math, and on the CPU test mesh the lowered constraints must degenerate
to exactly ``Machine.axes_for_degrees``'s assignment.  Also pinned here:
the loud FF_LOWERED knob, the CPU pjit fallback, one-compile-per-step-fn
through the memplane ledger, the provenance sidecar's lowering stamp,
and the DCN surcharge that keeps searched strategies from putting
parameter dims on the cross-host axis.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec

import flexflow_tpu as ff
from flexflow_tpu.parallel import lowering as low
from flexflow_tpu.parallel.mesh import Machine
from flexflow_tpu.simulator.machine import TPUMachineModel

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run16(code: str, timeout=900):
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    prologue = "import jax; jax.config.update('jax_platforms','cpu')\n"
    return subprocess.run([sys.executable, "-c", prologue + code],
                          cwd=_ROOT, env=env, timeout=timeout,
                          capture_output=True, text=True)


# ---------------------------------------------------------------------------
# pure helpers: layout shadow, role-aware assignment vs the mesh greedy
# ---------------------------------------------------------------------------

def test_hybrid_axis_layout_shadow():
    # 2-host v5e slice: dcn leads, ICI axes are the per-host factorization
    assert low.hybrid_axis_layout(16, 2) == (("dcn", "m0", "m1", "m2"),
                                             (2, 2, 2, 2))
    # single host: plain prime-factored mesh, larger factors first
    assert low.hybrid_axis_layout(8, 1) == (("m0", "m1", "m2"), (2, 2, 2))
    assert low.hybrid_axis_layout(12, 1) == (("m0", "m1", "m2"), (3, 2, 2))
    # host count that does not divide the device count: no dcn axis
    assert low.hybrid_axis_layout(12, 5)[0][0] != "dcn"
    assert low.hybrid_axis_layout(1, 1) == (("m0",), (1,))


def test_assign_axes_matches_machine_greedy(devices):
    """On a non-hybrid mesh (no dcn axis — this one) the role-aware
    assignment must be step-for-step the Machine greedy: the bitwise
    anchor for lowered-vs-dispatch parity on every CPU test."""
    mach = Machine(devices)
    sweep = [(8, 1), (1, 8), (2, 4), (4, 2), (2, 2, 2), (4, 1, 2, 1),
             (1, 1), (8,), (2, 1, 2, 2), (1, 4, 2)]
    for degs in sweep:
        groups, spill = low.assign_axes(mach.axis_names, mach.axis_sizes,
                                        degs)
        assert spill == (), (degs, spill)
        assert [tuple(g) for g in groups] == \
            [tuple(g) for g in mach.axes_for_degrees(degs)], degs
        assert PartitionSpec(*low.spec_entries(groups)) == \
            mach.spec_for_config(ff.ParallelConfig(dims=degs)), degs
    # inexpressible degree: same refusal, same message shape
    with pytest.raises(ValueError, match="not expressible"):
        low.assign_axes(mach.axis_names, mach.axis_sizes, (3,))
    with pytest.raises(ValueError):
        mach.axes_for_degrees([3])


def test_assign_axes_dcn_rules():
    """On the hybrid 16-dev/2-host shadow: batch takes dcn first; a
    non-sample degree stays on ICI when it can and spills (recorded)
    only when inexpressible intra-host."""
    names, sizes = low.hybrid_axis_layout(16, 2)
    # pure DP: batch spans everything, never a spill
    groups, spill = low.assign_axes(names, sizes, (16, 1))
    assert groups[0][0] == "dcn" and spill == ()
    # dp2 x tp8: batch on dcn, the whole TP split stays intra-host
    groups, spill = low.assign_axes(names, sizes, (2, 8))
    assert groups == [("dcn",), ("m0", "m1", "m2")] and spill == ()
    # tp16: the parameter dim MUST take dcn to reach 16 — recorded
    groups, spill = low.assign_axes(names, sizes, (1, 16))
    assert "dcn" in groups[1]
    assert spill == ((1, 2),)
    # model parallel 4x4: splits share dcn+ici without spilling sample
    groups, spill = low.assign_axes(names, sizes, (4, 4))
    assert spill == () and groups[0][0] == "dcn"


def test_spec_string_rendering():
    assert low.spec_string([("m0", "m1"), (), ("m2",)]) == \
        "('m0','m1'), None, 'm2'"
    assert low.spec_string([(), ()]) == "replicated"
    assert low.spec_string([("dcn",), ("m0",)]) == "'dcn', 'm0'"


# ---------------------------------------------------------------------------
# the knob: loud parse, precedence, compile()-time refusal
# ---------------------------------------------------------------------------

def test_lowered_env_knob_is_loud(monkeypatch):
    for raw, want in [("1", True), ("true", True), ("ON", True),
                      ("yes", True), ("0", False), ("False", False),
                      ("off", False), ("no", False), ("", None),
                      ("auto", None)]:
        monkeypatch.setenv("FF_LOWERED", raw)
        assert low.lowered_from_env() is want, raw
    monkeypatch.delenv("FF_LOWERED")
    assert low.lowered_from_env() is None
    monkeypatch.setenv("FF_LOWERED", "banana")
    with pytest.raises(ValueError, match="FF_LOWERED"):
        low.lowered_from_env()


def test_resolve_lowered_precedence(monkeypatch):
    monkeypatch.delenv("FF_LOWERED", raising=False)
    # auto: on exactly when the run spans nodes/processes
    assert low.resolve_lowered(None, 1, 1) is False
    assert low.resolve_lowered(None, 2, 1) is True
    assert low.resolve_lowered(None, 1, 4) is True
    # explicit config wins over auto and over the env
    monkeypatch.setenv("FF_LOWERED", "1")
    assert low.resolve_lowered(False, 2, 4) is False
    assert low.resolve_lowered(None, 1, 1) is True
    monkeypatch.setenv("FF_LOWERED", "0")
    assert low.resolve_lowered(True, 1, 1) is True
    assert low.resolve_lowered(None, 2, 1) is False
    # non-bool config values refuse loudly (a truthy "no" would flip it)
    with pytest.raises(ValueError, match="FFConfig.lowered"):
        low.resolve_lowered("yes", 1, 1)


def test_compile_refuses_garbage_env(devices, monkeypatch):
    monkeypatch.setenv("FF_LOWERED", "banana")
    m, _ = _tiny_dense()
    with pytest.raises(ValueError, match="FF_LOWERED"):
        m.compile(ff.SGDOptimizer(lr=0.1),
                  "sparse_categorical_crossentropy", ["accuracy"])


def test_cli_flags_set_config():
    cfg = ff.FFConfig(batch_size=8)
    cfg.parse_args(["--lowered"])
    assert cfg.lowered is True
    cfg.parse_args(["--no-lowered"])
    assert cfg.lowered is False


# ---------------------------------------------------------------------------
# the pjit wrapper: CPU fallback is plain jit
# ---------------------------------------------------------------------------

def test_pjit_cpu_fallback(devices):
    fn = low.pjit_with_cpu_fallback(lambda x: x * 2.0)
    x = np.arange(8, dtype=np.float32)
    np.testing.assert_array_equal(np.asarray(fn(x)), x * 2.0)
    # explicit shardings are dropped on CPU, not passed to jit
    mach = Machine(jax.devices())
    from jax.sharding import NamedSharding
    sh = NamedSharding(mach.mesh, PartitionSpec())
    fn2 = low.pjit_with_cpu_fallback(lambda x: x + 1.0, in_shardings=(sh,),
                                     out_shardings=sh)
    np.testing.assert_array_equal(np.asarray(fn2(x)), x + 1.0)


# ---------------------------------------------------------------------------
# bitwise parity: lowered step == per-op dispatch
# ---------------------------------------------------------------------------

HYBRID = {
    "conv1": ff.ParallelConfig(dims=(2, 2, 2, 1)),
    "pool1": ff.ParallelConfig(dims=(2, 2, 1, 1)),
    "flat1": ff.ParallelConfig(dims=(2, 1)),
    "fc1": ff.ParallelConfig(dims=(2, 4)),
    "fc2": ff.ParallelConfig(dims=(2, 1)),
    "softmax1": ff.ParallelConfig(dims=(8, 1)),
}


def _tiny_dense(batch=16, lowered=None):
    cfg = ff.FFConfig(batch_size=batch, compute_dtype="float32",
                      lowered=lowered)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((batch, 8), nchw=False)
    t = m.dense(inp, 16, activation=ff.ActiMode.RELU, name="fc1")
    m.softmax(m.dense(t, 4, name="fc2"), name="sm")
    return m, inp


def _train_hybrid(lowered, batch=16, steps=4, seed=3):
    cfg = ff.FFConfig(batch_size=batch, strategies=dict(HYBRID),
                      lowered=lowered)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((batch, 3, 12, 12))
    t = m.conv2d(inp, 8, 3, 3, 1, 1, 1, 1, activation=ff.ActiMode.RELU,
                 name="conv1")
    t = m.pool2d(t, 2, 2, 2, 2, 0, 0, name="pool1")
    t = m.flat(t, name="flat1")
    t = m.dense(t, 32, activation=ff.ActiMode.RELU, name="fc1")
    t = m.dense(t, 10, name="fc2")
    m.softmax(t, name="softmax1")
    m.compile(ff.SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy",
              ["accuracy", "sparse_categorical_crossentropy"])
    m.init_layers(seed=seed)
    assert (m._lowering is not None) is lowered
    rng = np.random.default_rng(7)
    x = rng.standard_normal((batch * 2, 3, 12, 12), dtype=np.float32)
    y = rng.integers(0, 10, size=(batch * 2, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y)
    for _ in range(steps):
        dl.next_batch(m)
        m.train_iteration()
    dl.next_batch(m)
    metrics = m.eval_batch()
    fc2 = np.asarray(m.get_parameter("fc2", "kernel"))
    conv1 = np.asarray(m.get_parameter("conv1", "kernel"))
    return fc2, conv1, metrics


def test_lowered_parity_hybrid_soap(devices):
    """Hybrid SOAP strategy (spatial conv + TP dense + DP tail): the
    lowered whole-graph step must match per-op dispatch bit for bit —
    train trajectory AND eval metrics."""
    fc2_a, conv_a, met_a = _train_hybrid(lowered=False)
    fc2_b, conv_b, met_b = _train_hybrid(lowered=True)
    np.testing.assert_array_equal(fc2_a, fc2_b)
    np.testing.assert_array_equal(conv_a, conv_b)
    assert met_a == met_b


def test_lowered_parity_transformer_tp(devices):
    """Transformer with head-TP attention and TP MLP: lowered == dispatch
    bitwise (the ISSUE's 'transformer' parity anchor at 8 devices)."""
    from flexflow_tpu.models.transformer import build_transformer

    strategies = {
        "attn_0": ff.ParallelConfig(dims=(2, 1, 4)),
        "mlp_up_0": ff.ParallelConfig(dims=(2, 4)),
        "mlp_down_0": ff.ParallelConfig(dims=(2, 1)),
        "lm_head": ff.ParallelConfig(dims=(2, 1, 4)),
        "softmax": ff.ParallelConfig(dims=(8, 1, 1)),
    }

    def run(lowered):
        cfg = ff.FFConfig(batch_size=8, strategies=dict(strategies),
                          lowered=lowered)
        m = ff.FFModel(cfg)
        tok, pos, _ = build_transformer(m, 8, seq_length=8, num_layers=1,
                                        embed_dim=32, num_heads=4,
                                        vocab_size=64)
        m.compile(ff.SGDOptimizer(lr=0.05),
                  "sparse_categorical_crossentropy", ["accuracy"])
        m.init_layers(seed=13)
        assert (m._lowering is not None) is lowered
        rng = np.random.default_rng(2)
        toks = rng.integers(0, 64, size=(8, 8)).astype(np.int32)
        posa = np.broadcast_to(np.arange(8, dtype=np.int32), (8, 8)).copy()
        m.set_batch({tok: toks, pos: posa},
                    np.roll(toks, -1, axis=1).astype(np.int32))
        for _ in range(2):
            m.train_iteration()
        m.sync()
        return (np.asarray(m.get_parameter("lm_head", "kernel")),
                np.asarray(m.get_parameter("mlp_up_0", "kernel")))

    lm_a, up_a = run(False)
    lm_b, up_b = run(True)
    np.testing.assert_array_equal(lm_a, lm_b)
    np.testing.assert_array_equal(up_a, up_b)


# ---------------------------------------------------------------------------
# exactly one trace+compile per step function (memplane ledger)
# ---------------------------------------------------------------------------

def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_lowered_single_compile_per_step(devices, tmp_path, monkeypatch):
    from flexflow_tpu.observability import events

    trace = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", trace)
    monkeypatch.setenv("FF_MEMPLANE", "1")
    events.reset_active()
    m, inp = _tiny_dense(lowered=True)
    m.compile(ff.SGDOptimizer(lr=0.1),
              "sparse_categorical_crossentropy", ["accuracy"])
    m.init_layers(seed=0)
    assert m._lowering is not None and m._memplane is not None
    rng = np.random.default_rng(0)
    x = rng.standard_normal((16 * 3, 8), np.float32)
    y = rng.integers(0, 4, (16 * 3, 1), dtype=np.int32)
    dl = ff.DataLoader(m, {inp: x}, y)
    for _ in range(3):
        dl.next_batch(m)
        m.train_iteration()
    m.eval_batch()
    m.eval_batch()
    m.sync()
    recs = _read_jsonl(trace)
    dones = [r for r in recs if r.get("name") == "compile_done"]
    per_site = {}
    for d in dones:
        per_site[d["attrs"]["site"]] = per_site.get(d["attrs"]["site"], 0) + 1
    # ONE compile per step function across repeated calls, zero retraces
    assert per_site.get("train_step") == 1, per_site
    assert per_site.get("eval_step") == 1, per_site
    assert m._memplane.retraces == 0
    assert all(d["attrs"]["retrace"] is False for d in dones)


# ---------------------------------------------------------------------------
# introspection: plan() and the provenance sidecar stamp
# ---------------------------------------------------------------------------

def test_lowering_plan_and_sidecar_stamp(devices, tmp_path):
    pb = str(tmp_path / "hybrid.pb")
    cfg = ff.FFConfig(batch_size=16, strategies=dict(HYBRID),
                      lowered=True, export_strategy_file=pb)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((16, 3, 12, 12))
    t = m.conv2d(inp, 8, 3, 3, 1, 1, 1, 1, name="conv1")
    t = m.pool2d(t, 2, 2, 2, 2, 0, 0, name="pool1")
    t = m.flat(t, name="flat1")
    t = m.dense(t, 32, name="fc1")
    t = m.dense(t, 10, name="fc2")
    m.softmax(t, name="softmax1")
    m.compile(ff.SGDOptimizer(lr=0.1), "sparse_categorical_crossentropy",
              ["accuracy"])
    plan = m._lowering.plan()
    # TP dense: out dim split 4 ways lands on ICI axes, roles s+p
    assert plan["fc1"]["roles"] == "sp"
    assert "m" in plan["fc1"]["spec"]
    # no dcn axis on this mesh → never a spill
    assert m._lowering.dcn_spill == {}
    with open(pb + ".meta.json") as f:
        meta = json.load(f)
    assert meta["lowered"] is True
    assert meta["lowering"]["fc1"]["spec"] == plan["fc1"]["spec"]
    # per-op attribution rows carry the resolved spec for --diff
    assert "spec" in next(iter(meta["ops"].values()))


def test_sidecar_not_lowered_by_default(devices, tmp_path):
    pb = str(tmp_path / "plain.pb")
    m, _ = _tiny_dense()
    m.config.export_strategy_file = pb
    m.compile(ff.SGDOptimizer(lr=0.1),
              "sparse_categorical_crossentropy", ["accuracy"])
    assert m._lowering is None
    with open(pb + ".meta.json") as f:
        meta = json.load(f)
    assert meta["lowered"] is False
    assert "lowering" not in meta


# ---------------------------------------------------------------------------
# DCN placement: machine-model surcharge and search pressure
# ---------------------------------------------------------------------------

def test_machine_dcn_spill_detection():
    mm = TPUMachineModel(num_devices=16)  # 2 hosts at 8 chips/host
    assert mm.num_hosts == 2
    # pure DP / dp2xtp8 / mp4x4: no non-sample dim crosses hosts
    assert mm.dcn_spill((16, 1)) == ()
    assert mm.dcn_spill((2, 8)) == ()
    assert mm.dcn_spill((4, 4)) == ()
    # tp16 forces the parameter dim across hosts
    assert mm.dcn_spill((1, 16)) == ((1, 2),)
    assert mm.dcn_spill_time((1, 16), 1e6) > 0
    assert mm.dcn_spill_time((2, 8), 1e6) == 0.0
    # single host: nothing to spill onto
    assert TPUMachineModel(num_devices=8).dcn_spill((1, 8)) == ()


def test_cost_model_charges_dcn_spill(devices):
    from flexflow_tpu.simulator.cost_model import CostModel

    m, _ = _tiny_dense(batch=64)
    op = next(o for o in m.ops if o.name == "fc1")
    mm = TPUMachineModel(num_devices=16)
    cm = CostModel(mm, cache_path=None)
    spilled = ff.ParallelConfig(dims=(1, 16))
    clean = ff.ParallelConfig(dims=(2, 8))
    assert cm._dcn_penalty(op, spilled) > 0
    assert cm._dcn_penalty(op, clean) == 0.0
    # the penalty lands in op_time (and sticks through the fast memo)
    t = cm.op_time(op, spilled, "forward")
    assert t >= cm._dcn_penalty(op, spilled)
    assert cm.op_time(op, spilled, "forward") == t


def test_search_never_spills_parameter_dims_to_dcn(devices):
    """Seeded MCMC over a 2-host simulated machine: the surcharge must
    keep every chosen config off the dcn axis for non-sample dims —
    gradient all-reduce stays the only DCN-crossing collective."""
    from flexflow_tpu.simulator.search import mcmc_search

    cfg = ff.FFConfig(batch_size=64, workers_per_node=16)
    m = ff.FFModel(cfg)
    inp = m.create_tensor((64, 64), nchw=False)
    t = m.dense(inp, 128, activation=ff.ActiMode.RELU, name="d1")
    t = m.dense(t, 64, activation=ff.ActiMode.RELU, name="d2")
    t = m.dense(t, 16, name="d3")
    m.softmax(t, name="sm")
    mm = TPUMachineModel(num_devices=16)
    res = mcmc_search(m, budget=300, seed=0, machine_model=mm,
                      verbose=False)
    assert res  # non-empty strategy map
    for name, pc in res.items():
        assert mm.dcn_spill(pc.dims) == (), (name, pc.dims)


# ---------------------------------------------------------------------------
# shipped strategies at 16 devices (subprocess: own XLA device count)
# ---------------------------------------------------------------------------

_PARITY16 = """
import sys
sys.path.insert(0, '.')
import numpy as np
import flexflow_tpu as ff

def run(lowered):
    {build}
    m.compile({compile_args})
    m.init_layers(seed=0)
    assert (m._lowering is not None) is lowered, m._lowering
    if lowered:
        assert m._lowering.dcn_spill == {{}}, m._lowering.dcn_spill
    {batch}
    for _ in range(2):
        m.train_iteration()
    m.sync()
    return [np.asarray(m.get_parameter(n, w)) for n, w in {params}]

a = run(False)
b = run(True)
for x, y in zip(a, b):
    assert np.array_equal(x, y), (x.shape, np.abs(x - y).max())
print('parity16 ok {name}')
"""


def _parity16_code(name, build, compile_args, batch, params):
    return _PARITY16.format(name=name, build=build,
                            compile_args=compile_args, batch=batch,
                            params=params)


@pytest.mark.slow
def test_shipped_alexnet16_lowered_parity():
    """strategies/alexnet_16.pb on 16 virtual devices: FF_LOWERED-style
    whole-graph step == per-op dispatch, bit for bit."""
    code = _parity16_code(
        "alexnet",
        build="""
    from flexflow_tpu.models.alexnet import build_alexnet
    cfg = ff.FFConfig(batch_size=16,
                      import_strategy_file='strategies/alexnet_16.pb',
                      lowered=lowered)
    m = ff.FFModel(cfg)
    inp, _ = build_alexnet(m, 16)""",
        compile_args="ff.SGDOptimizer(lr=0.01), "
                     "'sparse_categorical_crossentropy', ['accuracy']",
        batch="""
    rng = np.random.default_rng(5)
    x = rng.standard_normal((16, 229, 229, 3), dtype=np.float32)  # NHWC
    y = rng.integers(0, 10, size=(16, 1), dtype=np.int32)
    m.set_batch({inp: x}, y)""",
        params="[('conv1', 'kernel'), ('fc1', 'kernel'), ('fc3', 'kernel')]")
    r = _run16(code, timeout=1500)
    assert r.returncode == 0, r.stderr[-2500:]
    assert "parity16 ok alexnet" in r.stdout


@pytest.mark.slow
def test_shipped_dlrm16_lowered_parity():
    """strategies/dlrm_16.pb (embedding-dim splits + TP top MLP) on 16
    virtual devices: lowered == dispatch bitwise."""
    code = _parity16_code(
        "dlrm",
        build="""
    from flexflow_tpu.models.dlrm import build_dlrm, synthetic_batch
    sizes = [1000] * 8
    cfg = ff.FFConfig(batch_size=16,
                      import_strategy_file='strategies/dlrm_16.pb',
                      lowered=lowered)
    m = ff.FFModel(cfg)
    sparse_in, dense_in, _ = build_dlrm(m, 16, embedding_sizes=sizes)""",
        compile_args="ff.SGDOptimizer(m, lr=0.01), "
                     "ff.LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, "
                     "[ff.MetricsType.MEAN_SQUARED_ERROR]",
        batch="""
    sparse, dense, labels = synthetic_batch(16, sizes, 1, 64)
    inputs = {t: a for t, a in zip(sparse_in, sparse)}
    inputs[dense_in] = dense
    m.set_batch(inputs, labels)""",
        params="[('embedding1', 'weight'), ('Dense_114', 'kernel')]")
    r = _run16(code, timeout=1500)
    assert r.returncode == 0, r.stderr[-2500:]
    assert "parity16 ok dlrm" in r.stdout
