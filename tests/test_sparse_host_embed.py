"""Row-sparse host-resident embedding tables.

Reference: src/ops/embedding.cc:18-77 — the CPU embedding tasks touch
only the batch's rows of a host-zero-copy table; dlrm_strategy_hetero.cc
places 8x1M-row DLRM tables in host ZC memory.  Under test here: a
host-placed Embedding under plain SGD keeps its table host-side as
numpy, per-step transfer scales with the BATCH (u_max rows), not the
table, and training matches the dense device run bit-for-bit.
"""

import numpy as np
import pytest

import flexflow_tpu as ff
from flexflow_tpu.config import DeviceType


def _build(offload: bool, rows: int = 1000, momentum: float = 0.0,
           sparse=None, batch: int = 16, grad_accum: int = 1, seed: int = 11,
           fused: bool = False):
    cfg = ff.FFConfig(batch_size=batch, grad_accum_steps=grad_accum,
                      fused_optimizer=fused)
    cfg.sparse_host_embeddings = sparse
    if offload:
        cfg.strategies["emb"] = ff.ParallelConfig(
            DeviceType.CPU, (1, 1), (0,))
    m = ff.FFModel(cfg)
    ids = m.create_tensor((batch, 4), dtype="int32", name="ids")
    t = m.embedding(ids, rows, 8, name="emb")
    t = m.dense(t, 4, name="head")
    m.softmax(t, name="sm")
    m.compile(ff.SGDOptimizer(m, lr=0.1, momentum=momentum),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    m.init_layers(seed=seed)
    rng = np.random.default_rng(0)
    x = rng.integers(0, rows, (batch, 4)).astype(np.int32)
    y = (x[:, 0] % 4).astype(np.int32).reshape(-1, 1)
    m.set_batch({ids: x}, y)
    return m


def test_sparse_table_is_host_numpy(devices):
    m = _build(offload=True)
    assert "emb" in m._host_embed
    assert isinstance(m._params["emb"]["weight"], np.ndarray)
    # registered instead of the full-streaming path
    assert ("emb", "weight") not in m._offload


def test_sparse_training_matches_dense(devices):
    m_dev = _build(offload=False)
    m_host = _build(offload=True)
    assert "emb" in m_host._host_embed
    # identical init (threefry streams are platform-independent)
    np.testing.assert_array_equal(m_dev.get_parameter("emb", "weight"),
                                  m_host.get_parameter("emb", "weight"))
    for _ in range(8):
        m_dev.train_iteration()
        m_host.train_iteration()
    m_dev.sync()
    m_host.sync()
    np.testing.assert_allclose(m_dev.get_parameter("emb", "weight"),
                               m_host.get_parameter("emb", "weight"),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(m_dev.get_parameter("head", "kernel"),
                               m_host.get_parameter("head", "kernel"),
                               rtol=2e-5, atol=2e-6)
    # the table is STILL host-resident numpy after training
    assert isinstance(m_host._params["emb"]["weight"], np.ndarray)


def test_transfer_scales_with_batch_not_table(devices):
    """The device-side leaf fed into the step is (u_max, D) where u_max
    derives from the BATCH's index count — growing the table leaves the
    per-step transfer unchanged."""
    m_small = _build(offload=True, rows=500)
    m_large = _build(offload=True, rows=50_000)
    u_small = m_small._host_embed["emb"]["u_max"]
    u_large = m_large._host_embed["emb"]["u_max"]
    assert u_small == u_large  # batch-driven, not table-driven
    assert u_large * 8 < 50_000  # far below table row count
    p_in, _, batch_in, ctxs = m_large._host_embed_swap_in(
        m_large._params, m_large._opt_state, m_large._batch)
    u_hwm = m_large._host_embed["emb"]["u_hwm"]
    assert u_hwm <= u_large  # adaptive bucket never exceeds the cap
    assert p_in["emb"]["weight"].shape == (u_hwm, 8)
    m_large.train_iteration()
    m_large.sync()


def test_untouched_rows_do_not_move(devices):
    m = _build(offload=True, rows=1000)
    before = m.get_parameter("emb", "weight").copy()
    m.train_iteration()
    m.sync()
    after = m.get_parameter("emb", "weight")
    touched = np.unique(np.asarray(m._host_idx["in_0"]
                                   if "in_0" in m._host_idx else
                                   next(iter(m._host_idx.values()))))
    untouched = np.setdiff1d(np.arange(1000), touched)
    assert untouched.size > 0
    np.testing.assert_array_equal(before[untouched], after[untouched])
    # and at least one touched row moved
    assert np.abs(after[touched] - before[touched]).max() > 0


def test_momentum_defaults_to_streaming(devices):
    """Auto mode must NOT go sparse when the update rule touches every
    row (SGD momentum decays untouched rows' buffers)."""
    m = _build(offload=True, momentum=0.9)
    assert "emb" not in m._host_embed
    assert ("emb", "weight") in m._offload


def test_forced_sparse_with_momentum_is_lazy(devices):
    """sparse_host_embeddings=True opts into lazy per-touched-row
    momentum (torch SparseAdam-style): still trains, table stays host."""
    m = _build(offload=True, momentum=0.9, sparse=True)
    assert "emb" in m._host_embed
    assert isinstance(m._opt_state["v"]["emb"]["weight"], np.ndarray)
    for _ in range(3):
        m.train_iteration()
    m.sync()
    assert isinstance(m._params["emb"]["weight"], np.ndarray)


def test_sparse_checkpoint_roundtrip(tmp_path, devices):
    m = _build(offload=True)
    for _ in range(2):
        m.train_iteration()
    m.sync()
    w = m.get_parameter("emb", "weight").copy()
    path = str(tmp_path / "ck.npz")
    from flexflow_tpu.runtime.checkpoint import (load_checkpoint,
                                                 save_checkpoint)
    save_checkpoint(m, path)
    m2 = _build(offload=True)
    load_checkpoint(m2, path)
    np.testing.assert_array_equal(w, m2.get_parameter("emb", "weight"))
    # restored table is still host-resident numpy
    assert isinstance(m2._params["emb"]["weight"], np.ndarray)
    m2.train_iteration()
    m2.sync()


def test_adaptive_bucket_with_repeated_keys(devices):
    """Skewed key distributions (few unique ids — the DLRM norm) pay a
    small power-of-two bucket on the wire, not the all-unique worst
    case; the bucket grows monotonically to its high-water mark and
    never shrinks (no retrace thrash)."""
    cfg = ff.FFConfig(batch_size=16)
    cfg.strategies["emb"] = ff.ParallelConfig(DeviceType.CPU, (1, 1), (0,))
    m = ff.FFModel(cfg)
    ids = m.create_tensor((16, 4), dtype="int32", name="ids")
    t = m.embedding(ids, 1000, 8, name="emb")
    t = m.dense(t, 4, name="head")
    m.softmax(t, name="sm")
    m.compile(ff.SGDOptimizer(m, lr=0.1),
              ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
              [ff.MetricsType.ACCURACY])
    m.init_layers(seed=3)
    info = m._host_embed["emb"]
    y = np.zeros((16, 1), np.int32)
    x_skew = (np.arange(64).reshape(16, 4) % 5).astype(np.int32)  # 5 ids
    m.set_batch({ids: x_skew}, y)
    p_in, _, _, _ = m._host_embed_swap_in(m._params, m._opt_state, m._batch)
    assert info["u_max"] == 64          # all-unique worst case
    assert info["u_hwm"] == 8           # bucket for 5 uniques
    assert p_in["emb"]["weight"].shape == (8, 8)
    m.train_iteration()
    m.sync()
    # a more-unique batch grows the bucket...
    x_full = np.arange(64).reshape(16, 4).astype(np.int32)
    m.set_batch({ids: x_full}, y)
    m.train_iteration()
    m.sync()
    assert info["u_hwm"] == 64
    # ...and a skewed batch afterwards does NOT shrink it back
    m.set_batch({ids: x_skew}, y)
    m.train_iteration()
    m.sync()
    assert info["u_hwm"] == 64
    # actual unique counts are accounted for reporting
    assert info["uniq_rows_steps"] >= 3
    assert info["uniq_rows_total"] >= 5 + 64 + 5


def test_async_scatter_back_overlaps(devices):
    """update() returns at dispatch with the scatter-back in flight on
    the worker thread; every table read joins first, so results are
    identical to the synchronous path."""
    m = _build(offload=True)
    m.train_iteration()
    # the finisher was submitted (the future stays until a join point)
    assert m._he_pending is not None
    # accessor is a read barrier: joins, then sees the written rows
    w1 = m.get_parameter("emb", "weight")
    assert m._he_pending is None
    # next iteration resubmits; sync() is also a read barrier
    m.train_iteration()
    assert m._he_pending is not None
    m.sync()
    assert m._he_pending is None
    w2 = m.get_parameter("emb", "weight")
    assert np.abs(w2 - w1).max() > 0  # training progressed
    # worker exceptions surface at the join point, not silently
    from concurrent.futures import Future
    f = Future()
    f.set_exception(RuntimeError("boom"))
    m._he_pending = f
    with pytest.raises(RuntimeError, match="boom"):
        m.sync()
    assert m._he_pending is None


def test_decode_params_device_caches_host_table(devices):
    """generate()'s ids are data-dependent, so decode cannot pre-gather
    rows — _decode_params moves the host table to device ONCE per table
    version instead of re-feeding the numpy table into jit per call."""
    import jax as _jax

    m = _build(offload=True)
    dp = m._decode_params()
    assert isinstance(dp["emb"]["weight"], _jax.Array)
    assert m._decode_params()["emb"]["weight"] is dp["emb"]["weight"]
    m.train_iteration()
    m.sync()
    dp3 = m._decode_params()
    # invalidated by the step's row writes, and reflects them
    assert dp3["emb"]["weight"] is not dp["emb"]["weight"]
    np.testing.assert_array_equal(np.asarray(dp3["emb"]["weight"]),
                                  m.get_parameter("emb", "weight"))
    # the training path's table stays host-resident numpy
    assert isinstance(m._params["emb"]["weight"], np.ndarray)


def test_host_table_composes_with_pipeline(devices):
    """Hetero pipeline (reference dlrm_strategy_hetero.cc: CPU tables +
    accelerator pipeline): a host-placed row-sparse embedding is lifted
    OUT of the ring as a head op — table stays host-resident numpy, its
    output feeds stage 0 like an extra input — and numerics match the
    fully device-pipelined run."""
    def run(host):
        cfg = ff.FFConfig(batch_size=16, workers_per_node=8)
        if host:
            cfg.strategies["emb"] = ff.ParallelConfig(
                DeviceType.CPU, (1, 1), (0,))
        m = ff.FFModel(cfg)
        ids = m.create_tensor((16, 4), dtype="int32", name="ids")
        t = m.embedding(ids, 1000, 8, name="emb")
        t = m.dense(t, 24, activation="relu", name="fc1")
        t = m.dense(t, 24, activation="relu", name="fc2")
        t = m.dense(t, 4, name="head")
        m.softmax(t, name="sm")
        m.set_pipeline(num_stages=2, num_microbatches=4)
        m.compile(ff.SGDOptimizer(m, lr=0.1),
                  ff.LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                  [ff.MetricsType.ACCURACY])
        m.init_layers(seed=3)
        x = np.random.default_rng(0).integers(0, 1000, (16, 4)) \
            .astype(np.int32)
        y = (x[:, 0] % 4).astype(np.int32)[:, None]
        for _ in range(4):
            m.set_batch({ids: x}, y)
            m.train_iteration()
        m.sync()
        return m

    m_host = run(True)
    assert m_host._pipeline_plan is not None
    assert [o.name for o in m_host._pipeline_plan["head"]] == ["emb"]
    assert "emb" in m_host._host_embed  # NOT packed into the ring
    assert isinstance(m_host._params["emb"]["weight"], np.ndarray)
    m_dev = run(False)
    np.testing.assert_allclose(m_host.get_parameter("emb", "weight"),
                               m_dev.get_parameter("emb", "weight"),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(m_host.get_parameter("head", "kernel"),
                               m_dev.get_parameter("head", "kernel"),
                               rtol=2e-4, atol=2e-5)


def test_fused_optimizer_composes_with_host_table(devices):
    """fused_optimizer=True routes dense weights through the Pallas
    kernels while host tables take the plain (gather/scatter) update —
    numerics match the unfused dense run."""
    def run(host):
        m = _build(host, rows=500, fused=True)
        for _ in range(4):
            m.train_iteration()
        m.sync()
        return m

    m_h = run(True)
    assert "emb" in m_h._host_embed
    m_d = run(False)
    np.testing.assert_allclose(m_h.get_parameter("emb", "weight"),
                               m_d.get_parameter("emb", "weight"),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(m_h.get_parameter("head", "kernel"),
                               m_d.get_parameter("head", "kernel"),
                               rtol=2e-5, atol=2e-6)


def test_sync_scatter_knob(devices, monkeypatch):
    """FF_HE_SYNC_SCATTER=1 serializes the scatter-back with the step —
    the measurement knob bench.py A/Bs to report the async overlap's
    actual win."""
    m = _build(offload=True)
    monkeypatch.setenv("FF_HE_SYNC_SCATTER", "1")
    m.train_iteration()
    assert m._he_pending is None  # joined before update() returned
    monkeypatch.delenv("FF_HE_SYNC_SCATTER")
    m.train_iteration()
    assert m._he_pending is not None  # async again


def test_eval_uses_sparse_gather(devices):
    m = _build(offload=True)
    m.train_iteration()
    out = m.predict_batch()
    assert out.shape[0] == 16
    metrics = m.eval_batch()
    assert "loss" in metrics


def test_grad_accum_composes_with_sparse_table(devices):
    """K micro-batches per step: gathered rows cover the FULL batch's
    indices, grads average, one lazy row update — matches dense."""
    def build(offload):
        m = _build(offload, rows=300, grad_accum=2, seed=2)
        for _ in range(4):
            m.train_iteration()
        m.sync()
        return m

    m_dev = build(False)
    m_host = build(True)
    assert "emb" in m_host._host_embed
    np.testing.assert_allclose(m_dev.get_parameter("emb", "weight"),
                               m_host.get_parameter("emb", "weight"),
                               rtol=2e-5, atol=2e-6)
