"""NMT model parallelism: hidden-TP LSTM + vocab-sharded projection.

Reference: the NMT RNN Linear shards the hidden/vocab dim across GPUs and
sums per-shard input-gradient replicas in a dedicated backward2 launch
(nmt/rnn.h:91-158, nmt/linear.cu:594-621).  TPU-native equivalent: LSTM
gate weights shard on the 4H dim (config dim 2), the vocab projection on
its out dim, and GSPMD emits the per-step all-gather of h plus the psum
of input grads.  The contract under test: TP placement changes nothing
numerically vs data parallelism.
"""

import numpy as np

import flexflow_tpu as ff
from flexflow_tpu.models.nmt import build_nmt, synthetic_batch


def _train(strategies, batch=8, steps=3, seed=3):
    cfg = ff.FFConfig(batch_size=batch, strategies=dict(strategies))
    m = ff.FFModel(cfg)
    src, dst, _ = build_nmt(m, batch, seq_length=4, num_layers=1,
                            hidden_size=16, embed_size=16, vocab_size=32)
    m.compile(ff.SGDOptimizer(lr=0.05), "sparse_categorical_crossentropy",
              ["accuracy"])
    m.init_layers(seed=seed)
    srcs, dsts, labels = synthetic_batch(batch * 2, 4, 32)
    dl = ff.DataLoader(m, {src: srcs, dst: dsts}, labels)
    for _ in range(steps):
        dl.next_batch(m)
        m.train_iteration()
    m.sync()
    return (m.get_parameter("enc_lstm0", "w_ih"),
            m.get_parameter("enc_lstm0", "w_hh"),
            m.get_parameter("dec_lstm0", "w_ih"),
            m.get_parameter("vocab_proj", "kernel"), m)


TP = {
    "embed_src": ff.ParallelConfig(dims=(1, 1, 4)),
    "embed_dst": ff.ParallelConfig(dims=(1, 1, 4)),
    "enc_lstm0": ff.ParallelConfig(dims=(1, 1, 4)),
    "dec_lstm0": ff.ParallelConfig(dims=(1, 1, 4)),
    "vocab_proj": ff.ParallelConfig(dims=(2, 1, 4)),
    "softmax_dp": ff.ParallelConfig(dims=(2, 1, 1)),
}


def test_tp_lstm_numerics_vs_dp(devices):
    ref = _train({})
    tp = _train(TP)
    for a, b in zip(ref[:4], tp[:4]):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_tp_lstm_weights_actually_sharded(devices):
    *_, m = _train(TP, steps=1)
    spec = m._params["enc_lstm0"]["w_ih"].sharding.spec
    assert len(spec) >= 2 and spec[1] is not None, spec
    vspec = m._params["vocab_proj"]["kernel"].sharding.spec
    assert len(vspec) >= 2 and vspec[1] is not None, vspec


def test_lstm_in_search_space(devices):
    """The search proposes hidden splits for LSTM (config dim 2) and
    clamps time splits to 1."""
    import random

    from flexflow_tpu.simulator.search import (random_parallel_config,
                                               splittable_dims)

    cfg = ff.FFConfig(batch_size=8)
    m = ff.FFModel(cfg)
    src, dst, _ = build_nmt(m, 8, seq_length=4, num_layers=1,
                            hidden_size=16, embed_size=16, vocab_size=32)
    lstm = next(op for op in m.ops if op._type == "LSTM")
    assert splittable_dims(lstm) == (0, 2)
    rng = random.Random(0)
    saw_hidden = False
    for _ in range(60):
        pc = lstm.legalize_pc(random_parallel_config(lstm, 8, rng))
        assert pc.dims[1] == 1          # never splits time
        assert 16 % pc.dims[2] == 0     # hidden split divides H
        saw_hidden |= pc.dims[2] > 1
    assert saw_hidden


def test_attention_in_search_space(devices):
    """Attention proposals cover batch/seq/head-TP and legalize: the
    head split divides num_heads, never straddling a head."""
    import random

    from flexflow_tpu.models.transformer import build_transformer
    from flexflow_tpu.simulator.search import (random_parallel_config,
                                               splittable_dims)

    cfg = ff.FFConfig(batch_size=8)
    m = ff.FFModel(cfg)
    build_transformer(m, 8, seq_length=8, num_layers=1, embed_dim=32,
                      num_heads=4, vocab_size=64)
    attn = next(op for op in m.ops if op._type == "MultiHeadAttention")
    assert splittable_dims(attn) == (0, 1, 2)
    rng = random.Random(1)
    saw_seq = saw_tp = False
    for _ in range(80):
        pc = attn.legalize_pc(random_parallel_config(attn, 8, rng))
        assert 4 % pc.dims[2] == 0, pc          # head-aligned TP
        assert 8 % max(1, pc.dims[1]) == 0      # seq split divides S
        saw_seq |= pc.dims[1] > 1
        saw_tp |= pc.dims[2] > 1
    assert saw_seq and saw_tp


def test_attention_head_tp_numerics(devices):
    """Head-TP attention (config dim 2) == default placement."""
    from flexflow_tpu.models.transformer import build_transformer

    def run(strategies):
        cfg = ff.FFConfig(batch_size=8, strategies=dict(strategies))
        m = ff.FFModel(cfg)
        tok, pos, _ = build_transformer(m, 8, seq_length=8, num_layers=1,
                                        embed_dim=32, num_heads=4,
                                        vocab_size=64)
        m.compile(ff.SGDOptimizer(lr=0.05),
                  "sparse_categorical_crossentropy", ["accuracy"])
        m.init_layers(seed=13)
        rng = np.random.default_rng(2)
        toks = rng.integers(0, 64, size=(8, 8)).astype(np.int32)
        posa = np.broadcast_to(np.arange(8, dtype=np.int32), (8, 8)).copy()
        m.set_batch({tok: toks, pos: posa},
                    np.roll(toks, -1, axis=1).astype(np.int32))
        for _ in range(3):
            m.train_iteration()
        m.sync()
        return m.get_parameter("attn_0", "wq"), m

    a0, _ = run({})
    tp = {"attn_0": ff.ParallelConfig(dims=(2, 1, 4))}
    a1, m = run(tp)
    spec = m._params["attn_0"]["wq"].sharding.spec
    assert len(spec) >= 2 and spec[1] is not None, spec
    np.testing.assert_allclose(a0, a1, rtol=2e-4, atol=2e-5)
