"""Strategy-file wire-format tests.

Round-trips through our hand-rolled proto2 codec and — when protoc is
available — cross-validates against the *reference's own* strategy.proto
schema via ``protoc --decode/--encode``, proving byte-level compatibility
without a protobuf runtime dependency.
"""

import shutil
import subprocess

import pytest

from flexflow_tpu.config import DeviceType, ParallelConfig
from flexflow_tpu.parallel.strategy import (load_strategies_from_file,
                                            save_strategies_to_file)

REF_PROTO = "/root/reference/src/runtime/strategy.proto"


def sample_strategies():
    return {
        "conv1": ParallelConfig(DeviceType.TPU, (4, 1, 2, 1), tuple(range(8))),
        "dense_1": ParallelConfig(DeviceType.TPU, (2, 4), tuple(range(8))),
        "embed_cpu": ParallelConfig(DeviceType.CPU, (1, 1), (0,)),
    }


def test_round_trip(tmp_path):
    path = str(tmp_path / "strategy.pb")
    strategies = sample_strategies()
    save_strategies_to_file(path, strategies)
    loaded = load_strategies_from_file(path)
    assert set(loaded) == set(strategies)
    for k in strategies:
        assert loaded[k].dims == strategies[k].dims
        assert loaded[k].device_ids == strategies[k].device_ids
        assert loaded[k].device_type == strategies[k].device_type


def test_reference_order_import(tmp_path):
    path = str(tmp_path / "s.pb")
    save_strategies_to_file(path, {"op": ParallelConfig(DeviceType.TPU, (1, 2, 1, 4), (0,) * 8)})
    loaded = load_strategies_from_file(path, reference_order=True)
    assert loaded["op"].dims == (4, 1, 2, 1)


@pytest.mark.skipif(shutil.which("protoc") is None, reason="protoc not available")
def test_wire_compatible_with_reference_proto(tmp_path):
    path = str(tmp_path / "strategy.pb")
    save_strategies_to_file(path, sample_strategies())
    # Decode our bytes with the reference schema.
    with open(path, "rb") as f:
        out = subprocess.run(
            ["protoc", f"--proto_path=/root/reference/src/runtime",
             "--decode=FFProtoBuf.Strategy", "strategy.proto"],
            stdin=f, capture_output=True, check=True)
    text = out.stdout.decode()
    assert 'name: "conv1"' in text
    assert "dims: 4" in text and "device_type: CPU" in text

    # Re-encode the decoded text with protoc and parse with our codec.
    enc = subprocess.run(
        ["protoc", f"--proto_path=/root/reference/src/runtime",
         "--encode=FFProtoBuf.Strategy", "strategy.proto"],
        input=out.stdout, capture_output=True, check=True)
    path2 = str(tmp_path / "re.pb")
    with open(path2, "wb") as f:
        f.write(enc.stdout)
    loaded = load_strategies_from_file(path2)
    orig = sample_strategies()
    assert {k: (v.dims, v.device_ids) for k, v in loaded.items()} == \
           {k: (v.dims, v.device_ids) for k, v in orig.items()}
