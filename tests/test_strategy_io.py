"""Strategy-file wire-format tests.

Round-trips through our hand-rolled proto2 codec and — when protoc is
available — cross-validates against the *reference's own* strategy.proto
schema via ``protoc --decode/--encode``, proving byte-level compatibility
without a protobuf runtime dependency.  Also covers the provenance
sidecar: round-trip, hash staleness, corrupt-sidecar tolerance, and the
``strategy_provenance`` event a traced load emits.
"""

import json
import shutil
import subprocess

import pytest

from flexflow_tpu.config import DeviceType, ParallelConfig
from flexflow_tpu.observability import events
from flexflow_tpu.parallel.strategy import (load_strategies_from_file,
                                            read_provenance,
                                            save_strategies_to_file,
                                            sidecar_path,
                                            write_provenance)

REF_PROTO = "/root/reference/src/runtime/strategy.proto"


def sample_strategies():
    return {
        "conv1": ParallelConfig(DeviceType.TPU, (4, 1, 2, 1), tuple(range(8))),
        "dense_1": ParallelConfig(DeviceType.TPU, (2, 4), tuple(range(8))),
        "embed_cpu": ParallelConfig(DeviceType.CPU, (1, 1), (0,)),
    }


def test_round_trip(tmp_path):
    path = str(tmp_path / "strategy.pb")
    strategies = sample_strategies()
    save_strategies_to_file(path, strategies)
    loaded = load_strategies_from_file(path)
    assert set(loaded) == set(strategies)
    for k in strategies:
        assert loaded[k].dims == strategies[k].dims
        assert loaded[k].device_ids == strategies[k].device_ids
        assert loaded[k].device_type == strategies[k].device_type


def test_reference_order_import(tmp_path):
    path = str(tmp_path / "s.pb")
    save_strategies_to_file(path, {"op": ParallelConfig(DeviceType.TPU, (1, 2, 1, 4), (0,) * 8)})
    loaded = load_strategies_from_file(path, reference_order=True)
    assert loaded["op"].dims == (4, 1, 2, 1)


# ---------------------------------------------------------------------------
# provenance sidecar
# ---------------------------------------------------------------------------

def test_provenance_round_trip(tmp_path):
    path = str(tmp_path / "s.pb")
    meta = {"engine": "mcmc", "budget": 500, "seed": 7, "num_devices": 8,
            "best_ms": 3.21,
            "ops": {"conv1": {"dims": "4x1x2x1", "fwd_ms": 0.1}}}
    save_strategies_to_file(path, sample_strategies(), provenance=meta)
    got = read_provenance(path)
    assert got is not None
    for k, v in meta.items():
        assert got[k] == v
    # the stamper's own fields
    assert got["provenance_version"] == 1
    assert got["strategy_file"] == "s.pb"
    assert got["content_hash"].startswith("sha256:")
    assert got["created_unix"] > 0


def test_provenance_absent_without_metadata(tmp_path):
    path = str(tmp_path / "s.pb")
    save_strategies_to_file(path, sample_strategies())
    import os
    assert not os.path.exists(sidecar_path(path))
    assert read_provenance(path) is None


def test_corrupt_sidecar_warns_and_is_ignored(tmp_path):
    path = str(tmp_path / "s.pb")
    save_strategies_to_file(path, sample_strategies())
    for payload in ('{"truncat', '[1, 2, 3]', ""):
        with open(sidecar_path(path), "w") as f:
            f.write(payload)
        with pytest.warns(UserWarning, match="corrupt strategy sidecar"):
            assert read_provenance(path) is None
        # and a load never breaks on it
        assert set(load_strategies_from_file(path)) == \
            set(sample_strategies())


def test_traced_load_emits_provenance_event(tmp_path, monkeypatch):
    trace = tmp_path / "t.jsonl"
    monkeypatch.setenv("FF_TELEMETRY", "1")
    monkeypatch.setenv("FF_TELEMETRY_FILE", str(trace))
    events.reset_active()
    try:
        path = str(tmp_path / "s.pb")
        save_strategies_to_file(
            path, sample_strategies(),
            provenance={"engine": "native", "budget": 9, "seed": 1,
                        "best_ms": 5.5})
        load_strategies_from_file(path)  # sidecar ok
        # overwrite the .pb without re-stamping -> hash mismatch
        save_strategies_to_file(
            path, {"op": ParallelConfig(DeviceType.TPU, (1, 1), (0,))})
        load_strategies_from_file(path)  # sidecar stale
        nosc = str(tmp_path / "bare.pb")
        save_strategies_to_file(nosc, sample_strategies())
        load_strategies_from_file(nosc)  # sidecar missing
    finally:
        events.reset_active()
    with open(trace) as f:
        recs = [json.loads(line) for line in f if line.strip()]
    prov = [r["attrs"] for r in recs
            if r.get("name") == "strategy_provenance"]
    assert [p["provenance"] for p in prov] == ["ok", "stale", "missing"]
    assert prov[0]["engine"] == "native" and prov[0]["budget"] == 9
    assert prov[0]["best_ms"] == 5.5 and prov[0]["num_ops"] == 3


def test_untraced_load_makes_zero_event_log_calls(tmp_path, monkeypatch):
    monkeypatch.delenv("FF_TELEMETRY", raising=False)
    events.reset_active()
    monkeypatch.setattr(
        events.EventLog, "_write",
        lambda self, rec: (_ for _ in ()).throw(
            AssertionError(f"event-log call while disabled: {rec}")))
    path = str(tmp_path / "s.pb")
    save_strategies_to_file(path, sample_strategies(),
                            provenance={"engine": "mcmc"})
    assert set(load_strategies_from_file(path)) == set(sample_strategies())


def test_write_provenance_rebinds_hash(tmp_path):
    path = str(tmp_path / "s.pb")
    save_strategies_to_file(path, sample_strategies())
    write_provenance(path, {"engine": "mcmc"})
    h1 = read_provenance(path)["content_hash"]
    save_strategies_to_file(
        path, {"op": ParallelConfig(DeviceType.TPU, (2, 1), (0, 1))},
        provenance={"engine": "mcmc"})
    h2 = read_provenance(path)["content_hash"]
    assert h1 != h2  # the sidecar follows the bytes it describes


@pytest.mark.skipif(shutil.which("protoc") is None, reason="protoc not available")
def test_wire_compatible_with_reference_proto(tmp_path):
    path = str(tmp_path / "strategy.pb")
    save_strategies_to_file(path, sample_strategies())
    # Decode our bytes with the reference schema.
    with open(path, "rb") as f:
        out = subprocess.run(
            ["protoc", f"--proto_path=/root/reference/src/runtime",
             "--decode=FFProtoBuf.Strategy", "strategy.proto"],
            stdin=f, capture_output=True, check=True)
    text = out.stdout.decode()
    assert 'name: "conv1"' in text
    assert "dims: 4" in text and "device_type: CPU" in text

    # Re-encode the decoded text with protoc and parse with our codec.
    enc = subprocess.run(
        ["protoc", f"--proto_path=/root/reference/src/runtime",
         "--encode=FFProtoBuf.Strategy", "strategy.proto"],
        input=out.stdout, capture_output=True, check=True)
    path2 = str(tmp_path / "re.pb")
    with open(path2, "wb") as f:
        f.write(enc.stdout)
    loaded = load_strategies_from_file(path2)
    orig = sample_strategies()
    assert {k: (v.dims, v.device_ids) for k, v in loaded.items()} == \
           {k: (v.dims, v.device_ids) for k, v in orig.items()}
